"""Real-TPU smoke runner — the Mosaic-only bug net.

The pytest suite runs on a virtual CPU mesh (tests/conftest.py), where
Pallas executes in interpreter mode. That validates numerics but cannot
see Mosaic lowering rules: round 3 hit three real-chip-only failures a
green CPU suite shipped — a (1, 2) scalar block over a (B, 2) array
(illegal for B > 1), partial `unroll=8` on a fori_loop (full-or-none
only), and a compiler scoped-VMEM OOM from lane-padded narrow strips.

This runner drives every Mosaic-sensitive code path on the attached
chip in a few minutes. Run it whenever kernels change:

    python tpu_smoke.py

Exit 0 = all paths compiled AND matched the jnp golden model on-device.
"""

from __future__ import annotations

import sys

import numpy as np


def check(name, got, want, atol=1e-2, rtol=1e-5):
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=rtol, atol=atol)
    print(f"PASS {name}")


def main() -> int:
    import jax
    if jax.default_backend() != "tpu":
        # Exit 2, not 1: automation must be able to tell "no hardware"
        # from "kernel broke on hardware" (and a skip still can't
        # masquerade as a pass).
        print("SKIP: no TPU attached (backend "
              f"{jax.default_backend()!r}); this runner only means "
              "something on real hardware", file=sys.stderr)
        return 2

    from heat2d_tpu.config import HeatConfig
    from heat2d_tpu.models.ensemble import run_ensemble
    from heat2d_tpu.models.solver import Heat2DSolver

    def run(mode, nx, ny, steps, **kw):
        cfg = HeatConfig(nxprob=nx, nyprob=ny, steps=steps, mode=mode,
                         **kw)
        return Heat2DSolver(cfg).run(timed=False).u

    # Kernel A (VMEM-resident) with a non-multiple-of-8 step count: the
    # unrolled-group + rolled-remainder lowering.
    want = run("serial", 128, 256, 37)
    check("kernel A (VMEM resident, 37 steps)",
          run("pallas", 128, 256, 37), want)

    # Kernels B/C (band streaming) on an HBM-sized grid, plus the
    # bitwise-parity path.
    want = run("serial", 2048, 2048, 60)
    check("kernel C (band streaming, 2048^2)",
          run("pallas", 2048, 2048, 60), want)
    got = run("pallas", 2048, 2048, 60, bitwise_parity=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    print("PASS kernel C bitwise-parity (bit-identical to serial)")

    # Kernel C2 (gather-free window sweeps — the production pallas route
    # on TPU) pinned BITWISE to kernel C's legacy gather route: same
    # step sequence, different strip dataflow (pl.Element window +
    # sequential-grid scratch relay). Divisor-poor rows exercise the
    # m_pad + T overrun pad.
    import heat2d_tpu.ops.pallas_stencil as ps
    from heat2d_tpu.ops.init import inidat

    def legacy_chunk(v):          # kernel C sweeps, bypassing the router
        for _ in range(6):
            v = ps.band_multi_step(v, 8, 0.1, 0.1)
        return v

    for shape in ((2048, 2048), (1000, 2048)):
        u = inidat(*shape)
        want = jax.jit(legacy_chunk)(u)
        got = jax.jit(lambda v: ps.band_chunk(v, 48, 0.1, 0.1))(u)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        print(f"PASS kernel C2 bitwise vs kernel C ({shape[0]}x{shape[1]})")

    # Kernel C3 (column-panel window sweeps — the wide-row production
    # route) pinned BITWISE to the C2 window route: same per-cell step
    # DAG, different tiling (per-panel carries + cross-panel strip
    # windows). Covers P=2 and P=4, divisor-poor rows (m_pad overrun),
    # and a remainder sweep (n % T != 0).
    for shape, panels, bmp, n in (((1000, 4096), 2, 144, 52),
                                  ((512, 2048), 4, 64, 16)):
        u = inidat(*shape)
        want = jax.jit(lambda v: ps.band_chunk(v, n, 0.1, 0.1))(u)
        got = jax.jit(lambda v: ps.panel_chunk(
            v, n, 0.1, 0.1, panels=panels, bm=bmp))(u)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        print(f"PASS kernel C3 bitwise vs C2 ({shape[0]}x{shape[1]}, "
              f"P={panels}, bm={bmp})")

    # C3R: the panel resid sweep's state must stay bitwise equal to the
    # plain route, and its residual must match the last step pair's
    # Σ(Δu)² (per-band partial summation order differs at f32-ulp).
    import jax.numpy as jnp
    u = inidat(1000, 4096)

    def c3r(v):
        cs = ps._panel_split(v, 2, 144, 8)
        cs, r = ps._panel_sweep_all(cs, 8, 0.1, 0.1, 144, v.shape[0],
                                    ps._step_value, resid=True)
        return ps._panel_join(cs, v.shape[0]), r

    got8, res = jax.jit(c3r)(u)
    want8 = jax.jit(lambda v: ps.band_chunk(v, 8, 0.1, 0.1))(u)
    want7 = jax.jit(lambda v: ps.band_chunk(v, 7, 0.1, 0.1))(u)
    np.testing.assert_array_equal(np.asarray(got8), np.asarray(want8))
    np.testing.assert_allclose(
        float(res), float(jnp.sum((want8 - want7) ** 2)), rtol=1e-4)
    print("PASS kernel C3R resid sweep (state bitwise + residual)")

    # Solver-level C3: at >16 KB rows the production pallas route must
    # go through plan_panels (P=2 here) — fixed-step and the fused C3R
    # convergence path, both against the serial golden model.
    pp, pbm = ps.plan_panels(512, 8192, 8)
    assert pp == 2 and pbm is not None, (pp, pbm)
    want = run("serial", 512, 8192, 30)
    check("kernel C3 solver route (512x8192, plan P=2)",
          run("pallas", 512, 8192, 30), want)
    want = run("serial", 512, 8192, 48, convergence=True, interval=12,
               sensitivity=0.0)
    check("kernel C3R solver convergence (512x8192)",
          run("pallas", 512, 8192, 48, convergence=True, interval=12,
              sensitivity=0.0), want)

    # 16 KB rows + a remainder sweep (steps % 8 != 0): the legacy-C
    # remainder runs a ROLLED in-kernel loop, where the dual-body
    # interior fast path blew Mosaic's scoped-VMEM stack at this row
    # width (17.3 MB for bm=128/T=4 — the round-4 conv-sweep crash);
    # band_multi_step must gate the fast path off for partial groups.
    want = run("serial", 512, 4096, 20)
    check("kernel C remainder sweep (512x4096, 20 steps)",
          run("pallas", 512, 4096, 20), want)

    # Kernel B (single-step band) via the convergence path on an
    # HBM-sized grid: run_convergence_chunked's tracked step is a
    # band_step call, exercising the interior-fast-path pl.when branch
    # (round 4) on real Mosaic.
    def run_conv(mode):
        cfg = HeatConfig(nxprob=2048, nyprob=2048, steps=48, mode=mode,
                         convergence=True, interval=12, sensitivity=0.0)
        r = Heat2DSolver(cfg).run(timed=False)
        assert int(r.steps_done) == 48, r.steps_done
        return r.u

    check("kernel B (band single-step, convergence 2048^2)",
          run_conv("pallas"), run_conv("serial"))

    # C2R fused-residual convergence (the production streaming conv
    # route: interval >= T, so run_conv above already exercised the
    # fused kernel's state path). Early-exit: a huge sensitivity must
    # stop both modes at the first INTERVAL with the same steps_done.
    def first_exit(mode):
        cfg = HeatConfig(nxprob=2048, nyprob=2048, steps=48, mode=mode,
                         convergence=True, interval=12,
                         sensitivity=1e30)
        return int(Heat2DSolver(cfg).run(timed=False).steps_done)

    assert first_exit("pallas") == first_exit("serial") == 12
    print("PASS C2R fused-residual early exit (steps_done parity)")

    # Small-interval fused convergence (interval < T — viable since the
    # round-5 chunk-tail schedule lets the resid sweep depth adapt):
    # state + steps_done vs serial, pallas and hybrid.
    want = run("serial", 2048, 2048, 23, convergence=True, interval=5,
               sensitivity=0.0)
    for mode in ("pallas", "hybrid"):
        cfg = HeatConfig(nxprob=2048, nyprob=2048, steps=23, mode=mode,
                         convergence=True, interval=5, sensitivity=0.0)
        r = Heat2DSolver(cfg).run(timed=False)
        assert int(r.steps_done) == 23, (mode, r.steps_done)
        check(f"fused conv interval<T ({mode}, iv=5, 23 steps)", r.u,
              want)

    # D2R (the fused residual on the hybrid shard sweeps): same step
    # form and per-cell op sequence as C2R, so the final state must be
    # BITWISE equal to pallas's, with the same early-exit count.
    got = run("hybrid", 2048, 2048, 48, convergence=True, interval=12,
              sensitivity=0.0)
    want = run("pallas", 2048, 2048, 48, convergence=True, interval=12,
               sensitivity=0.0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert first_exit("hybrid") == 12
    print("PASS D2R fused-residual (hybrid bitwise vs pallas + exit)")

    # Kernel D (hybrid shard kernels) on a 1x1 mesh: VMEM route at a
    # small shard, band route at the round-1 OOM config, and a
    # divisor-poor height (pad rows + windowed column strips).
    want = run("serial", 512, 512, 30)
    check("kernel D VMEM route (hybrid 512^2)",
          run("hybrid", 512, 512, 30), want)
    want = run("serial", 2048, 2048, 30)
    check("kernel D band route (hybrid 2048^2, r1 OOM config)",
          run("hybrid", 2048, 2048, 30), want)
    want = run("serial", 1000, 2048, 30)
    check("kernel D band route, divisor-poor rows (hybrid 1000x2048)",
          run("hybrid", 1000, 2048, 30), want)

    # Solver-level padded D2: 1048 rows on a 1x1 mesh used to silently
    # drop to kernel D (no 8-aligned divisor > 2T); the padded plan
    # keeps the window route (asserted) and must match serial.
    plan = ps.plan_shard_window(1048, 2048, 8)
    assert plan is not None and plan[1] > 1048, plan
    want = run("serial", 1048, 2048, 30)
    check("kernel D2 padded solver route (hybrid 1048x2048)",
          run("hybrid", 1048, 2048, 30), want)

    # Kernel D2 (gather-free shard sweeps — the production hybrid route
    # on TPU; the solver-level hybrid checks above already ran through
    # it) pinned BITWISE to kernel D's gather route at the KERNEL level,
    # with nonzero halo strips and a mid-grid shard offset — the cases a
    # 1x1 mesh can't produce. Both column variants: with_cols=True (a
    # y-axis mesh) and the full-width row-only-mask path.
    import jax.numpy as jnp
    rng = np.random.default_rng(1612)
    m, bn, t = 512, 1024, 8
    nx = 4096
    for with_cols, y0 in ((True, 1024), (False, 0)):
        # The no-cols variant exists only for gy == 1, where the shard
        # spans the full global width (bn == ny) and the step form's
        # first/last-column keep IS the global y boundary.
        ny = 4096 if with_cols else bn
        u = jnp.asarray(rng.random((m, bn), dtype=np.float32))
        north = jnp.asarray(rng.random((t, bn), dtype=np.float32))
        south = jnp.asarray(rng.random((t, bn), dtype=np.float32))
        west = jnp.asarray(rng.random((m + 2 * t, t), dtype=np.float32))
        east = jnp.asarray(rng.random((m + 2 * t, t), dtype=np.float32))
        if not with_cols:
            west = jnp.zeros_like(west)
            east = jnp.zeros_like(east)
        x0 = 1024
        scalars = jnp.asarray([x0, y0], jnp.int32)
        want = jax.jit(lambda u: ps._shard_band_chunk(
            u, (north, south, west, east), scalars, t, 0.1, 0.1, nx, ny,
            step=ps._step_value))(u)
        plan = ps.plan_shard_window(m, bn, t, with_cols=with_cols)
        assert plan is not None, "D2 plan rejected an aligned config"
        rb, m_pad = plan
        assert m_pad == m, plan      # 512 divides: zero pad
        nblk = m_pad // rb

        def d2(u):
            ue = jnp.concatenate([u, south], axis=0)
            wwin = ps._strip_windows(west, nblk, rb, t) if with_cols \
                else None
            ewin = ps._strip_windows(east, nblk, rb, t) if with_cols \
                else None
            out = ps.shard_window_sweep(ue, north, wwin, ewin, scalars,
                                        rb=rb, tsteps=t, nx=nx, ny=ny,
                                        cx=0.1, cy=0.1)
            return out[:m]

        got = jax.jit(d2)(u)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        print(f"PASS kernel D2 bitwise vs kernel D (with_cols={with_cols},"
              f" rb={rb})")

    # D2 PADDED (the divisor-cliff fix): a 1048-row mid-grid shard has
    # no deep 8-aligned divisor (1048 = 8 x 131), and 1004 is not even
    # 8-aligned (the south halo lands at an unaligned offset); the
    # padded plan must keep the window route and stay bitwise-equal to
    # kernel D. Both column variants; nonzero halos, mid-grid offset.
    # The with_cols case also pins the D2R residual on a padded plan:
    # band centers past the shard's true height hold overwritten
    # garbage the valid_rows mask must exclude (review r5).
    bn, t = 1024, 8
    nx = 4096
    for m, with_cols, y0 in ((1048, True, 1024), (1048, False, 0),
                             (1004, False, 0)):
        ny = 4096 if with_cols else bn
        u = jnp.asarray(rng.random((m, bn), dtype=np.float32))
        north = jnp.asarray(rng.random((t, bn), dtype=np.float32))
        south = jnp.asarray(rng.random((t, bn), dtype=np.float32))
        west = jnp.asarray(rng.random((m + 2 * t, t), dtype=np.float32))
        east = jnp.asarray(rng.random((m + 2 * t, t), dtype=np.float32))
        if not with_cols:
            west = jnp.zeros_like(west)
            east = jnp.zeros_like(east)
        scalars = jnp.asarray([1024, y0], jnp.int32)
        want = jax.jit(lambda u: ps._shard_band_chunk(
            u, (north, south, west, east), scalars, t, 0.1, 0.1, nx, ny,
            step=ps._step_value))(u)
        plan = ps.plan_shard_window(m, bn, t, with_cols=with_cols)
        assert plan is not None, f"padded D2 plan rejected {m} rows"
        rb, m_pad = plan
        assert m_pad > m and rb > 2 * t, plan
        nblk = m_pad // rb

        def d2pad(u, resid=False):
            ue = jnp.concatenate(
                [u, south, jnp.zeros((m_pad - m, bn), u.dtype)], axis=0)
            if with_cols:
                zp = jnp.zeros((m_pad - m, t), u.dtype)
                wwin = ps._strip_windows(
                    jnp.concatenate([west, zp], axis=0), nblk, rb, t)
                ewin = ps._strip_windows(
                    jnp.concatenate([east, zp], axis=0), nblk, rb, t)
            else:
                wwin = ewin = None
            out = ps.shard_window_sweep(ue, north, wwin, ewin, scalars,
                                        rb=rb, tsteps=t, nx=nx, ny=ny,
                                        cx=0.1, cy=0.1, resid=resid,
                                        valid_rows=m)
            if resid:
                return out[0][:m], out[1]
            return out[:m]

        got = jax.jit(d2pad)(u)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        print(f"PASS kernel D2 padded bitwise vs D (with_cols={with_cols},"
              f" rb={rb}, m_pad={m_pad})")
        if with_cols:
            got8, part = jax.jit(lambda u: d2pad(u, resid=True))(u)
            np.testing.assert_array_equal(np.asarray(got8),
                                          np.asarray(want))
            # 7-step ground truth via a D chunk of depth t-1, its halos
            # sliced from the t-deep ones (the rows/cols adjacent to
            # the block; the staleness cone allows the shallower
            # depth).
            want7 = jax.jit(lambda u: ps._shard_band_chunk(
                u, (north[1:], south[:-1], west[1:-1, 1:],
                    east[1:-1, :-1]),
                scalars, t - 1, 0.1, 0.1, nx, ny,
                step=ps._step_value))(u)
            expect = float(jnp.sum((jnp.asarray(want)
                                    - jnp.asarray(want7)) ** 2))
            np.testing.assert_allclose(float(part), expect, rtol=1e-4)
            print("PASS kernel D2R padded residual excludes pad rows")

    # Pod-relevant D2 with-cols envelope: a 4096-wide (16 KB) shard with
    # column strips at the plan's rb must COMPILE on the real chip (a
    # 2x2 mesh at 8192^2 gives exactly this shard; C3's much tighter
    # with-cols envelope says allowances don't transfer between kernel
    # structures, so this pin keeps D2's -8 rule honest).
    m, bn, t = 2048, 4096, 8
    nx, ny = 8192, 8192
    plan = ps.plan_shard_window(m, bn, t, with_cols=True)
    assert plan is not None
    rb, m_pad = plan
    u = jnp.asarray(rng.random((m, bn), dtype=np.float32))
    north = jnp.asarray(rng.random((t, bn), dtype=np.float32))
    south = jnp.asarray(rng.random((t, bn), dtype=np.float32))
    west = jnp.asarray(rng.random((m + 2 * t, t), dtype=np.float32))
    east = jnp.asarray(rng.random((m + 2 * t, t), dtype=np.float32))
    scalars = jnp.asarray([2048, 4096], jnp.int32)
    nblk = m_pad // rb

    def d2wide(u):
        ue = jnp.concatenate(
            [u, south, jnp.zeros((m_pad - m, bn), u.dtype)], axis=0)
        zp = jnp.zeros((m_pad - m, t), u.dtype)
        wwin = ps._strip_windows(jnp.concatenate([west, zp], axis=0),
                                 nblk, rb, t)
        ewin = ps._strip_windows(jnp.concatenate([east, zp], axis=0),
                                 nblk, rb, t)
        out = ps.shard_window_sweep(ue, north, wwin, ewin, scalars,
                                    rb=rb, tsteps=t, nx=nx, ny=ny,
                                    cx=0.1, cy=0.1)
        return out[:m]

    jax.block_until_ready(jax.jit(d2wide)(u))
    print(f"PASS kernel D2 with-cols 16 KB shard compiles (rb={rb})")

    # Batched ensemble kernels with B > 1: the (B, 1, 2) scalar-block
    # layout (a (1, 2) block over (B, 2) is illegal on real TPU and
    # invisible in interpreter mode).
    cxs, cys = [0.05, 0.2], [0.1, 0.1]
    want = run_ensemble(128, 256, 25, cxs, cys, method="jnp")
    check("ensemble VMEM kernel (B=2 scalar blocks)",
          run_ensemble(128, 256, 25, cxs, cys, method="pallas"), want)
    want = run_ensemble(1024, 2048, 16, cxs, cys, method="jnp")
    check("ensemble band kernel (B=2, HBM members)",
          run_ensemble(1024, 2048, 16, cxs, cys, method="band"), want)

    # Batched WINDOW route (gather-free ensemble sweeps) bitwise vs the
    # legacy gathered-strip route: same per-member step DAG, different
    # dataflow (stacked carries + element windows + scratch relay across
    # member boundaries). Divisor-poor rows exercise the per-member pad;
    # 20 steps exercise the partial-depth remainder sweep.
    import unittest.mock as mock
    from heat2d_tpu.models import ensemble as ens
    got = run_ensemble(1000, 2048, 20, cxs, cys, method="band")
    with mock.patch.object(ps, "window_band_viable",
                           lambda *a, **k: False):
        want = run_ensemble(1000, 2048, 20, cxs, cys, method="band")
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    print("PASS ensemble window route bitwise vs legacy band (B=2)")

    # Convergence ensemble through the window-band chunks: per-member
    # early exit must match the vmap'd golden loop's steps_done.
    from heat2d_tpu.models.ensemble import run_ensemble_convergence
    uw, kw = run_ensemble_convergence(1000, 2048, 200, 20, 1e4,
                                      cxs, cys, method="band")
    uj, kj = run_ensemble_convergence(1000, 2048, 200, 20, 1e4,
                                      cxs, cys, method="jnp")
    assert [int(x) for x in kw] == [int(x) for x in kj], (kw, kj)
    # 200 steps of the kernel's FMA factoring vs the golden literal
    # form: ~2e-5 rel drift (the Appendix-B class; same allowance as
    # test_pallas_mode_convergence per step count).
    check("ensemble window convergence (steps_done parity)", uw, uj,
          rtol=1e-4)

    # Batch x spatial ensemble on the single chip (a (1,1,1) mesh): the
    # vmapped shard_map program with traced per-member (cx, cy) must
    # compile and run on real XLA:TPU (the CPU suite covers multi-device
    # meshes; this pins the TPU lowering of the vmapped halo ppermutes).
    from heat2d_tpu.models.ensemble import run_ensemble_spatial
    got, ks = run_ensemble_spatial(128, 256, 25, cxs, cys,
                                   gridx=1, gridy=1)
    check("ensemble batch x spatial ((1,1,1) mesh)", got[0],
          run_ensemble(128, 256, 25, cxs, cys, method="jnp")[0])
    assert [int(k) for k in ks] == [25, 25]
    print("PASS ensemble batch x spatial ((1,1,1) mesh) steps")

    # Fused halo route (ISSUE 8, docs/SCALING.md) on a real multi-chip
    # mesh: dist2d overlap tier AND hybrid kernel F (in-kernel ICI
    # async remote copies) must be BITWISE-identical to the collective
    # route; resolve_halo_route must report the tier actually engaged.
    ndev = len(jax.devices())
    if ndev >= 2:
        from heat2d_tpu.config import HeatConfig
        from heat2d_tpu.parallel.mesh import make_mesh
        from heat2d_tpu.parallel.scaling import square_mesh
        from heat2d_tpu.parallel.sharded import resolve_halo_route

        gxs, gys = square_mesh(ndev)
        base = dict(nxprob=128 * gxs, nyprob=128 * gys, steps=20,
                    gridx=gxs, gridy=gys)
        for mode in ("dist2d", "hybrid"):
            fcfg = HeatConfig(mode=mode, halo="fused", **base)
            ck = None
            if mode == "hybrid":
                ck = ps.make_shard_chunk_kernel(fcfg)
            route = resolve_halo_route(fcfg, make_mesh(gxs, gys),
                                       chunk_kernel=ck)
            fu = run(mode, base["nxprob"], base["nyprob"], 20,
                     gridx=gxs, gridy=gys, halo="fused")
            cu = run(mode, base["nxprob"], base["nyprob"], 20,
                     gridx=gxs, gridy=gys)
            np.testing.assert_array_equal(np.asarray(fu),
                                          np.asarray(cu))
            print(f"PASS fused halo {mode} bitwise vs collective "
                  f"({gxs}x{gys} mesh, tier={route['tier']})")
        # The hybrid resident shard must actually take kernel F here —
        # a silent degradation would make the parity above vacuous.
        hcfg = HeatConfig(mode="hybrid", halo="fused", **base)
        hroute = resolve_halo_route(
            hcfg, make_mesh(gxs, gys),
            chunk_kernel=ps.make_shard_chunk_kernel(hcfg))
        assert hroute["tier"] == "ici", (
            f"expected kernel F on a resident shard, got {hroute}")
        print("PASS fused halo hybrid engages kernel F (in-kernel ICI)")
    else:
        print("SKIP fused halo mesh checks (1 device attached)",
              file=sys.stderr)

    # Mesh serving (heat2d_tpu/mesh, docs/SERVING.md): the mesh-aware
    # engine on REAL chips — batch route bitwise vs the single-chip
    # engine on several occupancy rungs, wall-clock strong scaling
    # recorded (rate_source="wall" on hardware), and the spatial route
    # stamping its halo plan compiled:True with bitwise parity.
    if ndev >= 2:
        from heat2d_tpu.mesh.bench import (measure_serve_scaling,
                                           measure_spatial_serve)

        row = measure_serve_scaling(n_devices=ndev, nx=256, ny=256,
                                    steps=16)
        assert row["parity"], row["parity_rungs"]
        assert row["rate_source"] == "wall", row["rate_source"]
        print(f"PASS mesh serve batch route bitwise "
              f"({ndev} chips, wall efficiency "
              f"{row['wall_scaling_efficiency']:.3f})")
        sp = measure_spatial_serve(n_devices=ndev, nx=256 * gxs,
                                   ny=256 * gys, steps=16)
        assert sp["route"] == "spatial" and sp["parity"], sp
        assert sp["compiled"] is True, sp
        print(f"PASS mesh serve spatial route compiled "
              f"(tier={sp['halo_plan'].get('tier')}) bitwise")
    else:
        print("SKIP mesh serve checks (1 device attached)",
              file=sys.stderr)

    # Implicit routes (ISSUE 14, docs/ALGORITHMS.md) on real Mosaic:
    # kernel TD (batched Thomas along lanes) in BOTH transpose
    # variants vs the jnp scan route, the mg V-cycle step, and the
    # real-hardware wall-clock-to-solution comparison recorded as a
    # BENCH-style metric line — the first real-TPU validation point
    # the mesh PR left open.
    from heat2d_tpu.ops import tridiag as td

    import jax.numpy as jnp

    rng = np.random.default_rng(14)
    ub = rng.normal(size=(2, 128, 256)).astype(np.float32)
    cxs = np.asarray([8.0, 3.0], np.float32)
    cys = np.asarray([6.0, 2.0], np.float32)
    want = td.batched_adi_scan(jnp.asarray(ub), cxs, cys, steps=4)
    assert td.adi_kernel_viable(128, 256), (
        "kernel TD must be viable at 128x256 on a real chip")
    for variant in ("xpose", "strided"):
        got = td.batched_adi_kernel(jnp.asarray(ub), cxs, cys, steps=4,
                                    variant=variant)
        check(f"kernel TD ({variant}) vs jnp scan", got, want,
              atol=1e-4)
    # mg solver route vs the INDEPENDENT analytic oracle (the mg
    # runner is mode-agnostic, so a serial-vs-pallas comparison would
    # compare the program against itself — the oracle is the
    # non-vacuous check on real hardware).
    from heat2d_tpu.ops import analytic as an

    mg_steps, mg_c = 8, 4.0
    mcfg = HeatConfig(nxprob=65, nyprob=65, steps=mg_steps, cx=mg_c,
                      cy=mg_c, method="mg", mode="pallas")
    u_mg = Heat2DSolver(mcfg).run(
        u0=an.separable_mode(65, 65), timed=False).u
    ref = an.mode_solution(65, 65, mg_c * mg_steps, mg_c * mg_steps)
    assert an.l2_error(u_mg, ref) < 1e-3, an.l2_error(u_mg, ref)
    print("PASS mg CN step (solver route vs analytic mode)")

    # Wall-clock-to-solution at the bench shape class: measured on
    # REAL hardware (kernels engaged), printed as the BENCH-style
    # metric line the driver-record tail collects.
    from heat2d_tpu.models import solution

    tts = solution.bench_tts(on_tpu=True)
    s = tts["summary"]
    assert s["adi_matched_accuracy"], tts
    assert s["adi_steps_ratio"] >= 100.0, tts
    import json

    from heat2d_tpu.obs.record import attach_context
    by = {r["method"]: r for r in tts["rows"]}
    print("TTS_METRICS " + json.dumps(attach_context({
        "metric": (f"wall-clock-to-solution {s['nx']}x{s['ny']} "
                   f"that={s['that_x']:g} (explicit vs adi)"),
        "value": round(s["adi_wall_speedup"], 2),
        "unit": "x speedup",
        "explicit_s": round(by["explicit"]["time_to_solution_s"], 4),
        "adi_s": round(by["adi"]["time_to_solution_s"], 4),
        "steps_ratio": s["adi_steps_ratio"],
        "accuracy": {m: r["accuracy"] for m, r in by.items()},
    }, "bench"), default=float))
    print(f"PASS wall-clock-to-solution adi "
          f"{s['adi_wall_speedup']:.1f}x at matched accuracy "
          f"({s['adi_steps_ratio']:.0f}x fewer steps)")

    # Multihost pod leg (docs/DISTRIBUTED.md): only means something
    # under a real multi-process launch (one process per host). When
    # it runs, prove the pod world assembled — full topology, an ICI
    # census inside each host — and that the global ('batch','xy')
    # mesh actually builds over every device in the pod.
    if jax.process_count() > 1:
        from heat2d_tpu.dist.mesh import pod_mesh
        from heat2d_tpu.dist.runtime import DistWorld

        world = DistWorld.from_env()
        assert world.process_count == jax.process_count(), world
        census = world.link_census()
        assert census.get("ici", 0) > 0, census
        mesh = pod_mesh(world, batch=world.process_count,
                        xy=world.n_devices // world.process_count)
        assert mesh.devices.size == world.n_devices, mesh
        print(f"PASS pod world: {world.summary()} "
              f"links={census} mesh={dict(mesh.shape)}")
    else:
        print("SKIP pod leg: single-process launch "
              "(run one process per host to exercise it)")

    print("ALL TPU SMOKE PATHS PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
