"""Iteration-axis sweep — the Report.pdf Tables 10-11 analogue.

The reference proves its CUDA kernel's per-step cost is constant by
sweeping iterations 10 -> 100,000 at fixed grids and showing the
wall-clock scales linearly (Table 10 p.26: times; Table 11 p.27: the
speedup-vs-10-iterations column tracks the iteration ratio almost
exactly). The two-point estimator this framework's headline numbers use
*relies* on that amortized linearity; this sweep is the committed
artifact that demonstrates it on the attached chip (VERDICT r3 missing
#1).

Protocol: one compiled runner per step count (compile excluded via
warmup, like the reference's cudaEvent placement), min-of-3 fenced
wall-clocks per point. Columns:

- total (s): min elapsed for the row's step count;
- per-step (s): total / steps — CONTAMINATED by the fixed ~0.1-0.2 s
  tunnel fence at small counts (the honest reason the headline metric is
  two-point, not total/steps);
- marginal (s/step): (total_k - total_{k-1}) / (steps_k - steps_{k-1})
  between consecutive decades — fence cancelled; CONSTANCY down this
  column is the linearity claim;
- x vs 10 iters: total / total_10 — Table 11's own diagnostic (tracks
  steps/10 once the fence is amortized).

Usage:
    python benchmarks/sweep_iters.py [NX NY]   # default 2560x2048
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

STEP_COUNTS = [10, 100, 1_000, 10_000, 100_000]
REPS = 3
#: A decade-to-decade window smaller than this is fence jitter, not
#: signal (the sweep harness's NOISE_FLOOR_S, same tunnel, same reason);
#: its marginal would be meaningless noise — possibly negative.
NOISE_FLOOR_S = 0.05


def measure(nx: int, ny: int, mode: str = "pallas"):
    from heat2d_tpu.config import HeatConfig
    from heat2d_tpu.models.solver import Heat2DSolver

    rows = []
    for steps in STEP_COUNTS:
        cfg = HeatConfig(nxprob=nx, nyprob=ny, steps=steps, mode=mode)
        solver = Heat2DSolver(cfg)
        ts = [solver.run(timed=True, warmup=(i == 0)).elapsed
              for i in range(REPS)]
        rows.append({"steps": steps, "total_s": min(ts)})
        print(json.dumps(rows[-1]), file=sys.stderr)
    for i, r in enumerate(rows):
        r["per_step_s"] = r["total_s"] / r["steps"]
        r["x_vs_10it"] = (r["total_s"] / rows[0]["total_s"]
                          if rows[0]["total_s"] else None)
        if i:
            p = rows[i - 1]
            dt = r["total_s"] - p["total_s"]
            if dt > NOISE_FLOOR_S:
                r["marginal_s"] = dt / (r["steps"] - p["steps"])
            else:       # window inside fence jitter: no honest marginal
                r["marginal_noise"] = True
    return rows


def to_markdown(rows, nx, ny, mode, platform) -> str:
    lines = [
        f"# Iteration-axis sweep ({platform}) — {mode} {nx}x{ny}", "",
        "Tables 10-11 analogue (Report.pdf p.26-27): per-step cost "
        "constancy across 10 -> 100k iterations, the amortized-linearity "
        "property the two-point headline estimator relies on. 'per-step' "
        "divides the raw fenced wall-clock (the fixed ~0.1-0.2 s tunnel "
        "fence dominates small counts — exactly why the headline metric "
        "is two-point); 'marginal' differences consecutive decades, "
        "cancelling the fence. Constant marginal = linear scaling; "
        "'x vs 10 it' is Table 11's own speedup diagnostic (it "
        "approaches steps/10 as the fence amortizes to nothing).", "",
        "| steps | total (s) | per-step (s) | marginal (s/step) "
        "| x vs 10 iters | steps ratio |",
        "|---|---|---|---|---|---|",
    ]
    for r in rows:
        marg = r.get("marginal_s")
        if marg is not None:
            mcell = f"{marg:.3g}"
        elif r.get("marginal_noise"):
            mcell = "(window < noise floor)"
        else:
            mcell = "—"
        x10 = r["x_vs_10it"]
        lines.append(
            f"| {r['steps']} | {r['total_s']:.4g} "
            f"| {r['per_step_s']:.3g} "
            f"| {mcell} "
            f"| {'—' if x10 is None else format(x10, '.4g')} "
            f"| {r['steps'] // 10} |")
    margs = [r["marginal_s"] for r in rows if "marginal_s" in r]
    if margs:
        spread = max(margs) / min(margs)
        lines += [
            "",
            f"Marginal spread across the decades whose window clears "
            f"the {NOISE_FLOOR_S} s fence-noise floor: {spread:.3f}x "
            f"(min {min(margs):.3e}, max {max(margs):.3e} s/step). "
            "The reference's Table 11 shows the same flatness for its "
            "CUDA kernel; per-step cost here is step-count-independent "
            "once the fixed fence is cancelled.",
        ]
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    nx, ny = (int(argv[0]), int(argv[1])) if len(argv) >= 2 else (2560, 2048)
    mode = argv[2] if len(argv) > 2 else "pallas"

    import jax
    d = jax.devices()[0]
    platform = getattr(d, "device_kind", d.platform)
    rows = measure(nx, ny, mode)

    outdir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "results")
    os.makedirs(outdir, exist_ok=True)
    with open(os.path.join(outdir, "sweep_iters.jsonl"), "w") as f:
        f.writelines(json.dumps(r) + "\n" for r in rows)
    md = to_markdown(rows, nx, ny, mode, platform)
    with open(os.path.join(outdir, "sweep_iters.md"), "w") as f:
        f.write(md)
    print(md)
    return 0


if __name__ == "__main__":
    sys.exit(main())
