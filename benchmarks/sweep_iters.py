"""Iteration-axis sweep — the Report.pdf Tables 10-11 analogue.

The reference proves its CUDA kernel's per-step cost is constant by
sweeping iterations 10 -> 100,000 at fixed grids and showing the
wall-clock scales linearly (Table 10 p.26: times; Table 11 p.27: the
speedup-vs-10-iterations column tracks the iteration ratio almost
exactly). The two-point estimator this framework's headline numbers use
*relies* on that amortized linearity; this sweep is the committed
artifact that demonstrates it on the attached chip (VERDICT r3 missing
#1) — and, round 5, for EVERY headline path: pallas, hybrid (the D2
window route), and a dist2d CPU-mesh section (VERDICT r4 next #6).

Protocol: one compiled runner per step count (compile excluded via
warmup, like the reference's cudaEvent placement), min-of-3 fenced
wall-clocks per point. Columns:

- total (s): min elapsed for the row's step count;
- per-step (s): total / steps — CONTAMINATED by the fixed ~0.1-0.2 s
  tunnel fence at small counts (the honest reason the headline metric is
  two-point, not total/steps);
- marginal (s/step): (total_k - total_{k-1}) / (steps_k - steps_{k-1})
  between consecutive decades — fence cancelled; CONSTANCY down this
  column is the linearity claim;
- x vs 10 iters: total / total_10 — Table 11's own diagnostic (tracks
  steps/10 once the fence is amortized).

Sections merge by (mode, grid, platform) key into one artifact: each
invocation replaces its own sections and re-renders the whole file, so
the TPU modes and the CPU-mesh section come from separate processes
(platform forcing must precede backend init).

Usage:
    python benchmarks/sweep_iters.py [NX NY [mode1,mode2]]
    python benchmarks/sweep_iters.py 256 256 dist2d --platform cpu \
        --host-device-count 8 --gridx 4 --gridy 2
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

STEP_COUNTS = [10, 100, 1_000, 10_000, 100_000]
REPS = 3
#: A decade-to-decade window must clear the tunnel fence's ~0.05 s
#: jitter by a MARGIN for its marginal to mean anything: a 0.054 s
#: window measured a 30x-off marginal (and the next decade took less
#: total time — pure jitter). 4x the jitter bounds the marginal's
#: error at roughly +-25%; windows below it get no marginal.
NOISE_FLOOR_S = 0.2

OUTDIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "results")


def measure(nx: int, ny: int, mode: str = "pallas", gridx: int = 1,
            gridy: int = 1):
    from heat2d_tpu.config import HeatConfig
    from heat2d_tpu.models.solver import Heat2DSolver

    rows = []
    for steps in STEP_COUNTS:
        cfg = HeatConfig(nxprob=nx, nyprob=ny, steps=steps, mode=mode,
                         gridx=gridx, gridy=gridy)
        solver = Heat2DSolver(cfg)
        ts = [solver.run(timed=True, warmup=(i == 0)).elapsed
              for i in range(REPS)]
        rows.append({"steps": steps, "total_s": min(ts)})
        print(json.dumps(rows[-1]), file=sys.stderr)
    for i, r in enumerate(rows):
        r["per_step_s"] = r["total_s"] / r["steps"]
        r["x_vs_10it"] = (r["total_s"] / rows[0]["total_s"]
                          if rows[0]["total_s"] else None)
        if i:
            p = rows[i - 1]
            dt = r["total_s"] - p["total_s"]
            if dt > NOISE_FLOOR_S:
                r["marginal_s"] = dt / (r["steps"] - p["steps"])
            else:       # window inside fence jitter: no honest marginal
                r["marginal_noise"] = True
    return rows


def section_markdown(rows, key) -> str:
    lines = [
        f"## {key['mode']} {key['grid']} on {key['platform']}"
        + (f" (mesh {key['mesh']})" if key.get("mesh") else ""), "",
        "| steps | total (s) | per-step (s) | marginal (s/step) "
        "| x vs 10 iters | steps ratio |",
        "|---|---|---|---|---|---|",
    ]
    for r in rows:
        marg = r.get("marginal_s")
        if marg is not None:
            mcell = f"{marg:.3g}"
        elif r.get("marginal_noise"):
            mcell = "(window < noise floor)"
        else:
            mcell = "—"
        x10 = r["x_vs_10it"]
        lines.append(
            f"| {r['steps']} | {r['total_s']:.4g} "
            f"| {r['per_step_s']:.3g} "
            f"| {mcell} "
            f"| {'—' if x10 is None else format(x10, '.4g')} "
            f"| {r['steps'] // 10} |")
    margs = [r["marginal_s"] for r in rows if "marginal_s" in r]
    if margs:
        spread = max(margs) / min(margs)
        lines += [
            "",
            f"Marginal spread across the decades whose window clears "
            f"the {NOISE_FLOOR_S} s fence-noise floor: {spread:.3f}x "
            f"(min {min(margs):.3e}, max {max(margs):.3e} s/step).",
        ]
    return "\n".join(lines) + "\n"


def render(all_rows) -> str:
    head = [
        "# Iteration-axis sweep — Tables 10-11 analogue", "",
        "Per-step cost constancy across 10 -> 100k iterations, per "
        "headline path — the amortized-linearity property the two-point "
        "headline estimator relies on (Report.pdf p.26-27). 'per-step' "
        "divides the raw fenced wall-clock (the fixed ~0.1-0.2 s tunnel "
        "fence dominates small counts — exactly why the headline metric "
        "is two-point); 'marginal' differences consecutive decades, "
        "cancelling the fence. Constant marginal = linear scaling; "
        "'x vs 10 it' is Table 11's own speedup diagnostic (it "
        "approaches steps/10 as the fence amortizes to nothing). "
        "CPU-mesh sections validate the sharded program shape, not "
        "real-chip speed.", "",
    ]
    groups = {}
    for r in all_rows:
        groups.setdefault(json.dumps(r["key"], sort_keys=True),
                          []).append(r)
    parts = []
    for key_s, rows in groups.items():
        rows = sorted(rows, key=lambda r: r["steps"])
        parts.append(section_markdown(rows, json.loads(key_s)))
    return "\n".join(head) + "\n" + "\n".join(parts)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("nx", nargs="?", type=int, default=2560)
    p.add_argument("ny", nargs="?", type=int, default=2048)
    p.add_argument("modes", nargs="?", default="pallas")
    p.add_argument("--platform", default=None)
    p.add_argument("--host-device-count", type=int, default=0)
    p.add_argument("--gridx", type=int, default=1)
    p.add_argument("--gridy", type=int, default=1)
    args = p.parse_args(argv)

    if args.platform == "cpu":
        from heat2d_tpu.utils.platform import force_host_devices
        force_host_devices(args.host_device_count or 1, platform="cpu")
    import jax
    d = jax.devices()[0]
    platform = getattr(d, "device_kind", d.platform)

    path = os.path.join(OUTDIR, "sweep_iters.jsonl")
    os.makedirs(OUTDIR, exist_ok=True)
    existing = []
    if os.path.exists(path):
        with open(path) as f:
            existing = [json.loads(line) for line in f if line.strip()]

    new_keys = []
    new_rows = []
    for mode in args.modes.split(","):
        key = {"mode": mode, "grid": f"{args.nx}x{args.ny}",
               "platform": platform}
        if args.gridx * args.gridy > 1:
            key["mesh"] = f"{args.gridx}x{args.gridy}"
        new_keys.append(json.dumps(key, sort_keys=True))
        for r in measure(args.nx, args.ny, mode, args.gridx, args.gridy):
            r["key"] = key
            new_rows.append(r)

    kept = [r for r in existing if r.get("key")   # drop pre-round-5
            # keyless rows (regenerated under their section key)
            and json.dumps(r["key"], sort_keys=True) not in new_keys]
    all_rows = kept + new_rows
    with open(path, "w") as f:
        f.writelines(json.dumps(r) + "\n" for r in all_rows)
    md = render(all_rows)
    with open(os.path.join(OUTDIR, "sweep_iters.md"), "w") as f:
        f.write(md)
    print(md)
    return 0


if __name__ == "__main__":
    sys.exit(main())
