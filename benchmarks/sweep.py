"""Benchmark sweep harness — the Report.pdf methodology, reproduced.

The reference's benchmark protocol (SURVEY.md §2.1 C23, §6; Report.pdf
p.21-32) sweeps the same problem over a grid-size axis (80x64 ... 2560x2048)
and a parallelism axis (1..160 MPI tasks; CUDA iteration counts 10..100k),
timing the step loop with setup excluded and reporting wall-clock, speedup
vs the 1-task run, and efficiency. This harness reproduces that sweep for
the TPU framework:

- per-chip axis: every reference grid size (plus 4096x4096, the BASELINE.md
  north-star config) through the jnp-golden ("serial") and Pallas kernel
  paths on the attached accelerator — the CUDA-table analogue (Table 10/11).
- mesh axis: the same grid sizes through dist1d/dist2d/hybrid shard_map
  programs over an N-device mesh. On a single-chip machine these run on the
  virtual CPU host platform (--platform cpu), which validates the sharded
  program at every sweep point; the wall-clock columns are then CPU
  correctness-validation numbers — flagged in the output — and become real
  ICI numbers on a pod.

Measurement protocol (matches bench.py): the timing fence (a host readback
that guarantees completion through remote-tunneled runtimes,
utils/timing._fence) costs a fixed ~0.1-0.2 s per timed call, which at
small grids dwarfs the compute. Every fixed-step point therefore reports
the TWO-POINT marginal step time — (t_hi - t_lo) / (hi - lo) with the
fixed overhead cancelled — growing hi adaptively (x10 up to 100k steps,
the reference's own amortization span for its CUDA tables) until the
difference clears the measured fence jitter. Reference comparisons use
the marginal step time x 100 (their tables are 100-iteration wall-clocks
without our tunnel fence). Convergence points report end-to-end wall-clock
(steps_done is data-dependent), like the reference's Tables 4-6.

Usage:
    python benchmarks/sweep.py --suite chip            # real-accelerator perf
    python benchmarks/sweep.py --suite mesh --platform cpu --host-device-count 8
    python benchmarks/sweep.py --suite chip --quick    # smoke
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The reference's sweep sizes (Report.pdf Table 1) + the BASELINE north star.
REF_SIZES = [(80, 64), (160, 128), (320, 256), (640, 512),
             (1280, 1024), (2560, 2048)]
NORTH_STAR = (4096, 4096)

# Reference wall-clock to put beside ours, all 100 steps (BASELINE.md):
# Table 1 serial (1 node / 1 task) column, and the derived CUDA Mcells/s.
REF_SERIAL_S = {(80, 64): 2.53e-2, (160, 128): 9.87e-2, (320, 256): 7.52e-1,
                (640, 512): 3.01, (1280, 1024): 12.7, (2560, 2048): 50.9}
REF_BEST_S = {(80, 64): 9.30e-3, (160, 128): 2.91e-2, (320, 256): 1.04e-1,
              (640, 512): 2.13e-1, (1280, 1024): 2.52e-1, (2560, 2048): 5.18e-1}
REF_CUDA_MCELLS = {(1280, 1024): 705.0, (2560, 2048): 669.0}

# Tables 4/6 (convergence-enabled build; note the reference's check fires
# every iteration at these grids, not every INTERVAL — BASELINE.md caveat):
REF_CONV_SERIAL_S = {(80, 64): 3.33e-2, (160, 128): 1.24e-1,
                     (320, 256): 8.51e-1, (640, 512): 3.39,
                     (1280, 1024): 15.8, (2560, 2048): 62.9}
REF_CONV_BEST_S = {(80, 64): 2.06e-1, (160, 128): 2.49e-1,
                   (320, 256): 2.29e-1, (640, 512): 2.42e-1,
                   (1280, 1024): 2.63e-1, (2560, 2048): 4.80e-1}

#: Adaptive two-point hi ceiling — the reference's own CUDA tables amortize
#: over up to 100k iterations (Report.pdf p.26).
MAX_HI_STEPS = 100_000

# The adaptive cross-decade-confirmed estimator and its noise constants
# live in the tune subsystem now (heat2d_tpu/tune/measure.py) — ONE copy
# of the two-point protocol, shared with heat2d-tpu-tune and the
# tune_bands/tune_panels probes. Re-exported here so sweep consumers
# (tests, notebooks) keep their import path.
from heat2d_tpu.tune.measure import (AGREE_FACTOR,  # noqa: E402,F401
                                     NOISE_FLOOR_S, two_point_estimate)


def run_point(mode, nx, ny, steps, gridx=1, gridy=1, convergence=False,
              max_hi=MAX_HI_STEPS, min_hi=None, sensitivity=None):
    from heat2d_tpu.config import HeatConfig
    from heat2d_tpu.models.solver import Heat2DSolver

    solvers = {}

    def timed_run(n):
        # First call per step count compiles + warms up; repeats skip the
        # untimed priming run (the solver cache keeps the compiled runner).
        fresh = n not in solvers
        if fresh:
            kw = {} if sensitivity is None else dict(
                sensitivity=sensitivity)
            cfg = HeatConfig(nxprob=nx, nyprob=ny, steps=n, mode=mode,
                             gridx=gridx, gridy=gridy,
                             convergence=convergence, **kw)
            solvers[n] = Heat2DSolver(cfg)
        return solvers[n].run(timed=True, warmup=fresh)

    rec = {"mode": mode, "grid": f"{nx}x{ny}", "mesh": f"{gridx}x{gridy}"}
    # sensitivity=0: the residual (a sum of squares, >= 0) can never go
    # BELOW zero, so the check runs on schedule and never fires —
    # steps_done == steps, data-independent, and the two-point marginal
    # is valid. This is THE measurement of the residual-check overhead
    # (the reference's Tables 4-6 exist to quantify it; its end-to-end
    # rows here cannot separate it from the fence).
    marginal_conv = convergence and sensitivity == 0.0
    step_time = None
    if convergence and not marginal_conv:
        # steps_done is data-dependent — end-to-end is the honest figure
        # (and what the reference's Tables 4-6 clock).
        result = timed_run(steps)
        rec.update(steps=int(result.steps_done),
                   elapsed_s=round(result.elapsed, 6),
                   mcells_per_s=round(result.mcells_per_s, 2),
                   method="end-to-end", convergence=True)
    else:
        lo = max(steps // 5, 1)
        hi0 = max(steps, min_hi or 0, lo + 1)
        step_time, hi, result = two_point_estimate(
            timed_run, lo, hi0, max_hi)
        if step_time is not None:
            rec.update(steps=hi,
                       elapsed_s=round(result.elapsed, 6),
                       step_time_s=round(step_time, 9),
                       mcells_per_s=round(nx * ny / step_time / 1e6, 2),
                       method="two-point")
        else:
            rec.update(steps=hi,
                       elapsed_s=round(result.elapsed, 6),
                       mcells_per_s=round(result.mcells_per_s, 2),
                       method="end-to-end (two-point within noise)")
        if marginal_conv:
            rec.update(convergence=True, sensitivity=0.0)

    ref_serial = REF_CONV_SERIAL_S if convergence else REF_SERIAL_S
    ref_best = REF_CONV_BEST_S if convergence else REF_BEST_S
    ref_s = ref_serial.get((nx, ny))
    if ref_s is not None:
        # Reference tables are 100-iteration wall-clocks (no tunnel
        # fence); the like-for-like figure is marginal step time x 100.
        # Convergence rows compare end-to-end wall-clocks (both sides run
        # the same capped-iteration convergence workload; marginal
        # sensitivity-0 rows use step time x 100 like fixed-step rows).
        # Noise-fallback fixed-step rows get NO ref columns: comparing
        # our fence floor to the reference's real compute would be the
        # exact distortion this protocol exists to avoid.
        if convergence and not marginal_conv:
            ours_100 = rec["elapsed_s"]
        else:
            ours_100 = step_time * 100 if step_time is not None else None
        if ours_100:
            rec["ref_serial_100step_s"] = ref_s
            rec["speedup_vs_ref_serial"] = round(ref_s / ours_100, 2)
            rec["ref_best_160task_s"] = ref_best[(nx, ny)]
            rec["speedup_vs_ref_best"] = round(
                ref_best[(nx, ny)] / ours_100, 2)
    ref_mc = REF_CUDA_MCELLS.get((nx, ny))
    if ref_mc is not None:
        rec["ref_cuda_mcells_per_s"] = ref_mc
        rec["vs_ref_cuda"] = round(rec["mcells_per_s"] / ref_mc, 2)
    # Unified record envelope (obs/record.py): every sweep row carries
    # the same schema tag + execution context as the CLI and bench
    # records (the three divergent shapes collapsed into one).
    from heat2d_tpu.obs.record import attach_context
    return attach_context(rec, "sweep-point")


def mesh_shapes(n_devices):
    """Closest-to-square factorization plus the 1D strip shape."""
    gx = int(n_devices ** 0.5)
    while n_devices % gx:
        gx -= 1
    shapes = [(gx, n_devices // gx)]
    if gx != 1:
        shapes.append((n_devices, 1))
    return shapes


def suite_chip(steps, quick):
    sizes = REF_SIZES[:2] if quick else REF_SIZES + [NORTH_STAR,
                                                     (8192, 8192)]
    for nx, ny in sizes:
        # hybrid at 1x1 mesh = the per-shard fused kernel path on one
        # chip; rows at the large sizes document the hybrid-vs-pallas
        # per-chip ratio every chip of a pod would pay (VERDICT r2 #1).
        # 8192^2 (the C3 column-panel route, round 5) skips the serial
        # row: the jnp path's amortization span there costs ~10 min for
        # a number the 4096^2 row already anchors.
        if nx >= 8192:
            modes = ("pallas", "hybrid")
        elif not quick and nx * ny >= 1280 * 1024:
            modes = ("serial", "pallas", "hybrid")
        else:
            modes = ("serial", "pallas")
        for mode in modes:
            yield dict(mode=mode, nx=nx, ny=ny, steps=steps)


def suspect_rows(records):
    """Indices of fixed-step rows whose accepted marginal is physically
    implausible and deserves one higher-amortization re-measure:

    - an accelerated mode (pallas/hybrid/dist*) reporting >10x SLOWER
      than the same grid's serial marginal (the round-2 bogus row was
      122x slower), or
    - within one mode AND mesh shape, a SMALLER grid reporting a larger
      per-step time than a bigger grid by more than the estimator's own
      AGREE_FACTOR. Small grids are latency-bound (per-step dispatch
      dominates, the protocol's own premise), so step times are roughly
      flat there and a tight threshold would flag healthy rows; only a
      violation beyond what the confirmation rule itself tolerates marks
      a row as inflated. Rows from different mesh shapes are never
      compared — their dispatch/collective floors differ.
    """
    def mesh(r):
        return r.get("mesh", "1x1")

    # Serial rows only ever run at mesh 1x1, so the baseline is keyed by
    # grid alone — dist2d/hybrid rows on multi-device meshes must still
    # hit the >10x-slower check (the mesh key is only for the
    # monotonicity comparison below, where dispatch floors differ).
    serial_st = {r["grid"]: r["step_time_s"] for r in records
                 if r["mode"] == "serial" and "step_time_s" in r}

    def cells(r):
        nx, ny = r["grid"].split("x")
        return int(nx) * int(ny)

    out = set()
    for i, r in enumerate(records):
        st = r.get("step_time_s")
        if st is None:
            continue
        base = serial_st.get(r["grid"])
        if r["mode"] != "serial" and base and st > 10 * base:
            out.add(i)
        for q in records:
            qt = q.get("step_time_s")
            if (qt is not None and q["mode"] == r["mode"]
                    and mesh(q) == mesh(r)
                    and cells(q) > cells(r) and st > AGREE_FACTOR * qt):
                out.add(i)
    # Same-mode cross-grid plausibility for LARGE grids, where per-cell
    # step time is roughly flat: without it the sweep's LARGEST grid is
    # structurally unguardable — the monotonicity check above can only
    # flag a row when a bigger grid exists, and 8192^2 has no serial
    # anchor (review r5). A bogus two-point marginal (the round-2
    # class) lands far outside AGREE_FACTOR; healthy large-row spreads
    # measure <= ~1.25x. Both rows of a violating pair re-measure (two
    # rows cannot say which is wrong; a healthy row just re-confirms).
    # Kernel-backed streaming modes only: their per-cell rate really is
    # flat once HBM-streaming-bound, but serial's XLA whole-grid loop
    # may legitimately slow per-cell as grids outgrow cache — a genuine
    # serial row must not re-measure the whole group (advisor r5).
    big = {}
    for i, r in enumerate(records):
        st = r.get("step_time_s")
        if (st is not None and cells(r) >= 1280 * 1024
                and r["mode"] in ("pallas", "hybrid")):
            big.setdefault((r["mode"], mesh(r)), []).append(
                (i, st / cells(r)))
    for group in big.values():
        percell = [p for _, p in group]
        if len(group) > 1 and max(percell) > AGREE_FACTOR * min(percell):
            out.update(i for i, _ in group)
    return sorted(out)


def sanity_pass(records, points, max_hi):
    """Re-measure suspect rows with the starting window one decade up
    (Report.pdf Table 10's own answer: amortize until the signal is
    real). The re-run's internal confirmation rule applies again; the
    re-measured record replaces the original, flagged ``rechecked``."""
    for i in suspect_rows(records):
        old = records[i]
        print(f"# suspect row (re-measuring): {json.dumps(old)}",
              file=sys.stderr)
        min_hi = min(int(old["steps"]) * 10, max_hi)
        rec = run_point(**points[i], max_hi=max_hi, min_hi=min_hi)
        rec.update(suite=old.get("suite"), platform=old.get("platform"),
                   rechecked=True)
        records[i] = rec
        # Supersede the already-streamed row on stdout too — consumers
        # piping the JSON stream would otherwise keep the bogus row the
        # recheck exists to eliminate (rechecked=True marks the
        # replacement; last row per (mode, grid, mesh) wins).
        print(json.dumps(rec))
    return records


def suite_conv(steps, quick):
    """Convergence-enabled sweep — the Tables 4-6 analogue, on the
    *intended* every-INTERVAL schedule (the reference's actual build
    checked every iteration at its measured grids; BASELINE.md caveat).

    Two row families:
    - end-to-end rows at the reference's grids/steps (the literal
      Tables 4-6 workload — early exit allowed, fence included);
    - MARGINAL overhead pairs at the large grids: a fixed-step two-point
      row and a convergence sensitivity=0 two-point row (check always
      runs, never fires — data-independent, so the marginal is valid).
      The overhead post-pass (add_conv_overhead) turns each pair into a
      % cost of the residual schedule — the number the end-to-end rows
      cannot resolve under the ~0.15 s fence (VERDICT r3 weak #3).
    """
    sizes = REF_SIZES[:2] if quick else REF_SIZES
    for nx, ny in sizes:
        for mode in ("serial", "pallas"):
            yield dict(mode=mode, nx=nx, ny=ny, steps=steps,
                       convergence=True)
    big = [s for s in sizes if s[0] * s[1] >= 1280 * 1024]
    if not quick:
        big.append(NORTH_STAR)
    for nx, ny in big:
        # hybrid pairs measure the D2R fused path — the per-chip
        # residual-schedule cost every chip of a pod pays.
        for mode in ("serial", "pallas", "hybrid"):
            yield dict(mode=mode, nx=nx, ny=ny, steps=steps)
            yield dict(mode=mode, nx=nx, ny=ny, steps=steps,
                       convergence=True, sensitivity=0.0)


def add_conv_overhead(records):
    """Post-pass for --suite conv: % cost of the residual-check schedule
    from each (fixed-step, sensitivity=0 convergence) two-point pair —
    the reference's Tables 4 vs 1 comparison, fence-free."""
    fixed = {(r["mode"], r["grid"], r["mesh"]): r.get("step_time_s")
             for r in records if not r.get("convergence")}
    for r in records:
        if r.get("sensitivity") == 0.0 and r.get("step_time_s"):
            base = fixed.get((r["mode"], r["grid"], r["mesh"]))
            if base:
                r["conv_overhead_pct"] = round(
                    (r["step_time_s"] / base - 1) * 100, 1)
    return records


def suite_scaling(steps, quick, n_devices):
    """Strong scaling at fixed global size — the Tables 2-3 analogue
    (speedup/efficiency vs the 1-device run), over power-of-two device
    counts up to what is attached."""
    nx, ny = (320, 256) if quick else (2560, 2048)
    n = 1
    while n <= n_devices:
        gx, gy = mesh_shapes(n)[0]
        yield dict(mode="dist2d", nx=nx, ny=ny, steps=steps,
                   gridx=gx, gridy=gy)
        n *= 2


def add_scaling_columns(records):
    """Post-pass: speedup vs the 1-device row and parallel efficiency,
    from marginal step times where available (fence overhead cancelled)."""
    def cost(r):
        return r.get("step_time_s") or r["elapsed_s"]
    base = next((cost(r) for r in records if r["mesh"] == "1x1"), None)
    for r in records:
        gx, gy = map(int, r["mesh"].split("x"))
        if base:
            r["speedup_vs_1dev"] = round(base / cost(r), 2)
            r["efficiency"] = round(base / cost(r) / (gx * gy), 3)
    return records


def suite_mesh(steps, quick, n_devices):
    sizes = REF_SIZES[:2] if quick else REF_SIZES
    for nx, ny in sizes:
        for gx, gy in mesh_shapes(n_devices):
            mode = "dist1d" if gy == 1 and gx != 1 else "dist2d"
            if nx % gx or ny % gy:  # the reference's divisibility rule
                continue
            yield dict(mode=mode, nx=nx, ny=ny, steps=steps,
                       gridx=gx, gridy=gy)
            if mode == "dist1d":
                # The Table-13 pair (Report.pdf p.28): the reference
                # measured its old row-strip MPI program against the
                # redesigned 2D-grid program at IDENTICAL grid and task
                # count (up to 7.89x). Ours: dist1d (row strips, the
                # mpi_heat2Dn.c analogue) vs dist2d (2D blocks, the
                # grad1612_mpi_heat.c analogue) on the same devices.
                yield dict(mode="dist2d", nx=nx, ny=ny, steps=steps,
                           gridx=gx, gridy=gy)
    # hybrid (mesh x per-chip kernel) at the largest size that divides
    gx, gy = mesh_shapes(n_devices)[0]
    for nx, ny in reversed(sizes):
        if nx % gx == 0 and ny % gy == 0:
            yield dict(mode="hybrid", nx=nx, ny=ny, steps=steps,
                       gridx=gx, gridy=gy)
            break


def redesign_payoff(records):
    """The Table-13 analogue (Report.pdf p.28): for each grid where both
    decompositions ran on the SAME device count, the cost ratio of the
    old-style row-strip program (dist1d, the mpi_heat2Dn.c analogue) to
    the redesigned 2D-block program (dist2d, grad1612_mpi_heat.c). The
    reference measured up to 7.89x from this redesign at 144 tasks.
    Returns [(grid, ndev, mesh1d, cost1d, mesh2d, cost2d, ratio)]."""
    def cost(r):
        return r.get("step_time_s") or r["elapsed_s"] / max(r["steps"], 1)

    rows = []
    by_grid = {}
    for r in records:
        gx, gy = map(int, r["mesh"].split("x"))
        by_grid.setdefault((r["grid"], gx * gy), {})[
            (r["mode"], r["mesh"])] = r
    for (grid, ndev), d in sorted(by_grid.items()):
        d1 = next((v for (m, _), v in d.items() if m == "dist1d"), None)
        # The redesign pair is the 2D-shaped dist2d run (not the 8x1
        # degenerate one, which shares dist1d's decomposition).
        d2 = next((v for (m, mesh), v in d.items()
                   if m == "dist2d" and "1" not in mesh.split("x")), None)
        if d1 and d2:
            rows.append((grid, ndev, d1["mesh"], cost(d1),
                         d2["mesh"], cost(d2),
                         round(cost(d1) / cost(d2), 2)))
    return rows


def to_markdown(records, platform, is_cpu_host):
    scaling = any("speedup_vs_1dev" in r for r in records)
    conv_oh = any("conv_overhead_pct" in r for r in records)
    extra_hdr = " speedup vs 1 dev | efficiency |" if scaling else ""
    if conv_oh:
        extra_hdr += " conv overhead % |"
    lines = [f"# heat2d-tpu sweep ({platform})", ""]
    if is_cpu_host:
        lines += [
            "**CPU-host validation run.** These wall-clocks validate the "
            "sharded SPMD program end-to-end on a virtual device mesh; "
            "they are NOT accelerator performance and say nothing about "
            "ICI scaling (that needs a real TPU pod). Use them for "
            "correctness/plumbing evidence only.", ""]
    lines += [
        "Reference columns from Report.pdf via BASELINE.md (100-iteration "
        "wall-clocks on the HellasGrid cluster, up to 160 MPI tasks, and "
        "a 2 GB GPU). Our Mcells/s and step time are TWO-POINT marginal "
        "figures (fixed fence overhead cancelled, amortized over the "
        "steps shown); 'elapsed' is the raw end-to-end wall-clock of the "
        "largest timed run including the ~0.1-0.2 s tunnel fence. "
        "Speedup columns compare the reference's 100-iteration wall-clock "
        "to our marginal step time x 100. Per-cell rates are NOT "
        "monotone in grid size across the VMEM-residency boundary: "
        "pallas grids small enough to stay resident (<= ~2.6 MB, e.g. "
        "640x512) run the zero-HBM-traffic resident kernel and can beat "
        "the streaming band kernel's per-cell rate at larger grids "
        "(640x512 has measured ~244-283 Gcells/s across sessions under "
        "long amortization — the table row below is this run's "
        "number).", "",
        "| mode | grid | mesh | steps | step time (s) | Mcells/s | "
        "elapsed (s) | method | ref serial 100-step (s) | speedup vs ref "
        f"serial | vs ref best (160 tasks) | vs ref CUDA |{extra_hdr}",
        "|---|---|---|---|---|---|---|---|---|---|---|---|"
        + ("---|---|" if scaling else "") + ("---|" if conv_oh else ""),
    ]
    for r in records:
        st = r.get("step_time_s")
        mode_cell = r["mode"]
        if r.get("sensitivity") == 0.0:
            mode_cell += " +conv(sens=0)"
        elif r.get("convergence"):
            mode_cell += " +conv"
        row = (
            f"| {mode_cell} | {r['grid']} | {r['mesh']} | {r['steps']} "
            f"| {f'{st:.3g}' if st else '—'} "
            f"| {r['mcells_per_s']:.4g} "
            f"| {r['elapsed_s']:.4g} "
            f"| {r['method']}{' +recheck' if r.get('rechecked') else ''} "
            f"| {r.get('ref_serial_100step_s', '—')} "
            f"| {r.get('speedup_vs_ref_serial', '—')} "
            f"| {r.get('speedup_vs_ref_best', '—')} "
            f"| {r.get('vs_ref_cuda', '—')} |")
        if scaling:
            row += (f" {r.get('speedup_vs_1dev', '—')} "
                    f"| {r.get('efficiency', '—')} |")
        if conv_oh:
            row += f" {r.get('conv_overhead_pct', '—')} |"
        lines.append(row)

    payoff = redesign_payoff(records)
    if payoff:
        lines += [
            "", "## Redesign payoff — Table 13 analogue", "",
            "The reference's Report.pdf p.28 (Table 13) measures its "
            "old row-strip MPI program against the redesigned 2D-grid "
            "program at identical grid and task count (up to 7.89x "
            "faster). The same pair here: dist1d (row strips, the "
            "mpi_heat2Dn.c analogue) vs dist2d (2D blocks, the "
            "grad1612_mpi_heat.c analogue), same devices. Costs are "
            "per-step (marginal where the two-point window cleared "
            "noise, elapsed/steps otherwise)."
            + (" On this CPU-host validation mesh the ratio exercises "
               "the two programs end-to-end but says nothing about ICI "
               "halo economics — the perimeter-vs-area payoff needs a "
               "real pod." if is_cpu_host else ""), "",
            "| grid | devices | dist1d mesh | dist1d step (s) | dist2d "
            "mesh | dist2d step (s) | dist1d/dist2d |",
            "|---|---|---|---|---|---|---|",
        ]
        for grid, ndev, m1, c1, m2, c2, ratio in payoff:
            lines.append(f"| {grid} | {ndev} | {m1} | {c1:.3g} "
                         f"| {m2} | {c2:.3g} | {ratio} |")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--suite", default="chip",
                   choices=["chip", "mesh", "conv", "scaling"])
    p.add_argument("--steps", type=int, default=100,
                   help="reference default (grad1612_mpi_heat.c:7); "
                        "fixed-step points grow this adaptively until the "
                        "two-point window clears fence jitter")
    p.add_argument("--quick", action="store_true")
    p.add_argument("--outdir", default="benchmarks/results")
    p.add_argument("--platform", default=None, choices=["cpu", "tpu"])
    p.add_argument("--host-device-count", type=int, default=None)
    args = p.parse_args(argv)

    if args.platform == "cpu":
        from heat2d_tpu.utils.platform import force_host_devices
        force_host_devices(args.host_device_count or 8, platform="cpu")

    import jax
    devs = jax.devices()
    platform = f"{devs[0].device_kind} x{len(devs)}"
    is_cpu_host = devs[0].platform == "cpu"
    print(f"# sweep on {platform}", file=sys.stderr)

    if args.suite == "chip":
        points = list(suite_chip(args.steps, args.quick))
    elif args.suite == "conv":
        points = list(suite_conv(args.steps, args.quick))
    elif args.suite == "scaling":
        points = list(suite_scaling(args.steps, args.quick, len(devs)))
    else:
        points = list(suite_mesh(args.steps, args.quick, len(devs)))

    max_hi = 1000 if args.quick else MAX_HI_STEPS
    records = []
    for pt in points:
        t0 = time.perf_counter()
        rec = run_point(**pt, max_hi=max_hi)
        rec["suite"] = args.suite
        rec["platform"] = platform
        records.append(rec)
        print(json.dumps(rec))
        print(f"  [{time.perf_counter() - t0:.1f}s incl. compile]",
              file=sys.stderr)

    records = sanity_pass(records, points, max_hi)
    if args.suite == "scaling":
        add_scaling_columns(records)
    elif args.suite == "conv":
        add_conv_overhead(records)

    os.makedirs(args.outdir, exist_ok=True)
    tag = f"{args.suite}{'_quick' if args.quick else ''}"
    with open(os.path.join(args.outdir, f"sweep_{tag}.jsonl"), "w") as f:
        f.writelines(json.dumps(r) + "\n" for r in records)
    with open(os.path.join(args.outdir, f"sweep_{tag}.md"), "w") as f:
        f.write(to_markdown(records, platform, is_cpu_host))
    print(f"# wrote {args.outdir}/sweep_{tag}.jsonl and .md", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
