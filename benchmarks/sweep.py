"""Benchmark sweep harness — the Report.pdf methodology, reproduced.

The reference's benchmark protocol (SURVEY.md §2.1 C23, §6; Report.pdf
p.21-32) sweeps the same problem over a grid-size axis (80x64 ... 2560x2048)
and a parallelism axis (1..160 MPI tasks; CUDA iteration counts 10..100k),
timing the step loop with setup excluded and reporting wall-clock, speedup
vs the 1-task run, and efficiency. This harness reproduces that sweep for
the TPU framework:

- per-chip axis: every reference grid size (plus 4096x4096, the BASELINE.md
  north-star config) through the jnp-golden ("serial") and Pallas kernel
  paths on the attached accelerator — the CUDA-table analogue (Table 10/11).
- mesh axis: the same grid sizes through dist1d/dist2d/hybrid shard_map
  programs over an N-device mesh. On a single-chip machine these run on the
  virtual CPU host platform (--platform cpu), which validates the sharded
  program at every sweep point; the wall-clock columns are then CPU numbers
  — flagged in the output — and become real ICI numbers on a pod.

Outputs: one JSON line per point (jsonl), plus a markdown table with the
reference's published wall-clock beside ours where a figure exists
(Report.pdf Table 1 serial column and Table 10 CUDA per-step times,
transcribed in BASELINE.md).

Usage:
    python benchmarks/sweep.py --suite chip            # real-accelerator perf
    python benchmarks/sweep.py --suite mesh --platform cpu --host-device-count 8
    python benchmarks/sweep.py --suite chip --quick    # smoke
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The reference's sweep sizes (Report.pdf Table 1) + the BASELINE north star.
REF_SIZES = [(80, 64), (160, 128), (320, 256), (640, 512),
             (1280, 1024), (2560, 2048)]
NORTH_STAR = (4096, 4096)

# Reference wall-clock to put beside ours, all 100 steps (BASELINE.md):
# Table 1 serial (1 node / 1 task) column, and the derived CUDA Mcells/s.
REF_SERIAL_S = {(80, 64): 2.53e-2, (160, 128): 9.87e-2, (320, 256): 7.52e-1,
                (640, 512): 3.01, (1280, 1024): 12.7, (2560, 2048): 50.9}
REF_BEST_S = {(80, 64): 9.30e-3, (160, 128): 2.91e-2, (320, 256): 1.04e-1,
              (640, 512): 2.13e-1, (1280, 1024): 2.52e-1, (2560, 2048): 5.18e-1}
REF_CUDA_MCELLS = {(1280, 1024): 705.0, (2560, 2048): 669.0}

# Tables 4/6 (convergence-enabled build; note the reference's check fires
# every iteration at these grids, not every INTERVAL — BASELINE.md caveat):
REF_CONV_SERIAL_S = {(80, 64): 3.33e-2, (160, 128): 1.24e-1,
                     (320, 256): 8.51e-1, (640, 512): 3.39,
                     (1280, 1024): 15.8, (2560, 2048): 62.9}
REF_CONV_BEST_S = {(80, 64): 2.06e-1, (160, 128): 2.49e-1,
                   (320, 256): 2.29e-1, (640, 512): 2.42e-1,
                   (1280, 1024): 2.63e-1, (2560, 2048): 4.80e-1}


def run_point(mode, nx, ny, steps, gridx=1, gridy=1, convergence=False):
    from heat2d_tpu.config import HeatConfig
    from heat2d_tpu.models.solver import Heat2DSolver

    cfg = HeatConfig(nxprob=nx, nyprob=ny, steps=steps, mode=mode,
                     gridx=gridx, gridy=gridy, convergence=convergence)
    solver = Heat2DSolver(cfg)
    result = solver.run(timed=True)
    rec = {
        "mode": mode, "grid": f"{nx}x{ny}", "steps": int(result.steps_done),
        "mesh": f"{gridx}x{gridy}",
        "elapsed_s": round(result.elapsed, 6),
        "mcells_per_s": round(result.mcells_per_s, 2),
    }
    if convergence:
        rec["convergence"] = True
    ref_serial = REF_CONV_SERIAL_S if convergence else REF_SERIAL_S
    ref_best = REF_CONV_BEST_S if convergence else REF_BEST_S
    ref_s = ref_serial.get((nx, ny))
    if ref_s is not None and steps == 100:
        rec["ref_serial_s"] = ref_s
        rec["speedup_vs_ref_serial"] = round(ref_s / result.elapsed, 2)
        rec["ref_best_160task_s"] = ref_best[(nx, ny)]
        rec["speedup_vs_ref_best"] = round(
            ref_best[(nx, ny)] / result.elapsed, 2)
    ref_mc = REF_CUDA_MCELLS.get((nx, ny))
    if ref_mc is not None:
        rec["ref_cuda_mcells_per_s"] = ref_mc
        rec["vs_ref_cuda"] = round(result.mcells_per_s / ref_mc, 2)
    return rec


def mesh_shapes(n_devices):
    """Closest-to-square factorization plus the 1D strip shape."""
    gx = int(n_devices ** 0.5)
    while n_devices % gx:
        gx -= 1
    shapes = [(gx, n_devices // gx)]
    if gx != 1:
        shapes.append((n_devices, 1))
    return shapes


def suite_chip(steps, quick):
    sizes = REF_SIZES[:2] if quick else REF_SIZES + [NORTH_STAR]
    for nx, ny in sizes:
        for mode in ("serial", "pallas"):
            yield dict(mode=mode, nx=nx, ny=ny, steps=steps)


def suite_conv(steps, quick):
    """Convergence-enabled sweep — the Tables 4-6 analogue, on the
    *intended* every-INTERVAL schedule (the reference's actual build
    checked every iteration at its measured grids; BASELINE.md caveat)."""
    sizes = REF_SIZES[:2] if quick else REF_SIZES
    for nx, ny in sizes:
        for mode in ("serial", "pallas"):
            yield dict(mode=mode, nx=nx, ny=ny, steps=steps,
                       convergence=True)


def suite_scaling(steps, quick, n_devices):
    """Strong scaling at fixed global size — the Tables 2-3 analogue
    (speedup/efficiency vs the 1-device run), over power-of-two device
    counts up to what is attached."""
    nx, ny = (320, 256) if quick else (2560, 2048)
    n = 1
    while n <= n_devices:
        gx, gy = mesh_shapes(n)[0]
        yield dict(mode="dist2d", nx=nx, ny=ny, steps=steps,
                   gridx=gx, gridy=gy)
        n *= 2


def add_scaling_columns(records):
    """Post-pass: speedup vs the 1-device row and parallel efficiency."""
    base = next((r["elapsed_s"] for r in records if r["mesh"] == "1x1"),
                None)
    for r in records:
        gx, gy = map(int, r["mesh"].split("x"))
        if base:
            r["speedup_vs_1dev"] = round(base / r["elapsed_s"], 2)
            r["efficiency"] = round(base / r["elapsed_s"] / (gx * gy), 3)
    return records


def suite_mesh(steps, quick, n_devices):
    sizes = REF_SIZES[:2] if quick else REF_SIZES
    for nx, ny in sizes:
        for gx, gy in mesh_shapes(n_devices):
            mode = "dist1d" if gy == 1 and gx != 1 else "dist2d"
            if nx % gx or ny % gy:  # the reference's divisibility rule
                continue
            yield dict(mode=mode, nx=nx, ny=ny, steps=steps,
                       gridx=gx, gridy=gy)
    # hybrid (mesh x per-chip kernel) at the largest size that divides
    gx, gy = mesh_shapes(n_devices)[0]
    for nx, ny in reversed(sizes):
        if nx % gx == 0 and ny % gy == 0:
            yield dict(mode="hybrid", nx=nx, ny=ny, steps=steps,
                       gridx=gx, gridy=gy)
            break


def to_markdown(records, platform):
    scaling = any("speedup_vs_1dev" in r for r in records)
    extra_hdr = " speedup vs 1 dev | efficiency |" if scaling else ""
    lines = [
        f"# heat2d-tpu sweep ({platform})", "",
        "Reference columns from Report.pdf via BASELINE.md; all runs "
        "100 steps unless noted. Reference hardware: HellasGrid cluster "
        "(up to 160 MPI tasks) and a 2 GB GPU; ours: "
        f"{platform}.", "",
        "| mode | grid | mesh | steps | elapsed (s) | Mcells/s | "
        "ref serial (s) | speedup vs ref serial | vs ref best (160 tasks) | "
        f"vs ref CUDA |{extra_hdr}",
        "|---|---|---|---|---|---|---|---|---|---|"
        + ("---|---|" if scaling else ""),
    ]
    for r in records:
        row = (
            f"| {r['mode']} | {r['grid']} | {r['mesh']} | {r['steps']} "
            f"| {r['elapsed_s']:.4g} | {r['mcells_per_s']:.4g} "
            f"| {r.get('ref_serial_s', '—')} "
            f"| {r.get('speedup_vs_ref_serial', '—')} "
            f"| {r.get('speedup_vs_ref_best', '—')} "
            f"| {r.get('vs_ref_cuda', '—')} |")
        if scaling:
            row += (f" {r.get('speedup_vs_1dev', '—')} "
                    f"| {r.get('efficiency', '—')} |")
        lines.append(row)
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--suite", default="chip",
                   choices=["chip", "mesh", "conv", "scaling"])
    p.add_argument("--steps", type=int, default=100,
                   help="reference default (grad1612_mpi_heat.c:7)")
    p.add_argument("--quick", action="store_true")
    p.add_argument("--outdir", default="benchmarks/results")
    p.add_argument("--platform", default=None, choices=["cpu", "tpu"])
    p.add_argument("--host-device-count", type=int, default=None)
    args = p.parse_args(argv)

    if args.platform == "cpu":
        from heat2d_tpu.utils.platform import force_host_devices
        force_host_devices(args.host_device_count or 8, platform="cpu")

    import jax
    devs = jax.devices()
    platform = f"{devs[0].device_kind} x{len(devs)}"
    print(f"# sweep on {platform}", file=sys.stderr)

    if args.suite == "chip":
        points = list(suite_chip(args.steps, args.quick))
    elif args.suite == "conv":
        points = list(suite_conv(args.steps, args.quick))
    elif args.suite == "scaling":
        points = list(suite_scaling(args.steps, args.quick, len(devs)))
    else:
        points = list(suite_mesh(args.steps, args.quick, len(devs)))

    records = []
    for pt in points:
        t0 = time.perf_counter()
        rec = run_point(**pt)
        rec["suite"] = args.suite
        rec["platform"] = platform
        records.append(rec)
        print(json.dumps(rec))
        print(f"  [{time.perf_counter() - t0:.1f}s incl. compile]",
              file=sys.stderr)

    if args.suite == "scaling":
        add_scaling_columns(records)

    os.makedirs(args.outdir, exist_ok=True)
    tag = f"{args.suite}{'_quick' if args.quick else ''}"
    with open(os.path.join(args.outdir, f"sweep_{tag}.jsonl"), "w") as f:
        f.writelines(json.dumps(r) + "\n" for r in records)
    with open(os.path.join(args.outdir, f"sweep_{tag}.md"), "w") as f:
        f.write(to_markdown(records, platform))
    print(f"# wrote {args.outdir}/sweep_{tag}.jsonl and .md", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
