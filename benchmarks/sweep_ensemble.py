"""Ensemble batching-efficiency sweep — the Report.pdf Tables 4-6
analogue (VERDICT r4 missing #1).

The reference's parameter studies are separate launches per (cx, cy)
configuration; the ensemble subsystem batches them into ONE launch.
This sweep commits the number that justifies it: two-point batching
efficiency, eff = B x t_single / t_batch (per-step marginals, fixed
fence cancelled), for

- a VMEM-resident class (640x512, method='pallas': one kernel, program
  grid over members), and
- an HBM class (2560x2048, method='band': the round-5 gather-free
  batched WINDOW kernel), plus the window-vs-legacy route delta.

Fixed-step and convergence (sensitivity=0 so every member runs the
full budget: measures the batched convergence machinery, not early
exit). Protocol: min-of-3 per point, spans sized >= ~1 s at the
batched point (the round-4 noise study: >=1.2 s spans repeat within
~1-3%; singles run shorter spans, so quote them +-5%).

Usage:  python benchmarks/sweep_ensemble.py
Writes benchmarks/results/sweep_ensemble.{md,jsonl}.
"""

from __future__ import annotations

import functools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from benchmarks.sweep import two_point_estimate
from heat2d_tpu.models import ensemble as ens
from heat2d_tpu.ops import inidat
from heat2d_tpu.utils.timing import timed_call

INTERVAL = 20


def _batch(nx, ny, b):
    cxs = jnp.asarray([0.05 + 0.1 * i / max(b - 1, 1) for i in range(b)],
                      jnp.float32)
    cys = jnp.asarray([0.1] * b, jnp.float32)
    u0 = jnp.broadcast_to(inidat(nx, ny), (b, nx, ny))
    return u0, cxs, cys


class _Timed:
    def __init__(self, elapsed):
        self.elapsed = elapsed


def marginal(nx, ny, b, method, conv, lo, hi):
    """Two-point marginal via the shared guarded estimator (jitter
    floor + amortized-window acceptance — the round-2 'confidently
    wrong marginal' defense sweep.py documents; review r5). Raises if
    the window is inside noise rather than committing garbage."""
    u0, cxs, cys = _batch(nx, ny, b)
    jax.block_until_ready(u0)
    runners = {}

    def timed_run(steps):
        fresh = steps not in runners
        if fresh:
            if conv:
                runners[steps] = jax.jit(
                    ens._conv_runner(method, steps, INTERVAL, 0.0))
            else:
                runners[steps] = jax.jit(functools.partial(
                    ens._BATCH_RUNNERS[method], steps=steps))
        _, el = timed_call(runners[steps], u0, cxs, cys, warmup=fresh)
        return _Timed(el)

    step, _, _ = two_point_estimate(timed_run, lo, hi, hi)
    if step is None:
        raise RuntimeError(
            f"two-point window within noise at {nx}x{ny} B={b} "
            f"(lo={lo}, hi={hi}) — grow the spans")
    return step


#: (label, nx, ny, method, B, (lo, hi) single, (lo, hi) batched)
CLASSES = [
    ("VMEM 640x512", 640, 512, "pallas", 8,
     (200_000, 1_000_000), (50_000, 250_000)),
    ("HBM 2560x2048", 2560, 2048, "band", 4,
     (10_000, 50_000), (3_000, 15_000)),
]


def main() -> int:
    dev = jax.devices()[0].device_kind
    rows = []
    fixed_batch = {}      # (nx, ny, b) -> batched fixed-step marginal
    for label, nx, ny, method, b, span1, spanb in CLASSES:
        cells = nx * ny
        for conv in (False, True):
            t1 = marginal(nx, ny, 1, method, conv, *span1)
            tb = marginal(nx, ny, b, method, conv, *spanb)
            if not conv:
                fixed_batch[(nx, ny, b)] = (tb, spanb)
            row = {
                "class": label, "method": method,
                "convergence": conv, "B": b,
                "single_step_s": t1, "batch_step_s": tb,
                "single_mcells": cells / t1 / 1e6,
                "batch_mcells_per_member": cells / (tb / b) / 1e6,
                "batching_efficiency": b * t1 / tb,
            }
            rows.append(row)
            print(json.dumps(row), flush=True)

    # Window-vs-legacy route delta (the round-5 port's gain; legacy
    # forced by disabling the window gate). Measured at BOTH widths:
    # at 8 KB rows legacy's gather tax is only ~2T/bm = 6% (bm=256),
    # so the delta is small; the C2 win concentrates at 16 KB rows
    # where legacy's envelope caps bm at 128 (tune_bands.md).
    import unittest.mock as mock
    import heat2d_tpu.ops.pallas_stencil as ps
    deltas = []
    for label, nx, ny, b, lo, hi in (
            ("HBM 2560x2048 B=4", 2560, 2048, 4, 3_000, 15_000),
            ("HBM 4096x4096 B=2", 4096, 4096, 2, 2_000, 8_000)):
        # Reuse the CLASSES-loop measurement of the same quantity
        # rather than re-measuring it with independent noise.
        cached = fixed_batch.get((nx, ny, b))
        if cached and cached[1] == (lo, hi):
            t_win = cached[0]
        else:
            t_win = marginal(nx, ny, b, "band", False, lo, hi)
        with mock.patch.object(ps, "window_band_viable",
                               lambda *a, **k: False):
            t_leg = marginal(nx, ny, b, "band", False, lo, hi)
        delta = {"class": f"{label} route delta",
                 "window_step_s": t_win, "legacy_step_s": t_leg,
                 "window_speedup": t_leg / t_win}
        deltas.append(delta)
        rows.append(delta)
        print(json.dumps(delta), flush=True)

    outdir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "results")
    os.makedirs(outdir, exist_ok=True)
    with open(os.path.join(outdir, "sweep_ensemble.jsonl"), "w") as f:
        f.writelines(json.dumps(r) + "\n" for r in rows)
    md = [
        f"# Ensemble batching efficiency ({dev}) — round 5", "",
        "Report.pdf Tables 4-6 analogue: the reference ran one (cx, cy)",
        "configuration per launch; ensembles batch B of them. "
        "eff = B x t_single / t_batch (two-point per-step marginals; "
        f"sens=0 convergence runs the full budget, INTERVAL={INTERVAL}).",
        "",
        "| class | conv | B | single (s/step) | batch (s/step) "
        "| per-member Mcells/s | efficiency |",
        "|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        if "window_speedup" in r:
            continue
        md.append(
            f"| {r['class']} ({r['method']}) "
            f"| {'yes' if r['convergence'] else 'no'} | {r['B']} "
            f"| {r['single_step_s']:.3e} | {r['batch_step_s']:.3e} "
            f"| {r['batch_mcells_per_member']:,.0f} "
            f"| {r['batching_efficiency']:.2f}x |")
    md += ["", "Gather-free window route vs legacy gathered-strip "
           "route (fixed-step) — the round-4 C2 copy elimination "
           "applied to the batch (VERDICT r4 weak #2):", ""]
    for d in deltas:
        md.append(f"- {d['class']}: **{d['window_speedup']:.2f}x** "
                  f"({d['legacy_step_s']:.3e} -> "
                  f"{d['window_step_s']:.3e} s/step)")
    with open(os.path.join(outdir, "sweep_ensemble.md"), "w") as f:
        f.write("\n".join(md) + "\n")
    print("\n".join(md))
    return 0


if __name__ == "__main__":
    sys.exit(main())
