"""Per-phase time-share profile — the mpiP analogue (SURVEY.md §5.1).

The reference's authors audited where time goes with the mpiP link-time
profiler (Report.pdf p.34-37: per-callsite MPI time shares — File_open
29%, Waitall 21% at toy size). This harness produces the same artifact
for the TPU framework: it runs one configuration under
``jax.profiler.trace`` and aggregates the captured per-op device events
into phase shares (halo exchange vs stencil compute vs residual
reduction vs synchronization), written as a committed markdown table.

Attribution keys off the trace's own op identities — HLO instruction
names and ``hlo_category`` tags on TPU, per-thunk events on the CPU
backend — so it needs no instrumentation in the measured program (the
same zero-source-change property mpiP got from PMPI interposition).

Usage:
    # real-TPU kernel profile (the VPU-bound claim, with numbers):
    python benchmarks/profile_phases.py --mode pallas --nxprob 4096 \
        --nyprob 4096 --steps 2000
    # CPU-mesh dist2d comm/compute split (validation plumbing, not ICI):
    python benchmarks/profile_phases.py --mode dist2d --platform cpu \
        --host-device-count 8 --gridx 4 --gridy 2 --nxprob 512 \
        --nyprob 512 --steps 400 --convergence
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: name-prefix -> phase, checked in order (first hit wins). These are the
#: op families XLA emits for this workload; anything unmatched lands in
#: 'other (loop control, scalars)' via the parent-span remainder.
_PHASES = [
    ("ppermute", "halo exchange (ppermute)"),
    ("collective-permute", "halo exchange (ppermute)"),
    ("all-reduce", "residual reduction (psum)"),
    ("psum", "residual reduction (psum)"),
    ("Rendezvous", "synchronization (rendezvous/wait)"),
    ("Wait", "synchronization (rendezvous/wait)"),
    ("closed_call", "stencil kernel (pallas sweep)"),
    ("custom-call", "stencil kernel (pallas sweep)"),
    ("copy", "carry copies (HBM)"),
    ("fusion", "stencil compute / strip assembly (XLA fusions)"),
    ("concatenate", "stencil compute / strip assembly (XLA fusions)"),
    ("multiply", "stencil compute / strip assembly (XLA fusions)"),
    ("select", "stencil compute / strip assembly (XLA fusions)"),
    ("pad", "stencil compute / strip assembly (XLA fusions)"),
    ("slice", "stencil compute / strip assembly (XLA fusions)"),
    ("broadcast", "stencil compute / strip assembly (XLA fusions)"),
]


def classify(name: str) -> str | None:
    for prefix, phase in _PHASES:
        if name.startswith(prefix):
            return phase
    return None


def load_trace(logdir: str) -> list[dict]:
    paths = sorted(glob.glob(
        os.path.join(logdir, "plugins/profile/*/*.trace.json.gz")))
    if not paths:
        raise FileNotFoundError(f"no trace.json.gz under {logdir}")
    with gzip.open(paths[-1]) as f:
        return json.load(f)["traceEvents"]


def phase_shares(events: list[dict]) -> tuple[dict, float, int]:
    """(phase -> seconds, total device-span seconds, n device lanes).

    TPU: the total is the 'jit_*' module span ('XLA Modules' lane); leaf
    ops live on the 'XLA Ops' lane ('while' parents skipped). CPU
    backend: the total is the per-device executor's outermost
    ThunkExecutor::Execute spans; leaf thunks carry HLO names. The
    unattributed remainder is loop control + scalar work. Seconds sum
    across device lanes (8 CPU devices => 8 lane-seconds per wall
    second) — shares are what's meaningful, as in mpiP's tables.
    """
    pids, tids = {}, {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pids[e["pid"]] = e["args"].get("name", "")
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            tids[(e["pid"], e.get("tid"))] = e["args"].get("name", "")

    shares: dict = collections.defaultdict(float)
    total = 0.0
    lanes = set()
    for e in events:
        if e.get("ph") != "X":
            continue
        pname = pids.get(e["pid"], "")
        tname = tids.get((e["pid"], e.get("tid")), "")
        dur_s = e.get("dur", 0) / 1e6
        name = e["name"]
        if "/device:TPU" in pname:
            if tname == "XLA Modules" and name.startswith("jit"):
                total += dur_s
            elif tname == "XLA Ops" and not name.startswith("while"):
                lanes.add((e["pid"], e.get("tid")))
                phase = classify(name)
                if phase:
                    shares[phase] += dur_s
        elif tname.startswith("tf_XLAPjRtCpuClient"):
            lanes.add((e["pid"], e.get("tid")))
            if name == "ThunkExecutor::Execute":
                total += dur_s
            elif not name.startswith("while"):
                phase = classify(name)
                if phase:
                    shares[phase] += dur_s
    total = max(total, sum(shares.values()))
    return dict(shares), total, max(len(lanes), 1)


def run_and_profile(args):
    if args.platform == "cpu":
        from heat2d_tpu.utils.platform import force_host_devices
        force_host_devices(args.host_device_count or 8, platform="cpu")
    import jax
    from heat2d_tpu.config import HeatConfig
    from heat2d_tpu.models.solver import Heat2DSolver

    cfg = HeatConfig(nxprob=args.nxprob, nyprob=args.nyprob,
                     steps=args.steps, mode=args.mode, gridx=args.gridx,
                     gridy=args.gridy, convergence=args.convergence)
    solver = Heat2DSolver(cfg)
    solver.run(timed=False)          # compile + warm outside the trace
    logdir = tempfile.mkdtemp(prefix="heat2d_phases_")
    with jax.profiler.trace(logdir):
        result = solver.run(timed=True, warmup=False)
    devs = jax.devices()
    platform = f"{devs[0].device_kind} x{len(devs)}"
    shares, total, nthreads = phase_shares(load_trace(logdir))
    return shares, total, nthreads, platform, result


def to_markdown(args, shares, total, nthreads, platform, result) -> str:
    is_cpu = "cpu" in platform.lower() or args.platform == "cpu"
    lines = [
        f"# Per-phase time shares — {args.mode} "
        f"{args.nxprob}x{args.nyprob} ({platform})", "",
        "The mpiP analogue (Report.pdf p.34-37: per-callsite MPI time "
        "shares). Captured with jax.profiler.trace around ONE timed run "
        "(compile/warmup excluded); seconds are device-op durations "
        f"summed over {nthreads} device execution lane(s), attributed by "
        "HLO op family. The unattributed remainder is loop control and "
        "scalar work inside the step while-loop.", "",
        f"Provenance: `python benchmarks/profile_phases.py --mode "
        f"{args.mode} --nxprob {args.nxprob} --nyprob {args.nyprob} "
        f"--steps {args.steps}"
        + (f" --gridx {args.gridx} --gridy {args.gridy}"
           if args.gridx * args.gridy > 1 else "")
        + (" --convergence" if args.convergence else "")
        + (f" --platform cpu --host-device-count "
           f"{args.host_device_count or 8}" if args.platform == "cpu"
           else "")
        + f"`; steps_done={int(result.steps_done)}, "
          f"elapsed={result.elapsed:.4f}s.", "",
    ]
    if is_cpu:
        lines += [
            "**CPU-host validation run.** Shares describe the virtual-"
            "device-mesh plumbing (thread rendezvous stands in for ICI "
            "latency); they validate where the SPMD program spends time "
            "structurally, NOT accelerator comm/compute economics.", ""]
    lines += ["| phase | device-seconds | share |", "|---|---|---|"]
    other = total - sum(shares.values())
    rows = sorted(shares.items(), key=lambda kv: -kv[1])
    if other > 1e-9:
        rows.append(("other (loop control, scalars)", other))
    for phase, secs in rows:
        lines.append(f"| {phase} | {secs:.4f} | "
                     f"{100 * secs / total:.1f}% |")
    lines.append(f"| **total device span** | **{total:.4f}** | 100% |")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--mode", default="pallas")
    p.add_argument("--nxprob", type=int, default=4096)
    p.add_argument("--nyprob", type=int, default=4096)
    p.add_argument("--steps", type=int, default=2000)
    p.add_argument("--gridx", type=int, default=1)
    p.add_argument("--gridy", type=int, default=1)
    p.add_argument("--convergence", action="store_true")
    p.add_argument("--platform", default=None, choices=["cpu", "tpu"])
    p.add_argument("--host-device-count", type=int, default=None)
    p.add_argument("--outdir", default="benchmarks/results")
    args = p.parse_args(argv)

    shares, total, nthreads, platform, result = run_and_profile(args)
    md = to_markdown(args, shares, total, nthreads, platform, result)
    os.makedirs(args.outdir, exist_ok=True)
    tag = f"{args.mode}_{'cpu' if args.platform == 'cpu' else 'tpu'}"
    path = os.path.join(args.outdir, f"phases_{tag}.md")
    with open(path, "w") as f:
        f.write(md)
    print(md)
    print(f"# wrote {path}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
