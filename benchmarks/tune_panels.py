"""C3 panel-route probe: two-point panel sweeps over (P, bm).

The C2 envelope shrinks with row width (tune_bands.md), leaving 32 KB
rows (8192^2) at bm=48 — ~10-15% under the framework's own frontier
(VERDICT r4 weak #1). C3 walks the grid in P column panels so the
deep-band envelope of narrower rows applies; this harness measures the
real (P, bm) frontier on the attached chip, including the P=1 baseline
(plain C2), so the plan_panels policy is an observed number. Usage:

    python benchmarks/tune_panels.py [nx ny]        # default 8192 8192

Calls the panel internals directly (bypassing the probed-envelope
guard): the point is to probe past it. Two-point protocol and spans per
the round-4 noise study (>=1.2 s marginal spans repeat within ~1-3%);
the protocol itself lives in ``heat2d_tpu.tune.measure`` (one copy,
shared with heat2d-tpu-tune, tune_bands, and sweep.py).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

import heat2d_tpu.ops.pallas_stencil as ps
from heat2d_tpu.ops import inidat
from heat2d_tpu.tune.measure import min_of_two_point, probe_limits


def measure(u, panels, bm, lo, hi, reps=4):
    nx = u.shape[0]

    def chunk(v, n):
        if panels == 1:
            return ps.band_chunk(v, n, 0.1, 0.1, bm=bm)
        cs = ps._panel_split(v, panels, bm, 8)
        cs = ps._panel_multi(cs, n, 8, 0.1, 0.1, bm, nx, ps._step_value)
        return ps._panel_join(cs, nx)

    # The shared two-point min-of-reps protocol (tune/measure.py).
    return min_of_two_point(jax.jit(chunk, static_argnums=1), u, lo, hi,
                            reps=reps)


def main(argv):
    explicit = None
    for a in list(argv):
        if a.startswith("--configs="):    # e.g. --configs=2:112,4:192
            explicit = [tuple(int(x) for x in c.split(":"))
                        for c in a.split("=", 1)[1].split(",")]
            argv.remove(a)
    if len(argv) == 3:
        nx, ny = int(argv[1]), int(argv[2])
    else:
        nx, ny = 8192, 8192
    u = inidat(nx, ny)
    jax.block_until_ready(u)
    cells = (nx - 2) * (ny - 2)
    # Spans sized for a >=1.2 s marginal window at the expected rate.
    lo, hi = (3000, 12000) if nx * ny >= 8192 * 8192 else (4000, 20000)
    if explicit is not None:
        configs = explicit
    else:
        configs = [(1, None)]
        for p in (2, 4, 8):
            if ny % p or (ny // p) % 128:
                continue
            nyp = ny // p
            bmx, _ = ps.plan_panel_window(nx, nyp, 8)
            cands = sorted({bmx, max(24, bmx - 8), max(24, bmx - 48),
                            min(bmx + 8, 624)})
            configs += [(p, b) for b in cands]
    print(f"# {nx}x{ny} on {jax.devices()[0].device_kind}; "
          f"two-point {lo}->{hi} steps, min of 4 per point")
    best = None
    # Probe mode as a context manager: the envelope guard is what this
    # harness probes past, and the limit is restored on ANY exit (the
    # old module-global assignment leaked probe mode on exception).
    with probe_limits("lifted by the tune_panels probe"):
        for p, bm in configs:
            if bm is None:
                bm, _ = ps.plan_window_band(nx, ny, 8)
            try:
                step = measure(u, p, bm, lo, hi)
            except Exception as e:  # noqa: BLE001 - report and move on
                print(f"P={p} bm={bm:4d}  FAILED {type(e).__name__}: "
                      f"{str(e)[:90]}")
                continue
            mcells = cells / step / 1e6
            tag = ""
            if best is None or mcells > best[0]:
                best = (mcells, p, bm)
                tag = "  <-- best"
            print(f"P={p} bm={bm:4d}  step={step:.3e}s  "
                  f"{mcells:10.1f} Mcells/s{tag}", flush=True)
    if best:
        print(f"# best: P={best[1]} bm={best[2]} {best[0]:.1f} Mcells/s")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
