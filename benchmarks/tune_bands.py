"""Band-plan tuning harness: two-point-time band_chunk over (bm, T).

The chip sweep showed the 4096^2 north-star config ~20% below the
framework's own 2560x2048 best (VERDICT r2 weak #4): plan_bands lands
bm=128 at 16 KB rows where 8 KB rows get bm=256. This harness measures
the real frontier on the attached chip so the plan policy is an
observed number, not a guess. Usage:

    python benchmarks/tune_bands.py [nx ny]

Prints one line per (bm, T) config: marginal step time and Mcells/s via
the same two-point protocol as benchmarks/sweep.py (fixed overhead
cancels between a lo- and hi-step run). Configs that fail to compile
print the error class instead — the point is to probe past the
fast-fail estimate, so the hard limit is lifted for the probe.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

import heat2d_tpu.ops.pallas_stencil as ps
from heat2d_tpu.ops import inidat
from heat2d_tpu.utils.timing import timed_call


def route_for(ny, bm, t, force_legacy):
    """Which kernel a (bm, T) point measures — band_chunk routes T=8
    lane-aligned configs to the C2 window kernel, the rest to legacy C,
    and a mixed table without labels would let C2 numbers masquerade as
    legacy-C measurements (advisor r4)."""
    if not force_legacy and ps.window_band_viable(ny, bm, t):
        return "C2"
    return "C"


def measure(u, bm, t, lo=4000, hi=20000, reps=4, force_legacy=False):
    """Two-point marginal step time, min-of-reps at each point. Spans
    follow the round-4 noise study: ~0.5 s marginal spans swing +-15%
    through the tunnel fence's heavy tails; >=1.2 s spans repeat within
    ~1-3%. One warmup per step count covers compile + program load; the
    reps run warmup-free. ``force_legacy`` measures kernel C even where
    band_chunk would route to C2."""
    if force_legacy:
        # Mirror band_chunk's legacy branch exactly: pad ONCE outside
        # the sweep loop (domain_rows carries the true row count). A
        # naive per-call band_multi_step(bm=bm) re-pads and re-slices
        # every sweep at non-divisor bm, inflating exactly the kernel-C
        # rows this flag exists to measure fairly.
        def chunk(v, n):
            nx_dom = v.shape[0]
            _, m_pad = ps._resolve_bands(nx_dom, v.shape[1], v.dtype, bm)
            if m_pad > nx_dom:
                v = jnp.pad(v, ((0, m_pad - nx_dom), (0, 0)))
            full, rem = divmod(n, t)
            if full:
                v = jax.lax.fori_loop(
                    0, full,
                    lambda _, w: ps.band_multi_step(
                        w, t, 0.1, 0.1, bm=bm, domain_rows=nx_dom),
                    v, unroll=False)
            if rem:
                v = ps.band_multi_step(v, rem, 0.1, 0.1, bm=bm,
                                       domain_rows=nx_dom)
            return v[:nx_dom]
        fn = jax.jit(chunk, static_argnums=1)
    else:
        fn = jax.jit(
            lambda v, n: ps.band_chunk(v, n, 0.1, 0.1, tsteps=t, bm=bm),
            static_argnums=1)

    def min_of(n):
        ts = [timed_call(fn, u, n)[1]]          # warms up once
        ts += [timed_call(fn, u, n, warmup=False)[1]
               for _ in range(reps - 1)]
        return min(ts)

    return (min_of(hi) - min_of(lo)) / (hi - lo)


def main(argv):
    force_legacy = "--legacy" in argv
    argv = [a for a in argv if a != "--legacy"]
    if len(argv) == 3:
        nx, ny = int(argv[1]), int(argv[2])
    elif len(argv) == 1:
        nx, ny = 4096, 4096
    else:
        print(f"usage: {argv[0]} [nx ny] [--legacy]", file=sys.stderr)
        return 1
    # Probe past the planner's own ceiling: the envelope is what we are
    # here to measure. Stamp the origin so a fast-fail inside the probe
    # reports itself as probe-lifted, not as a --vmem-budget override.
    ps.VMEM_HARD_LIMIT_BYTES = 10**9
    ps.VMEM_LIMIT_ORIGIN = "lifted by the tune_bands probe"
    u = inidat(nx, ny)
    jax.block_until_ready(u)
    cells = (nx - 2) * (ny - 2)
    configs = []
    for t in (4, 8, 12, 16):
        for bm in (64, 96, 128, 160, 192, 224, 256):
            if bm > 2 * t:
                configs.append((bm, t))
    print(f"# {nx}x{ny} on {jax.devices()[0].device_kind}; "
          f"two-point 4000->20000 steps, min of 4 per point"
          + (" (forced legacy route)" if force_legacy else ""))
    best = None
    for bm, t in configs:
        est = 5 * (bm + 2 * t) * ny * 4 / 2**20
        route = route_for(ny, bm, t, force_legacy)
        try:
            step = measure(u, bm, t, force_legacy=force_legacy)
        except Exception as e:  # noqa: BLE001 - report and move on
            print(f"bm={bm:4d} T={t:2d} {route:2s} est={est:6.1f}MB  "
                  f"FAILED {type(e).__name__}: {str(e)[:90]}")
            continue
        mcells = cells / step / 1e6
        tag = ""
        if best is None or mcells > best[0]:
            best = (mcells, bm, t, route)
            tag = "  <-- best"
        print(f"bm={bm:4d} T={t:2d} {route:2s} est={est:6.1f}MB  "
              f"step={step:.3e}s  {mcells:10.1f} Mcells/s{tag}")
    if best:
        print(f"# best: bm={best[1]} T={best[2]} ({best[3]}) "
              f"{best[0]:.1f} Mcells/s")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
