"""Band-plan tuning harness: two-point-time band_chunk over (bm, T).

Thin wrapper over the ``heat2d_tpu.tune`` library — the measurement
protocol (two-point marginal, min-of-reps, probe-mode VMEM lift) lives
in ``tune/measure.py`` now, shared with ``heat2d-tpu-tune``, the panel
probe, and ``benchmarks/sweep.py``. This harness keeps the raw
envelope-probe ergonomics: a fixed (bm, T) grid printed one line per
config, failures printed as their error class (the point is to probe
PAST the fast-fail estimate), plus ``--db PATH`` to record every point
into a persistent tuning database instead of a throwaway table. Usage:

    python benchmarks/tune_bands.py [nx ny] [--legacy] [--db PATH]

Spans follow the round-4 noise study: >=1.2 s marginal spans repeat
within ~1-3%. ``--legacy`` measures kernel C even where band_chunk
would route to C2 (mixed tables without route labels let C2 numbers
masquerade as legacy-C measurements — advisor r4).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

import heat2d_tpu.ops.pallas_stencil as ps  # noqa: F401 (probe target)
from heat2d_tpu.ops import inidat
from heat2d_tpu.tune.measure import measure_band_point, probe_limits
from heat2d_tpu.tune.space import band_est_bytes, route_for


def measure(u, bm, t, lo=4000, hi=20000, reps=4, force_legacy=False):
    """Two-point marginal step time, min-of-reps at each point (the
    shared library protocol — tune/measure.py)."""
    return measure_band_point(u, bm, t, lo=lo, hi=hi, reps=reps,
                              force_legacy=force_legacy)


def main(argv):
    force_legacy = "--legacy" in argv
    argv = [a for a in argv if a != "--legacy"]
    db_path = None
    for a in list(argv):
        if a.startswith("--db="):
            db_path = a.split("=", 1)[1]
            argv.remove(a)
    if "--db" in argv:                   # space form: --db PATH
        i = argv.index("--db")
        if i + 1 >= len(argv):
            print(f"usage: {argv[0]} [nx ny] [--legacy] [--db PATH]",
                  file=sys.stderr)
            return 1
        db_path = argv[i + 1]
        del argv[i:i + 2]
    db = None
    if db_path is not None:
        from heat2d_tpu.tune.db import TuningDB
        db = TuningDB(db_path)
    if len(argv) == 3:
        nx, ny = int(argv[1]), int(argv[2])
    elif len(argv) == 1:
        nx, ny = 4096, 4096
    else:
        print(f"usage: {argv[0]} [nx ny] [--legacy] [--db PATH]",
              file=sys.stderr)
        return 1
    u = inidat(nx, ny)
    jax.block_until_ready(u)
    cells = (nx - 2) * (ny - 2)
    configs = []
    for t in (4, 8, 12, 16):
        for bm in (64, 96, 128, 160, 192, 224, 256):
            if bm > 2 * t:
                configs.append((bm, t))
    kind = jax.devices()[0].device_kind
    print(f"# {nx}x{ny} on {kind}; "
          f"two-point 4000->20000 steps, min of 4 per point"
          + (" (forced legacy route)" if force_legacy else ""))
    best = None
    # Probe past the planner's own ceiling: the envelope is what we are
    # here to measure. The context manager stamps the origin (so a
    # fast-fail inside the probe reports itself as probe-lifted, not as
    # a --vmem-budget override) and RESTORES the limit on any exit —
    # the old module-global assignment leaked probe mode on exception.
    with probe_limits("lifted by the tune_bands probe"):
        for bm, t in configs:
            est = band_est_bytes(bm, t, ny, 4) / 2**20
            route = route_for(ny, bm, t, force_legacy)
            try:
                step = measure(u, bm, t, force_legacy=force_legacy)
            except Exception as e:  # noqa: BLE001 - report and move on
                print(f"bm={bm:4d} T={t:2d} {route:2s} est={est:6.1f}MB  "
                      f"FAILED {type(e).__name__}: {str(e)[:90]}")
                if db is not None:
                    from heat2d_tpu.tune.measure import classify_failure
                    db.record_point(kind, f"{nx}x{ny}:float32", {
                        "route": route, "bm": bm, "tsteps": t,
                        "status": classify_failure(e),
                        "error": f"{type(e).__name__}: {str(e)[:200]}"})
                    db.save()
                continue
            mcells = cells / step / 1e6
            tag = ""
            if best is None or mcells > best[0]:
                best = (mcells, bm, t, route)
                tag = "  <-- best"
            print(f"bm={bm:4d} T={t:2d} {route:2s} est={est:6.1f}MB  "
                  f"step={step:.3e}s  {mcells:10.1f} Mcells/s{tag}")
            if db is not None:
                db.record_point(kind, f"{nx}x{ny}:float32", {
                    "route": route, "bm": bm, "tsteps": t,
                    "status": "ok", "step_time_s": step,
                    "mcells_per_s": mcells})
                db.save()
    if best:
        print(f"# best: bm={best[1]} T={best[2]} ({best[3]}) "
              f"{best[0]:.1f} Mcells/s")
        if db is not None:
            from heat2d_tpu.tune.cli import _provenance
            db.set_best(kind, f"{nx}x{ny}:float32",
                        {"route": best[3], "bm": best[1],
                         "tsteps": best[2]}, best[0],
                        _provenance(None, 4000, 20000, 4))
            db.save()
            print(f"# recorded into {db.path}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
