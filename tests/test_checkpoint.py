"""Checkpoint/resume tests — the loadable version of the reference's MPI-IO
binary dumps (SURVEY.md §5.4)."""

import numpy as np

from heat2d_tpu.config import HeatConfig
from heat2d_tpu.io import (load_checkpoint, read_binary, save_checkpoint,
                           write_binary)
from heat2d_tpu.models.solver import Heat2DSolver
from heat2d_tpu.ops import inidat


def test_binary_roundtrip(tmp_path):
    u = np.asarray(inidat(12, 8))
    p = tmp_path / "state.bin"
    write_binary(u, p)
    # byte format: raw row-major f32 — exactly the MPI-IO file layout
    assert p.stat().st_size == 12 * 8 * 4
    np.testing.assert_array_equal(read_binary(p, (12, 8)), u)


def test_checkpoint_sidecar(tmp_path):
    cfg = HeatConfig(nxprob=12, nyprob=8, steps=50)
    u = np.asarray(inidat(12, 8))
    p = tmp_path / "ckpt.bin"
    save_checkpoint(u, 30, cfg, p)
    grid, step, cfg_dict = load_checkpoint(p)
    assert step == 30
    assert cfg_dict["nxprob"] == 12
    np.testing.assert_array_equal(grid, u)


def test_resume_equals_straight_run(tmp_path):
    """run(100) == run(60) -> checkpoint -> resume(40), bitwise."""
    cfg100 = HeatConfig(nxprob=16, nyprob=16, steps=100)
    full = Heat2DSolver(cfg100).run(timed=False)

    cfg60 = cfg100.replace(steps=60)
    first = Heat2DSolver(cfg60).run(timed=False)
    p = tmp_path / "ckpt.bin"
    save_checkpoint(first.u, 60, cfg60, p)

    grid, step, _ = load_checkpoint(p)
    cfg40 = cfg100.replace(steps=100 - step)
    solver = Heat2DSolver(cfg40)
    second = solver.run(u0=solver.place(grid), timed=False)

    np.testing.assert_array_equal(second.u, full.u)


def test_resume_convergence_route(tmp_path):
    """Resume parity on the CONVERGENCE route: run k fixed steps ->
    checkpoint -> resume with convergence on must stop at the same
    global step as the unsegmented convergence run, bitwise. k is a
    multiple of INTERVAL so the resumed run's residual-check schedule
    (local steps INTERVAL, 2*INTERVAL, ...) lands on the same global
    steps as the full run's."""
    import jax.numpy as jnp

    from heat2d_tpu.ops import stencil_step

    nx = ny = 16
    interval, k = 4, 8
    # Σ(Δu)² at each INTERVAL check of a straight run, with the golden
    # step — so the test can PICK a sensitivity that fires at step 12.
    u, res = inidat(nx, ny), {}
    for s in range(1, 17):
        new = stencil_step(u, 0.1, 0.1)
        if s % interval == 0:
            res[s] = float(jnp.sum((new - u) ** 2))
        u = new
    assert res[8] > res[12], res
    sens = (res[8] * res[12]) ** 0.5     # first check below: step 12

    cfg = HeatConfig(nxprob=nx, nyprob=ny, steps=200, convergence=True,
                     interval=interval, sensitivity=sens)
    full = Heat2DSolver(cfg).run(timed=False)
    assert full.steps_done == 12

    first = Heat2DSolver(
        cfg.replace(steps=k, convergence=False)).run(timed=False)
    p = tmp_path / "ckpt.bin"
    save_checkpoint(first.u, k, cfg, p)

    grid, step, _ = load_checkpoint(p)
    solver = Heat2DSolver(cfg.replace(steps=cfg.steps - step))
    second = solver.run(u0=solver.place(grid), timed=False)

    assert step + second.steps_done == full.steps_done
    np.testing.assert_array_equal(second.u, full.u)


def test_resume_sharded(tmp_path):
    """Resume a serial checkpoint into a 2x2 sharded run."""
    cfg = HeatConfig(nxprob=16, nyprob=16, steps=80)
    full = Heat2DSolver(cfg).run(timed=False)

    first = Heat2DSolver(cfg.replace(steps=50)).run(timed=False)
    p = tmp_path / "ckpt.bin"
    save_checkpoint(first.u, 50, cfg, p)

    grid, step, _ = load_checkpoint(p)
    cfg2 = cfg.replace(steps=30, mode="dist2d", gridx=2, gridy=2)
    solver = Heat2DSolver(cfg2)
    second = solver.run(u0=solver.place(grid), timed=False)
    np.testing.assert_array_equal(second.u, full.u)
