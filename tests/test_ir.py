"""Jaxpr IR verifier tests — ISSUE 18.

Four blocks:

- **repo sweep**: ``verify_all()`` over every registered program on
  the 8-device sim mesh reports ZERO findings — the CI ``ir-gate``,
  as a test — and the derived halo radius equals the declared
  ``halo_width`` for all 5 families (the acceptance criterion,
  asserted directly from the evidence rows).
- **seeded violations** (non-vacuity, one per pass): a widened
  stencil, an undeclared downcast, and an injected ``all_gather`` are
  each detected and the finding NAMES the program and the responsible
  primitive; plus the adjacent contract checks (reads mismatch,
  underivable footprint, non-nearest-neighbor ppermute, missing
  exchange, collective in a batch program, wrong band strip depth).
- **abstract domain**: the offset-interval interpreter derives exact
  per-axis offsets through slice/pad/concatenate/roll/conv/transpose
  and the ``.at[].set`` scatter lowering.
- **pins**: running the full verifier never perturbs a traced
  program — solver and batch-runner jaxprs are byte-identical before
  and after a sweep (the verifier is observation-only).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from heat2d_tpu.analysis import ir
from heat2d_tpu.analysis.dtype_flow import census_casts, precision_card
from heat2d_tpu.analysis.footprint import derive_footprint
from heat2d_tpu.parallel.mesh import make_mesh, shard_map_compat
from heat2d_tpu.parallel.sharded import COLLECTIVE_CONTRACT
from heat2d_tpu.problems.registry import family_names, get_family
from tests._pin import assert_jaxpr_equal, batch_runner_jaxpr, solver_jaxpr


@pytest.fixture(scope="module")
def report():
    """One full sweep shared by the gate + pin tests (it traces ~17
    programs; tracing is pure so sharing is sound)."""
    return ir.verify_all()


# ------------------------------------------------------------------ #
# repo sweep: the CI gate as a test
# ------------------------------------------------------------------ #

def test_repo_sweep_zero_findings(report):
    assert report.ok, "\n".join(f.describe() for f in report.findings)


def test_derived_radius_matches_declared_for_all_families(report):
    rows = {r["program"]: r for r in report.footprint_rows}
    for name in family_names():
        spec = get_family(name).spec
        row = rows[f"{name}/step"]
        assert row["derived"] == (spec.halo_width, spec.halo_width), \
            (name, row)
        assert row["derived_reads"] == spec.reads_per_step, (name, row)
    # the value-form kernels the Pallas/band templates trace, too
    for name in family_names():
        spec = get_family(name).spec
        if any(r in spec.kernel_routes for r in ("pallas", "band")):
            row = rows[f"{name}/step_value"]
            assert row["derived"] == (spec.halo_width,
                                      spec.halo_width), (name, row)


def test_sweep_covers_every_registered_route(report):
    progs = {c.program for c in report.cards}
    for name in family_names():
        for route in get_family(name).spec.kernel_routes:
            assert f"{name}/{route}" in progs
    # both sharded halo routes, fixed + convergence
    assert any(p.startswith("sharded/collective") for p in progs)
    assert any(p.startswith("sharded/fused") for p in progs)
    assert not report.notes, report.notes   # 8-device mesh: no skips


def test_sharded_census_matches_contract(report):
    rows = {r["program"]: r for r in report.collective_rows
            if r["program"].startswith("sharded/")}
    assert len(rows) == 4
    for prog, row in rows.items():
        assert row["ppermutes"] > 0 and row["ppermutes"] % 4 == 0, \
            (prog, row)


# ------------------------------------------------------------------ #
# seeded violations: each pass fires and names the culprit
# ------------------------------------------------------------------ #

def _u(nx=24, ny=24):
    return jnp.zeros((nx, ny), jnp.float32)


def test_seeded_widened_stencil_names_program_and_primitive():
    """A kernel whose true radius is 2 declared as halo_width 1."""
    fam = get_family("heat9")        # genuinely radius-2
    findings, _ = ir.check_kernel_footprint(
        "seeded/widened", lambda v: fam.step(v, 0.1, 0.1), _u(),
        declared_width=1)
    assert findings, "widened stencil must be detected"
    msg = findings[0].describe()
    assert "seeded/widened" in msg
    assert "derived access radius 2 != declared halo_width 1" in msg
    assert "primitive" in msg        # the witness is named


def test_seeded_reads_mismatch_detected():
    fam = get_family("varcoef")      # streams u + 2 coefficient fields
    findings, row = ir.check_kernel_footprint(
        "seeded/reads", lambda v: fam.step(v, 0.1, 0.1), _u(),
        declared_width=1, declared_reads=1)
    assert row["derived_reads"] == 3
    assert any("derived HBM reads/step 3" in f.message
               and "declared reads_per_step 1" in f.message
               for f in findings)


def test_seeded_underivable_footprint_is_a_finding():
    findings, _ = ir.check_kernel_footprint(
        "seeded/strided", lambda v: v[::2, :], _u(),
        declared_width=1)
    assert any("underivable" in f.message for f in findings)


def test_seeded_undeclared_downcast_named_and_allowlistable():
    def kern(v):
        return v.at[1:-1, 1:-1].set(
            v[1:-1, 1:-1].astype(jnp.bfloat16).astype(jnp.float32))

    closed = jax.make_jaxpr(kern)(_u())
    findings, card = ir.check_dtypes("seeded/downcast", closed)
    assert findings, "undeclared downcast must be detected"
    msg = findings[0].describe()
    assert "seeded/downcast" in msg
    assert "float32" in msg and "bfloat16" in msg
    # declaring it in the allowlist silences exactly that cast
    allow = (("float32", "bfloat16"), ("bfloat16", "float32"))
    findings2, _ = ir.check_dtypes("seeded/downcast", closed, allow)
    assert findings2 == []
    # an allowlist entry matching nothing is NOT an error
    findings3, _ = ir.check_dtypes(
        "seeded/downcast", closed,
        allow + (("float64", "float16"),))
    assert findings3 == []


def test_integer_index_casts_are_carded_but_not_findings():
    def kern(v):
        idx = jnp.arange(v.shape[0], dtype=jnp.int32).astype(jnp.int64)
        return v + idx[:, None].astype(v.dtype) * 0

    closed = jax.make_jaxpr(kern)(_u())
    findings, card = ir.check_dtypes("seeded/intcast", closed)
    assert any(c.src == "int32" and c.dst == "int64"
               for c in card.casts)
    assert all("int32" not in f.message or "float" in f.message
               for f in findings)
    assert not any(c.src == "int32" and c.dst == "int64"
                   for c in card.findings())


def test_seeded_injected_all_gather_is_forbidden():
    mesh = make_mesh(2, 4)
    ax, ay = mesh.axis_names

    def local(u):
        g = jax.lax.all_gather(u, ax)       # the classic regression
        return u + g.sum(axis=0)

    from jax.sharding import PartitionSpec as P
    fn = shard_map_compat(local, mesh, in_specs=(P(ax, ay),),
                          out_specs=P(ax, ay))
    closed = jax.make_jaxpr(fn)(jnp.zeros((8, 8), jnp.float32))
    findings, _ = ir.check_collectives(
        "seeded/gather", closed, COLLECTIVE_CONTRACT,
        require_exchange=False)
    assert any("forbidden collective" in f.message
               and "all_gather" in f.message for f in findings)
    assert all(f.program == "seeded/gather" for f in findings)


def test_seeded_non_neighbor_ppermute_detected():
    mesh = make_mesh(2, 4)
    ax, ay = mesh.axis_names

    def local(u):
        perm = [(0, 2), (2, 0)]             # skips a neighbor
        return sum(jax.lax.ppermute(u, ay, perm) for _ in range(4))

    from jax.sharding import PartitionSpec as P
    fn = shard_map_compat(local, mesh, in_specs=(P(ax, ay),),
                          out_specs=P(ax, ay))
    closed = jax.make_jaxpr(fn)(jnp.zeros((8, 8), jnp.float32))
    findings, _ = ir.check_collectives(
        "seeded/teleport", closed, COLLECTIVE_CONTRACT)
    assert any("not a nearest-neighbor" in f.message for f in findings)


def test_missing_exchange_detected():
    mesh = make_mesh(2, 4)
    ax, ay = mesh.axis_names

    from jax.sharding import PartitionSpec as P
    fn = shard_map_compat(lambda u: u * 2, mesh,
                          in_specs=(P(ax, ay),), out_specs=P(ax, ay))
    closed = jax.make_jaxpr(fn)(jnp.zeros((8, 8), jnp.float32))
    findings, _ = ir.check_collectives(
        "seeded/silent", closed, COLLECTIVE_CONTRACT)
    assert any("no ppermute halo exchange" in f.message
               for f in findings)


def test_collective_in_batch_program_detected():
    mesh = make_mesh(2, 4)
    ax, ay = mesh.axis_names

    def local(u):
        return jax.lax.psum(u, ax)

    from jax.sharding import PartitionSpec as P
    fn = shard_map_compat(local, mesh, in_specs=(P(ax, ay),),
                          out_specs=P(None, ay))
    closed = jax.make_jaxpr(fn)(jnp.zeros((8, 8), jnp.float32))
    findings, _ = ir.check_no_collectives("seeded/batch", closed)
    assert any("unexpected collective" in f.message
               and "psum" in f.message for f in findings)


def test_seeded_wrong_band_strip_depth_detected():
    from heat2d_tpu.ops import pallas_stencil as ps
    from heat2d_tpu.problems.runners import fixed_runner

    u0 = jnp.zeros((2, 32, 64), jnp.float32)
    cs = jnp.full((2,), 0.1, jnp.float32)
    plan = ps.band_plan(32, 64, u0.dtype, halo_width=1)
    run = fixed_runner("heat5", "band")
    closed = jax.make_jaxpr(
        lambda a, b, c: run(a, b, c, steps=plan.tsteps))(u0, cs, cs)
    ok = ir.check_band_strips("band/ok", closed, plan.halo_rows, 1)
    assert ok == []
    bad = ir.check_band_strips("band/bad", closed,
                               2 * plan.halo_rows, 2)
    assert bad and "ghost strip ships" in bad[0].message


# ------------------------------------------------------------------ #
# abstract domain: exact offsets through the covered primitives
# ------------------------------------------------------------------ #

def test_offsets_through_slice_and_pad():
    # out[i,j] = v[i+2, j-1] where data exists: slice start (2, 0)
    # shifts +2 on rows, the 1-col low pad shifts -1 on cols
    fp = derive_footprint(lambda v: jnp.pad(v[2:, :-1],
                                            ((0, 2), (1, 0))), _u())
    assert fp.derivable
    assert fp.lo == (2, -1) and fp.hi == (2, -1)
    assert fp.radius(0) == 2 and fp.radius(1) == 1


def test_offsets_through_roll():
    # jnp.roll lowers to concatenate-of-slices; the footprint is the
    # shift in both directions of the wraparound
    fp = derive_footprint(lambda v: jnp.roll(v, 1, axis=0), _u())
    assert fp.derivable
    assert fp.radius(0) >= 1 and fp.radius(1) == 0


def test_offsets_through_at_set_scatter():
    def kern(v):
        return v.at[1:-1, 1:-1].set(v[:-2, 1:-1] + v[2:, 1:-1])

    fp = derive_footprint(kern, _u())
    assert fp.derivable
    assert fp.radii() == (1, 0)
    assert fp.witness(0) == "scatter"


def test_offsets_through_conv():
    k = jnp.ones((1, 1, 5, 3), jnp.float32)

    def kern(v):
        x = v[None, None]
        y = jax.lax.conv_general_dilated(
            x, k, window_strides=(1, 1), padding=((2, 2), (1, 1)))
        return y[0, 0]

    fp = derive_footprint(kern, _u())
    assert fp.derivable
    assert fp.radii() == (2, 1)
    assert fp.witness(0) == "conv_general_dilated"


def test_offsets_through_transpose():
    # the offset follows the axis through the permutation: a +2 row
    # shift before a transpose appears on output axis 1
    fp = derive_footprint(lambda v: (v[2:, :]).T, _u())
    assert fp.derivable
    assert fp.lo == (0, 2) and fp.hi == (0, 2)


def test_elementwise_broadcast_of_dep_value_is_top():
    # a dep value reduced then broadcast loses per-element
    # correspondence: must be TOP, not silently radius 0
    fp = derive_footprint(lambda v: v * v.mean(), _u())
    assert not fp.derivable


def test_coefficient_reads_counted_once_across_views():
    cxf = jnp.linspace(0.1, 0.2, 24 * 24).reshape(24, 24)

    def kern(v):
        # two slices of ONE field: one coefficient read, not two
        return v[1:-1, :] * cxf[1:-1, :] + v[:-2, :] * cxf[:-2, :]

    fp = derive_footprint(kern, _u())
    assert fp.coef_reads == 1


# ------------------------------------------------------------------ #
# precision cards
# ------------------------------------------------------------------ #

def test_precision_card_provenance_paths():
    def inner(v):
        return v.astype(jnp.float64)

    def outer(v):
        return jax.jit(inner)(v).astype(jnp.float32)

    card = precision_card("prov", outer, _u())
    paths = {c.path for c in card.casts}
    assert any(p and p[0].startswith("pjit") for p in paths)
    assert any(p == () for p in paths)


def test_census_casts_aggregates_counts():
    def kern(v):
        a = v.astype(jnp.float64).astype(jnp.float32)
        b = v.astype(jnp.float64).astype(jnp.float32)
        return a + b

    casts = census_casts(jax.make_jaxpr(kern)(_u()))
    up = [c for c in casts if c.dst == "float64"]
    assert up and up[0].count == 2


# ------------------------------------------------------------------ #
# pins: the verifier never perturbs a traced program
# ------------------------------------------------------------------ #

def test_verifier_leaves_traced_programs_byte_identical(report):
    # `report` ran the FULL sweep in this process before these traces
    before_solver = solver_jaxpr()
    before_batch = batch_runner_jaxpr(problem="varcoef")
    rep2 = ir.verify_all(include_sharded=False)
    assert rep2.ok
    assert_jaxpr_equal(before_solver, solver_jaxpr(),
                       label="solver after IR sweep")
    assert_jaxpr_equal(before_batch,
                       batch_runner_jaxpr(problem="varcoef"),
                       label="batch runner after IR sweep")


def test_render_report_names_programs(report):
    text = ir.render_report(report, verbose=True)
    assert "heat9/step: declared w=2, derived radii (2, 2)" in text
    assert "no IR findings" in text
