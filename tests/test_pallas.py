"""Pallas kernel tests (interpreter mode on the CPU harness — the
SURVEY.md §4 'pltpu interpret' strategy). The kernels must reproduce the
jnp golden model."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from heat2d_tpu.config import HeatConfig
from heat2d_tpu.models.solver import Heat2DSolver
from heat2d_tpu.ops import inidat, stencil_step
from heat2d_tpu.ops.pallas_stencil import (band_chunk, band_multi_step,
                                           band_step, fits_vmem,
                                           make_padded_kernel,
                                           multi_step_vmem, pick_band_rows)


def _golden(u, steps):
    for _ in range(steps):
        u = stencil_step(u, 0.1, 0.1)
    return np.asarray(u)


@pytest.mark.parametrize("shape", [(16, 16), (32, 128), (64, 256)])
def test_vmem_kernel_matches_golden(shape):
    u0 = inidat(*shape)
    got = np.asarray(jax.jit(
        lambda u: multi_step_vmem(u, 5, 0.1, 0.1))(u0))
    np.testing.assert_allclose(got, _golden(u0, 5), rtol=1e-6, atol=1e-4)


@pytest.mark.parametrize("shape,bm", [((32, 128), 8), ((64, 128), 16),
                                      ((64, 256), None)])
def test_band_kernel_matches_golden(shape, bm):
    u0 = inidat(*shape)
    got = np.asarray(jax.jit(
        lambda u: band_step(u, 0.1, 0.1, bm=bm))(u0))
    np.testing.assert_allclose(got, _golden(u0, 1), rtol=1e-6, atol=1e-4)


def test_band_kernel_multi_step():
    u0 = inidat(32, 128)
    u = u0
    for _ in range(4):
        u = band_step(u, 0.1, 0.1, bm=8)
    np.testing.assert_allclose(np.asarray(u), _golden(u0, 4),
                               rtol=1e-6, atol=1e-4)


@pytest.mark.parametrize("tsteps", [1, 2, 3, 7])
def test_band_multi_step_matches_golden(tsteps):
    """Temporal blocking: T steps per sweep == T golden steps, including
    the stale-halo firewall at the global boundary bands."""
    u0 = inidat(64, 128)
    got = np.asarray(jax.jit(
        lambda u: band_multi_step(u, tsteps, 0.1, 0.1, bm=16))(u0))
    np.testing.assert_allclose(got, _golden(u0, tsteps), rtol=1e-6, atol=1e-4)


def test_band_multi_step_shallow_band_fallback():
    # bm <= 2T: not enough halo depth — must fall back to stepwise and
    # still be exact.
    u0 = inidat(32, 128)
    got = np.asarray(jax.jit(
        lambda u: band_multi_step(u, 5, 0.1, 0.1, bm=8))(u0))
    np.testing.assert_allclose(got, _golden(u0, 5), rtol=1e-6, atol=1e-4)


@pytest.mark.parametrize("n", [1, 7, 8, 9, 20])
def test_band_chunk_any_step_count(n):
    u0 = inidat(64, 128)
    got = np.asarray(jax.jit(
        lambda u: band_chunk(u, n, 0.1, 0.1, tsteps=4, bm=16))(u0))
    np.testing.assert_allclose(got, _golden(u0, n), rtol=1e-6, atol=1e-4)


def test_pick_band_rows():
    assert pick_band_rows(4096, 4096) == 128      # 2MB / 16KB rows
    assert 4096 % pick_band_rows(4096, 4096) == 0
    assert pick_band_rows(10, 10) == 10           # tiny grid: one band
    # Wide grids (rows > 16KB) halve the target: 1MB / 32KB rows. The
    # empirical v5e VMEM envelope — 2MB bands fail to compile at ny=8192.
    assert pick_band_rows(8192, 8192) == 32


def test_fits_vmem():
    assert fits_vmem((640, 1024))       # the reference CUDA config
    assert not fits_vmem((4096, 4096))  # headline config streams


def test_pallas_mode_solver_matches_serial():
    cfg = HeatConfig(nxprob=32, nyprob=128, steps=20, mode="pallas")
    got = Heat2DSolver(cfg).run(timed=False)
    want = Heat2DSolver(cfg.replace(mode="serial")).run(timed=False)
    assert got.steps_done == 20
    np.testing.assert_allclose(got.u, want.u, rtol=1e-6, atol=1e-4)


def test_pallas_mode_convergence():
    cfg = HeatConfig(nxprob=32, nyprob=128, steps=100000, mode="pallas",
                     convergence=True, interval=20, sensitivity=0.5)
    got = Heat2DSolver(cfg).run(timed=False)
    want = Heat2DSolver(cfg.replace(mode="serial")).run(timed=False)
    assert got.steps_done == want.steps_done
    # ~10k steps: the kernel's FMA factoring drifts from the literal serial
    # form at ulp/step, compounding to ~3e-4 rel — the Appendix-B class of
    # deviation (long runs validate by residual/step-count, short runs are
    # held tight elsewhere in this file).
    np.testing.assert_allclose(got.u, want.u, rtol=1e-3, atol=1e-3)


def test_padded_kernel_matches_padded_golden(rng):
    from heat2d_tpu.ops.stencil import stencil_step_padded
    cfg = HeatConfig(nxprob=16, nyprob=16)
    k = make_padded_kernel(cfg)
    padded = rng.standard_normal((18, 18)).astype(np.float32)
    got = np.asarray(k(jnp.asarray(padded), 0.1, 0.1))
    want = np.asarray(stencil_step_padded(jnp.asarray(padded), 0.1, 0.1))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-5)


def test_hybrid_mode_matches_serial():
    """hybrid = 2D mesh x per-shard Pallas kernel (the MPI+OpenMP analogue
    done right — SURVEY.md A.3)."""
    cfg = HeatConfig(nxprob=32, nyprob=256, steps=10, mode="hybrid",
                     gridx=2, gridy=2)
    got = Heat2DSolver(cfg).run(timed=False)
    want = Heat2DSolver(cfg.replace(mode="serial", gridx=1, gridy=1)
                        ).run(timed=False)
    np.testing.assert_allclose(got.u, want.u, rtol=1e-6, atol=1e-4)
