"""Pallas kernel tests (interpreter mode on the CPU harness — the
SURVEY.md §4 'pltpu interpret' strategy). The kernels must reproduce the
jnp golden model."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from heat2d_tpu.config import HeatConfig
from heat2d_tpu.models.solver import Heat2DSolver
from heat2d_tpu.ops import inidat, stencil_step
from heat2d_tpu.ops.pallas_stencil import (band_chunk, band_multi_step,
                                           band_step, fits_vmem,
                                           make_shard_chunk_kernel,
                                           multi_step_vmem, plan_bands)


def _golden(u, steps):
    for _ in range(steps):
        u = stencil_step(u, 0.1, 0.1)
    return np.asarray(u)


@pytest.mark.parametrize("shape", [(16, 16), (32, 128), (64, 256)])
def test_vmem_kernel_matches_golden(shape):
    u0 = inidat(*shape)
    got = np.asarray(jax.jit(
        lambda u: multi_step_vmem(u, 5, 0.1, 0.1))(u0))
    np.testing.assert_allclose(got, _golden(u0, 5), rtol=1e-6, atol=1e-4)


@pytest.mark.parametrize("shape,bm", [((32, 128), 8), ((64, 128), 16),
                                      ((64, 256), None)])
def test_band_kernel_matches_golden(shape, bm):
    u0 = inidat(*shape)
    got = np.asarray(jax.jit(
        lambda u: band_step(u, 0.1, 0.1, bm=bm))(u0))
    np.testing.assert_allclose(got, _golden(u0, 1), rtol=1e-6, atol=1e-4)


def test_band_kernel_multi_step():
    u0 = inidat(32, 128)
    u = u0
    for _ in range(4):
        u = band_step(u, 0.1, 0.1, bm=8)
    np.testing.assert_allclose(np.asarray(u), _golden(u0, 4),
                               rtol=1e-6, atol=1e-4)


@pytest.mark.parametrize("tsteps", [1, 2, 3, 7])
def test_band_multi_step_matches_golden(tsteps):
    """Temporal blocking: T steps per sweep == T golden steps, including
    the stale-halo firewall at the global boundary bands."""
    u0 = inidat(64, 128)
    got = np.asarray(jax.jit(
        lambda u: band_multi_step(u, tsteps, 0.1, 0.1, bm=16))(u0))
    np.testing.assert_allclose(got, _golden(u0, tsteps), rtol=1e-6, atol=1e-4)


def test_band_multi_step_shallow_band_fallback():
    # bm <= 2T: not enough halo depth — must fall back to stepwise and
    # still be exact.
    u0 = inidat(32, 128)
    got = np.asarray(jax.jit(
        lambda u: band_multi_step(u, 5, 0.1, 0.1, bm=8))(u0))
    np.testing.assert_allclose(got, _golden(u0, 5), rtol=1e-6, atol=1e-4)


@pytest.mark.parametrize("n", [1, 7, 8, 9, 20])
def test_band_chunk_any_step_count(n):
    u0 = inidat(64, 128)
    got = np.asarray(jax.jit(
        lambda u: band_chunk(u, n, 0.1, 0.1, tsteps=4, bm=16))(u0))
    np.testing.assert_allclose(got, _golden(u0, n), rtol=1e-6, atol=1e-4)


def test_plan_bands():
    assert plan_bands(4096, 4096) == (128, 4096)  # 2MB / 16KB rows
    assert plan_bands(10, 10) == (10, 10)         # tiny grid: one band
    # 2MB bands hold through 32KB rows (bm=64 at ny=8192 measured 191
    # vs 143 Gcells/s with the old halved target); the halving kicks in
    # past 32KB rows where the band estimate would cross the hard limit.
    assert plan_bands(8192, 8192) == (64, 8192)
    assert plan_bands(16384, 16384) == (16, 16384)
    # Divisor-poor row counts keep a full 8-aligned band via padding
    # instead of collapsing to single-row programs (VERDICT r1 weak #4).
    bm, m_pad = plan_bands(4099, 4096)
    assert bm == 128 and m_pad == 4224 and m_pad % bm == 0
    bm, m_pad = plan_bands(2064, 2064)            # a shard's nx+2T block
    assert bm % 8 == 0 and m_pad % bm == 0 and bm >= 128


def test_band_vmem_fast_fail():
    """Over-wide rows must fail fast with an actionable message, not an
    opaque remote-compile HTTP 500 / multi-minute hang (VERDICT r1 #7)."""
    u0 = jnp.zeros((64, 70000), jnp.float32)
    with pytest.raises(ValueError, match="VMEM"):
        band_step(u0, 0.1, 0.1, bm=32)


def test_fits_vmem():
    assert fits_vmem((640, 1024))       # the reference CUDA config
    assert not fits_vmem((4096, 4096))  # headline config streams


def test_vmem_envelope_derivation(monkeypatch):
    """Budget and hard limit derive from the detected device kind (VERDICT
    r2 weak #5: was hard-coded v5e constants), with --vmem-budget as the
    override path."""
    import heat2d_tpu.ops.pallas_stencil as ps
    monkeypatch.setattr(ps, "_detected", (16 * 2**20, "TPU v5 lite"))
    assert ps.vmem_budget_bytes() == 8 * 2**20
    assert ps.vmem_hard_limit_bytes() == 14 * 2**20
    monkeypatch.setattr(ps, "_detected", (32 * 2**20, "TPU v4"))
    assert ps.vmem_budget_bytes() == 16 * 2**20
    assert ps.vmem_hard_limit_bytes() == 30 * 2**20
    # Override wins over detection and re-derives both numbers.
    monkeypatch.setattr(ps, "VMEM_BUDGET_BYTES", None)
    monkeypatch.setattr(ps, "VMEM_HARD_LIMIT_BYTES", None)
    ps.set_vmem_budget(8 * 2**20)
    try:
        assert ps.vmem_budget_bytes() == 4 * 2**20
        assert ps.vmem_hard_limit_bytes() == 6 * 2**20
    finally:
        ps.VMEM_BUDGET_BYTES = None
        ps.VMEM_HARD_LIMIT_BYTES = None
    with pytest.raises(ValueError, match="vmem-budget"):
        ps.set_vmem_budget(1024)


def test_band_vmem_fail_cites_detected_device(monkeypatch):
    import heat2d_tpu.ops.pallas_stencil as ps
    monkeypatch.setattr(ps, "_detected", (16 * 2**20, "TPU v5 lite"))
    u0 = jnp.zeros((64, 70000), jnp.float32)
    with pytest.raises(ValueError, match="TPU v5 lite"):
        band_step(u0, 0.1, 0.1, bm=32)


@pytest.mark.parametrize("shape", [(32, 128),     # VMEM-resident: kernel A
                                   (96, 20000)])  # HBM-routed: kernels B/C
def test_pallas_mode_bitwise_parity_flag(shape):
    """--bitwise-parity must make BOTH pallas routes (VMEM-resident and
    band-streamed) bitwise identical to serial, not silently no-op."""
    nx, ny = shape
    cfg = HeatConfig(nxprob=nx, nyprob=ny, steps=17, mode="pallas",
                     bitwise_parity=True)
    got = Heat2DSolver(cfg).run(timed=False)
    want = Heat2DSolver(cfg.replace(mode="serial")).run(timed=False)
    np.testing.assert_array_equal(got.u, want.u)


def test_shard_band_shallow_fallback_bitwise():
    """rb < t (deep halo, tiny band) must fall back to depth-1 sweeps on
    the assembled block and stay bitwise — the config class the round-2
    stepwise path served."""
    from heat2d_tpu.ops.pallas_stencil import _shard_band_chunk
    nx = ny = 48
    t = 12          # > rb=8: forces the fallback
    g = np.zeros((nx + 2 * t, ny + 2 * t), np.float32)
    g[t:-t, t:-t] = np.asarray(inidat(nx, ny))
    ext = jnp.asarray(g)   # whole grid as one "shard" with zero halos
    u, strips = _strips_from_ext(ext, t)
    scalars = jnp.asarray([0, 0], jnp.int32)
    got = _shard_band_chunk(u, strips, scalars, t, 0.1, 0.1, nx, ny, bm=8)
    want = _golden_shard_chunk(ext, t, -t, -t, nx, ny)
    np.testing.assert_array_equal(np.asarray(got), want[t:-t, t:-t])


def test_pallas_mode_solver_matches_serial():
    cfg = HeatConfig(nxprob=32, nyprob=128, steps=20, mode="pallas")
    got = Heat2DSolver(cfg).run(timed=False)
    want = Heat2DSolver(cfg.replace(mode="serial")).run(timed=False)
    assert got.steps_done == 20
    np.testing.assert_allclose(got.u, want.u, rtol=1e-6, atol=1e-4)


def test_pallas_mode_convergence():
    cfg = HeatConfig(nxprob=32, nyprob=128, steps=100000, mode="pallas",
                     convergence=True, interval=20, sensitivity=0.5)
    got = Heat2DSolver(cfg).run(timed=False)
    want = Heat2DSolver(cfg.replace(mode="serial")).run(timed=False)
    assert got.steps_done == want.steps_done
    # ~10k steps: the kernel's FMA factoring drifts from the literal serial
    # form at ulp/step, compounding to ~3e-4 rel — the Appendix-B class of
    # deviation (long runs validate by residual/step-count, short runs are
    # held tight elsewhere in this file).
    np.testing.assert_allclose(got.u, want.u, rtol=1e-3, atol=1e-3)


def _golden_shard_chunk(ext, t, row0, col0, nx, ny):
    """The jnp golden loop of parallel.sharded.make_local_chunk: t keep-
    masked steps on the extended block; only [t:-t, t:-t] is exact."""
    from jax import lax
    from heat2d_tpu.ops.stencil import stencil_step_padded
    from heat2d_tpu.parallel.sharded import _keep_mask
    keep = _keep_mask(ext.shape, nx, ny, row0, col0)
    v = jnp.asarray(ext)
    for _ in range(t):
        newint = stencil_step_padded(v, 0.1, 0.1)
        mid = jnp.concatenate([v[1:-1, :1], newint, v[1:-1, -1:]], axis=1)
        full = jnp.concatenate([v[:1, :], mid, v[-1:, :]], axis=0)
        v = jnp.where(keep, v, full)
    return np.asarray(v)


def _strips_from_ext(ext, t):
    """Fused-kernel operands from a pre-assembled extended block: the
    (bm, bn) center plus (north, south, west, east) halo strips in the
    exchange_halo_strips layout (west/east carry the corners)."""
    u = ext[t:-t, t:-t]
    north = ext[:t, t:-t]
    south = ext[-t:, t:-t]
    west = ext[:, :t]
    east = ext[:, -t:]
    return u, (north, south, west, east)


@pytest.mark.parametrize("si,sj", [(0, 0), (0, 1), (1, 0), (1, 1)])
@pytest.mark.parametrize("variant", ["vmem", "band", "band-uneven"])
def test_shard_chunk_kernels_center_bitwise(si, sj, variant):
    """Kernel D (both routes) must reproduce the golden wide-halo loop's
    kept center bitwise, at every shard position of a 2x2 decomposition
    (covers all global-boundary/ghost-corner cases). The band route runs
    with rb=8 (16-row block = 2 bands); 'band-uneven' with rb=12 so the
    block pads and the south strip embeds below the domain rows."""
    from heat2d_tpu.ops.pallas_stencil import (_shard_band_chunk,
                                               _shard_vmem_chunk)
    nx = ny = 32
    t = 3
    bm = bn = 16
    g = np.zeros((nx + 2 * t, ny + 2 * t), np.float32)
    g[t:-t, t:-t] = np.asarray(inidat(nx, ny))
    r0, c0 = si * bm, sj * bn
    ext = jnp.asarray(g[r0:r0 + bm + 2 * t, c0:c0 + bn + 2 * t])
    u, strips = _strips_from_ext(ext, t)
    scalars = jnp.asarray([r0, c0], jnp.int32)
    if variant == "vmem":
        got = _shard_vmem_chunk(u, strips, scalars, t, 0.1, 0.1, nx, ny)
    else:
        rb = 8 if variant == "band" else 12
        got = _shard_band_chunk(u, strips, scalars, t, 0.1, 0.1, nx, ny,
                                bm=rb)
    want = _golden_shard_chunk(ext, t, r0 - t, c0 - t, nx, ny)
    np.testing.assert_array_equal(np.asarray(got), want[t:-t, t:-t])


def test_hybrid_band_route_bitwise(monkeypatch):
    """Force the hybrid router down the streaming band path (as real-TPU
    shards >= ~1400^2 are) and require bitwise serial parity — the r1
    VMEM-OOM capability gap, VERDICT #1."""
    import heat2d_tpu.ops.pallas_stencil as ps
    monkeypatch.setattr(ps, "VMEM_BUDGET_BYTES", 1024)
    cfg = HeatConfig(nxprob=32, nyprob=256, steps=10, mode="hybrid",
                     gridx=2, gridy=2, bitwise_parity=True)
    got = Heat2DSolver(cfg).run(timed=False)
    want = Heat2DSolver(cfg.replace(mode="serial", gridx=1, gridy=1)
                        ).run(timed=False)
    np.testing.assert_array_equal(got.u, want.u)


def test_hybrid_band_route_fma_default(monkeypatch):
    """The band route with the default FMA step form: ulp-class agreement
    with serial (bitwise is opt-in via bitwise_parity)."""
    import heat2d_tpu.ops.pallas_stencil as ps
    monkeypatch.setattr(ps, "VMEM_BUDGET_BYTES", 1024)
    cfg = HeatConfig(nxprob=32, nyprob=256, steps=10, mode="hybrid",
                     gridx=2, gridy=2)
    got = Heat2DSolver(cfg).run(timed=False)
    want = Heat2DSolver(cfg.replace(mode="serial", gridx=1, gridy=1)
                        ).run(timed=False)
    np.testing.assert_allclose(got.u, want.u, rtol=1e-6, atol=1e-4)


def test_hybrid_mode_matches_serial():
    """hybrid = 2D mesh x per-shard Pallas kernel (the MPI+OpenMP analogue
    done right — SURVEY.md A.3). Default step form is the FMA factoring:
    ulp-class agreement."""
    cfg = HeatConfig(nxprob=32, nyprob=256, steps=10, mode="hybrid",
                     gridx=2, gridy=2)
    got = Heat2DSolver(cfg).run(timed=False)
    want = Heat2DSolver(cfg.replace(mode="serial", gridx=1, gridy=1)
                        ).run(timed=False)
    np.testing.assert_allclose(got.u, want.u, rtol=1e-6, atol=1e-4)


def test_window_envelope_planner():
    """The window planners' envelope decisions (the probed table applies
    off-TPU too: the VMEM fallback total matches the probed device).
    Pins the 8192^2 compile-OOM class: the fallback byte cap must never
    exceed the probed 32 KB entry or the verified off-table ceiling."""
    import heat2d_tpu.ops.pallas_stencil as ps

    # Probed entries (bm + 2T <= table ext rows).
    assert ps._window_ext_rows(16 * 1024, 8) == 176
    assert ps._window_ext_rows(8 * 1024, 8) == 336
    assert ps._window_ext_rows(32 * 1024, 8) == 64
    # Unprobed widths: 24 KB held to the widest probe point's byte
    # budget; 4 KB to the verified 640-row ceiling.
    assert ps._window_ext_rows(24 * 1024, 8) * 24 * 1024 \
        <= ps.vmem_budget_bytes() // 4
    assert ps._window_ext_rows(4 * 1024, 8) == 640
    # Budget override bypasses the table; exactly-32 KB rows must still
    # land at or under the probed break (the review finding: '>' vs
    # '>=' admitted the 16.76 MB OOM config under an override equal to
    # the default).
    old = ps.VMEM_BUDGET_BYTES
    try:
        ps.VMEM_BUDGET_BYTES = 8 * 1024 * 1024
        assert ps._window_ext_rows(32 * 1024, 8) <= 64
    finally:
        ps.VMEM_BUDGET_BYTES = old

    # On a kind the table was MEASURED on, no override direction may
    # admit shapes past the compile break points — the table binds the
    # plan AND (via the shared _probed_ext_rows) the explicit-bm
    # fast-fail (advisor r4 + review r5); off-table widths keep the
    # default-budget byte cap under a raise; a LOWERED override still
    # tightens everywhere.
    import unittest.mock as mock
    with mock.patch.object(ps, "_detected", (16 * 2**20, "TPU v5 lite")):
        old = ps.VMEM_BUDGET_BYTES
        default_24k = ps._window_ext_rows(24 * 1024, 8)
        try:
            ps.VMEM_BUDGET_BYTES = 32 * 1024 * 1024
            assert ps._probed_ext_rows(32 * 1024) == 64
            assert ps._window_ext_rows(32 * 1024, 8) == 64
            assert ps._window_ext_rows(16 * 1024, 8) == 176
            assert ps._window_ext_rows(24 * 1024, 8) == default_24k
            ps.VMEM_BUDGET_BYTES = 2 * 1024 * 1024
            assert ps._probed_ext_rows(32 * 1024) == 64  # fast-fail bound
            assert ps._window_ext_rows(16 * 1024, 8) < 176
        finally:
            ps.VMEM_BUDGET_BYTES = old
    # An UNPROBED kind honors an explicit raise (the documented escape
    # hatch — its true break points are unknown).
    with mock.patch.object(ps, "_detected", (16 * 2**20, "TPU vNext")):
        old = ps.VMEM_BUDGET_BYTES
        try:
            ps.VMEM_BUDGET_BYTES = 32 * 1024 * 1024
            assert ps._probed_ext_rows(16 * 1024) is None
            assert ps._window_ext_rows(16 * 1024, 8) > 176
        finally:
            ps.VMEM_BUDGET_BYTES = old

    # plan_window_band: pad-aware full-range scan (the 1280x1024 fix:
    # bm=624 padded 592 rows; 432 pads 16 and sweeps 30% fewer rows).
    bm, m_pad = ps.plan_window_band(1280, 1024, 8)
    assert bm == 432 and m_pad == 1296
    bm, _ = ps.plan_window_band(4096, 4096, 8)
    assert bm == 152
    bm, _ = ps.plan_window_band(2560, 2048, 8)
    assert bm == 320
    bm, _ = ps.plan_window_band(8192, 8192, 8)
    assert bm == 48


def test_pad_aware_bm_single_tall_band():
    """The advisor-r5 gap: when the single TALL band ceil(nrows/8)*8
    fits the ext envelope, it must compete — one (tall + 2T)-row sweep
    can beat every rounded-down multi-band candidate."""
    import heat2d_tpu.ops.pallas_stencil as ps

    # 100 rows, envelope 104: one 104-band sweeps 120 ext rows; the old
    # scan topped out at 96 (2 bands x 112 = 224) and picked 56 (144).
    assert ps._pad_aware_bm(100, 104, 8) == 104
    # Envelope one notch tighter: the tall band no longer fits and the
    # scan's best multi-band candidate is kept.
    assert ps._pad_aware_bm(100, 96, 8) == 56
    # Exact single band (zero pad) unchanged.
    assert ps._pad_aware_bm(320, 1000, 8) == 320
    # A tall band at/under the 2T window-viability floor never competes
    # (16 rows at T=8 == 2T: viability would reject it downstream).
    assert ps._pad_aware_bm(10, 1000, 8) == 8


def test_shard_window_planner_pads_divisor_poor_heights():
    """The D2 divisor cliff (VERDICT r4 weak #4): shard heights with no
    deep 8-aligned divisor must stay on the window route via padding,
    not silently fall to kernel D's ~1 MB gathered bands."""
    import unittest.mock as mock
    import heat2d_tpu.ops.pallas_stencil as ps

    assert ps.plan_shard_window(1048, 2048, 8) is None  # off-TPU: kernel D
    with mock.patch.object(ps, "_on_tpu", lambda: True):
        # 1048 = 8 x 131: only 8-aligned divisors are 8 and 1048 — the
        # old plan returned None. Padded: rb=264 sweeps 1120 ext rows
        # (vs 5240 at the divisor rb=8's fallback-free neighbor rb=24).
        rb, m_pad = ps.plan_shard_window(1048, 2048, 8)
        assert rb == 264 and m_pad == 1056 and m_pad % rb == 0
        # Non-8-aligned heights are viable too (window starts stay
        # 8-aligned; the south halo lands at an unaligned offset, which
        # only the dynamic_update_slice sees). 1000 % 8 == 0 but has no
        # deep aligned divisor; 1004 % 8 != 0 (the newly-admitted
        # class, pinned bitwise on hardware in tpu_smoke).
        rb, m_pad = ps.plan_shard_window(1000, 2048, 8)
        assert rb == 200 and m_pad == 1000
        rb, m_pad = ps.plan_shard_window(1004, 2048, 8)
        assert rb == 256 and m_pad == 1024 and m_pad % rb == 0
        # Exact divisors keep the old zero-pad picks.
        rb, m_pad = ps.plan_shard_window(512, 1024, 8, with_cols=True)
        assert m_pad == 512 and 512 % rb == 0
        # Tiny shards still fall back (rb floor).
        assert ps.plan_shard_window(16, 2048, 8) is None


def test_panel_planner():
    """plan_panels policy (measured, round 5): split only past 16 KB
    rows, smallest P landing panels at <= 16 KB, bm from the with-cols
    probed envelope (much tighter than C2's: the two strip windows cost
    ~50-90 ext rows of compiler headroom)."""
    import unittest.mock as mock
    import heat2d_tpu.ops.pallas_stencil as ps

    # Off-TPU (this harness): always P=1 — the CPU suite never panels.
    assert ps.plan_panels(8192, 8192, 8) == (1, None)

    with mock.patch.object(ps, "_on_tpu", lambda: True):
        # 32 KB rows split in 2; the probed 16 KB with-cols envelope
        # (128 ext rows) gives bm=112 at 8192 rows, bm=104 at 512.
        assert ps.plan_panels(8192, 8192, 8) == (2, 112)
        assert ps.plan_panels(512, 8192, 8) == (2, 104)
        # <= 16 KB rows: never split (panels measured 3-7% SLOWER at
        # 4096^2 — tune_panels round 5).
        assert ps.plan_panels(4096, 4096, 8) == (1, None)
        assert ps.plan_panels(2560, 2048, 8) == (1, None)
        # 64 KB rows: P=4.
        pp, bm = ps.plan_panels(8192, 16384, 8)
        assert pp == 4 and bm == 112
        # Misaligned tsteps: no panel route.
        assert ps.plan_panels(8192, 8192, 4) == (1, None)
        # With-cols probed entries + the off-table allowance.
        assert ps._panel_ext_rows(16 * 1024, 8) == 128
        assert ps._panel_ext_rows(8 * 1024, 8) == 264
        assert ps._panel_ext_rows(4 * 1024, 8) == 480
        assert ps._panel_ext_rows(2 * 1024, 8) \
            == ps._window_ext_rows(2 * 1024, 8) - 160
