"""analysis/ subsystem tests — ISSUE 10.

Four blocks:

- **linter**: every rule R001-R006 catches a SEEDED violation (deleting
  any single rule's implementation fails a test here — the rules are
  provably non-vacuous), exemptions hold, the baseline workflow
  (justification-required, line-number-free keys, stale reporting)
  works, and the REAL tree lints to zero non-baselined findings with
  <= 10 baselined entries (the CI gate, as a test).
- **locks**: zero-overhead passthrough when off; a seeded lock-order
  inversion and a guarded-write-without-lock are detected; consistent
  ordering and pre-publication writes are NOT flagged; Condition
  integration; and the jaxpr pin — an installed audit leaves the
  solver and serve batch-runner programs byte-identical.
- **recompile**: CompileWatch counts real XLA compiles, the serve
  engine compiles O(log max_batch) programs per signature, and a
  seeded cache-key blowup trips the budget.
- **jaxpr_pin**: the structural diff is readable.
"""

import json
import os
import textwrap
import threading

import jax
import jax.numpy as jnp
import pytest

from heat2d_tpu.analysis import jaxpr_pin, locks, recompile
from heat2d_tpu.analysis import lint
from heat2d_tpu.analysis.cli import main as lint_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _audit_reset():
    """Tests here install/uninstall auditors; never leak one."""
    yield
    locks.uninstall()


def _tree(tmp_path, files: dict) -> str:
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return str(tmp_path)


def _rules_of(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------------------------ #
# linter: seeded violations per rule (non-vacuity)
# ------------------------------------------------------------------ #

def test_r001_flags_direct_write_and_honors_idiom(tmp_path):
    root = _tree(tmp_path, {"pkg/io.py": '''
        import json, os

        def bad(path, data):
            with open(path, "w") as f:
                json.dump(data, f)

        def staged(path, data):
            with open(path + ".tmp", "w") as f:
                json.dump(data, f)

        def atomic(path, data):
            tmp2 = path + ".part"
            with open(tmp2, "w") as f:
                json.dump(data, f)
            os.replace(tmp2, path)

        def reader(path):
            with open(path) as f:
                return f.read()

        def appender(path, line):
            with open(path, "a") as f:
                f.write(line)
        '''})
    fs = lint.lint_tree(root, rules=["R001"])
    assert len(fs) == 1
    assert fs[0].context == "bad" and fs[0].rule == "R001"


def test_r002_flags_wallclock_in_traced_scopes(tmp_path):
    root = _tree(tmp_path, {"pkg/mod.py": '''
        import time, random, datetime, jax

        def traced(x):
            return x * time.time()

        def _my_kernel(ref, o_ref):
            o_ref[0] = ref[0] * random.random()

        def stamped(x):
            return x + datetime.datetime.now().timestamp()

        def host_side():
            return time.perf_counter()

        jax.jit(traced)
        jax.jit(stamped)
        '''})
    fs = lint.lint_tree(root, rules=["R002"])
    ctxs = sorted(f.context for f in fs)
    assert ctxs == ["_my_kernel", "stamped", "traced"]


def test_r002_host_callbacks_exempt(tmp_path):
    root = _tree(tmp_path, {"pkg/mod.py": '''
        import time, jax

        def collector(step):
            print(time.time(), step)     # host callback: fine

        def traced(x):
            jax.debug.callback(collector, 0)
            return x * 2

        jax.jit(traced)
        '''})
    assert lint.lint_tree(root, rules=["R002"]) == []


def test_r002_registry_bound_kernels_are_traced_roots(tmp_path):
    """Kernels reached ONLY through the problems registry dispatch
    (``Family(step=..., step_value=..., scalars=...)`` in another
    module) are traced scopes — a wall-clock leak inside one is
    caught; the numpy-oracle slot (``np_step``) stays host-side."""
    root = _tree(tmp_path, {
        "pkg/registry.py": '''
        from pkg import kernels as _k

        def build():
            return Family(spec=None, step=_k.fancy_step,
                          step_value=_k.fancy_step_value,
                          scalars=_k.fancy_scalars,
                          np_step=_k.numpy_oracle)
        ''',
        "pkg/kernels.py": '''
        import time

        def fancy_step(u, cx, cy):
            return u * time.time()          # leak: traced via registry

        def fancy_step_value(u, cx, cy):
            return _helper(u)               # fixpoint through a helper

        def _helper(u):
            return u + time.perf_counter()  # leak: traced transitively

        def fancy_scalars(cx, cy):
            return (cx, cy)

        def numpy_oracle(u):
            return u * time.time()          # host oracle: NOT traced
        ''',
    })
    fs = lint.lint_tree(root, rules=["R002"])
    ctxs = sorted(f.context for f in fs)
    assert ctxs == ["_helper", "fancy_step"]


def test_r005_covers_ir_and_analysis_metric_families(tmp_path):
    root = _tree(tmp_path, {
        "pkg/met.py": '''
        def record(reg):
            reg.counter("ir_findings_total")
            reg.counter("analysis_lint_runs_total")
            reg.gauge("ir_programs_swept", 1)
        ''',
        "docs/OBSERVABILITY.md":
            "| `ir_programs_swept` | gauge | documented |\n"
            "| `analysis_ghost_total` | counter | documented only |\n",
    })
    fs = lint.lint_tree(root, rules=["R005"])
    names = sorted(f.match for f in fs)
    assert names == ["analysis_ghost_total", "analysis_lint_runs_total",
                     "ir_findings_total"]


def test_r003_flags_traced_value_leaks(tmp_path):
    root = _tree(tmp_path, {"pkg/mod.py": '''
        import jax

        def leaky(x, n):
            lo = float(x)                # leak: x is traced
            hi = x.sum().item()          # leak
            k = int(n)                   # leak: n is traced too
            static = float(1.5)          # constant: fine
            return lo + hi + k + static

        jax.jit(leaky)

        def host(path):
            return float(open(path).read())   # untraced: fine
        '''})
    fs = lint.lint_tree(root, rules=["R003"])
    assert len(fs) == 3
    assert all(f.context == "leaky" for f in fs)


def test_r004_chaos_purity(tmp_path):
    root = _tree(tmp_path, {"pkg/resil/chaos.py": '''
        import jax.numpy as jnp

        def hook(u):
            return jnp.sum(u)
        '''})
    fs = lint.lint_tree(root, rules=["R004"])
    assert len(fs) == 2          # the import AND the jnp touch
    # the rule is scoped: the same code elsewhere is not chaos's business
    root2 = _tree(tmp_path / "b", {"pkg/resil/other.py": '''
        import jax.numpy as jnp
        '''})
    assert lint.lint_tree(root2, rules=["R004"]) == []


def test_r005_metric_doc_drift_both_directions(tmp_path):
    root = _tree(tmp_path, {
        "pkg/met.py": '''
        def record(reg):
            reg.counter("serve_phantom_total")
            reg.gauge("serve_known_depth", 1)
        ''',
        "docs/OBSERVABILITY.md":
            "| `serve_known_depth` | gauge | documented |\n"
            "| `serve_ghost_total` | counter | documented only |\n",
    })
    fs = lint.lint_tree(root, rules=["R005"])
    names = sorted(f.match for f in fs)
    assert names == ["serve_ghost_total", "serve_phantom_total"]


def test_r006_bare_locks_in_threaded_subsystems(tmp_path):
    root = _tree(tmp_path, {
        "pkg/serve/s.py": '''
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition()
        ''',
        "pkg/ops/free.py": '''
        import threading
        _lock = threading.Lock()     # not a serve/fleet/resil module
        ''',
    })
    fs = lint.lint_tree(root, rules=["R006"])
    assert len(fs) == 2
    assert all(f.path == "pkg/serve/s.py" for f in fs)


# ------------------------------------------------------------------ #
# baseline workflow
# ------------------------------------------------------------------ #

SEEDED = {"pkg/io.py": '''
    def bad(path, data):
        with open(path, "w") as f:
            f.write(data)
    '''}


def test_baseline_suppresses_with_justification(tmp_path):
    root = _tree(tmp_path, SEEDED)
    fs = lint.lint_tree(root, rules=["R001"])
    assert len(fs) == 1
    bl = {fs[0].key: "known cosmetic"}
    new, old, stale = lint.split_baselined(fs, bl)
    assert new == [] and len(old) == 1 and stale == []


def test_baseline_requires_justification(tmp_path):
    p = tmp_path / "baseline.json"
    p.write_text(json.dumps(
        {"findings": [{"key": "R001:x:y:z", "justification": "  "}]}))
    with pytest.raises(lint.BaselineError):
        lint.load_baseline(str(p))


def test_baseline_key_survives_unrelated_edits(tmp_path):
    root = _tree(tmp_path, SEEDED)
    key0 = lint.lint_tree(root, rules=["R001"])[0].key
    # prepend lines: the finding moves but its key must not
    p = tmp_path / "pkg" / "io.py"
    p.write_text("# a comment\nX = 1\n" + p.read_text())
    f1 = lint.lint_tree(root, rules=["R001"])[0]
    assert f1.key == key0 and f1.line > 2


def test_stale_baseline_entries_reported(tmp_path):
    root = _tree(tmp_path, {"pkg/clean.py": "X = 1\n"})
    new, old, stale = lint.split_baselined(
        lint.lint_tree(root), {"R001:gone:ctx:snippet": "was fixed"})
    assert stale == ["R001:gone:ctx:snippet"]


def test_cli_rc_and_json(tmp_path, capsys):
    root = _tree(tmp_path, SEEDED)
    assert lint_main([root, "--baseline", "none",
                      "--format", "json"]) == 1
    out = json.loads(capsys.readouterr().out)
    assert out["ok"] is False and len(out["new"]) == 1
    # baseline the finding -> rc 0
    bl = tmp_path / "bl.json"
    bl.write_text(json.dumps({"findings": [
        {"key": out["new"][0]["key"], "justification": "seeded"}]}))
    assert lint_main([root, "--baseline", str(bl)]) == 0


def test_cli_rejects_unknown_rule(tmp_path):
    root = _tree(tmp_path, {"pkg/x.py": "X = 1\n"})
    assert lint_main([root, "--rules", "R999"]) == 2


# ------------------------------------------------------------------ #
# THE gate: the real tree is clean
# ------------------------------------------------------------------ #

def test_repo_tree_lints_clean_with_bounded_baseline():
    """The acceptance criterion, as a test: rc 0 on the repo with
    <= 10 baselined findings, each justified."""
    baseline_path = os.path.join(REPO, "heat2d_tpu", "analysis",
                                 "baseline.json")
    baseline = lint.load_baseline(baseline_path)   # raises if any
    #                                                entry lacks a why
    assert len(baseline) <= 10
    findings = lint.lint_tree(REPO)
    new, old, stale = lint.split_baselined(findings, baseline)
    assert new == [], "\n".join(f.render() for f in new)
    assert stale == [], f"stale baseline entries: {stale}"


# ------------------------------------------------------------------ #
# locks: audit off / on, inversion, guarded writes
# ------------------------------------------------------------------ #

def test_audited_lock_is_plain_when_off(monkeypatch):
    monkeypatch.delenv(locks.ENV_VAR, raising=False)
    locks.uninstall()
    assert type(locks.AuditedLock()) is type(threading.Lock())
    assert type(locks.AuditedRLock()) is type(threading.RLock())
    assert isinstance(locks.AuditedCondition(), threading.Condition)


def test_lock_order_inversion_detected():
    locks.install()
    a, b = locks.AuditedLock("A"), locks.AuditedLock("B")

    def ab():
        with a:
            with b:
                pass

    def ba():
        with b:
            with a:
                pass

    for fn in (ab, ba):
        t = threading.Thread(target=fn)
        t.start()
        t.join()
    rep = locks.report()
    assert rep.cycles and sorted(rep.cycles[0]) == ["A", "B"]
    assert not rep.clean and "cycle" in rep.render()


def test_lock_outliving_an_install_cycle_still_reports():
    """Regression: a lock constructed under an EARLIER auditor (a
    module-level lock, or one built in a previous test under the
    per-test conftest fixture) must feed the LIVE auditor — binding at
    construction would send half of an inversion's edges to a dead
    collector and report clean."""
    locks.install()
    old = locks.AuditedLock("OLD")      # bound era: auditor #0
    locks.install()                     # fresh auditor #1
    new = locks.AuditedLock("NEW")

    def order(first, second):
        with first:
            with second:
                pass

    for a, b in ((old, new), (new, old)):
        t = threading.Thread(target=order, args=(a, b))
        t.start()
        t.join()
    rep = locks.report()
    assert rep.cycles and sorted(rep.cycles[0]) == ["NEW", "OLD"], \
        rep.render()


def test_consistent_order_is_clean():
    locks.install()
    a, b = locks.AuditedLock("A"), locks.AuditedLock("B")

    def ab():
        with a:
            with b:
                pass

    for _ in range(2):
        t = threading.Thread(target=ab)
        t.start()
        t.join()
    rep = locks.report()
    assert rep.clean and len(rep.edges) == 1


def test_guarded_write_without_lock_detected():
    locks.install()

    @locks.guarded_by("_lock", "count")
    class G:
        def __init__(self):
            self._lock = locks.AuditedLock("G")
            self.count = 0      # pre-publication: exempt

        def ok(self):
            with self._lock:
                self.count += 1

        def bad(self):
            self.count += 1

    locks.install()             # fresh collector; G already registered
    g = G()
    g.ok()
    assert locks.report().clean     # locked writes are fine
    g.bad()
    rep = locks.report()
    assert len(rep.violations) == 1
    v = rep.violations[0]
    assert (v.cls, v.attr, v.lock_attr) == ("G", "count", "_lock")
    locks.uninstall()
    # un-patched after uninstall: no checking, no recording
    g.bad()
    assert locks.report().clean


def test_guarded_by_condition_lock():
    locks.install()

    @locks.guarded_by("_cond", "state")
    class C:
        def __init__(self):
            self._cond = locks.AuditedCondition("C")
            self.state = 0

        def locked_write(self):
            with self._cond:
                self.state = 1
                self._cond.notify_all()

        def bare_write(self):
            self.state = 2

    locks.install()
    c = C()
    c.locked_write()
    assert locks.report().clean
    c.bare_write()
    assert len(locks.report().violations) == 1


def test_condition_wait_notify_through_audited_lock():
    locks.install()
    cond = locks.AuditedCondition("w")
    hits = []

    def waiter():
        with cond:
            while not hits:
                cond.wait(timeout=5)

    t = threading.Thread(target=waiter)
    t.start()
    with cond:
        hits.append(1)
        cond.notify_all()
    t.join(timeout=5)
    assert not t.is_alive()
    assert locks.report().clean


def test_jaxpr_pin_audit_installed_vs_off():
    """The audited-lock acceptance pin: audited == plain programs."""
    locks.uninstall()
    base_solver = jaxpr_pin.solver_jaxpr()
    base_batch = jaxpr_pin.batch_runner_jaxpr()
    locks.install()
    try:
        jaxpr_pin.assert_jaxpr_equal(
            base_solver, jaxpr_pin.solver_jaxpr(),
            label="solver (lock audit on)")
        jaxpr_pin.assert_jaxpr_equal(
            base_batch, jaxpr_pin.batch_runner_jaxpr(),
            label="batch runner (lock audit on)")
    finally:
        locks.uninstall()


# ------------------------------------------------------------------ #
# recompile sentinel
# ------------------------------------------------------------------ #

def test_compile_watch_counts_and_caches():
    with recompile.CompileWatch(match="sq_sentinel") as w:
        def sq_sentinel(x):
            return x * x

        f = jax.jit(sq_sentinel)
        f(jnp.ones(16))
        f(jnp.ones(16))         # cached: no second compile
    assert w.count == 1
    f(jnp.ones(16))             # outside the watch: not counted
    assert w.count == 1


def test_seeded_cache_key_blowup_trips_budget():
    """The failure class the sentinel exists for: a per-call-varying
    static turns the compile cache into a per-request compiler."""
    import functools
    with pytest.raises(recompile.RecompileBudgetError) as e:
        with recompile.CompileWatch(limit=2, match="blowup_sentinel"):
            @functools.partial(jax.jit, static_argnums=1)
            def blowup_sentinel(x, s):
                return x + s

            for i in range(4):
                blowup_sentinel(jnp.ones(4), float(i))
    assert "4" in str(e.value) and "blowup_sentinel" in str(e.value)


def test_serve_engine_compiles_log_max_batch_programs():
    """The serving contract (power-of-two padding) as a measured
    invariant: every occupancy 1..8 through the engine compiles the
    runner once per DISTINCT capacity — 4 programs, never 8."""
    rep = recompile.serve_compile_report(max_batch=8)
    assert rep["capacities"] == [1, 2, 4, 8]
    assert rep["launches"] == 8
    assert rep["budget"] == 4
    assert 1 <= rep["compiles"] <= rep["budget"], rep
    assert all("batch_runner" in n for n in rep["names"])


def test_serve_compile_budget_helpers():
    assert recompile.log2_capacity_budget(8) == 4
    assert recompile.log2_capacity_budget(1) == 1
    w = recompile.CompileWatch()
    w._handler.names = ["jit(f)", "jit(f)", "jit(g)"]
    with pytest.raises(recompile.RecompileBudgetError):
        recompile.assert_bounded(w, 2, label="x")


# ------------------------------------------------------------------ #
# jaxpr_pin structural diff
# ------------------------------------------------------------------ #

def test_assert_jaxpr_equal_produces_readable_diff():
    a = jaxpr_pin.jaxpr_text(lambda x: x + 1.0, jnp.ones(4))
    b = jaxpr_pin.jaxpr_text(lambda x: x * 2.0, jnp.ones(4))
    jaxpr_pin.assert_jaxpr_equal(a, a)      # identical: no raise
    with pytest.raises(AssertionError) as e:
        jaxpr_pin.assert_jaxpr_equal(a, b, label="demo",
                                     label_a="add", label_b="mul")
    msg = str(e.value)
    assert "demo" in msg and "--- add" in msg and "+++ mul" in msg
    assert any(ln.startswith("-") for ln in msg.splitlines())
    with pytest.raises(AssertionError):
        jaxpr_pin.assert_jaxpr_differs(a, a, label="vacuity")
    jaxpr_pin.assert_jaxpr_differs(a, b)    # differ: no raise
