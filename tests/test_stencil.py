"""Numeric-core tests: the jnp stencil vs the independent NumPy oracle of
the C semantics (SURVEY.md §2.1 C3, Appendix B)."""

import jax.numpy as jnp
import numpy as np
import pytest

from heat2d_tpu.ops import inidat, stencil_step, stencil_step_padded, residual_sq


def test_one_step_matches_oracle_f64(oracle):
    """f64 accumulation reproduces C's double-promoted update exactly."""
    u = np.asarray(inidat(10, 10))
    got = np.asarray(stencil_step(jnp.asarray(u), 0.1, 0.1, jnp.float64))
    np.testing.assert_array_equal(got, oracle.step(u))


def test_hundred_steps_match_oracle_f64(oracle):
    """The reference default workload: 10x10, 100 steps
    (mpi_heat2Dn.c:29-31). Bitwise equality in f64-accum mode."""
    u = inidat(10, 10)
    for _ in range(100):
        u = stencil_step(u, 0.1, 0.1, jnp.float64)
    np.testing.assert_array_equal(np.asarray(u), oracle.run(10, 10, 100))


def test_f32_accum_close_to_oracle(oracle):
    """The TPU-fast f32 path drifts only at rounding level over 100 steps
    at parity sizes (SURVEY.md Appendix B recommendation)."""
    u = inidat(10, 10)
    for _ in range(100):
        u = stencil_step(u, 0.1, 0.1, jnp.float32)
    ref = oracle.run(10, 10, 100)
    np.testing.assert_allclose(np.asarray(u), ref, rtol=1e-5, atol=1e-3)


def test_boundaries_clamped(oracle):
    """Edges are never updated (mpi_heat2Dn.c:228-229 loop bounds)."""
    u0 = np.asarray(inidat(12, 9))
    u = jnp.asarray(u0)
    for _ in range(7):
        u = stencil_step(u, 0.1, 0.1)
    u = np.asarray(u)
    np.testing.assert_array_equal(u[0], u0[0])
    np.testing.assert_array_equal(u[-1], u0[-1])
    np.testing.assert_array_equal(u[:, 0], u0[:, 0])
    np.testing.assert_array_equal(u[:, -1], u0[:, -1])


def test_padded_step_matches_global_interior(rng):
    """A halo-padded block step reproduces the corresponding window of the
    global step (the per-shard compute path, grad1612_mpi_heat.c:239-259)."""
    u = rng.standard_normal((16, 14)).astype(np.float32)
    full = np.asarray(stencil_step(jnp.asarray(u), 0.1, 0.1))
    # interior block [4:10, 3:9] with its 1-cell halo ring [3:11, 2:10]
    padded = jnp.asarray(u[3:11, 2:10])
    blk = np.asarray(stencil_step_padded(padded, 0.1, 0.1))
    np.testing.assert_array_equal(blk, full[4:10, 3:9])


@pytest.mark.parametrize("accum", [jnp.float32, jnp.float64])
def test_residual_sq(accum, rng):
    a = rng.standard_normal((8, 8)).astype(np.float32)
    b = rng.standard_normal((8, 8)).astype(np.float32)
    got = float(residual_sq(jnp.asarray(a), jnp.asarray(b), accum))
    want = np.sum((a.astype(np.float64) - b.astype(np.float64)) ** 2)
    np.testing.assert_allclose(got, want, rtol=1e-5)
