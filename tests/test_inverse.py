"""Inverse-problem workload (heat2d_tpu/diff) — driver, serving
integration, CLI, and the satellite surfaces (resil snapshot helpers,
io field save/load, obs record kind).

The ISSUE acceptance scenario: an InverseRequest submitted to a running
SolveServer converges on a known synthetic target, repeat submission is
a cache hit, and the run record carries iteration count + final loss.
"""

from __future__ import annotations

import json

import jax.numpy as jnp
import numpy as np
import pytest

from heat2d_tpu.diff.adjoint import make_diff_solve
from heat2d_tpu.diff.inverse import (InverseProblem, adam_minimize,
                                     observation_mask,
                                     synthetic_diffusivity,
                                     unit_reference_init)
from heat2d_tpu.diff.serving import InverseEngine, InverseRequest
from heat2d_tpu.obs import MetricsRegistry
from heat2d_tpu.serve.schema import Rejected, SolveRequest
from heat2d_tpu.serve.server import SolveServer


def _observed_problem(nx=12, ny=12, steps=16, every=1):
    """(true_k, u0, mask, values): a known diffusivity field and the
    final-state observations its forward solve produces."""
    true_k = synthetic_diffusivity(nx, ny)
    u0 = unit_reference_init(nx, ny)
    u_true = np.asarray(make_diff_solve(nx, ny, steps, coeff="var")(
        jnp.asarray(u0), jnp.asarray(true_k), jnp.asarray(true_k)))
    return true_k, u0, observation_mask(nx, ny, every=every), u_true


# --------------------------------------------------------------------- #
# request schema
# --------------------------------------------------------------------- #

def test_request_roundtrip_and_hash_sensitivity():
    _, _, mask, values = _observed_problem()
    req = InverseRequest.from_fields(12, 12, 16, mask, values,
                                     iterations=50, lr=0.02)
    # mask/values reconstruct exactly
    np.testing.assert_array_equal(req.mask(), mask)
    np.testing.assert_array_equal(req.values()[mask],
                                  values.astype(np.float32)[mask])
    h = req.content_hash()
    assert h == req.content_hash()
    # the observation DATA is part of the identity
    bumped = np.array(values)
    i, j = np.argwhere(mask)[0]
    bumped[i, j] += 1e-3
    req2 = InverseRequest.from_fields(12, 12, 16, mask, bumped,
                                      iterations=50, lr=0.02)
    assert req2.content_hash() != h
    # ...and so are the loop hyperparameters
    req3 = InverseRequest.from_fields(12, 12, 16, mask, values,
                                      iterations=50, lr=0.03)
    assert req3.content_hash() != h


def test_request_signature_disjoint_from_solves():
    _, _, mask, values = _observed_problem()
    inv = InverseRequest.from_fields(12, 12, 16, mask, values)
    sol = SolveRequest(nx=12, ny=12, steps=16)
    assert inv.signature() != sol.signature()
    assert inv.signature()[0] == "inverse"
    assert inv.request_kind == "inverse"


def test_request_validation_rejects():
    _, _, mask, values = _observed_problem()
    ok = dict(nx=12, ny=12, steps=16, mask=mask, values=values)
    with pytest.raises(Rejected):
        InverseRequest.from_fields(**{**ok, "target": "nope"})
    with pytest.raises(Rejected):
        InverseRequest.from_fields(**ok, iterations=0)
    with pytest.raises(Rejected):
        InverseRequest.from_fields(**ok, lr=0.0)
    with pytest.raises(Rejected):
        InverseRequest.from_fields(**ok, tol=-1.0)
    with pytest.raises(Rejected):
        InverseRequest.from_fields(**ok, adjoint="nope")
    with pytest.raises(Rejected):   # no observations at all
        InverseRequest(nx=12, ny=12, steps=16, obs_indices=(),
                       obs_values=()).validate()
    with pytest.raises(Rejected):   # index out of range
        InverseRequest(nx=12, ny=12, steps=16, obs_indices=(10_000,),
                       obs_values=(1.0,)).validate()
    with pytest.raises(Rejected):   # duplicate indices
        InverseRequest(nx=12, ny=12, steps=16, obs_indices=(5, 5),
                       obs_values=(1.0, 2.0)).validate()


def test_request_from_dict():
    _, _, mask, values = _observed_problem()
    req = InverseRequest.from_fields(12, 12, 16, mask, values)
    d = {"nx": 12, "ny": 12, "steps": 16,
         "obs_indices": list(req.obs_indices),
         "obs_values": list(req.obs_values)}
    again = InverseRequest.from_dict(d)
    assert again.content_hash() == req.content_hash()
    with pytest.raises(Rejected):
        InverseRequest.from_dict({**d, "bogus": 1})


# --------------------------------------------------------------------- #
# inverse driver
# --------------------------------------------------------------------- #

def test_recover_diffusivity_below_threshold():
    true_k, u0, mask, values = _observed_problem()
    prob = InverseProblem(nx=12, ny=12, steps=16, target="diffusivity",
                          obs_mask=mask, obs_values=values, u0=u0)
    reg = MetricsRegistry()
    sol = prob.solve(iterations=250, lr=0.02, tol=1e-8, registry=reg)
    assert sol.converged and sol.final_loss <= 1e-8
    err0 = np.abs(0.1 - true_k)[1:-1, 1:-1].mean()
    err = np.abs(sol.params - true_k)[1:-1, 1:-1].mean()
    assert err < 0.1 * err0
    # the stability-box projection held
    assert sol.params.min() >= 1e-4 and sol.params.max() <= 0.24
    # per-iteration telemetry streamed
    snap = reg.snapshot()
    series = [k for k in snap["series"] if k.startswith("inverse_loss")]
    assert series and len(snap["series"][series[0]]) == sol.iterations
    assert snap["counters"]["inverse_iterations_total"] == sol.iterations


def test_recover_initial_condition():
    nx, ny, steps = 12, 12, 10
    u0 = unit_reference_init(nx, ny)
    u_true = np.asarray(make_diff_solve(nx, ny, steps)(
        jnp.asarray(u0), 0.1, 0.1))
    mask = observation_mask(nx, ny, every=1)
    prob = InverseProblem(nx=nx, ny=ny, steps=steps, target="init",
                          obs_mask=mask, obs_values=u_true,
                          cx=0.1, cy=0.1)
    sol = prob.solve(iterations=300, lr=0.05, tol=1e-7)
    assert sol.converged and sol.final_loss <= 1e-7


def test_adam_minimize_returns_best_iterate_and_early_stop():
    # 1D quadratic: loss (x-3)^2 — tol stops the loop early and the
    # best iterate is returned even if a later step overshoots.
    import jax

    vg = jax.value_and_grad(lambda x: jnp.sum((x - 3.0) ** 2))
    sol = adam_minimize(vg, jnp.zeros(()), iterations=5000, lr=0.05,
                        tol=1e-6)
    assert sol.converged
    assert sol.iterations < 5000
    assert abs(float(sol.params) - 3.0) < 1e-2
    assert sol.final_loss == min(sol.loss_history)
    with pytest.raises(ValueError):
        adam_minimize(vg, jnp.zeros(()), iterations=0)


def test_inverse_problem_validation():
    _, _, mask, values = _observed_problem()
    with pytest.raises(ValueError):
        InverseProblem(nx=12, ny=12, steps=4, target="nope",
                       obs_mask=mask, obs_values=values)
    with pytest.raises(ValueError):
        InverseProblem(nx=10, ny=10, steps=4, target="init",
                       obs_mask=mask, obs_values=values)  # shape clash
    with pytest.raises(ValueError):
        InverseProblem(nx=12, ny=12, steps=4, target="init",
                       obs_mask=np.zeros((12, 12), bool),
                       obs_values=values)                 # empty mask


# --------------------------------------------------------------------- #
# serving integration — the acceptance scenario
# --------------------------------------------------------------------- #

def test_inverse_request_e2e_through_solve_server():
    true_k, _, mask, values = _observed_problem()
    req = InverseRequest.from_fields(12, 12, 16, mask, values,
                                     target="diffusivity",
                                     iterations=250, lr=0.02, tol=1e-8)
    reg = MetricsRegistry()
    with SolveServer(registry=reg, max_delay=0.01) as srv:
        res = srv.solve(req, timeout=300)
        assert res.converged and res.final_loss <= 1e-8
        assert res.iterations >= 1
        err0 = np.abs(0.1 - true_k)[1:-1, 1:-1].mean()
        err = np.abs(np.asarray(res.params) - true_k)[1:-1, 1:-1].mean()
        assert err < 0.1 * err0
        # repeat submission: a cache hit with the identical params
        again = srv.solve(req, timeout=60)
        assert again.cache_hit
        assert np.asarray(again.params).tobytes() == \
            np.asarray(res.params).tobytes()
        assert again.final_loss == res.final_loss
    snap = reg.snapshot()
    assert snap["counters"]["serve_requests_total{outcome=cache_hit}"] == 1
    assert snap["counters"]["inverse_iterations_total"] >= 1
    assert "inverse_solve_s" in snap["histograms"]


def test_inverse_and_solve_traffic_share_one_server():
    _, _, mask, values = _observed_problem()
    inv = InverseRequest.from_fields(12, 12, 16, mask, values,
                                     iterations=30, lr=0.02)
    with SolveServer(max_delay=0.01) as srv:
        f_solve = srv.submit(SolveRequest(nx=16, ny=16, steps=5,
                                          method="jnp"))
        f_inv = srv.submit(inv)
        r_solve = f_solve.result(120)
        r_inv = f_inv.result(300)
    assert r_solve.steps_done == 5
    assert r_inv.iterations == 30
    assert not r_inv.cache_hit


def test_inverse_duplicates_coalesce_in_flight():
    _, _, mask, values = _observed_problem()
    req = InverseRequest.from_fields(12, 12, 16, mask, values,
                                     iterations=40, lr=0.02)
    with SolveServer(max_delay=0.05) as srv:
        fa = srv.submit(req)
        fb = srv.submit(req)
        ra, rb = fa.result(300), fb.result(300)
    # one leader computed; the follower was relabeled coalesced
    assert {ra.coalesced, rb.coalesced} == {False, True}
    assert np.asarray(ra.params).tobytes() == \
        np.asarray(rb.params).tobytes()


def test_invalid_inverse_request_rejected_at_the_door():
    with SolveServer() as srv:
        fut = srv.submit(InverseRequest(nx=12, ny=12, steps=16,
                                        obs_indices=(), obs_values=()))
        with pytest.raises(Rejected):
            fut.result(10)


def test_inverse_engine_shares_launch_chaos_point(monkeypatch):
    """The injected launch fault hits inverse dispatch exactly like
    solve dispatch — the retry policy absorbs it."""
    from heat2d_tpu.resil import chaos

    _, _, mask, values = _observed_problem()
    req = InverseRequest.from_fields(12, 12, 16, mask, values,
                                     iterations=20, lr=0.02)
    chaos.install(chaos.ChaosConfig(fail_launches=1))
    try:
        from heat2d_tpu.resil.retry import RetryPolicy
        with SolveServer(max_delay=0.01,
                         retry_policy=RetryPolicy(
                             max_attempts=3, base_delay=0.01)) as srv:
            res = srv.solve(req, timeout=300)
        assert res.iterations == 20
    finally:
        chaos.install(None)


def test_same_signature_problems_share_one_compiled_runner():
    """Review fix: value_and_grad must not rebuild a fresh jitted
    closure per problem — two problems with the same compile signature
    share the ONE memoized executable (observations are operands)."""
    from heat2d_tpu.diff.inverse import loss_grad_runner

    _, u0, mask, values = _observed_problem()
    a = InverseProblem(nx=12, ny=12, steps=16, target="diffusivity",
                       obs_mask=mask, obs_values=values, u0=u0)
    shifted = np.array(values) + 0.01
    b = InverseProblem(nx=12, ny=12, steps=16, target="diffusivity",
                       obs_mask=mask, obs_values=shifted, u0=u0)
    va, vb = a.value_and_grad(), b.value_and_grad()
    assert va.func is vb.func          # same jitted runner underneath
    assert loss_grad_runner(12, 12, 16, "diffusivity", "checkpoint",
                            None, "auto", False) is va.func
    # ...and the bound operands still make them DIFFERENT problems
    la, _ = va(jnp.full((12, 12), 0.1, jnp.float32))
    lb, _ = vb(jnp.full((12, 12), 0.1, jnp.float32))
    assert float(la) != float(lb)


def test_adam_best_iterate_keeps_float64():
    """Review fix: the best-iterate snapshot must not truncate an f64
    optimization through float32."""
    import jax

    vg = jax.value_and_grad(
        lambda x: jnp.sum((x - jnp.asarray(3.0, jnp.float64)) ** 2))
    sol = adam_minimize(vg, jnp.zeros((), jnp.float64),
                        iterations=50, lr=0.1)
    assert sol.params.dtype == np.float64


def test_long_inverse_loop_aborts_on_nondrain_stop():
    """Review fix: inverse loops run on a dedicated lane and a
    non-drain stop interrupts them at the next iteration — shutdown
    never waits out a 100k-iteration budget."""
    import time

    _, _, mask, values = _observed_problem()
    req = InverseRequest.from_fields(12, 12, 16, mask, values,
                                     iterations=100_000, lr=0.02)
    reg = MetricsRegistry()
    srv = SolveServer(registry=reg, max_delay=0.01).start()
    fut = srv.submit(req)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:   # wait until the loop is live
        if reg.snapshot()["counters"].get("inverse_iterations_total", 0):
            break
        time.sleep(0.02)
    t0 = time.monotonic()
    srv.stop()                           # non-drain: interrupt
    assert time.monotonic() - t0 < 30
    with pytest.raises(Rejected) as exc_info:
        fut.result(5)
    assert exc_info.value.code == "shutdown"


class _StepClock:
    """A controllable monotonic clock: returns a fixed reading until
    the test advances it. Thread-safe (the watchdog polls it from its
    watcher thread, the engine guard reads it from the inverse
    lane)."""

    def __init__(self):
        import threading
        self._lock = threading.Lock()
        self._t = 0.0

    def __call__(self) -> float:
        with self._lock:
            return self._t

    def advance(self, dt: float) -> None:
        with self._lock:
            self._t += dt


def test_inverse_deadline_aborts_loop_and_frees_lane():
    """launch_deadline bounds an inverse loop: the watchdog fails the
    waiters and the engine aborts at the next iteration, after which
    the server still serves.

    The deadline is driven by a CONTROLLABLE clock (flake fix —
    previously a 0.5s wall-clock deadline, which a slow CI host's
    compile times could trip on the follow-up plain solve): real time
    never advances the deadline here, so only the explicit advance()
    past it can fire the watchdog — on any host speed."""
    import time

    _, _, mask, values = _observed_problem()
    req = InverseRequest.from_fields(12, 12, 16, mask, values,
                                     iterations=100_000, lr=0.02)
    clock = _StepClock()
    reg = MetricsRegistry()
    with SolveServer(registry=reg, max_delay=0.01,
                     launch_deadline=0.5,
                     deadline_clock=clock) as srv:
        fut = srv.submit(req)
        # wait until the optimization loop is live (iterating), then
        # push the modeled clock past the deadline
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if reg.snapshot()["counters"].get(
                    "inverse_iterations_total", 0):
                break
            time.sleep(0.02)
        clock.advance(1.0)
        with pytest.raises(Rejected) as exc_info:
            fut.result(120)
        assert exc_info.value.code == "watchdog_timeout"
        # the lane is free again: plain traffic still flows — and its
        # launch cannot trip the (frozen) deadline however slow the
        # host is
        r = srv.solve(SolveRequest(nx=16, ny=16, steps=3, method="jnp"),
                      timeout=60)
        assert r.steps_done == 3


# --------------------------------------------------------------------- #
# satellites: resil snapshot helpers
# --------------------------------------------------------------------- #

def test_snapshot_state_owns_its_data():
    from heat2d_tpu.resil import snapshot_state

    src = np.arange(12, dtype=np.float32).reshape(3, 4)
    snap = snapshot_state(src)
    src[0, 0] = 99.0
    assert snap[0, 0] == 0.0            # no aliasing
    assert snap.dtype == np.float32


def test_snapshot_state_crops_padding():
    from heat2d_tpu.resil import snapshot_state

    src = np.ones((6, 8), np.float32)
    snap = snapshot_state(src, shape=(5, 7))
    assert snap.shape == (5, 7)


def test_snapshot_state_device_array():
    from heat2d_tpu.resil import snapshot_state

    u = jnp.asarray(np.random.RandomState(0).rand(4, 4)
                    .astype(np.float32))
    snap = snapshot_state(u)
    np.testing.assert_array_equal(snap, np.asarray(u))


def test_snapshot_shards_cover_grid():
    import jax
    from heat2d_tpu.resil import snapshot_shards

    u = jnp.asarray(np.arange(24, dtype=np.float32).reshape(4, 6))
    u = jax.device_put(u)
    blocks = snapshot_shards(u)
    out = np.zeros((4, 6), np.float32)
    for r0, c0, blk in blocks:
        out[r0:r0 + blk.shape[0], c0:c0 + blk.shape[1]] = blk
    np.testing.assert_array_equal(out, np.asarray(u))


def test_async_checkpointer_still_roundtrips(tmp_path):
    """No behavior change from the snapshot factoring: a local async
    save commits a loadable, digest-verified checkpoint."""
    from heat2d_tpu.config import HeatConfig
    from heat2d_tpu.io.binary import load_checkpoint
    from heat2d_tpu.resil import AsyncCheckpointer

    cfg = HeatConfig(nxprob=6, nyprob=6, steps=4)
    path = str(tmp_path / "ck.bin")
    u = np.random.RandomState(1).rand(6, 6).astype(np.float32)
    with AsyncCheckpointer(path, cfg, shape=(6, 6)) as ck:
        ck.save_async(u, 4)
    grid, step, _ = load_checkpoint(path)
    assert step == 4
    np.testing.assert_array_equal(grid, u)


# --------------------------------------------------------------------- #
# satellites: io field save/load
# --------------------------------------------------------------------- #

def test_save_load_field_roundtrip_float(tmp_path):
    from heat2d_tpu.io import load_field, save_field

    k = synthetic_diffusivity(9, 11)
    p = str(tmp_path / "kappa.bin")
    save_field(k, p, name="kappa", extra={"note": "test"})
    back, meta = load_field(p)
    np.testing.assert_array_equal(back, k)
    assert back.dtype == np.float32
    assert meta["name"] == "kappa" and meta["note"] == "test"
    assert meta["format"] == "heat2d-tpu-field-v1"


def test_save_load_field_roundtrip_bool_mask(tmp_path):
    from heat2d_tpu.io import load_field, save_field

    m = observation_mask(10, 12, every=3)
    p = str(tmp_path / "mask.bin")
    save_field(m, p, name="obs_mask")
    back, meta = load_field(p)
    assert back.dtype == np.bool_
    np.testing.assert_array_equal(back, m)
    assert meta["dtype"] == "bool"


def test_load_field_rejects_corruption(tmp_path):
    from heat2d_tpu.io import load_field, save_field
    from heat2d_tpu.io.binary import CheckpointCorruptError

    k = synthetic_diffusivity(6, 6)
    p = str(tmp_path / "f.bin")
    save_field(k, p)
    raw = bytearray(open(p, "rb").read())
    raw[3] ^= 0xFF
    open(p, "wb").write(bytes(raw))
    with pytest.raises(CheckpointCorruptError):
        load_field(p)
    back, _ = load_field(p, verify=False)   # debugging escape hatch
    assert back.shape == (6, 6)


def test_load_field_rejects_truncation_and_bad_sidecar(tmp_path):
    from heat2d_tpu.io import load_field, save_field
    from heat2d_tpu.io.binary import CheckpointCorruptError

    k = synthetic_diffusivity(6, 6)
    p = str(tmp_path / "f.bin")
    save_field(k, p)
    open(p, "wb").write(b"\x00" * 8)        # truncated binary
    with pytest.raises(CheckpointCorruptError):
        load_field(p, verify=False)
    open(p + ".meta.json", "w").write("{not json")
    with pytest.raises(CheckpointCorruptError):
        load_field(p)


def test_save_field_rejects_unsupported_dtype(tmp_path):
    from heat2d_tpu.io import save_field

    with pytest.raises(ValueError):
        save_field(np.zeros((3, 3), np.complex64),
                   str(tmp_path / "c.bin"))


# --------------------------------------------------------------------- #
# satellites: record kind + CLI
# --------------------------------------------------------------------- #

def test_record_kinds_include_inverse():
    from heat2d_tpu.obs.record import RECORD_KINDS
    assert "inverse" in RECORD_KINDS


def test_cli_selftest_passes(tmp_path):
    from heat2d_tpu.diff.cli import main

    metrics = str(tmp_path / "inv.jsonl")
    record = str(tmp_path / "rec.json")
    rc = main(["--selftest", "--metrics-out", metrics,
               "--run-record", record])
    assert rc == 0
    rec = json.load(open(record))
    assert rec["kind"] == "inverse"
    assert rec["converged"] is True
    assert rec["iterations"] >= 1
    assert rec["final_loss"] <= rec["tol"]
    assert rec["cache_hit_repeat"] is True
    assert rec["selftest_failures"] == []
    lines = [json.loads(l) for l in open(metrics)]
    snap = [l for l in lines if l.get("event") == "snapshot"][0]
    assert snap["counters"]["inverse_iterations_total"] >= 1
    assert any(k.startswith("inverse_loss") for k in snap["series"])


def test_cli_direct_mode_with_field_files(tmp_path):
    from heat2d_tpu.diff.cli import main
    from heat2d_tpu.io import load_field, save_field

    nx, ny, steps = 12, 12, 12
    _, u0, mask, values = _observed_problem(nx, ny, steps)
    obs_p = str(tmp_path / "obs.bin")
    mask_p = str(tmp_path / "mask.bin")
    save_field(values, obs_p, name="observations")
    save_field(mask, mask_p, name="obs_mask")
    out_p = str(tmp_path / "recovered.bin")
    record = str(tmp_path / "rec.json")
    rc = main(["--target", "diffusivity", "--nxprob", str(nx),
               "--nyprob", str(ny), "--steps", str(steps),
               "--iterations", "60", "--lr", "0.02",
               "--observations", obs_p, "--obs-mask", mask_p,
               "--save-recovered", out_p, "--run-record", record])
    assert rc == 0
    rec = json.load(open(record))
    assert rec["kind"] == "inverse" and rec["iterations"] == 60
    back, meta = load_field(out_p)
    assert back.shape == (nx, ny)
    assert meta["name"] == "recovered_diffusivity"
    assert meta["iterations"] == 60
