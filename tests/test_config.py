"""Config validation tests (reference startup checks — SURVEY.md §5.6,
grad1612_mpi_heat.c:54-64, mpi_heat2Dn.c:72-78)."""

import pytest

from heat2d_tpu.config import ConfigError, HeatConfig


def test_defaults_match_reference():
    c = HeatConfig()
    assert (c.nxprob, c.nyprob, c.steps) == (10, 10, 100)
    assert (c.cx, c.cy) == (0.1, 0.1)
    assert (c.interval, c.sensitivity) == (20, 0.1)
    assert c.convergence is False  # grad1612_mpi_heat.c:14


def test_divisibility_validation():
    with pytest.raises(ConfigError, match="not an integer"):
        HeatConfig(nxprob=10, nyprob=10, gridx=3, gridy=2, mode="dist2d")


def test_strict_baseline_worker_range():
    with pytest.raises(ConfigError, match="between"):
        HeatConfig(mode="dist1d", numworkers=2, strict_baseline=True,
                   nxprob=10)


def test_bad_mode():
    with pytest.raises(ConfigError):
        HeatConfig(mode="cuda")


def test_cell_sizes():
    c = HeatConfig(nxprob=640, nyprob=512, gridx=4, gridy=2, mode="dist2d")
    assert (c.xcell, c.ycell) == (160, 256)
    assert c.n_shards == 8


def test_roundtrip_dict():
    c = HeatConfig(nxprob=64, nyprob=32, mode="dist2d", gridx=2, gridy=2,
                   convergence=True)
    assert HeatConfig.from_dict(c.to_dict()) == c
