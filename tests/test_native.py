"""Native C++ formatter parity: byte-identical to the pure-Python writers
(which are themselves printf-parity-tested in test_writers.py)."""

import numpy as np
import pytest

from heat2d_tpu.io import writers
from heat2d_tpu.ops import inidat


@pytest.fixture(scope="module")
def native():
    try:
        from heat2d_tpu.native import lib
        return lib.load()
    except ImportError:
        pytest.skip("native library unavailable (no compiler)")


def _python_rowmajor(a):
    rows = []
    for i in range(a.shape[0]):
        rows.append("".join(format(float(v), "6.1f") + " " for v in a[i]))
    return "\n".join(rows) + "\n"


def _python_baseline(a):
    nx, ny = a.shape
    lines = []
    for iy in range(ny - 1, -1, -1):
        lines.append(" ".join(format(float(a[ix, iy]), "6.1f")
                              for ix in range(nx)))
    return "\n".join(lines) + "\n"


def test_native_rowmajor_byte_parity(native, rng):
    a = np.concatenate([
        rng.uniform(-1e6, 1e6, 97),
        np.array([0.0, -0.0, 0.05, -2.25, 1e8]),
    ]).astype(np.float32).reshape(6, 17)
    assert native.format_rowmajor(a) == _python_rowmajor(a)


def test_native_baseline_byte_parity(native, rng):
    a = rng.uniform(-1e4, 1e4, (11, 7)).astype(np.float32)
    assert native.format_baseline(a) == _python_baseline(a)


def test_writers_use_native_when_available(native):
    """The io.writers module routes through the native path and produces
    the same bytes either way."""
    u = np.asarray(inidat(12, 9))
    via_module = writers.format_grid_rowmajor(u)
    assert via_module == _python_rowmajor(u)
    via_module_b = writers.format_grid_baseline(u)
    assert via_module_b == _python_baseline(u)
