"""Real multi-process (jax.distributed) integration test — the mpiexec-
style launch the reference's MPI programs assume, exercised with two
actual processes over the Gloo CPU backend (SURVEY.md §2.4: the
MPI_Init/Comm_rank bring-up surface).

Each subprocess gets 2 virtual CPU devices, so the (2,2) mesh spans both
processes and the dist2d shard_map program runs with genuinely
non-addressable remote shards — covering the cross-host gather, the
rank-0 output discipline, and coordinator bring-up that single-process
tests cannot reach.
"""

import os
import subprocess
import socket
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_dist2d_matches_serial(tmp_path, oracle):
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = []
    for i in range(2):
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "heat2d_tpu.cli", "--mode", "dist2d",
             "--gridx", "2", "--gridy", "2",
             "--nxprob", "16", "--nyprob", "16", "--steps", "10",
             "--platform", "cpu", "--host-device-count", "2",
             "--coordinator", f"localhost:{port}",
             "--num-processes", "2", "--process-id", str(i),
             "--outdir", str(tmp_path)],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = [p.communicate(timeout=220)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), outs

    # Rank-0 output discipline: exactly one process printed the banner.
    banners = sum("Problem size:16x16" in o for o in outs)
    assert banners == 1, outs

    from heat2d_tpu.io import read_grid_text
    got = read_grid_text(tmp_path / "final.dat", "rowmajor")
    ref = oracle.run(16, 16, 10)
    np.testing.assert_allclose(got, ref, atol=0.05)  # %6.1f resolution
