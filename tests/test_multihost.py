"""Real multi-process (jax.distributed) integration test — the mpiexec-
style launch the reference's MPI programs assume, exercised with two
actual processes over the Gloo CPU backend (SURVEY.md §2.4: the
MPI_Init/Comm_rank bring-up surface).

Each subprocess gets 2 virtual CPU devices, so the (2,2) mesh spans both
processes and the dist2d shard_map program runs with genuinely
non-addressable remote shards — covering the cross-host gather, the
rank-0 output discipline, and coordinator bring-up that single-process
tests cannot reach.

Spawn/rendezvous/collect plumbing and the once-per-session capability
probe live in ``heat2d_tpu.dist.harness`` (this file's original probe,
promoted to a library the ``heat2d-tpu-dist`` driver legs share).
These tests need cross-process XLA COLLECTIVES — the stronger of the
two probed capabilities — so builds whose backend cannot host them
skip with the exact backend error line; the rendezvous-only dist/
tests (tests/test_dist.py) keep running there.
"""

import subprocess
import sys

import numpy as np
import pytest

from heat2d_tpu.dist.harness import (
    REPO, clean_env, collectives_unsupported_reason, spawn_world)


@pytest.fixture(autouse=True)
def _require_two_process_harness():
    """Every test here spawns a REAL 2-process jax.distributed
    computation; skip-with-reason (not fail) when the environment
    can't host one — tier-1 stays green-or-skipped instead of
    silently red."""
    reason = collectives_unsupported_reason()
    if reason is not None:
        pytest.skip(f"2-process harness unavailable: {reason}")


def _launch_dist2d(outdir, extra, *, env=None, steps=10,
                   gridx=2, gridy=2, host_devices=2, timeout=220):
    """One 2-process dist2d world through the shared harness; returns
    the merged per-process outputs (asserting both ranks exited 0)."""
    results = spawn_world(
        2, lambda i, coord: [
            sys.executable, "-m", "heat2d_tpu.cli", "--mode", "dist2d",
            "--gridx", str(gridx), "--gridy", str(gridy),
            "--nxprob", "16", "--nyprob", "16", "--steps", str(steps),
            "--platform", "cpu",
            "--host-device-count", str(host_devices),
            "--coordinator", coord,
            "--num-processes", "2", "--process-id", str(i),
            "--outdir", str(outdir)] + extra(i),
        env=env, timeout=timeout)
    outs = [r.output for r in results]
    assert all(r.ok for r in results), outs
    return outs


def test_two_process_dist2d_matches_serial(tmp_path, oracle):
    outs = _launch_dist2d(tmp_path, lambda i: [])

    # Rank-0 output discipline: exactly one process printed the banner.
    banners = sum("Problem size:16x16" in o for o in outs)
    assert banners == 1, outs

    from heat2d_tpu.io import read_grid_text
    got = read_grid_text(tmp_path / "final.dat", "rowmajor")
    ref = oracle.run(16, 16, 10)
    np.testing.assert_allclose(got, ref, atol=0.05)  # %6.1f resolution


def test_two_process_periodic_checkpoint_device_resident(tmp_path):
    """--checkpoint-every across real processes stays device-resident:
    the carry is never allgathered between segments (VERDICT r3 weak #5)
    — the WHOLE flow runs under the HEAT2D_FORBID_GATHER tripwire
    (parallel.multihost.gather_to_host raises on any host-spanning
    gather), restart points ride the collective per-shard path, and the
    final per-shard binary must be byte-identical to an unsegmented
    2-process run of the same problem."""
    env = clean_env({"HEAT2D_FORBID_GATHER": "1"})

    def launch(outdir, extra):
        _launch_dist2d(
            outdir, lambda i: [
                "--binary-dumps", "--dat-layout", "none",
                "--run-record", str(outdir / f"rec{i}.json")] + extra,
            env=env)

    seg = tmp_path / "seg"
    ref = tmp_path / "ref"
    seg.mkdir(), ref.mkdir()
    launch(seg, ["--checkpoint", str(seg / "ck.bin"),
                 "--checkpoint-every", "4"])     # segments 4 + 4 + 2
    launch(ref, [])

    assert ((seg / "final_binary.dat").read_bytes()
            == (ref / "final_binary.dat").read_bytes())
    # The last restart point IS the final state, at the full step count.
    from heat2d_tpu.io import load_checkpoint
    grid, step, _ = load_checkpoint(str(seg / "ck.bin"))
    assert step == 10
    np.testing.assert_array_equal(
        grid.tobytes(), (ref / "final_binary.dat").read_bytes())


def _interval_residuals(nx, ny, steps, interval):
    """Σ(Δu)² at each INTERVAL check of a serial run — the quantity
    run_convergence compares against sensitivity (engine.py:62-63),
    computed with the golden step so the test can PICK sensitivities
    that fire at chosen checks."""
    import jax.numpy as jnp
    from heat2d_tpu.ops import inidat, stencil_step
    u = inidat(nx, ny)
    res = {}
    for k in range(1, steps + 1):
        new = stencil_step(u, 0.1, 0.1)
        if k % interval == 0:
            res[k] = float(jnp.sum((new - u) ** 2))
        u = new
    return res


def test_two_process_convergence_with_periodic_checkpoint(tmp_path):
    """Convergence x --checkpoint-every (VERDICT r4 weak #5): a
    sensitivity firing MID-SEGMENT must give segmented == unsegmented
    steps_done and byte-identical finals; a sensitivity firing exactly
    ON a segment boundary pins the ONE documented deviation
    (cli.py:163-167): the segmented run notices one INTERVAL late, so
    steps_done = unsegmented + INTERVAL."""
    import json

    nx = ny = 16
    interval, seg_k = 4, 8
    res = _interval_residuals(nx, ny, 24, interval)
    # Residuals must be strictly decreasing at these checks, or the
    # "first check below S" arithmetic below is ill-posed.
    assert res[4] > res[8] > res[12], res
    s_mid = (res[8] * res[12]) ** 0.5    # first check below: step 12
    s_bnd = (res[4] * res[8]) ** 0.5     # first check below: step 8

    env = clean_env({"HEAT2D_FORBID_GATHER": "1"})

    def launch(outdir, sens, extra):
        _launch_dist2d(
            outdir, lambda i: [
                "--convergence", "--interval", str(interval),
                "--sensitivity", repr(sens),
                "--binary-dumps", "--dat-layout", "none",
                "--run-record", str(outdir / f"rec{i}.json")] + extra,
            env=env, steps=200)
        rec = json.loads((outdir / "rec0.json").read_text())
        return rec["steps_done"]

    # Mid-segment convergence (step 12, segments of 8): identical.
    seg = tmp_path / "seg"
    ref = tmp_path / "ref"
    seg.mkdir(), ref.mkdir()
    k_seg = launch(seg, s_mid, ["--checkpoint", str(seg / "ck.bin"),
                                "--checkpoint-every", str(seg_k)])
    k_ref = launch(ref, s_mid, [])
    assert k_ref == 12 and k_seg == 12, (k_seg, k_ref)
    assert ((seg / "final_binary.dat").read_bytes()
            == (ref / "final_binary.dat").read_bytes())
    # The last restart point is the converged state at its step count.
    from heat2d_tpu.io import load_checkpoint
    grid, step, _ = load_checkpoint(str(seg / "ck.bin"))
    assert step == 12
    np.testing.assert_array_equal(
        grid.tobytes(), (ref / "final_binary.dat").read_bytes())

    # Boundary-landing convergence (step 8 == segment end): the
    # segmented run only notices one INTERVAL into the next segment —
    # steps_done = 8 + interval, the exact documented deviation.
    segb = tmp_path / "segb"
    refb = tmp_path / "refb"
    segb.mkdir(), refb.mkdir()
    k_segb = launch(segb, s_bnd, ["--checkpoint", str(segb / "ck.bin"),
                                  "--checkpoint-every", str(seg_k)])
    k_refb = launch(refb, s_bnd, [])
    assert k_refb == 8, k_refb
    assert k_segb == 8 + interval, k_segb


def test_two_process_parallel_binary_write(tmp_path):
    """The MPI_File_write_all analogue across real processes: each rank
    writes its shards into the one file; result must be byte-identical to
    a serial run's dump, with text conversion fed by rank-0 read-back
    (no cross-host allgather in the --dat-layout none path at all)."""
    env = clean_env()
    _launch_dist2d(
        tmp_path, lambda i: [
            "--binary-dumps", "--dat-layout", "none",
            "--checkpoint", str(tmp_path / "ck.bin")],
        env=env)

    # Serial single-process run for the byte-identical reference files.
    sdir = tmp_path / "serial"
    rc = subprocess.run(
        [sys.executable, "-m", "heat2d_tpu.cli", "--mode", "serial",
         "--nxprob", "16", "--nyprob", "16", "--steps", "10",
         "--platform", "cpu", "--binary-dumps", "--dat-layout", "none",
         "--outdir", str(sdir)],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert rc.returncode == 0, rc.stdout + rc.stderr

    for name in ("initial_binary.dat", "final_binary.dat"):
        assert ((tmp_path / name).read_bytes()
                == (sdir / name).read_bytes()), name
    # Collective per-shard checkpoint: loadable, correct step count.
    from heat2d_tpu.io import load_checkpoint
    grid, step, _ = load_checkpoint(str(tmp_path / "ck.bin"))
    assert step == 10 and grid.shape == (16, 16)
    np.testing.assert_array_equal(
        grid.tobytes(), (sdir / "final_binary.dat").read_bytes())


def test_two_process_managed_resume_parity(tmp_path):
    """Resume parity on the REAL 2-process sharded route, through the
    managed checkpoint directory: run 6 -> collective per-shard
    snapshot into a CheckpointManager dir -> resume from
    ``latest_valid()`` for the remaining 4 must be byte-identical to an
    uninterrupted 2-process run of 10 — under the FORBID_GATHER
    tripwire, so neither the snapshot nor the resume ever materializes
    the global grid on one host."""
    import json

    env = clean_env({"HEAT2D_FORBID_GATHER": "1"})

    def launch(outdir, steps, extra):
        return _launch_dist2d(
            outdir, lambda i: [
                "--binary-dumps", "--dat-layout", "none",
                "--run-record", str(outdir / f"rec{i}.json")] + extra,
            env=env, steps=steps)

    ref = tmp_path / "ref"
    first = tmp_path / "first"
    out = tmp_path / "out"
    ck = tmp_path / "ck"
    ref.mkdir(), first.mkdir(), out.mkdir(), ck.mkdir()

    launch(ref, 10, [])
    launch(first, 6, ["--checkpoint", str(ck)])

    from heat2d_tpu.resil import CheckpointManager
    m = CheckpointManager(ck, keep=None)
    assert m.steps() == [6]

    outs = launch(out, 10, ["--resume", str(ck)])
    assert sum("Resuming from step 6" in o for o in outs) == 1, outs
    assert ((out / "final_binary.dat").read_bytes()
            == (ref / "final_binary.dat").read_bytes())
    rec = json.loads((out / "rec0.json").read_text())
    assert rec["resume_from_step"] == 6
    assert rec["total_steps_including_resume"] == 10


def test_two_process_spatial_ensemble(tmp_path):
    """Batch x spatial ensemble across REAL processes: a ('b'=2, x=2,
    y=1) mesh spanning 2 processes x 2 devices — members ride the batch
    axis while each decomposes spatially; final member dumps must match
    single-process runs of the same members byte-for-byte."""
    env = clean_env()
    outs = _launch_dist2d(
        tmp_path, lambda i: ["--ensemble-cx", "0.1,0.2",
                             "--ensemble-cy", "0.1,0.1"],
        env=env, gridx=2, gridy=1)
    assert sum("spatial submesh" in o for o in outs) == 1, outs

    sdir = tmp_path / "single"
    rc = subprocess.run(
        [sys.executable, "-m", "heat2d_tpu.cli", "--mode", "serial",
         "--nxprob", "16", "--nyprob", "16", "--steps", "10",
         "--ensemble-cx", "0.1,0.2", "--ensemble-cy", "0.1,0.1",
         "--platform", "cpu", "--outdir", str(sdir)],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert rc.returncode == 0, rc.stdout + rc.stderr
    for i in range(2):
        assert ((tmp_path / f"final_m{i}.dat").read_bytes()
                == (sdir / f"final_m{i}.dat").read_bytes()), f"member {i}"
