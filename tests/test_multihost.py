"""Real multi-process (jax.distributed) integration test — the mpiexec-
style launch the reference's MPI programs assume, exercised with two
actual processes over the Gloo CPU backend (SURVEY.md §2.4: the
MPI_Init/Comm_rank bring-up surface).

Each subprocess gets 2 virtual CPU devices, so the (2,2) mesh spans both
processes and the dist2d shard_map program runs with genuinely
non-addressable remote shards — covering the cross-host gather, the
rank-0 output discipline, and coordinator bring-up that single-process
tests cannot reach.
"""

import os
import subprocess
import socket
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_dist2d_matches_serial(tmp_path, oracle):
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = []
    for i in range(2):
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "heat2d_tpu.cli", "--mode", "dist2d",
             "--gridx", "2", "--gridy", "2",
             "--nxprob", "16", "--nyprob", "16", "--steps", "10",
             "--platform", "cpu", "--host-device-count", "2",
             "--coordinator", f"localhost:{port}",
             "--num-processes", "2", "--process-id", str(i),
             "--outdir", str(tmp_path)],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = [p.communicate(timeout=220)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), outs

    # Rank-0 output discipline: exactly one process printed the banner.
    banners = sum("Problem size:16x16" in o for o in outs)
    assert banners == 1, outs

    from heat2d_tpu.io import read_grid_text
    got = read_grid_text(tmp_path / "final.dat", "rowmajor")
    ref = oracle.run(16, 16, 10)
    np.testing.assert_allclose(got, ref, atol=0.05)  # %6.1f resolution


def test_two_process_periodic_checkpoint_device_resident(tmp_path):
    """--checkpoint-every across real processes stays device-resident:
    the carry is never allgathered between segments (VERDICT r3 weak #5)
    — the WHOLE flow runs under the HEAT2D_FORBID_GATHER tripwire
    (parallel.multihost.gather_to_host raises on any host-spanning
    gather), restart points ride the collective per-shard path, and the
    final per-shard binary must be byte-identical to an unsegmented
    2-process run of the same problem."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["HEAT2D_FORBID_GATHER"] = "1"

    def launch(outdir, extra):
        port = _free_port()
        procs = []
        for i in range(2):
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "heat2d_tpu.cli", "--mode",
                 "dist2d", "--gridx", "2", "--gridy", "2",
                 "--nxprob", "16", "--nyprob", "16", "--steps", "10",
                 "--platform", "cpu", "--host-device-count", "2",
                 "--coordinator", f"localhost:{port}",
                 "--num-processes", "2", "--process-id", str(i),
                 "--binary-dumps", "--dat-layout", "none",
                 "--run-record", str(outdir / f"rec{i}.json"),
                 "--outdir", str(outdir)] + extra,
                cwd=REPO, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        outs = [p.communicate(timeout=220)[0] for p in procs]
        assert all(p.returncode == 0 for p in procs), outs

    seg = tmp_path / "seg"
    ref = tmp_path / "ref"
    seg.mkdir(), ref.mkdir()
    launch(seg, ["--checkpoint", str(seg / "ck.bin"),
                 "--checkpoint-every", "4"])     # segments 4 + 4 + 2
    launch(ref, [])

    assert ((seg / "final_binary.dat").read_bytes()
            == (ref / "final_binary.dat").read_bytes())
    # The last restart point IS the final state, at the full step count.
    from heat2d_tpu.io import load_checkpoint
    grid, step, _ = load_checkpoint(str(seg / "ck.bin"))
    assert step == 10
    np.testing.assert_array_equal(
        grid.tobytes(), (ref / "final_binary.dat").read_bytes())


def test_two_process_parallel_binary_write(tmp_path):
    """The MPI_File_write_all analogue across real processes: each rank
    writes its shards into the one file; result must be byte-identical to
    a serial run's dump, with text conversion fed by rank-0 read-back
    (no cross-host allgather in the --dat-layout none path at all)."""
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = []
    for i in range(2):
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "heat2d_tpu.cli", "--mode", "dist2d",
             "--gridx", "2", "--gridy", "2",
             "--nxprob", "16", "--nyprob", "16", "--steps", "10",
             "--platform", "cpu", "--host-device-count", "2",
             "--coordinator", f"localhost:{port}",
             "--num-processes", "2", "--process-id", str(i),
             "--binary-dumps", "--dat-layout", "none",
             "--checkpoint", str(tmp_path / "ck.bin"),
             "--outdir", str(tmp_path)],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = [p.communicate(timeout=220)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), outs

    # Serial single-process run for the byte-identical reference files.
    sdir = tmp_path / "serial"
    rc = subprocess.run(
        [sys.executable, "-m", "heat2d_tpu.cli", "--mode", "serial",
         "--nxprob", "16", "--nyprob", "16", "--steps", "10",
         "--platform", "cpu", "--binary-dumps", "--dat-layout", "none",
         "--outdir", str(sdir)],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert rc.returncode == 0, rc.stdout + rc.stderr

    for name in ("initial_binary.dat", "final_binary.dat"):
        assert ((tmp_path / name).read_bytes()
                == (sdir / name).read_bytes()), name
    # Collective per-shard checkpoint: loadable, correct step count.
    from heat2d_tpu.io import load_checkpoint
    grid, step, _ = load_checkpoint(str(tmp_path / "ck.bin"))
    assert step == 10 and grid.shape == (16, 16)
    np.testing.assert_array_equal(
        grid.tobytes(), (sdir / "final_binary.dat").read_bytes())


def test_two_process_spatial_ensemble(tmp_path):
    """Batch x spatial ensemble across REAL processes: a ('b'=2, x=2,
    y=1) mesh spanning 2 processes x 2 devices — members ride the batch
    axis while each decomposes spatially; final member dumps must match
    single-process runs of the same members byte-for-byte."""
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = []
    for i in range(2):
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "heat2d_tpu.cli", "--mode", "dist2d",
             "--gridx", "2", "--gridy", "1",
             "--nxprob", "16", "--nyprob", "16", "--steps", "10",
             "--ensemble-cx", "0.1,0.2", "--ensemble-cy", "0.1,0.1",
             "--platform", "cpu", "--host-device-count", "2",
             "--coordinator", f"localhost:{port}",
             "--num-processes", "2", "--process-id", str(i),
             "--outdir", str(tmp_path)],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = [p.communicate(timeout=220)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), outs
    assert sum("spatial submesh" in o for o in outs) == 1, outs

    sdir = tmp_path / "single"
    rc = subprocess.run(
        [sys.executable, "-m", "heat2d_tpu.cli", "--mode", "serial",
         "--nxprob", "16", "--nyprob", "16", "--steps", "10",
         "--ensemble-cx", "0.1,0.2", "--ensemble-cy", "0.1,0.1",
         "--platform", "cpu", "--outdir", str(sdir)],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert rc.returncode == 0, rc.stdout + rc.stderr
    for i in range(2):
        assert ((tmp_path / f"final_m{i}.dat").read_bytes()
                == (sdir / f"final_m{i}.dat").read_bytes()), f"member {i}"
