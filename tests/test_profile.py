"""Phase-share attribution tests for benchmarks/profile_phases.py — the
mpiP-analogue post-processor — on synthetic trace events shaped like the
two real layouts (TPU device lanes, CPU backend executor threads)."""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.profile_phases import classify, phase_shares  # noqa: E402


def test_classify_op_families():
    assert classify("ppermute.43") == "halo exchange (ppermute)"
    assert classify("collective-permute.2") == "halo exchange (ppermute)"
    assert classify("psum_invariant.6") == "residual reduction (psum)"
    assert classify("all-reduce.1") == "residual reduction (psum)"
    assert classify("Rendezvous") == "synchronization (rendezvous/wait)"
    assert classify("Wait: pending_threads=3/8") \
        == "synchronization (rendezvous/wait)"
    assert classify("closed_call.4") == "stencil kernel (pallas sweep)"
    assert classify("copy.11") == "carry copies (HBM)"
    assert classify("fusion.2").startswith("stencil compute")
    assert classify("while.60") is None          # parent span, not a phase
    assert classify("unknown_op.9") is None


def _meta(pid, pname, tid, tname):
    return [
        {"ph": "M", "pid": pid, "name": "process_name",
         "args": {"name": pname}},
        {"ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
         "args": {"name": tname}},
    ]


def _ev(pid, tid, name, dur_us):
    return {"ph": "X", "pid": pid, "tid": tid, "name": name,
            "dur": dur_us}


def test_phase_shares_tpu_layout():
    """TPU: total from the 'jit_*' module span, leaves from 'XLA Ops'
    (with 'while' parents skipped so nothing double-counts)."""
    events = (
        _meta(3, "/device:TPU:0", 2, "XLA Modules")
        + _meta(3, "/device:TPU:0", 3, "XLA Ops")
        + [
            _ev(3, 2, "jit__lambda(123)", 1_000_000),
            _ev(3, 3, "while", 990_000),                 # parent: skipped
            _ev(3, 3, "closed_call.4", 900_000),
            _ev(3, 3, "copy.11", 50_000),
            _ev(3, 3, "fusion.2", 20_000),
        ])
    shares, total, lanes = phase_shares(events)
    assert total == pytest.approx(1.0)
    assert lanes == 1
    assert shares["stencil kernel (pallas sweep)"] == pytest.approx(0.9)
    assert shares["carry copies (HBM)"] == pytest.approx(0.05)
    # remainder (loop control) is total - attributed
    assert total - sum(shares.values()) == pytest.approx(0.03)


def test_phase_shares_cpu_layout():
    """CPU backend: total from ThunkExecutor::Execute per device thread;
    leaf thunks carry HLO names; seconds sum across lanes."""
    events = []
    for d in range(2):
        tid = 10 + d
        events += _meta(700 + d, "/host:CPU", tid,
                        f"tf_XLAPjRtCpuClient/{d}")
        events += [
            _ev(700 + d, tid, "ThunkExecutor::Execute", 500_000),
            _ev(700 + d, tid, "while.60", 480_000),      # parent: skipped
            _ev(700 + d, tid, "ppermute.43", 200_000),
            _ev(700 + d, tid, "Rendezvous", 100_000),
            _ev(700 + d, tid, "multiply_add_fusion", 50_000),
        ]
    shares, total, lanes = phase_shares(events)
    assert lanes == 2
    assert total == pytest.approx(1.0)        # 2 lanes x 0.5 s
    assert shares["halo exchange (ppermute)"] == pytest.approx(0.4)
    assert shares["synchronization (rendezvous/wait)"] == pytest.approx(0.2)


def test_phase_shares_total_never_below_attributed():
    """A trace with leaves but no parent span still yields a sane total
    (max of parents, attributed sum)."""
    events = (_meta(3, "/device:TPU:0", 3, "XLA Ops")
              + [_ev(3, 3, "closed_call.1", 100_000)])
    shares, total, _ = phase_shares(events)
    assert total == pytest.approx(0.1)
    assert sum(shares.values()) == pytest.approx(0.1)
