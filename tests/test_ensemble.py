"""Ensemble (vmap-over-instances) tests — the DP-over-batch capability the
reference lacks (SURVEY.md §2.3)."""

import numpy as np
import pytest

from heat2d_tpu.models.ensemble import ensemble_summary, run_ensemble


def test_ensemble_matches_individual_runs(oracle):
    cxs = [0.05, 0.1, 0.2]
    cys = [0.1, 0.1, 0.05]
    batch = np.asarray(run_ensemble(12, 16, 30, cxs, cys))
    assert batch.shape == (3, 12, 16)
    for b, (cx, cy) in enumerate(zip(cxs, cys)):
        ref = oracle.run(12, 16, 30, cx=cx, cy=cy)
        np.testing.assert_allclose(batch[b], ref, rtol=1e-5, atol=1e-3)


def test_ensemble_custom_initial_states():
    u0 = np.zeros((2, 8, 8), np.float32)
    u0[:, 4, 4] = 100.0
    batch = np.asarray(run_ensemble(8, 8, 10, [0.1, 0.1], [0.1, 0.1], u0=u0))
    np.testing.assert_allclose(batch[0], batch[1])
    assert batch[0].max() < 100.0  # heat diffused


def test_ensemble_validates_shapes():
    with pytest.raises(ValueError):
        run_ensemble(8, 8, 1, [0.1, 0.2], [0.1])
    with pytest.raises(ValueError):
        run_ensemble(8, 8, 1, [0.1], [0.1],
                     u0=np.zeros((2, 8, 8), np.float32))


def test_ensemble_summary():
    s = ensemble_summary(np.ones((2, 4, 4), np.float32))
    assert s["members"] == 2
    assert s["total_heat"] == [16.0, 16.0]
