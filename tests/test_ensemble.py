"""Ensemble (vmap-over-instances) tests — the DP-over-batch capability the
reference lacks (SURVEY.md §2.3)."""

import numpy as np
import pytest

from heat2d_tpu.models.ensemble import ensemble_summary, run_ensemble


def test_ensemble_matches_individual_runs(oracle):
    cxs = [0.05, 0.1, 0.2]
    cys = [0.1, 0.1, 0.05]
    batch = np.asarray(run_ensemble(12, 16, 30, cxs, cys))
    assert batch.shape == (3, 12, 16)
    for b, (cx, cy) in enumerate(zip(cxs, cys)):
        ref = oracle.run(12, 16, 30, cx=cx, cy=cy)
        np.testing.assert_allclose(batch[b], ref, rtol=1e-5, atol=1e-3)


def test_ensemble_custom_initial_states():
    u0 = np.zeros((2, 8, 8), np.float32)
    u0[:, 4, 4] = 100.0
    batch = np.asarray(run_ensemble(8, 8, 10, [0.1, 0.1], [0.1, 0.1], u0=u0))
    np.testing.assert_allclose(batch[0], batch[1])
    assert batch[0].max() < 100.0  # heat diffused


def test_ensemble_validates_shapes():
    with pytest.raises(ValueError):
        run_ensemble(8, 8, 1, [0.1, 0.2], [0.1])
    with pytest.raises(ValueError):
        run_ensemble(8, 8, 1, [0.1], [0.1],
                     u0=np.zeros((2, 8, 8), np.float32))


def test_ensemble_summary():
    s = ensemble_summary(np.ones((2, 4, 4), np.float32))
    assert s["members"] == 2
    assert s["total_heat"] == [16.0, 16.0]


def test_ensemble_pallas_matches_jnp():
    """The batched kernel (per-member (cx,cy) as SMEM scalars, program
    grid over members) must agree with the vmap path."""
    cxs, cys = [0.05, 0.1, 0.2], [0.1, 0.1, 0.05]
    a = np.asarray(run_ensemble(16, 128, 25, cxs, cys, method="jnp"))
    b = np.asarray(run_ensemble(16, 128, 25, cxs, cys, method="pallas"))
    np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-4)


@pytest.mark.parametrize("steps", [12, 19])  # full sweeps + a remainder
def test_ensemble_band_matches_jnp(steps, monkeypatch):
    """The batched BAND kernel (HBM-sized members, (member, band) program
    grid) must agree with the vmap path — including pad rows from a
    divisor-poor member height, inter-band strips, and a remainder
    sweep. The VMEM budget is pinned tiny so plan_bands yields bm=8
    (multi-band + m_pad > nx) instead of one whole-member band."""
    import heat2d_tpu.ops.pallas_stencil as ps
    monkeypatch.setattr(ps, "VMEM_BUDGET_BYTES", 8 * 128 * 4 * 4)
    cxs, cys = [0.05, 0.1, 0.2], [0.1, 0.1, 0.05]
    a = np.asarray(run_ensemble(36, 128, steps, cxs, cys, method="jnp"))
    b = np.asarray(run_ensemble(36, 128, steps, cxs, cys, method="band"))
    np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-4)


def test_ensemble_auto_routes_big_members_to_band(monkeypatch):
    """'auto' must pick the band kernel, not the jnp fallback, when a
    member exceeds the VMEM budget (VERDICT r2 weak #3)."""
    import heat2d_tpu.models.ensemble as ens
    import heat2d_tpu.ops.pallas_stencil as ps
    monkeypatch.setattr(ps, "VMEM_BUDGET_BYTES", 1024)
    assert ens._pick_method("auto", 64, 128) == "band"
    a = np.asarray(run_ensemble(64, 128, 10, [0.1, 0.2], [0.1, 0.1],
                                method="auto"))
    b = np.asarray(run_ensemble(64, 128, 10, [0.1, 0.2], [0.1, 0.1],
                                method="jnp"))
    np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-4)


@pytest.mark.parametrize("members", [3, 8, 9])
def test_ensemble_sharded_matches_single(members):
    """Batch as a mesh axis over the 8 virtual devices (uneven member
    counts pad with inert members) == the single-device batch."""
    from heat2d_tpu.models.ensemble import run_ensemble_sharded
    cxs = [0.02 * (i + 1) for i in range(members)]
    cys = [0.1] * members
    want = np.asarray(run_ensemble(8, 16, 12, cxs, cys, method="jnp"))
    got = np.asarray(run_ensemble_sharded(8, 16, 12, cxs, cys,
                                          method="jnp"))
    assert got.shape == (members, 8, 16)
    np.testing.assert_array_equal(got, want)


def test_timed_ensemble():
    from heat2d_tpu.models.ensemble import timed_ensemble
    batch, steps_done, elapsed = timed_ensemble(
        8, 16, 5, [0.1, 0.2], [0.1, 0.1])
    assert batch.shape == (2, 8, 16)
    assert steps_done is None  # fixed-step: every member ran exactly 5
    assert elapsed > 0


# ------------------------------------------------------------------ #
# Convergence (per-member early-exit) ensembles — VERDICT r3 #4
# ------------------------------------------------------------------ #

def _individual_conv(nx, ny, steps, interval, sens, cx, cy):
    """One member's reference trajectory: the engine convergence loop on
    the golden step — what each ensemble member must bitwise-match."""
    import jax
    from heat2d_tpu.models import engine
    from heat2d_tpu.ops.init import inidat
    from heat2d_tpu.ops.stencil import residual_sq, stencil_step

    fn = jax.jit(lambda u: engine.run_convergence(
        lambda v: stencil_step(v, cx, cy), residual_sq,
        u, steps, interval, sens))
    u, k = fn(inidat(nx, ny))
    return np.asarray(u), int(k)


def test_ensemble_convergence_bitwise_matches_individual_runs():
    """Members with different diffusivities exit at different chunk
    counts; each must match its individual convergence run BITWISE, with
    the same steps_done (converged members froze — masked completion)."""
    from heat2d_tpu.models.ensemble import run_ensemble_convergence

    cxs, cys = [0.02, 0.1, 0.2], [0.02, 0.1, 0.2]
    steps, interval, sens = 400, 20, 5.0
    batch, ks = run_ensemble_convergence(12, 16, steps, interval, sens,
                                         cxs, cys, method="jnp")
    ks = [int(k) for k in ks]
    for b, (cx, cy) in enumerate(zip(cxs, cys)):
        want, k = _individual_conv(12, 16, steps, interval, sens, cx, cy)
        assert ks[b] == k, f"member {b}: {ks[b]} != {k}"
        np.testing.assert_array_equal(np.asarray(batch)[b], want)
    # the point of the test: the exits actually differ across members
    assert len(set(ks)) > 1, ks


def test_ensemble_convergence_kernel_matches_chunked():
    """The batched kernel convergence loop must reproduce the individual
    chunked schedule member-wise (chunks of interval-1 fused + 1 tracked
    step, remainder unchecked on unconverged members)."""
    import jax
    from heat2d_tpu.models import engine
    from heat2d_tpu.models.ensemble import run_ensemble_convergence
    from heat2d_tpu.ops import pallas_stencil as ps
    from heat2d_tpu.ops.init import inidat
    from heat2d_tpu.ops.stencil import residual_sq

    # Binary-exact diffusivities: the individual path bakes cx as a
    # Python float (k0 pre-computed in double), the batched kernel
    # computes it from an f32 SMEM scalar — inexact constants differ by
    # 1 ulp in k0 and drift apart over 150 steps. 2^-5 and 2^-2 are
    # exact in both, so the comparison isolates the *schedule*.
    cxs, cys = [0.03125, 0.25], [0.03125, 0.25]
    # sens between the members' chunk-1 residuals: the slow-diffusion
    # member (smaller per-step delta) exits at chunk 1, the fast one
    # runs the full budget incl. the 150 % 20 = 10 remainder.
    steps, interval, sens = 150, 20, 1e8
    batch, ks = run_ensemble_convergence(16, 128, steps, interval, sens,
                                         cxs, cys, method="pallas")
    for b, (cx, cy) in enumerate(zip(cxs, cys)):
        fn = jax.jit(lambda u, cx=cx, cy=cy: engine.run_convergence_chunked(
            lambda v, n: ps.multi_step_vmem(v, n, cx, cy),
            lambda v: ps.multi_step_vmem(v, 1, cx, cy),
            residual_sq, u, steps, interval, sens))
        want, k = fn(inidat(16, 128))
        assert int(ks[b]) == int(k), f"member {b}"
        np.testing.assert_allclose(np.asarray(batch)[b], np.asarray(want),
                                   rtol=1e-6, atol=1e-4)
    assert int(ks[0]) != int(ks[1])


def test_ensemble_convergence_band_method(monkeypatch):
    """Early-exit through the batched BAND kernel (HBM-sized members:
    budget pinned tiny so members stream in multi-band sweeps with pad
    rows) is BITWISE the batched VMEM kernel's result — same step form,
    different tiling — with heterogeneous exits (member 0 converges at
    chunk 1, member 1 runs the full budget)."""
    import heat2d_tpu.ops.pallas_stencil as ps
    from heat2d_tpu.models.ensemble import run_ensemble_convergence

    monkeypatch.setattr(ps, "VMEM_BUDGET_BYTES", 8 * 128 * 4 * 4)
    cxs, cys = [0.03125, 0.25], [0.03125, 0.25]
    a, ka = run_ensemble_convergence(36, 128, 200, 10, 2e8, cxs, cys,
                                     method="pallas")
    b, kb = run_ensemble_convergence(36, 128, 200, 10, 2e8, cxs, cys,
                                     method="band")
    assert [int(x) for x in ka] == [int(x) for x in kb]
    assert int(ka[0]) != int(ka[1])
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_ensemble_convergence_sharded_matches_single():
    """Convergence ensemble over the batch mesh axis (device-local
    while_loops, inert pad members) == single-device, members cropped."""
    from heat2d_tpu.models.ensemble import (run_ensemble_convergence,
                                            run_ensemble_convergence_sharded)
    cxs = [0.02 * (i + 1) for i in range(5)]
    cys = [0.1] * 5
    want, kw = run_ensemble_convergence(8, 16, 200, 10, 0.5, cxs, cys,
                                        method="jnp")
    got, kg = run_ensemble_convergence_sharded(8, 16, 200, 10, 0.5,
                                               cxs, cys, method="jnp")
    assert got.shape == (5, 8, 16)
    assert [int(x) for x in kg] == [int(x) for x in kw]
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_cli_ensemble_convergence_run(tmp_path, capsys):
    """--convergence + ensemble: per-member exit counts reported in the
    banner and the run record (no longer rejected — VERDICT r3 #4)."""
    import json
    from heat2d_tpu.cli import main

    rec_path = tmp_path / "rec.json"
    rc = main(["--mode", "serial", "--nxprob", "12", "--nyprob", "16",
               "--steps", "400", "--convergence", "--interval", "20",
               "--sensitivity", "5.0",
               "--ensemble-cx", "0.02,0.2", "--ensemble-cy", "0.02,0.2",
               "--outdir", str(tmp_path), "--run-record", str(rec_path)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Members exited after" in out
    rec = json.loads(rec_path.read_text())
    ks = rec["summary"]["steps_done"]
    assert len(ks) == 2 and ks[0] != ks[1]
    assert all(k % 20 == 0 or k == 400 for k in ks)


def test_cli_ensemble_run(tmp_path):
    """One launch, two members: per-member dumps + run record
    (VERDICT r1 #5 done criterion)."""
    import json
    from heat2d_tpu.cli import main
    from heat2d_tpu.io import read_grid_text

    rec_path = tmp_path / "rec.json"
    rc = main(["--mode", "serial", "--nxprob", "12", "--nyprob", "16",
               "--steps", "30", "--ensemble-cx", "0.05,0.2",
               "--ensemble-cy", "0.1,0.05",
               "--outdir", str(tmp_path), "--run-record", str(rec_path)])
    assert rc == 0
    rec = json.loads(rec_path.read_text())
    assert rec["members"] == [{"cx": 0.05, "cy": 0.1},
                              {"cx": 0.2, "cy": 0.05}]
    assert rec["summary"]["members"] == 2
    for i, (cx, cy) in enumerate([(0.05, 0.1), (0.2, 0.05)]):
        got = read_grid_text(tmp_path / f"final_m{i}.dat", "rowmajor")
        want = np.asarray(run_ensemble(12, 16, 30, [cx], [cy]))[0]
        np.testing.assert_allclose(got, want, atol=0.05)  # %6.1f res


def test_cli_ensemble_sharded_run(tmp_path):
    """Distributed mode: members shard over the 8 virtual devices."""
    import json
    from heat2d_tpu.cli import main

    rec_path = tmp_path / "rec.json"
    rc = main(["--mode", "dist2d", "--nxprob", "8", "--nyprob", "16",
               "--steps", "10", "--ensemble-cx", "0.1,0.1,0.2",
               "--ensemble-cy", "0.1,0.2,0.1", "--dat-layout", "none",
               "--outdir", str(tmp_path), "--run-record", str(rec_path)])
    assert rc == 0
    rec = json.loads(rec_path.read_text())
    assert rec["summary"]["members"] == 3


def test_cli_ensemble_validation(tmp_path, capsys):
    from heat2d_tpu.cli import main
    rc = main(["--mode", "serial", "--ensemble-cx", "0.1,0.2",
               "--ensemble-cy", "0.1", "--outdir", str(tmp_path)])
    assert rc == 1
    assert "equal-length" in capsys.readouterr().err


def test_cli_ensemble_rejects_f64_accum(tmp_path, capsys):
    """The batched runners evaluate steps and residuals in f32; a
    float64-accum request must be refused, not silently run as f32."""
    from heat2d_tpu.cli import main
    rc = main(["--mode", "serial", "--accum-dtype", "float64",
               "--ensemble-cx", "0.1,0.2", "--ensemble-cy", "0.1,0.1",
               "--outdir", str(tmp_path)])
    assert rc == 1
    assert "--accum-dtype float64" in capsys.readouterr().err


def test_cli_ensemble_rejects_spatial_grid_non_dist2d(tmp_path, capsys):
    """--gridx/--gridy with a non-dist2d mode would be silently
    reinterpreted — must be refused, not ignored (the dist2d composition
    is the supported batch x spatial path)."""
    from heat2d_tpu.cli import main
    rc = main(["--mode", "hybrid", "--nxprob", "8", "--nyprob", "16",
               "--gridx", "4", "--gridy", "2",
               "--ensemble-cx", "0.1,0.2", "--ensemble-cy", "0.1,0.1",
               "--outdir", str(tmp_path)])
    assert rc == 1
    err = capsys.readouterr().err
    assert "dist2d" in err and "--gridx" in err


# --------------------------------------------------------------------- #
# Batch x spatial composition (VERDICT r3 #5)
# --------------------------------------------------------------------- #

def test_ensemble_spatial_bitwise_vs_dist2d_runs():
    """2 members on a ('b'=2, x=2, y=1) mesh, each member BITWISE equal
    to its own dist2d run on a (2, 1) mesh — the composition changes the
    orchestration (vmapped halo ppermutes over the spatial sub-axes),
    never the numbers."""
    from heat2d_tpu.config import HeatConfig
    from heat2d_tpu.models.ensemble import run_ensemble_spatial
    from heat2d_tpu.models.solver import Heat2DSolver

    cxs, cys = [0.05, 0.2], [0.1, 0.15]
    batch, ks = run_ensemble_spatial(24, 16, 30, cxs, cys,
                                     gridx=2, gridy=1)
    assert batch.shape == (2, 24, 16)
    for i, (cx, cy) in enumerate(zip(cxs, cys)):
        cfg = HeatConfig(nxprob=24, nyprob=16, steps=30, mode="dist2d",
                         gridx=2, gridy=1, cx=cx, cy=cy)
        want = Heat2DSolver(cfg).run(timed=False).u
        np.testing.assert_array_equal(np.asarray(batch[i]), want)
        assert int(ks[i]) == 30


def test_ensemble_spatial_2d_submesh_uneven_batch():
    """3 members on a ('b'=2, 2, 2) mesh: batch pads to the 'b' axis
    with an inert member, spatial shards are genuine 2D blocks."""
    from heat2d_tpu.config import HeatConfig
    from heat2d_tpu.models.ensemble import run_ensemble_spatial
    from heat2d_tpu.models.solver import Heat2DSolver

    cxs, cys = [0.05, 0.1, 0.2], [0.1, 0.05, 0.15]
    batch, _ = run_ensemble_spatial(16, 12, 25, cxs, cys,
                                    gridx=2, gridy=2)
    assert batch.shape == (3, 16, 12)
    for i, (cx, cy) in enumerate(zip(cxs, cys)):
        cfg = HeatConfig(nxprob=16, nyprob=12, steps=25, mode="dist2d",
                         gridx=2, gridy=2, cx=cx, cy=cy)
        want = Heat2DSolver(cfg).run(timed=False).u
        np.testing.assert_array_equal(np.asarray(batch[i]), want)


def test_ensemble_spatial_convergence_matches_individual():
    """Per-member early exit on the batch x spatial mesh: steps_done and
    planes BITWISE match individual dist2d convergence runs (the psum'd
    residual rides the spatial axes only, vmapped over members)."""
    from heat2d_tpu.config import HeatConfig
    from heat2d_tpu.models.ensemble import run_ensemble_spatial
    from heat2d_tpu.models.solver import Heat2DSolver

    cxs, cys = [0.02, 0.2], [0.02, 0.2]
    steps, interval, sens = 400, 20, 5.0
    batch, ks = run_ensemble_spatial(
        12, 16, steps, cxs, cys, gridx=2, gridy=1,
        convergence=True, interval=interval, sensitivity=sens)
    ks = [int(k) for k in ks]
    for i, (cx, cy) in enumerate(zip(cxs, cys)):
        cfg = HeatConfig(nxprob=12, nyprob=16, steps=steps,
                         mode="dist2d", gridx=2, gridy=1, cx=cx, cy=cy,
                         convergence=True, interval=interval,
                         sensitivity=sens)
        r = Heat2DSolver(cfg).run(timed=False)
        assert ks[i] == int(r.steps_done), f"member {i}"
        np.testing.assert_array_equal(np.asarray(batch[i]), r.u)
    assert len(set(ks)) > 1, ks


def test_cli_ensemble_spatial_run(tmp_path, capsys):
    """CLI composition: --mode dist2d --gridx/--gridy + ensemble flags
    runs the batch x spatial mesh (previously rejected)."""
    import json
    from heat2d_tpu.cli import main

    rec_path = tmp_path / "rec.json"
    rc = main(["--mode", "dist2d", "--nxprob", "16", "--nyprob", "12",
               "--steps", "20", "--gridx", "2", "--gridy", "2",
               "--ensemble-cx", "0.1,0.2", "--ensemble-cy", "0.1,0.1",
               "--outdir", str(tmp_path), "--run-record", str(rec_path)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "2x2 spatial submesh" in out
    rec = json.loads(rec_path.read_text())
    assert rec["summary"]["members"] == 2
    assert (tmp_path / "final_m1.dat").exists()
