"""Ensemble (vmap-over-instances) tests — the DP-over-batch capability the
reference lacks (SURVEY.md §2.3)."""

import numpy as np
import pytest

from heat2d_tpu.models.ensemble import ensemble_summary, run_ensemble


def test_ensemble_matches_individual_runs(oracle):
    cxs = [0.05, 0.1, 0.2]
    cys = [0.1, 0.1, 0.05]
    batch = np.asarray(run_ensemble(12, 16, 30, cxs, cys))
    assert batch.shape == (3, 12, 16)
    for b, (cx, cy) in enumerate(zip(cxs, cys)):
        ref = oracle.run(12, 16, 30, cx=cx, cy=cy)
        np.testing.assert_allclose(batch[b], ref, rtol=1e-5, atol=1e-3)


def test_ensemble_custom_initial_states():
    u0 = np.zeros((2, 8, 8), np.float32)
    u0[:, 4, 4] = 100.0
    batch = np.asarray(run_ensemble(8, 8, 10, [0.1, 0.1], [0.1, 0.1], u0=u0))
    np.testing.assert_allclose(batch[0], batch[1])
    assert batch[0].max() < 100.0  # heat diffused


def test_ensemble_validates_shapes():
    with pytest.raises(ValueError):
        run_ensemble(8, 8, 1, [0.1, 0.2], [0.1])
    with pytest.raises(ValueError):
        run_ensemble(8, 8, 1, [0.1], [0.1],
                     u0=np.zeros((2, 8, 8), np.float32))


def test_ensemble_summary():
    s = ensemble_summary(np.ones((2, 4, 4), np.float32))
    assert s["members"] == 2
    assert s["total_heat"] == [16.0, 16.0]


def test_ensemble_pallas_matches_jnp():
    """The batched kernel (per-member (cx,cy) as SMEM scalars, program
    grid over members) must agree with the vmap path."""
    cxs, cys = [0.05, 0.1, 0.2], [0.1, 0.1, 0.05]
    a = np.asarray(run_ensemble(16, 128, 25, cxs, cys, method="jnp"))
    b = np.asarray(run_ensemble(16, 128, 25, cxs, cys, method="pallas"))
    np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-4)


@pytest.mark.parametrize("steps", [12, 19])  # full sweeps + a remainder
def test_ensemble_band_matches_jnp(steps, monkeypatch):
    """The batched BAND kernel (HBM-sized members, (member, band) program
    grid) must agree with the vmap path — including pad rows from a
    divisor-poor member height, inter-band strips, and a remainder
    sweep. The VMEM budget is pinned tiny so plan_bands yields bm=8
    (multi-band + m_pad > nx) instead of one whole-member band."""
    import heat2d_tpu.ops.pallas_stencil as ps
    monkeypatch.setattr(ps, "VMEM_BUDGET_BYTES", 8 * 128 * 4 * 4)
    cxs, cys = [0.05, 0.1, 0.2], [0.1, 0.1, 0.05]
    a = np.asarray(run_ensemble(36, 128, steps, cxs, cys, method="jnp"))
    b = np.asarray(run_ensemble(36, 128, steps, cxs, cys, method="band"))
    np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-4)


def test_ensemble_auto_routes_big_members_to_band(monkeypatch):
    """'auto' must pick the band kernel, not the jnp fallback, when a
    member exceeds the VMEM budget (VERDICT r2 weak #3)."""
    import heat2d_tpu.models.ensemble as ens
    import heat2d_tpu.ops.pallas_stencil as ps
    monkeypatch.setattr(ps, "VMEM_BUDGET_BYTES", 1024)
    assert ens._pick_method("auto", 64, 128) == "band"
    a = np.asarray(run_ensemble(64, 128, 10, [0.1, 0.2], [0.1, 0.1],
                                method="auto"))
    b = np.asarray(run_ensemble(64, 128, 10, [0.1, 0.2], [0.1, 0.1],
                                method="jnp"))
    np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-4)


@pytest.mark.parametrize("members", [3, 8, 9])
def test_ensemble_sharded_matches_single(members):
    """Batch as a mesh axis over the 8 virtual devices (uneven member
    counts pad with inert members) == the single-device batch."""
    from heat2d_tpu.models.ensemble import run_ensemble_sharded
    cxs = [0.02 * (i + 1) for i in range(members)]
    cys = [0.1] * members
    want = np.asarray(run_ensemble(8, 16, 12, cxs, cys, method="jnp"))
    got = np.asarray(run_ensemble_sharded(8, 16, 12, cxs, cys,
                                          method="jnp"))
    assert got.shape == (members, 8, 16)
    np.testing.assert_array_equal(got, want)


def test_timed_ensemble():
    from heat2d_tpu.models.ensemble import timed_ensemble
    batch, elapsed = timed_ensemble(8, 16, 5, [0.1, 0.2], [0.1, 0.1])
    assert batch.shape == (2, 8, 16)
    assert elapsed > 0


def test_cli_ensemble_run(tmp_path):
    """One launch, two members: per-member dumps + run record
    (VERDICT r1 #5 done criterion)."""
    import json
    from heat2d_tpu.cli import main
    from heat2d_tpu.io import read_grid_text

    rec_path = tmp_path / "rec.json"
    rc = main(["--mode", "serial", "--nxprob", "12", "--nyprob", "16",
               "--steps", "30", "--ensemble-cx", "0.05,0.2",
               "--ensemble-cy", "0.1,0.05",
               "--outdir", str(tmp_path), "--run-record", str(rec_path)])
    assert rc == 0
    rec = json.loads(rec_path.read_text())
    assert rec["members"] == [{"cx": 0.05, "cy": 0.1},
                              {"cx": 0.2, "cy": 0.05}]
    assert rec["summary"]["members"] == 2
    for i, (cx, cy) in enumerate([(0.05, 0.1), (0.2, 0.05)]):
        got = read_grid_text(tmp_path / f"final_m{i}.dat", "rowmajor")
        want = np.asarray(run_ensemble(12, 16, 30, [cx], [cy]))[0]
        np.testing.assert_allclose(got, want, atol=0.05)  # %6.1f res


def test_cli_ensemble_sharded_run(tmp_path):
    """Distributed mode: members shard over the 8 virtual devices."""
    import json
    from heat2d_tpu.cli import main

    rec_path = tmp_path / "rec.json"
    rc = main(["--mode", "dist2d", "--nxprob", "8", "--nyprob", "16",
               "--steps", "10", "--ensemble-cx", "0.1,0.1,0.2",
               "--ensemble-cy", "0.1,0.2,0.1", "--dat-layout", "none",
               "--outdir", str(tmp_path), "--run-record", str(rec_path)])
    assert rc == 0
    rec = json.loads(rec_path.read_text())
    assert rec["summary"]["members"] == 3


def test_cli_ensemble_validation(tmp_path, capsys):
    from heat2d_tpu.cli import main
    rc = main(["--mode", "serial", "--ensemble-cx", "0.1,0.2",
               "--ensemble-cy", "0.1", "--outdir", str(tmp_path)])
    assert rc == 1
    assert "equal-length" in capsys.readouterr().err


def test_cli_ensemble_rejects_spatial_grid(tmp_path, capsys):
    """--gridx/--gridy would be silently reinterpreted (members shard
    over a batch axis, never space) — must be refused, not ignored."""
    from heat2d_tpu.cli import main
    rc = main(["--mode", "dist2d", "--nxprob", "8", "--nyprob", "16",
               "--gridx", "4", "--gridy", "2",
               "--ensemble-cx", "0.1,0.2", "--ensemble-cy", "0.1,0.1",
               "--outdir", str(tmp_path)])
    assert rc == 1
    err = capsys.readouterr().err
    assert "batch axis" in err and "--gridx" in err
