"""Load-generation + capacity-model subsystem (heat2d_tpu/load;
ISSUE 11) — seeded-generator determinism, analytic shape checks on
the zipf/burst/diurnal processes, trace replay, open-loop fidelity,
capacity fitting, the baseline gate, and the satellite surfaces
(trace_cli --stats, the controllable watchdog clock, record kind)."""

from __future__ import annotations

import json
import statistics
import threading
import time
from concurrent.futures import Future

import pytest

from heat2d_tpu.load import capacity as cap_mod
from heat2d_tpu.load import gate as gate_mod
from heat2d_tpu.load import replay as replay_mod
from heat2d_tpu.load import synth
from heat2d_tpu.load.runner import measure_point, run_schedule
from heat2d_tpu.load.schedule import Arrival, Schedule
from heat2d_tpu.obs import MetricsRegistry
from heat2d_tpu.serve.schema import Rejected, SolveRequest

SMOKE = synth.PROFILES["smoke"]


# --------------------------------------------------------------------- #
# schedules
# --------------------------------------------------------------------- #

def _solve_arrival(t, steps=3, tenant="default"):
    return Arrival(t=t, kind="solve",
                   spec={"nx": 12, "ny": 12, "steps": steps,
                         "cx": 0.1, "cy": 0.1, "method": "jnp"},
                   tenant=tenant)


def test_schedule_sorts_scales_and_roundtrips(tmp_path):
    sched = Schedule([_solve_arrival(2.0), _solve_arrival(0.0),
                      _solve_arrival(1.0)], meta={"source": "test"})
    assert [a.t for a in sched] == [0.0, 1.0, 2.0]
    assert sched.duration() == 2.0
    assert sched.inter_arrivals() == [1.0, 1.0]
    fast = sched.scaled(2.0)
    assert [a.t for a in fast] == [0.0, 0.5, 1.0]
    assert fast.offered_rps() == pytest.approx(
        2 * sched.offered_rps())
    path = tmp_path / "sched.jsonl"
    sched.to_jsonl(str(path))
    back = Schedule.from_jsonl(str(path))
    assert back.fingerprint() == sched.fingerprint()
    assert back.meta == {"source": "test"}
    with pytest.raises(ValueError):
        sched.scaled(0.0)


def test_schedule_signatures_and_summary():
    sched = Schedule([_solve_arrival(0.0, steps=3),
                      _solve_arrival(0.5, steps=3),
                      _solve_arrival(1.0, steps=4, tenant="batch")])
    sigs = sched.signatures()
    assert len(sigs) == 2 and sum(sigs.values()) == 3
    s = sched.summary()
    assert s["arrivals"] == 3
    assert s["tenants"] == {"default": 2, "batch": 1}
    assert s["kinds"] == {"solve": 3}


# --------------------------------------------------------------------- #
# seeded synthesis: determinism + analytic shapes
# --------------------------------------------------------------------- #

def test_same_seed_is_bit_identical():
    a = synth.synthesize(SMOKE, 25.0, 3.0, seed=11)
    b = synth.synthesize(SMOKE, 25.0, 3.0, seed=11)
    assert a.fingerprint() == b.fingerprint()
    assert [(x.t, x.kind, x.tenant, x.spec) for x in a] \
        == [(x.t, x.kind, x.tenant, x.spec) for x in b]


def test_different_seed_differs():
    a = synth.synthesize(SMOKE, 25.0, 3.0, seed=11)
    b = synth.synthesize(SMOKE, 25.0, 3.0, seed=12)
    assert a.fingerprint() != b.fingerprint()


def test_zipf_weights_analytic():
    w = synth.zipf_weights(4, 1.0)
    h = 1 + 0.5 + 1 / 3 + 0.25
    assert w == pytest.approx([1 / h, 0.5 / h, (1 / 3) / h,
                               0.25 / h])
    assert synth.zipf_weights(5, 0.0) == pytest.approx([0.2] * 5)
    with pytest.raises(ValueError):
        synth.zipf_weights(0, 1.0)


def test_zipf_skew_matches_analytic_weights():
    prof = synth.MixProfile(name="z", signatures=6, zipf_s=1.2)
    sched = synth.synthesize(prof, 300.0, 10.0, seed=3)
    counts = [0] * prof.signatures
    for a in sched:
        counts[a.spec["steps"] - prof.steps] += 1
    n = sum(counts)
    assert n > 1500
    weights = synth.zipf_weights(prof.signatures, prof.zipf_s)
    # the hot head carries its analytic share (within sampling noise)
    assert counts[0] / n == pytest.approx(weights[0], abs=0.05)
    # and rank order holds where the analytic gap is meaningful
    assert counts[0] > counts[2] > counts[5]


def test_burst_modulation_shapes_the_process():
    """MMPP bursts: the realized rate exceeds the base rate by about
    the duty-cycle-weighted factor, and inter-arrivals are burstier
    than Poisson (CV > 1)."""
    prof = synth.MixProfile(name="b", burst_factor=4.0,
                            burst_on_s=1.5, burst_off_s=4.5)
    rate, duration = 60.0, 60.0
    sched = synth.synthesize(prof, rate, duration, seed=5)
    # expected multiplier: off-share*1 + on-share*4, on-share = 0.25
    mult = len(sched) / (rate * duration)
    assert 1.25 < mult < 2.4, mult
    gaps = sched.inter_arrivals()
    cv = statistics.pstdev(gaps) / statistics.fmean(gaps)
    assert cv > 1.15, cv
    # a plain Poisson process from the same machinery sits near CV=1
    plain = synth.synthesize(synth.PROFILES["uniform"], rate,
                             duration, seed=5)
    gaps_p = plain.inter_arrivals()
    cv_p = statistics.pstdev(gaps_p) / statistics.fmean(gaps_p)
    assert 0.8 < cv_p < 1.2, cv_p


def test_diurnal_modulation_shapes_the_process():
    """With period == duration, the sinusoid boosts the first half
    and suppresses the second: analytic ratio (1 + 2a/pi)/(1 - 2a/pi)
    ~= 3.1 at a=0.8."""
    prof = synth.MixProfile(name="d", diurnal_amplitude=0.8,
                            diurnal_period_s=40.0)
    sched = synth.synthesize(prof, 80.0, 40.0, seed=9)
    first = sum(1 for a in sched if a.t < 20.0)
    second = len(sched) - first
    assert second > 0 and first / second > 2.0, (first, second)


def test_tenant_mix_and_quotas():
    prof = synth.PROFILES["multitenant"]
    sched = synth.synthesize(prof, 150.0, 8.0, seed=2)
    counts: dict = {}
    for a in sched:
        counts[a.tenant] = counts.get(a.tenant, 0) + 1
    n = sum(counts.values())
    assert counts["interactive"] / n == pytest.approx(0.7, abs=0.08)
    quotas = prof.quotas(100)
    assert quotas["interactive"].priority == 0
    assert quotas["batch"].priority == 1
    assert quotas["interactive"].max_inflight == 70
    assert quotas["batch"].max_inflight == 30


def test_inverse_heavy_tail():
    prof = synth.PROFILES["inverse_heavy"]
    sched = synth.synthesize(prof, 150.0, 8.0, seed=4)
    inv = [a for a in sched if a.kind == "inverse"]
    n = len(sched)
    assert 0.08 < len(inv) / n < 0.35, len(inv) / n
    iters = [a.spec["iterations"] for a in inv]
    assert all(prof.inverse_iters_min <= i <= prof.inverse_iters_cap
               for i in iters)
    assert len(set(iters)) > 1          # a tail, not a constant
    # the synthesized spec is a valid serving request
    req = inv[0].build_request()
    assert req.request_kind == "inverse"
    assert req.signature()[0] == "inverse"


def test_profile_validation():
    with pytest.raises(ValueError):
        synth.MixProfile(name="x", burst_factor=0.5)
    with pytest.raises(ValueError):
        synth.MixProfile(name="x", diurnal_amplitude=1.0)
    with pytest.raises(ValueError):
        synth.MixProfile(name="x", inverse_fraction=1.5)
    with pytest.raises(ValueError):
        synth.synthesize(SMOKE, -1.0, 5.0)
    with pytest.raises(ValueError):
        synth.synthesize(SMOKE, 5.0, 0.0)


# --------------------------------------------------------------------- #
# trace replay
# --------------------------------------------------------------------- #

def test_spec_from_signature_roundtrips():
    import random
    rng = random.Random(0)
    for req in (SolveRequest(nx=24, ny=16, steps=7, method="pallas"),
                SolveRequest(nx=12, ny=12, steps=9, convergence=True,
                             interval=5, sensitivity=0.2)):
        kind, spec = replay_mod.spec_from_signature(req.signature(),
                                                    rng)
        assert kind == "solve"
        assert SolveRequest.from_dict(spec).signature() \
            == req.signature()

    from heat2d_tpu.diff.serving import InverseRequest
    inv = InverseRequest(nx=8, ny=8, steps=4, obs_indices=(9, 12),
                         obs_values=(1.0, 2.0), iterations=16)
    kind, spec = replay_mod.spec_from_signature(inv.signature(), rng)
    assert kind == "inverse"
    assert InverseRequest.from_dict(spec).signature() \
        == inv.signature()

    with pytest.raises(ValueError):
        replay_mod.spec_from_signature(("bogus",), rng)


def _write_spans(path, recs):
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")


def test_schedule_from_trace_dir(tmp_path):
    sig = str(SolveRequest(nx=12, ny=12, steps=3,
                           method="jnp").signature())

    def root(tid, t0, tenant="default", name="fleet.request"):
        return {"event": "span", "service": "router", "pid": 1,
                "trace_id": tid, "span_id": "s" + tid,
                "parent_id": None, "name": name, "kind": "request",
                "t0": t0, "t1": t0 + 0.1,
                "attrs": {"signature": sig, "tenant": tenant}}

    recs = [root("a", 100.0), root("b", 100.5, tenant="batch"),
            root("c", 101.75),
            # a worker-side serve.request nested under a wire span of
            # trace "a" must NOT count as a second arrival
            {"event": "span", "service": "worker0", "pid": 2,
             "trace_id": "a", "span_id": "w1", "parent_id": "sa",
             "name": "serve.request", "kind": "request",
             "t0": 100.01, "t1": 100.09,
             "attrs": {"signature": sig}},
            # a cli.run root has no signature: skipped, not an error
            {"event": "span", "service": "cli", "pid": 3,
             "trace_id": "d", "span_id": "s4", "parent_id": None,
             "name": "cli.run", "kind": "request",
             "t0": 99.0, "t1": 102.0, "attrs": {}}]
    _write_spans(tmp_path / "spans-router-1.jsonl", recs)

    sched = replay_mod.schedule_from_trace_dir(str(tmp_path), seed=0)
    assert len(sched) == 3
    assert [a.t for a in sched] == pytest.approx([0.0, 0.5, 1.75])
    assert [a.tenant for a in sched] == ["default", "batch",
                                         "default"]
    req = sched.arrivals[0].build_request()
    assert str(req.signature()) == sig
    assert sched.meta["source"] == "replay"
    # determinism: same dir + seed -> same payload synthesis
    again = replay_mod.schedule_from_trace_dir(str(tmp_path), seed=0)
    assert again.fingerprint() == sched.fingerprint()


def test_schedule_from_trace_dir_no_roots(tmp_path):
    _write_spans(tmp_path / "spans-x-1.jsonl", [])
    with pytest.raises(ValueError, match="no request root spans"):
        replay_mod.schedule_from_trace_dir(str(tmp_path))


# --------------------------------------------------------------------- #
# open-loop runner (fake targets: no jax, no sleeping servers)
# --------------------------------------------------------------------- #

class _FakeTarget:
    """Answers every submit per ``script(i)`` -> None (complete) or an
    exception; optional service delay on a background thread."""

    units = 2

    def __init__(self, script=None, delay=0.0):
        self.script = script or (lambda i: None)
        self.delay = delay
        self.submitted = []

    def submit(self, req, tenant, timeout):
        i = len(self.submitted)
        self.submitted.append((req, tenant))
        fut: Future = Future()
        exc = self.script(i)

        def finish():
            if exc is None:
                fut.set_result(None)
            else:
                fut.set_exception(exc)

        if self.delay:
            threading.Timer(self.delay, finish).start()
        else:
            finish()
        return fut

    def close(self):
        pass


def _fast_schedule(n=40, gap=0.01):
    return Schedule([_solve_arrival(i * gap) for i in range(n)])


def test_run_schedule_measures_and_keeps_fidelity():
    reg = MetricsRegistry()
    target = _FakeTarget()
    row = run_schedule(_fast_schedule(40), target, reg,
                       warmup=False)
    assert row["arrivals"] == row["answered"] == 40
    assert row["completed"] == 40 and row["shed"] == 0
    assert row["achieved_rps"] > 0
    assert row["fidelity"]["p99_skew_s"] < 0.25
    snap = reg.snapshot()
    assert snap["counters"][
        "load_requests_total{outcome=completed}"] == 40
    assert snap["histograms"]["load_submit_skew_s"]["count"] == 40
    assert snap["histograms"]["load_e2e_latency_s"]["count"] == 40


def test_run_schedule_classifies_shed_vs_failures():
    def script(i):
        if i % 4 == 1:
            return Rejected("queue_full", "full")
        if i % 4 == 2:
            return Rejected("timeout", "late")
        if i % 4 == 3:
            return RuntimeError("boom")
        return None

    reg = MetricsRegistry()
    row = run_schedule(_fast_schedule(40), _FakeTarget(script), reg,
                       warmup=False)
    assert row["completed"] == 10
    assert row["outcomes"]["rejected_queue_full"] == 10
    assert row["outcomes"]["rejected_timeout"] == 10
    assert row["outcomes"]["error"] == 10
    # only admission shedding counts as shed
    assert row["shed"] == 10
    assert row["shed_rate"] == pytest.approx(0.25)


def test_run_schedule_warmup_covers_each_signature():
    sched = Schedule([_solve_arrival(0.0, steps=3),
                      _solve_arrival(0.01, steps=4),
                      _solve_arrival(0.02, steps=3)])
    target = _FakeTarget()
    target.max_batch = 4
    run_schedule(sched, target, None, warmup=True)
    # 2 distinct signatures x the capacity ladder (1+2+4 bursts)
    # + 3 measured arrivals
    assert len(target.submitted) == 2 * 7 + 3
    # ladder members must not coalesce: distinct content hashes
    warm = [r for r, _t in target.submitted[:14]]
    assert len({r.content_hash() for r in warm}) == 14


def test_measure_point_evaluates_slo():
    from heat2d_tpu.obs.slo import SLOPolicy
    row = measure_point(_fast_schedule(20), _FakeTarget(),
                        warmup=False,
                        slo_policy=SLOPolicy(latency_p99_s=5.0))
    assert row["slo_ok"] is True
    assert row["slo"] and all(r["ok"] for r in row["slo"])

    slow = measure_point(
        _fast_schedule(20), _FakeTarget(delay=0.06), warmup=False,
        slo_policy=SLOPolicy(latency_p99_s=0.005))
    assert slow["slo_ok"] is False
    assert any(not r["latency_ok"] for r in slow["slo"])


# --------------------------------------------------------------------- #
# capacity model
# --------------------------------------------------------------------- #

def _row(offered, achieved, shed=0.0, slo_ok=True, p99=0.01):
    return {"offered_rps": offered, "achieved_rps": achieved,
            "shed_rate": shed, "slo_ok": slo_ok,
            "latency": {"p99": p99, "p50": p99 / 2}}


def test_fit_capacity_finds_the_knee():
    rows = [_row(4, 4), _row(8, 8), _row(16, 12, slo_ok=False),
            _row(32, 12, shed=0.3)]
    fit = cap_mod.fit_capacity(rows, units=2)
    assert fit["max_sustainable_rps"] == 8
    assert fit["per_unit_rps"] == 4
    assert fit["saturated"] is True
    assert fit["qualifying_points"] == 2
    assert cap_mod.units_for(fit, 10) == 3
    assert cap_mod.sustainable_at(fit, 4) == 16


def test_fit_capacity_unsaturated_is_flagged():
    fit = cap_mod.fit_capacity([_row(4, 4), _row(8, 7.5)], units=1)
    assert fit["max_sustainable_rps"] == 7.5
    assert fit["saturated"] is False


def test_fit_capacity_nothing_qualifies():
    fit = cap_mod.fit_capacity([_row(8, 2), _row(16, 2)], units=2)
    assert fit["max_sustainable_rps"] == 0.0
    assert cap_mod.units_for(fit, 10) is None
    with pytest.raises(ValueError):
        cap_mod.fit_capacity([], units=0)


# --------------------------------------------------------------------- #
# the gate
# --------------------------------------------------------------------- #

def test_gate_passes_healthy_and_catches_regressions():
    rows = [_row(4, 4, p99=0.02), _row(8, 8, p99=0.04)]
    fit = cap_mod.fit_capacity(rows, units=2)
    base = gate_mod.build_baseline(rows, fit, meta={"profile": "t"})
    assert base["schema"] == gate_mod.BASELINE_SCHEMA
    assert gate_mod.compare(rows, fit, base) == []

    # seeded regression: latency x20, throughput halved, shedding up
    bad = [_row(4, 1.8, p99=0.6, shed=0.3),
           _row(8, 3.5, p99=0.9, shed=0.4, slo_ok=False)]
    bad_fit = cap_mod.fit_capacity(bad, units=2)
    fails = gate_mod.compare(bad, bad_fit, base)
    text = "\n".join(fails)
    assert "throughput regression" in text
    assert "latency regression" in text
    assert "shed-rate regression" in text
    assert "capacity regression" in text


def test_gate_refuses_unknown_schema_and_unmatched_points():
    rows = [_row(4, 4)]
    fit = cap_mod.fit_capacity(rows, units=1)
    assert gate_mod.compare(rows, fit, {"schema": "nope"})
    base = gate_mod.build_baseline([_row(40, 40)],
                                   cap_mod.fit_capacity(
                                       [_row(40, 40)], units=1))
    fails = gate_mod.compare(rows, fit, base)
    assert any("no baseline partner" in f for f in fails)


def test_gate_rejects_a_shrunken_sweep():
    """A measured sweep that silently drops a baseline point must
    fail: shrinking the sweep is not a way to pass the gate."""
    full = [_row(4, 4), _row(8, 8)]
    base = gate_mod.build_baseline(
        full, cap_mod.fit_capacity(full, units=1))
    shrunk = [_row(4, 4)]
    fails = gate_mod.compare(shrunk,
                             cap_mod.fit_capacity(shrunk, units=1),
                             base)
    assert any("never measured" in f for f in fails)


# --------------------------------------------------------------------- #
# CLI end to end (in-process serve target) + the kind="load" record
# --------------------------------------------------------------------- #

def _read_record(path):
    recs = [json.loads(line) for line in open(path)]
    return [r for r in recs if r.get("event") == "run_record"][0]


def test_cli_selftest_writes_load_record(tmp_path):
    from heat2d_tpu.load import cli
    from heat2d_tpu.obs.record import RECORD_KINDS

    assert "load" in RECORD_KINDS
    out = tmp_path / "load.jsonl"
    rc = cli.main(["--selftest", "--metrics-out", str(out)])
    assert rc == 0
    rec = _read_record(out)
    assert rec["kind"] == "load"
    assert rec["capacity"]["model"] == cap_mod.CAPACITY_MODEL
    assert rec["surface"] and rec["surface"][0]["completed"] >= 1
    assert rec["failures"] == []


def test_cli_gate_roundtrip_catches_seeded_regression(tmp_path):
    """The acceptance loop on the serve target: measure a healthy
    baseline, gate a healthy re-run (pass), then a chaos-slowed run
    (fail) — the CI load-gate job's fleet-flavored logic in-process."""
    from heat2d_tpu.load import cli

    base = tmp_path / "base.json"
    args = ["--profile", "smoke", "--rate", "12", "--duration", "2",
            "--seed", "5", "--target", "serve", "--slo-p99", "5"]
    rc = cli.main(args + ["--write-baseline", str(base)])
    assert rc == 0 and base.exists()
    doc = json.loads(base.read_text())
    assert doc["schema"] == gate_mod.BASELINE_SCHEMA

    out = tmp_path / "healthy.jsonl"
    rc = cli.main(args + ["--gate", "--baseline", str(base),
                          "--metrics-out", str(out)])
    assert rc == 0
    rec = _read_record(out)
    assert rec["gate"]["passed"] is True

    out2 = tmp_path / "slow.jsonl"
    rc = cli.main(args + ["--gate", "--baseline", str(base),
                          "--chaos-slow", "1.0",
                          "--metrics-out", str(out2)])
    assert rc == 1
    rec2 = _read_record(out2)
    assert rec2["gate"]["passed"] is False
    assert rec2["gate"]["failures"]


def test_cli_replay_fidelity_against_live_server(tmp_path):
    """Closed loop in miniature: record a traced serve run, replay it
    through the CLI against a fresh server, and hold the fidelity
    bound."""
    from heat2d_tpu.load import cli
    from heat2d_tpu.obs import tracing

    trace_dir = tmp_path / "tr"
    tracing.install(tracing.Tracer(str(trace_dir), service="serve"))
    try:
        from heat2d_tpu.serve.server import SolveServer
        with SolveServer(max_delay=0.01, registry=None) as srv:
            futs = []
            for i in range(6):
                time.sleep(0.03)
                futs.append(srv.submit(SolveRequest(
                    nx=12, ny=12, steps=3, cx=0.05 + 0.01 * i,
                    method="jnp")))
            for f in futs:
                f.result(60)
    finally:
        tracing.uninstall()

    out = tmp_path / "replay.jsonl"
    rc = cli.main(["--replay", str(trace_dir), "--target", "serve",
                   "--max-skew", "0.5",
                   "--metrics-out", str(out)])
    assert rc == 0
    rec = _read_record(out)
    assert rec["source"] == "replay"
    row = rec["surface"][0]
    assert row["arrivals"] == 6
    assert row["completed"] == 6
    assert row["fidelity"]["p99_skew_s"] <= 0.5
    # the replayed schedule preserved the recorded gaps (~30ms): the
    # offered rate is production's, not the replayer's convenience
    assert 10 < row["offered_rps"] < 400


# --------------------------------------------------------------------- #
# satellites: trace_cli --stats, controllable watchdog clock
# --------------------------------------------------------------------- #

def test_trace_cli_segment_stats(tmp_path, capsys):
    from heat2d_tpu.obs import trace_cli

    sig = str(SolveRequest(nx=12, ny=12, steps=3,
                           method="jnp").signature())
    recs = []
    for i, tid in enumerate(("a", "b")):
        t0 = 100.0 + i
        recs.append({"event": "span", "service": "s", "pid": 1,
                     "trace_id": tid, "span_id": "r" + tid,
                     "parent_id": None, "name": "serve.request",
                     "kind": "request", "t0": t0, "t1": t0 + 0.5,
                     "attrs": {"signature": sig}})
        recs.append({"event": "span", "service": "s", "pid": 1,
                     "trace_id": tid, "span_id": "q" + tid,
                     "parent_id": "r" + tid, "name": "serve.queue",
                     "kind": "queue", "t0": t0, "t1": t0 + 0.2,
                     "attrs": {}})
    _write_spans(tmp_path / "spans-s-1.jsonl", recs)

    report = trace_cli.merge_report(str(tmp_path))
    stats = trace_cli.segment_stats(report)
    assert stats["queue"]["count"] == 2
    assert stats["queue"]["p50"] == pytest.approx(0.2, abs=1e-6)
    assert stats["total"]["mean"] == pytest.approx(0.5, abs=1e-6)
    # the summary rows now carry the replay join keys
    assert report["traces"][0]["signature"] == sig

    assert trace_cli.main([str(tmp_path), "--stats"]) == 0
    out = capsys.readouterr().out
    assert "Segment statistics" in out and "| queue |" in out
    assert trace_cli.main([str(tmp_path), "--stats",
                           "--format", "json"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["segments"]["queue"]["count"] == 2


def test_watchdog_controllable_clock():
    from heat2d_tpu.resil.retry import Watchdog

    class Clock:
        def __init__(self):
            self.t = 0.0

        def __call__(self):
            return self.t

    fired = threading.Event()
    clock = Clock()
    with Watchdog(0.5, fired.set, clock=clock) as wd:
        time.sleep(0.05)            # real time passes...
        assert not wd.fired         # ...the modeled deadline doesn't
        clock.t = 1.0
        assert fired.wait(2.0)
        assert wd.fired
    # cancelled watchdogs stay quiet after exit
    fired2 = threading.Event()
    clock2 = Clock()
    with Watchdog(0.5, fired2.set, clock=clock2):
        pass
    clock2.t = 5.0
    time.sleep(0.05)
    assert not fired2.is_set()
