"""Benchmark sweep harness units (SURVEY.md C23)."""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from benchmarks import sweep  # noqa: E402


def test_mesh_shapes():
    assert sweep.mesh_shapes(8) == [(2, 4), (8, 1)]
    assert sweep.mesh_shapes(1) == [(1, 1)]
    assert sweep.mesh_shapes(16) == [(4, 4), (16, 1)]


def test_run_point_has_reference_columns():
    rec = sweep.run_point("serial", 80, 64, 100, max_hi=1000)
    assert rec["steps"] >= 100      # adaptive two-point may grow hi
    assert rec["mcells_per_s"] > 0
    assert rec["method"].startswith(("two-point", "end-to-end"))
    if rec["method"] == "two-point":
        # 80x64 compares against a published Table 1 cell via marginal
        # step time x 100.
        assert rec["ref_serial_100step_s"] == 2.53e-2
        assert rec["speedup_vs_ref_serial"] > 0
        assert rec["step_time_s"] > 0


def test_sweep_quick_end_to_end(tmp_path):
    rc = sweep.main(["--suite", "chip", "--quick", "--steps", "10",
                     "--outdir", str(tmp_path)])
    assert rc == 0
    jsonl = tmp_path / "sweep_chip_quick.jsonl"
    recs = [json.loads(line) for line in jsonl.read_text().splitlines()]
    assert len(recs) == 4  # 2 quick sizes x (serial, pallas)
    assert (tmp_path / "sweep_chip_quick.md").read_text().startswith("#")


def test_suite_mesh_respects_divisibility():
    pts = list(sweep.suite_mesh(10, quick=False, n_devices=8))
    for pt in pts:
        assert pt["nx"] % pt["gridx"] == 0
        assert pt["ny"] % pt["gridy"] == 0
    assert any(pt["mode"] == "hybrid" for pt in pts)
    assert any(pt["mode"] == "dist1d" for pt in pts)
    assert any(pt["mode"] == "dist2d" for pt in pts)


def test_scaling_suite_and_columns():
    pts = list(sweep.suite_scaling(10, quick=True, n_devices=8))
    assert [p["gridx"] * p["gridy"] for p in pts] == [1, 2, 4, 8]
    recs = [{"mesh": f"{p['gridx']}x{p['gridy']}", "elapsed_s": 1.0 / (i + 1)}
            for i, p in enumerate(pts)]
    sweep.add_scaling_columns(recs)
    assert recs[0]["speedup_vs_1dev"] == 1.0
    assert recs[3]["speedup_vs_1dev"] == 4.0
    assert recs[3]["efficiency"] == 0.5
