"""Benchmark sweep harness units (SURVEY.md C23)."""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from benchmarks import sweep  # noqa: E402


def test_mesh_shapes():
    assert sweep.mesh_shapes(8) == [(2, 4), (8, 1)]
    assert sweep.mesh_shapes(1) == [(1, 1)]
    assert sweep.mesh_shapes(16) == [(4, 4), (16, 1)]


def test_run_point_has_reference_columns():
    rec = sweep.run_point("serial", 80, 64, 100, max_hi=1000)
    assert rec["steps"] >= 100      # adaptive two-point may grow hi
    assert rec["mcells_per_s"] > 0
    assert rec["method"].startswith(("two-point", "end-to-end"))
    if rec["method"] == "two-point":
        # 80x64 compares against a published Table 1 cell via marginal
        # step time x 100.
        assert rec["ref_serial_100step_s"] == 2.53e-2
        assert rec["speedup_vs_ref_serial"] > 0
        assert rec["step_time_s"] > 0


def test_sweep_quick_end_to_end(tmp_path):
    rc = sweep.main(["--suite", "chip", "--quick", "--steps", "10",
                     "--outdir", str(tmp_path)])
    assert rc == 0
    jsonl = tmp_path / "sweep_chip_quick.jsonl"
    recs = [json.loads(line) for line in jsonl.read_text().splitlines()]
    assert len(recs) == 4  # 2 quick sizes x (serial, pallas)
    assert (tmp_path / "sweep_chip_quick.md").read_text().startswith("#")


def test_suite_mesh_respects_divisibility():
    pts = list(sweep.suite_mesh(10, quick=False, n_devices=8))
    for pt in pts:
        assert pt["nx"] % pt["gridx"] == 0
        assert pt["ny"] % pt["gridy"] == 0
    assert any(pt["mode"] == "hybrid" for pt in pts)
    assert any(pt["mode"] == "dist1d" for pt in pts)
    assert any(pt["mode"] == "dist2d" for pt in pts)


class _FakeTimer:
    """Scripted timed_run: elapsed = overhead + marginal*n, plus scripted
    per-call noise spikes keyed by (n, call_index)."""

    def __init__(self, marginal, overhead=0.2, spikes=None):
        self.marginal = marginal
        self.overhead = overhead
        self.spikes = dict(spikes or {})
        self.calls = {}

    def __call__(self, n):
        i = self.calls.get(n, 0)
        self.calls[n] = i + 1
        t = self.overhead + self.marginal * n + self.spikes.get((n, i), 0.0)
        import types
        return types.SimpleNamespace(elapsed=t)


def test_two_point_rejects_lucky_jitter():
    """The round-2 bogus-row scenario: a jitter spike at the first hi
    clears the absolute floor and would have committed a ~600x-inflated
    marginal; the confirmation rule must ride past it to the true one."""
    # True marginal 1.2e-6 s/step; BOTH hi=100 runs spike (min() can't
    # save us), faking dt=0.06 > the 0.05 floor -> bogus cand 7.5e-4.
    fake = _FakeTimer(1.2e-6, spikes={(100, 0): 0.06, (100, 1): 0.062})
    st, hi, _ = sweep.two_point_estimate(fake, lo=20, hi0=100,
                                         max_hi=100_000)
    assert st is not None
    assert abs(st - 1.2e-6) / 1.2e-6 < 0.2     # the true marginal
    assert hi == 100_000                        # rode past the spike


def test_two_point_confirms_across_decades():
    fake = _FakeTimer(1e-4)
    st, hi, _ = sweep.two_point_estimate(fake, lo=20, hi0=100,
                                         max_hi=100_000)
    # First candidate at hi=1000 (dt=0.098); confirmed at hi=10000.
    assert abs(st - 1e-4) / 1e-4 < 0.05
    assert hi == 10_000


def test_two_point_noise_fallback():
    # Marginal so small no window ever clears the floor -> honest None.
    fake = _FakeTimer(1e-9)
    st, hi, _ = sweep.two_point_estimate(fake, lo=20, hi0=100,
                                         max_hi=100_000)
    assert st is None
    assert hi == 100_000


def test_suspect_rows_flags_committed_bogus_row():
    """The exact round-2 committed rows: pallas 320x256 at 122x slower
    than serial must be flagged (by BOTH rules); honest rows must not."""
    recs = [
        {"mode": "serial", "grid": "320x256", "step_time_s": 2.768e-6},
        {"mode": "pallas", "grid": "320x256", "step_time_s": 3.38677e-4},
        {"mode": "serial", "grid": "1280x1024", "step_time_s": 3.5101e-5},
        {"mode": "pallas", "grid": "1280x1024", "step_time_s": 9.94e-6},
        {"mode": "pallas", "grid": "80x64",
         "method": "end-to-end (two-point within noise)"},  # no step_time
    ]
    assert sweep.suspect_rows(recs) == [1]


def test_suspect_rows_guards_largest_large_grid():
    """The cross-grid per-cell plausibility rule (review r5): the
    sweep's LARGEST grid has no bigger-grid monotonicity partner and
    (at 8192^2) no serial anchor, so a bogus two-point row there was
    structurally unguardable. Healthy large-row spreads stay within
    AGREE_FACTOR; a bogus row flags the whole (mode, mesh) group for
    re-measurement (two rows cannot say which is wrong)."""
    recs = [
        {"mode": "pallas", "grid": "4096x4096", "step_time_s": 7.6e-5},
        {"mode": "pallas", "grid": "8192x8192", "step_time_s": 3.3e-4},
    ]
    assert sweep.suspect_rows(recs) == []          # healthy pair (1.09x)
    recs[1]["step_time_s"] = 3.3e-3                # 10x-off largest row
    assert sweep.suspect_rows(recs) == [0, 1]
    # Small grids are exempt (dispatch-dominated, per-cell rates wild).
    recs = [
        {"mode": "pallas", "grid": "80x64", "step_time_s": 2.0e-6},
        {"mode": "pallas", "grid": "640x512", "step_time_s": 2.4e-6},
    ]
    assert sweep.suspect_rows(recs) == []
    # Only the kernel-backed STREAMING modes (pallas/hybrid) are held to
    # the flat-per-cell premise: serial's whole-grid XLA loop may
    # legitimately slow per-cell as grids outgrow cache, and a genuine
    # serial row must not re-measure the whole group (advisor r5).
    recs = [
        {"mode": "serial", "grid": "4096x4096", "step_time_s": 7.6e-5},
        {"mode": "serial", "grid": "8192x8192", "step_time_s": 3.3e-3},
    ]
    assert sweep.suspect_rows(recs) == []


def test_suspect_rows_monotonicity():
    # A smaller grid slower per step than a larger one (same mode), but
    # not >10x serial: caught by the monotonicity rule alone.
    recs = [
        {"mode": "pallas", "grid": "640x512", "step_time_s": 2e-5},
        {"mode": "pallas", "grid": "1280x1024", "step_time_s": 9.9e-6},
    ]
    assert sweep.suspect_rows(recs) == [0]
    # Monotone costs: clean.
    recs[0]["step_time_s"] = 5e-6
    assert sweep.suspect_rows(recs) == []
    # Latency-bound wobble within the estimator's own tolerance
    # (AGREE_FACTOR) must NOT trigger a re-measure: small grids are
    # dispatch-dominated and roughly flat in step time.
    recs = [
        {"mode": "serial", "grid": "80x64", "step_time_s": 2.0e-6},
        {"mode": "serial", "grid": "160x128", "step_time_s": 1.8e-6},
    ]
    assert sweep.suspect_rows(recs) == []
    # Different mesh shapes are never compared — their dispatch and
    # collective floors differ.
    recs = [
        {"mode": "dist2d", "grid": "640x512", "mesh": "8x1",
         "step_time_s": 2e-5},
        {"mode": "dist2d", "grid": "1280x1024", "mesh": "2x4",
         "step_time_s": 9.9e-6},
    ]
    assert sweep.suspect_rows(recs) == []


def test_redesign_payoff_pairs():
    recs = [
        {"mode": "dist1d", "grid": "2560x2048", "mesh": "8x1",
         "steps": 100, "step_time_s": 1.2e-2, "elapsed_s": 1.2},
        {"mode": "dist2d", "grid": "2560x2048", "mesh": "2x4",
         "steps": 100, "step_time_s": 0.4e-2, "elapsed_s": 0.4},
        {"mode": "dist2d", "grid": "2560x2048", "mesh": "8x1",
         "steps": 100, "step_time_s": 1.1e-2, "elapsed_s": 1.1},
    ]
    rows = sweep.redesign_payoff(recs)
    assert len(rows) == 1
    grid, ndev, m1, c1, m2, c2, ratio = rows[0]
    assert (grid, ndev, m1, m2) == ("2560x2048", 8, "8x1", "2x4")
    assert ratio == 3.0


def test_scaling_suite_and_columns():
    pts = list(sweep.suite_scaling(10, quick=True, n_devices=8))
    assert [p["gridx"] * p["gridy"] for p in pts] == [1, 2, 4, 8]
    recs = [{"mesh": f"{p['gridx']}x{p['gridy']}", "elapsed_s": 1.0 / (i + 1)}
            for i, p in enumerate(pts)]
    sweep.add_scaling_columns(recs)
    assert recs[0]["speedup_vs_1dev"] == 1.0
    assert recs[3]["speedup_vs_1dev"] == 4.0
    assert recs[3]["efficiency"] == 0.5


def test_conv_suite_marginal_pairs_and_overhead():
    """The conv suite emits (fixed, sensitivity=0) two-point pairs at
    the large grids; add_conv_overhead turns each into a % cost of the
    residual schedule (VERDICT r3 weak #3's missing measurement)."""
    pts = list(sweep.suite_conv(100, quick=False))
    pairs = [p for p in pts if p.get("sensitivity") == 0.0]
    fixed = [p for p in pts if not p.get("convergence")]
    # 1280x1024, 2560x2048 and the 4096^2 north star; serial, pallas
    # and hybrid (the D2R fused path).
    assert len(pairs) == 9 and len(fixed) == 9
    assert all(p["convergence"] for p in pairs)

    recs = [
        {"mode": "pallas", "grid": "2560x2048", "mesh": "1x1",
         "step_time_s": 2.0e-5},
        {"mode": "pallas", "grid": "2560x2048", "mesh": "1x1",
         "step_time_s": 2.2e-5, "convergence": True, "sensitivity": 0.0},
        # end-to-end conv row (no step time): untouched
        {"mode": "pallas", "grid": "80x64", "mesh": "1x1",
         "elapsed_s": 0.1, "convergence": True},
    ]
    sweep.add_conv_overhead(recs)
    assert recs[1]["conv_overhead_pct"] == 10.0
    assert "conv_overhead_pct" not in recs[0]
    assert "conv_overhead_pct" not in recs[2]


def test_sweep_iters_markdown_math():
    """Marginal column differences consecutive decades (fence
    cancelled); the spread line appears."""
    from benchmarks import sweep_iters

    rows = [{"steps": 10, "total_s": 0.2},       # fence-dominated
            {"steps": 100, "total_s": 0.29},
            {"steps": 1000, "total_s": 1.19}]
    # mimic measure()'s post-pass
    for i, r in enumerate(rows):
        r["per_step_s"] = r["total_s"] / r["steps"]
        r["x_vs_10it"] = r["total_s"] / rows[0]["total_s"]
        if i:
            p = rows[i - 1]
            r["marginal_s"] = ((r["total_s"] - p["total_s"])
                               / (r["steps"] - p["steps"]))
    assert abs(rows[1]["marginal_s"] - 1e-3) < 1e-12
    assert abs(rows[2]["marginal_s"] - 1e-3) < 1e-12
    key = {"mode": "pallas", "grid": "2560x2048", "platform": "test"}
    md = sweep_iters.section_markdown(rows, key)
    assert "fence-noise floor: 1.000x" in md
    assert "| 1000 |" in md
    # A window under the floor gets no marginal, and is labeled so.
    noisy = [{"steps": 10, "total_s": 0.2, "per_step_s": 0.02,
              "x_vs_10it": 1.0},
             {"steps": 100, "total_s": 0.21, "per_step_s": 0.0021,
              "x_vs_10it": 1.05, "marginal_noise": True}]
    md2 = sweep_iters.section_markdown(noisy, key)
    assert "(window < noise floor)" in md2
    # Sections merge by key; pre-round-5 keyless rows are dropped.
    for r in rows:
        r["key"] = key
    full = sweep_iters.render(rows)
    assert "## pallas 2560x2048 on test" in full
