"""End-to-end parity: the solver reproduces the reference's default
workload (10x10 grid, 100 steps — mpi_heat2Dn.c:29-31) against the
independent C-semantics oracle, and the .dat outputs round-trip."""

import numpy as np

from heat2d_tpu.config import HeatConfig
from heat2d_tpu.io import format_grid_rowmajor
from heat2d_tpu.models.solver import Heat2DSolver


def test_serial_f64_accum_bitwise_parity(oracle):
    cfg = HeatConfig(accum_dtype="float64")
    result = Heat2DSolver(cfg).run(timed=False)
    assert result.steps_done == 100
    np.testing.assert_array_equal(result.u, oracle.run(10, 10, 100))


def test_serial_f32_close_parity(oracle):
    cfg = HeatConfig()  # f32 fast path
    result = Heat2DSolver(cfg).run(timed=False)
    np.testing.assert_allclose(result.u, oracle.run(10, 10, 100),
                               rtol=1e-5, atol=1e-3)


def test_final_dat_text_parity(oracle):
    """The rowmajor final.dat text for the default workload matches the
    oracle's formatted dump byte-for-byte (f64-accum mode)."""
    cfg = HeatConfig(accum_dtype="float64")
    result = Heat2DSolver(cfg).run(timed=False)
    assert (format_grid_rowmajor(result.u)
            == format_grid_rowmajor(oracle.run(10, 10, 100)))


def test_mcells_metric():
    cfg = HeatConfig(nxprob=32, nyprob=32, steps=10)
    result = Heat2DSolver(cfg).run(timed=True)
    assert result.elapsed > 0
    assert result.mcells_per_s > 0
    rec = result.to_record()
    assert rec["steps_done"] == 10
    assert rec["config"]["nxprob"] == 32
