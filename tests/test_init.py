"""Initial-condition tests (reference inidat — mpi_heat2Dn.c:242-248,
grad1612_mpi_heat.c:163-168)."""

import numpy as np
import pytest

from heat2d_tpu.ops import inidat, inidat_block


@pytest.mark.parametrize("nx,ny", [(10, 10), (7, 13), (80, 64)])
def test_inidat_matches_closed_form(nx, ny, oracle):
    got = np.asarray(inidat(nx, ny))
    np.testing.assert_array_equal(got, oracle.inidat(nx, ny))
    assert got.dtype == np.float32


def test_inidat_edges_zero():
    u = np.asarray(inidat(16, 12))
    assert (u[0] == 0).all() and (u[-1] == 0).all()
    assert (u[:, 0] == 0).all() and (u[:, -1] == 0).all()
    # hot in the middle (readme.md:3-5)
    assert u.max() == u[8, 6] or u.max() > 0


def test_inidat_block_tiles_reassemble():
    """Per-shard local-coordinate init (grad1612_mpi_heat.c:163-168) must
    tile back into the global grid."""
    nx, ny, gx, gy = 12, 8, 3, 2
    bm, bn = nx // gx, ny // gy
    full = np.asarray(inidat(nx, ny))
    for i in range(gx):
        for j in range(gy):
            blk = np.asarray(inidat_block((bm, bn), nx, ny, i * bm, j * bn))
            np.testing.assert_array_equal(
                blk, full[i * bm:(i + 1) * bm, j * bn:(j + 1) * bn])
