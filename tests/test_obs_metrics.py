"""obs/metrics registry + obs/record unified schema + timing satellite
(warmup/compile time captured instead of discarded)."""

import json

import jax
import jax.numpy as jnp

from heat2d_tpu.obs.metrics import MetricsRegistry, get_registry
from heat2d_tpu.obs.record import (RECORD_SCHEMA, attach_context,
                                   build_record)
from heat2d_tpu.utils.timing import TimedCall, timed_call


def test_counters_gauges_histograms_series():
    r = MetricsRegistry()
    r.counter("steps_total", 10)
    r.counter("steps_total", 5)
    r.counter("steps_total", 1, mode="pallas")   # distinct labeled series
    r.gauge("vmem_budget_mib", 16)
    for v in (1.0, 2.0, 3.0, 4.0):
        r.observe("chunk_s", v)
    r.series("residual", 20, 0.5)
    r.series("residual", 40, 0.25)
    snap = r.snapshot()
    assert snap["counters"]["steps_total"] == 15
    assert snap["counters"]["steps_total{mode=pallas}"] == 1
    assert snap["gauges"]["vmem_budget_mib"] == 16
    h = snap["histograms"]["chunk_s"]
    assert h["count"] == 4 and h["min"] == 1.0 and h["max"] == 4.0
    assert h["mean"] == 2.5 and h["p50"] == 2.0
    assert snap["series"]["residual"] == [[20, 0.5], [40, 0.25]]


def test_timer_contextmanager():
    r = MetricsRegistry()
    with r.timer("span_s", phase="halo"):
        pass
    h = r.snapshot()["histograms"]["span_s{phase=halo}"]
    assert h["count"] == 1 and h["min"] >= 0.0


def test_jsonl_export_roundtrips(tmp_path):
    r = MetricsRegistry()
    r.event("run_start", mode="serial")
    r.counter("steps_total", 100)
    path = tmp_path / "metrics.jsonl"
    r.write_jsonl(str(path), extra_records=[{"event": "run_record",
                                             "steps_done": 100}])
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    kinds = [l["event"] for l in lines]
    assert kinds == ["run_start", "snapshot", "run_record"]
    assert lines[1]["counters"]["steps_total"] == 100


def test_prometheus_text():
    r = MetricsRegistry()
    r.counter("steps_total", 7, mode="serial")
    r.gauge("elapsed_s", 1.5)
    r.observe("chunk_s", 0.25)
    text = r.prometheus_text()
    assert "# TYPE steps_total counter" in text
    assert 'steps_total{mode="serial"} 7.0' in text
    assert "# TYPE elapsed_s gauge" in text
    assert "chunk_s_sum 0.25" in text
    assert "chunk_s_count 1" in text


def test_prometheus_label_values_escaped():
    r = MetricsRegistry()
    r.counter("io_errors", 1, path='grid "final"\\x\n.dat')
    line = [l for l in r.prometheus_text().splitlines()
            if l.startswith("io_errors{")][0]
    assert line == r'io_errors{path="grid \"final\"\\x\n.dat"} 1.0'


def test_aggregate_multihost_single_process():
    r = MetricsRegistry()
    r.gauge("elapsed_s", 2.0)
    r.counter("steps_total", 50)
    agg = r.aggregate_multihost()
    assert agg["elapsed_s"] == {"rank_max": 2.0, "rank_mean": 2.0,
                                "rank_min": 2.0}
    assert agg["steps_total"]["rank_max"] == 50


def test_default_registry_singleton():
    assert get_registry() is get_registry()


# -- unified run-record schema (obs/record.py) ------------------------- #

def test_build_record_envelope():
    rec = build_record("run", steps_done=10, elapsed_s=0.5,
                       warmup_s=1.25, extra={"custom": 1})
    assert rec["schema"] == RECORD_SCHEMA
    assert rec["kind"] == "run"
    assert rec["steps_done"] == 10 and rec["warmup_s"] == 1.25
    assert rec["custom"] == 1
    assert rec["jax_version"] == jax.__version__
    assert rec["device"]["n_devices"] >= 1
    assert rec["world"]["process_count"] >= 1
    assert "T" in rec["timestamp"]    # ISO 8601


def test_attach_context_keeps_existing_keys():
    rec = {"device": {"custom": True}, "value": 1.0}
    out = attach_context(rec, "bench")
    assert out is rec
    assert rec["device"] == {"custom": True}   # emitter's richer value wins
    assert rec["kind"] == "bench" and rec["schema"] == RECORD_SCHEMA


def test_all_emitters_share_the_envelope():
    """The three formerly-divergent shapes all carry the shared schema."""
    import sys, os
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    from heat2d_tpu.config import HeatConfig
    from heat2d_tpu.models.solver import Heat2DSolver

    b = bench.build_record(100.0, "two-point", 1.0, nx=640, ny=512,
                           steps=10)
    r = Heat2DSolver(HeatConfig(steps=2)).run(timed=False).to_record()
    assert b["schema"] == r["schema"] == RECORD_SCHEMA
    assert b["kind"] == "bench" and r["kind"] == "run"
    # bench driver-contract keys unchanged by the envelope
    assert b["unit"] == "Mcells/s" and "vs_baseline" in b


# -- timing satellite: warmup/compile time kept, 2-tuple compatible ---- #

def test_timed_call_returns_warmup_and_unpacks_as_pair():
    f = jax.jit(lambda x: x * 2.0)
    tc = timed_call(f, jnp.ones((8, 8)))
    assert isinstance(tc, TimedCall)
    out, elapsed = tc                    # existing call-site contract
    assert out.shape == (8, 8) and elapsed > 0
    assert tc.out is tc[0] and tc.elapsed == tc[1]
    assert tc.warmup_s is not None and tc.warmup_s > 0


def test_timed_call_no_warmup_reports_none():
    f = jax.jit(lambda x: x + 1.0)
    x = jnp.ones((4, 4))
    jax.block_until_ready(f(x))
    tc = timed_call(f, x, warmup=False)
    assert tc.warmup_s is None


def test_run_result_surfaces_warmup():
    from heat2d_tpu.config import HeatConfig
    from heat2d_tpu.models.solver import Heat2DSolver

    result = Heat2DSolver(HeatConfig(nxprob=16, nyprob=16, steps=5)).run(
        timed=True)
    assert result.warmup_s is not None and result.warmup_s > 0
    assert result.to_record()["warmup_s"] == result.warmup_s
