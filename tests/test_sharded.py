"""Distributed-mode tests on 8 virtual CPU devices (SURVEY.md §4):
sharded runs must be bitwise identical to the serial golden model —
the stencil is deterministic and reduction-free except the convergence
psum."""

import jax
import numpy as np
import pytest

from heat2d_tpu.config import HeatConfig
from heat2d_tpu.models.solver import Heat2DSolver
from heat2d_tpu.ops import inidat
from heat2d_tpu.parallel.mesh import make_mesh
from heat2d_tpu.parallel.sharded import make_sharded_runner, sharded_inidat


def _serial_result(nx, ny, steps, **kw):
    cfg = HeatConfig(nxprob=nx, nyprob=ny, steps=steps, mode="serial", **kw)
    return Heat2DSolver(cfg).run(timed=False)


@pytest.mark.parametrize("gx,gy", [(4, 1), (1, 4), (2, 2), (4, 2), (2, 4)])
def test_dist2d_bitwise_matches_serial(gx, gy):
    nx, ny, steps = 16, 16, 30
    serial = _serial_result(nx, ny, steps)
    cfg = HeatConfig(nxprob=nx, nyprob=ny, steps=steps, mode="dist2d",
                     gridx=gx, gridy=gy)
    result = Heat2DSolver(cfg).run(timed=False)
    assert result.steps_done == steps
    np.testing.assert_array_equal(result.u, serial.u)


def test_dist1d_matches_serial():
    nx, ny, steps = 40, 12, 25
    serial = _serial_result(nx, ny, steps)
    cfg = HeatConfig(nxprob=nx, nyprob=ny, steps=steps, mode="dist1d",
                     numworkers=8)
    result = Heat2DSolver(cfg).run(timed=False)
    np.testing.assert_array_equal(result.u, serial.u)


def test_sharded_inidat_matches_global():
    cfg = HeatConfig(nxprob=16, nyprob=16, mode="dist2d", gridx=2, gridy=2)
    mesh = make_mesh(2, 2)
    u = sharded_inidat(cfg, mesh)
    np.testing.assert_array_equal(np.asarray(u),
                                  np.asarray(inidat(16, 16)))


def test_dist2d_convergence_early_exit_matches_serial():
    nx, ny = 16, 16
    serial_cfg = HeatConfig(nxprob=nx, nyprob=ny, steps=100000,
                            convergence=True, interval=20, sensitivity=0.1,
                            mode="serial")
    serial = Heat2DSolver(serial_cfg).run(timed=False)
    cfg = serial_cfg.replace(mode="dist2d", gridx=2, gridy=2)
    result = Heat2DSolver(cfg).run(timed=False)
    # psum ordering may differ from the serial sum at float rounding level,
    # but the step count and field must agree.
    assert result.steps_done == serial.steps_done
    np.testing.assert_allclose(result.u, serial.u, rtol=1e-6, atol=1e-4)


@pytest.mark.parametrize("depth", [1, 2, 3, 5])
def test_wide_halo_depths_bitwise(depth):
    # Odd step count exercises the remainder chunk; depth=1 is the
    # reference's per-step exchange.
    nx, ny, steps = 24, 16, 23
    serial = _serial_result(nx, ny, steps)
    cfg = HeatConfig(nxprob=nx, nyprob=ny, steps=steps, mode="dist2d",
                     gridx=4, gridy=2, halo_depth=depth)
    result = Heat2DSolver(cfg).run(timed=False)
    np.testing.assert_array_equal(result.u, serial.u)


def test_wide_halo_depth_clamped_to_shard():
    # halo_depth far beyond the shard size must clamp, not crash/corrupt.
    nx, ny, steps = 16, 16, 12
    serial = _serial_result(nx, ny, steps)
    cfg = HeatConfig(nxprob=nx, nyprob=ny, steps=steps, mode="dist2d",
                     gridx=4, gridy=2, halo_depth=100)
    result = Heat2DSolver(cfg).run(timed=False)
    np.testing.assert_array_equal(result.u, serial.u)


def test_wide_halo_hybrid_kernel_bitwise():
    nx, ny, steps = 16, 32, 9
    serial = _serial_result(nx, ny, steps)
    cfg = HeatConfig(nxprob=nx, nyprob=ny, steps=steps, mode="hybrid",
                     gridx=2, gridy=2, halo_depth=3, bitwise_parity=True)
    result = Heat2DSolver(cfg).run(timed=False)
    np.testing.assert_array_equal(result.u, serial.u)


def test_wide_halo_hybrid_fma_default_close():
    """Hybrid's default step form is the FMA factoring — ulp-class
    agreement with serial; --bitwise-parity restores exactness (above)."""
    nx, ny, steps = 16, 32, 9
    serial = _serial_result(nx, ny, steps)
    cfg = HeatConfig(nxprob=nx, nyprob=ny, steps=steps, mode="hybrid",
                     gridx=2, gridy=2, halo_depth=3)
    result = Heat2DSolver(cfg).run(timed=False)
    np.testing.assert_allclose(result.u, serial.u, rtol=1e-6, atol=1e-4)


@pytest.mark.parametrize("nw", [3, 6, 7])
def test_uneven_row_strips_bitwise(nw):
    """The reference's averow/extra uneven strips (mpi_heat2Dn.c:89-94) as
    pad-to-multiple: 10 rows over 3/6/7 workers, bitwise vs serial —
    including the reference's own default 10x10 config on 3 workers."""
    nx, ny, steps = 10, 10, 100
    serial = _serial_result(nx, ny, steps)
    cfg = HeatConfig(nxprob=nx, nyprob=ny, steps=steps, mode="dist1d",
                     numworkers=nw)
    result = Heat2DSolver(cfg).run(timed=False)
    assert result.u.shape == (nx, ny)
    np.testing.assert_array_equal(result.u, serial.u)


def test_uneven_2d_still_rejected():
    # grad1612_mpi_heat.c:60-64 enforces divisibility for the 2D program;
    # parity keeps that validation for dist2d/hybrid.
    with pytest.raises(Exception, match="not an integer"):
        HeatConfig(nxprob=10, nyprob=10, mode="dist2d", gridx=3, gridy=2)


def test_halo_exchange_zero_fill_edges():
    """Edge shards' ghosts are zero (MPI_PROC_NULL analogue) — verified
    indirectly: global boundary cells never change even when sharded."""
    nx, ny, steps = 16, 16, 10
    cfg = HeatConfig(nxprob=nx, nyprob=ny, steps=steps, mode="dist2d",
                     gridx=4, gridy=2)
    result = Heat2DSolver(cfg).run(timed=False)
    u0 = np.asarray(inidat(nx, ny))
    np.testing.assert_array_equal(result.u[0], u0[0])
    np.testing.assert_array_equal(result.u[-1], u0[-1])
    np.testing.assert_array_equal(result.u[:, 0], u0[:, 0])
    np.testing.assert_array_equal(result.u[:, -1], u0[:, -1])
