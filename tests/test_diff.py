"""Differentiable-solve subsystem (heat2d_tpu/diff) — adjoint tests.

The ISSUE acceptance pins, in order:
- gradient parity: custom-VJP gradients match central finite
  differences (f32 rtol <= 1e-3, tighter in f64) on BOTH the
  constant-coefficient and variable-coefficient routes;
- the checkpointed-segment adjoint matches the full-storage adjoint
  BITWISE for the same segment schedule;
- differentiability costs nothing on the serve hot path: the forward
  solver and the batched band runner trace byte-identically with the
  diff subsystem imported and exercised (the obs/chaos/tune jaxpr-pin
  pattern).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from heat2d_tpu.diff.adjoint import (DiffSpec, make_diff_solve,
                                     segment_schedule)
from heat2d_tpu.models.engine import run_fixed, run_fixed_stacked
from heat2d_tpu.ops.init import inidat
from tests._pin import (assert_jaxpr_equal, band_runner_jaxpr,
                        solver_jaxpr)
from heat2d_tpu.ops.stencil import stencil_step, stencil_step_var


def _u0(nx, ny, dtype=np.float32):
    u = np.asarray(inidat(nx, ny), dtype)
    return jnp.asarray(u / u.max())


def _w(nx, ny, seed=0, dtype=np.float32):
    return jnp.asarray(
        np.random.RandomState(seed).randn(nx, ny).astype(dtype))


# --------------------------------------------------------------------- #
# segment schedule
# --------------------------------------------------------------------- #

def test_segment_schedule_default_is_sqrt():
    assert segment_schedule(16) == (4, 4, 4, 4)
    assert sum(segment_schedule(100)) == 100
    assert segment_schedule(100)[0] == 10


def test_segment_schedule_explicit_and_remainder():
    assert segment_schedule(12, 5) == (5, 5, 2)
    assert segment_schedule(5, 5) == (5,)
    assert segment_schedule(3, 100) == (3,)
    assert segment_schedule(0) == ()


def test_segment_schedule_rejects_bad_args():
    with pytest.raises(ValueError):
        segment_schedule(-1)
    with pytest.raises(ValueError):
        segment_schedule(10, 0)


# --------------------------------------------------------------------- #
# primal parity
# --------------------------------------------------------------------- #

@pytest.mark.parametrize("adjoint", ["checkpoint", "full"])
def test_primal_bitwise_vs_step_loop(adjoint):
    nx, ny, steps = 10, 12, 14
    u0 = _u0(nx, ny)
    f = make_diff_solve(nx, ny, steps, adjoint=adjoint)
    ref = u0
    for _ in range(steps):
        ref = stencil_step(ref, 0.1, 0.1, accum_dtype=None)
    assert np.asarray(f(u0, 0.1, 0.1)).tobytes() == \
        np.asarray(ref).tobytes()


def test_var_route_bitwise_const_fields():
    nx, ny, steps = 9, 11, 10
    u0 = _u0(nx, ny)
    fc = make_diff_solve(nx, ny, steps)
    fv = make_diff_solve(nx, ny, steps, coeff="var")
    k = jnp.full((nx, ny), 0.1, jnp.float32)
    assert np.asarray(fv(u0, k, k)).tobytes() == \
        np.asarray(fc(u0, 0.1, 0.1)).tobytes()


def test_band_primal_close_to_jnp():
    """method='band' (the batched band kernel at B=1, interpret mode on
    CPU) agrees with the jnp route to f32-ulp (FMA step form)."""
    nx, ny, steps = 24, 32, 10
    u0 = _u0(nx, ny)
    out_b = make_diff_solve(nx, ny, steps, method="band")(u0, 0.1, 0.1)
    out_j = make_diff_solve(nx, ny, steps, method="jnp")(u0, 0.1, 0.1)
    np.testing.assert_allclose(np.asarray(out_b), np.asarray(out_j),
                               rtol=1e-5, atol=1e-7)


def test_zero_steps_identity_and_grad():
    nx, ny = 8, 8
    u0 = _u0(nx, ny)
    w = _w(nx, ny)
    f = make_diff_solve(nx, ny, 0)
    assert np.asarray(f(u0, 0.1, 0.1)).tobytes() == \
        np.asarray(u0).tobytes()
    du, da = jax.grad(lambda u, a: jnp.sum(w * f(u, a, 0.1)),
                      argnums=(0, 1))(u0, 0.1)
    assert np.asarray(du).tobytes() == np.asarray(w).tobytes()
    assert float(da) == 0.0


def test_jit_composes():
    nx, ny = 8, 9
    u0 = _u0(nx, ny)
    f = make_diff_solve(nx, ny, 6)
    a = jax.jit(f)(u0, 0.1, 0.1)
    b = f(u0, 0.1, 0.1)
    assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


# --------------------------------------------------------------------- #
# gradient parity vs central finite differences
# --------------------------------------------------------------------- #

def _fd_directional(L, args, argnum, direction, h):
    args_p = list(args)
    args_m = list(args)
    args_p[argnum] = args[argnum] + h * direction
    args_m[argnum] = args[argnum] - h * direction
    return (L(*args_p) - L(*args_m)) / (2 * h)


@pytest.mark.parametrize("adjoint", ["checkpoint", "full"])
def test_grad_parity_fd_const_f32(adjoint):
    nx, ny, steps = 8, 9, 12
    u0 = _u0(nx, ny)
    w = _w(nx, ny)
    f = make_diff_solve(nx, ny, steps, adjoint=adjoint)

    def L(u, a, b):
        return jnp.sum(w * f(u, a, b))

    du, da, db = jax.grad(L, argnums=(0, 1, 2))(u0, 0.1, 0.1)
    # coefficient grads vs scalar central differences
    for argnum, g in ((1, da), (2, db)):
        fd = float(_fd_directional(L, (u0, 0.1, 0.1), argnum,
                                   jnp.asarray(1.0, jnp.float32), 1e-3))
        np.testing.assert_allclose(float(g), fd, rtol=1e-3)
    # u0 grad vs a random directional derivative
    d = _w(nx, ny, seed=1)
    d = d / jnp.sqrt(jnp.sum(d * d))
    fd = float(_fd_directional(L, (u0, 0.1, 0.1), 0, d, 1e-2))
    np.testing.assert_allclose(float(jnp.vdot(du, d)), fd, rtol=1e-3)


def test_grad_parity_fd_var_f32():
    nx, ny, steps = 8, 9, 10
    u0 = _u0(nx, ny)
    w = _w(nx, ny)
    kx = jnp.full((nx, ny), 0.08, jnp.float32)
    ky = jnp.full((nx, ny), 0.11, jnp.float32)
    f = make_diff_solve(nx, ny, steps, coeff="var")

    def L(u, a, b):
        return jnp.sum(w * f(u, a, b))

    gkx, gky = jax.grad(L, argnums=(1, 2))(u0, kx, ky)
    for argnum, g in ((1, gkx), (2, gky)):
        d = _w(nx, ny, seed=2 + argnum)
        d = d / jnp.sqrt(jnp.sum(d * d))
        fd = float(_fd_directional(L, (u0, kx, ky), argnum, d, 1e-3))
        np.testing.assert_allclose(float(jnp.vdot(g, d)), fd, rtol=1e-3)


@pytest.mark.parametrize("coeff", ["const", "var"])
def test_grad_parity_fd_f64_tighter(coeff):
    """x64 is on (conftest): float64 inputs flow f64 through the whole
    solve+adjoint, and central differences agree to ~1e-6."""
    nx, ny, steps = 8, 8, 10
    u0 = _u0(nx, ny, np.float64)
    w = _w(nx, ny, dtype=np.float64)
    f = make_diff_solve(nx, ny, steps, coeff=coeff)
    if coeff == "const":
        args = (u0, jnp.asarray(0.1, jnp.float64),
                jnp.asarray(0.1, jnp.float64))
    else:
        args = (u0, jnp.full((nx, ny), 0.09, jnp.float64),
                jnp.full((nx, ny), 0.12, jnp.float64))

    def L(u, a, b):
        return jnp.sum(w * f(u, a, b))

    grads = jax.grad(L, argnums=(0, 1, 2))(*args)
    for argnum in (0, 1, 2):
        g = grads[argnum]
        d = jnp.asarray(np.random.RandomState(10 + argnum)
                        .randn(*np.shape(args[argnum])))
        n = jnp.sqrt(jnp.sum(d * d))
        d = d / jnp.where(n == 0, 1.0, n)
        fd = float(_fd_directional(L, args, argnum, d, 1e-6))
        np.testing.assert_allclose(float(jnp.vdot(g, d)), fd, rtol=1e-6,
                                   atol=1e-12)


# --------------------------------------------------------------------- #
# checkpointed == full storage, bitwise
# --------------------------------------------------------------------- #

@pytest.mark.parametrize("segment", [None, 1, 5, 13])
def test_checkpoint_matches_full_bitwise_const(segment):
    nx, ny, steps = 10, 11, 13
    u0 = _u0(nx, ny)
    w = _w(nx, ny)
    grads = {}
    for adjoint in ("checkpoint", "full"):
        f = make_diff_solve(nx, ny, steps, adjoint=adjoint,
                            segment=segment)
        grads[adjoint] = jax.grad(
            lambda u, a, b: jnp.sum(w * f(u, a, b)),  # noqa: B023
            argnums=(0, 1, 2))(u0, 0.1, 0.1)
    for g_ck, g_full in zip(grads["checkpoint"], grads["full"]):
        assert np.asarray(g_ck).tobytes() == np.asarray(g_full).tobytes()


def test_checkpoint_matches_full_bitwise_var():
    nx, ny, steps = 9, 9, 12
    u0 = _u0(nx, ny)
    w = _w(nx, ny)
    kx = jnp.asarray(np.random.RandomState(3)
                     .uniform(0.05, 0.15, (nx, ny)).astype(np.float32))
    ky = jnp.asarray(np.random.RandomState(4)
                     .uniform(0.05, 0.15, (nx, ny)).astype(np.float32))
    grads = {}
    for adjoint in ("checkpoint", "full"):
        f = make_diff_solve(nx, ny, steps, coeff="var", adjoint=adjoint,
                            segment=4)
        grads[adjoint] = jax.grad(
            lambda u, a, b: jnp.sum(w * f(u, a, b)),  # noqa: B023
            argnums=(0, 1, 2))(u0, kx, ky)
    for g_ck, g_full in zip(grads["checkpoint"], grads["full"]):
        assert np.asarray(g_ck).tobytes() == np.asarray(g_full).tobytes()


# --------------------------------------------------------------------- #
# the hot-path jaxpr pin (differentiability costs nothing unused)
# --------------------------------------------------------------------- #

def test_forward_solver_jaxpr_identical_with_diff_exercised():
    """The acceptance pin: building AND differentiating a diff operator
    leaves the forward solver's traced program byte-identical — the
    serve hot path pays zero for the subsystem's existence."""
    before = solver_jaxpr(12, 12, 8)

    f = make_diff_solve(12, 12, 8)
    w = _w(12, 12)
    jax.grad(lambda u: jnp.sum(w * f(u, 0.1, 0.1)))(_u0(12, 12))

    after = solver_jaxpr(12, 12, 8)
    assert_jaxpr_equal(before, after,
                       label="forward solver (diff exercised)")


def test_batched_band_runner_jaxpr_identical_with_diff_exercised(
        monkeypatch):
    """Same pin for the serve compile cache's kernel path."""
    from heat2d_tpu.ops import pallas_stencil as ps

    monkeypatch.setattr(ps, "VMEM_BUDGET_BYTES", 256 * 1024)
    before = band_runner_jaxpr(64, 128, 10, b=2)

    f = make_diff_solve(16, 16, 6)
    jax.grad(lambda u: jnp.sum(f(u, 0.1, 0.1)))(_u0(16, 16))

    after = band_runner_jaxpr(64, 128, 10, b=2)
    assert_jaxpr_equal(before, after,
                       label="batched band runner (diff exercised)")


# --------------------------------------------------------------------- #
# ops/engine satellites
# --------------------------------------------------------------------- #

def test_stencil_step_var_holds_edges():
    nx, ny = 7, 8
    u = _u0(nx, ny) + 1.0   # nonzero edges
    k = jnp.full((nx, ny), 0.1, jnp.float32)
    out = np.asarray(stencil_step_var(u, k, k))
    u_np = np.asarray(u)
    np.testing.assert_array_equal(out[0, :], u_np[0, :])
    np.testing.assert_array_equal(out[-1, :], u_np[-1, :])
    np.testing.assert_array_equal(out[:, 0], u_np[:, 0])
    np.testing.assert_array_equal(out[:, -1], u_np[:, -1])


def test_stencil_step_var_heterogeneous_matches_numpy():
    nx, ny = 6, 7
    rs = np.random.RandomState(7)
    u = rs.rand(nx, ny).astype(np.float32)
    kx = rs.uniform(0.05, 0.2, (nx, ny)).astype(np.float32)
    ky = rs.uniform(0.05, 0.2, (nx, ny)).astype(np.float32)
    out = np.asarray(stencil_step_var(jnp.asarray(u), jnp.asarray(kx),
                                      jnp.asarray(ky)))
    ref = u.copy()
    c = u[1:-1, 1:-1]
    sx = u[2:, 1:-1] + u[:-2, 1:-1]
    sy = u[1:-1, 2:] + u[1:-1, :-2]
    ref[1:-1, 1:-1] = (c + kx[1:-1, 1:-1] * (sx - 2.0 * c)
                       + ky[1:-1, 1:-1] * (sy - 2.0 * c))
    np.testing.assert_allclose(out, ref, rtol=1e-6)


def test_run_fixed_stacked_states():
    u0 = _u0(6, 6)
    step = lambda v: stencil_step(v, 0.1, 0.1)  # noqa: E731
    u_fin, states = run_fixed_stacked(step, u0, 5)
    assert states.shape == (5, 6, 6)
    assert np.asarray(states[0]).tobytes() == np.asarray(u0).tobytes()
    # states[t] is the input of step t; the final output continues it
    # (allclose: the eager re-application fuses differently than the
    # scan body — one-ulp class, not a semantic difference)
    np.testing.assert_allclose(np.asarray(step(states[-1])),
                               np.asarray(u_fin), rtol=1e-6)
    ref, _ = run_fixed(step, u0, 5)
    assert np.asarray(u_fin).tobytes() == np.asarray(ref).tobytes()


def test_make_diff_solve_validation():
    with pytest.raises(ValueError):
        make_diff_solve(2, 8, 4)
    with pytest.raises(ValueError):
        make_diff_solve(8, 8, 4, coeff="nope")
    with pytest.raises(ValueError):
        make_diff_solve(8, 8, 4, adjoint="nope")
    with pytest.raises(ValueError):
        make_diff_solve(8, 8, 4, coeff="var", method="band")
    # full storage records every step state — the fused band primal
    # cannot reproduce the per-step scan bit for bit, so the combo is
    # an error (and 'auto' resolves full to the jnp route everywhere)
    with pytest.raises(ValueError):
        make_diff_solve(24, 32, 8, adjoint="full", method="band")
    assert make_diff_solve(24, 32, 8, adjoint="full").spec.method == "jnp"
    f = make_diff_solve(8, 8, 4)
    with pytest.raises(ValueError):
        f(jnp.zeros((4, 4)), 0.1, 0.1)          # wrong grid shape
    fv = make_diff_solve(8, 8, 4, coeff="var")
    with pytest.raises(ValueError):
        fv(jnp.zeros((8, 8)), 0.1, 0.1)         # scalar where field due


def test_spec_is_hashable_and_exposed():
    f = make_diff_solve(8, 9, 12, segment=5)
    assert isinstance(f.spec, DiffSpec)
    assert f.spec.schedule == (5, 5, 2)
    hash(f.spec)
