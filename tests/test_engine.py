"""Loop-assembly tests: fixed stepping and the (correctly implemented)
convergence early-exit (SURVEY.md A.2)."""

import jax
import jax.numpy as jnp
import numpy as np

from heat2d_tpu.models import engine
from heat2d_tpu.ops import inidat, residual_sq, stencil_step


def _step(u):
    return stencil_step(u, 0.1, 0.1)


def _residual(a, b):
    return residual_sq(a, b)


def test_run_fixed_equals_unrolled():
    u0 = inidat(10, 10)
    u_loop, k = jax.jit(lambda u: engine.run_fixed(_step, u, 17))(u0)
    step = jax.jit(_step)  # same compiled body as the loop
    u_ref = u0
    for _ in range(17):
        u_ref = step(u_ref)
    assert int(k) == 17
    np.testing.assert_array_equal(np.asarray(u_loop), np.asarray(u_ref))


def test_convergence_runs_all_steps_when_tight():
    """With an unreachably small sensitivity, all STEPS run."""
    u0 = inidat(10, 10)
    run = jax.jit(lambda u: engine.run_convergence(
        _step, _residual, u, 60, 20, 1e-30))
    u, k = run(u0)
    assert int(k) == 60
    u_fixed, _ = jax.jit(lambda u: engine.run_fixed(_step, u, 60))(u0)
    np.testing.assert_array_equal(np.asarray(u), np.asarray(u_fixed))


def test_convergence_early_exit():
    """With a huge sensitivity, the loop exits at the first INTERVAL check
    — grad1612_mpi_heat.c:269's intended break."""
    u0 = inidat(10, 10)
    run = jax.jit(lambda u: engine.run_convergence(
        _step, _residual, u, 100, 20, 1e30))
    _, k = run(u0)
    assert int(k) == 20


def test_convergence_interval_not_divisible():
    """STEPS not a multiple of INTERVAL: the final short chunk still runs
    and the step count is exact."""
    u0 = inidat(10, 10)
    run = jax.jit(lambda u: engine.run_convergence(
        _step, _residual, u, 50, 20, 1e-30))
    u, k = run(u0)
    assert int(k) == 50
    u_fixed, _ = jax.jit(lambda u: engine.run_fixed(_step, u, 50))(u0)
    np.testing.assert_array_equal(np.asarray(u), np.asarray(u_fixed))


def test_convergence_physics_actually_converges():
    """A real physical run decays to a flat field; the residual check must
    fire before the step cap."""
    u0 = inidat(10, 10)
    run = jax.jit(lambda u: engine.run_convergence(
        _step, _residual, u, 100000, 20, 0.1))
    _, k = run(u0)
    assert int(k) < 100000
    assert int(k) % 20 == 0


def test_convergence_fused_matches_chunked():
    """run_convergence_fused with a chunk_resid built from the SAME step
    form must reproduce run_convergence_chunked's schedule, planes, and
    steps_done exactly — early exit, full budget, and remainder cases."""
    def multi(u, n):
        for _ in range(n):
            u = _step(u)
        return u

    def chunk_resid(u, n):
        u_prev = multi(u, n - 1)
        u_new = _step(u_prev)
        return u_new, _residual(u_new, u_prev)

    u0 = inidat(12, 16)
    for steps, interval, sens in [(100, 20, 5.0),     # early exit
                                  (50, 20, 0.0),      # full budget + rem
                                  (40, 20, 1e30)]:    # first-chunk exit
        want_u, want_k = jax.jit(
            lambda u, s=steps, i=interval, e=sens:
            engine.run_convergence_chunked(multi, _step, _residual,
                                           u, s, i, e))(u0)
        got_u, got_k = jax.jit(
            lambda u, s=steps, i=interval, e=sens:
            engine.run_convergence_fused(chunk_resid, multi,
                                         u, s, i, e))(u0)
        assert int(got_k) == int(want_k), (steps, interval, sens)
        np.testing.assert_array_equal(np.asarray(got_u),
                                      np.asarray(want_u))
