"""Elastic capacity (heat2d_tpu/autoscale/, ISSUE 19): the actuator
that EXECUTES the control plane's sizing advice.

Four tiers, mirroring the subsystem's layers:

- **parole + resize** (mesh/health.py, mesh/engine.py): quarantine
  parole demands N consecutive verified probe passes, one failure
  denies; re-admission is a seq-fenced ``readmit`` event the serving
  invariant stays provable through (including a re-conviction AFTER
  parole — the mid-parole kill-storm case); voluntary resize validates
  its bounds and truncates the next launch's device set.
- **live migration** (autoscale/migrate.py): the Adam state + problem
  spec round-trip bitwise through the JSON wire ticket, and a solve
  paused mid-flight and resumed elsewhere is BITWISE-identical to one
  that never paused — params and every loss in the history.
- **actuator decisions** (autoscale/actuator.py): cooldowns, the
  scale-down hold, clamping, step limits, victim selection, the
  chip-seconds ledger — all on a fake fleet with an injected clock.
- **drain-to-retire** (fleet/supervisor.py + router.py): the
  retirement-ordering contract — fence BEFORE drain — at the router
  level (a fenced slot is unroutable, its in-flight work flushes or
  replays) and end to end with real worker subprocesses, including the
  drain-timeout kill + replay leg on an injected clock, and the
  kill-storm-mid-scale-up case where the only surviving worker is
  still cold (uncompiled) and must never see client traffic.
"""

import json
import time

import numpy as np
import pytest

import jax.numpy as jnp

from heat2d_tpu.autoscale import Actuator, AutoscalePolicy
from heat2d_tpu.autoscale import migrate
from heat2d_tpu.diff.adjoint import make_diff_solve
from heat2d_tpu.diff.inverse import (AdamState, InverseProblem,
                                     observation_mask,
                                     unit_reference_init)
from heat2d_tpu.fleet import WorkerGone
from heat2d_tpu.mesh import FaultPolicy, HealthMonitor, MeshEnsembleEngine
from heat2d_tpu.mesh import health as health_mod
from heat2d_tpu.mesh.degrade import serving_invariant
from heat2d_tpu.mesh.health import PAROLE_PASSES
from heat2d_tpu.obs import MetricsRegistry
from heat2d_tpu.resil.retry import wait_for
from tests.test_fleet import STEPS, answer, fleet, make_router
from tests.test_fleet import req as freq


def counters(reg):
    return reg.snapshot()["counters"]


# --------------------------------------------------------------------- #
# parole — quarantine gains a way back (mesh/health.py)
# --------------------------------------------------------------------- #

def test_parole_readmits_with_seq_fenced_event():
    reg = MetricsRegistry()
    m = HealthMonitor(n_devices=4, registry=reg)
    m.quarantine(2, "probe_failure")
    assert m.capacity_fraction() == 0.75
    fence_before = m.seq()
    calls = []
    assert m.parole(2, passes=2, probe=lambda i: calls.append(i) or True)
    assert calls == [2, 2]              # exactly ``passes`` probes ran
    assert not m.is_quarantined(2)
    assert m.capacity_fraction() == 1.0
    ev = m.snapshot()["events"][-1]
    assert ev["kind"] == "readmit" and ev["device"] == 2
    assert ev["passes"] == 2 and ev["seq"] == fence_before + 1
    assert counters(reg)["mesh_parole_total{outcome=paroled}"] == 1


def test_parole_denied_on_any_failure_stays_quarantined():
    reg = MetricsRegistry()
    m = HealthMonitor(n_devices=2, registry=reg)
    m.quarantine(1, "device_fail")
    calls = []

    def flaky(i):                       # second pass fails
        calls.append(i)
        return len(calls) < 2

    assert not m.parole(1, passes=3, probe=flaky)
    assert calls == [1, 1]              # the hearing ended AT the failure
    assert m.is_quarantined(1)
    # a denial leaves no event: the audit trail still reads "convicted"
    assert all(e.get("kind") != "readmit"
               for e in m.snapshot()["events"])
    assert counters(reg)["mesh_parole_total{outcome=denied}"] == 1


def test_parole_validation():
    m = HealthMonitor(n_devices=2)
    assert not m.parole(0)              # not quarantined: nothing to do
    with pytest.raises(ValueError):
        m.parole(0, passes=0)
    with pytest.raises(ValueError):
        m.parole(9)
    assert PAROLE_PASSES >= 2           # a single pass is not a hearing


def test_serving_invariant_through_parole_lifecycle():
    """quarantine -> (violating launch) -> parole -> (clean launch) ->
    re-conviction mid-serving -> (violating launch): the seq fence
    keeps every verdict a pure ordinal comparison — the chaos case a
    kill storm landing mid-parole must stay provable through."""
    m = HealthMonitor(n_devices=4)
    log = []

    def launch(devs):
        log.append({"signature": f"L{len(log)}",
                    "mesh": {"devices": list(devs),
                             "health_seq": m.seq()}})

    launch((0, 1, 2, 3))                        # L0: healthy
    m.quarantine(3, "probe_failure")
    launch((0, 1, 2))                           # L1: correctly excludes 3
    launch((0, 1, 3))                           # L2: VIOLATION
    assert m.parole(3, passes=2, probe=lambda i: True)
    launch((0, 1, 2, 3))                        # L3: ok — fenced after readmit
    m.quarantine(3, "mesh_stall")               # the storm re-convicts
    launch((0, 1, 2, 3))                        # L4: VIOLATION again
    rep = serving_invariant(m, log)
    assert not rep["ok"] and rep["checked"] == 5
    assert sorted(v["launch"] for v in rep["violations"]) == ["L2", "L4"]
    # the L4 conviction is the re-quarantine, not the original one
    assert rep["violations"][1]["event"]["reason"] == "mesh_stall"


# --------------------------------------------------------------------- #
# voluntary resize (mesh/engine.py)
# --------------------------------------------------------------------- #

def test_resize_validates_bounds_and_truncates_launch_set():
    reg = MetricsRegistry()
    eng = MeshEnsembleEngine(registry=reg, fault=FaultPolicy())
    nd = eng.n_devices
    with pytest.raises(ValueError):
        eng.resize(0)
    with pytest.raises(ValueError):
        eng.resize(nd + 1)
    row = eng.resize(1)
    assert row["from"] == nd and row["to"] == 1
    assert row["health_seq"] == eng.health.seq()
    assert eng.active_devices() == (0,)
    back = eng.resize(nd)                       # grow back: devices were
    assert back["from"] == 1 and back["to"] == nd   # never released
    assert eng.active_devices() == tuple(range(nd))
    assert eng.resize_log == [row, back]
    c = counters(reg)
    assert c["mesh_resize_total{direction=down}"] == 1
    assert c["mesh_resize_total{direction=up}"] == 1
    assert reg.snapshot()["gauges"]["mesh_target_devices"] == nd


def test_resize_target_composes_with_quarantine():
    eng = MeshEnsembleEngine(registry=MetricsRegistry(),
                             fault=FaultPolicy())
    nd = eng.n_devices
    if nd < 3:
        pytest.skip("needs >= 3 devices to compose resize + quarantine")
    eng.resize(nd - 1)
    eng.health.quarantine(0, "device_fail")
    # survivors first, THEN the voluntary truncation
    assert eng.active_devices() == tuple(range(1, nd))[:nd - 1]
    assert 0 not in eng.active_devices()


# --------------------------------------------------------------------- #
# live migration — the wire ticket and the bitwise resume contract
# --------------------------------------------------------------------- #

NXI = NYI = 8
ISTEPS, ITERS, PAUSE_AT, LR = 5, 24, 7, 0.05


@pytest.fixture(scope="module")
def tiny_problem():
    u0 = unit_reference_init(NXI, NYI)
    u_true = np.asarray(make_diff_solve(NXI, NYI, ISTEPS)(
        jnp.asarray(u0), 0.1, 0.1))
    return InverseProblem(nx=NXI, ny=NYI, steps=ISTEPS, target="init",
                          obs_mask=observation_mask(NXI, NYI, every=1),
                          obs_values=u_true, cx=0.1, cy=0.1)


@pytest.fixture(scope="module")
def tiny_oracle(tiny_problem):
    """The unmigrated run every migrated trajectory must match."""
    return migrate.run_unmigrated(tiny_problem, iterations=ITERS, lr=LR)


@pytest.fixture(scope="module")
def tiny_ticket(tiny_problem):
    """A checkpoint taken DETERMINISTICALLY at iteration PAUSE_AT."""
    sol = tiny_problem.solve(iterations=ITERS, lr=LR,
                             pause=lambda it: it >= PAUSE_AT)
    assert sol.paused and sol.state.iteration == PAUSE_AT
    assert len(sol.loss_history) == PAUSE_AT
    return migrate.encode_ticket(tiny_problem, sol.state,
                                 iterations=ITERS, lr=LR)


def test_adam_state_wire_roundtrip_bitwise():
    rng = np.random.default_rng(7)

    def arr():
        return rng.standard_normal((NXI, NYI))

    st = AdamState(iteration=17, params=arr(), m=arr(), v=arr(),
                   best=arr(), best_loss=0.123456789,
                   loss_history=[1.0, 0.5],
                   grad_norm_history=[2.0, 1.25])
    back = migrate.decode_state(
        json.loads(json.dumps(migrate.encode_state(st))))
    for f in ("params", "m", "v", "best"):
        assert getattr(back, f).dtype == getattr(st, f).dtype
        assert getattr(back, f).tobytes() == getattr(st, f).tobytes()
    assert back.iteration == 17
    assert back.best_loss == st.best_loss
    assert back.loss_history == st.loss_history


def test_ticket_schema_is_validated(tiny_ticket):
    assert migrate.decode_ticket(json.dumps(tiny_ticket)) == \
        migrate.decode_ticket(tiny_ticket)
    with pytest.raises(ValueError):
        migrate.decode_ticket({"schema": "heat2d-tpu/other/v9"})


def test_problem_spec_roundtrip(tiny_problem, tiny_ticket):
    prob = migrate.problem_from_spec(tiny_ticket["problem"])
    assert (prob.nx, prob.ny, prob.steps) == (NXI, NYI, ISTEPS)
    assert prob.target == "init" and prob.method == tiny_problem.method
    assert np.asarray(prob.obs_values).tobytes() == \
        np.asarray(tiny_problem.obs_values).tobytes()
    assert np.array_equal(np.asarray(prob.obs_mask),
                          np.asarray(tiny_problem.obs_mask))


def test_pause_resume_is_bitwise_vs_unmigrated(tiny_ticket, tiny_oracle):
    """The headline contract: ship the mid-flight ticket over a JSON
    wire line, resume on 'another worker', and the finished trajectory
    is indistinguishable from one that never moved."""
    job = migrate.resume_job(json.dumps(tiny_ticket))
    job.join(timeout=300)
    sol = job.solution
    assert not sol.paused and sol.iterations == ITERS
    assert np.asarray(sol.params).tobytes() == \
        np.asarray(tiny_oracle.params).tobytes()
    assert sol.loss_history == list(tiny_oracle.loss_history)
    assert sol.grad_norm_history == list(tiny_oracle.grad_norm_history)


def test_inverse_job_threaded_checkpoint_resume(tiny_problem):
    """The actuator's actual path: a RUNNING job is paused at whatever
    iteration boundary the drain catches it, and the resumed run still
    lands bitwise on the never-paused oracle."""
    budget = 5000   # big enough that the pause always lands mid-flight
    reg = MetricsRegistry()
    job = migrate.InverseJob(tiny_problem, iterations=budget, lr=LR,
                             registry=reg).start()
    assert wait_for(
        lambda: counters(reg).get("inverse_iterations_total", 0) >= 5,
        120.0)
    ticket = job.checkpoint()
    assert ticket is not None
    it0 = ticket["state"]["iteration"]
    assert 0 < it0 < budget
    resumed = migrate.resume_job(json.dumps(ticket))
    resumed.join(timeout=300)
    oracle = migrate.run_unmigrated(ticket)     # budget from the ticket
    assert np.asarray(resumed.solution.params).tobytes() == \
        np.asarray(oracle.params).tobytes()
    assert resumed.solution.loss_history == list(oracle.loss_history)


def test_finished_job_checkpoints_to_none(tiny_problem):
    job = migrate.InverseJob(tiny_problem, iterations=3, lr=LR).start()
    job.join(timeout=120)
    assert job.done() and job.completed_iterations() == 3
    assert job.checkpoint() is None     # nothing to migrate


# --------------------------------------------------------------------- #
# actuator decisions — fake fleet, injected clock
# --------------------------------------------------------------------- #

class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class FakeFleet:
    """The FleetServer surface the actuator drives, minus processes."""

    def __init__(self, n=1):
        self.sup = self
        self._slots = list(range(n))
        self._next = n
        self.retired = []

    def pool_size(self):
        return len(self._slots)

    def provisioned_slots(self):
        return list(self._slots)

    def add_worker(self):
        slot = self._next
        self._next += 1
        self._slots.append(slot)
        return slot

    def retire_worker(self, slot, timeout=30.0):
        self._slots.remove(slot)
        self.retired.append(slot)
        return True


POL = AutoscalePolicy(min_workers=1, max_workers=4, up_cooldown_s=10.0,
                      down_cooldown_s=10.0, down_hold_ticks=3,
                      max_step_up=2, max_step_down=1)


def test_policy_validation():
    with pytest.raises(ValueError):
        AutoscalePolicy(min_workers=0)
    with pytest.raises(ValueError):
        AutoscalePolicy(min_workers=3, max_workers=2)
    with pytest.raises(ValueError):
        AutoscalePolicy(down_hold_ticks=0)
    with pytest.raises(ValueError):
        AutoscalePolicy(drain_timeout_s=0.0)


def test_actuator_scale_up_steps_cooldown_and_clamp():
    fl, clk, reg = FakeFleet(1), FakeClock(), MetricsRegistry()
    act = Actuator(fl, POL, registry=reg, clock=clk)
    rows = act.observe({"needed_units": 10})    # clamped to max 4
    assert [r["action"] for r in rows] == ["scale_up"]
    assert rows[0]["slots"] == [1, 2]           # max_step_up bounds it
    assert fl.pool_size() == 3 and rows[0]["target"] == 4
    clk.t = 5.0
    assert act.observe({"needed_units": 10}) == []      # up cooldown
    assert fl.pool_size() == 3
    clk.t = 12.0
    act.observe({"needed_units": 10})
    assert fl.pool_size() == 4                  # converged to the clamp
    clk.t = 24.0
    assert act.observe({"needed_units": 9}) == []       # at target
    c = counters(reg)
    assert c["autoscale_actions_total{action=scale_up}"] == 2
    assert reg.snapshot()["gauges"]["autoscale_workers"] == 4.0


def test_actuator_scale_down_hold_cooldown_and_victims():
    fl, clk = FakeFleet(3), FakeClock()
    act = Actuator(fl, POL, clock=clk)
    clk.t = 1.0
    assert act.observe({"needed_units": 1}) == []       # hold 1
    clk.t = 2.0
    assert act.observe({"needed_units": 1}) == []       # hold 2
    clk.t = 3.0
    rows = act.observe({"needed_units": 1})             # hold met
    assert [r["action"] for r in rows] == ["scale_down"]
    assert rows[0]["slot"] == 2 and fl.retired == [2]   # newest first
    assert fl.pool_size() == 2                  # max_step_down bounds it
    # hold resets after an action; the cooldown then gates the next one
    clk.t = 4.0
    assert act.observe({"needed_units": 1}) == []
    clk.t = 5.0
    assert act.observe({"needed_units": 1}) == []
    clk.t = 6.0
    assert act.observe({"needed_units": 1}) == []       # held by cooldown
    assert fl.pool_size() == 2
    clk.t = 14.0
    act.observe({"needed_units": 1})
    assert fl.pool_size() == 1 and fl.retired == [2, 1]
    # min_workers floor: advice 0 clamps to 1 == current, never below
    clk.t = 30.0
    assert act.observe({"needed_units": 0}) == []
    assert fl.pool_size() == 1


def test_actuator_equal_advice_resets_the_hold():
    fl, clk = FakeFleet(2), FakeClock()
    act = Actuator(fl, POL, clock=clk)
    clk.t = 1.0
    act.observe({"needed_units": 1})            # hold 1
    clk.t = 2.0
    act.observe({"needed_units": 2})            # equal: hold resets
    clk.t = 3.0
    act.observe({"needed_units": 1})            # hold 1 again
    clk.t = 4.0
    act.observe({"needed_units": 1})            # hold 2
    assert fl.pool_size() == 2                  # still no retire
    clk.t = 5.0
    rows = act.observe({"needed_units": 1})     # hold 3: NOW
    assert rows and fl.pool_size() == 1


def test_actuator_chip_seconds_ledger():
    fl, clk = FakeFleet(2), FakeClock()
    act = Actuator(fl, POL, clock=clk)
    act.observe(None)                           # arms the ledger at t=0
    clk.t = 1.0
    act.observe(None)                           # + 1s x 2 workers
    clk.t = 3.0
    act.observe(None)                           # + 2s x 2 workers
    s = act.summary()
    assert s["chip_seconds"] == pytest.approx(6.0)
    assert s["static_chip_seconds"] == pytest.approx(3.0 * 4)
    assert s["savings_fraction"] == pytest.approx(0.5)
    assert s["workers_min"] == s["workers_max"] == 2
    assert s["trace"] == [(0.0, 2), (1.0, 2), (3.0, 2)]


def test_actuator_live_migrates_jobs_on_retire(tiny_ticket, tiny_oracle):
    """Scale-down with an attached long-running job: checkpoint, JSON
    wire trip, resume on the lowest surviving slot — then the moved
    job finishes bitwise on the oracle."""

    class StubJob:
        def checkpoint(self, timeout=120.0):
            return tiny_ticket

        def completed_iterations(self):
            return PAUSE_AT

    fl, reg = FakeFleet(2), MetricsRegistry()
    act = Actuator(fl, AutoscalePolicy(), registry=reg,
                   clock=FakeClock())
    act.attach_job(1, StubJob())
    row = act.retire(1)
    assert row["clean"] is True and fl.retired == [1]
    mig = row["migrated"]
    assert len(mig) == 1 and mig[0]["resumed"] is True
    assert mig[0]["from"] == 1 and mig[0]["to"] == 0
    assert mig[0]["iteration"] == PAUSE_AT and mig[0]["bytes"] > 0
    moved = act.jobs_on(0)[-1]
    moved.join(timeout=300)
    sol = moved.solution
    assert not sol.paused and sol.iterations == ITERS
    assert np.asarray(sol.params).tobytes() == \
        np.asarray(tiny_oracle.params).tobytes()
    assert sol.loss_history == list(tiny_oracle.loss_history)
    assert counters(reg)["autoscale_migrations_total"] == 1


def test_actuator_finished_job_is_not_migrated():
    class DoneJob:
        def checkpoint(self, timeout=120.0):
            return None                 # finished before the pause

        def completed_iterations(self):
            return 42

    fl = FakeFleet(2)
    act = Actuator(fl, AutoscalePolicy(), clock=FakeClock())
    act.attach_job(1, DoneJob())
    row = act.retire(1)
    assert row["migrated"] == [{"from": 1, "to": None,
                                "iteration": 42, "resumed": False}]
    assert act.migrations == [] and act.jobs_on(0) == []


def test_actuator_parole_all_and_resize(monkeypatch):
    reg = MetricsRegistry()
    m = HealthMonitor(n_devices=4, registry=reg)
    m.quarantine(1, "probe_failure")
    m.quarantine(3, "device_fail")
    monkeypatch.setattr(health_mod, "probe_device", lambda i: i == 1)
    act = Actuator(FakeFleet(1), AutoscalePolicy(parole_passes=2),
                   registry=reg, health=m, clock=FakeClock())
    rows = act.parole_all()
    assert [(r["device"], r["outcome"]) for r in rows] == \
        [(1, "paroled"), (3, "denied")]
    assert m.quarantined() == (3,)
    c = counters(reg)
    assert c["autoscale_actions_total{action=parole}"] == 2
    # and the mesh-resize action funnels through the same audit trail
    eng = MeshEnsembleEngine(registry=reg, fault=FaultPolicy())
    act.mesh_engine = eng
    row = act.resize_mesh(1)
    assert row["action"] == "mesh_resize" and row["to"] == 1
    assert eng.active_devices() == (0,)
    act.resize_mesh(eng.n_devices)
    assert counters(reg)["autoscale_actions_total{action=mesh_resize}"] \
        == 2


# --------------------------------------------------------------------- #
# drain-to-retire — the router-level ordering contract (fake sup)
# --------------------------------------------------------------------- #

def test_retiring_fence_blocks_routing_and_unclean_drain_replays():
    """The satellite ordering fix, observable at the router: once a
    slot is fenced for retirement, NO new request routes to it; its
    in-flight work stays recorded and replays on an unclean drain."""
    fs = make_router()
    fut = msg = None
    for i in range(16):                 # land an in-flight on slot 1
        f = fs.submit(freq(cx=0.4, steps=STEPS + i))
        s, m = fs.sup.sent[-1]
        if s == 1:
            fut, msg = f, m
            break
        answer(fs, s, m)
        f.result(timeout=5)
    assert fut is not None, "no signature routed to slot 1"
    fs._on_worker_retiring(1)           # the fence, BEFORE any drain
    n0 = len(fs.sup.sent)
    others = [fs.submit(freq(cx=0.5, steps=STEPS + 20 + i))
              for i in range(4)]
    assert [s for s, _ in fs.sup.sent[n0:]] == [0, 0, 0, 0]
    # unclean drain: the fenced slot's in-flight replays to a survivor
    fs.sup.alive.remove(1)
    fs._on_worker_lost(1)
    rs, rm = fs.sup.sent[-1]
    assert rs == 0 and rm["req"]["steps"] == msg["req"]["steps"]
    answer(fs, rs, rm)
    assert fut.result(timeout=5).steps_done == msg["req"]["steps"]
    for f2, (s, m) in zip(others, fs.sup.sent[n0:n0 + 4]):
        answer(fs, s, m)
        f2.result(timeout=5)


def test_kill_storm_mid_scale_up_cold_worker_is_fenced():
    """Chaos coverage (satellite): warm workers die while a scale-up
    spawn is still compiling. While ANY warm worker survives, the cold
    spawn never sees a client request; when the storm takes the LAST
    warm worker, the router's availability fallback replays the
    in-flight work onto the cold worker rather than stranding it —
    every request is still answered."""
    fs = make_router()
    f = fs.submit(freq(cx=0.2))
    slot0, msg0 = fs.sup.sent[-1]
    answer(fs, slot0, msg0)
    f.result(timeout=5)                 # hot set established
    fs.sup.alive.append(2)
    fs._on_worker_ready(2, via="scale_up")      # the scale-up spawn
    assert 2 in fs._cold
    warmups = [(s, m) for s, m in fs.sup.sent
               if m.get("event") == "warmup"]
    assert len(warmups) == 1 and warmups[0][0] == 2
    fs.sup.alive.remove(1)              # the storm's first hit
    fs._on_worker_lost(1)
    n0 = len(fs.sup.sent)
    pairs = [(fs.submit(freq(cx=0.3, steps=STEPS + i)), STEPS + i)
             for i in range(3)]
    storm_sent = fs.sup.sent[n0:]
    # a warm worker survives: ALL storm traffic lands on it — the
    # uncompiled scale-up spawn serves nothing
    assert len(storm_sent) == 3
    assert all(s == 0 for s, _ in storm_sent)
    fs.sup.alive.remove(0)              # the storm takes the last one
    fs._on_worker_lost(0)
    replayed = fs.sup.sent[-3:]
    # whole fleet cold: availability beats the gate, nothing is lost
    assert all(s == 2 for s, _ in replayed)
    for s, m in replayed:
        answer(fs, s, m)
    for f2, want in pairs:
        assert f2.result(timeout=5).steps_done == want
    wslot, wmsg = warmups[0]            # the warm answer readmits it
    fs._on_response(wslot, {"id": wmsg["id"], "ok": True, "warm": True})
    assert 2 not in fs._cold


# --------------------------------------------------------------------- #
# drain-to-retire — end to end with real worker subprocesses
# --------------------------------------------------------------------- #

def test_retire_worker_end_to_end_clean_drain():
    reg = MetricsRegistry()
    with fleet(workers=2, registry=reg) as fs:
        assert fs.solve(freq(cx=0.11), timeout=120).steps_done == STEPS
        victim = fs.sup.provisioned_slots()[-1]
        assert fs.retire_worker(victim, timeout=30.0) is True
        assert fs.sup.pool_size() == 1
        assert victim not in fs.sup.alive_slots()
        assert victim not in fs.sup.provisioned_slots()
        with pytest.raises(WorkerGone):
            fs.sup.send(victim, {"event": "ping"})
        assert fs.retire_worker(victim) is True     # idempotent
        # the survivor still serves, and shutdown stays clean
        assert fs.solve(freq(cx=0.12, steps=STEPS + 1),
                        timeout=120).steps_done == STEPS + 1
        assert fs.stop()
    c = counters(reg)
    assert c["fleet_worker_retirements_total{outcome=clean}"] == 1
    assert reg.snapshot()["gauges"]["fleet_pool_size"] == 1.0


def test_retire_drain_timeout_kills_and_replays_injected_clock():
    """The drain deadline on the supervisor's injectable clock: a
    worker pinned mid-compile cannot drain, the advanced clock expires
    the wait deterministically (no wall-clock flake), the worker is
    killed, and its in-flight request replays to the scale-up spawn —
    nothing lost."""
    reg = MetricsRegistry()
    with fleet(workers=1, registry=reg, max_replays=5) as fs:
        fut = fs.submit(freq(cx=0.21, steps=STEPS + 2))     # -> slot 0
        assert fs.add_worker() == 1     # the survivor-to-be
        time.sleep(0.2)                 # the dispatch is in the pipe
        t = [0.0]

        def clk():
            t[0] += 1000.0
            return t[0]

        fs.sup.clock = clk              # every wait expires immediately
        clean = fs.retire_worker(0, timeout=5.0)
        fs.sup.clock = None
        assert clean is False           # drain timed out -> killed
        res = fut.result(timeout=120)   # replayed, answered elsewhere
        assert res.steps_done == STEPS + 2
        assert fs.stop()
    c = counters(reg)
    assert c["fleet_worker_retirements_total{outcome=unclean}"] == 1
