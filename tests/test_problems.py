"""Problem registry (heat2d_tpu/problems/): the pluggable spatial-
operator axis — registry contract, per-family kernel/oracle parity,
analytic accuracy, capability gating, per-family stability bounds,
heat5 byte-identity pins, serve round-trips, replay back-compat, and
the problem-namespaced tune keys (ISSUE 17 acceptance criteria)."""

import numpy as np
import pytest

from heat2d_tpu import vocab
from heat2d_tpu.config import ConfigError, HeatConfig
from heat2d_tpu.problems import (FAMILY_SPECS, capability_matrix,
                                 family_names, get_family, spec_for)
from heat2d_tpu.problems import runners as prunners

from tests._pin import (assert_jaxpr_equal, batch_runner_jaxpr,
                        mesh_runner_jaxpr, solver_jaxpr)

FAMILIES = vocab.PROBLEMS
NEW_FAMILIES = tuple(f for f in FAMILIES if f != "heat5")


def small_state(nx=12, ny=12, seed=0):
    """A smooth positive O(1) field with a cold boundary ring — inside
    every family's stable regime (reactdiff's saturating source is
    bounded for any u >= 0; the ring matches the held-boundary
    semantics all families share)."""
    rng = np.random.default_rng(seed)
    u = rng.uniform(0.2, 1.0, (nx, ny)).astype(np.float32)
    return u


# --------------------------------------------------------------------- #
# registry contract
# --------------------------------------------------------------------- #

def test_registry_matches_vocabulary():
    assert family_names() == FAMILIES
    assert tuple(FAMILY_SPECS) == FAMILIES
    assert vocab.DEFAULT_PROBLEM == "heat5"


@pytest.mark.parametrize("fam", FAMILIES)
def test_family_ships_the_contract(fam):
    """Adding a family = one spec + the bound callables (registry
    module docstring) — every registered family carries all of them,
    with internally consistent declarations."""
    f = get_family(fam)
    s = f.spec
    assert f.name == fam == s.name
    assert callable(f.step) and callable(f.step_value)
    assert callable(f.scalars) and callable(f.np_step)
    assert s.halo_width >= 1
    assert s.min_grid == 2 * s.halo_width + 1
    assert s.state_arrays >= 1 and s.reads_per_step >= 1
    # scalar mapping arity matches the declared SMEM operand count
    import jax.numpy as jnp
    ops = f.scalars(jnp.asarray([0.1]), jnp.asarray([0.1]))
    assert len(ops) == s.n_scalars
    # explicit families name at least one kernel route; implicit
    # methods only appear on linear families (the ADI/MG gate)
    assert "jnp" in s.kernel_routes
    if not s.linear:
        assert not any(m in s.time_methods for m in
                       vocab.IMPLICIT_METHODS)


def test_capability_matrix_shape():
    m = capability_matrix()
    assert set(m) == set(FAMILIES)
    for fam, row in m.items():
        assert set(row) == {"time_methods", "kernel_routes", "abft",
                            "adjoint", "linear", "halo_width"}, fam
    # heat5 inherits every serve method; the nonlinear family's gate
    # reason NAMES the unsupported combination
    for method in vocab.SERVE_METHODS:
        ok, _ = spec_for("heat5").supports_method(method)
        assert ok, f"heat5 lost method {method}"
    ok, reason = spec_for("reactdiff").supports_method("adi")
    assert not ok and "reactdiff" in reason and "adi" in reason


# --------------------------------------------------------------------- #
# numpy-oracle parity + route agreement
# --------------------------------------------------------------------- #

@pytest.mark.parametrize("fam", FAMILIES)
def test_numpy_oracle_parity(fam):
    """family.step (the jnp reference kernel) tracks the float64 numpy
    golden oracle over a multi-step evolution."""
    import jax.numpy as jnp
    f = get_family(fam)
    u = small_state(nx=max(12, f.spec.min_grid), ny=12)
    uj, un = jnp.asarray(u), u.copy()
    for _ in range(10):
        uj = f.step(uj, 0.1, 0.12)
        un = f.np_step(un, 0.1, 0.12)
    np.testing.assert_allclose(np.asarray(uj), un, rtol=2e-5,
                               atol=2e-6)


@pytest.mark.parametrize("fam", NEW_FAMILIES)
def test_kernel_routes_agree(fam):
    """Every declared kernel route computes the same evolution: the
    value-form Pallas/band templates against the jnp reference (the
    two-kernel-forms contract the registry docstring pins)."""
    import jax.numpy as jnp
    f = get_family(fam)
    nx = max(16, f.spec.min_grid)
    b = 2
    u0 = jnp.asarray(np.stack([small_state(nx, 16, seed=i)
                               for i in range(b)]))
    cxs = jnp.asarray([0.1, 0.08], jnp.float32)
    cys = jnp.asarray([0.12, 0.1], jnp.float32)
    ref = prunners.fixed_runner(fam, "jnp")(u0, cxs, cys, steps=7)
    for route in f.spec.kernel_routes:
        if route == "jnp":
            continue
        out = prunners.fixed_runner(fam, route)(u0, cxs, cys, steps=7)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-6,
                                   err_msg=f"{fam}:{route}")


def test_heat9_analytic_mode_factor():
    """The 4th-order operator damps the lowest sine mode by EXACTLY
    ``1 - cx*lam4(kx) - cy*lam4(ky)`` in one step (the discrete sine
    is an eigenvector of the wide stencil at step 0) — the family's
    analytic accuracy oracle, checked in float64."""
    f = get_family("heat9")
    nx = ny = 17
    i = np.arange(nx)[:, None]
    j = np.arange(ny)[None, :]
    u = (np.sin(np.pi * i / (nx - 1))
         * np.sin(np.pi * j / (ny - 1))).astype(np.float64)
    cx, cy = 0.1, 0.12
    stepped = f.np_step(u, cx, cy)
    factor = f.mode_factor(nx, ny, cx, cy)
    c = (slice(2, -2), slice(2, -2))
    np.testing.assert_allclose(stepped[c], factor * u[c], rtol=1e-12)
    # 4th-order: lam4 approximates the continuous k^2 far better than
    # the 2nd-order 3-point eigenvalue does (the reason the family
    # exists) — accuracy, not just stability.
    k = np.pi / (nx - 1)
    lam4 = (30.0 - 32.0 * np.cos(k) + 2.0 * np.cos(2 * k)) / 12.0
    lam2 = 2.0 - 2.0 * np.cos(k)
    assert abs(lam4 - k * k) < abs(lam2 - k * k) / 50.0


def test_heat5_family_is_the_reference_kernel():
    """The heat5 entry binds the EXISTING kernels (no second copy of
    the hot math), and its band/pallas batch runners are literally the
    legacy ensemble runners (not generic twins)."""
    from heat2d_tpu.models import ensemble
    from heat2d_tpu.ops.stencil import stencil_step

    f = get_family("heat5")
    u = np.asarray(small_state())
    import jax.numpy as jnp
    np.testing.assert_array_equal(
        np.asarray(f.step(jnp.asarray(u), 0.1, 0.1)),
        np.asarray(stencil_step(jnp.asarray(u), 0.1, 0.1)))
    for route in ("jnp", "pallas", "band"):
        assert prunners.fixed_runner("heat5", route) \
            is ensemble._BATCH_RUNNERS[route]


# --------------------------------------------------------------------- #
# heat5 byte-identity pins
# --------------------------------------------------------------------- #

def test_heat5_jaxpr_pins():
    """Naming problem='heat5' anywhere on the dispatch spine traces
    the SAME program as the pre-registry call shape — the solver, the
    serve batch runner, and the mesh-sharded runner are byte-identical
    (the aggressive-refactor safety anchor)."""
    assert_jaxpr_equal(solver_jaxpr(), solver_jaxpr(problem="heat5"),
                       label="solver")
    assert_jaxpr_equal(batch_runner_jaxpr(),
                       batch_runner_jaxpr(problem="heat5"),
                       label="batch_runner")
    assert_jaxpr_equal(mesh_runner_jaxpr(n_devices=2),
                       mesh_runner_jaxpr(n_devices=2, problem="heat5"),
                       label="mesh_runner")


def test_heat5_band_runner_jaxpr_pin():
    """The batched band runner's program with the problem axis named
    vs not — the HBM-sized serve kernel path stays byte-identical."""
    assert_jaxpr_equal(
        batch_runner_jaxpr(nx=64, ny=128, steps=10, method="band"),
        batch_runner_jaxpr(nx=64, ny=128, steps=10, method="band",
                           problem="heat5"),
        label="band_runner")


# --------------------------------------------------------------------- #
# capability gating + stability bounds at validation
# --------------------------------------------------------------------- #

def test_pick_route_enforces_matrix():
    assert prunners.pick_route("heat5", "auto", 16, 16) in ("pallas",
                                                           "band")
    assert prunners.pick_route("varcoef", "auto", 16, 16) == "jnp"
    with pytest.raises(ConfigError, match="varcoef"):
        prunners.pick_route("varcoef", "band", 16, 16)
    with pytest.raises(ConfigError, match="reactdiff"):
        prunners.pick_route("reactdiff", "adi", 16, 16)


@pytest.mark.parametrize("fam", NEW_FAMILIES)
def test_config_accepts_each_family_serial(fam):
    cfg = HeatConfig(nxprob=max(12, spec_for(fam).min_grid),
                     nyprob=12, steps=4, problem=fam)
    assert cfg.problem == fam


def test_config_rejects_with_named_bounds():
    # heat9: tighter diffusion box, the 16/3 worst eigenvalue
    with pytest.raises(ConfigError, match=r"0\.375"):
        HeatConfig(nxprob=12, nyprob=12, steps=4, problem="heat9",
                   cx=0.2, cy=0.2)
    # advdiff: the cell-Reynolds bound names v^2 <= 2c
    with pytest.raises(ConfigError, match="cell-Reynolds"):
        HeatConfig(nxprob=12, nyprob=12, steps=4, problem="advdiff",
                   cx=0.001, cy=0.1)
    # halo-width floor: heat9 needs 5x5
    with pytest.raises(ConfigError, match="at least 5x5"):
        HeatConfig(nxprob=4, nyprob=12, steps=4, problem="heat9")
    # implicit methods stay heat5-only (the linearity gate)
    with pytest.raises(ConfigError, match="heat9"):
        HeatConfig(nxprob=12, nyprob=12, steps=4, problem="heat9",
                   method="adi")
    # non-heat5 families run the serial solver mode only
    with pytest.raises(ConfigError, match="serial"):
        HeatConfig(nxprob=12, nyprob=12, steps=4, problem="advdiff",
                   mode="dist2d", gridx=2, gridy=2)
    with pytest.raises(ConfigError, match="must be one of"):
        HeatConfig(nxprob=12, nyprob=12, steps=4, problem="heat7")


# --------------------------------------------------------------------- #
# serve round-trips + back-compat
# --------------------------------------------------------------------- #

def test_serve_roundtrip_every_family():
    """One request per family through the real server path: admitted,
    bucketed, launched, answered finite — and the reactdiff x adi
    combination is a structured rejection naming the combination."""
    from heat2d_tpu.obs import MetricsRegistry
    from heat2d_tpu.serve import Rejected, SolveRequest, SolveServer

    registry = MetricsRegistry()
    with SolveServer(registry=registry, max_delay=0.02) as server:
        for fam in NEW_FAMILIES:
            nx = max(16, spec_for(fam).min_grid)
            r = server.solve(SolveRequest(nx=nx, ny=16, steps=5,
                                          cx=0.1, cy=0.1, method="jnp",
                                          problem=fam), timeout=120)
            u = np.asarray(r.u)
            assert u.shape == (nx, 16) and np.isfinite(u).all(), fam
        with pytest.raises(Rejected) as ei:
            server.solve(SolveRequest(nx=16, ny=16, steps=5,
                                      method="adi",
                                      problem="reactdiff"), timeout=60)
        assert ei.value.code == "unsupported_combination"
        assert "reactdiff" in ei.value.message
    snap = registry.snapshot()
    for fam in NEW_FAMILIES:
        key = "problem_requests_total{problem=%s}" % fam
        assert snap["counters"].get(key, 0) >= 1, key


def test_serve_signature_and_hash_carry_problem():
    from heat2d_tpu.serve import SolveRequest

    a = SolveRequest(nx=16, ny=16, steps=5, method="jnp")
    b = SolveRequest(nx=16, ny=16, steps=5, method="jnp",
                     problem="heat9")
    assert a.signature() != b.signature()
    assert a.content_hash() != b.content_hash()
    # problem rides at index 8, after the legacy 8-tuple — which heat5
    # keeps byte-identical (hashes, rendezvous routing, trace
    # campaigns, tune consults are untouched by the registry)
    assert len(a.signature()) == 8 and "problem" not in a.spec()
    assert b.signature()[8] == "heat9"
    assert a.signature() == b.signature()[:8]


def test_replay_parses_both_signature_generations():
    """Pre-registry trace campaigns recorded 8-tuple solve signatures:
    they replay as heat5; current 9-tuples carry the family."""
    import random

    from heat2d_tpu.load.replay import spec_from_signature

    rng = random.Random(0)
    legacy = (20, 24, 8, "float32", "jnp", False, 0, 0.0)
    kind, spec = spec_from_signature(legacy, rng)
    assert kind == "solve"
    assert "problem" not in spec      # heat5 spec stays byte-identical
    kind, spec = spec_from_signature(legacy + ("advdiff",), rng)
    assert kind == "solve" and spec["problem"] == "advdiff"
    kind, spec = spec_from_signature(legacy + ("heat5",), rng)
    assert "problem" not in spec
    with pytest.raises(ValueError, match="malformed"):
        spec_from_signature(legacy[:7], rng)
    with pytest.raises(ValueError, match="malformed"):
        spec_from_signature(legacy + ("advdiff", "extra"), rng)


def test_mesh_scheduler_problem_routing():
    """The resource model prices a member by its declared state-array
    count, and oversized non-heat5 members route single (the spatial
    decomposition is heat5-only) — served, never rejected."""
    from heat2d_tpu.mesh.scheduler import MeshScheduler, grid_bytes
    from heat2d_tpu.serve import SolveRequest

    assert grid_bytes(16, 16, problem="varcoef") == \
        3 * grid_bytes(16, 16)
    sched = MeshScheduler(n_devices=2, spatial_bytes_threshold=1024)
    big = SolveRequest(nx=64, ny=64, steps=2, method="jnp",
                       problem="heat9")
    d = sched.decide(big)
    assert d["route"] == "single"
    assert d["reason"] == "problem_spatial"
    small = SolveRequest(nx=12, ny=12, steps=2, method="jnp",
                         problem="heat9")
    assert sched.decide(small)["route"] == "batch"


def test_mesh_runner_serves_families():
    """The mesh-sharded runner advances a non-heat5 family identically
    to the single-chip batch runner (whole members shard, so the wrap
    is family-independent), and the ABFT gate rejects non-heat5
    arming with the declared reason."""
    import jax.numpy as jnp

    from heat2d_tpu.mesh.runner import mesh_batch_runner
    from heat2d_tpu.models import ensemble

    run = mesh_batch_runner(12, 12, 5, "jnp", n_devices=2,
                            problem="advdiff")
    u0 = jnp.asarray(np.stack([small_state(seed=i) for i in range(2)]))
    cxs = jnp.asarray([0.1, 0.08], jnp.float32)
    cys = jnp.asarray([0.12, 0.1], jnp.float32)
    got = np.asarray(run(u0, cxs, cys))
    want = np.asarray(ensemble.batch_runner(
        12, 12, 5, "jnp", problem="advdiff")(u0, cxs, cys))
    np.testing.assert_array_equal(got, want)
    with pytest.raises(ValueError, match="reactdiff"):
        mesh_batch_runner(12, 12, 5, "jnp", n_devices=2, abft=True,
                          problem="reactdiff")


# --------------------------------------------------------------------- #
# tune keys + roofline resource model
# --------------------------------------------------------------------- #

def test_tune_keys_namespace_families():
    from heat2d_tpu.tune.space import Problem

    legacy = Problem(64, 128)
    assert legacy.key() == "64x128:float32"
    fam = Problem(64, 128, problem="heat9")
    assert fam.key() == "heat9:64x128:float32"
    rt = Problem.from_key(fam.key())
    assert (rt.nx, rt.ny, rt.problem) == (64, 128, "heat9")
    assert Problem.from_key("64x128:float32").problem == "heat5"
    # the adi:/fused: namespaces must NOT parse as problem keys
    with pytest.raises(ValueError):
        Problem.from_key("adi:64x128:float32")


def test_roofline_bytes_model_per_family():
    """varcoef streams its two coefficient fields beside the state
    (3x the jnp-route traffic); the calibrated bound stays heat5-only
    (honestly absent elsewhere)."""
    from heat2d_tpu.obs import roofline

    base = roofline.analytic_bytes_per_cell_step(
        64, 64, method="jnp", problem="heat5")
    var = roofline.analytic_bytes_per_cell_step(
        64, 64, method="jnp", problem="varcoef")
    assert var["bytes_per_cell_step"] == \
        pytest.approx(2.0 * base["bytes_per_cell_step"])
    row = {}
    roofline.stamp_launch_row(row, None, nx=16, ny=16, steps=5,
                              members=2, elapsed_s=0.01, method="jnp",
                              signature="sig", problem="advdiff")
    assert row["perf"]["bound_mcells_per_s"] is None
    assert row["perf"]["pct_of_bound"] is None
    assert row["perf"]["bytes_per_cell_step"] > 0
