"""N-process-save → M-process-restore: the reshard contract.

A collective checkpoint commits the FULL grid through the same
crash-consistent single-file path as every other checkpoint
(io/binary.py), so the saving and restoring process counts are
independent — each restoring process loads the full grid and slices
its own slab (dist/exchange.run_process_slab's ``u0`` contract).
These tests pin it BITWISE both ways (2-save → 1-restore and
1-save → 2-restore) against an uninterrupted single-process run,
with real processes; they need rendezvous + the coordination-service
KV store only, so they run on plain CPU builds where cross-process
XLA collectives are unavailable.
"""

import sys

import numpy as np
import pytest

from heat2d_tpu.dist.exchange import run_process_slab
from heat2d_tpu.dist.harness import (
    clean_env, rendezvous_unsupported_reason, spawn_world)
from heat2d_tpu.io import load_checkpoint

NX, NY, SEG = 32, 24, 4
HALF, FULL = 8, 16


@pytest.fixture(autouse=True)
def _require_rendezvous():
    reason = rendezvous_unsupported_reason()
    if reason is not None:
        pytest.skip(f"2-process rendezvous unavailable: {reason}")


def _worker_argv(extra):
    def argv_fn(i, coord):
        return [sys.executable, "-m", "heat2d_tpu.dist.cli",
                "--coordinator", coord,
                "--num-processes", "2", "--process-id", str(i),
                "--nx", str(NX), "--ny", str(NY),
                "--segment", str(SEG)] + extra
    return argv_fn


def _spawn2(extra):
    results = spawn_world(
        2, _worker_argv(extra),
        env=clean_env({"JAX_PLATFORMS": "cpu"}), timeout=300)
    assert all(r.ok for r in results), [r.output for r in results]


def _run1(extra):
    results = spawn_world(
        1, lambda i, coord: [
            sys.executable, "-m", "heat2d_tpu.dist.cli",
            "--num-processes", "1",
            "--nx", str(NX), "--ny", str(NY),
            "--segment", str(SEG)] + extra,
        env=clean_env({"JAX_PLATFORMS": "cpu"}), timeout=300)
    assert all(r.ok for r in results), [r.output for r in results]


def _reference():
    ref, _ = run_process_slab(NX, NY, FULL, depth=SEG)
    return np.asarray(ref, np.float32)


def test_two_process_save_one_process_restore(tmp_path):
    ck = tmp_path / "ck.bin"
    out = tmp_path / "final.bin"
    _spawn2(["--steps", str(HALF),
             "--checkpoint", str(ck), "--checkpoint-every", str(SEG)])
    grid, step, cfg = load_checkpoint(str(ck))
    assert step == HALF and grid.shape == (NX, NY)
    assert cfg["processes"] == 2

    _run1(["--steps", str(FULL), "--resume", str(ck),
           "--out", str(out)])
    got = np.fromfile(out, np.float32).reshape(NX, NY)
    assert got.tobytes() == _reference().tobytes()


def test_one_process_save_two_process_restore(tmp_path):
    ck = tmp_path / "ck.bin"
    out = tmp_path / "final.bin"
    _run1(["--steps", str(HALF),
           "--checkpoint", str(ck), "--checkpoint-every", str(SEG)])
    grid, step, cfg = load_checkpoint(str(ck))
    assert step == HALF and cfg["processes"] == 1

    _spawn2(["--steps", str(FULL), "--resume", str(ck),
             "--out", str(out)])
    got = np.fromfile(out, np.float32).reshape(NX, NY)
    assert got.tobytes() == _reference().tobytes()
