/* Independent C oracle for parity testing (NOT copied from the reference —
 * written from the behavioral spec in SURVEY.md §1/Appendix B):
 *
 *   - f32 storage, two planes, functional swap
 *   - init u[ix][iy] = ix*(nx-ix-1)*iy*(ny-iy-1)
 *   - per step, interior only:
 *       u' = u + CX*(uE + uW - 2u) + CY*(uN + uS - 2u)
 *     with CX/CY/2.0 as *double* literals, so C promotes each cell update
 *     through double and truncates to f32 on store — the exact numeric
 *     semantics of the reference's CPU variants.
 *
 * Usage: c_oracle NX NY STEPS OUT.bin [CX CY]
 * (raw little-endian f32, row-major; CX/CY default 0.1. As doubles they
 * reproduce the promotion semantics of the reference's double literals.)
 */
#include <stdio.h>
#include <stdlib.h>

int main(int argc, char **argv) {
    if (argc != 5 && argc != 7) return 2;
    int nx = atoi(argv[1]), ny = atoi(argv[2]), steps = atoi(argv[3]);
    double CX = argc == 7 ? atof(argv[5]) : 0.1;
    double CY = argc == 7 ? atof(argv[6]) : 0.1;
    float *a = malloc((size_t)nx * ny * sizeof(float));
    float *b = malloc((size_t)nx * ny * sizeof(float));
    if (!a || !b) return 3;

    for (int ix = 0; ix < nx; ix++)
        for (int iy = 0; iy < ny; iy++)
            a[ix * ny + iy] =
                (float)(ix * (nx - ix - 1)) * (float)(iy * (ny - iy - 1));
    for (int i = 0; i < nx * ny; i++) b[i] = 0.0f;
    /* boundary rows/cols of b stay 0 == a's boundary (init is 0 there) */

    float *src = a, *dst = b;
    for (int k = 0; k < steps; k++) {
        for (int ix = 1; ix < nx - 1; ix++)
            for (int iy = 1; iy < ny - 1; iy++)
                dst[ix * ny + iy] = src[ix * ny + iy]
                    + CX * (src[(ix + 1) * ny + iy] + src[(ix - 1) * ny + iy]
                            - 2.0 * src[ix * ny + iy])
                    + CY * (src[ix * ny + iy + 1] + src[ix * ny + iy - 1]
                            - 2.0 * src[ix * ny + iy]);
        float *t = src; src = dst; dst = t;
    }

    FILE *f = fopen(argv[4], "wb");
    if (!f) return 4;
    fwrite(src, sizeof(float), (size_t)nx * ny, f);
    fclose(f);
    return 0;
}
