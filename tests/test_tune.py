"""Autotune subsystem tests (heat2d_tpu/tune/, docs/TUNING.md).

The load-bearing guarantees:

- the db lookup ladder: exact hit -> nearest-shape flagged -> None;
- NO tuning db => the band planners and batched runners trace programs
  byte-identical to a build without the subsystem (jaxpr-pinned);
- a db entry present => the tuned (bm, T, route) steers the plan and
  surfaces in run-record ``tuned_config`` provenance;
- corrupt/torn/salt-stale dbs degrade to "no db" with a warning, never
  a crash;
- probe mode restores the VMEM limit on every exit path;
- the HEAT2D_VMEM_BUDGET env override and budget-source provenance;
- the simulated search end to end: db written, resume is a pure cache
  hit, frontier table matches the stored entries.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import heat2d_tpu.ops.pallas_stencil as ps
from tests._pin import (assert_jaxpr_differs, assert_jaxpr_equal,
                        band_runner_jaxpr, jaxpr_text)
from heat2d_tpu.tune import runtime as tr
from heat2d_tpu.tune.cli import frontier_table, search_problem
from heat2d_tpu.tune.db import TuningDB
from heat2d_tpu.tune.measure import (SimulatedBackend, classify_failure,
                                     measure_candidate, probe_limits)
from heat2d_tpu.tune.space import Candidate, Problem, candidate_space


@pytest.fixture(autouse=True)
def _no_db():
    """Every test starts and ends with no tuning db active."""
    tr.set_tuning_db(None)
    yield
    tr.set_tuning_db(None)


def make_db(path, entries, kind="cpu", salt=None, stamp=None):
    """A db file with pre-stamped best entries:
    entries = {"64x64:float32": {"route": "C", "bm": 16, "tsteps": 4,
               "mcells": 123.0}}"""
    db = TuningDB(str(path))
    for key, e in entries.items():
        db.set_best(kind, key,
                    {"route": e["route"], "bm": e["bm"],
                     "tsteps": e["tsteps"]},
                    e.get("mcells", 100.0), {"protocol": "test"})
        if salt is not None:
            db.data["devices"][kind]["entries"][key]["salt"] = salt
    if stamp:
        db.stamp_device(kind, **stamp)
    db.save()
    return db


# --------------------------------------------------------------------- #
# Candidate space
# --------------------------------------------------------------------- #

def test_candidate_space_respects_band_rules():
    cands, pruned = candidate_space(Problem(4096, 4096),
                                    assume_tpu=True)
    assert cands, "empty candidate space"
    for c in cands:
        if c.route == "vmem":
            continue
        assert c.bm % 8 == 0, c            # Mosaic sublane rule
        assert c.bm > 2 * c.tsteps, c      # amortizable band core
        if c.route == "C2":
            assert c.tsteps % 8 == 0, c    # window alignment gate
    # The resource model pruned something and said why.
    assert pruned
    assert all(reason for _, reason in pruned)


def test_candidate_space_prunes_over_envelope():
    cands, pruned = candidate_space(Problem(4096, 8192),
                                    assume_tpu=True)
    est_limit = ps.vmem_hard_limit_bytes()
    for c in cands:
        if c.route in ("C", "C2"):
            # the band kernels' working-set expression; the adi
            # routes carry their own panel estimate (3*nx*bn)
            assert 5 * (c.bm + 2 * c.tsteps) * 8192 * 4 <= est_limit
        elif c.route.startswith("adi"):
            assert 3 * 4096 * c.bm * 4 <= est_limit
    # probe_past_envelope keeps the rejects measurable.
    cands2, _ = candidate_space(Problem(4096, 8192), assume_tpu=True,
                                probe_past_envelope=True)
    assert len(cands2) > len(cands)


def test_candidate_space_includes_planner_picks():
    p = Problem(4096, 4096)
    cands, _ = candidate_space(p, assume_tpu=True)
    plan_bm = ps.plan_bands(p.nx, p.ny)[0]
    assert any(c.bm == plan_bm for c in cands if c.route == "C")


# --------------------------------------------------------------------- #
# Measurement library
# --------------------------------------------------------------------- #

def test_simulated_backend_deterministic_and_classified():
    b = SimulatedBackend()
    p = Problem(4096, 4096)
    ok = measure_candidate(p, Candidate("C2", 144, 16), backend=b)
    assert ok.status == "ok"
    assert ok.step_time_s == measure_candidate(
        p, Candidate("C2", 144, 16), backend=b).step_time_s
    oom = measure_candidate(p, Candidate("C", 320, 16), backend=b)
    assert oom.status == "oom"
    wide = Problem(4096, 8192)         # 32 KB rows: C2 envelope = 64
    # bm=56, T=8: 72 ext rows — under the working-set limit but over
    # the probed window envelope, the compile-error class.
    ce = measure_candidate(wide, Candidate("C2", 56, 8), backend=b)
    assert ce.status == "compile_error"


def test_classify_failure_maps_config_error_to_oom():
    from heat2d_tpu.config import ConfigError
    assert classify_failure(ConfigError("needs ~20 MB of VMEM")) == "oom"
    assert classify_failure(RuntimeError("Mosaic lowering bug")) \
        == "compile_error"
    assert classify_failure(RuntimeError("flaky tunnel")) == "error"


def test_probe_limits_restores_on_exception():
    before = (ps.VMEM_HARD_LIMIT_BYTES, ps.VMEM_LIMIT_ORIGIN,
              ps.VMEM_BUDGET_SOURCE)
    with pytest.raises(ValueError):
        with probe_limits("test probe"):
            assert ps.VMEM_HARD_LIMIT_BYTES == 10 ** 9
            assert ps.VMEM_BUDGET_SOURCE == "probe"
            raise ValueError("boom")
    assert (ps.VMEM_HARD_LIMIT_BYTES, ps.VMEM_LIMIT_ORIGIN,
            ps.VMEM_BUDGET_SOURCE) == before


# --------------------------------------------------------------------- #
# The db: persistence, corruption, salt
# --------------------------------------------------------------------- #

def test_db_roundtrip_atomic(tmp_path):
    path = tmp_path / "db.json"
    db = TuningDB(str(path))
    db.record_point("cpu", "64x64:float32",
                    {"route": "C", "bm": 16, "tsteps": 4,
                     "status": "ok", "step_time_s": 1e-6,
                     "mcells_per_s": 100.0})
    db.set_best("cpu", "64x64:float32",
                {"route": "C", "bm": 16, "tsteps": 4}, 100.0, {})
    db.save()
    assert path.exists()
    assert not (tmp_path / "db.json.tmp").exists()   # no torn staging
    again = TuningDB(str(path))
    assert again.entry("cpu", "64x64:float32")["best"]["bm"] == 16


def test_corrupt_db_ignored_with_warning(tmp_path, caplog):
    path = tmp_path / "db.json"
    path.write_text("{ torn json!!")
    with caplog.at_level("WARNING", logger="heat2d_tpu.tune"):
        db = TuningDB(str(path))
    assert db.corrupt
    assert any("corrupt" in r.message for r in caplog.records)
    assert db.lookup("cpu", 64, 64) is None          # degrades, no crash
    # And through the runtime hook: active but useless, never fatal.
    tr.set_tuning_db(db)
    assert tr.band_config(64, 64) is None
    # A save against the unreadable file moves the original ASIDE
    # instead of silently destroying it (it may not be a db at all).
    db.save()
    assert (tmp_path / "db.json.corrupt").read_text() == "{ torn json!!"
    assert TuningDB(str(path)).corrupt is False      # fresh db readable


def test_salt_mismatch_invisible(tmp_path):
    make_db(tmp_path / "db.json",
            {"64x64:float32": {"route": "C", "bm": 16, "tsteps": 4}},
            salt="stale-salt")
    db = TuningDB(str(tmp_path / "db.json"))
    assert db.entry("cpu", "64x64:float32") is None
    assert db.lookup("cpu", 64, 64) is None
    # Unsalted read still sees it (export/inspection path).
    assert db.entry("cpu", "64x64:float32", salted=False) is not None


# --------------------------------------------------------------------- #
# The lookup ladder
# --------------------------------------------------------------------- #

def test_lookup_exact_hit(tmp_path):
    db = make_db(tmp_path / "db.json",
                 {"64x64:float32": {"route": "C", "bm": 16,
                                    "tsteps": 4}})
    cfg = db.lookup("cpu", 64, 64)
    assert cfg is not None and cfg.source == "exact"
    assert (cfg.route, cfg.bm, cfg.tsteps) == ("C", 16, 4)
    assert cfg.matched_key == "64x64:float32"


def test_lookup_nearest_is_flagged(tmp_path):
    db = make_db(tmp_path / "db.json",
                 {"64x64:float32": {"route": "C", "bm": 16,
                                    "tsteps": 4}})
    cfg = db.lookup("cpu", 96, 64)       # same width, nearby height
    assert cfg is not None
    assert cfg.source == "nearest"
    assert cfg.matched_key == "64x64:float32"
    # Too far away (beyond the 4x log-distance): no match at all.
    assert db.lookup("cpu", 64, 4096) is None
    # dtype never crosses.
    assert db.lookup("cpu", 64, 64, "bfloat16") is None


def test_lookup_missing_db_is_none(tmp_path):
    db = TuningDB(str(tmp_path / "absent.json"))
    assert db.lookup("cpu", 64, 64) is None


# --------------------------------------------------------------------- #
# Runtime hook: fallback parity and tuned steering
# --------------------------------------------------------------------- #

def test_resolve_bands_without_db_is_plan_bands():
    for m, n in ((64, 64), (100, 128), (1000, 512)):
        assert ps._resolve_bands(m, n, jnp.float32, None) \
            == ps.plan_bands(m, n, jnp.float32)


def test_band_chunk_jaxpr_identical_without_db(monkeypatch):
    """The acceptance pin: with no tuning db, band_chunk traces the
    SAME program as a build without the tune subsystem (hook forced
    off)."""
    monkeypatch.setattr(ps, "VMEM_BUDGET_BYTES", 256 * 1024)  # band route
    u = jnp.zeros((64, 128), jnp.float32)
    with_hook = jaxpr_text(lambda v: ps.band_chunk(v, 20, 0.1, 0.1), u)
    monkeypatch.setattr(ps, "_tuned_band_config",
                        lambda *a, **k: None)
    without = jaxpr_text(lambda v: ps.band_chunk(v, 20, 0.1, 0.1), u)
    assert_jaxpr_equal(with_hook, without,
                       label="band_chunk (db hook vs none)")


def test_batched_band_runner_jaxpr_identical_without_db(monkeypatch):
    """The serve compile cache's kernel path (ensemble batched band
    runner) is likewise pinned when no db is active."""
    monkeypatch.setattr(ps, "VMEM_BUDGET_BYTES", 256 * 1024)
    with_hook = band_runner_jaxpr(64, 128, 10, b=2)
    monkeypatch.setattr(ps, "_tuned_band_config",
                        lambda *a, **k: None)
    without = band_runner_jaxpr(64, 128, 10, b=2)
    assert_jaxpr_equal(with_hook, without,
                       label="batched band runner (db hook vs none)")


def test_db_entry_steers_band_chunk(tmp_path, monkeypatch):
    """With an entry present the tuned (bm, T) is used — the traced
    program changes shape — and the result stays bitwise identical
    (band height never changes values, only scheduling)."""
    monkeypatch.setattr(ps, "VMEM_BUDGET_BYTES", 256 * 1024)
    u = jnp.asarray(np.linspace(0, 1, 64 * 128, dtype=np.float32)
                    .reshape(64, 128))
    fn = jax.jit(lambda v: ps.band_chunk(v, 20, 0.1, 0.1))
    base_jaxpr = jaxpr_text(fn, u)
    base_out = np.asarray(fn(u))

    make_db(tmp_path / "db.json",
            {"64x128:float32": {"route": "C", "bm": 24, "tsteps": 4}})
    tr.set_tuning_db(str(tmp_path / "db.json"))
    tuned = ps._resolve_bands(64, 128, jnp.float32, None)
    assert tuned == (24, 72)             # tuned bm, ceil-padded rows
    tuned_jaxpr = jaxpr_text(lambda v: ps.band_chunk(v, 20, 0.1, 0.1),
                             u)
    assert_jaxpr_differs(tuned_jaxpr, base_jaxpr,
                         label="tuned band plan")  # plan actually moved
    out = np.asarray(jax.jit(
        lambda v: ps.band_chunk(v, 20, 0.1, 0.1))(u))
    np.testing.assert_array_equal(out, base_out)
    # Provenance recorded for run records.
    applied = tr.applied_configs()
    assert applied and applied[0]["bm"] == 24
    assert applied[0]["source"] == "exact"


def test_invalid_db_entry_falls_back(tmp_path):
    """Entries that fail the live resource model degrade to the
    heuristic: a misaligned bm, and a bm too large for the hard
    limit."""
    make_db(tmp_path / "db.json",
            {"64x128:float32": {"route": "C", "bm": 20, "tsteps": 4},
             "64x256:float32": {"route": "C", "bm": 99992,
                                "tsteps": 4},
             # bm=80 at 32 KB rows fits its own T=4 (~14.4 MB) but NOT
             # the DEFAULT_TSTEPS=8 its _resolve_bands consumers run
             # at (~15.7 MB) — must fall back, not crash downstream
             # _check_band_vmem (review r6).
             "4096x8192:float32": {"route": "C", "bm": 80,
                                   "tsteps": 4}})
    tr.set_tuning_db(str(tmp_path / "db.json"))
    assert tr.band_config(64, 128) is None         # bm % 8
    assert tr.band_config(64, 256) is None         # over the limit
    assert tr.band_config(4096, 8192) is None      # over at caller's T
    assert ps._resolve_bands(64, 128, jnp.float32, None) \
        == ps.plan_bands(64, 128, jnp.float32)
    assert ps._resolve_bands(4096, 8192, jnp.float32, None) \
        == ps.plan_bands(4096, 8192, jnp.float32)


def test_c2_entry_degrades_to_legacy_off_tpu(tmp_path):
    """A TPU-tuned C2 entry consulted off-TPU (window route not
    viable) degrades to route C with the same knobs, not to a crash."""
    make_db(tmp_path / "db.json",
            {"64x128:float32": {"route": "C2", "bm": 24, "tsteps": 8}})
    tr.set_tuning_db(str(tmp_path / "db.json"))
    cfg = tr.band_config(64, 128)
    assert cfg is not None and cfg.route == "C"
    assert (cfg.bm, cfg.tsteps) == (24, 8)


def test_allow_window_relabels_c2_for_legacy_consumers(tmp_path,
                                                       monkeypatch):
    """A legacy-only consumer (parity step form, _resolve_bands) must
    get — and record — route C even where the window route IS viable:
    provenance describes the program that actually compiles."""
    make_db(tmp_path / "db.json",
            {"64x128:float32": {"route": "C2", "bm": 24, "tsteps": 8}})
    tr.set_tuning_db(str(tmp_path / "db.json"))
    monkeypatch.setattr(ps, "window_band_viable",
                        lambda *a, **k: True)
    assert tr.band_config(64, 128).route == "C2"
    tr.reset_applied()
    cfg = tr.band_config(64, 128, allow_window=False)
    assert cfg.route == "C" and cfg.bm == 24
    assert tr.applied_configs()[0]["route"] == "C"


def test_env_var_activates_db(tmp_path, monkeypatch):
    make_db(tmp_path / "db.json",
            {"64x128:float32": {"route": "C", "bm": 24, "tsteps": 4}})
    monkeypatch.setenv(tr.ENV_VAR, str(tmp_path / "db.json"))
    assert tr.active_db() is not None
    assert tr.band_config(64, 128).bm == 24
    monkeypatch.delenv(tr.ENV_VAR)
    assert tr.active_db() is None


# --------------------------------------------------------------------- #
# VMEM budget: env override + source provenance
# --------------------------------------------------------------------- #

@pytest.fixture
def _budget_state(monkeypatch):
    monkeypatch.setattr(ps, "VMEM_BUDGET_BYTES", None)
    monkeypatch.setattr(ps, "VMEM_HARD_LIMIT_BYTES", None)
    monkeypatch.setattr(ps, "VMEM_LIMIT_ORIGIN", None)
    monkeypatch.setattr(ps, "VMEM_BUDGET_SOURCE", "default")
    monkeypatch.setattr(ps, "_env_budget_checked", False)
    yield monkeypatch


def test_env_vmem_budget_honored(_budget_state):
    _budget_state.setenv("HEAT2D_VMEM_BUDGET", "32")
    assert ps.vmem_budget_bytes() == 16 * 1024 * 1024   # total // 2
    assert ps.vmem_hard_limit_bytes() == 30 * 1024 * 1024
    assert ps.vmem_budget_source() == "env"


def test_env_vmem_budget_bad_value_is_config_error(_budget_state):
    from heat2d_tpu.config import ConfigError
    _budget_state.setenv("HEAT2D_VMEM_BUDGET", "not-a-number")
    with pytest.raises(ConfigError):
        ps.vmem_budget_bytes()
    # EVERY query raises — a typo'd cap must not raise once and then
    # silently serve the default as if the override were applied.
    with pytest.raises(ConfigError):
        ps.vmem_budget_bytes()


def test_vmem_budget_source_default_and_flag(_budget_state):
    assert ps.vmem_budget_source() == "default"
    ps.set_vmem_budget(32 * 1024 * 1024)
    assert ps.vmem_budget_source() == "flag"


def test_probe_limits_with_env_budget(_budget_state):
    """The env override must not fire MID-probe (un-lifting the limit),
    and after the probe the env's limit/source must be fully in force."""
    _budget_state.setenv("HEAT2D_VMEM_BUDGET", "16")
    with probe_limits("test probe"):
        # First budget query happens inside the probe window: the hard
        # limit must stay lifted, not snap to the env-derived 14 MB.
        assert ps.vmem_hard_limit_bytes() == 10 ** 9
    assert ps.vmem_hard_limit_bytes() == 14 * 1024 * 1024
    assert ps.vmem_budget_source() == "env"


def test_db_vmem_stamp_applies_as_budget(tmp_path, _budget_state):
    make_db(tmp_path / "db.json", {},
            stamp={"vmem_total_bytes": 24 * 1024 * 1024})
    tr.set_tuning_db(str(tmp_path / "db.json"))
    assert ps.vmem_budget_bytes() == 12 * 1024 * 1024
    assert ps.vmem_budget_source() == "db"


def test_flag_beats_db_vmem_stamp(tmp_path, _budget_state):
    ps.set_vmem_budget(32 * 1024 * 1024)
    make_db(tmp_path / "db.json", {},
            stamp={"vmem_total_bytes": 24 * 1024 * 1024})
    tr.set_tuning_db(str(tmp_path / "db.json"))
    assert ps.vmem_budget_bytes() == 16 * 1024 * 1024
    assert ps.vmem_budget_source() == "flag"


# --------------------------------------------------------------------- #
# Search end to end (simulated backend)
# --------------------------------------------------------------------- #

def test_search_resumes_as_pure_cache_hit(tmp_path):
    backend = SimulatedBackend()
    path = str(tmp_path / "db.json")
    import io
    s1 = search_problem(TuningDB(path), Problem(4096, 4096),
                        backend=backend, probe_past_envelope=True,
                        out=io.StringIO())
    assert s1["measured"] > 0 and s1["best"] is not None
    assert s1["failed"] > 0              # envelope failures captured
    s2 = search_problem(TuningDB(path), Problem(4096, 4096),
                        backend=backend, probe_past_envelope=True,
                        out=io.StringIO())
    assert s2["measured"] == 0           # pure cache hit
    assert s2["cached"] == s1["measured"] + s1["cached"]
    assert s2["best"] == s1["best"]


def test_plain_resume_never_clobbers_probed_measurements(tmp_path):
    """A plain run after --probe-past-envelope must not overwrite the
    probe's measured over-envelope points with prune notes."""
    backend = SimulatedBackend()
    path = str(tmp_path / "db.json")
    import io
    search_problem(TuningDB(path), Problem(4096, 4096),
                   backend=backend, probe_past_envelope=True,
                   out=io.StringIO())
    db = TuningDB(path)
    before = db.entry(backend.device_kind,
                      "4096x4096:float32")["points"]
    assert any(p["status"] == "oom" for p in before)  # rejects measured
    search_problem(TuningDB(path), Problem(4096, 4096),
                   backend=backend, out=io.StringIO())  # plain run
    after = TuningDB(path).entry(backend.device_kind,
                                 "4096x4096:float32")["points"]

    def by_key(points):
        return sorted(points, key=lambda p: (p["route"], p["bm"],
                                             p["tsteps"]))
    # Not a single point clobbered (re-recording an unchanged prune
    # note may reorder the list; content is what matters).
    assert by_key(after) == by_key(before)


def test_cli_rejects_bad_env_budget_at_startup(tmp_path, monkeypatch,
                                               capsys):
    from heat2d_tpu.cli import main
    monkeypatch.setenv("HEAT2D_VMEM_BUDGET", "16MiB")
    monkeypatch.setattr(ps, "_env_budget_checked", False)
    rc = main(["--mode", "serial", "--nxprob", "8", "--nyprob", "8",
               "--steps", "2", "--dat-layout", "none",
               "--outdir", str(tmp_path)])
    assert rc == 1
    assert "HEAT2D_VMEM_BUDGET" in capsys.readouterr().err
    # Nothing ran: no output artifacts were produced.
    assert not list(tmp_path.iterdir())


def test_search_then_lookup_roundtrip(tmp_path):
    """What the search stamps, the runtime hook serves."""
    backend = SimulatedBackend()
    path = str(tmp_path / "db.json")
    import io
    s = search_problem(TuningDB(path), Problem(4096, 4096),
                       backend=backend, out=io.StringIO())
    db = TuningDB(path)
    cfg = db.lookup(backend.device_kind, 4096, 4096)
    assert cfg is not None and cfg.source == "exact"
    assert cfg.bm == s["best"]["bm"]


def test_frontier_table_matches_entries(tmp_path):
    backend = SimulatedBackend()
    path = str(tmp_path / "db.json")
    import io
    search_problem(TuningDB(path), Problem(640, 512), backend=backend,
                   out=io.StringIO())
    db = TuningDB(path)
    table = frontier_table(db, backend.device_kind)
    best = db.entry(backend.device_kind, "640x512:float32")["best"]
    tagged = [ln for ln in table.splitlines() if "<-- best" in ln]
    # One best per FRONTIER: the single-chip shape entry plus the
    # fused-route and adi-route namespaces ("fused:640x512" /
    # "adi:640x512" — their own frontiers so global-mesh rates and
    # implicit per-step rates never contend with the single-chip
    # best).
    assert len(tagged) == 3
    plain = [ln for ln in tagged
             if ln.lstrip().startswith("640x512:")]
    assert len(plain) == 1 and best["route"] in plain[0]
    fused = [ln for ln in tagged if ln.lstrip().startswith("fused:")]
    assert len(fused) == 1 and "fused" in fused[0]
    adi = [ln for ln in tagged if ln.lstrip().startswith("adi:")]
    assert len(adi) == 1 and "adi" in adi[0]


def test_selftest_cli_idempotent(tmp_path, capsys):
    from heat2d_tpu.tune.cli import main
    rc = main(["--selftest", "--db", str(tmp_path / "db.json")])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "selftest passed" in out
    assert (tmp_path / "db.json").exists()
    # Idempotent: a second selftest against the same path cold-starts
    # (its invariants assume a fresh db) instead of failing spuriously.
    rc2 = main(["--selftest", "--db", str(tmp_path / "db.json")])
    out2 = capsys.readouterr().out
    assert rc2 == 0, out2


def test_tune_metrics_flow_through_registry(tmp_path):
    from heat2d_tpu.obs import MetricsRegistry
    reg = MetricsRegistry()
    import io
    search_problem(TuningDB(str(tmp_path / "db.json")),
                   Problem(640, 512), backend=SimulatedBackend(),
                   registry=reg, out=io.StringIO())
    snap = reg.snapshot()
    measured = [v for k, v in snap["counters"].items()
                if k.startswith("tune_points_measured_total")]
    assert measured and sum(measured) > 0
    assert any(k.startswith("tune_best_mcells_per_s")
               for k in snap["gauges"])
    assert "tune_measure_s" in snap["histograms"]


# --------------------------------------------------------------------- #
# Run-record + serve provenance
# --------------------------------------------------------------------- #

def test_cli_run_record_has_tuned_config(tmp_path, monkeypatch):
    """Acceptance: a CLI pallas run against a db entry surfaces the
    tuned config in the run record (and the vmem budget source)."""
    from heat2d_tpu.cli import main
    # Small enough that 64x128 is NOT VMEM-resident: the runner takes
    # the band route, where the tuning hook lives.
    monkeypatch.setattr(ps, "VMEM_BUDGET_BYTES", 64 * 1024)
    make_db(tmp_path / "db.json",
            {"64x128:float32": {"route": "C", "bm": 24, "tsteps": 4}})
    tr.set_tuning_db(str(tmp_path / "db.json"))
    rec_path = tmp_path / "rec.json"
    rc = main(["--mode", "pallas", "--nxprob", "64", "--nyprob", "128",
               "--steps", "24", "--dat-layout", "none",
               "--outdir", str(tmp_path),
               "--run-record", str(rec_path)])
    assert rc == 0
    rec = json.loads(rec_path.read_text())
    assert rec["vmem_budget"]["source"] in ("default", "flag", "env",
                                            "db", "probe")
    tuned = rec["tuned_config"]
    assert tuned and tuned[0]["bm"] == 24 and tuned[0]["route"] == "C"
    assert tuned[0]["source"] == "exact"


def test_cli_run_record_no_db_has_no_tuned_config(tmp_path):
    from heat2d_tpu.cli import main
    rec_path = tmp_path / "rec.json"
    rc = main(["--mode", "serial", "--nxprob", "16", "--nyprob", "16",
               "--steps", "4", "--dat-layout", "none",
               "--outdir", str(tmp_path),
               "--run-record", str(rec_path)])
    assert rc == 0
    rec = json.loads(rec_path.read_text())
    assert "tuned_config" not in rec
    assert "vmem_budget" in rec


def test_serve_engine_preresolves_tuned_config(tmp_path, monkeypatch):
    """The serve engine resolves the db's answer per signature before
    the first launch and logs it with every launch row."""
    from heat2d_tpu.serve.engine import EnsembleEngine
    from heat2d_tpu.serve.schema import SolveRequest
    monkeypatch.setattr(ps, "VMEM_BUDGET_BYTES", 1024)  # band method
    make_db(tmp_path / "db.json",
            {"24x128:float32": {"route": "C2", "bm": 24, "tsteps": 8}})
    tr.set_tuning_db(str(tmp_path / "db.json"))
    eng = EnsembleEngine(max_batch=4)
    req = SolveRequest(nx=24, ny=128, steps=4, cx=0.1, cy=0.1,
                       method="band")
    out = eng.solve_batch([req])
    assert len(out) == 1
    row = eng.launch_log[-1]
    assert row["tuned_config"] is not None
    assert row["tuned_config"]["bm"] == 24
    # The batched runner compiles the LEGACY band kernel; the record
    # reports the route actually in play, even for a C2-stamped entry.
    assert row["tuned_config"]["route"] == "C"
    assert eng.tuned[req.signature()]["bm"] == 24


def test_serve_engine_tuned_none_without_db(monkeypatch):
    from heat2d_tpu.serve.engine import EnsembleEngine
    from heat2d_tpu.serve.schema import SolveRequest
    eng = EnsembleEngine(max_batch=4)
    req = SolveRequest(nx=16, ny=24, steps=2, cx=0.1, cy=0.1,
                       method="jnp")
    eng.solve_batch([req])
    assert eng.launch_log[-1]["tuned_config"] is None


# --------------------------------------------------------------------- #
# fleet-wide db consolidation: TuningDB.merge + --merge CLI
# --------------------------------------------------------------------- #

def _point(route, bm, t, mcells=None, status="ok"):
    p = {"route": route, "bm": bm, "tsteps": t, "status": status}
    if mcells is not None:
        p["mcells_per_s"] = mcells
        p["step_time_s"] = 1.0 / mcells
    return p


def _worker_db(path, kind="cpu", points=(), best=None, ts="2026-01-01"):
    db = TuningDB(str(path))
    key = "64x64:float32"
    for p in points:
        db.record_point(kind, key, dict(p))
    if best is not None:
        db.set_best(kind, key,
                    {"route": best["route"], "bm": best["bm"],
                     "tsteps": best["tsteps"]}, best["mcells_per_s"],
                    {"protocol": f"worker@{path}",
                     "timestamp": f"{ts}T00:00:00+00:00"})
    db.save()
    return db


def test_db_merge_same_salt_keeps_best_and_unions_points(tmp_path):
    """Two workers measured overlapping spaces: the merge unions the
    points (the better datum wins per (route, bm, T)) and restamps the
    best — with the winning measurement's provenance."""
    a = _worker_db(tmp_path / "a.json",
                   points=[_point("C", 8, 8, 100.0),
                           _point("C", 16, 8, 120.0),
                           _point("C2", 8, 8, status="oom")],
                   best={"route": "C", "bm": 16, "tsteps": 8,
                         "mcells_per_s": 120.0})
    _worker_db(tmp_path / "b.json",
               points=[_point("C", 16, 8, 150.0),     # faster re-measure
                       _point("C2", 8, 8, 140.0),     # succeeded here
                       _point("C2", 16, 8, 90.0)],
               best={"route": "C", "bm": 16, "tsteps": 8,
                     "mcells_per_s": 150.0}, ts="2026-02-01")
    s = a.merge(TuningDB(str(tmp_path / "b.json")))
    assert s["entries_merged"] == 1 and s["points_added"] == 1
    e = a.entry("cpu", "64x64:float32")
    by_key = {(p["route"], p["bm"], p["tsteps"]): p
              for p in e["points"]}
    assert len(by_key) == 4
    assert by_key[("C", 16, 8)]["mcells_per_s"] == 150.0  # better won
    assert by_key[("C2", 8, 8)]["status"] == "ok"         # ok beat oom
    assert e["best"] == {"route": "C", "bm": 16, "tsteps": 8}
    assert e["mcells_per_s"] == 150.0
    assert e["provenance"]["protocol"].endswith("b.json")
    # lookup serves the merged best
    cfg = a.lookup("cpu", 64, 64)
    assert cfg is not None and cfg.bm == 16 and cfg.source == "exact"


def test_db_merge_current_salt_wins_over_stale(tmp_path):
    """Entries measured under a different kernel revision lose the
    storage slot to current-salt entries no matter their rate; between
    two stale salts the newer provenance wins."""
    a = _worker_db(tmp_path / "a.json",
                   points=[_point("C", 8, 8, 999.0)],
                   best={"route": "C", "bm": 8, "tsteps": 8,
                         "mcells_per_s": 999.0})
    a.data["devices"]["cpu"]["entries"]["64x64:float32"]["salt"] = \
        "stale-aaaa"
    b = _worker_db(tmp_path / "b.json",
                   points=[_point("C", 16, 8, 10.0)],
                   best={"route": "C", "bm": 16, "tsteps": 8,
                         "mcells_per_s": 10.0})
    a.merge(b)
    e = a.entry("cpu", "64x64:float32")       # salted lookup: current
    assert e is not None and e["best"]["bm"] == 16
    # reversed: a current-salt holder keeps its slot against stale
    b2 = TuningDB(str(tmp_path / "b.json"))
    stale = {"devices": {"cpu": {"entries": {"64x64:float32": {
        "salt": "stale-bbbb", "points": [_point("C", 24, 8, 5000.0)],
        "best": {"route": "C", "bm": 24, "tsteps": 8},
        "mcells_per_s": 5000.0,
        "provenance": {"timestamp": "2030-01-01T00:00:00+00:00"}}}}}}
    s = b2.merge(stale)
    assert s["entries_kept"] == 1
    assert b2.entry("cpu", "64x64:float32")["best"]["bm"] == 16


def test_db_merge_new_device_kind_and_stamps(tmp_path):
    a = TuningDB(str(tmp_path / "a.json"))
    a.stamp_device("cpu", vmem_total_bytes=111)
    b = _worker_db(tmp_path / "b.json", kind="TPU v5e",
                   points=[_point("C2", 64, 16, 9000.0)],
                   best={"route": "C2", "bm": 64, "tsteps": 16,
                         "mcells_per_s": 9000.0})
    b.stamp_device("cpu", vmem_total_bytes=222)
    s = a.merge(b)
    assert s["entries_added"] == 1
    assert a.lookup("TPU v5e", 64, 64).route == "C2"
    # an existing device stamp is never overwritten by a merge
    assert a.device("cpu")["vmem_total_bytes"] == 111
    with pytest.raises(ValueError):
        a.merge({"not": "a db"})


def test_db_rollout_stamps_roundtrip(tmp_path):
    """Document-level epoch/validated stamps (the control plane's
    rollout provenance) survive save/load; a db without them is the
    validated incumbent at epoch 0."""
    db = _worker_db(tmp_path / "a.json",
                    points=[_point("C", 8, 8, 100.0)],
                    best={"route": "C", "bm": 8, "tsteps": 8,
                          "mcells_per_s": 100.0})
    assert db.epoch == 0 and db.validated is True
    db.stamp_rollout(epoch=3, validated=False)
    assert db.mark_entries(validated=False, epoch=3) == 1
    db.save()
    back = TuningDB(str(tmp_path / "a.json"))
    assert back.epoch == 3 and back.validated is False
    e = back.entry("cpu", "64x64:float32")
    assert e["validated"] is False and e["epoch"] == 3


def test_db_merge_prefers_validated_at_equal_salt(tmp_path):
    """A VALIDATED entry's best beats a staged CANDIDATE's at the same
    salt even when the candidate measured a faster rate — a rollout
    proved the validated config; the faster point is a claim. Points
    still union both ways."""
    a = _worker_db(tmp_path / "a.json",
                   points=[_point("C", 8, 8, 100.0)],
                   best={"route": "C", "bm": 8, "tsteps": 8,
                         "mcells_per_s": 100.0})
    a.mark_entries(validated=True, epoch=2)
    a.save()
    b = _worker_db(tmp_path / "b.json",
                   points=[_point("C", 16, 8, 500.0)],   # faster, unproven
                   best={"route": "C", "bm": 16, "tsteps": 8,
                         "mcells_per_s": 500.0}, ts="2026-03-01")
    b.mark_entries(validated=False, epoch=3)             # staged candidate
    b.save()
    s = a.merge(TuningDB(str(tmp_path / "b.json")))
    assert s["points_added"] == 1
    e = a.entry("cpu", "64x64:float32")
    assert e["best"]["bm"] == 8                  # validated kept the slot
    assert e["validated"] is True and e["epoch"] == 2
    assert {(p["route"], p["bm"]) for p in e["points"]} == \
        {("C", 8), ("C", 16)}
    # the mirror merge: the candidate holder CEDES to the validated
    b2 = TuningDB(str(tmp_path / "b.json"))
    b2.merge(TuningDB(str(tmp_path / "a.json")))
    e2 = b2.entry("cpu", "64x64:float32")
    assert e2["best"]["bm"] == 8 and e2["validated"] is True
    # equal validation status falls back to the frontier restamp
    c = _worker_db(tmp_path / "c.json",
                   points=[_point("C", 24, 8, 900.0)],
                   best={"route": "C", "bm": 24, "tsteps": 8,
                         "mcells_per_s": 900.0}, ts="2026-04-01")
    c.mark_entries(validated=False, epoch=3)
    b3 = TuningDB(str(tmp_path / "b.json"))      # still a candidate
    b3.merge(c)
    assert b3.entry("cpu", "64x64:float32")["best"]["bm"] == 24


def test_db_merge_unstamped_incumbent_beats_staged_candidate(tmp_path):
    """Review regression: an UNSTAMPED entry (a db that predates
    rollout stamps) counts as the validated incumbent — a staged
    candidate's faster claim must not displace its best in a merge."""
    inc = _worker_db(tmp_path / "incumbent.json",
                     points=[_point("C", 8, 8, 100.0)],
                     best={"route": "C", "bm": 8, "tsteps": 8,
                           "mcells_per_s": 100.0})
    cand = _worker_db(tmp_path / "candidate.json",
                      points=[_point("C", 16, 8, 999.0)],
                      best={"route": "C", "bm": 16, "tsteps": 8,
                            "mcells_per_s": 999.0}, ts="2026-05-01")
    cand.mark_entries(validated=False, epoch=1)
    cand.save()
    inc.merge(TuningDB(str(tmp_path / "candidate.json")))
    e = inc.entry("cpu", "64x64:float32")
    assert e["best"]["bm"] == 8                  # incumbent held
    assert "validated" not in e or e["validated"]
    # the mirror direction: the CANDIDATE adopting the unstamped
    # incumbent's best must also shed its own validated=False stamp —
    # otherwise a later candidate merge (False == False) would let an
    # unproven faster point displace the adopted proven best
    cand2 = TuningDB(str(tmp_path / "candidate.json"))
    cand2.merge(inc)
    e2 = cand2.entry("cpu", "64x64:float32")
    assert e2["best"]["bm"] == 8
    assert e2.get("validated", True) is True
    cand3 = _worker_db(tmp_path / "candidate3.json",
                       points=[_point("C", 32, 8, 5000.0)],
                       best={"route": "C", "bm": 32, "tsteps": 8,
                             "mcells_per_s": 5000.0}, ts="2026-07-01")
    cand3.mark_entries(validated=False, epoch=2)
    cand2.merge(cand3)
    assert cand2.entry("cpu",
                       "64x64:float32")["best"]["bm"] == 8
    # two unstamped dbs keep the plain frontier-restamp behavior
    d1 = _worker_db(tmp_path / "d1.json",
                    points=[_point("C", 8, 8, 100.0)],
                    best={"route": "C", "bm": 8, "tsteps": 8,
                          "mcells_per_s": 100.0})
    d2 = _worker_db(tmp_path / "d2.json",
                    points=[_point("C", 24, 8, 500.0)],
                    best={"route": "C", "bm": 24, "tsteps": 8,
                          "mcells_per_s": 500.0}, ts="2026-06-01")
    d1.merge(d2)
    assert d1.entry("cpu", "64x64:float32")["best"]["bm"] == 24


def test_frontier_table_surfaces_validation_stamps(tmp_path):
    """The frontier's best row carries the rollout provenance tag —
    [candidate eN] for a staged db, [validated eN] after promote."""
    from heat2d_tpu.tune.cli import frontier_table

    db = _worker_db(tmp_path / "a.json",
                    points=[_point("C", 8, 8, 100.0)],
                    best={"route": "C", "bm": 8, "tsteps": 8,
                          "mcells_per_s": 100.0})
    plain = frontier_table(db, "cpu")
    assert "<-- best" in plain and "[" not in plain.split("best")[-1]
    db.mark_entries(validated=False, epoch=4)
    staged = frontier_table(db, "cpu")
    assert "<-- best [candidate e4]" in staged
    db.mark_entries(validated=True, epoch=4)
    assert "<-- best [validated e4]" in frontier_table(db, "cpu")
    # an epoch stamp WITHOUT a validated key defaults validated (the
    # incumbent back-compat rule every consumer applies)
    for dev in db.data["devices"].values():
        for e in dev["entries"].values():
            e.pop("validated", None)
    assert "<-- best [validated e4]" in frontier_table(db, "cpu")


def test_merge_cli_writes_consolidated_db(tmp_path, capsys):
    """heat2d-tpu-tune --merge a.json b.json -o out.json — the
    fleet-wide consolidation entry point; corrupt inputs contribute
    nothing and flag the exit code."""
    from heat2d_tpu.tune.cli import main

    _worker_db(tmp_path / "a.json",
               points=[_point("C", 8, 8, 100.0)],
               best={"route": "C", "bm": 8, "tsteps": 8,
                     "mcells_per_s": 100.0})
    _worker_db(tmp_path / "b.json",
               points=[_point("C", 16, 8, 160.0)],
               best={"route": "C", "bm": 16, "tsteps": 8,
                     "mcells_per_s": 160.0}, ts="2026-03-01")
    out = tmp_path / "merged.json"
    assert main(["--merge", str(tmp_path / "a.json"),
                 str(tmp_path / "b.json"), "-o", str(out)]) == 0
    merged = TuningDB(str(out))
    cfg = merged.lookup("cpu", 64, 64)
    assert cfg is not None and cfg.bm == 16
    assert cfg.mcells_per_s == 160.0
    # missing -o is a usage error
    assert main(["--merge", str(tmp_path / "a.json")]) == 2
    # a corrupt input degrades to an empty contribution, rc 1
    bad = tmp_path / "bad.json"
    bad.write_text("{torn")
    assert main(["--merge", str(tmp_path / "a.json"), str(bad),
                 "-o", str(out)]) == 1
    assert TuningDB(str(out)).lookup("cpu", 64, 64).bm == 8
