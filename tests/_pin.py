"""The suite's jaxpr-pin helpers — ONE import of the consolidated
``heat2d_tpu.analysis.jaxpr_pin`` library.

Every "subsystem X is free when off" acceptance pin (obs, tune, diff,
tracing, chaos, fused-halo, lock-audit, mesh) goes through these; a
broken pin now fails with a readable structural diff of the two traced
programs instead of a bare ``assert a == b`` over multi-thousand-line
strings."""

from heat2d_tpu.analysis.jaxpr_pin import (assert_jaxpr_differs,
                                           assert_jaxpr_equal,
                                           band_runner_jaxpr,
                                           batch_runner_jaxpr,
                                           diff_jaxprs, jaxpr_text,
                                           mesh_runner_jaxpr,
                                           sharded_runner_jaxpr,
                                           solver_jaxpr,
                                           spatial_runner_jaxpr)

__all__ = [
    "assert_jaxpr_differs", "assert_jaxpr_equal", "band_runner_jaxpr",
    "batch_runner_jaxpr", "diff_jaxprs", "jaxpr_text",
    "mesh_runner_jaxpr", "sharded_runner_jaxpr", "solver_jaxpr",
    "spatial_runner_jaxpr",
]
