"""CLI end-to-end tests (the reference's build/run recipes, readme.md:9-19,
as --mode flags)."""

import json

import numpy as np

from heat2d_tpu.cli import main
from heat2d_tpu.io import read_binary, read_grid_text


def test_cli_serial_run(tmp_path, capsys):
    rc = main(["--mode", "serial", "--outdir", str(tmp_path),
               "--binary-dumps",
               "--run-record", str(tmp_path / "record.json")])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Problem size:10x10" in out
    assert "Elapsed time:" in out
    initial = read_grid_text(tmp_path / "initial.dat", "rowmajor")
    final = read_grid_text(tmp_path / "final.dat", "rowmajor")
    assert initial.shape == (10, 10)
    assert final.shape == (10, 10)
    # binary dump parses to the same grid as the text dump (at %6.1f res)
    b = read_binary(tmp_path / "final_binary.dat", (10, 10))
    np.testing.assert_allclose(b, final, atol=0.05)
    rec = json.loads((tmp_path / "record.json").read_text())
    assert rec["steps_done"] == 100


def test_cli_dist2d_run(tmp_path):
    rc = main(["--mode", "dist2d", "--gridx", "2", "--gridy", "2",
               "--nxprob", "16", "--nyprob", "16", "--steps", "20",
               "--outdir", str(tmp_path)])
    assert rc == 0
    final = read_grid_text(tmp_path / "final.dat", "rowmajor")
    assert final.shape == (16, 16)


def test_cli_debug_neighbor_map(tmp_path, capsys):
    """--debug on dist modes dumps the per-shard N/S/E/W topology
    (grad1612_mpi_heat.c:170-175 parity; -1 = MPI_PROC_NULL edge)."""
    rc = main(["--mode", "dist2d", "--gridx", "2", "--gridy", "2",
               "--nxprob", "16", "--nyprob", "16", "--steps", "4",
               "--debug", "--outdir", str(tmp_path)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "shard 0 at (0,0): N=-1 S=2 W=-1 E=1" in out
    assert "shard 3 at (1,1): N=1 S=-1 W=2 E=-1" in out


def test_neighbor_table_row_strip():
    """dist1d (N,1) topology: chain over rows, no E/W neighbors —
    mpi_heat2Dn.c's up/down exchange partners."""
    from heat2d_tpu.parallel.mesh import neighbor_table
    t = neighbor_table(3, 1)
    assert [r["north"] for r in t] == [-1, 0, 1]
    assert [r["south"] for r in t] == [1, 2, -1]
    assert all(r["west"] == -1 and r["east"] == -1 for r in t)


def test_cli_uneven_dist1d_initial_dump_cropped(tmp_path):
    """Uneven decomposition (10 rows over 3 workers pads to 12): both
    dumps must still be the problem domain, not the padded shard shape
    (ADVICE r1 medium: initial.dat used to carry the pad rows)."""
    rc = main(["--mode", "dist1d", "--numworkers", "3",
               "--nxprob", "10", "--nyprob", "10", "--steps", "10",
               "--outdir", str(tmp_path), "--binary-dumps"])
    assert rc == 0
    initial = read_grid_text(tmp_path / "initial.dat", "rowmajor")
    final = read_grid_text(tmp_path / "final.dat", "rowmajor")
    assert initial.shape == (10, 10)
    assert final.shape == (10, 10)
    bi = read_binary(tmp_path / "initial_binary.dat", (10, 10))
    assert bi.shape == (10, 10)
    # the binary initial dump must be the true initial condition
    from heat2d_tpu.ops.init import inidat
    np.testing.assert_array_equal(bi, np.asarray(inidat(10, 10)))


def test_cli_baseline_layout(tmp_path):
    rc = main(["--mode", "serial", "--dat-layout", "baseline",
               "--outdir", str(tmp_path)])
    assert rc == 0
    g = read_grid_text(tmp_path / "initial.dat", "baseline")
    assert g.shape == (10, 10)


def test_cli_invalid_config(tmp_path, capsys):
    rc = main(["--mode", "dist2d", "--gridx", "3", "--nxprob", "10",
               "--outdir", str(tmp_path)])
    assert rc == 1
    assert "Quitting" in capsys.readouterr().err


def test_cli_checkpoint_resume(tmp_path):
    ck = tmp_path / "ck.bin"
    rc = main(["--mode", "serial", "--nxprob", "16", "--nyprob", "16",
               "--steps", "60", "--outdir", str(tmp_path / "a"),
               "--checkpoint", str(ck)])
    assert rc == 0
    rc = main(["--mode", "serial", "--nxprob", "16", "--nyprob", "16",
               "--steps", "100", "--outdir", str(tmp_path / "b"),
               "--resume", str(ck)])
    assert rc == 0
    resumed = read_grid_text(tmp_path / "b" / "final.dat", "rowmajor")
    rc = main(["--mode", "serial", "--nxprob", "16", "--nyprob", "16",
               "--steps", "100", "--outdir", str(tmp_path / "c")])
    straight = read_grid_text(tmp_path / "c" / "final.dat", "rowmajor")
    np.testing.assert_array_equal(resumed, straight)


def test_cli_periodic_checkpoints(tmp_path):
    """--checkpoint-every: restart points land every K steps and the final
    grid is byte-identical to an unsegmented run."""
    from heat2d_tpu.io import load_checkpoint

    ck = tmp_path / "ck.bin"
    rc = main(["--mode", "serial", "--steps", "50", "--outdir",
               str(tmp_path / "a"), "--checkpoint", str(ck),
               "--checkpoint-every", "20"])
    assert rc == 0
    grid, step, _cfg = load_checkpoint(str(ck))
    assert step == 50  # final segment (20+20+10) checkpointed last

    rc = main(["--mode", "serial", "--steps", "50",
               "--outdir", str(tmp_path / "b")])
    assert rc == 0
    a = (tmp_path / "a" / "final.dat").read_bytes()
    b = (tmp_path / "b" / "final.dat").read_bytes()
    assert a == b


def test_cli_periodic_checkpoint_resume_roundtrip(tmp_path):
    """A run resumed from a segmented run's restart point must end
    byte-identical to a straight unsegmented run."""
    from heat2d_tpu.io import load_checkpoint

    ck = tmp_path / "ck.bin"
    main(["--mode", "serial", "--steps", "60", "--outdir",
          str(tmp_path / "x"), "--checkpoint", str(ck),
          "--checkpoint-every", "25"])
    _, step, _ = load_checkpoint(str(ck))
    assert step == 60  # segments 25+25+10
    rc = main(["--mode", "serial", "--steps", "100", "--resume", str(ck),
               "--outdir", str(tmp_path / "y")])
    assert rc == 0
    main(["--mode", "serial", "--steps", "100",
          "--outdir", str(tmp_path / "z")])
    assert ((tmp_path / "y" / "final.dat").read_bytes()
            == (tmp_path / "z" / "final.dat").read_bytes())


def test_cli_checkpoint_every_requires_aligned_interval(tmp_path, capsys):
    rc = main(["--mode", "serial", "--steps", "100", "--convergence",
               "--interval", "20", "--checkpoint-every", "30",
               "--checkpoint", str(tmp_path / "ck.bin"),
               "--outdir", str(tmp_path)])
    assert rc == 1
    assert "multiple of" in capsys.readouterr().err


def test_cli_run_record_has_device_context(tmp_path):
    rec_path = tmp_path / "rec.json"
    rc = main(["--mode", "dist2d", "--gridx", "2", "--gridy", "2",
               "--nxprob", "16", "--nyprob", "16", "--steps", "5",
               "--outdir", str(tmp_path),
               "--run-record", str(rec_path)])
    assert rc == 0
    rec = json.loads(rec_path.read_text())
    assert rec["device"]["n_devices"] >= 4
    assert rec["mesh"]["mesh_shape"] == {"x": 2, "y": 2}
