"""CLI end-to-end tests (the reference's build/run recipes, readme.md:9-19,
as --mode flags)."""

import json

import numpy as np

from heat2d_tpu.cli import main
from heat2d_tpu.io import read_binary, read_grid_text


def test_cli_serial_run(tmp_path, capsys):
    rc = main(["--mode", "serial", "--outdir", str(tmp_path),
               "--binary-dumps",
               "--run-record", str(tmp_path / "record.json")])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Problem size:10x10" in out
    assert "Elapsed time:" in out
    initial = read_grid_text(tmp_path / "initial.dat", "rowmajor")
    final = read_grid_text(tmp_path / "final.dat", "rowmajor")
    assert initial.shape == (10, 10)
    assert final.shape == (10, 10)
    # binary dump parses to the same grid as the text dump (at %6.1f res)
    b = read_binary(tmp_path / "final_binary.dat", (10, 10))
    np.testing.assert_allclose(b, final, atol=0.05)
    rec = json.loads((tmp_path / "record.json").read_text())
    assert rec["steps_done"] == 100


def test_cli_dist2d_run(tmp_path):
    rc = main(["--mode", "dist2d", "--gridx", "2", "--gridy", "2",
               "--nxprob", "16", "--nyprob", "16", "--steps", "20",
               "--outdir", str(tmp_path)])
    assert rc == 0
    final = read_grid_text(tmp_path / "final.dat", "rowmajor")
    assert final.shape == (16, 16)


def test_cli_baseline_layout(tmp_path):
    rc = main(["--mode", "serial", "--dat-layout", "baseline",
               "--outdir", str(tmp_path)])
    assert rc == 0
    g = read_grid_text(tmp_path / "initial.dat", "baseline")
    assert g.shape == (10, 10)


def test_cli_invalid_config(tmp_path, capsys):
    rc = main(["--mode", "dist2d", "--gridx", "3", "--nxprob", "10",
               "--outdir", str(tmp_path)])
    assert rc == 1
    assert "Quitting" in capsys.readouterr().err


def test_cli_checkpoint_resume(tmp_path):
    ck = tmp_path / "ck.bin"
    rc = main(["--mode", "serial", "--nxprob", "16", "--nyprob", "16",
               "--steps", "60", "--outdir", str(tmp_path / "a"),
               "--checkpoint", str(ck)])
    assert rc == 0
    rc = main(["--mode", "serial", "--nxprob", "16", "--nyprob", "16",
               "--steps", "100", "--outdir", str(tmp_path / "b"),
               "--resume", str(ck)])
    assert rc == 0
    resumed = read_grid_text(tmp_path / "b" / "final.dat", "rowmajor")
    rc = main(["--mode", "serial", "--nxprob", "16", "--nyprob", "16",
               "--steps", "100", "--outdir", str(tmp_path / "c")])
    straight = read_grid_text(tmp_path / "c" / "final.dat", "rowmajor")
    np.testing.assert_array_equal(resumed, straight)
