"""Observability + multi-host bring-up units (SURVEY.md §5.1, §2.4)."""

import os

import jax

from heat2d_tpu.parallel.multihost import (
    initialize_distributed, world_summary)
from heat2d_tpu.utils.profiling import annotate, profile_span


def test_profile_span_writes_trace(tmp_path):
    logdir = str(tmp_path / "trace")
    with profile_span(logdir):
        with annotate("stencil"):
            jax.block_until_ready(jax.numpy.ones((8, 8)) * 2.0)
    files = [os.path.join(r, f)
             for r, _, fs in os.walk(logdir) for f in fs]
    assert any("xplane" in f or "trace" in f for f in files), files


def test_profile_span_none_is_noop():
    with profile_span(None):
        pass  # no logdir -> no tracing machinery touched


def test_world_summary_single_process():
    w = world_summary()
    assert w["process_index"] == 0
    assert w["process_count"] == 1
    assert w["global_device_count"] == len(jax.devices())


def test_initialize_distributed_single_process_noop():
    # No coordinator/pod env and force=False: must not try to connect.
    w = initialize_distributed()
    assert w["process_count"] == 1
