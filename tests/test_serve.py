"""Solve-serving subsystem (heat2d_tpu/serve/): micro-batch coalescing,
content-addressed caching with single-flight, admission control, and the
serve-path telemetry contract (ISSUE 2 acceptance criteria)."""

import json

import numpy as np
import pytest

from heat2d_tpu.models import ensemble
from heat2d_tpu.obs import MetricsRegistry
from heat2d_tpu.serve import (Client, Rejected, SolveRequest, SolveResult,
                              SolveServer)

NX, NY, STEPS = 20, 24, 8


def make_server(**kw):
    kw.setdefault("registry", MetricsRegistry())
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_delay", 0.1)
    return SolveServer(**kw)


def req(cx=0.1, cy=0.1, **kw):
    kw.setdefault("nx", NX)
    kw.setdefault("ny", NY)
    kw.setdefault("steps", STEPS)
    kw.setdefault("method", "jnp")
    return SolveRequest(cx=cx, cy=cy, **kw)


# --------------------------------------------------------------------- #
# schema
# --------------------------------------------------------------------- #

def test_content_hash_and_signature():
    a, b = req(cx=0.1), req(cx=0.2)
    assert a.content_hash() == req(cx=0.1).content_hash()
    assert a.content_hash() != b.content_hash()
    # Different diffusivities, SAME compiled signature (one bucket).
    assert a.signature() == b.signature()
    # A different shape/steps-class is a different signature.
    assert a.signature() != req(nx=NX + 8).signature()
    assert a.signature() != req(steps=STEPS + 1).signature()


def test_fixed_step_ignores_convergence_knobs():
    """interval/sensitivity are unused on fixed-step runs — they must
    not fragment cache entries, batch buckets, or compiled runners."""
    a, b = req(interval=20), req(interval=7, sensitivity=9.9)
    assert a.content_hash() == b.content_hash()
    assert a.signature() == b.signature()
    # On convergence runs they ARE the computation.
    c = req(convergence=True, interval=7)
    d = req(convergence=True, interval=8)
    assert c.signature() != d.signature()
    assert c.content_hash() != d.content_hash()


def test_request_validation_is_structured():
    with pytest.raises(Rejected) as e:
        SolveRequest(nx=1, ny=1, steps=5).validate()
    assert e.value.code == "invalid"
    with pytest.raises(Rejected):
        SolveRequest.from_dict({"nx": 8, "ny": 8, "steps": 1,
                                "bogus_field": 3})
    with pytest.raises(Rejected):
        SolveRequest(nx=8, ny=8, steps=1, dtype="float64").validate()


# --------------------------------------------------------------------- #
# batching / coalescing (the acceptance-criteria test)
# --------------------------------------------------------------------- #

def test_n_concurrent_requests_fewer_than_n_launches():
    """N same-shape concurrent requests are served by STRICTLY fewer
    than N ensemble launches, and every member's grid is bitwise the
    grid a standalone ensemble launch of that (cx, cy) produces."""
    n = 5
    reqs = [req(cx=0.05 + 0.01 * i) for i in range(n)]
    with make_server() as server:
        results = [f.result(timeout=60)
                   for f in [server.submit(r) for r in reqs]]
        launches = server.engine.launches
    assert launches < n                      # strictly fewer: coalesced
    assert launches == 1                     # same signature, one bucket
    assert server.engine.launch_log[0]["occupancy"] == n
    for r, res in zip(reqs, results):
        assert isinstance(res, SolveResult)
        assert res.batch_size == n and res.steps_done == STEPS
        solo = np.asarray(ensemble.run_ensemble(
            NX, NY, STEPS, [r.cx], [r.cy], method="jnp"))[0]
        assert np.asarray(res.u).tobytes() == solo.tobytes()


def test_duplicate_inflight_requests_coalesce_to_one_member():
    """Two identical in-flight requests share one compute — one launch,
    occupancy 1, the same grid, and the follower is labeled
    coalesced."""
    registry = MetricsRegistry()
    with make_server(registry=registry) as server:
        f1 = server.submit(req(cx=0.17))
        f2 = server.submit(req(cx=0.17))
        r1, r2 = f1.result(timeout=60), f2.result(timeout=60)
        assert server.engine.launches == 1
        assert server.engine.launch_log[0]["occupancy"] == 1
    assert r2.u is r1.u          # shared, never recomputed or copied
    assert r2.coalesced and not r1.coalesced
    snap = registry.snapshot()
    assert snap["counters"]["serve_coalesced_total"] == 1
    assert snap["counters"]["serve_requests_total{outcome=coalesced}"] == 1


def test_mixed_shape_traffic_lands_in_separate_buckets():
    shapes = [(NX, NY), (16, 12)]
    with make_server() as server:
        futs = [server.submit(req(nx=nx, ny=ny, cx=0.05 + 0.01 * i))
                for i, (nx, ny) in enumerate(shapes * 2)]
        results = [f.result(timeout=60) for f in futs]
    assert server.engine.launches == 2
    sigs = {row["signature"] for row in server.engine.launch_log}
    assert len(sigs) == 2
    for res, (nx, ny) in zip(results, shapes * 2):
        assert np.asarray(res.u).shape == (nx, ny)


def test_convergence_requests_serve_steps_done():
    r = req(cx=0.1, convergence=True, interval=4, sensitivity=1e30)
    with make_server() as server:
        res = Client(server).solve(r, timeout=60)
    # Infinite sensitivity: converges at the first check.
    assert res.steps_done == 4
    u_ref, k_ref = ensemble.run_ensemble_convergence(
        NX, NY, STEPS, 4, 1e30, [r.cx], [r.cy], method="jnp")
    assert int(np.asarray(k_ref)[0]) == res.steps_done
    assert np.asarray(res.u).tobytes() == np.asarray(u_ref)[0].tobytes()


# --------------------------------------------------------------------- #
# cache
# --------------------------------------------------------------------- #

def test_cache_hit_is_bitwise_identical_to_cold_solve():
    r = req(cx=0.123)
    with make_server() as server:
        client = Client(server)
        cold = client.solve(r, timeout=60)
        warm = client.solve(r, timeout=60)
        assert server.engine.launches == 1   # second one never computed
    assert not cold.cache_hit and warm.cache_hit
    assert np.asarray(warm.u).tobytes() == np.asarray(cold.u).tobytes()
    # And bitwise against a COLD solve on a fresh server too.
    with make_server() as fresh:
        cold2 = Client(fresh).solve(r, timeout=60)
    assert np.asarray(cold2.u).tobytes() == np.asarray(warm.u).tobytes()


def test_cache_lru_bound_evicts():
    from heat2d_tpu.serve.cache import ResultCache
    c = ResultCache(capacity=2)
    c.put("a", 1)
    c.put("b", 2)
    assert c.get("a") == 1      # refresh a: b is now LRU
    c.put("c", 3)
    assert c.get("b") is None and c.get("a") == 1 and c.get("c") == 3
    assert c.evictions == 1


# --------------------------------------------------------------------- #
# admission control
# --------------------------------------------------------------------- #

def test_queue_full_sheds_load_with_structured_rejection():
    registry = MetricsRegistry()
    # max_delay far beyond the test: nothing dispatches, the queue fills.
    with make_server(registry=registry, max_queue=2, max_batch=100,
                     max_delay=60.0) as server:
        futs = [server.submit(req(cx=0.05 + 0.01 * i)) for i in range(3)]
        with pytest.raises(Rejected) as e:
            futs[2].result(timeout=10)
        assert e.value.code == "queue_full"
        assert "content_hash" in e.value.to_record()
        assert not futs[0].done() and not futs[1].done()
    # stop() rejects whatever was still queued — nobody hangs.
    for f in futs[:2]:
        with pytest.raises(Rejected) as e:
            f.result(timeout=10)
        assert e.value.code == "shutdown"
    snap = registry.snapshot()
    assert snap["counters"][
        "serve_rejected_total{reason=queue_full}"] == 1
    assert snap["counters"][
        "serve_requests_total{outcome=rejected_queue_full}"] == 1


def test_ready_buckets_dispatch_oldest_head_first():
    """A sustained hot signature must not starve other buckets: among
    ready buckets the scheduler serves the one with the OLDEST head,
    not the first-inserted (which a non-empty hot bucket keeps being)."""
    import time

    from heat2d_tpu.serve.batcher import MicroBatcher

    mb = MicroBatcher(lambda sig, batch: None, max_batch=1,
                      max_delay=0.0)
    mb._running = True          # admit without starting the thread
    hot = req(cx=0.1)           # bucket A, inserted first
    other = req(nx=NX + 8, cx=0.1)   # bucket B
    hot2 = req(cx=0.2)          # bucket A again — A stays non-empty
    for r in (hot, other, hot2):
        mb.submit(r, r.content_hash(), lambda e: None)
        time.sleep(0.002)       # strictly ordered enqueue stamps
    now = time.monotonic() + 1.0
    order = []
    for _ in range(3):
        with mb._cond:      # the _locked suffix is a real contract:
            #                 the lock audit flags a bare call
            sig, batch = mb._pop_ready_locked(now)
        order.append(batch[0].req.content_hash())
    # Insertion-order service would yield hot, hot2, other.
    assert order == [r.content_hash() for r in (hot, other, hot2)]
    assert mb.depth() == 0


def test_per_request_timeout_returns_structured_rejection():
    with make_server(max_delay=60.0, max_batch=100) as server:
        fut = server.submit(req(cx=0.3), timeout=0.05)
        with pytest.raises(Rejected) as e:
            fut.result(timeout=10)
    assert e.value.code == "timeout"
    rec = e.value.to_record()
    assert rec["rejected"] == "timeout" and rec["waited_s"] >= 0.05


def test_stop_drain_resolves_every_queued_request():
    """Fleet satellite: stop(drain=True) closes admission, flushes the
    queued buckets WITHOUT waiting out max_delay, and resolves every
    in-flight future before returning — the graceful path a rolling
    worker restart needs (no admitted request is dropped)."""
    server = make_server(max_delay=60.0, max_batch=100)
    server.start()
    # max_delay=60s: nothing would dispatch on its own within the test
    futs = [server.submit(req(cx=0.05 + 0.01 * i)) for i in range(5)]
    futs += [server.submit(req(nx=NX + 8, cx=0.3))]   # second bucket
    assert not any(f.done() for f in futs)
    server.stop(drain=True)
    # drain returned => every future is already resolved, successfully
    for f in futs:
        res = f.result(timeout=0)
        assert isinstance(res, SolveResult)
    assert server.batcher.depth() == 0
    # admission is closed during/after a drain
    with pytest.raises(Rejected) as e:
        server.submit(req(cx=0.9)).result(timeout=5)
    assert e.value.code == "shutdown"
    # and the server can come back up for the next restart cycle
    server.start()
    fut = server.submit(req(cx=0.91))
    server.stop(drain=True)
    assert fut.result(timeout=0).steps_done == STEPS


def test_stop_default_still_rejects_queued():
    """Non-drain stop keeps the legacy contract: queued requests fail
    with a structured shutdown rejection rather than hanging."""
    with make_server(max_delay=60.0, max_batch=100) as server:
        fut = server.submit(req(cx=0.4))
    with pytest.raises(Rejected) as e:
        fut.result(timeout=5)
    assert e.value.code == "shutdown"


# --------------------------------------------------------------------- #
# compile cache
# --------------------------------------------------------------------- #

def test_batch_runner_is_memoized_per_signature():
    a = ensemble.batch_runner(NX, NY, STEPS, "jnp")
    b = ensemble.batch_runner(NX, NY, STEPS, "jnp")
    c = ensemble.batch_runner(NX, NY, STEPS + 1, "jnp")
    assert a is b           # warm signature: the SAME jitted callable
    assert a is not c


def test_pad_capacity_power_of_two_capped():
    from heat2d_tpu.serve.engine import _pad_capacity
    assert [_pad_capacity(n, 8) for n in (1, 2, 3, 5, 8)] == \
        [1, 2, 4, 8, 8]
    assert _pad_capacity(5, 6) == 6      # cap wins over the power of 2


# --------------------------------------------------------------------- #
# telemetry contract (--metrics-out JSONL via the CLI selftest)
# --------------------------------------------------------------------- #

def test_serve_cli_selftest_emits_telemetry_jsonl(tmp_path):
    from heat2d_tpu.serve.cli import main

    path = tmp_path / "serve.jsonl"
    assert main(["--selftest", "--metrics-out", str(path)]) == 0
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    snap = [l for l in lines if l["event"] == "snapshot"][0]
    # The acceptance-criteria metric families, in the JSONL snapshot:
    assert "serve_queue_depth" in snap["gauges"]          # queue depth
    occ = snap["histograms"]["serve_batch_occupancy"]     # occupancy
    assert occ["count"] >= 1 and occ["max"] >= 2
    assert snap["counters"]["serve_cache_hits_total"] >= 1
    assert snap["gauges"]["serve_cache_hit_rate"] > 0     # hit rate
    assert snap["histograms"]["serve_e2e_latency_s"]["count"] >= 1
    rec = [l for l in lines if l["event"] == "run_record"][0]
    assert rec["kind"] == "serve" and rec["launches"] >= 1


def test_serve_cli_requests_file(tmp_path):
    from heat2d_tpu.serve.cli import main

    reqs = tmp_path / "reqs.jsonl"
    reqs.write_text("\n".join(json.dumps(d) for d in [
        {"nx": NX, "ny": NY, "steps": 4, "cx": 0.1, "cy": 0.1,
         "method": "jnp"},
        {"nx": NX, "ny": NY, "steps": 4, "cx": 0.2, "cy": 0.1,
         "method": "jnp"},
        {"nx": 4, "ny": 4, "steps": -1},        # invalid -> rejection row
    ]) + "\n")
    out = tmp_path / "results.jsonl"
    rc = main(["--requests", str(reqs), "--results-out", str(out),
               "--max-delay", "0.05"])
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    assert rc == 0          # invalid rows are reported, not fatal
    ok = [r for r in rows if "content_hash" in r]
    bad = [r for r in rows if r.get("rejected")]
    assert len(ok) == 2 and len(bad) == 1
    assert bad[0]["rejected"] == "invalid"
    assert ok[0]["steps_done"] == 4 and ok[0]["shape"] == [NX, NY]
