"""The multihost pod runtime (heat2d_tpu/dist/, docs/DISTRIBUTED.md).

Unit layers run against a fake in-memory KV client and injected
clocks — bounded barriers, heartbeats, the DCN halo route's bitwise
parity, and the failure-domain bridge's seq-fenced shrink+failover —
so the loss arithmetic is deterministic with no processes spawned.
The REAL 2-process legs at the bottom ride dist/harness's rendezvous
probe: they need only ``jax.distributed`` + the coordination service
(which plain CPU builds support), NOT cross-process XLA collectives
(which this CI backend cannot run — those tests live in
tests/test_multihost.py and skip with the backend's exact reason).
"""

import json
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from heat2d_tpu.dist.exchange import (
    DcnHaloExchanger, run_process_slab, slab_split)
from heat2d_tpu.dist.mesh import arrange_pod, pod_device_order, seam_profile
from heat2d_tpu.dist.runtime import (
    KV_NS, DistWorld, Heartbeat, HostLostError, KVBarrier,
    elect_recovery_owner)
from heat2d_tpu.dist.topology import (
    FailureDomainBridge, PodTopology, pod_monitor)
from heat2d_tpu.obs.metrics import MetricsRegistry


class FakeKV:
    """The coordination-service KV semantics this jaxlib exhibits
    (probed: dist/runtime.py module docstring): set raises on
    overwrite, blocking get times out with DEADLINE_EXCEEDED,
    dir_get lists (key, value) pairs, delete takes a key or a
    ``.../`` prefix."""

    def __init__(self):
        self.store = {}
        self.lock = threading.Lock()

    def _set(self, key, value):
        with self.lock:
            if key in self.store:
                raise RuntimeError(f"ALREADY_EXISTS: {key}")
            self.store[key] = value

    key_value_set = _set
    key_value_set_bytes = _set

    def blocking_key_value_get_bytes(self, key, timeout_ms):
        deadline = time.monotonic() + timeout_ms / 1000.0
        while True:
            with self.lock:
                if key in self.store:
                    v = self.store[key]
                    return v if isinstance(v, bytes) else v.encode()
            if time.monotonic() >= deadline:
                raise TimeoutError(f"DEADLINE_EXCEEDED: {key}")
            time.sleep(0.001)

    def key_value_dir_get(self, prefix):
        with self.lock:
            return [(k, v) for k, v in self.store.items()
                    if k.startswith(prefix)]

    def key_value_delete(self, key):
        with self.lock:
            if key.endswith("/"):
                for k in [k for k in self.store
                          if k.startswith(key)]:
                    del self.store[k]
            else:
                self.store.pop(key, None)


def _world(pid, count, device_process=None, device_slice=None):
    if device_process is None:
        device_process = tuple(range(count))
    return DistWorld(process_index=pid, process_count=count,
                     device_process=tuple(device_process),
                     device_slice=device_slice)


# ------------------------------------------------------------------ #
# slabs + the DCN halo route
# ------------------------------------------------------------------ #

def test_slab_split_partitions_exactly():
    for nx, p in ((48, 2), (17, 3), (5, 5), (64, 1)):
        slabs = slab_split(nx, p)
        assert slabs[0][0] == 0 and slabs[-1][1] == nx
        for (lo, hi), (lo2, _) in zip(slabs, slabs[1:]):
            assert hi == lo2 and hi > lo
    with pytest.raises(ValueError):
        slab_split(2, 3)
    with pytest.raises(ValueError):
        slab_split(8, 0)


def test_single_process_slab_is_the_compiled_program():
    """P=1 run_process_slab == one compiled stencil_step per step
    (the segment fori_loop changes nothing — the selftest's
    bitwise_vs_plain_loop anchor)."""
    import jax

    from heat2d_tpu.ops import inidat, stencil_step

    got, step = run_process_slab(24, 16, 10, depth=4)
    assert step == 10
    u = inidat(24, 16)
    jstep = jax.jit(stencil_step)
    for _ in range(10):
        u = jstep(u, 0.1, 0.1)
    assert got.tobytes() == np.asarray(u, np.float32).tobytes()


def test_two_thread_dcn_halo_bitwise_and_bounded_store():
    """Two in-process 'hosts' over the fake KV: owned slabs
    concatenate BITWISE to the single-process grid, and every halo
    key is consumed (the store stays bounded)."""
    kv = FakeKV()
    reg = MetricsRegistry()
    nx, ny, steps, depth = 32, 24, 12, 4
    out = {}

    def run(pid):
        ex = DcnHaloExchanger(_world(pid, 2), depth, client=kv,
                              timeout_s=30, registry=reg)
        out[pid], _ = run_process_slab(
            nx, ny, steps, depth=depth, process_index=pid,
            process_count=2, exchanger=ex)

    ts = [threading.Thread(target=run, args=(p,)) for p in range(2)]
    [t.start() for t in ts]
    [t.join(timeout=120) for t in ts]
    assert set(out) == {0, 1}
    ref, _ = run_process_slab(nx, ny, steps, depth=depth)
    got = np.concatenate([out[0], out[1]], axis=0)
    assert got.tobytes() == ref.tobytes()
    leaked = [k for k in kv.store if k.startswith(f"{KV_NS}halo/")]
    assert leaked == []
    moved = sum(reg.find_counters("dist_halo_bytes_total").values())
    # 3 exchanges (steps 0,4,8) x 2 processes, each sending one
    # (depth, ny) f32 strip and receiving one
    assert moved == 3 * 2 * 2 * depth * ny * 4


def test_halo_timeout_names_the_silent_host():
    """A neighbor that never publishes is a HostLostError naming THAT
    host and the halo phase — detection names the corpse."""
    ex = DcnHaloExchanger(_world(0, 2), 2, client=FakeKV(),
                          timeout_s=0.05)
    strip = np.zeros((2, 8), np.float32)
    with pytest.raises(HostLostError) as ei:
        ex.exchange("s0", strip, strip)
    assert ei.value.hosts == (1,)
    assert ei.value.phase == "halo:s0"


def test_run_process_slab_guards():
    with pytest.raises(ValueError, match="exchanger"):
        run_process_slab(32, 16, 4, process_index=0, process_count=2)
    with pytest.raises(ValueError, match="halo"):
        run_process_slab(6, 16, 4, depth=4, process_index=0,
                         process_count=2,
                         exchanger=DcnHaloExchanger(
                             _world(0, 2), 4, client=FakeKV()))
    with pytest.raises(ValueError, match="shape"):
        run_process_slab(8, 8, 2, u0=np.zeros((4, 4), np.float32))


# ------------------------------------------------------------------ #
# bounded liveness: barrier + heartbeat
# ------------------------------------------------------------------ #

def _fake_clock():
    state = {"t": 0.0}

    def clock():
        return state["t"]

    def sleep(dt):
        state["t"] += dt

    return state, clock, sleep


def test_kv_barrier_names_missing_peers():
    state, clock, sleep = _fake_clock()
    bar = KVBarrier(_world(0, 3), client=FakeKV(), clock=clock,
                    sleep=sleep)
    with pytest.raises(HostLostError) as ei:
        bar.wait("go", timeout_s=5.0)
    assert ei.value.hosts == (1, 2)
    assert ei.value.phase == "barrier:go"
    assert state["t"] >= 5.0


def test_kv_barrier_completes_and_gcs_old_rounds():
    state, clock, sleep = _fake_clock()
    kv = FakeKV()
    reg = MetricsRegistry()
    bar = KVBarrier(_world(0, 2), client=kv, clock=clock, sleep=sleep,
                    registry=reg)
    for n in range(3):
        kv.key_value_set(f"{KV_NS}bar/go/{n}/1", "1")  # peer arrives
        assert bar.wait("go", timeout_s=5.0) == 0.0
    # round 0 GC'd once round 2 completed; round 1+ still present
    assert not any(k.startswith(f"{KV_NS}bar/go/0/") for k in kv.store)
    assert any(k.startswith(f"{KV_NS}bar/go/2/") for k in kv.store)
    # single-process worlds never touch the KV store
    assert KVBarrier(_world(0, 1), client=None).wait("solo") == 0.0


def test_heartbeat_ages_by_local_clock_and_convicts_stale():
    state, clock, _ = _fake_clock()
    kv = FakeKV()
    reg = MetricsRegistry()
    hb = Heartbeat(_world(0, 2), client=kv, clock=clock, registry=reg)
    kv.key_value_set(f"{KV_NS}hb/1/1", "1")      # peer's first beacon
    assert hb.ages() == {1: 0.0}
    state["t"] = 4.0                             # no new beacon
    assert hb.ages() == {1: 4.0}
    assert hb.stale(3.0) == (1,)
    with pytest.raises(HostLostError) as ei:
        hb.require_live(3.0, phase="soak")
    assert ei.value.hosts == (1,) and ei.value.phase == "soak"
    kv.key_value_set(f"{KV_NS}hb/1/2", "1")      # counter advances
    assert hb.ages() == {1: 0.0}
    assert hb.stale(3.0) == ()
    gauges = reg.find_gauges("dist_heartbeat_age_s")
    assert gauges, "ages() must gauge dist_heartbeat_age_s"


def test_heartbeat_beat_gcs_behind_itself():
    kv = FakeKV()
    hb = Heartbeat(_world(0, 2), client=kv)
    for _ in range(5):
        hb.beat()
    keys = sorted(k for k in kv.store if k.startswith(f"{KV_NS}hb/0/"))
    assert keys == [f"{KV_NS}hb/0/4", f"{KV_NS}hb/0/5"]


def test_elect_recovery_owner():
    assert elect_recovery_owner([2, 0, 3]) == 0
    assert elect_recovery_owner((3, 2)) == 2
    with pytest.raises(ValueError):
        elect_recovery_owner([])


# ------------------------------------------------------------------ #
# topology: links, arrangement, seam pricing
# ------------------------------------------------------------------ #

def test_world_link_kind_by_process_and_slice():
    w = _world(0, 2, device_process=(0, 0, 1, 1))
    assert w.link_kind(0, 0) == "local"
    assert w.link_kind(0, 1) == "ici"
    assert w.link_kind(1, 2) == "dcn"
    assert w.link_census() == {"ici": 2, "dcn": 4}
    assert w.devices_of(1) == (2, 3)
    assert w.peers() == (1,)
    # slice identity (TPU pods) overrides process identity
    ws = _world(0, 2, device_process=(0, 0, 1, 1),
                device_slice=(0, 0, 0, 0))
    assert ws.link_kind(1, 2) == "ici"
    assert ws.link_census() == {"ici": 6, "dcn": 0}


def test_arrange_pod_keeps_xy_intra_host():
    w = _world(0, 2, device_process=(0, 0, 1, 1))
    assert pod_device_order(w) == [0, 1, 2, 3]
    rows = arrange_pod(w, 2, 2)
    assert rows == [[0, 1], [2, 3]]
    prof = seam_profile(w, rows, ny=64)
    assert prof["dcn_seams"] == 0 and prof["ici_seams"] == 4
    assert prof["dcn_bytes_per_step"] == 0
    assert prof["seam_bytes_per_step"] == 4 * 2 * 64 * 4
    # the transposed (bad) arrangement pays every seam over DCN
    bad = seam_profile(w, [[0, 2], [1, 3]], ny=64)
    assert bad["dcn_seams"] == 4
    assert bad["dcn_bytes_per_step"] == 4 * 2 * 64 * 4
    with pytest.raises(ValueError):
        arrange_pod(w, 3, 2)


def test_scheduler_prices_cross_host_seams():
    from heat2d_tpu.mesh.scheduler import MeshScheduler
    from heat2d_tpu.tune.measure import link_bytes_per_s

    w = _world(0, 2, device_process=(0, 0, 1, 1))
    sched = MeshScheduler(n_devices=1, world=w)
    links = sched._seam_links(2, 2, ny=64)
    assert links["dcn_seams"] == 0 and links["ici_seams"] == 4
    assert links["seam_s_per_step"] == pytest.approx(
        4 * 2 * 64 * 4 / link_bytes_per_s("ici"))
    # a submesh that does not cover the pod has no arrangement
    assert sched._seam_links(1, 2, ny=64) is None
    # and without a world the scheduler prices nothing (unchanged
    # single-host behavior)
    assert MeshScheduler(n_devices=1)._seam_links(2, 2, 64) is None


def test_measure_link_model_prices_the_asymmetry():
    from heat2d_tpu.tune.measure import (
        LINK_BYTES_PER_S, SimulatedBackend, link_bytes_per_s)
    from heat2d_tpu.tune.space import Candidate, Problem

    assert link_bytes_per_s("ici") == LINK_BYTES_PER_S["ici"]
    assert link_bytes_per_s("dcn") == LINK_BYTES_PER_S["dcn"]
    assert link_bytes_per_s("dcn") < link_bytes_per_s("ici")
    assert (link_bytes_per_s("local")
            == SimulatedBackend.HBM_BYTES_PER_S)
    with pytest.raises(ValueError):
        link_bytes_per_s("carrier_pigeon")

    p, c = Problem(640, 512), Candidate("fused", 0, 8)
    ici = SimulatedBackend().step_time(p, c)
    # default link must stay bitwise-identical to explicit 'ici'
    # (every existing frontier reproduces)
    assert ici == SimulatedBackend(link="ici").step_time(p, c)
    # the same edge traffic over DCN is strictly harder to hide
    assert SimulatedBackend(link="dcn").step_time(p, c) > ici


# ------------------------------------------------------------------ #
# failure domains: one host loss, one transaction
# ------------------------------------------------------------------ #

def _pod4():
    topo = PodTopology({0: 0, 1: 0, 2: 1, 3: 1})
    reg = MetricsRegistry()
    return topo, pod_monitor(4, registry=reg), reg


def test_pod_topology_maps_failure_domains():
    topo, monitor, _ = _pod4()
    assert topo.n_devices == 4 and topo.hosts == (0, 1)
    assert topo.devices_of(1) == (2, 3)
    assert topo.host_of(0) == 0
    assert monitor.n_devices == 4    # pod ordinals, not local clamp
    w = _world(0, 2, device_process=(0, 0, 1, 1))
    assert PodTopology.from_world(w).devices_of(1) == (2, 3)
    with pytest.raises(ValueError):
        PodTopology({})


def test_bridge_rejects_a_monitor_too_small_for_the_pod():
    topo, _, _ = _pod4()
    with pytest.raises(ValueError, match="outside the book"):
        FailureDomainBridge(topo, pod_monitor(2))


def test_host_loss_is_one_seq_fenced_transaction():
    """The tentpole's failure-domain contract: quarantines land
    BEFORE the transaction's fence, the failover runs under it, and
    the unchanged serving_invariant proves launches on both sides."""
    from heat2d_tpu.mesh.degrade import serving_invariant

    topo, monitor, reg = _pod4()
    bridge = FailureDomainBridge(topo, monitor, registry=reg)
    log = [{"signature": "pre",
            "mesh": {"devices": [0, 1, 2, 3],
                     "health_seq": monitor.seq()}}]

    called = {}

    def failover():
        called["fence"] = monitor.seq()
        called["survivors"] = monitor.survivors()
        return {"resumed": True}

    txn = bridge.on_host_lost(1, failover=failover)
    assert txn["devices"] == [2, 3] and txn["quarantined"] == [2, 3]
    assert txn["survivors"] == [0, 1]
    assert txn["failover"] == {"resumed": True}
    assert txn["health_seq"] > txn["seq_before"]
    # the failover already saw the post-quarantine fence + survivors
    assert called == {"fence": txn["health_seq"], "survivors": (0, 1)}
    assert monitor.quarantined() == (2, 3)

    log.append({"signature": "post",
                "mesh": {"devices": [0, 1],
                         "health_seq": txn["health_seq"]}})
    inv = serving_invariant(monitor, log)
    assert inv["ok"] and inv["checked"] == 2

    # a launch fenced at the transaction that still names a dead
    # host's device is exactly what the invariant must catch
    bad = log + [{"signature": "bad",
                  "mesh": {"devices": [2],
                           "health_seq": txn["health_seq"]}}]
    inv2 = serving_invariant(monitor, bad)
    assert not inv2["ok"]
    assert inv2["violations"][0]["device"] == 2
    assert inv2["violations"][0]["event"]["reason"] == "host_lost"

    assert sum(reg.find_counters("dist_host_lost_total").values()) == 1
    snap = bridge.snapshot()
    assert snap["transactions"] == [txn]
    # re-reporting re-quarantines nothing (idempotent per device)
    assert bridge.on_host_lost(1)["quarantined"] == []


def test_host_lost_is_a_documented_quarantine_reason():
    from heat2d_tpu.mesh.health import QUARANTINE_REASONS
    assert "host_lost" in QUARANTINE_REASONS


def test_dist_is_a_record_kind():
    from heat2d_tpu.obs.record import RECORD_KINDS, build_record
    assert "dist" in RECORD_KINDS
    rec = build_record("dist", extra={"leg": "selftest"})
    assert rec["kind"] == "dist" and rec["leg"] == "selftest"


# ------------------------------------------------------------------ #
# harness + real 2-process legs (rendezvous only — no collectives)
# ------------------------------------------------------------------ #

def test_harness_helpers():
    from heat2d_tpu.dist.harness import clean_env, first_error_line, free_port

    assert 0 < free_port() < 65536
    env = clean_env({"EXTRA": "1"})
    assert env["EXTRA"] == "1"
    assert "JAX_PLATFORMS" not in clean_env()
    line = first_error_line(["all fine", "x\nValueError: boom\ny"])
    assert line == "ValueError: boom"
    assert first_error_line(["nothing here"]) is None


def _require_rendezvous():
    from heat2d_tpu.dist.harness import rendezvous_unsupported_reason
    reason = rendezvous_unsupported_reason()
    if reason is not None:
        pytest.skip(f"2-process rendezvous unavailable: {reason}")


def test_real_two_process_worker_bitwise(tmp_path):
    """REAL 2-process world end to end through the worker CLI: the
    gathered final grid is bitwise the single-process program's (the
    tentpole's correctness anchor), and the kind='dist' record
    carries serving_invariant ok with the dist_* metric totals."""
    from heat2d_tpu.dist.harness import clean_env, spawn_world

    _require_rendezvous()
    nx, ny, steps, seg = 32, 24, 12, 4
    out = tmp_path / "dist_final.bin"
    rec_path = tmp_path / "rec.json"
    results = spawn_world(
        2, lambda i, coord: [
            sys.executable, "-m", "heat2d_tpu.dist.cli",
            "--coordinator", coord,
            "--num-processes", "2", "--process-id", str(i),
            "--nx", str(nx), "--ny", str(ny), "--steps", str(steps),
            "--segment", str(seg), "--heartbeat", "0.5",
            "--out", str(out), "--run-record", str(rec_path)],
        env=clean_env({"JAX_PLATFORMS": "cpu"}), timeout=300)
    assert all(r.ok for r in results), [r.output for r in results]

    got = np.fromfile(out, np.float32).reshape(nx, ny)
    ref, _ = run_process_slab(nx, ny, steps, depth=seg)
    assert got.tobytes() == ref.tobytes()

    rec = json.loads(rec_path.read_text())
    assert rec["kind"] == "dist" and rec["leg"] == "run"
    assert rec["serving_invariant"]["ok"]
    assert rec["world"]["process_count"] == 2
    assert rec["metrics"]["dist_halo_bytes_total"] > 0


@pytest.mark.slow
def test_real_soak_kill_host(tmp_path):
    """The acceptance-criteria soak: SIGKILL one host mid-run, the
    survivor recovers through the unified shrink+failover, bitwise
    parity + serving_invariant ok (CI's dist-gate runs this leg
    directly; here it is the slow-tier pytest wrapper)."""
    from heat2d_tpu.dist.harness import REPO, clean_env

    _require_rendezvous()
    rec_path = tmp_path / "soak.json"
    rc = subprocess.run(
        [sys.executable, "-m", "heat2d_tpu.dist.cli", "--soak",
         "--kill-host", "--nx", "48", "--ny", "32", "--steps", "32",
         "--segment", "4", "--checkpoint-every", "8",
         "--pace", "0.4", "--outdir", str(tmp_path),
         "--run-record", str(rec_path)],
        cwd=REPO, env=clean_env({"JAX_PLATFORMS": "cpu"}),
        capture_output=True, text=True, timeout=540)
    assert rc.returncode == 0, rc.stdout + rc.stderr
    rec = json.loads(rec_path.read_text())
    assert rec["leg"] == "soak_kill_host" and rec["verdict_ok"]
    assert rec["worker_record"]["leg"] == "host_loss_recovery"
    assert rec["worker_record"]["serving_invariant"]["ok"]
