"""Fleet subsystem (heat2d_tpu/fleet/): supervised multi-worker pool —
routing, quotas, failover replay, warm restart, chaos-driven worker
kills (ISSUE 6 acceptance criteria).

Two tiers: router-logic tests against a FAKE supervisor (no
subprocesses — the failover/quota/warmup state machines exercised
deterministically), and end-to-end tests with real worker subprocesses
under injected faults (self-kill mid-load, dropped heartbeats, the CLI
chaos soak)."""

import json
import time

import numpy as np
import pytest

from heat2d_tpu.fleet import (FleetServer, TenantPolicy, WorkerGone,
                              route_signature)
from heat2d_tpu.fleet import wire
from heat2d_tpu.obs import MetricsRegistry
from heat2d_tpu.resil.retry import DegradedMode
from heat2d_tpu.serve.schema import Rejected, SolveRequest, SolveResult

NX, NY, STEPS = 16, 16, 4


def req(cx=0.1, **kw):
    kw.setdefault("nx", NX)
    kw.setdefault("ny", NY)
    kw.setdefault("steps", STEPS)
    kw.setdefault("method", "jnp")
    return SolveRequest(cx=cx, cy=0.1, **kw)


# --------------------------------------------------------------------- #
# wire protocol
# --------------------------------------------------------------------- #

def test_wire_result_roundtrip_bitwise():
    u = np.arange(12, dtype=np.float32).reshape(3, 4) * 0.37
    res = SolveResult(u=u, steps_done=7, content_hash="abc",
                      batch_size=3)
    msg = wire.encode_result(41, res)
    assert json.loads(json.dumps(msg)) == msg      # JSON-safe
    back = wire.decode_result(msg)
    assert back.steps_done == 7 and back.content_hash == "abc"
    assert np.asarray(back.u).tobytes() == u.tobytes()
    assert np.asarray(back.u).dtype == u.dtype


def test_wire_rejection_roundtrip():
    exc = Rejected("queue_full", "depth 9 at limit 8", content_hash="h")
    back = wire.decode_rejection(wire.encode_rejection(3, exc))
    assert back.code == "queue_full"
    assert back.fields["content_hash"] == "h"
    other = wire.decode_rejection(
        wire.encode_rejection(4, ValueError("boom")))
    assert other.code == "error" and "boom" in other.message


# --------------------------------------------------------------------- #
# rendezvous routing
# --------------------------------------------------------------------- #

def test_route_signature_deterministic_and_minimally_disruptive():
    sigs = [f"sig-{i}" for i in range(64)]
    alive = [0, 1, 2]
    before = {s: route_signature(s, alive) for s in sigs}
    assert before == {s: route_signature(s, alive) for s in sigs}
    # every worker owns some share
    assert set(before.values()) == {0, 1, 2}
    # removing worker 1 remaps ONLY worker 1's signatures
    after = {s: route_signature(s, [0, 2]) for s in sigs}
    for s in sigs:
        if before[s] != 1:
            assert after[s] == before[s]
        else:
            assert after[s] in (0, 2)
    with pytest.raises(ValueError):
        route_signature("s", [])


def test_tenant_policy_validation():
    with pytest.raises(ValueError):
        TenantPolicy(max_inflight=0)
    with pytest.raises(ValueError):
        TenantPolicy(priority=-1)


# --------------------------------------------------------------------- #
# router logic against a fake supervisor (no subprocesses)
# --------------------------------------------------------------------- #

class FakeSup:
    """The Supervisor surface the router uses, minus the processes."""

    def __init__(self, alive=(0, 1)):
        self.alive = list(alive)
        self.sent = []                  # (slot, msg) in send order
        self.deaths = 0
        self.restarts = 0

    def alive_slots(self):
        return list(self.alive)

    def generations_snapshot(self):
        return []

    def send(self, slot, obj):
        if slot not in self.alive:
            raise WorkerGone(f"worker {slot} is not running")
        self.sent.append((slot, obj))

    def start(self, wait_ready=True):
        return self

    def stop(self, timeout=30.0):
        return True


def make_router(**kw):
    kw.setdefault("registry", MetricsRegistry())
    fs = FleetServer(workers=2, **kw)
    fs.sup = FakeSup()
    return fs


def answer(fs, slot, msg, u=None):
    """Worker-side completion for a dispatched envelope."""
    spec = msg["req"]
    if u is None:
        u = np.full((spec["nx"], spec["ny"]), spec["cx"],
                    dtype=np.float32)
    res = SolveResult(u=u, steps_done=spec["steps"],
                      content_hash="computed")
    fs._on_response(slot, wire.encode_result(msg["id"], res))


def test_router_dispatch_response_cache_and_coalesce():
    fs = make_router()
    r = req(cx=0.17)
    f1 = fs.submit(r)
    f2 = fs.submit(r)                   # identical, in flight: coalesce
    assert len(fs.sup.sent) == 1        # ONE dispatch for both
    slot, msg = fs.sup.sent[0]
    assert msg["req"]["cx"] == 0.17
    answer(fs, slot, msg)
    r1, r2 = f1.result(timeout=5), f2.result(timeout=5)
    assert not r1.coalesced and r2.coalesced
    assert np.asarray(r1.u).tobytes() == np.asarray(r2.u).tobytes()
    # repeat: served from the shared fleet cache, no new dispatch
    r3 = fs.submit(r).result(timeout=5)
    assert r3.cache_hit and len(fs.sup.sent) == 1
    snap = fs.registry.snapshot()
    assert snap["counters"]["fleet_cache_hits_total"] == 1
    assert snap["counters"]["fleet_coalesced_total"] == 1
    assert snap["counters"][
        "fleet_requests_total{outcome=completed}"] == 1


def test_router_worker_rejection_is_an_answer_not_a_fault():
    fs = make_router()
    f = fs.submit(req())
    slot, msg = fs.sup.sent[-1]
    fs._on_response(slot, wire.encode_rejection(
        msg["id"], Rejected("queue_full", "worker side")))
    with pytest.raises(Rejected) as e:
        f.result(timeout=5)
    assert e.value.code == "queue_full"
    assert fs.breaker.state == "closed"   # rejections never trip it


def test_router_failover_replays_to_survivor():
    fs = make_router()
    f = fs.submit(req(cx=0.3))
    slot0, msg0 = fs.sup.sent[-1]
    # the assigned worker dies with the request in flight
    fs.sup.alive = [s for s in fs.sup.alive if s != slot0]
    fs._on_worker_lost(slot0)
    assert len(fs.sup.sent) == 2
    slot1, msg1 = fs.sup.sent[-1]
    assert slot1 != slot0
    assert msg1["id"] != msg0["id"]       # fresh wire id per dispatch
    assert msg1["req"] == msg0["req"]
    answer(fs, slot1, msg1)
    assert f.result(timeout=5).steps_done == STEPS
    assert fs.replays == 1
    snap = fs.registry.snapshot()
    assert snap["counters"]["fleet_failover_replays_total"] == 1
    # a LATE answer under the dead worker's old id is dropped
    fs._on_response(slot0, wire.encode_result(
        msg0["id"], SolveResult(u=np.zeros((2, 2), np.float32),
                                steps_done=1, content_hash="stale")))


def test_router_replay_budget_exhausts_to_structured_rejection():
    fs = make_router(max_replays=1)
    f = fs.submit(req(cx=0.4))
    for _ in range(2):
        slot, _msg = fs.sup.sent[-1]
        fs._on_worker_lost(slot)
    with pytest.raises(Rejected) as e:
        f.result(timeout=5)
    assert e.value.code == "worker_lost"


def test_router_parks_without_workers_and_flushes_on_ready():
    fs = make_router()
    fs.sup.alive = []
    f = fs.submit(req(cx=0.5))
    assert not fs.sup.sent and len(fs._parked) == 1
    fs.sup.alive = [1]
    fs._on_worker_ready(1)
    assert len(fs.sup.sent) == 1
    slot, msg = fs.sup.sent[-1]
    answer(fs, slot, msg)
    assert f.result(timeout=5).steps_done == STEPS


def test_router_fleet_deadline_expires_parked_requests():
    fs = make_router(default_timeout=0.01)
    fs.sup.alive = []
    f = fs.submit(req(cx=0.6))
    time.sleep(0.05)
    fs._expire_overdue()
    with pytest.raises(Rejected) as e:
        f.result(timeout=5)
    assert e.value.code == "timeout"


def test_router_tenant_quota_and_priority_watermark():
    fs = make_router(
        max_inflight=10,
        quotas={"small": TenantPolicy(max_inflight=1),
                "batch": TenantPolicy(max_inflight=10, priority=1)})
    # per-tenant cap: second in-flight request is shed at the door
    f1 = fs.submit(req(cx=0.61), tenant="small")
    f2 = fs.submit(req(cx=0.62), tenant="small")
    with pytest.raises(Rejected) as e:
        f2.result(timeout=5)
    assert e.value.code == "quota" and e.value.fields["tenant"] == "small"
    # resolving the first frees the slot
    slot, msg = fs.sup.sent[-1]
    answer(fs, slot, msg)
    f1.result(timeout=5)
    fs.submit(req(cx=0.63), tenant="small")
    assert len(fs.sup.sent) == 2
    # watermark: standard-priority tenants shed at 80% of capacity,
    # the critical default tenant fills the reserved headroom
    futs = [fs.submit(req(cx=0.7 + 0.001 * i), tenant="batch")
            for i in range(8)]
    with pytest.raises(Rejected) as e:
        futs[-1].result(timeout=5)      # 8th standard would pass 8/10
    assert e.value.code == "overloaded"
    crit = fs.submit(req(cx=0.81))      # priority-0 default tenant
    assert not crit.done()              # admitted, waiting on a worker
    snap = fs.registry.snapshot()
    assert snap["counters"][
        "fleet_quota_rejected_total{tenant=small}"] == 1


def test_router_breaker_sheds_fresh_but_cache_answers():
    fs = make_router(breaker=DegradedMode(threshold=1, cooldown=60.0))
    warm = req(cx=0.9)
    f = fs.submit(warm)
    slot, msg = fs.sup.sent[-1]
    answer(fs, slot, msg)
    f.result(timeout=5)
    fs._on_worker_lost(0)               # death trips threshold=1
    with pytest.raises(Rejected) as e:
        fs.submit(req(cx=0.91)).result(timeout=5)
    assert e.value.code == "degraded"
    hit = fs.submit(warm).result(timeout=5)
    assert hit.cache_hit                # answers the fleet holds flow


def test_router_warm_restart_gates_routing_until_warm():
    fs = make_router()
    # serve one request: its signature becomes the hot set
    f = fs.submit(req(cx=0.2))
    slot0, msg0 = fs.sup.sent[-1]
    answer(fs, slot0, msg0)
    f.result(timeout=5)
    # a restarted worker rejoins: warmup goes to IT, marked as such
    # (a FIRST spawn never warm-gates — only replacements do)
    other = 1 - slot0
    fs._on_worker_ready(other, restarted=False)
    assert other not in fs._cold
    fs._on_worker_ready(other, restarted=True)
    warmups = [(s, m) for s, m in fs.sup.sent
               if m.get("event") == "warmup"]
    assert len(warmups) == 1 and warmups[0][0] == other
    assert other in fs._cold
    # while cold, client traffic avoids it
    n_before = len(fs.sup.sent)
    f2 = fs.submit(req(cx=0.21))
    assert fs.sup.sent[n_before][0] == slot0
    # the warm-done answer readmits the slot
    wslot, wmsg = warmups[0]
    fs._on_response(wslot, {"id": wmsg["id"], "ok": True, "warm": True})
    assert other not in fs._cold
    answer(fs, *fs.sup.sent[n_before])
    f2.result(timeout=5)
    snap = fs.registry.snapshot()
    assert snap["counters"]["fleet_worker_warmups_total"] == 1


# --------------------------------------------------------------------- #
# end to end: real worker subprocesses under injected faults
# --------------------------------------------------------------------- #

def fleet(**kw):
    kw.setdefault("registry", MetricsRegistry())
    kw.setdefault("env", {"JAX_PLATFORMS": "cpu"})
    kw.setdefault("heartbeat_timeout", 1.5)
    return FleetServer(**kw)


def oracle_grid(r):
    from heat2d_tpu.serve.server import SolveServer
    with SolveServer(registry=MetricsRegistry()) as s:
        return np.asarray(s.solve(r, timeout=120).u).tobytes()


def test_fleet_serves_and_fails_over_bitwise():
    """ISSUE acceptance (core): requests in flight on a hard-killed
    worker are replayed to a survivor; every request is answered,
    bitwise-identical to a single-worker oracle; the dead worker is
    restarted; shutdown is clean."""
    reg = MetricsRegistry()
    reqs = [req(cx=0.05 + 0.01 * i, steps=STEPS + (i % 2))
            for i in range(6)]
    with fleet(workers=2, registry=reg,
               per_worker_env={0: {"HEAT2D_CHAOS_SLOW_WORKER_S": "0.4"}}
               ) as fs:
        futs = [fs.submit(r) for r in reqs]
        time.sleep(0.2)                 # work lands on both workers
        fs.sup.kill_worker(0)
        results = [f.result(timeout=120) for f in futs]
        assert fs.sup.deaths == 1
        deadline = time.monotonic() + 30
        while (len(fs.sup.alive_slots()) < 2
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert len(fs.sup.alive_slots()) == 2   # restarted and ready
        assert fs.stop()                        # clean drain exit
    assert fs.sup.restarts >= 1
    for r, res in zip(reqs, results):
        assert np.asarray(res.u).tobytes() == oracle_grid(r)
    snap = reg.snapshot()
    assert snap["counters"][
        "fleet_worker_deaths_total{cause=exit}"] == 1
    assert snap["counters"]["fleet_worker_restarts_total"] >= 1
    assert snap["counters"][
        "fleet_requests_total{outcome=completed}"] == 6


def test_fleet_chaos_env_self_kill_parks_and_recovers():
    """A worker armed with HEAT2D_CHAOS_WORKER_KILL_AFTER dies picking
    up its 3rd request; the survivors of its queue park (single
    worker), the replacement drains them, nothing is lost."""
    reg = MetricsRegistry()
    reqs = [req(cx=0.2 + 0.01 * i) for i in range(4)]
    with fleet(workers=1, registry=reg, max_replays=5,
               per_worker_env={0: {"HEAT2D_CHAOS_WORKER_KILL_AFTER":
                                   "3"}}) as fs:
        # sequential load: the worker serves #1 and #2, dies PICKING UP
        # #3 (accepted, never answered) — the replacement, whose chaos
        # counter is fresh, drains the replay and #4
        results = [fs.solve(r, timeout=120) for r in reqs]
        assert fs.sup.deaths >= 1 and fs.sup.restarts >= 1
    assert len(results) == 4
    for r, res in zip(reqs, results):
        assert np.asarray(res.u).tobytes() == oracle_grid(r)


def test_fleet_heartbeat_drop_is_detected_and_fenced():
    """A worker that goes silent but keeps running (dropped heartbeats
    — the gray failure) is declared dead on heartbeat age, killed, and
    replaced; traffic keeps flowing."""
    reg = MetricsRegistry()
    # 25 beats at 0.1s: the worker serves its first request, then goes
    # silent while IDLE — responses also count as liveness, so only an
    # idle-and-silent worker ages past the heartbeat timeout
    with fleet(workers=1, registry=reg, max_replays=5,
               heartbeat_interval=0.1, heartbeat_timeout=0.8,
               per_worker_env={0: {"HEAT2D_CHAOS_HEARTBEAT_DROP_AFTER":
                                   "25"}}) as fs:
        first = fs.solve(req(cx=0.31), timeout=120)
        deadline = time.monotonic() + 60
        while fs.sup.deaths < 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert fs.sup.deaths >= 1
        # the replacement (same env: it will drop heartbeats too and
        # die again eventually) still serves fresh load meanwhile
        second = fs.solve(req(cx=0.32), timeout=120)
    assert first.steps_done == STEPS and second.steps_done == STEPS
    snap = reg.snapshot()
    assert snap["counters"].get(
        "fleet_worker_deaths_total{cause=heartbeat}", 0) >= 1


def test_fleet_stop_start_cycle_rearms_monitoring():
    """A stop()/start() cycle must re-arm the monitor (regression: a
    stale stop event left failure detection silently dead), and a
    stopped fleet answers submits with Rejected('shutdown') instead of
    parking a future nobody will resolve."""
    fs = fleet(workers=1)
    fs.start()
    assert fs.solve(req(cx=0.41), timeout=120).steps_done == STEPS
    fs.stop()
    with pytest.raises(Rejected) as e:
        fs.solve(req(cx=0.42), timeout=5)
    assert e.value.code == "shutdown"
    fs.start()
    try:
        assert fs.solve(req(cx=0.43), timeout=120).steps_done == STEPS
        # the re-armed monitor still detects kills and restarts
        fs.sup.kill_worker(0)
        deadline = time.monotonic() + 30
        while fs.sup.deaths < 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert fs.sup.deaths == 1
        assert fs.solve(req(cx=0.44), timeout=120).steps_done == STEPS
    finally:
        fs.stop()


def test_fleet_cli_chaos_soak(tmp_path):
    """ISSUE acceptance, end to end through the CLI: sustained load, 1
    of 2 workers killed mid-soak, zero incorrect results (bitwise
    oracle), nothing silently lost, throughput recovered, clean exit —
    the CLI exits 0 iff all of it held. Telemetry lands as a
    kind='fleet' run record with the new metric families."""
    from heat2d_tpu.fleet.cli import main

    out = tmp_path / "fleet.jsonl"
    # 10s soak, kill at 5s, 3s windows: the post-restart window starts
    # after the failover blip (survivor compiles the dead worker's
    # share) and contains the restarted worker's warm rejoin
    rc = main(["--workers", "2", "--soak", "10", "--window", "3",
               "--chaos", "--concurrency", "4",
               "--metrics-out", str(out)])
    assert rc == 0
    lines = [json.loads(l) for l in out.read_text().splitlines()]
    rec = [l for l in lines if l.get("event") == "run_record"][0]
    assert rec["kind"] == "fleet"
    assert rec["completed"] == rec["submitted"] > 0
    assert rec["deaths"] >= 1 and rec["restarts"] >= 1
    assert rec["clean_exit"] is True
    assert rec["pre_kill_rps"] > 0
    assert rec["throughput_recovery_s"] is not None
    assert rec["post_restart_rps"] >= 0.8 * rec["pre_kill_rps"]
    snap = [l for l in lines if l.get("event") == "snapshot"][0]
    # the snapshot is written post-shutdown: the gauge exists and ends 0
    assert snap["gauges"]["fleet_workers_alive"] == 0
    assert snap["counters"]["fleet_worker_restarts_total"] >= 1
    assert "fleet_e2e_latency_s" in snap["histograms"]
    assert snap["gauges"][
        "fleet_throughput_rps{window=post_restart}"] > 0
