"""Mesh fault tolerance (ISSUE 15): ABFT checksum recurrences
(ops/abft.py), the device chaos campaigns' strict env contract, the
quarantine book + hung-collective watchdog (mesh/health.py), shrink-
and-requeue recovery with bitwise parity (mesh/degrade.py + the
guarded engine), the no-quarantined-serving invariant, and the
control plane's quarantine feed. Device-shrink scenarios need the
8-device sim mesh (CI mesh-chaos-gate); everything else runs at any
device count."""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from heat2d_tpu.mesh import (FaultPolicy, HealthMonitor,
                             MeshEnsembleEngine, MeshStallError,
                             mesh_batch_runner, mesh_capacity)
from heat2d_tpu.mesh import degrade, health
from heat2d_tpu.obs.metrics import MetricsRegistry
from heat2d_tpu.ops import abft
from heat2d_tpu.ops.init import inidat
from heat2d_tpu.ops.stencil import stencil_step
from heat2d_tpu.resil import chaos
from heat2d_tpu.resil.retry import wait_for
from heat2d_tpu.serve.engine import EnsembleEngine
from heat2d_tpu.serve.schema import Rejected, SolveRequest
from tests._pin import assert_jaxpr_differs, assert_jaxpr_equal, \
    mesh_runner_jaxpr

ND = len(jax.devices())
NX, NY, STEPS = 16, 20, 6

multichip = pytest.mark.skipif(ND < 8, reason="needs 8 devices")


@pytest.fixture(autouse=True)
def _no_chaos():
    chaos.uninstall()
    yield
    chaos.uninstall()


def req(cx=0.1, cy=0.1, **kw):
    kw.setdefault("nx", NX)
    kw.setdefault("ny", NY)
    kw.setdefault("steps", STEPS)
    kw.setdefault("method", "jnp")
    return SolveRequest(cx=cx, cy=cy, **kw)


def reqs(n, base=0.1, **kw):
    return [req(cx=base + 0.01 * i, **kw) for i in range(n)]


def grids(pairs):
    return [np.asarray(u).tobytes() for u, _ in pairs]


def counters(reg):
    return reg.snapshot()["counters"]


# --------------------------------------------------------------------- #
# ABFT — the checksum recurrence (ops/abft.py)
# --------------------------------------------------------------------- #

def _run_explicit(u0, cx, cy, steps):
    u = jnp.asarray(u0)
    for _ in range(steps):
        u = stencil_step(u, cx, cy)
    return np.asarray(u)


def test_explicit_recurrence_with_boundary_flux():
    """Nonzero edges: the closed-form prediction (mode factor +
    constant flux) tracks the real f32 run to roundoff."""
    rng = np.random.default_rng(7)
    u0 = rng.uniform(0.0, 2.0, (NX, NY)).astype(np.float32)
    cx, cy = 0.22, 0.15
    T = 40
    uT = _run_explicit(u0, cx, cy, T)
    s_obs = float(abft.host_checksum(uT))
    s_pred = abft.host_predict(u0, cx, cy, T, method="jnp")
    w = abft.mode_weights(NX, NY)
    scale = float(np.einsum("ij,ij->", np.abs(u0), w)) + abs(
        float(abft.host_checksum(u0)))
    assert not abft.classify(s_obs, s_pred, scale, T)
    # and the flux term is LOAD-BEARING: dropping it must miss
    beta = float(abft.boundary_flux(
        np.asarray(u0, np.float64), w, cx, cy))
    assert beta != 0.0
    alpha = abft.step_factor("explicit", NX, NY, cx, cy)
    no_flux = (alpha ** T) * float(abft.host_checksum(u0))
    assert abft.classify(s_obs, no_flux, scale, T)


def test_explicit_flux_zero_for_zero_edges():
    u0 = np.asarray(inidat(NX, NY))
    w = abft.mode_weights(NX, NY)
    assert float(abft.boundary_flux(u0, w, 0.2, 0.2)) == 0.0


def test_adi_recurrence_zero_edges():
    from heat2d_tpu.ops.tridiag import adi_multi_step

    u0 = np.asarray(inidat(NX, NY))
    T = 30
    cx, cy = 0.4, 0.3         # implicit: outside the explicit box
    uT = np.asarray(adi_multi_step(jnp.asarray(u0), T, cx, cy))
    s_pred = abft.host_predict(u0, cx, cy, T, method="adi")
    w = abft.mode_weights(NX, NY)
    scale = float(np.einsum("ij,ij->", np.abs(u0), w)) + abs(
        float(abft.host_checksum(u0)))
    assert not abft.classify(abft.host_checksum(uT), s_pred, scale, T)


def test_flip_detected_healthy_passes():
    u0 = np.asarray(inidat(NX, NY))
    T = 25
    uT = _run_explicit(u0, 0.2, 0.18, T)
    s_pred = abft.host_predict(u0, 0.2, 0.18, T, method="jnp")
    w = abft.mode_weights(NX, NY)
    scale = float(np.einsum("ij,ij->", np.abs(u0), w)) + abs(
        float(abft.host_checksum(u0)))
    assert not abft.classify(abft.host_checksum(uT), s_pred, scale, T)
    bad = uT.copy()
    bad.view(np.uint32)[NX // 2, NY // 2] ^= np.uint32(1 << 30)
    assert abft.classify(abft.host_checksum(bad), s_pred, scale, T)


def test_power_negative_base_traced():
    alphas = jnp.asarray([-0.5, 0.5, -1.0, 0.0, 1.0], jnp.float32)
    ks = jnp.asarray([3, 4, 5, 2, 0], jnp.int32)
    got = np.asarray(jax.jit(abft._power)(alphas, ks))
    want = np.asarray([(-0.5) ** 3, 0.5 ** 4, -1.0, 0.0, 1.0],
                      np.float32)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-7)
    # k == 0 is 1 even at alpha == 0
    assert float(jax.jit(abft._power)(
        jnp.float32(0.0), jnp.int32(0))) == 1.0


def test_supported_family_vocabulary():
    assert abft.supported_family("jnp") == "explicit"
    assert abft.supported_family("pallas") == "explicit"
    assert abft.supported_family("band") == "explicit"
    assert abft.supported_family("adi") == "adi"
    assert abft.supported_family("mg") is None
    with pytest.raises(ValueError):
        abft.host_predict(np.zeros((4, 4)), 0.1, 0.1, 2, method="mg")


def test_predict_batch_traced_matches_host_oracle():
    B = 3
    u0 = np.stack([np.asarray(inidat(NX, NY))] * B)
    cxs = jnp.asarray([0.1, 0.2, 0.24], jnp.float32)
    cys = jnp.asarray([0.12, 0.15, 0.2], jnp.float32)
    k = jnp.asarray([STEPS] * B, jnp.int32)
    w = jnp.asarray(abft.mode_weights(NX, NY), jnp.float32)
    s_pred, scale = jax.jit(
        lambda a, b, c, d: abft.predict_batch(a, b, c, d, w,
                                              family="explicit"))(
        jnp.asarray(u0), cxs, cys, k)
    for i in range(B):
        want = abft.host_predict(u0[i], float(cxs[i]), float(cys[i]),
                                 STEPS, method="jnp")
        got = float(np.asarray(s_pred)[i])
        tol = float(abft.tolerance(float(np.asarray(scale)[i]), STEPS))
        assert abs(got - want) <= tol


# --------------------------------------------------------------------- #
# chaos — strict env contract for the three device campaigns
# --------------------------------------------------------------------- #

@pytest.mark.parametrize("var", [
    "HEAT2D_CHAOS_DEVICE_FAIL_AT", "HEAT2D_CHAOS_DEVICE_FAIL_INDEX",
    "HEAT2D_CHAOS_HANG_COLLECTIVE", "HEAT2D_CHAOS_FLIP_BIT"])
def test_chaos_env_garbage_raises_naming_the_var(var):
    with pytest.raises(ValueError, match=var):
        chaos.ChaosConfig.from_env({var: "lots"})


def test_chaos_env_hang_seconds_garbage_raises():
    with pytest.raises(ValueError,
                       match="HEAT2D_CHAOS_HANG_COLLECTIVE_S"):
        chaos.ChaosConfig.from_env(
            {"HEAT2D_CHAOS_HANG_COLLECTIVE": "1",
             "HEAT2D_CHAOS_HANG_COLLECTIVE_S": "soon"})


def test_chaos_env_unset_empty_zero_are_off():
    assert chaos.ChaosConfig.from_env({}) is None
    assert chaos.ChaosConfig.from_env(
        {"HEAT2D_CHAOS_DEVICE_FAIL_AT": "",
         "HEAT2D_CHAOS_HANG_COLLECTIVE": "0",
         "HEAT2D_CHAOS_FLIP_BIT": "0"}) is None
    cfg = chaos.ChaosConfig(device_fail_at=0, hang_collective=0,
                            flip_bit=0)
    assert not cfg.any_active()


def test_chaos_env_armed_parses():
    cfg = chaos.ChaosConfig.from_env(
        {"HEAT2D_CHAOS_DEVICE_FAIL_AT": "2",
         "HEAT2D_CHAOS_DEVICE_FAIL_INDEX": "3",
         "HEAT2D_CHAOS_HANG_COLLECTIVE": "4",
         "HEAT2D_CHAOS_HANG_COLLECTIVE_S": "0.5",
         "HEAT2D_CHAOS_FLIP_BIT": "1"})
    assert cfg is not None and cfg.any_active()
    assert (cfg.device_fail_at, cfg.device_fail_index) == (2, 3)
    assert (cfg.hang_collective, cfg.hang_collective_s) == (4, 0.5)
    assert cfg.flip_bit == 1


def test_device_fail_fires_at_ordinal_and_kills_probes():
    chaos.install(chaos.ChaosConfig(device_fail_at=2,
                                    device_fail_index=1))
    chaos.mesh_launch_point()             # attempt 1: healthy
    assert chaos.device_probe_point(1)
    with pytest.raises(chaos.DeviceLostError) as ei:
        chaos.mesh_launch_point()         # attempt 2: the kill
    assert ei.value.device_index == 1
    assert not chaos.device_probe_point(1)    # dead stays dead
    assert chaos.device_probe_point(0)
    chaos.mesh_launch_point()             # attempt 3: no re-fire


def test_hang_collective_blocks_and_marks_dead():
    chaos.install(chaos.ChaosConfig(hang_collective=1,
                                    hang_collective_s=0.2,
                                    device_fail_index=2))
    t0 = time.monotonic()
    chaos.mesh_launch_point()
    assert time.monotonic() - t0 >= 0.2
    assert not chaos.device_probe_point(2)


def test_flip_bit_point_only_at_armed_ordinal():
    chaos.install(chaos.ChaosConfig(flip_bit=2))
    chaos.mesh_launch_point()
    assert chaos.flip_bit_point() is None
    chaos.mesh_launch_point()
    assert chaos.flip_bit_point() == 30
    chaos.mesh_launch_point()
    assert chaos.flip_bit_point() is None


def test_chaos_idle_hooks_are_noops():
    assert chaos.flip_bit_point() is None
    assert chaos.device_probe_point(0)
    chaos.mesh_launch_point()     # must not raise


# --------------------------------------------------------------------- #
# jaxpr pins — chaos-armed == disarmed; ABFT is a separate program
# --------------------------------------------------------------------- #

def test_mesh_runner_jaxpr_chaos_armed_equals_disarmed():
    """Arming every device campaign changes NOTHING in the traced
    mesh program — chaos lives on the host orchestration only."""
    base = mesh_runner_jaxpr()
    chaos.install(chaos.ChaosConfig(device_fail_at=5,
                                    hang_collective=6, flip_bit=7))
    armed = mesh_runner_jaxpr()
    assert_jaxpr_equal(armed, base, "chaos-armed mesh runner")


def test_abft_runner_is_its_own_program():
    plain = mesh_batch_runner(NX, NY, STEPS, "jnp")
    armed = mesh_batch_runner(NX, NY, STEPS, "jnp", abft=True)
    assert plain is not armed and armed.abft
    assert_jaxpr_differs(
        mesh_runner_jaxpr(NX, NY, STEPS, abft=True),
        mesh_runner_jaxpr(NX, NY, STEPS),
        "abft runner vs plain")


def test_abft_runner_results_bitwise_equal_plain():
    plain = mesh_batch_runner(NX, NY, STEPS, "jnp")
    armed = mesh_batch_runner(NX, NY, STEPS, "jnp", abft=True)
    b = ND
    u0 = jnp.broadcast_to(inidat(NX, NY), (b, NX, NY))
    cs = jnp.linspace(0.1, 0.2, b, dtype=jnp.float32)
    u_armed, k, s_obs, s_pred, scale = armed(u0, cs, cs)
    u_plain = plain(u0, cs, cs)
    assert np.asarray(u_armed).tobytes() == np.asarray(u_plain).tobytes()
    assert not np.any(abft.classify(np.asarray(s_obs),
                                    np.asarray(s_pred),
                                    np.asarray(scale), STEPS))


def test_mesh_runner_device_subset():
    sub = tuple(range(max(1, ND - 1)))
    run = mesh_batch_runner(NX, NY, STEPS, "jnp", device_indices=sub)
    assert run.n_devices == len(sub)
    b = len(sub)
    u0 = jnp.broadcast_to(inidat(NX, NY), (b, NX, NY))
    cs = jnp.linspace(0.1, 0.2, b, dtype=jnp.float32)
    full = mesh_batch_runner(NX, NY, STEPS, "jnp")(
        jnp.broadcast_to(inidat(NX, NY), (ND, NX, NY)),
        jnp.pad(cs, (0, ND - b), mode="edge"),
        jnp.pad(cs, (0, ND - b), mode="edge"))
    got = run(u0, cs, cs)
    assert (np.asarray(got).tobytes()
            == np.asarray(full)[:b].tobytes())


# --------------------------------------------------------------------- #
# health — quarantine book, probes, the stall guard
# --------------------------------------------------------------------- #

def test_health_monitor_book():
    reg = MetricsRegistry()
    m = HealthMonitor(n_devices=4, registry=reg)
    assert m.survivors() == (0, 1, 2, 3)
    assert m.capacity_fraction() == 1.0
    assert m.quarantine(2, "device_fail")
    assert not m.quarantine(2, "device_fail")     # idempotent
    assert m.is_quarantined(2)
    assert m.survivors() == (0, 1, 3)
    assert m.capacity_fraction() == 0.75
    snap = m.snapshot()
    assert snap["quarantined"] == [2]
    assert snap["events"][0]["reason"] == "device_fail"
    c = counters(reg)
    assert c["mesh_quarantine_total{reason=device_fail}"] == 1.0
    assert reg.snapshot()["gauges"]["mesh_quarantined_devices"] == 1.0
    with pytest.raises(ValueError):
        m.quarantine(9, "device_fail")
    with pytest.raises(ValueError):
        m.quarantine(0, "bored")


def test_health_seq_orders_events():
    m = HealthMonitor(n_devices=3)
    fence = m.seq()
    m.quarantine(0, "probe_failure")
    assert m.seq() == fence + 1
    assert m.snapshot()["events"][0]["seq"] == fence + 1


def test_probe_sweep_quarantines_chaos_dead_device():
    chaos.install(chaos.ChaosConfig(device_fail_at=1,
                                    device_fail_index=0))
    with pytest.raises(chaos.DeviceLostError):
        chaos.mesh_launch_point()
    reg = MetricsRegistry()
    m = HealthMonitor(n_devices=min(ND, 2), registry=reg)
    out = m.probe()
    assert out[0] is False
    assert m.is_quarantined(0)
    assert counters(reg)["mesh_probe_failures_total"] >= 1.0


def test_probe_device_real_roundtrip():
    assert health.probe_device(0)


def test_guarded_call_passthrough_and_errors():
    assert health.guarded_call(lambda: 7, None) == 7
    assert health.guarded_call(lambda: 7, 5.0) == 7
    with pytest.raises(KeyError):
        health.guarded_call(lambda: {}["x"], 5.0)


def test_guarded_call_stall_discards_late_result():
    release = threading.Event()
    discards = []
    t = [0.0]

    def clock():
        return t[0]

    def slow():
        release.wait(5.0)
        return "late"

    def run():
        with pytest.raises(MeshStallError):
            health.guarded_call(slow, 1.0, clock=clock,
                                on_discard=lambda: discards.append(1))

    th = threading.Thread(target=run)
    th.start()
    time.sleep(0.05)          # the guard is polling a frozen clock
    assert th.is_alive()
    t[0] = 2.0                # NOW the deadline has passed
    th.join(5.0)
    assert not th.is_alive()
    assert discards == []     # the slow call hasn't finished yet
    release.set()
    deadline = time.monotonic() + 5.0
    while not discards and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(discards) == 1     # late result observed as DISCARDED


def test_fault_policy_validation():
    with pytest.raises(ValueError):
        FaultPolicy(max_requeues=-1)
    with pytest.raises(ValueError):
        FaultPolicy(stall_deadline_s=0.0)
    p = FaultPolicy()
    assert p.stall_deadline_s is None and not p.abft


def test_member_owner_contiguous():
    devs = (0, 2, 3, 5)
    assert [degrade.member_owner(m, 8, devs) for m in range(8)] \
        == [0, 0, 2, 2, 3, 3, 5, 5]


def test_serving_invariant_detects_violation():
    m = HealthMonitor(n_devices=4)
    m.quarantine(1, "device_fail")
    good = {"signature": "s", "mesh": {"devices": [0, 2, 3],
                                       "health_seq": m.seq()}}
    # a launch claiming to have chosen device 1 AFTER its quarantine
    bad = {"signature": "s", "mesh": {"devices": [0, 1],
                                      "health_seq": m.seq()}}
    ok = degrade.serving_invariant(m, [good])
    assert ok["ok"] and ok["checked"] == 1
    res = degrade.serving_invariant(m, [good, bad])
    assert not res["ok"] and res["violations"][0]["device"] == 1


def test_wait_for_deadline_and_injected_clock():
    assert wait_for(lambda: True, None)
    assert wait_for(lambda: True, 0.001)
    t0 = time.monotonic()
    assert not wait_for(lambda: False, 0.05)
    assert time.monotonic() - t0 < 2.0
    # injected clock: each poll advances it far past the deadline,
    # so the watchdog fires on modeled time, not wall time
    ticks = iter(range(0, 10_000, 100))
    assert not wait_for(lambda: False, 50.0,
                        clock=lambda: float(next(ticks)), poll=0.001)


# --------------------------------------------------------------------- #
# engine — guarded behavior at ANY device count
# --------------------------------------------------------------------- #

def test_engine_without_fault_has_no_fault_state():
    eng = MeshEnsembleEngine(registry=MetricsRegistry())
    assert eng.health is None and eng.degrader is None
    assert eng.fault_snapshot() is None
    out = eng.solve_batch(reqs(min(3, ND) or 1))
    assert len(out) == min(3, ND) or 1
    row = eng.launch_log[-1]
    assert "devices" not in row.get("mesh", {})


def _batch_decision(eng, r0):
    """A batch-route decision row (the scheduler routes 'single' on
    1-device processes; the guarded path itself is device-count
    agnostic)."""
    return {"route": "batch", "reason": "fits_chip",
            "signature": str(r0.signature()),
            "n_devices": eng.n_devices}


def test_device_loss_with_no_survivors_propagates_and_quarantines():
    chaos.install(chaos.ChaosConfig(device_fail_at=1,
                                    device_fail_index=0))
    reg = MetricsRegistry()
    eng = MeshEnsembleEngine(registry=reg, n_devices=1,
                             fault=FaultPolicy())
    rs = reqs(1)
    with pytest.raises(chaos.DeviceLostError):
        eng._solve_batch_mesh(rs, _batch_decision(eng, rs[0]))
    assert eng.health.quarantined() == (0,)
    # nothing served: the launch log has no served mesh row
    assert all("devices" not in (r.get("mesh") or {})
               for r in eng.launch_log)
    # and the NEXT request is a structured rejection, not a crash
    with pytest.raises(Rejected) as ei:
        eng._solve_batch_mesh(rs, _batch_decision(eng, rs[0]))
    assert ei.value.code == "mesh_degraded"


def test_stall_budget_exhausted_is_rejected_mesh_stall():
    chaos.install(chaos.ChaosConfig(hang_collective=2,
                                    hang_collective_s=0.4,
                                    device_fail_index=0))
    reg = MetricsRegistry()
    eng = MeshEnsembleEngine(
        registry=reg, n_devices=1,
        fault=FaultPolicy(stall_deadline_s=0.05))
    rs = reqs(1)
    eng._solve_batch_mesh(rs, _batch_decision(eng, rs[0]))  # warm
    with pytest.raises(Rejected) as ei:
        eng._solve_batch_mesh(rs, _batch_decision(eng, rs[0]))
    assert ei.value.code == "mesh_stall"
    assert eng.health.quarantined() == (0,)
    assert counters(reg)["mesh_stall_total"] >= 1.0


def test_runtime_error_without_conviction_propagates_unrequeued():
    """An accelerator runtime error that names no device and whose
    probe sweep convicts nobody is NOT a device fault: the guarded
    loop must propagate it (the server's transient classification
    owns it), not relaunch the same failing program through the
    requeue budget."""
    XlaRuntimeError = type("XlaRuntimeError", (RuntimeError,), {})
    reg = MetricsRegistry()
    eng = MeshEnsembleEngine(registry=reg, n_devices=1,
                             fault=FaultPolicy())
    calls = []

    def boom(requests, device_indices, abft):
        calls.append(1)
        raise XlaRuntimeError("deterministic launch failure")

    eng._launch_batch = boom
    rs = reqs(1)
    with pytest.raises(XlaRuntimeError):
        eng._solve_batch_mesh(rs, _batch_decision(eng, rs[0]))
    assert len(calls) == 1                    # no requeue
    assert eng.health.quarantined() == ()     # no conviction
    assert "mesh_requeue_total{cause=device_fail}" not in counters(reg)


def test_abft_unsupported_method_served_and_counted():
    reg = MetricsRegistry()
    eng = MeshEnsembleEngine(registry=reg, n_devices=1,
                             fault=FaultPolicy(abft=True))
    rs = reqs(1, method="mg", steps=4)
    out = eng._solve_batch_mesh(rs, _batch_decision(eng, rs[0]))
    assert len(out) == 1
    assert counters(reg)["mesh_abft_unsupported_total{reason=mg}"] \
        == 1.0


# --------------------------------------------------------------------- #
# engine — shrink-and-requeue on the 8-device mesh (the CI gate's
# in-suite twins)
# --------------------------------------------------------------------- #

@multichip
def test_device_loss_shrinks_and_recovers_bitwise():
    oracle = grids(EnsembleEngine(max_batch=8).solve_batch(reqs(5)))
    chaos.install(chaos.ChaosConfig(device_fail_at=1,
                                    device_fail_index=3))
    reg = MetricsRegistry()
    eng = MeshEnsembleEngine(registry=reg, fault=FaultPolicy())
    out = eng.solve_batch(reqs(5))
    assert grids(out) == oracle
    assert eng.health.quarantined() == (3,)
    row = eng.launch_log[-1]["mesh"]
    assert row["devices"] == [0, 1, 2, 4, 5, 6, 7]
    assert row["degraded"] is True
    rec = row["recovery"]
    assert rec["cause"] == "device_fail" and rec["recovery_s"] > 0
    snap = eng.fault_snapshot()
    assert snap["invariant"]["ok"]
    assert counters(reg)["mesh_requeue_total{cause=device_fail}"] \
        == 1.0


@multichip
def test_flip_bit_abft_detects_quarantines_recovers_bitwise():
    oracle = grids(EnsembleEngine(max_batch=8).solve_batch(reqs(5)))
    chaos.install(chaos.ChaosConfig(flip_bit=1))
    reg = MetricsRegistry()
    eng = MeshEnsembleEngine(registry=reg,
                             fault=FaultPolicy(abft=True))
    out = eng.solve_batch(reqs(5))
    assert grids(out) == oracle
    # member 0's owner (device 0) was convicted of silent corruption
    assert eng.health.quarantined() == (0,)
    assert eng.health.snapshot()["events"][0]["reason"] \
        == "silent_corruption"
    c = counters(reg)
    assert c["mesh_abft_mismatch_total"] >= 1.0
    assert c["mesh_requeue_total{cause=silent_corruption}"] == 1.0
    assert eng.fault_snapshot()["invariant"]["ok"]


@multichip
def test_flip_bit_without_abft_is_served_corrupt():
    """The vulnerability the verify tier exists for: without ABFT the
    flipped result IS served (and differs from the oracle)."""
    oracle = grids(EnsembleEngine(max_batch=8).solve_batch(reqs(5)))
    chaos.install(chaos.ChaosConfig(flip_bit=1))
    eng = MeshEnsembleEngine(registry=MetricsRegistry(),
                             fault=FaultPolicy(abft=False))
    out = eng.solve_batch(reqs(5))
    assert grids(out) != oracle


@multichip
def test_hang_stall_detected_shrinks_recovers_bitwise():
    # the recovery pays a cold compile on the 7-survivor mesh; the
    # hang must comfortably exceed deadline + compile or the
    # beat-the-hang assertion races the XLA compiler, not the watchdog
    hang_s = 3.0
    base = 0.3
    victims = reqs(5, base=base)
    oracle = grids(EnsembleEngine(max_batch=8).solve_batch(victims))
    chaos.install(chaos.ChaosConfig(hang_collective=2,
                                    hang_collective_s=hang_s,
                                    device_fail_index=2))
    reg = MetricsRegistry()
    eng = MeshEnsembleEngine(
        registry=reg, fault=FaultPolicy(stall_deadline_s=0.25,
                                        max_requeues=3))
    eng.solve_batch(reqs(5))                  # warm (attempt 1)
    t0 = time.monotonic()
    out = eng.solve_batch(victims)
    recovered = time.monotonic() - t0
    assert grids(out) == oracle
    assert recovered < hang_s                # the watchdog BEAT the hang
    assert 2 in eng.health.quarantined()
    # a stall-sweep conviction carries the stall's own reason label —
    # the documented mesh_quarantine_total{reason} vocabulary is
    # reachable end to end
    assert [e["reason"] for e in eng.health.snapshot()["events"]
            if e["device"] == 2] == ["mesh_stall"]
    assert eng.fault_snapshot()["invariant"]["ok"]
    # the abandoned launch's late result is discarded, observably
    deadline = time.monotonic() + hang_s + 3.0
    while time.monotonic() < deadline:
        c = counters(reg)
        if c.get("mesh_discarded_results_total{cause=mesh_stall}"):
            break
        time.sleep(0.05)
    assert c["mesh_discarded_results_total{cause=mesh_stall}"] >= 1.0
    assert c["mesh_stall_total"] >= 1.0


@multichip
def test_spatial_signature_degrades_to_survivor_batch_bitwise():
    from heat2d_tpu.mesh.scheduler import MeshScheduler

    reg = MetricsRegistry()
    sched = MeshScheduler(registry=reg, spatial_bytes_threshold=1)
    eng = MeshEnsembleEngine(registry=reg, scheduler=sched,
                             fault=FaultPolicy())
    rs = reqs(3)
    assert sched.decide(rs[0])["route"] == "spatial"
    eng.health.quarantine(4, "device_fail")
    out = eng.solve_batch(rs)
    oracle = grids(EnsembleEngine(max_batch=8).solve_batch(rs))
    assert grids(out) == oracle
    row = eng.launch_log[-1]["mesh"]
    assert row["route"] == "batch" and row["reason"] == "quarantined"
    assert 4 not in row["devices"]
    assert counters(reg)["mesh_fallback_total{reason=quarantined}"] \
        == 1.0


@multichip
def test_spatial_route_device_loss_reroutes_to_survivors_bitwise():
    """A chip dying MID-SPATIAL-LAUNCH is classified like the batch
    route's failures — quarantine, then the same batch re-dispatches
    onto the survivor batch mesh bitwise — instead of propagating raw
    and failing forever on retries of the identical full-mesh
    program."""
    from heat2d_tpu.mesh.scheduler import MeshScheduler

    rs = reqs(3)
    oracle = grids(EnsembleEngine(max_batch=8).solve_batch(rs))
    chaos.install(chaos.ChaosConfig(device_fail_at=1,
                                    device_fail_index=2))
    reg = MetricsRegistry()
    sched = MeshScheduler(registry=reg, spatial_bytes_threshold=1)
    eng = MeshEnsembleEngine(registry=reg, scheduler=sched,
                             fault=FaultPolicy())
    assert sched.decide(rs[0])["route"] == "spatial"
    out = eng.solve_batch(rs)
    assert grids(out) == oracle
    assert eng.health.quarantined() == (2,)
    row = eng.launch_log[-1]["mesh"]
    assert row["route"] == "batch" and row["reason"] == "quarantined"
    assert 2 not in row["devices"]
    assert counters(reg)["mesh_requeue_total{cause=device_fail}"] \
        == 1.0
    assert eng.degrader.events[-1]["cause"] == "device_fail"
    assert eng.degrader.events[-1]["recovery_s"] > 0
    assert eng.fault_snapshot()["invariant"]["ok"]


def test_hung_probe_convicts_within_deadline(monkeypatch):
    """A gray-failing device can HANG its probe, not just fail it —
    the sweep bounds each round trip so a wedged chip cannot wedge
    the very recovery path the stall watchdog hands off to."""
    m = HealthMonitor(n_devices=1)
    monkeypatch.setattr(health, "PROBE_DEADLINE_S", 0.1)
    release = threading.Event()

    def hang(_index):
        release.wait(10.0)
        return True

    monkeypatch.setattr(health, "probe_device", hang)
    t0 = time.monotonic()
    out = m.probe()
    took = time.monotonic() - t0
    release.set()
    assert out[0] is False and m.is_quarantined(0)
    assert took < 5.0            # bounded, not the 10s hang


def test_fault_clock_threads_into_health_monitor():
    """One clock domain for the whole fault stack: quarantine event
    stamps, detection, and recovery rows all read the injected
    fault_clock."""
    eng = MeshEnsembleEngine(registry=MetricsRegistry(), n_devices=1,
                             fault=FaultPolicy(),
                             fault_clock=lambda: 42.0)
    eng.health.quarantine(0, "device_fail")
    assert eng.health.snapshot()["events"][0]["t"] == 42.0
    assert eng.degrader.now() == 42.0


def test_serve_cli_mesh_flags_require_mesh():
    """Mesh-dependent serve flags without --mesh are a usage error
    (rc 2), never a silently-unarmed run."""
    from heat2d_tpu.serve import cli

    for argv in (["--mesh-abft"], ["--mesh-stall-deadline", "5"],
                 ["--mesh-admission-mcells", "100"]):
        with pytest.raises(SystemExit) as ei:
            cli.main(argv + ["--selftest"])
        assert ei.value.code == 2


@multichip
def test_single_route_pins_to_survivor_and_stamps_invariant():
    """The single-chip fallback may not serve from a convicted chip:
    an unpinned jit computes on the DEFAULT device — exactly the
    quarantined one after a device-0 conviction — so the guarded
    engine pins the launch to the first survivor and stamps devices +
    the health fence, bringing this route under the
    no-quarantined-serving invariant instead of past it."""
    from heat2d_tpu.mesh.scheduler import MeshScheduler

    reg = MetricsRegistry()
    sched = MeshScheduler(registry=reg, spatial_bytes_threshold=1)
    eng = MeshEnsembleEngine(registry=reg, scheduler=sched,
                             fault=FaultPolicy())
    eng.health.quarantine(0, "silent_corruption")
    rs = reqs(2, nx=15, ny=18)          # unplannable -> single route
    assert sched.decide(rs[0])["route"] == "single"
    oracle = grids(EnsembleEngine(max_batch=8).solve_batch(rs))
    assert grids(eng.solve_batch(rs)) == oracle
    row = eng.launch_log[-1]["mesh"]
    assert row["route"] == "single"
    assert row["devices"] == [1]        # OFF the convicted device 0
    assert row["health_seq"] == 1
    assert eng.fault_snapshot()["invariant"]["ok"]
    # the fence is load-bearing: the same launch attributed to the
    # convicted device would be flagged
    row["devices"] = [0]
    assert not eng.fault_snapshot()["invariant"]["ok"]


def test_single_route_all_quarantined_is_rejected():
    reg = MetricsRegistry()
    eng = MeshEnsembleEngine(registry=reg, n_devices=1,
                             fault=FaultPolicy())
    eng.health.quarantine(0, "device_fail")
    with pytest.raises(Rejected) as ei:
        eng.solve_batch(reqs(1))
    assert ei.value.code == "mesh_degraded"


@multichip
def test_requeue_capacity_repads_to_survivor_multiple():
    """After a shrink to 7 devices the padded capacity is a 7-multiple
    (the compile ladder per mesh shape), not the old 8-multiple."""
    chaos.install(chaos.ChaosConfig(device_fail_at=1,
                                    device_fail_index=6))
    eng = MeshEnsembleEngine(registry=MetricsRegistry(),
                             fault=FaultPolicy())
    eng.solve_batch(reqs(5))
    row = eng.launch_log[-1]
    assert len(row["mesh"]["devices"]) == 7
    assert row["capacity"] % 7 == 0
    assert row["capacity"] == mesh_capacity(5, eng.max_batch, 7)


@multichip
def test_recovery_through_solve_server_single_flight():
    """The requeue is invisible to the serving machinery: leader and
    coalesced follower both get the recovered, bitwise-correct
    answer."""
    from heat2d_tpu.serve.server import SolveServer

    victims = reqs(3, base=0.31)
    oracle = grids(EnsembleEngine(max_batch=8).solve_batch(victims))
    chaos.install(chaos.ChaosConfig(device_fail_at=2,
                                    device_fail_index=5))
    reg = MetricsRegistry()
    eng = MeshEnsembleEngine(registry=reg, fault=FaultPolicy())
    server = SolveServer(registry=reg, engine=eng,
                         max_batch=eng.max_batch,
                         default_timeout=120.0)
    with server:
        for f in [server.submit(r) for r in reqs(8, base=0.05)]:
            f.result(120)                       # warm = attempt 1
        futs = [server.submit(r) for r in victims]
        dup = server.submit(victims[0])         # coalesced follower
        got = [np.asarray(f.result(120).u).tobytes() for f in futs]
        dup_res = dup.result(120)
    assert got == oracle
    assert np.asarray(dup_res.u).tobytes() == oracle[0]
    assert dup_res.coalesced
    assert eng.health.quarantined() == (5,)
    assert eng.fault_snapshot()["invariant"]["ok"]


@multichip
def test_chaos_gate_record_shape():
    from heat2d_tpu.mesh import chaos_gate

    payload = chaos_gate.run_gate()
    assert payload["passed"] is True
    names = [s["scenario"] for s in payload["scenarios"]]
    assert names == ["device_loss", "bit_flip", "hung_collective"]
    for s in payload["scenarios"]:
        assert s["bitwise"] and s["recovered"]
        assert s["recovery_s"] > 0 and s["invariant"]["ok"]


# --------------------------------------------------------------------- #
# control plane — quarantine feeds capacity decisions
# --------------------------------------------------------------------- #

class _FakeSup:
    def __init__(self, alive=(0, 1)):
        self._alive = list(alive)
        self.clock = None

    def alive_slots(self):
        return list(self._alive)


class _FakeFleet:
    def __init__(self):
        self.registry = MetricsRegistry()
        self.sup = _FakeSup()
        self.shed_calls = []

    def set_preemptive_shed(self, wm):
        self.shed_calls.append(wm)


def test_control_plane_quarantine_feed():
    from heat2d_tpu.control.plane import ControlPlane

    fleet = _FakeFleet()
    monitor = HealthMonitor(n_devices=4)
    plane = ControlPlane(fleet, registry=fleet.registry,
                         mesh_health=monitor)
    plane.tick()      # healthy startup: baseline, no decision row
    assert not [d for d in plane.decisions
                if d["action"] == "device_quarantine"]
    monitor.quarantine(2, "silent_corruption")
    plane.tick()
    plane.tick()      # no transition -> no duplicate row
    rows = [d for d in plane.decisions
            if d["action"] == "device_quarantine"]
    assert len(rows) == 1
    assert rows[0]["quarantined"] == [2]
    assert rows[0]["capacity_fraction"] == 0.75
    assert rows[0]["events"] == [{"device": 2,
                                  "reason": "silent_corruption"}]
    g = fleet.registry.snapshot()["gauges"]
    assert g["control_quarantined_devices"] == 1.0
    # a later conviction logs ONLY its own transition's events, not a
    # growing copy of the whole history
    monitor.quarantine(0, "device_fail")
    plane.tick()
    rows = [d for d in plane.decisions
            if d["action"] == "device_quarantine"]
    assert len(rows) == 2
    assert rows[1]["quarantined"] == [0, 2]
    assert rows[1]["events"] == [{"device": 0,
                                  "reason": "device_fail"}]


def test_control_plane_logs_preexisting_quarantine_on_first_tick():
    """Quarantines that PRE-DATE the plane (a restart mid-incident)
    are state the audit trail must carry: the startup baseline only
    suppresses the healthy 'nothing is quarantined' row."""
    from heat2d_tpu.control.plane import ControlPlane

    fleet = _FakeFleet()
    monitor = HealthMonitor(n_devices=4)
    monitor.quarantine(1, "device_fail")     # before the plane exists
    plane = ControlPlane(fleet, registry=fleet.registry,
                         mesh_health=monitor)
    plane.tick()
    plane.tick()      # still one transition -> still one row
    rows = [d for d in plane.decisions
            if d["action"] == "device_quarantine"]
    assert len(rows) == 1
    assert rows[0]["quarantined"] == [1]
