"""Resilience subsystem tests — crash-consistent checkpoints, fault
injection, retry/degraded-mode serving (docs/RESILIENCE.md).

The two headline scenarios (ISSUE acceptance criteria):

- a run KILLED mid-checkpoint-write restores from the last durable
  snapshot and finishes bitwise-identical to an uninterrupted run
  (``test_chaos_kill_mid_checkpoint_then_resume_bitwise``);
- a serve request whose first launch is injected to fail still succeeds
  via retry, with the retry counters visible in the metrics export
  (``test_serve_retries_injected_launch_failure``).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from heat2d_tpu.config import HeatConfig
from heat2d_tpu.io import (CheckpointCorruptError, load_checkpoint,
                           save_checkpoint)
from heat2d_tpu.io.binary import checkpoint_tmp_path
from heat2d_tpu.obs import MetricsRegistry
from heat2d_tpu.ops import inidat
from heat2d_tpu.resil import (AsyncCheckpointer, ChaosConfig,
                              CheckpointManager, DegradedMode,
                              RetryPolicy, Watchdog, call_with_retries,
                              is_manager_dir)
from heat2d_tpu.resil import chaos
from heat2d_tpu.resil.chaos import ChaosError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _chaos_disarmed():
    """Every test starts and ends with no chaos campaign installed."""
    chaos.uninstall()
    yield
    chaos.uninstall()


def _cfg(**kw):
    base = dict(nxprob=16, nyprob=16, steps=12)
    base.update(kw)
    return HeatConfig(**base)


# --------------------------------------------------------------------- #
# atomic commit + digest (io/binary.py surgery)
# --------------------------------------------------------------------- #

def test_sidecar_carries_digest_and_no_tmp_left(tmp_path):
    u = np.asarray(inidat(12, 8))
    p = tmp_path / "ck.bin"
    save_checkpoint(u, 7, _cfg(), p)
    meta = json.loads((tmp_path / "ck.bin.meta.json").read_text())
    assert len(meta["sha256"]) == 64
    assert not os.path.exists(checkpoint_tmp_path(p))
    grid, step, _ = load_checkpoint(p)
    assert step == 7
    np.testing.assert_array_equal(grid, u)


def test_corrupt_binary_detected(tmp_path):
    u = np.asarray(inidat(12, 8))
    p = tmp_path / "ck.bin"
    save_checkpoint(u, 7, _cfg(), p)
    with open(p, "r+b") as f:
        f.write(b"\xde\xad\xbe\xef")
    with pytest.raises(CheckpointCorruptError, match="sha256 mismatch"):
        load_checkpoint(p)
    # verify=False loads the bytes as-is (forensics escape hatch)
    grid, step, _ = load_checkpoint(p, verify=False)
    assert step == 7


def test_torn_pair_detected(tmp_path):
    """Crash between the binary replace and the sidecar replace: the
    new binary sits beside the OLD sidecar — the digest must refuse."""
    u = np.asarray(inidat(12, 8))
    p = tmp_path / "ck.bin"
    save_checkpoint(u, 7, _cfg(), p)
    # simulate: a newer state replaced the binary, sidecar never landed
    (u + 1.0).astype(np.float32).tofile(p)
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(p)


def test_truncated_binary_detected(tmp_path):
    u = np.asarray(inidat(12, 8))
    p = tmp_path / "ck.bin"
    save_checkpoint(u, 7, _cfg(), p)
    with open(p, "r+b") as f:
        f.truncate(100)
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(p)


def test_sidecar_missing_fields_is_corrupt_not_crash(tmp_path):
    """A sidecar that parses as JSON but lacks required fields must be
    CheckpointCorruptError (so latest_valid falls back past it), not a
    bare KeyError that escapes the manifest walk."""
    u = np.asarray(inidat(12, 8))
    p = tmp_path / "ck.bin"
    u.tofile(p)
    (tmp_path / "ck.bin.meta.json").write_text(
        json.dumps({"shape": [12, 8]}))        # no "step"
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(p)


def test_pre_digest_checkpoints_still_load(tmp_path):
    """Sidecars written before the digest field (or by hand) load
    unverified — format v1 stays backward compatible."""
    u = np.asarray(inidat(12, 8))
    p = tmp_path / "ck.bin"
    u.tofile(p)
    (tmp_path / "ck.bin.meta.json").write_text(json.dumps(
        {"step": 3, "shape": [12, 8], "dtype": "float32", "config": {},
         "format": "heat2d-tpu-checkpoint-v1"}))
    grid, step, _ = load_checkpoint(p)
    assert step == 3
    np.testing.assert_array_equal(grid, u)


# --------------------------------------------------------------------- #
# CheckpointManager: manifest, retention, latest_valid fallback
# --------------------------------------------------------------------- #

def test_manager_retention_gc(tmp_path):
    reg = MetricsRegistry()
    m = CheckpointManager(tmp_path / "ck", keep=2, registry=reg)
    u = np.asarray(inidat(8, 8))
    for step in (4, 8, 12):
        m.save(u + step, step, _cfg())
    assert m.steps() == [8, 12]
    assert not os.path.exists(m.path_for(4))
    assert not os.path.exists(m.path_for(4) + ".meta.json")
    snap = reg.snapshot()
    assert snap["counters"]["resil_ckpt_saves_total"] == 3
    assert snap["counters"]["resil_ckpt_gc_total"] == 1
    assert snap["gauges"]["resil_ckpt_latest_step"] == 12


def test_manager_latest_valid_skips_torn(tmp_path):
    reg = MetricsRegistry()
    m = CheckpointManager(tmp_path / "ck", keep=None, registry=reg)
    u = np.asarray(inidat(8, 8))
    for step in (4, 8, 12):
        m.save(u + step, step, _cfg())
    # newest torn (binary corrupted), next-newest missing entirely
    with open(m.path_for(12), "r+b") as f:
        f.write(b"\x00" * 16)
    os.remove(m.path_for(8))
    grid, step, cfg_dict = m.latest_valid()
    assert step == 4
    np.testing.assert_array_equal(grid, u + 4)
    assert cfg_dict["nxprob"] == 16
    assert reg.snapshot()["counters"][
        "resil_ckpt_skipped_torn_total"] == 2


def test_manager_latest_valid_empty(tmp_path):
    m = CheckpointManager(tmp_path / "ck", keep=3)
    assert m.latest_valid() is None
    assert m.latest_step() is None


def test_manager_survives_lost_manifest(tmp_path):
    """The manifest is an index, not the source of truth: deleting it
    degrades to a directory scan over the verified sidecars."""
    m = CheckpointManager(tmp_path / "ck", keep=None)
    u = np.asarray(inidat(8, 8))
    for step in (4, 8):
        m.save(u + step, step, _cfg())
    os.remove(m.manifest_path)
    assert m.steps() == [4, 8]
    grid, step, _ = m.latest_valid()
    assert step == 8


def test_is_manager_dir(tmp_path):
    assert is_manager_dir(tmp_path)
    assert not is_manager_dir(tmp_path / "ck.bin")


# --------------------------------------------------------------------- #
# AsyncCheckpointer: overlap + double buffering
# --------------------------------------------------------------------- #

def test_async_writer_overlaps_write_with_caller(tmp_path):
    """With an injected 0.3s write latency, save_async must return well
    before the write completes (the I/O rides the background thread);
    flush() then makes it durable."""
    chaos.install(ChaosConfig(ckpt_latency_s=0.3))
    m = CheckpointManager(tmp_path / "ck", keep=None)
    u = np.asarray(inidat(16, 16))
    w = AsyncCheckpointer(m, _cfg(), shape=(16, 16))
    t0 = time.monotonic()
    w.save_async(u, 4)
    returned_in = time.monotonic() - t0
    assert returned_in < 0.25, (
        f"save_async blocked {returned_in:.3f}s — checkpoint I/O is "
        f"back on the hot path")
    assert m.latest_valid() is None      # not yet committed
    w.flush()
    grid, step, _ = m.latest_valid()
    assert step == 4
    np.testing.assert_array_equal(grid, u)
    w.close()


def test_async_writer_double_buffer_backpressure(tmp_path):
    """At most ONE write in flight: the second save_async waits out the
    first (slow) write instead of queueing snapshots unbounded."""
    chaos.install(ChaosConfig(ckpt_latency_s=0.2))
    m = CheckpointManager(tmp_path / "ck", keep=None)
    u = np.asarray(inidat(16, 16))
    with AsyncCheckpointer(m, _cfg(), shape=(16, 16)) as w:
        t0 = time.monotonic()
        w.save_async(u, 4)
        w.save_async(u * 2, 8)
        assert time.monotonic() - t0 >= 0.2   # waited for ckpt 4
    assert m.steps() == [4, 8]
    grid, step, _ = m.latest_valid()
    assert step == 8
    np.testing.assert_array_equal(grid, u * 2)


def test_async_writer_plain_path_target(tmp_path):
    p = tmp_path / "ck.bin"
    u = np.asarray(inidat(16, 16))
    with AsyncCheckpointer(str(p), _cfg(), shape=(16, 16)) as w:
        w.save_async(u, 4)
        w.save_async(u * 3, 8)
    grid, step, _ = load_checkpoint(p)
    assert step == 8
    np.testing.assert_array_equal(grid, u * 3)


def test_async_writer_failed_write_never_commits(tmp_path):
    """A failed background block write must ABANDON its pending commit:
    a later flush/close must not promote the partial staging file into
    a 'verified' checkpoint (it would digest the torn data into a
    matching sidecar)."""
    from concurrent.futures import Future

    from heat2d_tpu.resil.writer import _PendingCommit

    m = CheckpointManager(tmp_path / "ck", keep=None)
    w = AsyncCheckpointer(m, _cfg(), shape=(16, 16))
    path = m.path_for(4)
    tmp = checkpoint_tmp_path(path)
    with open(tmp, "wb") as f:
        f.write(b"\x00" * 64)               # partial staging data
    fut = Future()
    fut.set_exception(OSError("disk full"))
    w._future = fut
    w._pending = _PendingCommit(step=4, tmp=tmp, path=path,
                                config=_cfg(), out_shape=(16, 16))
    with pytest.raises(OSError):
        w.flush()
    w.close()                               # must not commit either
    assert m.latest_valid() is None
    assert not os.path.exists(path)


# --------------------------------------------------------------------- #
# retry / watchdog / degraded mode
# --------------------------------------------------------------------- #

def test_retry_policy_delays_capped():
    p = RetryPolicy(base_delay=0.1, backoff=2.0, max_delay=0.35)
    assert [p.delay(i) for i in range(4)] == [0.1, 0.2, 0.35, 0.35]


def test_call_with_retries_absorbs_transients():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ChaosError("injected")
        return "ok"

    slept = []
    assert call_with_retries(
        flaky, RetryPolicy(max_attempts=3, base_delay=0.01),
        sleep=slept.append) == "ok"
    assert len(calls) == 3 and slept == [0.01, 0.02]


def test_call_with_retries_terminal_not_retried():
    calls = []

    def broken():
        calls.append(1)
        raise ValueError("a bug, not a fault")

    with pytest.raises(ValueError):
        call_with_retries(broken, RetryPolicy(max_attempts=5,
                                              base_delay=0.01),
                          sleep=lambda _s: None)
    assert len(calls) == 1


def test_call_with_retries_exhaustion_raises_last():
    def always():
        raise ChaosError("still down")

    with pytest.raises(ChaosError):
        call_with_retries(always, RetryPolicy(max_attempts=2,
                                              base_delay=0.0),
                          sleep=lambda _s: None)


def test_watchdog_fires_once_and_cancels():
    fired = []
    with Watchdog(0.05, lambda: fired.append(1)) as w:
        time.sleep(0.15)
    assert w.fired and fired == [1]
    with Watchdog(5.0, lambda: fired.append(2)) as w:
        pass
    time.sleep(0.05)
    assert not w.fired and fired == [1]


def test_degraded_mode_state_machine():
    t = [0.0]
    b = DegradedMode(threshold=2, cooldown=10.0, clock=lambda: t[0])
    assert b.state == "closed" and b.allow()
    b.record_failure()
    assert b.state == "closed"           # below threshold
    b.record_failure()
    assert b.state == "open" and not b.allow()
    t[0] = 11.0
    assert b.allow()                     # the half-open probe
    assert b.state == "half_open" and not b.allow()   # others shed
    b.record_failure()                   # probe failed -> re-open
    assert b.state == "open"
    t[0] = 22.0
    assert b.allow()
    b.record_success()                   # probe succeeded -> closed
    assert b.state == "closed" and b.allow()
    assert b.trips == 1                  # re-open of an open breaker
    #                                      is not a second trip


def test_retry_policy_full_jitter_bounded_and_seeded():
    """Fleet satellite: jittered delays stay within the deterministic
    cap at every attempt, and a seeded rng pins the schedule — N
    restarted workers decorrelate without losing reproducible tests."""
    import random

    p = RetryPolicy(base_delay=0.1, backoff=2.0, max_delay=0.35,
                    jitter=True)
    rng = random.Random(1234)
    sched = [p.delay(i, rng=rng) for i in range(6)]
    assert all(0.0 <= d <= p.cap(i) for i, d in enumerate(sched))
    assert [p.cap(i) for i in range(4)] == [0.1, 0.2, 0.35, 0.35]
    # seeded: the exact schedule reproduces
    rng_again = random.Random(1234)
    assert sched == [p.delay(i, rng=rng_again) for i in range(6)]
    # two differently-seeded workers do NOT share a schedule
    other = [p.delay(i, rng=random.Random(5678)) for i in range(6)]
    assert sched != other
    # jitter off (the default) stays byte-for-byte the legacy behavior
    q = RetryPolicy(base_delay=0.1, backoff=2.0, max_delay=0.35)
    assert [q.delay(i) for i in range(4)] == [0.1, 0.2, 0.35, 0.35]


def test_call_with_retries_jitter_sleeps_within_cap():
    import random

    calls, slept = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 4:
            raise ChaosError("injected")
        return "ok"

    p = RetryPolicy(max_attempts=4, base_delay=0.1, backoff=2.0,
                    max_delay=0.25, jitter=True)
    assert call_with_retries(flaky, p, sleep=slept.append,
                             rng=random.Random(7)) == "ok"
    assert len(slept) == 3
    assert all(0.0 <= d <= p.cap(i) for i, d in enumerate(slept))


# --------------------------------------------------------------------- #
# chaos env parsing edge cases (fleet satellite)
# --------------------------------------------------------------------- #

def test_chaos_env_unset_and_empty_mean_off():
    assert ChaosConfig.from_env({}) is None
    assert ChaosConfig.from_env({"HEAT2D_CHAOS_FAIL_LAUNCHES": "",
                                 "HEAT2D_CHAOS_KILL_CKPT_AT": ""}) \
        is None
    # phase alone arms nothing
    assert ChaosConfig.from_env(
        {"HEAT2D_CHAOS_KILL_CKPT_PHASE": "pre_meta"}) is None


def test_chaos_env_zero_means_off():
    """An explicit 0 is 'off', not 'an ordinal that never fires': the
    config canonicalizes and from_env returns None."""
    env = {"HEAT2D_CHAOS_KILL_CKPT_AT": "0",
           "HEAT2D_CHAOS_FAIL_LAUNCHES": "0",
           "HEAT2D_CHAOS_LAUNCH_LATENCY_S": "0",
           "HEAT2D_CHAOS_WORKER_KILL_AFTER": "0",
           "HEAT2D_CHAOS_HEARTBEAT_DROP_AFTER": "0",
           "HEAT2D_CHAOS_SLOW_WORKER_S": "0"}
    assert ChaosConfig.from_env(env) is None
    assert ChaosConfig(kill_ckpt_at=0).kill_ckpt_at is None
    assert not ChaosConfig(worker_kill_after=0).any_active()


def test_chaos_env_garbage_raises_not_noops():
    """A typo'd chaos var must refuse loudly: a campaign that silently
    no-ops lets the chaos test it drives pass vacuously."""
    with pytest.raises(ValueError, match="FAIL_LAUNCHES"):
        ChaosConfig.from_env({"HEAT2D_CHAOS_FAIL_LAUNCHES": "lots"})
    with pytest.raises(ValueError, match="LAUNCH_LATENCY_S"):
        ChaosConfig.from_env({"HEAT2D_CHAOS_LAUNCH_LATENCY_S": "fast"})
    with pytest.raises(ValueError, match="kill_ckpt_phase"):
        ChaosConfig.from_env({"HEAT2D_CHAOS_KILL_CKPT_AT": "1",
                              "HEAT2D_CHAOS_KILL_CKPT_PHASE": "bogus"})
    with pytest.raises(ValueError, match="WORKER_KILL_AFTER"):
        ChaosConfig.from_env({"HEAT2D_CHAOS_WORKER_KILL_AFTER": "x"})


def test_chaos_worker_hooks_in_process():
    """The fleet fault modes, at the hook level: slow-worker injects
    real latency into request pickup; heartbeat-drop silences beats
    after N while the process keeps running."""
    reg = MetricsRegistry()
    chaos.install(ChaosConfig(slow_worker_s=0.05,
                              heartbeat_drop_after=2), registry=reg)
    t0 = time.monotonic()
    chaos.worker_request_point()
    assert time.monotonic() - t0 >= 0.05
    assert [chaos.heartbeat_point() for _ in range(4)] == \
        [True, True, False, False]
    snap = reg.snapshot()
    assert snap["counters"][
        "resil_chaos_injected_total{point=slow_worker}"] == 1
    assert snap["counters"][
        "resil_chaos_injected_total{point=heartbeat_drop}"] == 2
    chaos.uninstall()
    # disarmed: the hooks are no-ops again
    assert chaos.heartbeat_point() is True
    chaos.worker_request_point()


def test_degraded_mode_concurrent_half_open_single_probe():
    """Fleet load pattern: many threads hit allow() the instant the
    cooldown expires — exactly ONE gets the half-open probe token; its
    success re-closes the breaker for everyone."""
    import threading

    t = [0.0]
    b = DegradedMode(threshold=1, cooldown=10.0, clock=lambda: t[0])
    b.record_failure()
    t[0] = 11.0
    grants = []
    barrier = threading.Barrier(8)

    def hammer():
        barrier.wait()
        grants.append(b.allow())

    threads = [threading.Thread(target=hammer) for _ in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert sum(grants) == 1                 # one probe token
    b.record_success()                      # probe verdict: healthy
    assert b.state == "closed"
    grants2 = [b.allow() for _ in range(8)]
    assert all(grants2)                     # re-closed for everyone


def test_degraded_probe_token_expires():
    """A probe that hangs (its verdict never arrives) must not shed
    traffic forever: the token expires after one cooldown and another
    caller may probe."""
    t = [0.0]
    b = DegradedMode(threshold=1, cooldown=10.0, clock=lambda: t[0])
    b.record_failure()
    t[0] = 10.0
    assert b.allow()                     # probe 1 granted ... and hangs
    assert not b.allow()                 # token held
    t[0] = 21.0
    assert b.allow()                     # token expired -> probe 2
    b.record_success()
    assert b.state == "closed"


# --------------------------------------------------------------------- #
# serve integration: retry, watchdog, degraded shedding
# --------------------------------------------------------------------- #

def _req(**kw):
    from heat2d_tpu.serve.schema import SolveRequest
    base = dict(nx=12, ny=12, steps=4, method="jnp")
    base.update(kw)
    return SolveRequest(**base)


def test_serve_retries_injected_launch_failure(tmp_path):
    """ISSUE acceptance: first launch injected to fail -> the request
    still succeeds via retry, and the retry/restore counters land in
    the metrics JSONL export."""
    from heat2d_tpu.serve.server import Client, SolveServer

    reg = MetricsRegistry()
    chaos.install(ChaosConfig(fail_launches=1), registry=reg)
    with SolveServer(registry=reg,
                     retry_policy=RetryPolicy(base_delay=0.01)) as s:
        res = Client(s).solve(_req(), timeout=60)
    assert res.steps_done == 4
    out = tmp_path / "metrics.jsonl"
    reg.write_jsonl(str(out))
    snap = [json.loads(line) for line in out.read_text().splitlines()
            if json.loads(line).get("event") == "snapshot"][0]
    assert snap["counters"]["serve_retries_total"] >= 1
    assert snap["counters"]["serve_launch_failures_total"] >= 1
    assert snap["counters"][
        "resil_chaos_injected_total{point=launch_failure}"] == 1
    assert snap["counters"]["serve_requests_total{outcome=completed}"] \
        == 1


def test_serve_degraded_sheds_but_cache_answers(tmp_path):
    """Breaker open: fresh compute is shed with Rejected('degraded'),
    warm signatures keep answering from the cache."""
    from heat2d_tpu.serve.schema import Rejected
    from heat2d_tpu.serve.server import Client, SolveServer

    reg = MetricsRegistry()
    warm = _req()
    with SolveServer(registry=reg,
                     retry_policy=RetryPolicy(max_attempts=1),
                     breaker=DegradedMode(threshold=1, cooldown=60.0,
                                          registry=reg)) as s:
        c = Client(s)
        cold = c.solve(warm, timeout=60)          # fills the cache
        chaos.install(ChaosConfig(fail_launches=1000), registry=reg)
        with pytest.raises(ChaosError):
            c.solve(_req(steps=5), timeout=30)    # trips the breaker
        with pytest.raises(Rejected) as ei:
            c.solve(_req(steps=6), timeout=30)    # shed at the door
        assert ei.value.code == "degraded"
        hit = c.solve(warm, timeout=30)           # cache still serves
        assert hit.cache_hit
        np.testing.assert_array_equal(np.asarray(hit.u),
                                      np.asarray(cold.u))
    snap = reg.snapshot()
    assert snap["counters"]["serve_breaker_trips_total"] == 1
    assert snap["counters"]["serve_degraded_shed_total"] >= 1
    assert snap["gauges"]["serve_degraded"] == 1.0


def test_serve_watchdog_converts_hang_to_rejection():
    """A launch that outlives the deadline fails its waiters with a
    structured Rejected('watchdog_timeout') instead of hanging them."""
    from heat2d_tpu.serve.schema import Rejected
    from heat2d_tpu.serve.server import Client, SolveServer

    reg = MetricsRegistry()
    with SolveServer(registry=reg,
                     retry_policy=RetryPolicy(max_attempts=1),
                     launch_deadline=0.15) as s:
        c = Client(s)
        c.solve(_req(), timeout=60)           # warm compile un-hobbled
        chaos.install(ChaosConfig(launch_latency_s=1.0), registry=reg)
        t0 = time.monotonic()
        with pytest.raises(Rejected) as ei:
            c.solve(_req(steps=5), timeout=30)
        assert ei.value.code == "watchdog_timeout"
        assert time.monotonic() - t0 < 5.0
    assert reg.snapshot()["counters"][
        "serve_watchdog_timeouts_total"] >= 1


# --------------------------------------------------------------------- #
# hot path unchanged when resilience is off
# --------------------------------------------------------------------- #

def test_jaxpr_identical_with_chaos_armed():
    """The resilience layer is host-side orchestration only: arming a
    chaos campaign (or none) must not change the traced program of the
    engine loops — pinned here the same way test_telemetry pins the
    tap-off path."""
    from heat2d_tpu.models.solver import Heat2DSolver

    from tests._pin import assert_jaxpr_equal, jaxpr_text

    cfg = _cfg(convergence=True, interval=4)
    u0 = inidat(16, 16)
    before = jaxpr_text(Heat2DSolver(cfg).make_runner(), u0)
    chaos.install(ChaosConfig(fail_launches=3, ckpt_latency_s=0.5,
                              kill_ckpt_at=99))
    armed = jaxpr_text(Heat2DSolver(cfg).make_runner(), u0)
    assert_jaxpr_equal(before, armed, label="chaos armed vs disarmed")
    assert "debug_callback" not in before


# --------------------------------------------------------------------- #
# the headline crash/restore scenario, end to end through the CLI
# --------------------------------------------------------------------- #

def _cli(outdir, extra, env_extra=None, expect_rc=0):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("HEAT2D_CHAOS_")}
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra or {})
    r = subprocess.run(
        [sys.executable, "-m", "heat2d_tpu.cli", "--mode", "serial",
         "--nxprob", "16", "--nyprob", "16", "--steps", "12",
         "--platform", "cpu", "--dat-layout", "none",
         "--outdir", str(outdir)] + extra,
        cwd=REPO, env=env, capture_output=True, text=True, timeout=220)
    assert r.returncode == expect_rc, (r.returncode, r.stdout, r.stderr)
    return r


def test_chaos_kill_mid_checkpoint_then_resume_bitwise(tmp_path):
    """ISSUE acceptance: a run killed mid-checkpoint-write (hard
    os._exit, no cleanup) restores from the last durable snapshot and
    produces a final grid bitwise-identical to an uninterrupted run."""
    ref = tmp_path / "ref"
    out = tmp_path / "out"
    ck = tmp_path / "ck"
    ref.mkdir(), out.mkdir(), ck.mkdir()

    _cli(ref, ["--binary-dumps"])
    # Killed at the 2nd checkpoint's mid-write window: step 8's temp
    # file exists, the manifest's only durable entry is step 4.
    _cli(tmp_path, ["--checkpoint", str(ck), "--checkpoint-every", "4"],
         env_extra={"HEAT2D_CHAOS_KILL_CKPT_AT": "2"}, expect_rc=137)
    m = CheckpointManager(ck, keep=None)
    assert m.steps() == [4]
    assert os.path.exists(checkpoint_tmp_path(m.path_for(8)))

    r = _cli(out, ["--resume", str(ck), "--binary-dumps",
                   "--run-record", str(out / "rec.json")])
    assert "Resuming from step 4" in r.stdout
    assert ((out / "final_binary.dat").read_bytes()
            == (ref / "final_binary.dat").read_bytes())
    rec = json.loads((out / "rec.json").read_text())
    assert rec["resume_from_step"] == 4
    assert rec["total_steps_including_resume"] == 12


def test_resume_directory_falls_back_past_torn(tmp_path):
    """--resume DIR with the newest snapshot torn: the previous one is
    used and the run still reaches the full-run state bitwise."""
    ref = tmp_path / "ref"
    out = tmp_path / "out"
    ck = tmp_path / "ck"
    ref.mkdir(), out.mkdir(), ck.mkdir()
    _cli(ref, ["--binary-dumps"])
    _cli(tmp_path / "seed", ["--checkpoint", str(ck),
                             "--checkpoint-every", "4"])
    m = CheckpointManager(ck, keep=None)
    assert m.steps() == [4, 8, 12]
    with open(m.path_for(12), "r+b") as f:   # tear the newest
        f.write(b"\xff" * 32)
    r = _cli(out, ["--resume", str(ck), "--binary-dumps"])
    assert "Resuming from step 8" in r.stdout
    assert ((out / "final_binary.dat").read_bytes()
            == (ref / "final_binary.dat").read_bytes())
