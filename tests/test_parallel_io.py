"""Per-shard parallel binary output (SURVEY.md C15 write path — the
MPI_File_write_all analogue, grad1612_mpi_heat.c:182-189): every process
writes its addressable shards at their global row-major offsets; nobody
materializes the full grid. Single-host coverage here; the genuinely
multi-process path is exercised in test_multihost.py."""

import numpy as np

from heat2d_tpu.config import HeatConfig
from heat2d_tpu.io import read_binary, write_binary_sharded
from heat2d_tpu.models.solver import Heat2DSolver


def test_sharded_write_matches_serial_grid(tmp_path):
    cfg = HeatConfig(nxprob=16, nyprob=16, steps=12, mode="dist2d",
                     gridx=2, gridy=4)
    r = Heat2DSolver(cfg).run(timed=False, gather=False)
    path = tmp_path / "final_binary.dat"
    write_binary_sharded(r.u, path, shape=cfg.shape)
    got = read_binary(path, cfg.shape)
    want = Heat2DSolver(cfg.replace(mode="serial", gridx=1, gridy=1)
                        ).run(timed=False).u
    np.testing.assert_array_equal(got, want)


def test_sharded_write_crops_uneven_padding(tmp_path):
    """10 rows over 3 workers pads shards to 12 rows; the file must be the
    exact 10x10 reference layout (pad rows cropped at the write)."""
    cfg = HeatConfig(nxprob=10, nyprob=10, steps=7, mode="dist1d",
                     numworkers=3)
    r = Heat2DSolver(cfg).run(timed=False, gather=False)
    assert np.asarray(r.u).shape[0] == 12   # padded (pre-crop) carrier
    path = tmp_path / "final_binary.dat"
    write_binary_sharded(r.u, path, shape=cfg.shape)
    assert path.stat().st_size == 10 * 10 * 4
    got = read_binary(path, cfg.shape)
    want = Heat2DSolver(cfg.replace(mode="serial", numworkers=None)
                        ).run(timed=False).u
    np.testing.assert_array_equal(got, want)
