"""Performance observatory acceptance (docs/OBSERVABILITY.md).

- roofline models: analytic bytes/cell-step per route, boundary-bytes
  model, the relocated calibrated bound (bench.py identity).
- cost cards: XLA boundary bytes agree with the analytic model within
  the documented tolerance on every batch route; extraction is FREE
  when off and jaxpr-pinned when on (solver/batch/band/mesh programs
  byte-identical with the observer + duty sampler armed).
- duty-cycle sampler: interval merge math on a synthetic span feed.
- anomaly sentinel: a seeded latency regression flags within the
  detection budget, a healthy twin stays silent, and findings land in
  the ControlPlane decision log.
- launch stamping: serve + mesh launch rows carry the roofline fields.
- surfaces: RECORD_KINDS, trace --stats cost-card join, the perf CLI.
"""

import itertools
import json
import os

import pytest

from heat2d_tpu.obs import perf, roofline
from heat2d_tpu.obs.metrics import MetricsRegistry
from heat2d_tpu.serve.schema import SolveRequest
from tests._pin import (assert_jaxpr_equal, band_runner_jaxpr,
                        batch_runner_jaxpr, mesh_runner_jaxpr,
                        solver_jaxpr)


def reqs(n, nx=16, ny=16, steps=4, method="jnp", **kw):
    return [SolveRequest(nx=nx, ny=ny, steps=steps, method=method,
                         cx=0.1 + 0.01 * i, cy=0.1, **kw).validate()
            for i in range(n)]


# --------------------------------------------------------------------- #
# roofline models
# --------------------------------------------------------------------- #

def test_analytic_bytes_per_cell_step_routes():
    m = roofline.analytic_bytes_per_cell_step(64, 64, method="jnp")
    assert m["bytes_per_cell_step"] == 8.0 and m["route"] == "jnp"
    # bf16 storage halves the stream — the ROADMAP item 2 lever
    m16 = roofline.analytic_bytes_per_cell_step(
        64, 64, method="jnp", dtype="bfloat16")
    assert m16["bytes_per_cell_step"] == 4.0
    from heat2d_tpu.ops import pallas_stencil as ps
    t = ps.DEFAULT_TSTEPS
    m = roofline.analytic_bytes_per_cell_step(64, 64, method="pallas")
    assert m["bytes_per_cell_step"] == pytest.approx(8.0 / t)
    m = roofline.analytic_bytes_per_cell_step(4096, 4096,
                                              method="band")
    # band: 1 write + (bm+2T)/bm read per T steps — strictly above the
    # resident route, strictly below plain streaming
    assert 8.0 / t < m["bytes_per_cell_step"] < 8.0
    for meth in ("adi", "mg"):
        assert roofline.analytic_bytes_per_cell_step(
            64, 64, method=meth)["coarse"]


def test_mcells_per_hbm_byte_is_reciprocal():
    m = roofline.analytic_bytes_per_cell_step(64, 64, method="jnp")
    assert roofline.mcells_per_hbm_byte(64, 64, method="jnp") \
        == pytest.approx(1.0 / (1e6 * m["bytes_per_cell_step"]))


def test_boundary_bytes_model():
    bb = roofline.boundary_bytes(16, 24, batch=3)
    assert bb["argument_bytes"] == 3 * 16 * 24 * 4 + 2 * 3 * 4
    assert bb["output_bytes"] == 3 * 16 * 24 * 4
    conv = roofline.boundary_bytes(16, 24, batch=3, convergence=True)
    assert conv["output_bytes"] == bb["output_bytes"] + 4 * 3


def test_calibrated_bound_relocated_identity():
    """The bench.py formula, verbatim: calib x bm/(bm+2T) at the
    4096^2 window plan (tune_bands.md round 4)."""
    import bench
    assert bench.calibrated_bound_mcells is roofline.calibrated_bound_mcells
    assert bench.VPU_CALIB_MCELLS is roofline.VPU_CALIB_MCELLS
    from heat2d_tpu.ops import pallas_stencil as ps
    t = ps.DEFAULT_TSTEPS
    bm, _ = ps.plan_window_band(4096, 4096, t)
    want = roofline.VPU_CALIB_MCELLS[4096] * bm / (bm + 2 * t)
    assert roofline.calibrated_bound_mcells(4096, 4096) \
        == pytest.approx(want)


def test_calibrated_bound_honest_absences():
    # VMEM-resident: no streaming structure to bound
    assert roofline.calibrated_bound_mcells(64, 64) is None
    # uncalibrated dtype / device kind: absent, never a guess
    assert roofline.calibrated_bound_mcells(4096, 4096,
                                            dtype="bfloat16") is None
    assert roofline.calibrated_bound_mcells(
        4096, 4096, device_kind="TPU v9000") is None
    assert roofline.roofline_bound(64, 64, method="jnp") is None


def test_bench_record_carries_efficiency_rows():
    import bench
    rec = bench.build_record(100.0, "two-point", 1.0, nx=64, ny=64,
                             steps=8, mode="jnp")
    assert rec["bytes_per_cell_step"] == 8.0
    assert rec["mcells_per_hbm_byte"] == pytest.approx(1 / 8e6,
                                                       rel=1e-3)


# --------------------------------------------------------------------- #
# cost cards
# --------------------------------------------------------------------- #

def _card(nx, ny, steps, method, batch=2, registry=None):
    import jax.numpy as jnp

    from heat2d_tpu.models import ensemble
    runner = ensemble.batch_runner(nx, ny, steps, method)
    u0 = jnp.zeros((batch, nx, ny), jnp.float32)
    cxs = jnp.asarray([0.1] * batch, jnp.float32)
    return perf.extract_cost_card(
        runner, (u0, cxs, cxs), registry=registry,
        meta={"signature": f"t:{nx}x{ny}:{method}", "nx": nx, "ny": ny,
              "steps": steps, "method": method, "convergence": False,
              "capacity": batch, "dtype": "float32", "route": "batch"})


@pytest.mark.parametrize("method", ["jnp", "auto", "band", "adi", "mg"])
def test_cost_card_boundary_within_tolerance(method):
    """The acceptance tolerance: XLA's program-boundary bytes within
    +-15% of the analytic boundary model, per batch route."""
    card = _card(24, 32, 4, method)
    assert card is not None, f"no cost card for {method}"
    agree = card["model"]["boundary_agreement_pct"]
    assert agree is not None and abs(agree - 100.0) <= 15.0, \
        f"{method}: boundary bytes {agree}% of model"
    assert card["model"]["route"] in ("jnp", "pallas", "band", "adi",
                                      "mg")


def test_cost_card_streaming_sanity():
    """Op-level bytes accessed can never undercut one read + one write
    of the grid (2b per cell for ONE loop-body application)."""
    card = _card(24, 32, 4, "jnp")
    assert card["bytes_accessed"] >= 2 * 4 * 2 * 24 * 32
    assert card["flops"] > 0
    assert card["arithmetic_intensity"] is not None


def test_cost_card_failure_is_counted_not_raised():
    reg = MetricsRegistry()
    assert perf.extract_cost_card(object(), (), meta={},
                                  registry=reg) is None
    assert reg.find_counters("perf_card_failures_total")


def test_perf_observer_dedup_and_persistence(tmp_path):
    import jax.numpy as jnp

    from heat2d_tpu.models import ensemble
    reg = MetricsRegistry()
    obs = perf.PerfObserver(registry=reg, dir=str(tmp_path),
                            service="t")
    runner = ensemble.batch_runner(16, 16, 2, "jnp")
    u0 = jnp.zeros((1, 16, 16), jnp.float32)
    cxs = jnp.asarray([0.1], jnp.float32)
    meta = {"signature": "s", "capacity": 1, "route": "batch",
            "nx": 16, "ny": 16, "method": "jnp", "dtype": "float32"}
    first = obs.observe(runner, (u0, cxs, cxs), meta)
    assert first is not None
    # second observe: dict hit, no re-extraction, returns the card
    assert obs.observe(runner, (u0, cxs, cxs), meta) is first
    assert obs.card_for("s", 1, "batch") is first
    obs.close()
    files = [f for f in os.listdir(tmp_path)
             if f.startswith("cost-cards-t-")]
    assert len(files) == 1
    lines = [json.loads(ln) for ln in
             (tmp_path / files[0]).read_text().splitlines()]
    assert len(lines) == 1 and lines[0]["signature"] == "s"


# --------------------------------------------------------------------- #
# jaxpr pins — extraction + sampler change NO traced program
# --------------------------------------------------------------------- #

def test_observatory_armed_programs_byte_identical(tmp_path):
    from heat2d_tpu.obs import tracing
    base = {
        "solver": solver_jaxpr(),
        "batch": batch_runner_jaxpr(),
        "band": band_runner_jaxpr(),
        "mesh": mesh_runner_jaxpr(),
    }
    reg = MetricsRegistry()
    sampler = perf.DutyCycleSampler(reg, interval_s=0.01)
    perf.install(perf.PerfObserver(registry=reg, dir=str(tmp_path)))
    tracing.add_span_tap(sampler.feed)
    sampler.start()
    try:
        # extraction actually exercised while armed (card on the very
        # runner whose program the pin retraces)
        assert _card(16, 16, 4, "jnp") is not None
        armed = {
            "solver": solver_jaxpr(),
            "batch": batch_runner_jaxpr(),
            "band": band_runner_jaxpr(),
            "mesh": mesh_runner_jaxpr(),
        }
    finally:
        sampler.stop()
        tracing.remove_span_tap(sampler.feed)
        perf.uninstall()
    for name in base:
        assert_jaxpr_equal(armed[name], base[name],
                           f"perf-armed {name} runner")


# --------------------------------------------------------------------- #
# duty-cycle sampler
# --------------------------------------------------------------------- #

def _span(t0, t1, lane="serve", pid=1):
    return {"event": "span", "kind": "launch", "service": lane,
            "pid": pid, "span_id": f"{t0}-{t1}", "t0": t0, "t1": t1}


def test_duty_cycle_interval_merge():
    s = perf.DutyCycleSampler(window_s=2.0)
    now = 1000.0
    # two overlapping spans + one disjoint: busy = [998.5,999.5] +
    # [999.8,1000] = 1.2s of a 2s window
    s.feed(_span(998.5, 999.2))
    s.feed(_span(999.0, 999.5))
    s.feed(_span(999.8, 1000.0))
    duty = s._sample(now)
    assert duty["serve:1"] == pytest.approx(0.6)
    # an open span counts to 'now'; lanes are independent
    s.feed({"event": "span_start", "kind": "launch", "service": "mesh",
            "pid": 2, "span_id": "o", "t0": 999.0})
    duty = s._sample(now)
    assert duty["mesh:2"] == pytest.approx(0.5)
    # the retroactive close replaces the open span; idle decay then
    # reports an explicit 0.0 instead of holding stale duty
    s.feed({"event": "span", "kind": "launch", "service": "mesh",
            "pid": 2, "span_id": "o", "t0": 999.0, "t1": 1000.2})
    duty = s._sample(now + 100.0)
    assert duty["serve:1"] == 0.0 and duty["mesh:2"] == 0.0
    assert s.samples == 3


def test_duty_cycle_ignores_other_span_kinds():
    s = perf.DutyCycleSampler(window_s=2.0)
    s.feed({"event": "span", "kind": "queue", "service": "serve",
            "pid": 1, "span_id": "q", "t0": 999.0, "t1": 1000.0})
    assert s._sample(1000.0) == {}


# --------------------------------------------------------------------- #
# anomaly sentinel
# --------------------------------------------------------------------- #

def _drive(sentinel, reg, windows, latency, sig="sig", n=3):
    out = []
    for w in range(windows):
        for i in range(n):
            reg.counter("serve_signature_requests_total",
                        signature=sig, outcome="completed")
            reg.observe("serve_signature_latency_s",
                        latency(w, i), signature=sig)
        out.append(sentinel.tick(reg))
    return out


def _sentinel():
    clock = itertools.count()
    return perf.AnomalySentinel(warmup=3, sustain=2,
                                clock=lambda: float(next(clock)))


def test_sentinel_flags_seeded_regression_within_budget():
    reg = MetricsRegistry()
    s = _sentinel()
    _drive(s, reg, 8, lambda w, i: 0.02 + 0.001 * (i % 2))
    assert s.findings == []          # healthy phase: silent
    per_window = _drive(s, reg, 4, lambda w, i: 0.5)
    first = next(i for i, f in enumerate(per_window) if f)
    assert first + 1 <= 3, "detection blew the 3-window budget"
    assert any(f["metric"] == "latency_mean_s"
               for f in per_window[first])
    f = [f for f in per_window[first]
         if f["metric"] == "latency_mean_s"][0]
    assert f["score"] >= s.k and f["windows"] == s.sustain
    # one finding per episode, not one per window
    assert sum(1 for fs in per_window
               for f in fs if f["metric"] == "latency_mean_s") == 1
    # frozen baseline: the outburst never became its own reference
    assert s._state[("sig", "latency_mean_s")]["ewma"] \
        == pytest.approx(0.02, abs=0.005)


def test_sentinel_healthy_soak_zero_findings():
    reg = MetricsRegistry()
    s = _sentinel()
    _drive(s, reg, 20, lambda w, i: 0.02 * (1 + 0.2 * ((w + i) % 3)))
    assert s.findings == []


def test_sentinel_zero_traffic_is_no_evidence():
    reg = MetricsRegistry()
    s = _sentinel()
    _drive(s, reg, 5, lambda w, i: 0.02)
    for _ in range(10):              # drained queue: nothing arrives
        assert s.tick(reg) == []
    assert s.findings == []


def test_sentinel_scores_exported(tmp_path):
    reg = MetricsRegistry()
    s = _sentinel()
    _drive(s, reg, 6, lambda w, i: 0.02)
    assert reg.find_gauges("perf_anomaly_score")


def test_sentinel_findings_reach_control_plane_decision_log():
    from heat2d_tpu.control.plane import ControlPlane
    from heat2d_tpu.obs.perf_cli import _StubFleet
    reg = MetricsRegistry()
    s = _sentinel()
    plane = ControlPlane(_StubFleet(), registry=reg, sentinel=s)
    for w in range(12):
        for i in range(3):
            reg.counter("serve_signature_requests_total",
                        signature="sig", outcome="completed")
            reg.observe("serve_signature_latency_s",
                        0.02 if w < 8 else 0.5, signature="sig")
        plane.tick()
    rows = [d for d in plane.decisions if d["action"] == "perf_anomaly"]
    assert rows and rows[0]["metric"] == "latency_mean_s"
    assert reg.find_counters("perf_anomalies_total")


# --------------------------------------------------------------------- #
# launch stamping — serve + mesh rows carry the roofline fields
# --------------------------------------------------------------------- #

PERF_ROW_KEYS = {"achieved_mcells_per_s", "bound_mcells_per_s",
                 "pct_of_bound", "bytes_per_cell_step",
                 "mcells_per_hbm_byte", "route", "elapsed_s"}


def test_serve_launch_rows_stamped():
    from heat2d_tpu.serve.engine import EnsembleEngine
    reg = MetricsRegistry()
    eng = EnsembleEngine(registry=reg, max_batch=4)
    eng.solve_batch(reqs(2))
    row = eng.launch_log[-1]
    assert PERF_ROW_KEYS <= set(row["perf"])
    p = row["perf"]
    assert p["route"] == "jnp" and p["achieved_mcells_per_s"] > 0
    assert p["bytes_per_cell_step"] == 8.0
    assert reg.find_counters("perf_launches_stamped_total")
    assert reg.find_gauges("perf_achieved_mcells_per_s")
    assert reg.find_gauges("perf_bytes_per_cell_step")


def test_serve_launch_card_joined_when_armed(tmp_path):
    from heat2d_tpu.serve.engine import EnsembleEngine
    reg = MetricsRegistry()
    perf.install(perf.PerfObserver(registry=reg, dir=str(tmp_path),
                                   service="serve"))
    try:
        eng = EnsembleEngine(registry=reg, max_batch=4)
        eng.solve_batch(reqs(2))
        obs = perf.observer()
        cards = obs.cards()
        assert len(cards) == 1 and cards[0]["route"] == "batch"
        assert eng.launch_log[-1]["perf"]["arithmetic_intensity"] \
            == cards[0]["arithmetic_intensity"]
        assert reg.find_counters("perf_cost_cards_total")
    finally:
        perf.uninstall()
    assert [f for f in os.listdir(tmp_path)
            if f.startswith("cost-cards-serve-")]


def test_mesh_launch_rows_stamped():
    from heat2d_tpu.mesh.engine import MeshEnsembleEngine
    reg = MetricsRegistry()
    eng = MeshEnsembleEngine(registry=reg)
    eng.solve_batch(reqs(1))
    row = eng.launch_log[-1]
    assert PERF_ROW_KEYS <= set(row["perf"])
    assert row["perf"]["achieved_mcells_per_s"] > 0


def test_convergence_launch_stamps_mean_steps():
    from heat2d_tpu.serve.engine import EnsembleEngine
    reg = MetricsRegistry()
    eng = EnsembleEngine(registry=reg, max_batch=4)
    rs = reqs(2, steps=50, convergence=True, interval=5)
    out = eng.solve_batch(rs)
    p = eng.launch_log[-1]["perf"]
    mean_done = sum(s for _, s in out) / len(out)
    # stamped throughput used steps-actually-done, not the cap
    assert p["achieved_mcells_per_s"] > 0
    assert mean_done <= 50


# --------------------------------------------------------------------- #
# surfaces: record kind, trace --stats join, CLI
# --------------------------------------------------------------------- #

def test_record_kinds_includes_perf():
    from heat2d_tpu.obs.record import RECORD_KINDS
    assert "perf" in RECORD_KINDS


def test_trace_stats_cost_card_join(tmp_path):
    from heat2d_tpu.obs import trace_cli
    (tmp_path / "cost-cards-t-1.jsonl").write_text(json.dumps(
        {"signature": "SIG", "bytes_accessed": 128.0,
         "arithmetic_intensity": 0.25}) + "\n")
    cards = trace_cli.load_cost_cards(str(tmp_path))
    assert cards == {"SIG": {"signature": "SIG",
                             "bytes_accessed": 128.0,
                             "arithmetic_intensity": 0.25}}
    report = {"dir": str(tmp_path), "traces": [
        {"signature": "SIG", "connected": True,
         "breakdown": {"launch": 1.0}}]}
    stats = trace_cli.segment_stats(report, cards=cards)
    assert stats["launch"]["hbm_bytes"] == 128.0
    assert stats["launch"]["arith_intensity"] == 0.25
    assert "hbm_bytes" not in stats["queue"]
    md = trace_cli.stats_markdown(report, cards=cards)
    assert "hbm bytes" in md and "128" in md
    # no cards -> the table keeps its old shape
    md = trace_cli.stats_markdown(report, cards={})
    assert "hbm bytes" not in md


def test_perf_cli_roofline_and_card_gate(capsys):
    from heat2d_tpu.obs import perf_cli
    assert perf_cli.main(["--roofline", "64x64,4096x4096",
                          "--json"]) == 0
    rows = json.loads(capsys.readouterr().out)
    assert rows[1]["route"] == "band"
    assert rows[1]["bound_mcells_per_s"] == pytest.approx(
        roofline.calibrated_bound_mcells(4096, 4096), abs=0.1)
    assert perf_cli.main(["--card", "24x24", "--steps", "3",
                          "--method", "jnp", "--batch", "2",
                          "--gate-model-pct", "15", "--json"]) == 0
    card = json.loads(capsys.readouterr().out.splitlines()[0])
    assert card["model"]["boundary_agreement_pct"] is not None


def test_perf_cli_requires_a_mode(capsys):
    from heat2d_tpu.obs import perf_cli
    assert perf_cli.main([]) == 2


def test_env_arming(tmp_path, monkeypatch):
    monkeypatch.setenv("HEAT2D_PERF_DIR", str(tmp_path))
    perf._env_checked = False
    perf._observer = None
    try:
        assert perf.enabled()
        assert perf.observer().dir == str(tmp_path)
    finally:
        perf.uninstall()
