"""Pod-scale mesh serving (heat2d_tpu/mesh/): the batch-vs-spatial
scheduler, mesh-sharded runner parity (bitwise on every occupancy
rung), the spatial route's compiled:True stamp, modeled-capacity
admission control, the O(log max_batch) compile contract per mesh
config, and the chips_per_unit capacity satellite (ISSUE 13)."""

import numpy as np
import pytest

import jax

from heat2d_tpu.mesh import (MeshAdmission, MeshEnsembleEngine,
                             MeshScheduler)
from heat2d_tpu.mesh.runner import mesh_batch_runner, mesh_capacity
from heat2d_tpu.models import ensemble
from heat2d_tpu.obs import MetricsRegistry
from heat2d_tpu.serve.engine import EnsembleEngine
from heat2d_tpu.serve.schema import Rejected, SolveRequest
from tests._pin import (assert_jaxpr_differs, assert_jaxpr_equal,
                        batch_runner_jaxpr, mesh_runner_jaxpr,
                        spatial_runner_jaxpr)

ND = len(jax.devices())
NX, NY, STEPS = 16, 20, 6

multichip = pytest.mark.skipif(ND < 8, reason="needs 8 devices")


def req(cx=0.1, cy=0.1, **kw):
    kw.setdefault("nx", NX)
    kw.setdefault("ny", NY)
    kw.setdefault("steps", STEPS)
    kw.setdefault("method", "jnp")
    return SolveRequest(cx=cx, cy=cy, **kw)


def reqs(n, **kw):
    return [req(cx=0.1 + 0.01 * i, **kw) for i in range(n)]


def grids(pairs):
    return [np.asarray(u).tobytes() for u, _ in pairs]


# --------------------------------------------------------------------- #
# capacity rule
# --------------------------------------------------------------------- #

def test_mesh_capacity_power_of_two_device_multiples():
    # classic ladder at nd=1
    assert [mesh_capacity(n, 8, 1) for n in (1, 2, 3, 5, 8)] \
        == [1, 2, 4, 8, 8]
    # device multiples at nd=4: never below one member per device
    assert mesh_capacity(1, 32, 4) == 4
    assert mesh_capacity(5, 32, 4) == 8
    assert mesh_capacity(9, 32, 4) == 16
    assert mesh_capacity(17, 32, 4) == 32
    # cap is the largest device multiple <= max_batch
    assert mesh_capacity(8, 10, 4) == 8
    # a bucket bigger than the cap still gets a shardable capacity
    assert mesh_capacity(12, 10, 4) == 12
    with pytest.raises(ValueError):
        mesh_capacity(1, 8, 0)


def test_mesh_capacity_ladder_is_log_bounded():
    caps = {mesh_capacity(n, 64, 8) for n in range(1, 65)}
    assert caps == {8, 16, 32, 64}          # log2(64/8)+1 rungs


# --------------------------------------------------------------------- #
# mesh runner parity — bitwise on every occupancy rung
# --------------------------------------------------------------------- #

def test_mesh_runner_bitwise_parity_every_rung():
    """The mesh-sharded runner's cropped results equal the single-chip
    batch_runner's byte-for-byte at every occupancy, across DIFFERENT
    pad capacities (the batch-composition-independence the padding
    design rests on)."""
    import jax.numpy as jnp

    single = ensemble.batch_runner(NX, NY, STEPS, "jnp")
    meshed = mesh_batch_runner(NX, NY, STEPS, "jnp", n_devices=ND)
    for n in (1, 2, 3, 5, 8):
        cxs = [0.1 + 0.01 * i for i in range(n)]
        cap_s = mesh_capacity(n, 8, 1)
        cap_m = mesh_capacity(n, 8 * ND, ND)
        pad_s = jnp.asarray(cxs + [cxs[-1]] * (cap_s - n), jnp.float32)
        pad_m = jnp.asarray(cxs + [cxs[-1]] * (cap_m - n), jnp.float32)
        u_s = jnp.broadcast_to(jnp.zeros((NX, NY), jnp.float32) + 1.0,
                               (cap_s, NX, NY))
        u_m = jnp.broadcast_to(jnp.zeros((NX, NY), jnp.float32) + 1.0,
                               (cap_m, NX, NY))
        a = np.asarray(single(u_s, pad_s, pad_s))[:n]
        b = np.asarray(meshed(u_m, pad_m, pad_m))[:n]
        np.testing.assert_array_equal(a, b)


def test_mesh_runner_rejects_unshardable_batch():
    meshed = mesh_batch_runner(NX, NY, STEPS, "jnp", n_devices=ND)
    if ND == 1:
        pytest.skip("every batch shards on one device")
    import jax.numpy as jnp
    bad = ND + 1
    with pytest.raises(ValueError, match="multiple"):
        meshed(jnp.zeros((bad, NX, NY), jnp.float32),
               jnp.zeros((bad,)), jnp.zeros((bad,)))


def test_engine_parity_every_rung_fixed_and_convergence():
    """MeshEnsembleEngine.solve_batch == EnsembleEngine.solve_batch,
    bitwise, on every occupancy rung — fixed-step AND the convergence
    early-exit schedule (steps_done included)."""
    meshed = MeshEnsembleEngine(n_devices=ND)
    single = EnsembleEngine(max_batch=8)
    for n in (1, 2, 3, 5, 8):
        rs = reqs(n)
        assert grids(meshed.solve_batch(rs)) \
            == grids(single.solve_batch(rs))
    conv = dict(convergence=True, interval=5, sensitivity=1e-4,
                steps=40)
    for n in (1, 4):
        rs = reqs(n, **conv)
        a = meshed.solve_batch(rs)
        b = single.solve_batch(rs)
        assert grids(a) == grids(b)
        assert [s for _, s in a] == [s for _, s in b]


@multichip
def test_engine_routes_batch_on_mesh():
    meshed = MeshEnsembleEngine(n_devices=ND)
    meshed.solve_batch(reqs(3))
    row = meshed.launch_log[-1]
    assert row["mesh"]["route"] == "batch"
    assert row["mesh"]["n_devices"] == ND
    assert row["capacity"] % ND == 0


# --------------------------------------------------------------------- #
# the scheduler's split
# --------------------------------------------------------------------- #

def test_scheduler_split_decisions():
    reg = MetricsRegistry()
    s = MeshScheduler(n_devices=ND, registry=reg)
    d = s.decide(req())
    if ND < 2:
        assert d["route"] == "single" and d["reason"] == "one_device"
    else:
        assert d["route"] == "batch" and d["reason"] == "fits_chip"
    # memoized per signature: same row object, one route count
    assert s.decide(req(cx=0.9)) is d
    assert reg.find_counters("mesh_route_total")
    # non-solve kinds stay on the single-chip path
    class FakeInverse:
        nx, ny, steps = NX, NY, STEPS
        request_kind = "inverse"
        dtype = "float32"

        def signature(self):
            return ("inverse", NX, NY)
    assert s.decide(FakeInverse())["reason"] == "request_kind"


@multichip
def test_scheduler_spatial_when_member_exceeds_threshold():
    s = MeshScheduler(n_devices=ND, spatial_bytes_threshold=1)
    d = s.decide(req(nx=48, ny=64))
    assert d["route"] == "spatial"
    assert d["spatial_grid"] == s.spatial_grid()
    assert d["plan"]["tier"] in ("overlap", "ici", "window",
                                 "collective")


@multichip
def test_unplannable_routes_single_chip_with_counter():
    """The totality follow-through: a shape the (2, 4) decomposition
    cannot take is SERVED single-chip (bitwise the single-chip
    answer) with mesh_fallback_total{reason="unplannable"} — never
    rejected."""
    reg = MetricsRegistry()
    sched = MeshScheduler(n_devices=ND, registry=reg,
                          spatial_bytes_threshold=1)
    meshed = MeshEnsembleEngine(n_devices=ND, scheduler=sched,
                                registry=reg)
    single = EnsembleEngine(max_batch=8)
    rs = reqs(2, nx=15, ny=18)           # 15 % 2, 18 % 4 != 0
    assert sched.decide(rs[0])["reason"] == "unplannable"
    assert grids(meshed.solve_batch(rs)) \
        == grids(single.solve_batch(rs))
    fallbacks = reg.find_counters("mesh_fallback_total")
    assert {dict(k)["reason"]: v for k, v in fallbacks.items()} \
        == {"unplannable": 1}
    assert meshed.launch_log[-1]["mesh"]["route"] == "single"
    # the plan row records WHY (the PR 7 error-carrying plan)
    plan = meshed.halo_plans[rs[0].signature()]
    assert plan["tier"] == "unplannable" and "error" in plan


# --------------------------------------------------------------------- #
# spatial route: compiled:True + bitwise vs collective/single-chip
# --------------------------------------------------------------------- #

@multichip
def test_spatial_route_compiles_plan_and_matches_single_chip():
    reg = MetricsRegistry()
    sched = MeshScheduler(n_devices=ND, registry=reg,
                          spatial_bytes_threshold=1)
    meshed = MeshEnsembleEngine(n_devices=ND, scheduler=sched,
                                registry=reg)
    single = EnsembleEngine(max_batch=8)
    rs = reqs(3, nx=48, ny=64)
    sig = rs[0].signature()
    # pre-launch: the PR 7 socket still reads compiled: False
    meshed._preresolve_tuned(rs[0])
    assert meshed.halo_plans[sig]["compiled"] is False
    assert grids(meshed.solve_batch(rs)) \
        == grids(single.solve_batch(rs))
    plan = meshed.halo_plans[sig]
    assert plan["compiled"] is True          # the socket, closed
    assert plan["mesh"] == sched.spatial_grid()
    assert meshed.launch_log[-1]["mesh"]["route"] == "spatial"
    assert meshed.launch_log[-1]["halo_plan"]["compiled"] is True
    assert reg.find_counters("mesh_spatial_compiled_total")
    # warm relaunch reuses the memoized spatial runner
    launches = meshed.launches
    meshed.solve_batch(reqs(2, nx=48, ny=64))
    assert meshed.launches == launches + 1


def test_spatial_runner_jaxpr_degraded_fused_equals_collective():
    """The serve spatial runner inherits PR 7's degradation contract:
    on a 1x1 grid there is nothing to overlap, so the fused program
    is byte-identical to the collective one."""
    a = spatial_runner_jaxpr(24, 24, 8, 1, 1, halo="collective",
                             n_devices=1)
    b = spatial_runner_jaxpr(24, 24, 8, 1, 1, halo="fused",
                             n_devices=1)
    assert_jaxpr_equal(a, b, "spatial serve runner (1x1 degraded)")


@multichip
def test_spatial_runner_jaxpr_fused_differs_and_is_bitwise():
    """Non-vacuity + parity: on a real 2x2 submesh the fused program
    DIFFERS from the collective one, and their served results are
    bitwise-identical (the PR 7 overlap contract through the serve
    path)."""
    a = spatial_runner_jaxpr(32, 32, 8, 2, 2, halo="collective",
                             n_devices=4)
    b = spatial_runner_jaxpr(32, 32, 8, 2, 2, halo="fused",
                             n_devices=4)
    assert_jaxpr_differs(a, b, "spatial serve runner (2x2 fused)")
    rc = ensemble.spatial_batch_runner(32, 32, 8, 2, 2,
                                       halo="collective", n_devices=4)
    rf = ensemble.spatial_batch_runner(32, 32, 8, 2, 2, halo="fused",
                                       n_devices=4)
    import jax.numpy as jnp
    u0 = jnp.broadcast_to(
        jnp.arange(32 * 32, dtype=jnp.float32).reshape(32, 32),
        (3, 32, 32))
    cx = jnp.asarray([0.1, 0.12, 0.14], jnp.float32)
    uc, kc = rc(u0, cx, cx)
    uf, kf = rf(u0, cx, cx)
    np.testing.assert_array_equal(np.asarray(uc), np.asarray(uf))
    np.testing.assert_array_equal(np.asarray(kc), np.asarray(kf))


# --------------------------------------------------------------------- #
# admission control — modeled saturation, deterministic clock
# --------------------------------------------------------------------- #

def make_admission(reg=None, cells_per_launch=None, **kw):
    clock = {"t": 0.0}
    # capacity chosen so ~2 requests fit one window
    kw.setdefault("per_chip_mcells_per_s",
                  2 * NX * NY * STEPS / 1e6 / kw.get("window_s", 1.0)
                  / max(ND, 1) / kw.get("headroom", 1.0))
    kw.setdefault("window_s", 1.0)
    kw.setdefault("headroom", 1.0)
    adm = MeshAdmission(n_devices=ND, registry=reg,
                        clock=lambda: clock["t"], **kw)
    return adm, clock


def test_admission_sheds_on_modeled_saturation():
    reg = MetricsRegistry()
    adm, clock = make_admission(reg)
    assert adm.admit(req()) is None
    assert adm.admit(req(cx=0.2)) is None
    rej = adm.admit(req(cx=0.3))         # window full: shed
    assert isinstance(rej, Rejected)
    assert rej.code == "mesh_saturated"
    assert rej.fields["offered_cells_per_s"] \
        > rej.fields["capacity_cells_per_s"]
    assert reg.find_counters("mesh_admission_shed_total")
    # shed work was NOT charged: the window drains on the clock and
    # admission resumes exactly when the model says capacity frees
    clock["t"] = 1.01
    assert adm.admit(req(cx=0.4)) is None


def test_admission_through_the_server():
    """A saturated leader is shed with rejected_mesh_saturated while
    cache hits keep answering (the shed-compute-not-answers
    contract)."""
    from heat2d_tpu.serve.server import SolveServer

    reg = MetricsRegistry()
    adm, clock = make_admission()
    server = SolveServer(registry=reg, max_delay=0.02,
                         admission=adm)
    with server:
        a = server.submit(req()).result(60)
        b = server.submit(req(cx=0.2)).result(60)
        assert not a.cache_hit and not b.cache_hit
        with pytest.raises(Rejected, match="mesh_saturated"):
            server.submit(req(cx=0.3)).result(60)
        # the first request again: a cache hit, served while saturated
        hit = server.submit(req()).result(60)
        assert hit.cache_hit
    counts = reg.snapshot()["counters"]
    assert counts["serve_requests_total{outcome=rejected_"
                  "mesh_saturated}"] >= 1


def test_admission_exempts_non_solve_kinds():
    """Inverse requests route OFF the mesh (scheduler) and their cost
    is iterations-scaled, not nx*ny*steps — admission must neither
    price nor shed them, and must not let them distort the solve
    window."""
    adm, _clock = make_admission()

    class FakeInverse:
        nx, ny, steps = 1_000_000, 1_000_000, 1_000_000
        request_kind = "inverse"
    assert adm.admit(FakeInverse()) is None     # never shed
    # and never charged: the solve window is still empty
    assert adm.admit(req()) is None
    assert adm.admit(req(cx=0.2)) is None


def test_engine_max_batch_per_chip_scales_with_mesh():
    """The CLIs' --max-batch survives --mesh as a PER-CHIP bound
    rather than being silently replaced by the engine default."""
    e = MeshEnsembleEngine(n_devices=ND, max_batch_per_chip=2)
    assert e.max_batch == 2 * ND
    # explicit total still wins
    e2 = MeshEnsembleEngine(n_devices=ND, max_batch=3 * ND,
                            max_batch_per_chip=2)
    assert e2.max_batch == 3 * ND


def test_admission_validation():
    with pytest.raises(ValueError):
        MeshAdmission(n_devices=ND, window_s=0)
    with pytest.raises(ValueError):
        MeshAdmission(n_devices=ND, headroom=0)


def test_mesh_saturated_is_a_shed_code():
    from heat2d_tpu.load.runner import SHED_CODES
    assert "mesh_saturated" in SHED_CODES


# --------------------------------------------------------------------- #
# compile budget — O(log max_batch) per mesh config
# --------------------------------------------------------------------- #

def test_serve_compile_report_mesh_engine_holds_budget():
    from heat2d_tpu.analysis.recompile import serve_compile_report

    rep = serve_compile_report(
        max_batch=8,
        engine_factory=lambda: MeshEnsembleEngine(n_devices=ND))
    assert rep["compiles"] <= rep["budget"], rep
    if ND > 1:
        # device-multiple padding: every capacity shards
        assert all(c % ND == 0 for c in rep["capacities"]), rep
        assert all("mesh_batch_runner" in n for n in rep["names"]), rep


def test_serve_compile_report_single_chip_unchanged():
    from heat2d_tpu.analysis.recompile import serve_compile_report

    rep = serve_compile_report(max_batch=8)
    assert rep["compiles"] <= rep["budget"], rep
    assert rep["capacities"] == [1, 2, 4, 8]


# --------------------------------------------------------------------- #
# free-when-off pins
# --------------------------------------------------------------------- #

def test_single_chip_runner_program_untouched_by_mesh():
    """Building/serving through the whole mesh stack must leave the
    single-chip batch runner's traced program byte-identical — the
    mesh is a new engine, not a tax on the old one."""
    before = batch_runner_jaxpr(NX, NY, STEPS, "jnp")
    meshed = MeshEnsembleEngine(n_devices=ND)
    meshed.solve_batch(reqs(2))
    adm, _ = make_admission()
    adm.admit(req(cx=0.5))
    after = batch_runner_jaxpr(NX, NY, STEPS, "jnp")
    assert_jaxpr_equal(before, after, "single-chip batch runner")


def test_mesh_runner_program_independent_of_scheduler_state():
    """Scheduler decisions and admission are host-side math: the mesh
    runner's traced program is identical with them armed."""
    before = mesh_runner_jaxpr(NX, NY, STEPS, "jnp", n_devices=ND)
    reg = MetricsRegistry()
    sched = MeshScheduler(n_devices=ND, registry=reg)
    sched.decide(req())
    adm, _ = make_admission(reg)
    adm.admit(req(cx=0.7))
    after = mesh_runner_jaxpr(NX, NY, STEPS, "jnp", n_devices=ND)
    assert_jaxpr_equal(before, after, "mesh batch runner")


# --------------------------------------------------------------------- #
# bench_serve payload
# --------------------------------------------------------------------- #

def test_measure_serve_scaling_payload():
    from heat2d_tpu.mesh.bench import measure_serve_scaling

    p = measure_serve_scaling(n_devices=ND, nx=16, ny=20, steps=4,
                              wall=False)
    assert p["parity"] is True
    assert all(r["bitwise"] for r in p["parity_rungs"])
    assert p["n_devices"] == ND
    assert 0 < p["modeled_scaling_efficiency"] <= 1.0
    assert p["model"]["name"].startswith("heat2d-tpu/serve-scaling")
    assert p["serve_scaling_efficiency"] \
        == p["modeled_scaling_efficiency"]
    if ND >= 8:
        assert p["serve_scaling_efficiency"] >= 0.75   # >= 6x at 8


@multichip
def test_measure_spatial_serve_payload():
    from heat2d_tpu.mesh.bench import measure_spatial_serve

    p = measure_spatial_serve(n_devices=ND, nx=48, ny=64, steps=8)
    assert p["route"] == "spatial"
    assert p["compiled"] is True and p["parity"] is True
    assert p["halo_plan"]["mesh"] == [2, 4]


# --------------------------------------------------------------------- #
# chips_per_unit capacity satellite
# --------------------------------------------------------------------- #

def test_fit_capacity_chips_dimension():
    from heat2d_tpu.load.capacity import (advise, chips_for,
                                          fit_capacity, units_for)

    rows = [{"offered_rps": r, "achieved_rps": r, "shed_rate": 0.0,
             "slo_ok": True} for r in (4.0, 8.0)]
    rows.append({"offered_rps": 16.0, "achieved_rps": 9.0,
                 "shed_rate": 0.2, "slo_ok": False})
    fit = fit_capacity(rows, 2, chips_per_unit=8)
    assert fit["chips_per_unit"] == 8 and fit["chips"] == 16
    assert fit["per_chip_rps"] == pytest.approx(8.0 / 16)
    assert units_for(fit, 12.0) == 3
    assert chips_for(fit, 12.0) == 24
    adv = advise(fit, observed_rps=12.0, current_units=2)
    assert adv["needed_units"] == 3 and adv["needed_chips"] == 24
    assert adv["current_chips"] == 16 and adv["chips_per_unit"] == 8
    # pre-mesh fits: chips rows equal unit rows
    fit1 = fit_capacity(rows, 2)
    assert fit1["chips_per_unit"] == 1
    assert fit1["chips"] == fit1["units"]
    assert chips_for(fit1, 12.0) == units_for(fit1, 12.0)
    with pytest.raises(ValueError):
        fit_capacity(rows, 2, chips_per_unit=0)


def test_serve_target_mesh_chips_per_unit():
    from heat2d_tpu.load.runner import ServeTarget

    t = ServeTarget(registry=MetricsRegistry(), mesh=True)
    try:
        assert t.units == 1
        assert t.chips_per_unit == ND
        assert t.server.engine.n_devices == ND
        fut = t.submit(req(), "tenant", 60.0)
        assert fut.result(60).steps_done == STEPS
    finally:
        t.close()
