"""Fused (overlap) halo-route tests — ISSUE 8.

The fused route must be BITWISE-identical to the collective route on
every path (the overlap decomposition recomputes the t-wide boundary
frames from strip-extended regions, but every kept cell's per-step
arithmetic DAG is unchanged — the temporal-blocking cone argument), and
must DEGRADE byte-identically to the collective program wherever the
overlap geometry fails (deep halos, 1-wide shards). Runs on the 8
virtual CPU devices of conftest; CI additionally runs this file under an
explicit ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` job
(multichip-sim) so mesh control flow gates every PR.
"""

import jax
import numpy as np
import pytest

from heat2d_tpu.config import ConfigError, HeatConfig
from tests._pin import (assert_jaxpr_differs, assert_jaxpr_equal,
                        sharded_runner_jaxpr)
from heat2d_tpu.models.solver import Heat2DSolver
from heat2d_tpu.parallel.halo import fused_halo_viable
from heat2d_tpu.parallel.mesh import make_mesh
from heat2d_tpu.parallel.sharded import (effective_halo_depth,
                                         resolve_halo_route)

MESHES = [(1, 2), (2, 2), (2, 4)]


def _run(cfg):
    return Heat2DSolver(cfg).run(timed=False)


def _serial(nx, ny, steps, **kw):
    return _run(HeatConfig(nxprob=nx, nyprob=ny, steps=steps,
                           mode="serial", **kw))


# ------------------------------------------------------------------ #
# Bitwise parity: fused vs collective vs serial
# ------------------------------------------------------------------ #

@pytest.mark.parametrize("gx,gy", MESHES)
def test_fused_fixed_step_bitwise(gx, gy):
    nx, ny, steps = 32, 32, 23
    base = dict(nxprob=nx, nyprob=ny, steps=steps, mode="dist2d",
                gridx=gx, gridy=gy, halo_depth=3)
    fused = _run(HeatConfig(halo="fused", **base))
    col = _run(HeatConfig(**base))
    serial = _serial(nx, ny, steps)
    # The overlap tier must actually engage (not a vacuous pass through
    # full degradation).
    route = resolve_halo_route(HeatConfig(halo="fused", **base),
                               make_mesh(gx, gy))
    assert route["tier"] == "overlap"
    np.testing.assert_array_equal(fused.u, col.u)
    np.testing.assert_array_equal(fused.u, serial.u)


@pytest.mark.parametrize("gx,gy", MESHES)
def test_fused_convergence_bitwise(gx, gy):
    """Convergence path (the chunked residual loop — on CPU the D2R
    window route cannot lower, so this IS the residual path the mesh
    runs here): step counts and fields must match the collective route
    exactly."""
    base = dict(nxprob=32, nyprob=32, steps=100000, convergence=True,
                interval=20, sensitivity=0.1, mode="dist2d",
                gridx=gx, gridy=gy, halo_depth=3)
    fused = _run(HeatConfig(halo="fused", **base))
    col = _run(HeatConfig(**base))
    assert fused.steps_done == col.steps_done
    np.testing.assert_array_equal(fused.u, col.u)


def test_fused_residual_f64_accum_bitwise():
    """The float64-accumulation residual branch (the f64 gate that
    keeps D2R off even on TPU) — fused vs collective bitwise, and the
    same early-exit step count as serial."""
    base = dict(nxprob=16, nyprob=16, steps=100000, convergence=True,
                interval=10, sensitivity=0.1, accum_dtype="float64",
                mode="dist2d", gridx=2, gridy=2, halo_depth=2)
    fused = _run(HeatConfig(halo="fused", **base))
    col = _run(HeatConfig(**base))
    serial = _serial(16, 16, 100000, convergence=True, interval=10,
                     sensitivity=0.1, accum_dtype="float64")
    assert fused.steps_done == col.steps_done == serial.steps_done
    np.testing.assert_array_equal(fused.u, col.u)


def test_fused_interval_one_residual_path():
    """interval=1: every chunk is a tracked-step + residual pair — the
    densest residual schedule the engine has."""
    base = dict(nxprob=24, nyprob=24, steps=300, convergence=True,
                interval=1, sensitivity=1e-30, mode="dist2d",
                gridx=2, gridy=2, halo_depth=2)
    fused = _run(HeatConfig(halo="fused", **base))
    col = _run(HeatConfig(**base))
    assert fused.steps_done == col.steps_done
    np.testing.assert_array_equal(fused.u, col.u)


def test_fused_remainder_chunk_bitwise():
    """Odd step counts exercise the remainder chunk (depth n % T) on
    the fused route — remainder depths degrade independently."""
    base = dict(nxprob=32, nyprob=32, steps=19, mode="dist2d",
                gridx=2, gridy=2, halo_depth=4)
    fused = _run(HeatConfig(halo="fused", **base))
    np.testing.assert_array_equal(fused.u, _serial(32, 32, 19).u)


def test_fused_dist1d_row_strips_bitwise():
    """dist1d (row-strip mesh, N/S traffic only) through the fused
    route — the (numworkers, 1) mesh has gy=1, so E/W strips are the
    zero-fill path."""
    base = dict(nxprob=40, nyprob=12, steps=25, mode="dist1d",
                numworkers=4, halo_depth=3)
    fused = _run(HeatConfig(halo="fused", **base))
    col = _run(HeatConfig(**base))
    np.testing.assert_array_equal(fused.u, col.u)
    np.testing.assert_array_equal(fused.u, _serial(40, 12, 25).u)


def test_fused_hybrid_degrades_bitwise():
    """mode='hybrid' + halo='fused' off-TPU: kernel F cannot lower
    (remote DMA needs Mosaic), so the route must degrade to the
    collective hybrid path — bitwise vs serial under bitwise_parity."""
    cfg = HeatConfig(nxprob=16, nyprob=32, steps=9, mode="hybrid",
                     gridx=2, gridy=2, halo_depth=3, halo="fused",
                     bitwise_parity=True)
    r = _run(cfg)
    np.testing.assert_array_equal(r.u, _serial(16, 32, 9).u)
    from heat2d_tpu.ops.pallas_stencil import make_shard_chunk_kernel
    route = resolve_halo_route(cfg, make_mesh(2, 2),
                               chunk_kernel=make_shard_chunk_kernel(cfg))
    assert route["tier"] == "collective"


# ------------------------------------------------------------------ #
# jaxpr pins: degradation is BYTE-identical, collective is untouched
# ------------------------------------------------------------------ #

def _runner_jaxpr(cfg, mesh):
    return sharded_runner_jaxpr(cfg, mesh)


def test_jaxpr_pin_collective_route_unchanged():
    """Selecting the collective route traces the EXACT program a config
    that never mentions halo traces (the field's default) — the fused
    subsystem costs the existing sharded runner nothing."""
    mesh = make_mesh(2, 2)
    base = dict(nxprob=16, nyprob=16, steps=12, mode="dist2d",
                gridx=2, gridy=2)
    explicit = _runner_jaxpr(HeatConfig(halo="collective", **base), mesh)
    default = _runner_jaxpr(HeatConfig(**base), mesh)
    assert_jaxpr_equal(explicit, default,
                       label="collective route (explicit vs default)")


def test_jaxpr_pin_degraded_fused_is_collective():
    """A fused request whose geometry fails at EVERY chunk depth
    (1-row shards: no depth can tile an overlap frame) must trace the
    collective program BYTE-identically — degradation is not 'nearly
    the same route', it IS the route. (Deep-halo configs degrade only
    their full-depth chunks; remainder chunks stay fused where viable,
    so they are parity-tested, not jaxpr-pinned.)"""
    mesh = make_mesh(8, 1)
    base = dict(nxprob=8, nyprob=16, steps=12, mode="dist2d",
                gridx=8, gridy=1, halo_depth=100)
    fused = _runner_jaxpr(HeatConfig(halo="fused", **base), mesh)
    col = _runner_jaxpr(HeatConfig(halo="collective", **base), mesh)
    assert_jaxpr_equal(fused, col,
                       label="fully-degraded fused vs collective")


def test_jaxpr_pin_viable_fused_differs():
    """Sanity for the pins above: a VIABLE fused request traces a
    different program (otherwise the parity tests prove nothing)."""
    mesh = make_mesh(2, 2)
    base = dict(nxprob=32, nyprob=32, steps=12, mode="dist2d",
                gridx=2, gridy=2, halo_depth=3)
    fused = _runner_jaxpr(HeatConfig(halo="fused", **base), mesh)
    col = _runner_jaxpr(HeatConfig(halo="collective", **base), mesh)
    assert_jaxpr_differs(fused, col, label="viable fused route")


# ------------------------------------------------------------------ #
# Deep-halo / degenerate-shard edge cases (previously unpinned)
# ------------------------------------------------------------------ #

def test_effective_halo_depth_clamps_to_shard():
    cfg = HeatConfig(nxprob=16, nyprob=16, mode="dist2d", gridx=4,
                     gridy=2, halo_depth=100)
    assert effective_halo_depth(cfg, make_mesh(4, 2)) == 4  # min(bm, bn)
    cfg2 = cfg.replace(halo_depth=None)
    assert effective_halo_depth(cfg2, make_mesh(4, 2)) == 4


def test_deep_halo_fused_degrades_and_matches():
    """halo_depth far beyond the shard interior: clamped, fused
    degrades, result still bitwise vs serial."""
    base = dict(nxprob=16, nyprob=16, steps=12, mode="dist2d",
                gridx=4, gridy=2, halo_depth=100)
    for halo in ("collective", "fused"):
        r = _run(HeatConfig(halo=halo, **base))
        np.testing.assert_array_equal(r.u, _serial(16, 16, 12).u)


def test_one_wide_shards_both_routes():
    """1-row shards (bm=1, depth clamps to 1): the overlap frames can
    never tile a 1-wide block, so fused degrades — and both routes stay
    bitwise vs serial (the corner the issue calls out as unpinned)."""
    base = dict(nxprob=8, nyprob=16, steps=10, mode="dist2d",
                gridx=8, gridy=1)
    serial = _serial(8, 16, 10)
    for halo in ("collective", "fused"):
        cfg = HeatConfig(halo=halo, **base)
        assert effective_halo_depth(cfg, make_mesh(8, 1)) == 1
        r = _run(cfg)
        np.testing.assert_array_equal(r.u, serial.u)
    assert not fused_halo_viable(1, 16, 1)


def test_depth_equals_half_shard_boundary():
    """bm == 2T exactly: the interior region is empty but the frames
    still tile — the geometry gate's boundary (viable) — and bm < 2T
    (non-viable) right next to it."""
    assert fused_halo_viable(8, 8, 4)
    assert not fused_halo_viable(7, 8, 4)
    base = dict(nxprob=16, nyprob=16, steps=9, mode="dist2d",
                gridx=2, gridy=2, halo_depth=4)   # shard 8x8, T=4
    fused = _run(HeatConfig(halo="fused", **base))
    np.testing.assert_array_equal(fused.u, _serial(16, 16, 9).u)


def test_fused_uneven_padded_shards():
    """Pad-to-multiple decomposition (10 rows over 4 shards) under the
    fused route: pad rows sit outside the keep mask on every region."""
    base = dict(nxprob=10, nyprob=16, steps=14, mode="dist1d",
                numworkers=4, halo_depth=1)
    fused = _run(HeatConfig(halo="fused", **base))
    col = _run(HeatConfig(**base))
    np.testing.assert_array_equal(fused.u, col.u)
    np.testing.assert_array_equal(fused.u, _serial(10, 16, 14).u)


# ------------------------------------------------------------------ #
# Ensemble / serving integration
# ------------------------------------------------------------------ #

def test_ensemble_spatial_fused_bitwise():
    from heat2d_tpu.models.ensemble import run_ensemble_spatial
    cxs, cys = [0.1, 0.2], [0.1, 0.05]
    got, ks = run_ensemble_spatial(16, 16, 12, cxs, cys, gridx=2,
                                   gridy=2, halo="fused", halo_depth=2)
    want, kw = run_ensemble_spatial(16, 16, 12, cxs, cys, gridx=2,
                                    gridy=2, halo_depth=2)
    assert [int(k) for k in ks] == [int(k) for k in kw]
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_spatial_halo_plan_preresolves():
    from heat2d_tpu.models.ensemble import spatial_halo_plan
    plan = spatial_halo_plan(32, 32, 2, 2, halo="fused", halo_depth=3)
    assert plan["route"] == "fused" and plan["tier"] == "overlap"
    plan = spatial_halo_plan(32, 32, 2, 2, halo="collective")
    assert plan["route"] == "collective"
    # Deep halo: the plan records the degradation, not the request.
    plan = spatial_halo_plan(8, 8, 4, 4, halo="fused")
    assert plan["route"] == "collective"


def test_serve_engine_preresolves_halo_plan():
    """A spatial serve engine resolves the halo plan per signature
    before first compile and stamps it on every launch row; the default
    engine records nothing new (byte-identical launch rows)."""
    from heat2d_tpu.serve.engine import EnsembleEngine
    from heat2d_tpu.serve.schema import SolveRequest

    reqs = [SolveRequest(nx=16, ny=16, steps=4, cx=0.1, cy=0.1),
            SolveRequest(nx=16, ny=16, steps=4, cx=0.2, cy=0.1)]
    eng = EnsembleEngine(spatial_grid=(2, 2), halo="fused")
    eng.solve_batch(reqs)
    sig = reqs[0].signature()
    assert sig in eng.halo_plans
    assert eng.halo_plans[sig]["requested"] == "fused"
    # Advisory until the mesh-aware engine lands: the record must not
    # claim a spatial program compiled (the launch was a single-device
    # batch runner).
    assert eng.halo_plans[sig]["compiled"] is False
    assert eng.launch_log[-1]["halo_plan"] == eng.halo_plans[sig]

    plain = EnsembleEngine()
    plain.solve_batch(reqs)
    assert "halo_plan" not in plain.launch_log[-1]
    assert plain.halo_plans == {}


def test_serve_engine_halo_plan_is_advisory_never_fatal():
    """A shape the spatial decomposition cannot take (15 % 2 != 0) must
    still SERVE — the plan is advisory: it records the failure instead
    of raising out of solve_batch (the single-device runner that
    actually launches handles the shape fine)."""
    from heat2d_tpu.serve.engine import EnsembleEngine
    from heat2d_tpu.serve.schema import SolveRequest

    reqs = [SolveRequest(nx=15, ny=16, steps=3, cx=0.1, cy=0.1)]
    eng = EnsembleEngine(spatial_grid=(2, 2), halo="fused")
    out = eng.solve_batch(reqs)          # must not raise
    assert len(out) == 1
    plan = eng.halo_plans[reqs[0].signature()]
    assert plan["tier"] == "unplannable" and "error" in plan
    assert plan["route"] == "collective"


# ------------------------------------------------------------------ #
# Tune integration: the fused candidate dimension
# ------------------------------------------------------------------ #

def test_candidate_space_covers_fused():
    from heat2d_tpu.tune.space import Problem, candidate_space
    cands, pruned = candidate_space(Problem(640, 512),
                                    routes=("fused",), assume_tpu=True)
    assert {c.route for c in cands} == {"fused"}
    assert all(c.tsteps >= 1 for c in cands)
    # Geometry prune: a shard too small for the deepest ladder entries.
    cands2, pruned2 = candidate_space(Problem(24, 24),
                                      routes=("fused",), assume_tpu=True)
    reasons = [r for c, r in pruned2 if c.route == "fused"]
    assert any("overlap frames" in r for r in reasons)
    assert all(c.tsteps <= 8 for c in cands2)


def test_simulated_backend_fused_deterministic():
    from heat2d_tpu.tune.measure import SimulatedBackend
    from heat2d_tpu.tune.space import Candidate, Problem
    b = SimulatedBackend()
    p = Problem(640, 512)
    t1 = b.step_time(p, Candidate("fused", 0, 8))
    assert t1 == b.step_time(p, Candidate("fused", 0, 8))
    # Failure mode: frames exceed the shard.
    from heat2d_tpu.tune.measure import SimulatedCompileError
    with pytest.raises(SimulatedCompileError):
        b.step_time(Problem(12, 12), Candidate("fused", 0, 8))


def test_fused_config_validation_ladder(tmp_path, monkeypatch):
    """runtime.fused_config: no db -> None; a fused best -> applied
    (and effective_halo_depth consumes it); a too-deep entry -> None
    (degrades to the static depth); a non-fused best -> None."""
    from heat2d_tpu.ops import pallas_stencil as ps
    from heat2d_tpu.tune import runtime as rt
    from heat2d_tpu.tune.db import TuningDB

    monkeypatch.setattr(rt, "_explicit", None)
    rt.set_tuning_db(None)
    assert rt.fused_config(16, 16) is None

    kind = ps._vmem_total()[1]
    db = TuningDB(str(tmp_path / "db.json"))
    fkey = "fused:16x16:float32"    # the fused-frontier namespace
    db.record_point(kind, fkey,
                    {"route": "fused", "bm": 0, "tsteps": 2,
                     "status": "ok", "mcells_per_s": 100.0})
    db.set_best(kind, fkey,
                {"route": "fused", "bm": 0, "tsteps": 2}, 100.0, {})
    db.save()
    try:
        rt.set_tuning_db(db)
        cfg = rt.fused_config(16, 16)
        assert cfg is not None and cfg.tsteps == 2
        # The depth planner consumes it (fused requests only).
        hc = HeatConfig(nxprob=32, nyprob=32, mode="dist2d", gridx=2,
                        gridy=2, halo="fused")
        assert effective_halo_depth(hc, make_mesh(2, 2)) == 2
        col = hc.replace(halo="collective")
        assert effective_halo_depth(col, make_mesh(2, 2)) == 8
        # Too-deep for the shard: re-validation rejects it.
        db.set_best(kind, fkey,
                    {"route": "fused", "bm": 0, "tsteps": 12}, 90.0, {})
        rt.set_tuning_db(db)
        assert rt.fused_config(16, 16) is None
        # A plain-frontier (single-chip) best never answers for fused —
        # the namespaces are disjoint by design (global-mesh rates must
        # not shadow band configs and vice versa).
        db.set_best(kind, "16x16:float32",
                    {"route": "C", "bm": 32, "tsteps": 8}, 80.0, {})
        rt.set_tuning_db(db)
        assert rt.fused_config(16, 16) is None
        assert rt.band_config(16, 16) is not None   # band side intact
    finally:
        rt.set_tuning_db(None)


def test_tuned_depth_steers_fused_run_bitwise(tmp_path):
    """A db-steered overlap depth changes the schedule, never the
    answer: fused with tuned T=2 stays bitwise-equal to collective."""
    from heat2d_tpu.ops import pallas_stencil as ps
    from heat2d_tpu.tune import runtime as rt
    from heat2d_tpu.tune.db import TuningDB

    kind = ps._vmem_total()[1]
    db = TuningDB(str(tmp_path / "db.json"))
    db.record_point(kind, "fused:16x16:float32",
                    {"route": "fused", "bm": 0, "tsteps": 2,
                     "status": "ok", "mcells_per_s": 100.0})
    db.set_best(kind, "fused:16x16:float32",
                {"route": "fused", "bm": 0, "tsteps": 2}, 100.0, {})
    base = dict(nxprob=32, nyprob=32, steps=13, mode="dist2d",
                gridx=2, gridy=2)
    col = _run(HeatConfig(**base))
    try:
        rt.set_tuning_db(db)
        fused = _run(HeatConfig(halo="fused", **base))
    finally:
        rt.set_tuning_db(None)
    np.testing.assert_array_equal(fused.u, col.u)


# ------------------------------------------------------------------ #
# Strong-scaling measurement (the MULTICHIP gate metric)
# ------------------------------------------------------------------ #

def test_measure_strong_scaling_record(tmp_path):
    from heat2d_tpu.parallel.scaling import (measure_strong_scaling,
                                             scaling_record)
    payloads = [measure_strong_scaling(4, nx=32, ny=32, steps=8,
                                       halo=h)
                for h in ("collective", "fused")]
    for p in payloads:
        assert p["n_devices"] == 4 and p["mesh"] == [2, 2]
        assert p["per_chip_mcells_per_s_nchip"] > 0
        assert np.isfinite(p["strong_scaling_efficiency"])
    assert payloads[1]["halo"] == "fused"
    assert payloads[1]["halo_tier"] in ("overlap", "ici")
    out = tmp_path / "multichip.json"
    rec = scaling_record(payloads, out_path=str(out))
    assert rec["kind"] == "multichip" and out.exists()
    import json
    loaded = json.loads(out.read_text())
    assert loaded["schema"] == rec["schema"]
    assert len(loaded["scaling"]) == 2


def test_scaling_square_mesh():
    from heat2d_tpu.parallel.scaling import square_mesh
    assert square_mesh(8) == (2, 4)
    assert square_mesh(4) == (2, 2)
    assert square_mesh(7) == (1, 7)
    assert square_mesh(1) == (1, 1)


def test_config_rejects_bad_halo():
    with pytest.raises(ConfigError, match="halo must be"):
        HeatConfig(nxprob=8, nyprob=8, halo="nonsense")
