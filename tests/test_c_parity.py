"""Parity against real C numeric semantics (SURVEY.md Appendix B).

tests/c_oracle.c implements the reference's *behavioral spec* — f32
storage with each cell update promoted through double (the C promotion of
the double literals CX/CY/2.0) — compiled fresh with the system compiler.
The framework's accum_dtype='float64' mode must match it bit-for-bit at
small grids, proving the promotion mirror is exact and not merely close.
"""

import shutil
import subprocess
import sys

import numpy as np
import pytest

from heat2d_tpu.config import HeatConfig
from heat2d_tpu.models.solver import Heat2DSolver

CC = shutil.which("cc") or shutil.which("gcc") or shutil.which("g++")


@pytest.fixture(scope="module")
def c_oracle(tmp_path_factory):
    if CC is None:
        pytest.skip("no C compiler")
    d = tmp_path_factory.mktemp("c_oracle")
    exe = d / "c_oracle"
    src = __file__.replace("test_c_parity.py", "c_oracle.c")
    # -ffp-contract=off: ISO C evaluation (no FMA contraction). gcc's GNU
    # dialect defaults to contract=fast, which fuses the double multiply-
    # adds and perturbs results by ~1 f32 ulp vs XLA's uncontracted f64.
    subprocess.run([CC, "-O2", "-ffp-contract=off", "-o", str(exe), src],
                   check=True)

    def run(nx, ny, steps, cx=0.1, cy=0.1):
        out = d / f"out_{nx}x{ny}x{steps}_{cx}_{cy}.bin"
        subprocess.run([str(exe), str(nx), str(ny), str(steps), str(out),
                        repr(cx), repr(cy)], check=True)
        return np.fromfile(out, dtype="<f4").reshape(nx, ny)

    return run


@pytest.mark.parametrize("nx,ny,steps", [(10, 10, 100), (16, 24, 57)])
def test_f64_accum_matches_c_bitwise(c_oracle, nx, ny, steps):
    ref = c_oracle(nx, ny, steps)
    cfg = HeatConfig(nxprob=nx, nyprob=ny, steps=steps, mode="serial",
                     accum_dtype="float64")
    got = Heat2DSolver(cfg).run(timed=False).u
    np.testing.assert_array_equal(got, ref)


def test_anisotropic_diffusivity_bitwise(c_oracle):
    # cx != cy: catches any axis/coefficient pairing swap (cx must
    # multiply the ix-neighbor sum, as in the reference kernels).
    ref = c_oracle(12, 18, 80, cx=0.15, cy=0.05)
    cfg = HeatConfig(nxprob=12, nyprob=18, steps=80, cx=0.15, cy=0.05,
                     mode="serial", accum_dtype="float64")
    got = Heat2DSolver(cfg).run(timed=False).u
    np.testing.assert_array_equal(got, ref)


def test_f32_close_to_c_at_small_grids(c_oracle):
    # Appendix B: at small grids (values <= ~2k) the pure-f32 path agrees
    # with the double-promoted path to tight tolerance.
    ref = c_oracle(10, 10, 100)
    cfg = HeatConfig(nxprob=10, nyprob=10, steps=100, mode="serial")
    got = Heat2DSolver(cfg).run(timed=False).u
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-2)
