"""Writer byte-format tests, including a live C printf parity check
(the reference's %6.1f writers — mpi_heat2Dn.c:253-268,
grad1612_mpi_heat.c:191-203; orientation split per SURVEY.md A.6)."""

import shutil
import subprocess

import numpy as np
import pytest

from heat2d_tpu.io import (format_grid_baseline, format_grid_rowmajor,
                           read_grid_text, write_grid_baseline,
                           write_grid_rowmajor)
from heat2d_tpu.ops import inidat


def test_rowmajor_format_exact():
    u = np.array([[0.0, 1.5], [-2.25, 1234.5]], dtype=np.float32)
    # "%6.1f " per value (trailing space), newline per row.
    assert format_grid_rowmajor(u) == "   0.0    1.5 \n  -2.2 1234.5 \n"


def test_baseline_format_exact():
    u = np.array([[0.0, 1.5], [-2.25, 1234.5]], dtype=np.float32)
    # Lines iterate iy descending, ix across; space *between* values only.
    assert format_grid_baseline(u) == "   1.5 1234.5\n   0.0   -2.2\n"


def test_roundtrip_rowmajor(tmp_path):
    u = np.asarray(inidat(10, 10))
    p = tmp_path / "x.dat"
    write_grid_rowmajor(u, p)
    back = read_grid_text(p, "rowmajor")
    np.testing.assert_array_equal(back, u)  # inidat values are x.0-exact


def test_roundtrip_baseline(tmp_path):
    u = np.asarray(inidat(8, 6))
    p = tmp_path / "x.dat"
    write_grid_baseline(u, p)
    back = read_grid_text(p, "baseline")
    np.testing.assert_array_equal(back, u)


@pytest.mark.skipif(shutil.which("gcc") is None and shutil.which("g++") is None,
                    reason="no C compiler")
def test_printf_byte_parity(tmp_path, rng):
    """Format random floats with an actual C printf("%6.1f") and compare
    byte-for-byte with the Python formatter."""
    vals = np.concatenate([
        rng.uniform(-1e4, 1e4, 200),
        np.array([0.0, -0.0, 0.05, -0.05, 2.5, -2.5, 99.95, 1e6]),
    ]).astype(np.float32)
    src = tmp_path / "fmt.c"
    src.write_text(
        '#include <stdio.h>\n'
        'int main(void){float v;'
        'while(fread(&v,sizeof v,1,stdin)==1) printf("%6.1f ", v);'
        'return 0;}\n')
    exe = tmp_path / "fmt"
    cc = shutil.which("gcc") or shutil.which("g++")
    subprocess.run([cc, str(src), "-o", str(exe)], check=True)
    out = subprocess.run([str(exe)], input=vals.tobytes(),
                         capture_output=True, check=True).stdout.decode()
    ours = format_grid_rowmajor(vals.reshape(1, -1)).replace("\n", "")
    assert out == ours
