"""bench.py record contract: the one JSON line the driver consumes, and
its calibrated self-honesty field (VERDICT r4 next #7)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench


def test_calibrated_bound_tracks_the_planned_route():
    # 4096^2 streams through C2 at bm=152 (plan_window_band): bound =
    # VPU calibration at 16 KB rows x bm/(bm+2T).
    b = bench.calibrated_bound_mcells(4096, 4096)
    assert abs(b - 248_000.0 * 152 / 168) < 1e-6
    # VMEM-resident shapes have no streaming structure to bound.
    assert bench.calibrated_bound_mcells(640, 512) is None


def test_record_emits_pct_of_calibrated_bound():
    rec = bench.build_record(220_000.0, "two-point", 1.5,
                             nx=4096, ny=4096, steps=24000)
    b = bench.calibrated_bound_mcells(4096, 4096)
    assert rec["pct_of_calibrated_bound"] == round(100 * 220_000.0 / b, 1)
    assert 50 < rec["pct_of_calibrated_bound"] < 120
    assert rec["unit"] == "Mcells/s"
    assert rec["vs_baseline"] == round(220_000.0 / 669.0, 2)
    # Resident shapes: the field is absent, not wrong.
    rec = bench.build_record(200_000.0, "two-point", 1.0,
                             nx=640, ny=512, steps=100)
    assert "pct_of_calibrated_bound" not in rec
    # Fence-dominated single-run fallbacks are not comparable to the
    # ceiling: no field.
    rec = bench.build_record(500.0, "single-run (two-point within "
                             "noise)", 0.2, nx=4096, ny=4096, steps=100)
    assert "pct_of_calibrated_bound" not in rec
