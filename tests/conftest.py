"""Test harness: 8 virtual CPU devices (SURVEY.md §4 — the TPU answer to
"multi-node without a cluster"), x64 enabled so accum_dtype=float64 can
mirror the C reference's double promotion."""

from heat2d_tpu.utils.platform import force_host_devices

force_host_devices(8)

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

import os  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from heat2d_tpu.analysis import locks as _locks  # noqa: E402

if _locks._env_enabled():
    # Opt-in lock audit (the CI lock-audit job; the env parse is
    # locks._env_enabled so this gate and the lock factories can never
    # disagree about what arms the audit): every test runs with an
    # installed auditor — serve/fleet/resil locks become instrumented,
    # @guarded_by checks arm — and FAILS on any lock-order cycle or
    # guarded-state violation it observed.
    @pytest.fixture(autouse=True)
    def _lock_audit():
        _locks.install()
        yield
        rep = _locks.report()
        _locks.uninstall()
        assert rep.clean, rep.render()


@pytest.fixture
def rng():
    return np.random.default_rng(1612)


def ref_inidat(nx: int, ny: int) -> np.ndarray:
    """Independent NumPy oracle for the reference's inidat
    (mpi_heat2Dn.c:242-248): ix*(nx-ix-1)*iy*(ny-iy-1)."""
    ix = np.arange(nx, dtype=np.float64)[:, None]
    iy = np.arange(ny, dtype=np.float64)[None, :]
    return (ix * (nx - ix - 1) * iy * (ny - iy - 1)).astype(np.float32)


def ref_step(u: np.ndarray, cx: float = 0.1, cy: float = 0.1) -> np.ndarray:
    """Independent NumPy oracle for one reference time step: f32 storage,
    C usual-arithmetic-conversions semantics (SURVEY.md Appendix B,
    sharpened by tests/test_c_parity.py): the float neighbor sums uE+uW /
    uN+uS round in f32, every op touching the double literals CX/CY/2.0
    runs in double, truncated to f32 on store. Edges never updated."""
    assert u.dtype == np.float32
    new = u.astype(np.float64)
    c = new[1:-1, 1:-1]
    # sx pairs with cx (axis-0/ix neighbors), sy with cy — reference
    # convention (CX multiplies the ix neighbors).
    sx = (u[2:, 1:-1] + u[:-2, 1:-1]).astype(np.float64)  # f32 sum, then up
    sy = (u[1:-1, 2:] + u[1:-1, :-2]).astype(np.float64)
    new[1:-1, 1:-1] = c + cx * (sx - 2.0 * c) + cy * (sy - 2.0 * c)
    return new.astype(np.float32)


def ref_run(nx: int, ny: int, steps: int,
            cx: float = 0.1, cy: float = 0.1) -> np.ndarray:
    u = ref_inidat(nx, ny)
    for _ in range(steps):
        u = ref_step(u, cx, cy)
    return u


@pytest.fixture
def oracle():
    class Oracle:
        inidat = staticmethod(ref_inidat)
        step = staticmethod(ref_step)
        run = staticmethod(ref_run)
    return Oracle
