"""Implicit time-stepping (ISSUE 14): batched tridiagonal solves,
Crank-Nicolson ADI, multigrid, and the wall-clock-to-solution
contract — plus the free-when-off pins proving the explicit hot path
is byte-identical with the new routes registered."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from heat2d_tpu.config import ConfigError, HeatConfig
from heat2d_tpu.ops import analytic, multigrid as mg, stability
from heat2d_tpu.ops import tridiag as td

from tests._pin import (assert_jaxpr_differs, assert_jaxpr_equal,
                        band_runner_jaxpr, batch_runner_jaxpr,
                        solver_jaxpr)


def dense_tridiag(dl, d, du):
    n = len(d)
    T = np.diag(np.asarray(d, np.float64))
    T += np.diag(np.asarray(dl, np.float64)[1:], -1)
    T += np.diag(np.asarray(du, np.float64)[:-1], 1)
    return T


def random_bands(rng, n):
    dl = np.zeros(n)
    du = np.zeros(n)
    d = np.ones(n)
    dl[1:-1] = rng.normal(size=n - 2) * 0.3
    du[1:-1] = rng.normal(size=n - 2) * 0.3
    d[1:-1] = 3.0 + rng.normal(size=n - 2) * 0.2
    return dl, d, du


# --------------------------------------------------------------------- #
# thomas_solve: the jnp golden model + implicit differentiation
# --------------------------------------------------------------------- #

def test_thomas_matches_dense_solve(rng):
    n = 23
    dl, d, du = random_bands(rng, n)
    rhs = rng.normal(size=(n, 7))
    want = np.linalg.solve(dense_tridiag(dl, d, du), rhs)
    got = td.thomas_solve(jnp.asarray(dl), jnp.asarray(d),
                          jnp.asarray(du), jnp.asarray(rhs))
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-12)


def test_thomas_vjp_is_transpose_solve(rng):
    """The custom_vjp's band/rhs cotangents against central finite
    differences — the implicit-differentiation contract the adjoint
    rides (the backward pass solves T^T, not an unrolled scan)."""
    n = 11
    dl, d, du = random_bands(rng, n)
    rhs = rng.normal(size=(n, 3))

    def loss(dl_, d_, du_, r_):
        return jnp.sum(jnp.sin(td.thomas_solve(dl_, d_, du_, r_)))

    args = (jnp.asarray(dl), jnp.asarray(d), jnp.asarray(du),
            jnp.asarray(rhs))
    grads = jax.grad(loss, argnums=(0, 1, 2, 3))(*args)
    eps = 1e-6
    for argi in range(4):
        flat = np.asarray(args[argi], np.float64).copy()
        idx = (2,) if flat.ndim == 1 else (2, 1)
        for sign in (1,):
            pert = [np.asarray(a, np.float64).copy() for a in args]
            pert[argi][idx] += eps
            lp = float(loss(*[jnp.asarray(a) for a in pert]))
            pert[argi][idx] -= 2 * eps
            lm = float(loss(*[jnp.asarray(a) for a in pert]))
            fd = (lp - lm) / (2 * eps)
        np.testing.assert_allclose(float(np.asarray(grads[argi])[idx]),
                                   fd, rtol=1e-5, atol=1e-8)


# --------------------------------------------------------------------- #
# the ADI step: exactness, stability, kernels
# --------------------------------------------------------------------- #

def test_adi_step_exact_mode_factor():
    """The separable mode is an exact eigenvector of the PR-ADI step:
    one step must scale it by the analytic rational factor to f64
    precision — the strongest single-step correctness check there is."""
    nx, ny = 33, 41
    v = jnp.asarray(analytic.separable_mode(nx, ny, np.float64))
    for cx, cy in ((0.1, 0.2), (5.0, 7.0), (300.0, 100.0)):
        got = np.asarray(td.adi_step(v, cx, cy))
        fac = analytic.adi_mode_factor(nx, ny, cx, cy)
        np.testing.assert_allclose(
            got[1:-1, 1:-1] / np.asarray(v)[1:-1, 1:-1], fac,
            rtol=1e-12)
        assert abs(fac) < 1.0      # unconditional stability


def test_adi_step_holds_edges_and_constants(rng):
    u = rng.normal(size=(12, 15))
    got = np.asarray(td.adi_step(jnp.asarray(u), 9.0, 4.0))
    np.testing.assert_array_equal(got[0, :], u[0, :])
    np.testing.assert_array_equal(got[-1, :], u[-1, :])
    np.testing.assert_array_equal(got[:, 0], u[:, 0])
    np.testing.assert_array_equal(got[:, -1], u[:, -1])
    c = np.full((9, 9), 2.5)
    out = np.asarray(td.adi_step(jnp.asarray(c), 50.0, 50.0))
    np.testing.assert_allclose(out, c, rtol=1e-12)


@pytest.mark.parametrize("variant", ["xpose", "strided"])
def test_tridiag_kernel_matches_scan(rng, variant):
    """Kernel TD (interpret mode on CPU) against the jnp scan route,
    both transpose strategies, mixed panel widths."""
    ub = rng.normal(size=(3, 16, 24)).astype(np.float32)
    cxs = np.asarray([0.5, 2.0, 10.0], np.float32)
    cys = np.asarray([1.0, 3.0, 0.3], np.float32)
    want = np.asarray(td.batched_adi_scan(jnp.asarray(ub), cxs, cys,
                                          steps=3))
    for panel in (8, 24, None):
        got = np.asarray(td.batched_adi_kernel(
            jnp.asarray(ub), cxs, cys, steps=3, panel=panel,
            variant=variant))
        np.testing.assert_allclose(got, want, atol=5e-6)


def test_plan_adi_panel_tiles_lanes():
    assert td.plan_adi_panel(4096) == 512
    assert 4096 % td.plan_adi_panel(4096) == 0
    assert td.plan_adi_panel(100) <= 100
    assert 100 % td.plan_adi_panel(100) == 0
    assert td.plan_adi_panel(64) == 64


# --------------------------------------------------------------------- #
# method parity: both schemes converge to the analytic solution at
# their expected orders (satellite: O(dt) vs O(dt^2), f32 and f64)
# --------------------------------------------------------------------- #

def _leg_error(method, nx, ny, steps, c, dtype):
    u0 = jnp.asarray(analytic.separable_mode(nx, ny, dtype))
    if method == "explicit":
        from heat2d_tpu.models import engine
        from heat2d_tpu.ops.stencil import stencil_step
        u, _ = engine.run_fixed(
            lambda v: stencil_step(v, c, c, accum_dtype=None), u0,
            steps)
    elif method == "adi":
        u = td.adi_multi_step(u0, steps, c, c)
    else:
        u = mg.mg_multi_step(u0, steps, c, c)
    ref = analytic.mode_solution(nx, ny, c * steps, c * steps,
                                 np.float64)
    return analytic.l2_error(u, ref)


def test_convergence_orders_f64():
    """Halving dt at fixed t_final: the explicit error halves (O(dt)),
    the ADI error quarters (O(dt^2))."""
    nx = ny = 65
    that = 32.0           # t_hat = c * steps on both axes
    e1 = _leg_error("explicit", nx, ny, 160, that / 160, np.float64)
    e2 = _leg_error("explicit", nx, ny, 320, that / 320, np.float64)
    assert 1.6 < e1 / e2 < 2.4, (e1, e2)
    a1 = _leg_error("adi", nx, ny, 8, that / 8, np.float64)
    a2 = _leg_error("adi", nx, ny, 16, that / 16, np.float64)
    assert 3.2 < a1 / a2 < 4.8, (a1, a2)
    # ...and the implicit leg beats the explicit one outright at a
    # fraction of the steps.
    assert a2 < e2


def test_mg_matches_cn_order_f64():
    nx = ny = 65
    that = 32.0
    m1 = _leg_error("mg", nx, ny, 8, that / 8, np.float64)
    m2 = _leg_error("mg", nx, ny, 16, that / 16, np.float64)
    assert 3.0 < m1 / m2 < 5.2, (m1, m2)


def test_methods_converge_f32():
    """f32 twin of the parity satellite: every scheme converges to the
    analytic answer, and the implicit legs at 20x fewer steps stay at
    matched accuracy (no worse than the explicit leg's O(dt)
    truncation + its roundoff)."""
    errs = {m: _leg_error(m, 65, 65, s, 32.0 / s, np.float32)
            for m, s in (("explicit", 160), ("adi", 8), ("mg", 8))}
    assert all(e < 2e-4 for e in errs.values()), errs
    floor = 400 * np.finfo(np.float32).eps
    for m in ("adi", "mg"):
        assert errs[m] <= max(1.5 * errs["explicit"], floor), errs


# --------------------------------------------------------------------- #
# multigrid internals
# --------------------------------------------------------------------- #

def test_vcycle_contracts_residual():
    nx = ny = 65
    cx = cy = 8.0
    u_true = jnp.asarray(analytic.separable_mode(nx, ny, np.float64))
    rhs = mg.cn_apply(u_true, cx, cy)
    u = jnp.zeros_like(u_true)
    r_prev = float(jnp.linalg.norm(mg.residual(u, rhs, cx, cy)))
    for _ in range(3):
        u = mg.v_cycle(u, rhs, cx, cy)
        r = float(jnp.linalg.norm(mg.residual(u, rhs, cx, cy)))
        assert r < 0.1 * r_prev, (r, r_prev)   # >= 10x per cycle
        r_prev = r


def test_mg_step_matches_unsplit_cn_factor():
    nx = ny = 33
    cx, cy = 6.0, 9.0
    v = jnp.asarray(analytic.separable_mode(nx, ny, np.float64))
    lx, ly = analytic.mode_eigenvalues(nx, ny)
    a = cx * lx / 2 + cy * ly / 2
    want = (1 - a) / (1 + a)
    got = np.asarray(mg.mg_step(v, cx, cy))
    rat = got[1:-1, 1:-1] / np.asarray(v)[1:-1, 1:-1]
    # two V-cycles land ~1e-4 relative of the exact CN factor — far
    # below the CN truncation the step carries anyway
    np.testing.assert_allclose(rat, want, rtol=5e-4)


def test_mg_even_sizes_still_converge():
    """A non-coarsenable (even) grid degrades to smoother-only
    relaxation — slower, still correct for moderate dt."""
    err = _leg_error("mg", 32, 48, 16, 0.5, np.float64)
    assert err < 1e-4, err


# --------------------------------------------------------------------- #
# the routes: ensemble / solver / serve / mesh
# --------------------------------------------------------------------- #

def test_ensemble_adi_matches_per_member(rng):
    from heat2d_tpu.models import ensemble

    cxs = [4.0, 9.0, 1.5]
    cys = [2.0, 3.0, 8.0]
    out = ensemble.run_ensemble(17, 21, 5, cxs, cys, method="adi")
    u0 = jnp.asarray(analytic.separable_mode(17, 21))
    u0 = jnp.broadcast_to(
        jnp.asarray(np.asarray(ensemble.inidat(17, 21))), (3, 17, 21))
    for i, (cx, cy) in enumerate(zip(cxs, cys)):
        want = td.adi_multi_step(u0[i], 5, jnp.float32(cx),
                                 jnp.float32(cy))
        np.testing.assert_array_equal(np.asarray(out[i]),
                                      np.asarray(want))


def test_ensemble_conv_adi_per_member_exit():
    """The generic batched convergence loop drives the ADI runner:
    a fast-decaying member freezes while a slow one runs on."""
    from heat2d_tpu.models import ensemble

    u, k = ensemble.run_ensemble_convergence(
        17, 17, 50, 5, 1e-4, [8.0, 0.5], [8.0, 0.5], method="adi")
    ks = [int(v) for v in np.asarray(k)]
    assert ks[0] < ks[1], ks


def test_solver_adi_and_mg_routes():
    base = dict(nxprob=33, nyprob=33, steps=4, cx=16.0, cy=16.0)
    from heat2d_tpu.models.solver import Heat2DSolver

    for method in ("adi", "mg"):
        r = Heat2DSolver(HeatConfig(method=method, **base)).run(
            timed=False)
        assert r.steps_done == 4
        assert np.isfinite(r.u).all()
    # convergence route: early exit on a violent decay
    cfg = HeatConfig(nxprob=33, nyprob=33, steps=400, cx=40.0, cy=40.0,
                     method="adi", convergence=True, interval=10,
                     sensitivity=1e30)
    r = Heat2DSolver(cfg).run(timed=False)
    assert r.steps_done == 10


def test_serve_engine_adi_bitwise_across_capacities():
    from heat2d_tpu.serve.engine import EnsembleEngine
    from heat2d_tpu.serve.schema import SolveRequest

    req = SolveRequest(nx=16, ny=24, steps=3, cx=8.0, cy=6.0,
                       method="adi")
    twin = SolveRequest(nx=16, ny=24, steps=3, cx=3.0, cy=2.0,
                        method="adi")
    a = EnsembleEngine(max_batch=8).solve_batch([req])[0]
    b = EnsembleEngine(max_batch=8).solve_batch([req, twin])[0]
    assert np.asarray(a[0]).tobytes() == np.asarray(b[0]).tobytes()


def test_serve_schema_accepts_implicit_methods():
    from heat2d_tpu.serve.schema import Rejected, SolveRequest

    for m in ("adi", "mg"):
        SolveRequest(nx=8, ny=8, steps=2, method=m).validate()
    with pytest.raises(Rejected):
        SolveRequest(nx=8, ny=8, steps=2, method="nope").validate()


def test_mesh_runner_adi_bitwise(rng):
    """The PR 13 mesh machinery carries the new route unchanged:
    mesh-sharded answers bitwise == single-chip at a padded batch."""
    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device sim mesh")
    from heat2d_tpu.mesh.runner import mesh_batch_runner
    from heat2d_tpu.models import ensemble

    run = mesh_batch_runner(16, 24, 3, "adi")
    b = run.n_devices
    u0 = jnp.asarray(rng.normal(size=(b, 16, 24)).astype(np.float32))
    cxs = jnp.asarray([2.0 + i for i in range(b)], jnp.float32)
    got = np.asarray(run(u0, cxs, cxs))
    want = np.asarray(ensemble.batch_runner(16, 24, 3, "adi")(
        u0, cxs, cxs))
    assert got.tobytes() == want.tobytes()


# --------------------------------------------------------------------- #
# jaxpr pins: implicit support costs nothing on the explicit hot path
# --------------------------------------------------------------------- #

def test_explicit_programs_untouched_by_implicit_routes():
    before_solver = solver_jaxpr()
    before_band = band_runner_jaxpr()
    before_batch = batch_runner_jaxpr(method="jnp")
    # Exercise the new routes end to end (trace + run), then re-trace.
    from heat2d_tpu.models import ensemble

    ensemble.run_ensemble(16, 16, 2, [8.0], [6.0], method="adi")
    ensemble.run_ensemble(17, 17, 1, [8.0], [6.0], method="mg")
    assert_jaxpr_equal(before_solver, solver_jaxpr(),
                       "solver runner with implicit routes live")
    assert_jaxpr_equal(before_band, band_runner_jaxpr(),
                       "band runner with implicit routes live")
    assert_jaxpr_equal(before_batch, batch_runner_jaxpr(method="jnp"),
                       "jnp batch runner with implicit routes live")
    # Non-vacuity: the adi program is genuinely a different program.
    assert_jaxpr_differs(before_batch, batch_runner_jaxpr(method="adi"),
                         "adi vs jnp batch runner")


def test_diffing_adi_leaves_band_runner_pinned():
    before = band_runner_jaxpr()
    from heat2d_tpu.diff.adjoint import make_diff_solve

    solve = make_diff_solve(9, 9, 3, method="adi")
    jax.grad(lambda u, a, b: jnp.sum(solve(u, a, b)))(
        jnp.ones((9, 9)), 4.0, 2.0)
    assert_jaxpr_equal(before, band_runner_jaxpr(),
                       "band runner after adi adjoint build")


# --------------------------------------------------------------------- #
# adjoint: FD parity + storage-route bitwise equality
# --------------------------------------------------------------------- #

def test_adi_adjoint_fd_parity(rng):
    from heat2d_tpu.diff.adjoint import make_diff_solve

    solve = make_diff_solve(9, 11, 4, method="adi")
    u0 = jnp.asarray(rng.normal(size=(9, 11)))

    def loss(u, a, b):
        return jnp.sum(solve(u, a, b) ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(u0, 5.0, 3.0)
    eps = 1e-6
    fd_cx = (loss(u0, 5.0 + eps, 3.0) - loss(u0, 5.0 - eps, 3.0)) \
        / (2 * eps)
    np.testing.assert_allclose(float(g[1]), float(fd_cx), rtol=1e-5)
    fd_u = (loss(u0.at[4, 5].add(eps), 5.0, 3.0)
            - loss(u0.at[4, 5].add(-eps), 5.0, 3.0)) / (2 * eps)
    np.testing.assert_allclose(float(g[0][4, 5]), float(fd_u),
                               rtol=1e-4, atol=1e-9)


def test_adi_adjoint_checkpoint_equals_full(rng):
    from heat2d_tpu.diff.adjoint import make_diff_solve

    u0 = jnp.asarray(rng.normal(size=(9, 9)))

    def grads(adjoint, segment=None):
        solve = make_diff_solve(9, 9, 6, method="adi",
                                adjoint=adjoint, segment=segment)
        return jax.grad(lambda u, a, b: jnp.sum(solve(u, a, b) ** 2),
                        argnums=(0, 1, 2))(u0, 7.0, 2.0)

    full = grads("full")
    for seg in (None, 2, 3):
        ck = grads("checkpoint", seg)
        for a, b in zip(full, ck):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_adjoint_method_validation():
    from heat2d_tpu.diff.adjoint import make_diff_solve

    with pytest.raises(ValueError, match="coeff='const'"):
        make_diff_solve(9, 9, 3, coeff="var", method="adi")
    # full-storage + adi composes (per-step primal on both routes)
    make_diff_solve(9, 9, 3, adjoint="full", method="adi")


# --------------------------------------------------------------------- #
# stability (satellite: the box factored into ops/stability.py)
# --------------------------------------------------------------------- #

def test_stability_limit_values():
    assert stability.stability_limit() == pytest.approx(0.25)
    assert stability.stability_limit(2.0, 2.0) == pytest.approx(1.0)
    with pytest.raises(ConfigError):
        stability.stability_limit(0.0, 1.0)


def test_explicit_config_validates_against_box():
    with pytest.raises(ConfigError, match=r"cx \+ cy <= 0.5"):
        HeatConfig(cx=0.4, cy=0.2)
    # Implicit methods skip the box by design.
    HeatConfig(cx=40.0, cy=20.0, method="adi")
    HeatConfig(cx=40.0, cy=20.0, method="mg")
    with pytest.raises(ConfigError, match="single-device"):
        HeatConfig(cx=4.0, cy=2.0, method="adi", mode="dist2d",
                   nxprob=8, nyprob=8, gridx=2, gridy=2)


def test_inverse_box_reexport_and_projection():
    from heat2d_tpu.diff import inverse

    assert inverse.KAPPA_MAX == stability.KAPPA_MAX
    assert inverse.KAPPA_MIN == stability.KAPPA_MIN
    out = np.asarray(stability.project_stable(
        jnp.asarray([-1.0, 0.1, 9.0])))
    assert out[0] == stability.KAPPA_MIN
    assert out[1] == pytest.approx(0.1)
    assert out[2] == stability.KAPPA_MAX
    assert stability.is_implicit("adi") and stability.is_implicit("mg")
    assert not stability.is_implicit("explicit")


# --------------------------------------------------------------------- #
# tune space: the adi routes under their own key namespace
# --------------------------------------------------------------------- #

def test_candidate_space_has_adi_routes():
    from heat2d_tpu.tune.space import Problem, candidate_space

    cands, pruned = candidate_space(Problem(4096, 4096),
                                    assume_tpu=True)
    adi = [c for c in cands if c.route.startswith("adi")]
    assert {c.route for c in adi} == {"adi", "adi_s"}
    assert all(4096 % c.bm == 0 for c in adi)
    # Non-divisor panels are pruned with a reason, never measured.
    cands2, pruned2 = candidate_space(Problem(4096, 4000),
                                      assume_tpu=True)
    dropped = [r for c, r in pruned2 if c.route.startswith("adi")]
    assert any("tile" in r for r in dropped)


def test_adi_key_namespace_is_invisible_to_band_lookup(tmp_path):
    from heat2d_tpu.tune.db import TuningDB
    from heat2d_tpu.tune.space import Problem

    p = Problem(64, 128)
    assert p.adi_key().startswith("adi:")
    db = TuningDB(str(tmp_path / "db.json"))
    db.record_point("cpu", p.adi_key(),
                    {"route": "adi", "bm": 128, "tsteps": 0,
                     "status": "ok", "step_time_s": 1e-3,
                     "mcells_per_s": 10.0})
    from heat2d_tpu.tune.db import current_salt
    db.set_best("cpu", p.adi_key(),
                {"route": "adi", "bm": 128, "tsteps": 0}, 10.0,
                {"salt": current_salt()})
    db.save()
    # The band lookup ladder must not surface the adi entry even as a
    # nearest-shape answer.
    assert db.lookup("cpu", 64, 128, "float32") is None


def test_simulated_backend_measures_adi_routes():
    from heat2d_tpu.tune.measure import (SimulatedBackend,
                                         measure_candidate)
    from heat2d_tpu.tune.space import Candidate, Problem

    b = SimulatedBackend()
    p = Problem(4096, 4096)
    ok = measure_candidate(p, Candidate("adi", 128, 0), backend=b)
    assert ok.status == "ok"
    assert ok.step_time_s == measure_candidate(
        p, Candidate("adi", 128, 0), backend=b).step_time_s
    # strided pays the lane-serialization tax in the model
    s = measure_candidate(p, Candidate("adi_s", 128, 0), backend=b)
    assert s.step_time_s > ok.step_time_s
    bad = measure_candidate(p, Candidate("adi", 500, 0), backend=b)
    assert bad.status == "compile_error"
    # a panel past the working-set envelope is the oom class
    oom = measure_candidate(p, Candidate("adi", 1024, 0), backend=b)
    assert oom.status == "oom"


def test_search_problem_stamps_adi_frontier(tmp_path):
    from heat2d_tpu.tune.cli import search_problem
    from heat2d_tpu.tune.db import TuningDB
    from heat2d_tpu.tune.measure import SimulatedBackend
    from heat2d_tpu.tune.space import Problem

    db = TuningDB(str(tmp_path / "db.json"))
    p = Problem(640, 512)
    backend = SimulatedBackend()
    s = search_problem(db, p, backend=backend)
    assert s["measured"] > 0
    e = db.entry(backend.device_kind, p.adi_key())
    assert e is not None and e.get("best"), e
    assert e["best"]["route"].startswith("adi")
    # resume: a fresh search must re-measure nothing
    db2 = TuningDB(str(tmp_path / "db.json"))
    s2 = search_problem(db2, p, backend=backend)
    assert s2["measured"] == 0 and s2["cached"] > 0


# --------------------------------------------------------------------- #
# wall-clock-to-solution harness (satellite: the bench block)
# --------------------------------------------------------------------- #

def test_time_to_solution_contract():
    from heat2d_tpu.models import solution

    out = solution.time_to_solution(
        129, 129, steps_explicit=512, step_ratio=128,
        methods=("explicit", "adi"))
    s = out["summary"]
    assert s["adi_steps_ratio"] >= 100.0
    assert s["adi_modeled_speedup"] >= 10.0
    assert s["adi_matched_accuracy"] is True
    rows = {r["method"]: r for r in out["rows"]}
    assert rows["adi"]["steps"] * s["adi_steps_ratio"] \
        == rows["explicit"]["steps"]
    # Both legs hit the same physical time: c * steps matches.
    assert rows["adi"]["cx"] * rows["adi"]["steps"] == pytest.approx(
        rows["explicit"]["cx"] * rows["explicit"]["steps"])


def test_time_to_solution_explicit_leg_validates_stability():
    from heat2d_tpu.models import solution

    with pytest.raises(ConfigError, match="stability limit"):
        solution.time_to_solution(33, 33, steps_explicit=8,
                                  step_ratio=4, cx=0.4, cy=0.2)


def test_time_to_solution_emits_metrics():
    from heat2d_tpu.models import solution
    from heat2d_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    solution.time_to_solution(33, 33, steps_explicit=64, step_ratio=16,
                              methods=("explicit", "adi", "mg"),
                              registry=reg)
    snap = reg.snapshot()
    assert "adi_time_to_solution_s" in snap["gauges"]
    assert "adi_wall_speedup" in snap["gauges"]
    assert "mg_time_to_solution_s" in snap["gauges"]
