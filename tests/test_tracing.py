"""Distributed tracing + crash flight recorder + SLO (heat2d_tpu/obs/
tracing.py, flight.py, slo.py, trace_cli.py — ISSUE 9).

Tiers: tracer/flight/SLO units; the bounded-histogram and Prometheus
satellites; serve-path integration against an in-process server with a
sink tracer; the jaxpr pins (tracing enabled/disabled leaves the
forward solver, band runner, and serve batch runner byte-identical);
wire back-compat; and ONE end-to-end fleet test — a chaos kill
mid-flight whose post-mortem must be present, digest-valid, and
contain the in-flight request's spans, with the merged timeline
connected across processes."""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from heat2d_tpu.obs import flight, slo, tracing
from heat2d_tpu.obs.metrics import MetricsRegistry
from heat2d_tpu.obs.tracing import TraceContext, Tracer
from heat2d_tpu.serve.schema import SolveRequest, attach_trace

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


@pytest.fixture
def sink():
    """An installed in-memory tracer; uninstalls after the test."""
    recs = []
    tracing.install(Tracer(sink=recs.append, service="test"))
    yield recs
    tracing.uninstall()


@pytest.fixture(autouse=True)
def _clean_tracing():
    yield
    tracing.uninstall()
    flight.uninstall()


def spans(recs):
    return [r for r in recs if r["event"] == "span"]


# --------------------------------------------------------------------- #
# tracer core
# --------------------------------------------------------------------- #

def test_span_lifecycle_and_parenting(sink):
    t = tracing.tracer()
    root = t.begin("root", kind="request", content_hash="h")
    child = t.begin("child", kind="queue", parent=root.ctx)
    child.end(n=3)
    root.end(outcome="completed")
    ss = spans(sink)
    assert [s["name"] for s in ss] == ["child", "root"]
    c, r = ss
    assert c["trace_id"] == r["trace_id"]       # one trace
    assert c["parent_id"] == r["span_id"]       # causality
    assert r["parent_id"] is None
    assert c["attrs"]["n"] == 3 and r["attrs"]["outcome"] == "completed"
    assert r["t1"] >= r["t0"] and c["t0"] >= r["t0"]
    # begin() additionally leaves a span_start marker (crash safety)
    starts = [x for x in sink if x["event"] == "span_start"]
    assert {s["span_id"] for s in starts} == {c["span_id"],
                                              r["span_id"]}


def test_end_is_idempotent(sink):
    sp = tracing.begin("once")
    sp.end()
    sp.end()
    assert len(spans(sink)) == 1


def test_retroactive_emit_and_event(sink):
    t = tracing.tracer()
    t0 = time.monotonic() - 1.0
    ctx = t.emit_span("serve.queue", t0, time.monotonic(), kind="queue")
    t.event("fleet.recv", parent=ctx, rid=7)
    q, e = spans(sink)
    assert 0.9 < q["t1"] - q["t0"] < 1.5
    assert e["kind"] == "event" and e["parent_id"] == ctx.span_id
    assert e["t1"] == e["t0"]


def test_disabled_hooks_are_noops():
    tracing.uninstall()
    os.environ.pop("HEAT2D_TRACE_DIR", None)
    assert not tracing.enabled()
    sp = tracing.begin("nope")
    assert sp is tracing.NULL_SPAN
    sp.set(x=1).end()
    assert tracing.emit("nope", 0.0, 1.0) is None
    assert tracing.event("nope") is None


def test_env_activation_and_file_output(tmp_path, monkeypatch):
    tracing.uninstall()
    monkeypatch.setenv("HEAT2D_TRACE_DIR", str(tmp_path))
    t = tracing.activate_from_env(service="envtest")
    assert tracing.enabled() and t is not None
    tracing.begin("a").end()
    assert os.path.exists(t.path)
    recs = [json.loads(l) for l in open(t.path)]
    assert [r["event"] for r in recs] == ["span_start", "span"]
    assert recs[1]["service"] == "envtest"


def test_wire_context_roundtrip_and_malformed():
    ctx = TraceContext(trace_id="ab" * 16, span_id="cd" * 8)
    assert TraceContext.from_wire(ctx.to_wire()) == ctx
    for bad in (None, {}, {"trace_id": "x"}, {"span_id": "y"},
                {"trace_id": 1, "span_id": 2}, "junk", 42):
        assert TraceContext.from_wire(bad) is None


def test_trace_attachment_never_changes_request_identity():
    a = SolveRequest(nx=16, ny=16, steps=4, cx=0.3, method="jnp")
    b = SolveRequest(nx=16, ny=16, steps=4, cx=0.3, method="jnp")
    ctx = TraceContext(trace_id="t" * 32, span_id="s" * 16)
    attach_trace(b, ctx)
    assert b.trace is ctx
    assert a == b and hash(a) == hash(b)
    assert a.content_hash() == b.content_hash()
    assert a.signature() == b.signature()
    assert "trace" not in a.spec() and "trace" not in b.spec()
    # and the wire spec never carries it: from_dict REJECTS the key
    from heat2d_tpu.serve.schema import Rejected
    with pytest.raises(Rejected):
        SolveRequest.from_dict(dict(b.spec(), trace={"trace_id": "x"}))


# --------------------------------------------------------------------- #
# flight recorder
# --------------------------------------------------------------------- #

def test_flight_ring_is_bounded_and_flush_digest_valid(tmp_path):
    reg = MetricsRegistry()
    reg.counter("x_total", 3)
    rec = flight.FlightRecorder(str(tmp_path / "flight-t-1.jsonl"),
                                ring=16, service="t", registry=reg)
    for i in range(100):
        rec.note("tick", i=i)
    assert len(rec) == 16                       # bounded under soak
    path = rec.flush("test")
    assert path and os.path.exists(path + ".digest.json")
    entries = flight.load_postmortem(path)
    assert entries[0]["event"] == "flight_header"
    assert entries[0]["reason"] == "test"
    ticks = [e for e in entries if e["event"] == "tick"]
    assert [e["i"] for e in ticks] == list(range(84, 100))  # newest 16
    snap = [e for e in entries if e["event"] == "metrics_snapshot"]
    assert snap and snap[0]["counters"]["x_total"] == 3
    # first flush wins
    assert rec.flush("again") is None


def test_flight_postmortem_corruption_detected(tmp_path):
    rec = flight.FlightRecorder(str(tmp_path / "flight-t-2.jsonl"),
                                service="t")
    rec.note("tick")
    path = rec.flush("test")
    with open(path, "a") as f:
        f.write('{"event": "forged"}\n')
    with pytest.raises(flight.PostmortemCorruptError):
        flight.load_postmortem(path)
    os.remove(path + ".digest.json")
    with pytest.raises(flight.PostmortemCorruptError):
        flight.load_postmortem(path)
    assert flight.load_postmortem(path, verify=False)  # escape hatch


def test_tracer_tees_spans_into_flight_ring(tmp_path, sink):
    rec = flight.FlightRecorder(str(tmp_path / "flight-t-3.jsonl"),
                                service="t")
    flight.install(rec, crash_hooks=False)
    tracing.begin("traced.op", kind="launch").end()
    path = rec.flush("test")
    names = [e.get("name") for e in flight.load_postmortem(path)
             if e.get("event") in ("span", "span_start")]
    assert names == ["traced.op", "traced.op"]  # start + end


def test_crash_flush_noop_without_recorder():
    flight.uninstall()
    assert flight.crash_flush("nothing") is None


# --------------------------------------------------------------------- #
# satellites: bounded histograms + Prometheus exposition
# --------------------------------------------------------------------- #

def test_histogram_memory_bounded_under_soak():
    """The regression for the append-forever leak: 100k observations
    hold at most hist_cap samples while count/sum/min/max/mean stay
    exact."""
    r = MetricsRegistry(hist_cap=512)
    n = 100_000
    for i in range(n):
        r.observe("soak_s", float(i % 1000))
    res = list(r._histograms.values())[0]
    assert len(res.samples) == 512              # bounded
    s = r.snapshot()["histograms"]["soak_s"]
    assert s["count"] == n                      # exact
    assert s["sum"] == float(sum(i % 1000 for i in range(n)))
    assert s["min"] == 0.0 and s["max"] == 999.0
    assert 0.0 <= s["p50"] <= 999.0             # sane estimate


def test_histogram_quantiles_exact_below_cap():
    r = MetricsRegistry(hist_cap=4096)
    vals = [float(v) for v in range(1, 101)]
    for v in vals:
        r.observe("lat_s", v)
    s = r.snapshot()["histograms"]["lat_s"]
    assert s["p50"] == 50.0 and s["p90"] == 90.0 and s["p99"] == 99.0
    assert s["count"] == 100 and s["mean"] == 50.5


def test_prometheus_exposition_quantiles_and_backcompat():
    r = MetricsRegistry()
    for v in (0.1, 0.2, 0.3, 0.4):
        r.observe("lat_s", v, route="a")
    text = r.prometheus_text()
    # the pre-existing lines are unchanged (backward compatibility)
    assert "# TYPE lat_s summary" in text
    assert 'lat_s_sum{route="a"} 1.0' in text
    assert 'lat_s_count{route="a"} 4' in text
    # new: quantile sample lines per the summary convention
    assert 'lat_s{route="a",quantile="0.5"} 0.2' in text
    assert 'lat_s{route="a",quantile="0.99"} 0.4' in text


def test_find_histograms_and_counters_structured_labels():
    r = MetricsRegistry()
    sig = "(16, 16, 4, 'float32', 'jnp', False, 0, 0.0)"  # commas!
    r.observe("serve_signature_latency_s", 0.5, signature=sig)
    r.counter("serve_signature_requests_total", 2, signature=sig,
              outcome="completed")
    h = r.find_histograms("serve_signature_latency_s")
    assert [dict(k)["signature"] for k in h] == [sig]
    c = r.find_counters("serve_signature_requests_total")
    (labels, v), = c.items()
    assert dict(labels) == {"signature": sig, "outcome": "completed"}
    assert v == 2


# --------------------------------------------------------------------- #
# SLO objectives
# --------------------------------------------------------------------- #

def _slo_registry(p99=0.5, failures=0, completed=100):
    r = MetricsRegistry()
    sig = "sigA"
    for _ in range(completed):
        r.counter("serve_signature_requests_total", signature=sig,
                  outcome="completed")
        r.observe("serve_signature_latency_s", p99, signature=sig)
    for _ in range(failures):
        r.counter("serve_signature_requests_total", signature=sig,
                  outcome="rejected_watchdog_timeout")
    return r


def test_slo_policy_validation():
    with pytest.raises(ValueError):
        slo.SLOPolicy(latency_p99_s=0)
    with pytest.raises(ValueError):
        slo.SLOPolicy(latency_p99_s=1, error_budget=0)
    with pytest.raises(ValueError):
        slo.SLOPolicy(latency_p99_s=1, error_budget=1.5)


def test_slo_pass_and_gauges():
    r = _slo_registry(p99=0.1)
    rows = slo.evaluate(r, default=slo.SLOPolicy(latency_p99_s=1.0,
                                                 error_budget=0.01))
    (row,) = rows
    assert row["ok"] and row["latency_ok"] and row["budget_ok"]
    assert row["burn_rate"] == 0.0
    g = r.snapshot()["gauges"]
    assert g["slo_ok{signature=sigA}"] == 1.0
    assert g["slo_latency_target_s{signature=sigA}"] == 1.0


def test_slo_burn_rate_and_latency_violation():
    r = _slo_registry(p99=2.0, failures=5, completed=95)
    rows = slo.evaluate(r, default=slo.SLOPolicy(latency_p99_s=1.0,
                                                 error_budget=0.01))
    (row,) = rows
    assert not row["latency_ok"]            # p99 2.0 > target 1.0
    assert row["error_rate"] == 0.05
    assert row["burn_rate"] == pytest.approx(5.0)   # 5% vs 1% budget
    assert not row["budget_ok"] and not row["ok"]


def test_slo_invalid_requests_spend_no_budget():
    r = _slo_registry(completed=10)
    r.counter("serve_signature_requests_total", 5, signature="sigA",
              outcome="rejected_invalid")
    (row,) = slo.evaluate(r, default=slo.SLOPolicy(latency_p99_s=1.0))
    assert row["failures"] == 0 and row["burn_rate"] == 0.0


def test_watchdog_fired_batch_spends_budget_exactly_once():
    """Review regression: a launch that outlives the watchdog deadline
    charges its members to the per-signature FAILURE counters once —
    the late resolve must not also count them completed or feed the
    failed requests' latencies into the SLO sources (that would halve
    the burn rate and pollute the p99)."""
    from heat2d_tpu.resil import chaos
    from heat2d_tpu.serve.schema import Rejected
    from heat2d_tpu.serve.server import SolveServer

    reg = MetricsRegistry()
    chaos.install(chaos.ChaosConfig(launch_latency_s=0.6))
    try:
        with SolveServer(registry=reg, launch_deadline=0.1) as s:
            fut = s.submit(SolveRequest(nx=16, ny=16, steps=3, cx=0.23,
                                        method="jnp"))
            with pytest.raises(Rejected) as ei:
                fut.result(timeout=60)
            assert ei.value.code == "watchdog_timeout"
            # wait for the LATE launch to resolve (completed_late)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                c = reg.snapshot()["counters"]
                if c.get("serve_requests_total{outcome=completed_late}"):
                    break
                time.sleep(0.05)
            else:
                pytest.fail("late launch never resolved")
    finally:
        chaos.uninstall()
    outcomes = {dict(k)["outcome"]: v for k, v in reg.find_counters(
        "serve_signature_requests_total").items()}
    assert outcomes == {"rejected_watchdog_timeout": 1.0}
    assert not reg.find_histograms("serve_signature_latency_s")
    (row,) = slo.evaluate(reg, default=slo.SLOPolicy(
        latency_p99_s=1.0, error_budget=0.5))
    assert row["error_rate"] == 1.0     # one request, one failure


def test_slo_stamp_record():
    extra = {}
    rows = [{"signature": "s", "ok": True}]
    assert slo.stamp_record(extra, rows) is extra
    assert extra["slo"] == rows


# --------------------------------------------------------------------- #
# serve-path integration (in-process, sink tracer)
# --------------------------------------------------------------------- #

def test_serve_request_traced_end_to_end(sink):
    from heat2d_tpu.serve.server import Client, SolveServer

    reg = MetricsRegistry()
    with SolveServer(registry=reg) as s:
        c = Client(s)
        r = SolveRequest(nx=16, ny=16, steps=4, cx=0.41, method="jnp")
        c.solve(r, timeout=60)
        c.solve(r, timeout=60)          # cache hit
    ss = spans(sink)
    by_name = {}
    for sp in ss:
        by_name.setdefault(sp["name"], []).append(sp)
    assert set(by_name) == {"serve.request", "serve.queue",
                            "serve.launch"}
    cold, hit = by_name["serve.request"]
    assert cold["attrs"]["outcome"] == "completed"
    assert hit["attrs"]["outcome"] == "cache_hit"
    (queue,) = by_name["serve.queue"]
    (launch,) = by_name["serve.launch"]
    # causal chain: queue and launch are children of the cold request
    assert queue["parent_id"] == cold["span_id"]
    assert launch["parent_id"] == cold["span_id"]
    assert launch["attrs"]["first_launch"] is True
    # per-signature SLO sources landed
    assert reg.find_histograms("serve_signature_latency_s")
    assert reg.find_counters("serve_signature_requests_total")


def test_serve_untraced_emits_nothing_and_no_sig_spam():
    tracing.uninstall()
    os.environ.pop("HEAT2D_TRACE_DIR", None)
    from heat2d_tpu.serve.server import Client, SolveServer

    reg = MetricsRegistry()
    with SolveServer(registry=reg) as s:
        Client(s).solve(SolveRequest(nx=16, ny=16, steps=4, cx=0.43,
                                     method="jnp"), timeout=60)
    # tracing off: the request still records per-signature metrics
    # (they are cheap host counters), but no span machinery ran
    assert not tracing.enabled()


# --------------------------------------------------------------------- #
# the jaxpr pins: tracing is FREE when off — and when on
# --------------------------------------------------------------------- #

from tests._pin import (assert_jaxpr_equal, band_runner_jaxpr,
                        batch_runner_jaxpr, solver_jaxpr)


def _solver_jaxpr():
    return solver_jaxpr(12, 12, 8)


def _batch_runner_jaxpr():
    return batch_runner_jaxpr(16, 16, 4, "jnp", b=2)


def _band_runner_jaxpr():
    return band_runner_jaxpr(64, 128, 10, b=2)


def test_jaxpr_pin_solver_band_and_batch_runner(monkeypatch, sink):
    """The ISSUE acceptance pin: with a tracer INSTALLED and spans
    actively emitting (phase() included), the forward solver, the
    batched band runner, and the serve batch runner trace to programs
    byte-identical to the untraced ones — tracing is host-side
    bookkeeping only."""
    from heat2d_tpu.ops import pallas_stencil as ps
    from heat2d_tpu.utils.profiling import phase

    monkeypatch.setattr(ps, "VMEM_BUDGET_BYTES", 256 * 1024)

    with_tracing = {}
    assert tracing.enabled()
    with phase("interior_stencil"):     # a live phase span under trace
        pass
    with_tracing["solver"] = _solver_jaxpr()
    with_tracing["batch"] = _batch_runner_jaxpr()
    with_tracing["band"] = _band_runner_jaxpr()
    assert spans(sink)                  # spans actually emitted

    tracing.uninstall()
    os.environ.pop("HEAT2D_TRACE_DIR", None)
    assert not tracing.enabled()
    assert_jaxpr_equal(with_tracing["solver"], _solver_jaxpr(),
                       label="solver (traced vs untraced)")
    assert_jaxpr_equal(with_tracing["batch"], _batch_runner_jaxpr(),
                       label="batch runner (traced vs untraced)")
    assert_jaxpr_equal(with_tracing["band"], _band_runner_jaxpr(),
                       label="band runner (traced vs untraced)")


def test_phase_emits_host_span_only_when_traced(sink):
    from heat2d_tpu.utils.profiling import phase

    @jax.jit
    def f(x):
        with phase("residual_reduction"):
            return x * 2.0

    f(jnp.ones((4, 4))).block_until_ready()
    names = [s["name"] for s in spans(sink)]
    assert "phase.residual_reduction" in names


# --------------------------------------------------------------------- #
# wire back-compat + fenced-worker isolation
# --------------------------------------------------------------------- #

def test_wire_lines_without_trace_parse_unchanged():
    """Old-supervisor/new-worker mix: a DISPATCH line with no trace
    field decodes to 'no context'; a result line with an unexpected
    trace-era field still decodes (readers are .get-based)."""
    from heat2d_tpu.fleet import wire
    from heat2d_tpu.serve.schema import SolveResult

    assert wire.decode_trace({"id": 1, "req": {"nx": 16}}) is None
    assert wire.decode_trace({"id": 1, "trace": "garbage"}) is None
    ctx = wire.decode_trace(
        {"id": 1, "trace": {"trace_id": "a" * 32,
                            "span_id": "b" * 16}})
    assert ctx is not None and ctx.trace_id == "a" * 32
    # new-supervisor/old-worker direction: extra envelope keys ride
    # through the result codec untouched
    u = np.ones((3, 3), np.float32)
    msg = wire.encode_result(5, SolveResult(u=u, steps_done=2,
                                            content_hash="h"))
    msg["trace"] = {"trace_id": "a" * 32, "span_id": "b" * 16}
    back = wire.decode_result(msg)
    assert np.asarray(back.u).tobytes() == u.tobytes()


def test_late_line_from_fenced_worker_attaches_no_span(sink):
    """A late answer for an unknown wire id (a fenced worker racing
    its replacement) is dropped WITHOUT touching any trace — spans
    can never be attributed to a replay by a zombie."""
    import tests.test_fleet as tf

    fs = tf.make_router()
    f = fs.submit(tf.req(cx=0.71))
    slot, msg = fs.sup.sent[0]
    n_before = len(sink)
    # a line with a wire id nobody is waiting on
    tf.answer(fs, slot, {"id": 999999, "req": msg["req"]})
    assert not f.done()                     # real request unaffected
    assert len(sink) == n_before            # and NO span was emitted
    tf.answer(fs, slot, msg)
    assert f.result(timeout=5) is not None
    fs.stop()


# --------------------------------------------------------------------- #
# trace CLI: merge, connectivity, critical path, chrome export
# --------------------------------------------------------------------- #

def _write_span_file(tmp_path, service, recs):
    p = tmp_path / f"spans-{service}-1.jsonl"
    with open(p, "a") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    return p


def _mkspan(trace, span, parent, name, kind, t0, t1, service="router",
            pid=1, **attrs):
    return {"event": "span", "schema": tracing.TRACE_SCHEMA,
            "service": service, "pid": pid, "trace_id": trace,
            "span_id": span, "parent_id": parent, "name": name,
            "kind": kind, "t0": t0, "t1": t1, "attrs": attrs}


def test_merge_connectivity_and_critical_path(tmp_path):
    from heat2d_tpu.obs import trace_cli

    t = "t" * 32
    _write_span_file(tmp_path, "router", [
        _mkspan(t, "r1", None, "fleet.request", "request", 0.0, 1.0,
                content_hash="hash1"),
        _mkspan(t, "w1", "r1", "fleet.dispatch", "wire", 0.1, 0.4),
        _mkspan(t, "w2", "r1", "fleet.dispatch", "wire", 0.5, 1.0),
    ])
    _write_span_file(tmp_path, "worker0", [
        _mkspan(t, "s1", "w2", "serve.request", "request", 0.55, 0.95,
                service="worker0", pid=2),
        _mkspan(t, "q1", "s1", "serve.queue", "queue", 0.55, 0.65,
                service="worker0", pid=2),
        _mkspan(t, "l1", "s1", "serve.launch", "launch", 0.65, 0.95,
                service="worker0", pid=2, first_launch=True),
    ])
    rep = trace_cli.merge_report(str(tmp_path))
    (row,) = rep["traces"]
    assert row["connected"] and row["processes"] == 2
    assert row["content_hash"] == "hash1"
    b = row["breakdown"]
    assert b["total"] == 1.0
    assert b["queue"] == pytest.approx(0.1)
    assert b["compile"] == pytest.approx(0.3)
    # wire = both dispatch spans minus the nested worker request
    assert b["wire"] == pytest.approx(0.3 + 0.5 - 0.4)
    # replay gap: w1 ended 0.4, w2 began 0.5
    assert b["replay"] == pytest.approx(0.1)
    assert rep["request_hashes"] == {"hash1": [t]}

    # chrome export: per-process lanes + a flow edge across processes
    loaded = trace_cli.load_dir(str(tmp_path))
    chrome = trace_cli.to_chrome(loaded["spans"])
    evs = chrome["traceEvents"]
    lanes = {e["args"]["name"] for e in evs
             if e.get("name") == "process_name"}
    assert len(lanes) == 2
    assert any(e["ph"] == "s" for e in evs)     # flow start
    assert any(e["ph"] == "f" for e in evs)     # flow finish


def test_merge_flags_disconnected_and_unfinished(tmp_path):
    from heat2d_tpu.obs import trace_cli

    t = "u" * 32
    _write_span_file(tmp_path, "router", [
        _mkspan(t, "c1", "missing-parent", "serve.queue", "queue",
                0.0, 0.1),
        dict(_mkspan(t, "zz", None, "serve.request", "request",
                     0.0, 0.0), event="span_start"),
    ])
    rep = trace_cli.merge_report(str(tmp_path))
    (row,) = rep["traces"]
    assert not row["connected"] and row["orphans"] == 1
    # the start-only span was synthesized as unfinished
    synth = [s for s in trace_cli.load_dir(str(tmp_path))["spans"]
             if s["span_id"] == "zz"]
    assert synth and synth[0]["attrs"]["unfinished"] is True


# --------------------------------------------------------------------- #
# END TO END: chaos kill mid-flight -> post-mortem + connected merge
# --------------------------------------------------------------------- #

def test_fleet_chaos_kill_postmortem_and_connected_timeline(tmp_path):
    """The ISSUE acceptance scenario, in one subprocess test: a
    2-worker fleet serves requests while worker 0 is armed to
    chaos-kill at its 2nd pickup. Afterwards:

    - every request completed (failover replayed the in-flight one);
    - the killed worker left a flight-recorder file that is present,
      DIGEST-VALID, and contains the in-flight request's spans;
    - ``heat2d-tpu-trace`` merges every process's span file + the
      post-mortem into timelines that are each CONNECTED, including
      the replayed request's (router -> wire -> worker0[died] ->
      replay -> wire -> worker1), which crosses >= 2 processes."""
    import tests.test_fleet as tf
    from heat2d_tpu.fleet.router import FleetServer
    from heat2d_tpu.obs import trace_cli

    tdir = str(tmp_path)
    tracing.install(Tracer(tdir, service="router"))
    reg = MetricsRegistry()
    fs = FleetServer(
        workers=2, registry=reg, max_replays=5,
        env={"JAX_PLATFORMS": "cpu", "HEAT2D_TRACE_DIR": tdir,
             "HEAT2D_FLIGHT_DIR": tdir},
        per_worker_env={0: {"HEAT2D_CHAOS_WORKER_KILL_AFTER": "2"}})
    reqs = [tf.req(cx=0.51 + 0.01 * i, steps=tf.STEPS + (i % 3))
            for i in range(6)]
    with fs:
        results = [fs.solve(r, timeout=120) for r in reqs]
        deaths = fs.sup.deaths
        assert fs.stop()
    tracing.uninstall()
    assert len(results) == 6 and deaths >= 1
    for r, res in zip(reqs, results):
        assert np.asarray(res.u).tobytes() == tf.oracle_grid(r)

    # -- the killed worker's black box ------------------------------- #
    pms = flight.find_postmortems(tdir)
    assert pms, "no flight-recorder file from the killed worker"
    entries = flight.load_postmortem(pms[0])    # digest-verified
    header = entries[0]
    assert header["event"] == "flight_header"
    assert header["reason"] == "chaos_worker_kill"
    pm_spans = [e for e in entries
                if e.get("event") in ("span", "span_start")]
    assert pm_spans, "post-mortem holds no spans"
    # the in-flight request's pickup marker is in the black box: the
    # LAST thing worker 0 did was receive the request it died holding
    recvs = [e for e in pm_spans if e.get("name") == "fleet.recv"]
    assert recvs, "no wire-receive span in the post-mortem"

    # -- the merged cross-process timeline --------------------------- #
    rep = trace_cli.merge_report(tdir)
    assert rep["postmortems"] and not rep["corrupt_postmortems"]
    assert len(rep["traces"]) == 6
    assert all(r["connected"] for r in rep["traces"]), rep["traces"]
    replayed = [r for r in rep["traces"] if r["replays"] >= 1]
    assert replayed, "no replayed trace recorded"
    assert replayed[0]["processes"] >= 2        # crossed the fleet
    # every request hash maps to exactly one (connected) trace
    assert len(rep["request_hashes"]) == 6
    assert all(len(tids) == 1
               for tids in rep["request_hashes"].values())
    # segments exist for the breakdown (queue/launch on some trace)
    assert any(r["breakdown"]["queue"] > 0 for r in rep["traces"])
    assert any(r["breakdown"]["compile"] + r["breakdown"]["launch"] > 0
               for r in rep["traces"])

    # CLI assertion mode agrees
    assert trace_cli.main([tdir, "--assert-connected",
                           "--require-postmortem"]) == 0
