"""heat2d-tpu-prof (obs/trace_report): the mpiP-style digest of a
captured jax.profiler.trace logdir — synthetic-event units plus an
end-to-end CPU capture through profile_span."""

import gzip
import json
import os

import jax
import jax.numpy as jnp
import pytest

from heat2d_tpu.obs import trace_report
from heat2d_tpu.utils.profiling import annotate, profile_span


# -- synthetic Chrome-trace events: deterministic digest units --------- #

def _meta(pid, pname, tid, tname):
    return [
        {"ph": "M", "pid": pid, "name": "process_name",
         "args": {"name": pname}},
        {"ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
         "args": {"name": tname}},
    ]


def _op(pid, tid, name, dur_us, ts=0):
    return {"ph": "X", "pid": pid, "tid": tid, "name": name,
            "ts": ts, "dur": dur_us}


def _tpu_style_events():
    """Two device lanes (TPU-flavored) + one python host lane."""
    ev = _meta(1, "/device:TPU:0", 10, "XLA Ops")
    ev += _meta(2, "/device:TPU:1", 10, "XLA Ops")
    ev += _meta(3, "python-host", 20, "python")
    ev += [
        _op(1, 10, "fusion.1", 600_000),
        _op(1, 10, "fusion.1", 200_000, ts=700_000),
        _op(1, 10, "all-reduce.3", 150_000),
        _op(1, 10, "collective-permute.2", 50_000),
        _op(2, 10, "fusion.1", 700_000),
        _op(2, 10, "all-reduce.3", 300_000),
        _op(2, 10, "copy.5", 100_000),
        # executor bookkeeping lines must not count as op self-time
        _op(1, 10, "while", 999_000),
        # host-side user annotation (profiling.annotate span)
        _op(3, 20, "halo_exchange", 42_000),
    ]
    return ev


def test_categorize_prefix_table():
    assert trace_report.categorize("all-reduce.1") == "collective"
    assert trace_report.categorize("collective-permute.7") == "collective"
    assert trace_report.categorize("copy.2") == "host/transfer"
    assert trace_report.categorize("infeed.0") == "host/transfer"
    assert trace_report.categorize("fusion.9") == "compute"


def test_digest_synthetic_shares_and_lanes():
    d = trace_report.digest(_tpu_style_events())
    assert d["schema"] == trace_report.DIGEST_SCHEMA
    assert d["n_lanes"] == 2
    # fusion.1: 0.6+0.2+0.7 s over 3 invocations, the top op
    top = d["top_ops"][0]
    assert top["op"] == "fusion.1" and top["count"] == 3
    assert top["total_s"] == pytest.approx(1.5)
    assert top["category"] == "compute"
    # total excludes the 'while' bookkeeping event
    assert d["total_op_s"] == pytest.approx(2.1)
    assert d["categories"]["collective"] == pytest.approx(0.5)
    assert d["categories"]["host/transfer"] == pytest.approx(0.1)
    # per-lane MPI%-analogue: lane 1 collective share = 0.2/1.0
    lane1 = next(r for r in d["lanes"] if "TPU:0" in r["lane"])
    assert lane1["collective_pct"] == pytest.approx(20.0)
    # shares sum to ~100
    assert sum(o["share_pct"] for o in d["top_ops"]) == pytest.approx(
        100.0, abs=0.1)
    # the host annotation is surfaced separately, not as op time
    assert any(a["name"] == "halo_exchange" for a in d["annotations"])


def test_markdown_rendering():
    md = trace_report.to_markdown(
        trace_report.digest(_tpu_style_events()), logdir="/tmp/x")
    assert "Per-device category shares" in md
    assert "Top ops by self-time" in md
    assert "`fusion.1`" in md and "all-reduce.3" in md


def test_load_events_missing_logdir_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        trace_report.load_events(str(tmp_path))


def test_main_reads_synthetic_logdir(tmp_path, capsys):
    inner = tmp_path / "plugins" / "profile" / "run1"
    inner.mkdir(parents=True)
    path = inner / "host.trace.json.gz"
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": _tpu_style_events()}, f)
    out_json = tmp_path / "digest.json"
    rc = trace_report.main([str(tmp_path), "--format", "json",
                            "--json-out", str(out_json)])
    assert rc == 0
    stdout = json.loads(capsys.readouterr().out)   # valid JSON on stdout
    assert stdout["top_ops"][0]["op"] == "fusion.1"
    assert json.loads(out_json.read_text()) == stdout


def test_main_missing_logdir_rc1(tmp_path, capsys):
    assert trace_report.main([str(tmp_path)]) == 1
    assert "trace.json.gz" in capsys.readouterr().err


def test_multihost_capture_merges_all_host_files(tmp_path):
    """A multihost capture writes one trace file per host into ONE run
    directory — the digest must merge them all, keeping same-numbered
    pids on different hosts as distinct lanes."""
    run = tmp_path / "plugins" / "profile" / "run1"
    run.mkdir(parents=True)
    for host in ("hostA", "hostB"):    # identical pid namespaces
        ev = _meta(1, "/device:TPU:0", 10, "XLA Ops")
        ev.append(_op(1, 10, "fusion.1", 500_000))
        with gzip.open(run / f"{host}.trace.json.gz", "wt") as f:
            json.dump({"traceEvents": ev}, f)
    d = trace_report.digest(trace_report.load_events(str(tmp_path)))
    assert d["n_lanes"] == 2            # one lane per host, not merged
    assert d["top_ops"][0]["count"] == 2
    assert d["total_op_s"] == pytest.approx(1.0)


def test_stale_captures_in_reused_logdir_skipped(tmp_path):
    """Two sequential --profile runs into one logdir: only the latest
    capture directory is digested (not double-counted)."""
    for run, dur in (("run1", 900_000), ("run2", 300_000)):
        d = tmp_path / "plugins" / "profile" / run
        d.mkdir(parents=True)
        ev = _meta(1, "/device:TPU:0", 10, "XLA Ops")
        ev.append(_op(1, 10, "fusion.1", dur))
        with gzip.open(d / "host.trace.json.gz", "wt") as f:
            json.dump({"traceEvents": ev}, f)
    d = trace_report.digest(trace_report.load_events(str(tmp_path)))
    assert d["total_op_s"] == pytest.approx(0.3)   # run2 only


# -- end-to-end: capture a tiny CPU trace, digest it ------------------- #

def test_cpu_capture_digest_nonempty(tmp_path):
    """The ISSUE acceptance flow: a profile_span capture under
    JAX_PLATFORMS=cpu digests to a non-empty top-op table and valid
    JSON — no TPU needed for the whole trace-digest workflow."""
    logdir = str(tmp_path / "trace")
    with profile_span(logdir):
        with annotate("stencil_phase"):
            x = jnp.ones((64, 64))
            for _ in range(3):
                x = jax.block_until_ready(
                    jax.jit(lambda u: u @ u + 1.0)(x))
    rep = trace_report.report(logdir)
    assert rep["n_lanes"] >= 1
    assert rep["top_ops"], "empty top-op table from a real capture"
    assert rep["total_op_s"] > 0
    assert all(o["total_s"] > 0 and o["count"] >= 1
               for o in rep["top_ops"])
    # the user's own phase marker survives into the digest
    assert any(a["name"] == "stencil_phase" for a in rep["annotations"])
    json.dumps(rep)    # the digest is JSON-serializable as-is
    md = trace_report.to_markdown(rep, logdir=logdir)
    assert rep["top_ops"][0]["op"] in md
