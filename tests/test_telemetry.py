"""In-loop telemetry streaming (obs/stream + engine taps): residual
trajectories out of the COMPILED convergence loops, the no-overhead
guarantee when disabled, and the --metrics-out CLI flow."""

import json

import jax
import jax.numpy as jnp
import numpy as np

from heat2d_tpu.config import HeatConfig
from heat2d_tpu.models import engine
from heat2d_tpu.models.solver import Heat2DSolver
from heat2d_tpu.obs import MetricsRegistry, TelemetryStream
from heat2d_tpu.ops.stencil import residual_sq, stencil_step


CFG = dict(nxprob=24, nyprob=24, steps=200, convergence=True,
           interval=20, sensitivity=1e-6)


def test_serial_stream_trajectory_monotone_and_sized():
    stream = TelemetryStream()
    cfg = HeatConfig(**CFG)
    result = Heat2DSolver(cfg, telemetry=stream).run(timed=False)
    traj = stream.trajectory()
    # One point per INTERVAL chunk, in step order.
    assert [p["step"] for p in traj] == list(
        range(cfg.interval, result.steps_done + 1, cfg.interval))
    assert len(traj) == result.steps_done // cfg.interval
    # Diffusion decays: the residual trajectory is monotone decreasing.
    resid = [p["residual"] for p in traj]
    assert all(a > b for a, b in zip(resid, resid[1:]))
    assert all(r >= 0 for r in resid)


def test_stream_registry_series_mirror():
    reg = MetricsRegistry()
    stream = TelemetryStream(registry=reg)
    Heat2DSolver(HeatConfig(**CFG), telemetry=stream).run(timed=False)
    series = reg.snapshot()["series"]["residual"]
    assert series == [[p["step"], p["residual"]]
                      for p in stream.trajectory()]


def test_disabled_streaming_adds_nothing_to_the_program():
    """The no-overhead guarantee: telemetry off (the default) leaves the
    compiled convergence loop free of any callback machinery — jaxpr and
    lowered HLO — while the enabled program carries the tap."""
    cfg = HeatConfig(**CFG)
    u0 = Heat2DSolver(cfg).init_state()

    off = Heat2DSolver(cfg).make_runner()
    on = Heat2DSolver(cfg, telemetry=TelemetryStream()).make_runner()
    jaxpr_off = jax.make_jaxpr(off)(u0)
    jaxpr_on = jax.make_jaxpr(on)(u0)
    assert "debug_callback" not in str(jaxpr_off)
    assert "debug_callback" in str(jaxpr_on)
    assert "callback" not in off.lower(u0).as_text()
    # A second telemetry-free solver traces to the identical program
    # (determinism of the disabled path).
    from tests._pin import assert_jaxpr_equal
    again = jax.make_jaxpr(Heat2DSolver(cfg).make_runner())(u0)
    assert_jaxpr_equal(str(jaxpr_off), str(again),
                       label="telemetry-off solver (determinism)")


def test_tapless_engine_loop_is_the_seed_loop():
    """engine.run_convergence with tap=None must trace to EXACTLY the
    pre-telemetry loop (replicated here verbatim from the seed) — the
    byte-identical-hot-path contract."""
    from jax import lax

    def seed_run_convergence(step_fn, residual_fn, u0, steps, interval,
                             sensitivity):
        interval = min(interval, steps) if steps else interval

        def chunk_body(carry):
            u_prev, u, k, _ = carry
            n = jnp.minimum(interval, steps - k)

            def body(_, pu):
                p, c = pu
                del p
                return (c, step_fn(c))

            u_prev, u = lax.fori_loop(0, n, body, (u_prev, u))
            res = residual_fn(u, u_prev).astype(jnp.float32)
            return (u_prev, u, k + n, res)

        def cond(carry):
            _, _, k, res = carry
            return jnp.logical_and(k < steps, res >= sensitivity)

        init = (u0, u0, jnp.asarray(0, jnp.int32),
                jnp.asarray(jnp.inf, jnp.float32))
        _, u, k, _ = lax.while_loop(cond, chunk_body, init)
        return u, k

    step = lambda u: stencil_step(u, 0.1, 0.1)          # noqa: E731
    u0 = jnp.ones((12, 12), jnp.float32)
    ours = jax.make_jaxpr(
        lambda u: engine.run_convergence(step, residual_sq, u,
                                         100, 10, 0.1))(u0)
    seed = jax.make_jaxpr(
        lambda u: seed_run_convergence(step, residual_sq, u,
                                       100, 10, 0.1))(u0)
    from tests._pin import assert_jaxpr_equal
    assert_jaxpr_equal(str(ours), str(seed),
                       label="tapless engine loop vs seed loop")


def test_streaming_does_not_change_results():
    cfg = HeatConfig(**CFG)
    off = Heat2DSolver(cfg).run(timed=False)
    on = Heat2DSolver(cfg, telemetry=TelemetryStream()).run(timed=False)
    np.testing.assert_array_equal(off.u, on.u)
    assert off.steps_done == on.steps_done


def test_sharded_stream_dedupes_across_shards():
    """dist2d: the callback fires once per shard with the replicated
    psum'd residual — the stream must report ONE point per chunk."""
    stream = TelemetryStream()
    cfg = HeatConfig(nxprob=16, nyprob=16, steps=100, mode="dist2d",
                     gridx=2, gridy=2, convergence=True, interval=10,
                     sensitivity=1e-9)
    result = Heat2DSolver(cfg, telemetry=stream).run(timed=False)
    traj = stream.trajectory()
    assert len(traj) == result.steps_done // cfg.interval
    assert [p["step"] for p in traj] == list(
        range(cfg.interval, result.steps_done + 1, cfg.interval))
    # and the sharded trajectory tracks the serial one: the GRID is
    # pinned bitwise to serial, but the residual is a psum of per-shard
    # partial sums — a different summation order than serial's single
    # full-grid reduce, so it deviates at f32 ulp.
    serial = TelemetryStream()
    Heat2DSolver(HeatConfig(nxprob=16, nyprob=16, steps=100,
                            convergence=True, interval=10,
                            sensitivity=1e-9),
                 telemetry=serial).run(timed=False)
    np.testing.assert_allclose(
        [p["residual"] for p in traj],
        [p["residual"] for p in serial.trajectory()], rtol=1e-5)


def test_ensemble_chunk_progress_stream():
    from heat2d_tpu.models.ensemble import run_ensemble_convergence

    stream = TelemetryStream()
    batch, steps_done = run_ensemble_convergence(
        16, 16, 60, 10, 1e-7, [0.1, 0.05], [0.1, 0.05],
        method="pallas", tap=stream.tap_members)
    prog = stream.chunk_progress()
    assert len(prog) == 6        # 60 steps / interval 10, none converge
    assert [p["chunk"] for p in prog] == list(range(1, 7))
    for p in prog:
        assert len(p["residuals"]) == 2 == len(p["done"])
    # per-member residuals decrease chunk over chunk
    r0 = [p["residuals"][0] for p in prog]
    assert all(a > b for a, b in zip(r0, r0[1:]))
    assert list(prog[-1]["steps_done"]) == [int(s) for s in steps_done]


def test_cli_metrics_out_writes_unified_jsonl(tmp_path):
    """Acceptance flow: --metrics-out writes a JSONL whose run_record
    line carries the unified schema, the residual trajectory, and the
    compile/warmup metric."""
    from heat2d_tpu.cli import main
    from heat2d_tpu.obs.record import RECORD_SCHEMA

    out = tmp_path / "run.jsonl"
    rc = main(["--mode", "serial", "--convergence", "--nxprob", "24",
               "--nyprob", "24", "--steps", "100", "--interval", "20",
               "--outdir", str(tmp_path), "--metrics-out", str(out)])
    assert rc == 0
    lines = [json.loads(l) for l in out.read_text().splitlines()]
    rec = next(l for l in lines if l["event"] == "run_record")
    assert rec["schema"] == RECORD_SCHEMA
    assert rec["warmup_s"] > 0
    assert len(rec["residual_trajectory"]) == rec["steps_done"] // 20
    assert rec["metrics_aggregate"]["warmup_compile_s"]["rank_max"] > 0
    snap = next(l for l in lines if l["event"] == "snapshot")
    assert snap["gauges"]["steps_done"] == rec["steps_done"]


def test_cli_resume_trajectory_uses_absolute_steps(tmp_path):
    """Resumed runs count engine steps from 0 — the emitted trajectory
    must be shifted to absolute step numbers."""
    from heat2d_tpu.cli import main

    ck = tmp_path / "ck.bin"
    rc = main(["--mode", "serial", "--nxprob", "24", "--nyprob", "24",
               "--steps", "60", "--checkpoint", str(ck),
               "--outdir", str(tmp_path), "--dat-layout", "none"])
    assert rc == 0
    out = tmp_path / "resume.jsonl"
    rc = main(["--mode", "serial", "--convergence", "--nxprob", "24",
               "--nyprob", "24", "--steps", "120", "--interval", "20",
               "--resume", str(ck), "--outdir", str(tmp_path),
               "--dat-layout", "none", "--metrics-out", str(out)])
    assert rc == 0
    rec = next(json.loads(l) for l in out.read_text().splitlines()
               if json.loads(l)["event"] == "run_record")
    # 60 checkpointed + 60 streamed-in-segment steps at interval 20:
    # absolute steps 80, 100, 120 — not segment-local 20, 40, 60.
    assert [p["step"] for p in rec["residual_trajectory"]] == [80, 100,
                                                               120]
    assert rec["total_steps_including_resume"] == 120


def test_cli_without_metrics_out_is_untelemetered(tmp_path):
    """Default path: no --metrics-out, no telemetry object anywhere —
    the solver's runner stays callback-free."""
    cfg = HeatConfig(**CFG)
    s = Heat2DSolver(cfg)
    assert s.telemetry is None
    u0 = s.init_state()
    assert "debug_callback" not in str(jax.make_jaxpr(s.make_runner())(u0))
