"""Control plane (heat2d_tpu/control/): SLO-driven decisions, safe
tuning rollouts with auto-revert, and the chaos-proven
no-unvalidated-serving invariant (ISSUE 12).

Three tiers, mirroring test_fleet.py: unit tests over the new
obs/slo.py windowed-burn API and tune-db rollout provenance; router
logic (probe semantics, pre-emptive shedding) against the FAKE
supervisor; and end-to-end rollouts over real worker subprocesses —
healthy promote, deliberately-bad candidate auto-revert with bitwise
post-revert parity, and a kill storm landing mid-rollout."""

import time

import numpy as np
import pytest

from heat2d_tpu.control import (ControlPlane, Retuner, Rollout,
                                RolloutConfig, problem_from_signature)
from heat2d_tpu.fleet.router import FleetServer, TenantPolicy
from heat2d_tpu.obs import slo
from heat2d_tpu.obs.metrics import MetricsRegistry
from heat2d_tpu.resil import chaos
from heat2d_tpu.serve.schema import Rejected, SolveRequest
from heat2d_tpu.tune.db import TuningDB

import tests.test_fleet as tf


def _policy(budget=0.01):
    return slo.SLOPolicy(latency_p99_s=1.0, error_budget=budget)


def _traffic(reg, n_ok=0, n_fail=0, sig="sigA"):
    if n_ok:
        reg.counter("fleet_signature_requests_total", value=n_ok,
                    signature=sig, outcome="completed")
    if n_fail:
        reg.counter("fleet_signature_requests_total", value=n_fail,
                    signature=sig, outcome="rejected_timeout")


# --------------------------------------------------------------------- #
# obs/slo.py satellites: zero-traffic + the windowed burn API
# --------------------------------------------------------------------- #

def test_slo_evaluate_zero_traffic_emits_no_burn_gauge_or_verdict():
    """A signature with latency samples but zero requests must not
    read as a met objective OR a violation: no slo_burn_rate gauge,
    and the verdict key is MISSING (every consumer does
    row.get("ok", True) — a None would read as a violation)."""
    reg = MetricsRegistry()
    reg.observe("fleet_signature_latency_s", 0.1, signature="dead")
    (row,) = slo.evaluate(reg, prefix="fleet", default=_policy())
    assert row["requests"] == 0
    assert "ok" not in row and "burn_rate" not in row
    assert row["latency_target_p99_s"] == 1.0
    assert row.get("ok", True) is True      # the consumers' idiom
    assert reg.snapshot()["gauges"] == {}


def test_burn_window_sustained_detection_and_reset():
    reg = MetricsRegistry()
    bw = slo.BurnWindow(_policy(), threshold=1.0, sustain=2)
    _traffic(reg, n_ok=100)
    res = bw.tick(reg)
    assert res["sigA"]["burn_rate"] == 0.0
    assert not bw.sustained(res)
    # two consecutive burning windows -> sustained
    _traffic(reg, n_fail=10)
    res = bw.tick(reg)
    assert res["sigA"]["burn_rate"] == pytest.approx(100.0)
    assert res["sigA"]["windows"] == 1 and not res["sigA"]["sustained"]
    _traffic(reg, n_fail=10)
    res = bw.tick(reg)
    assert res["sigA"]["sustained"] and bw.sustained(res) == ["sigA"]
    g = reg.snapshot()["gauges"]
    assert g["slo_windowed_burn_rate{signature=sigA}"] == \
        pytest.approx(100.0)
    # one clean window resets the streak
    _traffic(reg, n_ok=100)
    res = bw.tick(reg)
    assert res["sigA"]["windows"] == 0 and not res["sigA"]["sustained"]


def test_burn_window_zero_traffic_holds_streak_without_gauge():
    reg = MetricsRegistry()
    bw = slo.BurnWindow(_policy(), threshold=1.0, sustain=1)
    _traffic(reg, n_fail=5)
    assert bw.tick(reg)["sigA"]["sustained"]
    # idle window: the streak neither grows nor resets, burn is absent
    res = bw.tick(reg)
    assert res["sigA"]["burn_rate"] is None
    assert res["sigA"]["sustained"]
    # a registry-less caller gets an empty window, never a crash
    assert bw.tick(None) == {}
    with pytest.raises(ValueError):
        slo.BurnWindow(_policy(), sustain=0)
    with pytest.raises(ValueError):
        slo.BurnWindow(_policy(), threshold=0)


def test_counter_deltas_windows_and_registry_swap():
    from heat2d_tpu.obs.metrics import CounterDeltas
    reg = MetricsRegistry()
    cd = CounterDeltas()
    reg.counter("fleet_requests_total", value=5, outcome="completed")
    (d,) = cd.tick(reg, "fleet_requests_total").values()
    assert d == 5.0                       # first tick: the full total
    assert list(cd.tick(reg, "fleet_requests_total").values()) == [0.0]
    reg.counter("fleet_requests_total", value=3, outcome="completed")
    assert list(cd.tick(reg, "fleet_requests_total").values()) == [3.0]
    # a swapped (fresh) registry resets the series to its new total
    reg2 = MetricsRegistry()
    reg2.counter("fleet_requests_total", value=2, outcome="completed")
    assert list(cd.tick(reg2, "fleet_requests_total").values()) == [2.0]


# --------------------------------------------------------------------- #
# router probe semantics (fake supervisor)
# --------------------------------------------------------------------- #

def test_probe_targets_slot_and_bypasses_cache():
    fs = tf.make_router()
    r = tf.req(cx=0.33)
    f = fs.submit(r)
    slot, msg = fs.sup.sent[-1]
    tf.answer(fs, slot, msg)
    f.result(timeout=5)
    assert fs.submit(r).result(timeout=5).cache_hit
    n = len(fs.sup.sent)
    other = 1 - slot
    pf = fs.probe(other, r)
    assert len(fs.sup.sent) == n + 1       # a real dispatch, no cache
    pslot, pmsg = fs.sup.sent[-1]
    assert pslot == other                  # pinned to the target slot
    assert "event" not in pmsg             # served as a normal request
    tf.answer(fs, pslot, pmsg)
    res = pf.result(timeout=5)
    assert not res.cache_hit and not res.coalesced
    # probes never enter the hot-signature warmup set
    assert str(r.signature()) in fs._hot   # from the ORIGINAL submit
    probe_only = SolveRequest(nx=tf.NX, ny=tf.NY, steps=tf.STEPS + 7,
                              cx=0.9, cy=0.1, method="jnp")
    fs.probe(other, probe_only)
    assert str(probe_only.signature()) not in fs._hot


def test_probe_fails_fast_without_replay():
    fs = tf.make_router()
    f = fs.probe(0, tf.req(cx=0.41))
    n = len(fs.sup.sent)
    fs.sup.alive = [1]
    fs._on_worker_lost(0)
    with pytest.raises(Rejected) as e:
        f.result(timeout=5)
    assert e.value.code == "worker_lost"
    assert len(fs.sup.sent) == n           # never replayed elsewhere
    # a probe aimed at a dead slot fails immediately
    with pytest.raises(Rejected) as e:
        fs.probe(0, tf.req(cx=0.42)).result(timeout=5)
    assert e.value.code == "worker_lost"


def test_probe_deadline_expires():
    fs = tf.make_router(default_timeout=0.01)
    f = fs.probe(0, tf.req(cx=0.43))
    time.sleep(0.05)
    fs._expire_overdue()
    with pytest.raises(Rejected) as e:
        f.result(timeout=5)
    assert e.value.code == "timeout"


# --------------------------------------------------------------------- #
# pre-emptive shedding (extends the PR 5 quota/watermark suite)
# --------------------------------------------------------------------- #

def test_preemptive_shed_low_priority_only_cache_still_answers():
    """Under a control-plane shed, standard-priority tenants shed at
    the lowered watermark while priority-0 traffic and cache hits keep
    answering; lifting the shed restores the default watermark."""
    fs = tf.make_router(
        max_inflight=10,
        quotas={"batch": TenantPolicy(max_inflight=10, priority=1)})
    warm = tf.req(cx=0.77)
    f = fs.submit(warm, tenant="batch")
    slot, msg = fs.sup.sent[-1]
    tf.answer(fs, slot, msg)
    f.result(timeout=5)
    fs.set_preemptive_shed(0.3)            # watermark 10 -> 3
    futs = [fs.submit(tf.req(cx=0.5 + 0.001 * i), tenant="batch")
            for i in range(4)]
    with pytest.raises(Rejected) as e:
        futs[-1].result(timeout=5)         # 4th standard passes 3/10
    assert e.value.code == "overloaded"
    assert e.value.fields["preemptive_shed"] is True
    # priority-0 (default tenant) is untouched by the shed
    crit = fs.submit(tf.req(cx=0.81))
    assert not crit.done()                 # admitted
    # an answer the fleet already owns is never shed
    assert fs.submit(warm, tenant="batch").result(timeout=5).cache_hit
    snap = fs.registry.snapshot()
    assert snap["gauges"]["fleet_shed_watermark"] == 0.3
    fs.set_preemptive_shed(None)
    assert fs.registry.snapshot()["gauges"][
        "fleet_shed_watermark"] == 0.8
    with pytest.raises(ValueError):
        fs.set_preemptive_shed(1.5)


# --------------------------------------------------------------------- #
# control plane decisions (fake fleet)
# --------------------------------------------------------------------- #

class FakePlaneFleet:
    """The FleetServer surface the plane uses, minus everything."""

    def __init__(self):
        self.registry = MetricsRegistry()
        self.shed_calls = []
        self._total_inflight = 0
        self.sup = tf.FakeSup(alive=(0, 1))

    def set_preemptive_shed(self, wm):
        self.shed_calls.append(wm)


def test_plane_sheds_on_sustained_burn_and_lifts_on_recovery():
    fleet = FakePlaneFleet()
    fit = {"model": "m", "per_unit_rps": 50.0, "saturated": True}
    plane = ControlPlane(fleet, policy=_policy(), sustain=2,
                         shed_watermark=0.4, capacity_fit=fit)
    _traffic(fleet.registry, n_ok=100)
    plane.tick()
    assert fleet.shed_calls == []
    for _ in range(2):
        _traffic(fleet.registry, n_fail=10)
        plane.tick()
    assert fleet.shed_calls == [0.4]
    acts = [d["action"] for d in plane.decisions]
    assert "shed" in acts and "retune_wanted" in acts
    assert "capacity_advice" in acts
    advice = [d for d in plane.decisions
              if d["action"] == "capacity_advice"][0]
    assert advice["current_units"] == 2
    # advice dedupes while the burn state holds: more burning ticks
    # with the same advised unit count append no new rows
    for _ in range(3):
        _traffic(fleet.registry, n_fail=10)
        plane.tick()
    assert len([d for d in plane.decisions
                if d["action"] == "capacity_advice"]) == 1
    snap = fleet.registry.snapshot()
    assert snap["gauges"]["control_shed_active"] == 1.0
    assert snap["gauges"]["control_burning_signatures"] == 1.0
    assert snap["counters"]["control_actions_total{action=shed}"] == 1
    # burn clears -> unshed exactly once
    _traffic(fleet.registry, n_ok=500)
    plane.tick()
    plane.tick()
    assert fleet.shed_calls == [0.4, None]
    assert fleet.registry.snapshot()["gauges"][
        "control_shed_active"] == 0.0


def test_capacity_advice_reemits_when_quarantine_shrinks_capacity():
    """A mid-burn quarantine keeps needed_units (a function of the
    observed rate and the fit alone) but grows the add-units gap —
    the corrected advice must land as a new decision row, not dedupe
    away behind an unchanged needed_units."""
    from heat2d_tpu.mesh.health import HealthMonitor

    fleet = FakePlaneFleet()
    monitor = HealthMonitor(n_devices=8)
    fit = {"model": "m", "per_unit_rps": 50.0, "saturated": True}
    plane = ControlPlane(fleet, policy=_policy(), sustain=2,
                         shed_watermark=0.4, capacity_fit=fit,
                         mesh_health=monitor)
    plane._observed_rps = lambda: 120.0    # 3 units needed, 2 deployed
    _traffic(fleet.registry, n_ok=100)
    plane.tick()
    for _ in range(2):
        _traffic(fleet.registry, n_fail=10)
        plane.tick()
    rows = [d for d in plane.decisions
            if d["action"] == "capacity_advice"]
    assert len(rows) == 1
    assert rows[0]["needed_units"] == 3 and rows[0]["add_units"] == 1
    monitor.quarantine(3, "device_fail")   # 8 -> 7 chips mid-burn
    _traffic(fleet.registry, n_fail=10)
    plane.tick()
    rows = [d for d in plane.decisions
            if d["action"] == "capacity_advice"]
    assert len(rows) == 2
    assert rows[1]["needed_units"] == 3    # unchanged: rate-driven
    assert rows[1]["capacity_fraction"] == 0.875
    assert rows[1]["add_units"] == 2       # ceil(3 - 2 * 0.875)
    # and the corrected row still dedupes while the state holds
    _traffic(fleet.registry, n_fail=10)
    plane.tick()
    assert len([d for d in plane.decisions
                if d["action"] == "capacity_advice"]) == 2


def test_plane_stages_retune_off_peak(tmp_path):
    fleet = FakePlaneFleet()
    ret = Retuner(fleet,
                  candidate_path=str(tmp_path / "candidate.json"),
                  validated_path=str(tmp_path / "validated.json"))
    plane = ControlPlane(fleet, policy=_policy(), sustain=1,
                         retuner=ret)
    sig = str(SolveRequest(nx=64, ny=64, steps=4).signature())
    _traffic(fleet.registry, n_fail=5, sig=sig)
    fleet._total_inflight = 99             # peak: nothing stages
    plane.tick()
    assert plane.staged == [] and plane.retune_wanted
    fleet._total_inflight = 0              # off-peak: stage
    _traffic(fleet.registry, n_fail=5, sig=sig)
    plane.tick()
    assert len(plane.staged) == 1
    # one attempt per burn episode: further burning idle ticks must
    # not re-run the search or re-log retune decisions every interval
    for _ in range(3):
        _traffic(fleet.registry, n_fail=5, sig=sig)
        plane.tick()
    assert len(plane.staged) == 1
    assert len([d for d in plane.decisions
                if d["action"] == "retune_wanted"]) == 1
    staged = plane.staged[0]
    assert staged["epoch"] == 1
    cdb = TuningDB(str(tmp_path / "candidate.json"))
    assert cdb.epoch == 1 and cdb.validated is False
    e = cdb.entry("sim-v5e", "64x64:float32")
    assert e is not None and e["validated"] is False
    assert e.get("best")
    # summary carries the decision log for the kind="control" record
    s = plane.summary()
    assert s["staged"] and s["no_unvalidated_serving"] is True


def test_retuner_signature_mapping_and_hot_ranking():
    fleet = FakePlaneFleet()
    ret = Retuner(fleet, candidate_path="c.json",
                  validated_path="v.json")
    sig_a = str(SolveRequest(nx=32, ny=32, steps=4).signature())
    sig_b = str(SolveRequest(nx=48, ny=32, steps=4).signature())
    _traffic(fleet.registry, n_ok=10, sig=sig_a)
    _traffic(fleet.registry, n_ok=30, sig=sig_b)
    hot = ret.hot_signatures()
    assert [s for s, _ in hot] == [sig_b, sig_a]    # hottest first
    assert ret.hot_signatures() == []               # deltas consumed
    p = problem_from_signature(sig_a)
    assert (p.nx, p.ny, p.dtype) == (32, 32, "float32")
    assert problem_from_signature("('inverse', 1)") is None
    assert problem_from_signature("garbage((") is None


def test_capacity_advise_units():
    from heat2d_tpu.load import capacity
    fit = {"model": "m", "per_unit_rps": 25.0, "saturated": True}
    adv = capacity.advise(fit, observed_rps=90.0, current_units=2)
    assert adv["needed_units"] == 4 and adv["add_units"] == 2
    none = capacity.advise({"per_unit_rps": 0.0}, 10.0, 2)
    assert none["needed_units"] is None and none["add_units"] is None


def test_chaos_rollout_env_parse_and_single_fire():
    cfg = chaos.ChaosConfig.from_env(
        {"HEAT2D_CHAOS_ROLLOUT_KILL_PHASE": "observe"})
    assert cfg is not None and cfg.rollout_kills == 0
    with pytest.raises(ValueError):
        chaos.ChaosConfig(rollout_kill_phase="nonsense")
    with pytest.raises(ValueError):
        chaos.ChaosConfig.from_env(
            {"HEAT2D_CHAOS_ROLLOUT_KILLS": "lots"})
    assert chaos.ChaosConfig.from_env(
        {"HEAT2D_CHAOS_ROLLOUT_KILL_PHASE": ""}) is None
    # the storm fires exactly once, at its phase only
    fired = []
    ctl = chaos._Controller(chaos.ChaosConfig(
        rollout_kill_phase="parity", rollout_kills=2))
    ctl.rollout_point("canary", fired.append)
    assert fired == []
    ctl.rollout_point("parity", fired.append)
    ctl.rollout_point("parity", fired.append)
    assert fired == [2]


# --------------------------------------------------------------------- #
# supervisor: one-generation overlays vs durable env (real processes)
# --------------------------------------------------------------------- #

def test_supervisor_overlay_is_one_generation_only(tmp_path):
    """The ISSUE's supervisor satellite: a deliberate rollout restart
    hands the canary its candidate db via env overlay; a NON-rollout
    (crash) restart of the same slot rebuilds from the durable env —
    the overlay can never be resurrected by the failure path."""
    cand = str(tmp_path / "candidate.json")
    TuningDB(cand).save()
    with tf.fleet(workers=1) as fs:
        assert fs.sup.worker_info(0).get("tune") is None
        fs.sup.restart_worker(0, env_overlay={"HEAT2D_TUNE_DB": cand})
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            info = fs.sup.worker_info(0)
            if info and (info.get("tune") or {}).get("path") == cand:
                break
            time.sleep(0.05)
        assert (fs.sup.worker_info(0)["tune"] or {})["path"] == cand
        # the crash path: monitor restart, durable env only
        fs.sup.kill_worker(0)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            info = fs.sup.worker_info(0)
            if info is not None and info.get("tune") is None \
                    and fs.sup.deaths >= 1:
                break
            time.sleep(0.05)
        assert fs.sup.worker_info(0).get("tune") is None
        gens = fs.sup.generations_snapshot()
        assert fs.stop()
    vias = [g["via"] for g in gens]
    assert vias == ["start", "rollout", "restart"]
    assert gens[1]["overlay"] == {"HEAT2D_TUNE_DB": cand}
    assert gens[2]["overlay"] is None and gens[2]["tune"] is None


def test_supervisor_update_slot_env_is_durable(tmp_path):
    """update_slot_env changes survive crash restarts (the durable
    counterpart of the one-generation overlay)."""
    vali = str(tmp_path / "validated.json")
    with tf.fleet(workers=1) as fs:
        fs.sup.update_slot_env(0, {"HEAT2D_TUNE_DB": vali})
        fs.sup.kill_worker(0)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            info = fs.sup.worker_info(0)
            if info and (info.get("tune") or {}).get("path") == vali:
                break
            time.sleep(0.05)
        assert (fs.sup.worker_info(0)["tune"] or {})["path"] == vali
        assert fs.stop()


def test_cli_storm_and_bad_candidate_require_rollout():
    """Review regression: the chaos flags act on a live rollout — a
    soak that 'passed' without one would prove nothing, so the CLI
    refuses the combination outright."""
    from heat2d_tpu.fleet.cli import main
    assert main(["--soak", "1", "--control-storm-phase",
                 "observe"]) == 2
    assert main(["--soak", "1", "--control-bad-candidate"]) == 2


# --------------------------------------------------------------------- #
# end to end: rollouts over real worker subprocesses
# --------------------------------------------------------------------- #

PROBE = {"nx": tf.NX, "ny": tf.NY, "steps": tf.STEPS,
         "cx": 0.123, "cy": 0.1, "method": "jnp"}


def _control_fleet(tmp_path, workers=2):
    vp = str(tmp_path / "validated.json")
    cp = str(tmp_path / "candidate.json")
    fs = tf.fleet(workers=workers, max_replays=5,
                  env={"JAX_PLATFORMS": "cpu", "HEAT2D_TUNE_DB": vp},
                  cache_size=0, worker_cache_size=0)
    return fs, vp, cp


def _stage(fs, vp, cp):
    ret = Retuner(fs, candidate_path=cp, validated_path=vp)
    for i in range(3):
        fs.solve(tf.req(cx=0.05 + 0.01 * i), timeout=120)
    staged = ret.stage_candidate(ret.hot_signatures()[0][0])
    assert staged is not None and staged["epoch"] == 1
    return staged


def test_rollout_healthy_candidate_promotes(tmp_path):
    """Canary -> bitwise parity -> observe -> promote: the validated
    db advances an epoch and every worker ends up serving it."""
    fs, vp, cp = _control_fleet(tmp_path)
    reg = fs.registry
    with fs:
        _stage(fs, vp, cp)
        out = Rollout(fs, RolloutConfig(
            candidate_path=cp, validated_path=vp, probe_spec=PROBE,
            observe_s=0.8, observe_probes=2, probe_timeout=60),
            policy=_policy(budget=0.5), registry=reg).run()
        assert out["outcome"] == "promoted", out
        assert [p["phase"] for p in out["phases"]] == [
            "baseline", "canary", "parity", "observe", "promote",
            "roll"]
        vdb = TuningDB(vp)
        assert vdb.epoch == 1 and vdb.validated is True
        for s in fs.sup.alive_slots():
            t = (fs.sup.worker_info(s) or {}).get("tune") or {}
            assert t.get("path") == vp and t.get("validated") is True
            assert t.get("epoch") == 1
        assert fs.stop()
    snap = reg.snapshot()
    assert snap["counters"][
        "control_rollouts_total{outcome=promoted}"] == 1
    assert snap["counters"][
        "control_probe_parity_total{result=match}"] >= 1
    assert snap["gauges"]["control_epoch"] == 1


def test_rollout_bad_candidate_auto_reverts_bitwise(tmp_path):
    """ISSUE acceptance: a seeded regression candidate (chaos-slow on
    the canary's overlay) is MEASURED as a latency regression and
    auto-reverted; post-revert answers are bitwise-identical to the
    pre-rollout baseline; nothing non-validated survives."""
    fs, vp, cp = _control_fleet(tmp_path)
    with fs:
        _stage(fs, vp, cp)
        out = Rollout(fs, RolloutConfig(
            candidate_path=cp, validated_path=vp, probe_spec=PROBE,
            observe_s=1.2, observe_probes=3, probe_timeout=60,
            extra_canary_env={"HEAT2D_CHAOS_SLOW_WORKER_S": "0.6"}),
            policy=_policy(budget=0.5), registry=fs.registry).run()
        assert out["outcome"] == "reverted:latency_regression", out
        assert out["post_revert_parity"] is True
        # the candidate never reached the validated db
        vdb = TuningDB(vp)
        assert vdb.epoch == 0 and vdb.validated is True
        gens = fs.sup.generations_snapshot()
        assert fs.stop()
    bad = [g for g in gens
           if not (g["via"] == "rollout" and g.get("overlay"))
           and g.get("tune") is not None
           and not g["tune"].get("validated", True)]
    assert bad == []


def test_rollout_promote_guards_against_midflight_restage(
        tmp_path, monkeypatch):
    """Review regression: if the candidate file changes between the
    canary's observation and promote (a concurrent re-stage), the
    never-canaried content must NOT be validated — the rollout
    reverts instead."""
    from heat2d_tpu.control import rollout as rmod

    fs, vp, cp = _control_fleet(tmp_path)
    real_point = chaos.rollout_point

    def restage_at_promote(phase, kill_cb=None):
        if phase == "promote":
            db = TuningDB(cp)
            db.stamp_rollout(epoch=7, validated=False)
            db.save()
        return real_point(phase, kill_cb)

    monkeypatch.setattr(rmod.chaos, "rollout_point",
                        restage_at_promote)
    with fs:
        _stage(fs, vp, cp)
        out = rmod.Rollout(fs, RolloutConfig(
            candidate_path=cp, validated_path=vp, probe_spec=PROBE,
            observe_s=0.5, observe_probes=1, probe_timeout=60),
            policy=_policy(budget=0.5), registry=fs.registry).run()
        assert out["outcome"] == \
            "reverted:candidate_changed_mid_rollout", out
        assert out["post_revert_parity"] is True
        assert TuningDB(vp).epoch == 0      # nothing was promoted
        assert fs.stop()


def test_restart_worker_forced_kill_notifies_router(tmp_path):
    """Review regression: a worker that misses the drain window for a
    deliberate restart is killed — and the router must get the same
    worker-lost sweep the crash path runs, or its in-flight records
    sit until their deadline instead of replaying."""
    lost = []
    with tf.fleet(workers=1) as fs:
        orig = fs.sup.on_worker_lost
        fs.sup.on_worker_lost = lambda s: (lost.append(s), orig(s))
        # timeout=0 forces the kill path even on an idle worker (the
        # drain cannot complete in zero time)
        fs.sup.restart_worker(0, timeout=0)
        assert lost == [0]
        deadline = time.monotonic() + 60
        while not fs.sup.alive_slots() and time.monotonic() < deadline:
            time.sleep(0.05)
        # the replacement serves
        assert fs.solve(tf.req(cx=0.91), timeout=120).steps_done \
            == tf.STEPS
        assert fs.stop()


def test_rollout_kill_storm_never_serves_unvalidated(tmp_path):
    """ISSUE acceptance (chaos-proven): a kill storm landing mid-
    rollout (observation window) takes every worker down; the rollout
    auto-reverts, post-revert answers match the pre-rollout baseline
    bitwise, and NO non-rollout worker generation ever reported a
    non-validated config — crash restarts always rejoin the validated
    epoch."""
    fs, vp, cp = _control_fleet(tmp_path, workers=2)
    chaos.install(chaos.ChaosConfig(rollout_kill_phase="observe",
                                    rollout_kills=0))
    try:
        with fs:
            _stage(fs, vp, cp)
            pre = np.asarray(fs.solve(
                SolveRequest.from_dict(dict(PROBE)),
                timeout=120).u).tobytes()
            out = Rollout(fs, RolloutConfig(
                candidate_path=cp, validated_path=vp,
                probe_spec=PROBE, observe_s=3.0, observe_probes=4,
                probe_timeout=60),
                policy=_policy(budget=0.5),
                registry=fs.registry).run()
            assert out["outcome"].startswith("reverted:"), out
            assert out["post_revert_parity"] is True
            assert fs.sup.deaths >= 2          # the storm landed
            # the incumbent config still answers, bitwise
            deadline = time.monotonic() + 60
            while (len(fs.sup.alive_slots()) < 2
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            post = np.asarray(fs.solve(
                SolveRequest.from_dict(dict(PROBE)),
                timeout=120).u).tobytes()
            assert post == pre
            gens = fs.sup.generations_snapshot()
            assert fs.stop()
    finally:
        chaos.uninstall()
    # THE invariant: only rollout-spawned generations may be
    # unvalidated; every crash restart rejoined the validated epoch
    restarts = [g for g in gens if g["via"] == "restart"]
    assert restarts, "the storm produced no crash restarts"
    for g in gens:
        if g["via"] == "rollout" and g.get("overlay"):
            continue
        assert g.get("tune") is None or \
            g["tune"].get("validated", True), g
    # the validated db never advanced
    assert TuningDB(vp).epoch == 0
