"""``heat2d-tpu-trace`` — merge per-process span files into ONE
cross-process timeline.

The tracing layer (obs/tracing.py) leaves one ``spans-<service>-
<pid>.jsonl`` per process in the trace directory, and chaos-killed
workers leave ``flight-*.jsonl`` post-mortems (obs/flight.py) holding
the spans that died with them. This tool is the read side:

- **merge** — every span from every process (post-mortem spans
  included: a killed worker's last seconds are part of the story),
  deduped by span id, grouped by ``trace_id``;
- **causality check** — a trace is CONNECTED when exactly one root
  span exists and every other span's parent resolves inside the
  trace: the property the fleet propagation exists to guarantee
  (router -> wire -> worker -> batcher -> launch), and what CI's
  trace-smoke job asserts (``--assert-connected``);
- **critical path** — per request: queue wait vs compile (a
  signature's first launch pays the jit) vs launch vs wire overhead
  (dispatch span minus the worker-side serving span it carried) vs
  replay gap (failover dead time) vs other;
- **export** — a Chrome trace-event file (``--perfetto-out``)
  loadable at ui.perfetto.dev: one lane per process, flow arrows on
  every cross-process parent/child edge.

``--require-postmortem`` additionally fails unless at least one
digest-valid, non-empty flight-recorder post-mortem is present — the
CI chaos gate that proves the black box actually flushed.
"""

from __future__ import annotations

import argparse
import collections
import glob
import json
import os
import sys

from heat2d_tpu.obs import flight as flight_mod

MERGE_SCHEMA = "heat2d-tpu/trace-merge/v1"

#: critical-path segment order (md table column order)
SEGMENTS = ("queue", "compile", "launch", "wire", "replay", "other")


def load_dir(trace_dir: str, verify: bool = True) -> dict:
    """Read every span file + flight post-mortem under ``trace_dir``.
    Returns ``{"spans": [...], "postmortems": [...], "corrupt": [...],
    "files": n}``. Span files are torn-line tolerant (a killed
    process's final line may be cut); post-mortems are digest-verified
    unless ``verify=False`` — a corrupt one is REPORTED, never
    silently merged."""
    spans: dict = {}     # (trace_id, span_id) -> record (first wins)
    starts: dict = {}    # span_start records awaiting a matching end
    postmortems, corrupt = [], []

    def take(rec, source=None):
        key = (rec.get("trace_id"), rec.get("span_id"))
        if source is not None:
            rec = dict(rec, source=source)
        if rec.get("event") == "span":
            spans.setdefault(key, rec)
            return True
        if rec.get("event") == "span_start":
            starts.setdefault(key, rec)
        return False

    span_files = sorted(glob.glob(os.path.join(trace_dir,
                                               "spans-*.jsonl")))
    for path in span_files:
        with open(path, errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue    # torn tail line of a killed process
                take(rec)
    for path in flight_mod.find_postmortems(trace_dir):
        try:
            entries = flight_mod.load_postmortem(path, verify=verify)
        except flight_mod.PostmortemCorruptError as e:
            corrupt.append({"path": path, "error": str(e)})
            continue
        header = (entries[0] if entries
                  and entries[0].get("event") == "flight_header"
                  else {})
        n_spans = 0
        for rec in entries:
            # a span teed to both the live file and the black box
            # keeps the live copy; one that only survived in the
            # black box (killed before/without a span file) merges
            # from here
            if take(rec, source="flight"):
                n_spans += 1
        postmortems.append({
            "path": path, "reason": header.get("reason"),
            "service": header.get("service"), "pid": header.get("pid"),
            "entries": header.get("entries"), "spans": n_spans,
        })
    # A start with no end is a span the process never got to close —
    # usually because it DIED inside it (the chaos kill). Synthesize
    # an UNFINISHED zero-length span so its children stay connected
    # and the timeline shows exactly where the process stopped.
    for key, rec in starts.items():
        if key not in spans:
            spans[key] = dict(rec, event="span", t1=rec.get("t0"),
                              attrs=dict(rec.get("attrs") or {},
                                         unfinished=True))
    return {"spans": list(spans.values()), "postmortems": postmortems,
            "corrupt": corrupt,
            "files": len(span_files) + len(postmortems) + len(corrupt)}


def assemble(spans: list) -> dict:
    """{trace_id: spans sorted by t0}."""
    traces: dict = collections.defaultdict(list)
    for s in spans:
        if s.get("trace_id"):
            traces[s["trace_id"]].append(s)
    return {tid: sorted(ss, key=lambda s: (s.get("t0", 0.0),
                                           s.get("t1", 0.0)))
            for tid, ss in traces.items()}


def connectivity(trace_spans: list) -> dict:
    """roots/orphans of one trace; connected == one root, no orphans
    (every span's parent resolvable inside the merged trace)."""
    ids = {s["span_id"] for s in trace_spans}
    roots = [s for s in trace_spans if not s.get("parent_id")]
    orphans = [s for s in trace_spans
               if s.get("parent_id") and s["parent_id"] not in ids]
    return {"roots": len(roots), "orphans": len(orphans),
            "connected": len(roots) == 1 and not orphans}


def _dur(s: dict) -> float:
    return max(0.0, float(s.get("t1", 0.0)) - float(s.get("t0", 0.0)))


def critical_path(trace_spans: list) -> dict:
    """Per-request segment breakdown (seconds). Segments:

    - ``queue``   — batcher queue-wait spans;
    - ``compile`` — launch spans flagged ``first_launch`` (the jit
      compile is paid inside that launch);
    - ``launch``  — warm launch spans;
    - ``wire``    — fleet dispatch spans MINUS the worker-side serving
      span each one carried (serialization + pipe + scheduling);
    - ``replay``  — failover dead time: the gap between a dispatch
      closed by a worker death and the next dispatch's start;
    - ``other``   — the root's remaining unattributed time.
    """
    children: dict = collections.defaultdict(list)
    for s in trace_spans:
        if s.get("parent_id"):
            children[s["parent_id"]].append(s)
    seg = dict.fromkeys(SEGMENTS, 0.0)
    roots = [s for s in trace_spans if not s.get("parent_id")]
    total = _dur(roots[0]) if len(roots) == 1 else sum(
        _dur(s) for s in roots)
    wire_spans = []
    for s in trace_spans:
        kind = s.get("kind")
        if kind == "queue":
            seg["queue"] += _dur(s)
        elif kind == "launch":
            key = ("compile" if s.get("attrs", {}).get("first_launch")
                   else "launch")
            seg[key] += _dur(s)
        elif kind == "wire":
            wire_spans.append(s)
            nested = sum(_dur(c) for c in children[s["span_id"]]
                         if c.get("kind") == "request")
            seg["wire"] += max(0.0, _dur(s) - nested)
    wire_spans.sort(key=lambda s: s.get("t0", 0.0))
    for a, b in zip(wire_spans, wire_spans[1:]):
        seg["replay"] += max(0.0, b["t0"] - a["t1"])
    attributed = sum(v for k, v in seg.items() if k != "other")
    seg["other"] = max(0.0, total - attributed)
    seg["total"] = total
    return {k: round(v, 6) for k, v in seg.items()}


def summarize(trace_spans: list) -> dict:
    """One report row per trace."""
    conn = connectivity(trace_spans)
    roots = [s for s in trace_spans if not s.get("parent_id")]
    root = roots[0] if roots else {}
    attrs = root.get("attrs", {})
    return {
        "trace_id": trace_spans[0]["trace_id"],
        "content_hash": attrs.get("content_hash"),
        "signature": attrs.get("signature"),
        "tenant": attrs.get("tenant"),
        "root": root.get("name"),
        "service": root.get("service"),
        "t0": min(s.get("t0", 0.0) for s in trace_spans),
        "spans": len(trace_spans),
        "processes": len({(s.get("service"), s.get("pid"))
                          for s in trace_spans}),
        "replays": sum(1 for s in trace_spans
                       if s.get("name") == "fleet.replay"),
        "flight_spans": sum(1 for s in trace_spans
                            if s.get("source") == "flight"),
        "outcome": attrs.get("outcome"),
        **conn,
        "breakdown": critical_path(trace_spans),
    }


def merge_report(trace_dir: str, verify: bool = True,
                 loaded: dict = None) -> dict:
    """The full merged report (the library entry point). ``loaded``
    reuses a prior ``load_dir`` result — one read serves both the
    report and a Perfetto export."""
    if loaded is None:
        loaded = load_dir(trace_dir, verify=verify)
    traces = assemble(loaded["spans"])
    rows = sorted((summarize(ss) for ss in traces.values()),
                  key=lambda r: r["t0"])
    by_hash: dict = collections.defaultdict(list)
    for r in rows:
        if r["content_hash"]:
            by_hash[r["content_hash"]].append(r["trace_id"])
    return {
        "schema": MERGE_SCHEMA,
        "dir": trace_dir,
        "files": loaded["files"],
        "spans": len(loaded["spans"]),
        "traces": rows,
        "request_hashes": {h: tids for h, tids in sorted(by_hash.items())},
        "postmortems": loaded["postmortems"],
        "corrupt_postmortems": loaded["corrupt"],
    }


# -- per-segment statistics (--stats) ----------------------------------- #

def _seg_quantile(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return 0.0
    from heat2d_tpu.obs.metrics import quantile
    return quantile(sorted_vals, q)    # the registry's one convention


def load_cost_cards(trace_dir: str) -> dict:
    """{signature string: cost card} from the ``cost-cards-*.jsonl``
    sidecars a ``--perf`` serve run leaves beside its span files
    (obs/perf.PerfObserver). First card per signature wins — capacity
    rungs of one signature share the per-program shape figures the
    stats table renders. Torn-line tolerant like the span reader."""
    cards: dict = {}
    for path in sorted(glob.glob(os.path.join(trace_dir,
                                              "cost-cards-*.jsonl"))):
        with open(path, errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                sig = rec.get("signature")
                if sig:
                    cards.setdefault(sig, rec)
    return cards


def segment_stats(report: dict, cards: dict = None) -> dict:
    """Per-segment distribution over every trace in a merged report:
    {segment: {count, mean, p50, p99, max, total}} across the
    per-trace critical-path breakdowns. The aggregate view of where
    requests spend time — what the load subsystem's replay rides on
    (load/replay.py consumes the same ``load_dir``/``assemble``
    parser) and what ``--stats`` renders.

    With ``cards`` (``load_cost_cards``), the program-executing
    segments (compile/launch) additionally carry ``hbm_bytes`` and
    ``arith_intensity`` — the XLA cost-card figures of the programs
    those spans ran, joined per trace through the root span's
    signature (mean over the traces a card matched). Program-level
    properties, not span sums: one launch's bytes, not bytes x spans.
    """
    out = {}
    rows = report.get("traces", [])
    for seg in SEGMENTS + ("total",):
        vals = sorted(r["breakdown"].get(seg, 0.0) for r in rows)
        n = len(vals)
        out[seg] = {
            "count": n,
            "mean": round(sum(vals) / n, 6) if n else 0.0,
            "p50": round(_seg_quantile(vals, 0.50), 6),
            "p99": round(_seg_quantile(vals, 0.99), 6),
            "max": round(vals[-1], 6) if n else 0.0,
            "total": round(sum(vals), 6),
        }
    if cards:
        matched = [cards[r["signature"]] for r in rows
                   if r.get("signature") in cards]
        byt = [c["bytes_accessed"] for c in matched
               if c.get("bytes_accessed")]
        ai = [c["arithmetic_intensity"] for c in matched
              if c.get("arithmetic_intensity") is not None]
        for seg in ("compile", "launch"):
            if byt:
                out[seg]["hbm_bytes"] = round(sum(byt) / len(byt), 1)
            if ai:
                out[seg]["arith_intensity"] = round(
                    sum(ai) / len(ai), 4)
    return out


def stats_markdown(report: dict, cards: dict = None) -> str:
    stats = segment_stats(report, cards=cards)
    has_cards = any("hbm_bytes" in stats[seg] for seg in SEGMENTS)
    n = len(report.get("traces", []))
    lines = [
        f"# Segment statistics — {report['dir']} ({n} trace(s))", "",
        "| segment | mean | p50 | p99 | max | total (s) |"
        + (" hbm bytes | arith int |" if has_cards else ""),
        "|---|---|---|---|---|---|"
        + ("---|---|" if has_cards else ""),
    ]
    for seg in SEGMENTS + ("total",):
        s = stats[seg]
        line = (
            f"| {seg} | {s['mean']:.4g} | {s['p50']:.4g} "
            f"| {s['p99']:.4g} | {s['max']:.4g} | {s['total']:.4g} |")
        if has_cards:
            line += (f" {s['hbm_bytes']:.4g} |"
                     if "hbm_bytes" in s else " — |")
            line += (f" {s['arith_intensity']:.4g} |"
                     if "arith_intensity" in s else " — |")
        lines.append(line)
    return "\n".join(lines) + "\n"


# -- Chrome trace-event export ----------------------------------------- #

def to_chrome(spans: list) -> dict:
    """The merged spans as a Chrome trace-event JSON object (Perfetto/
    chrome://tracing loadable): one pid lane per (service, pid), an
    ``X`` event per span, and ``s``/``f`` flow arrows on every
    cross-process parent->child edge."""
    procs: dict = {}
    events = []
    by_id = {s["span_id"]: s for s in spans}

    def pid_of(s) -> int:
        key = (s.get("service") or "?", s.get("pid") or 0)
        if key not in procs:
            procs[key] = len(procs) + 1
            events.append({"ph": "M", "pid": procs[key], "tid": 0,
                           "name": "process_name",
                           "args": {"name": f"{key[0]} (pid {key[1]})"}})
        return procs[key]

    flow = 0
    for s in spans:
        pid = pid_of(s)
        ts = s.get("t0", 0.0) * 1e6
        dur = max(_dur(s) * 1e6, 1.0)   # sub-us events stay visible
        events.append({
            "ph": "X", "pid": pid, "tid": 0, "ts": ts, "dur": dur,
            "name": s.get("name"), "cat": s.get("kind", "internal"),
            "args": {"trace_id": s.get("trace_id"),
                     "span_id": s.get("span_id"),
                     "source": s.get("source", "live"),
                     **(s.get("attrs") or {})},
        })
        parent = by_id.get(s.get("parent_id") or "")
        if parent is not None and (
                (parent.get("service"), parent.get("pid"))
                != (s.get("service"), s.get("pid"))):
            flow += 1
            ppid = pid_of(parent)
            pts = max(parent.get("t0", 0.0) * 1e6, ts - 1.0)
            events.append({"ph": "s", "id": flow, "pid": ppid,
                           "tid": 0, "ts": pts, "name": "dispatch",
                           "cat": "flow"})
            events.append({"ph": "f", "bp": "e", "id": flow,
                           "pid": pid, "tid": 0, "ts": ts,
                           "name": "dispatch", "cat": "flow"})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"schema": MERGE_SCHEMA}}


# -- rendering --------------------------------------------------------- #

def to_markdown(report: dict, top: int = 25) -> str:
    rows = report["traces"]
    lines = [
        f"# Merged trace — {report['dir']}", "",
        f"{report['spans']} spans in {report['files']} file(s); "
        f"{len(rows)} trace(s) over "
        f"{len(report['request_hashes'])} distinct request hash(es); "
        f"{len(report['postmortems'])} post-mortem(s)"
        + (f", {len(report['corrupt_postmortems'])} CORRUPT"
           if report["corrupt_postmortems"] else "") + ".", "",
        "| trace | request | spans | procs | replays | connected "
        "| queue | compile | launch | wire | replay | total (s) |",
        "|---|---|---|---|---|---|---|---|---|---|---|---|",
    ]
    for r in rows[:top]:
        b = r["breakdown"]
        lines.append(
            f"| {r['trace_id'][:8]} "
            f"| {(r['content_hash'] or '—')[:10]} | {r['spans']} "
            f"| {r['processes']} | {r['replays']} "
            f"| {'yes' if r['connected'] else 'NO'} "
            + "".join(f"| {b[k]:.4g} " for k in
                      ("queue", "compile", "launch", "wire", "replay"))
            + f"| {b['total']:.4g} |")
    if len(rows) > top:
        lines.append(f"| … {len(rows) - top} more | | | | | | | | | | | |")
    if report["postmortems"]:
        lines += ["", "## Flight-recorder post-mortems", "",
                  "| file | reason | service | spans |", "|---|---|---|---|"]
        for p in report["postmortems"]:
            lines.append(f"| {os.path.basename(p['path'])} "
                         f"| {p['reason']} | {p['service']} "
                         f"| {p['spans']} |")
    for c in report["corrupt_postmortems"]:
        lines.append(f"\nCORRUPT post-mortem: {c['path']}: {c['error']}")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="heat2d-tpu-trace",
        description="merge per-process span files (+ flight-recorder "
                    "post-mortems) from a HEAT2D_TRACE_DIR into one "
                    "cross-process timeline (docs/OBSERVABILITY.md)")
    p.add_argument("trace_dir", help="the span directory to merge")
    p.add_argument("--format", default="md", choices=["md", "json"])
    p.add_argument("--stats", action="store_true",
                   help="print per-segment (queue/compile/launch/"
                        "wire/replay) p50/p99 tables over the merged "
                        "timeline instead of per-trace rows")
    p.add_argument("--top", type=int, default=25,
                   help="trace rows in the markdown table")
    p.add_argument("--perfetto-out", default=None, metavar="PATH",
                   help="write a Chrome trace-event JSON (loadable at "
                        "ui.perfetto.dev / chrome://tracing)")
    p.add_argument("--no-verify", action="store_true",
                   help="skip post-mortem digest verification")
    p.add_argument("--assert-connected", action="store_true",
                   help="exit 1 unless every trace is one connected "
                        "timeline (and at least one trace exists)")
    p.add_argument("--require-postmortem", action="store_true",
                   help="exit 1 unless a digest-valid post-mortem with "
                        "at least one span is present")
    args = p.parse_args(argv)

    if not os.path.isdir(args.trace_dir):
        print(f"not a directory: {args.trace_dir}", file=sys.stderr)
        return 1
    loaded = load_dir(args.trace_dir, verify=not args.no_verify)
    report = merge_report(args.trace_dir, loaded=loaded)
    if args.perfetto_out:
        from heat2d_tpu.io.binary import write_json_atomic
        write_json_atomic(to_chrome(loaded["spans"]), args.perfetto_out,
                          indent=None)
        print(f"wrote {args.perfetto_out} "
              f"({len(loaded['spans'])} spans)", file=sys.stderr)

    if args.stats:
        # Cost-card join (obs/perf.py): a --perf run's sidecars in the
        # same dir stamp the compile/launch rows with program bytes +
        # arithmetic intensity; absent sidecars, the table is as before.
        cards = load_cost_cards(args.trace_dir)
        if args.format == "json":
            print(json.dumps({"dir": report["dir"],
                              "traces": len(report["traces"]),
                              "segments": segment_stats(
                                  report, cards=cards),
                              "cost_cards": len(cards)},
                             indent=2))
        else:
            print(stats_markdown(report, cards=cards), end="")
    elif args.format == "json":
        print(json.dumps(report, indent=2))
    else:
        print(to_markdown(report, top=args.top), end="")

    failures = []
    if args.assert_connected:
        bad = [r["trace_id"] for r in report["traces"]
               if not r["connected"]]
        if not report["traces"]:
            failures.append("no traces found")
        if bad:
            failures.append(f"{len(bad)} disconnected trace(s), e.g. "
                            f"{bad[0][:16]}")
    if args.require_postmortem:
        ok = [p for p in report["postmortems"] if p["spans"] > 0]
        if not ok:
            failures.append("no digest-valid post-mortem with spans "
                            "found")
        if report["corrupt_postmortems"]:
            failures.append(f"{len(report['corrupt_postmortems'])} "
                            f"corrupt post-mortem(s)")
    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
