"""``heat2d-tpu-prof`` — the mpiP-style digest of a captured device trace.

The reference's profiling artifact is an mpiP report (Report.pdf p.34-37):
per-rank AppTime/MPITime and an aggregate per-callsite table (File_open
29% of app time, Waitall 21%, ...). The TPU analogue is a
``jax.profiler.trace`` logdir — rich, but only viewable interactively
(Perfetto/XProf). This tool turns the logdir into the mpiP tables as
markdown/JSON:

- **Top ops by self-time** — the per-callsite aggregate table: each HLO
  op (kernel, collective, copy) with total seconds, share, and count.
- **Per-device category shares** — the AppTime/MPITime analogue: compute
  vs collective vs host/transfer vs sync seconds per device lane (mpiP's
  "MPI%" column maps to the collective share).

Usage::

    heat2d-tpu --profile /tmp/trace --mode dist2d ...   # capture
    heat2d-tpu-prof /tmp/trace                          # digest (markdown)
    heat2d-tpu-prof /tmp/trace --format json            # digest (JSON)

Parses the ``*.trace.json.gz`` Chrome-trace export jax writes into the
logdir; works on both TPU device lanes ("XLA Ops" threads) and the CPU
backend's thunk-executor lanes (``tf_XLA*`` threads) so the workflow is
testable without hardware.
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import sys

DIGEST_SCHEMA = "heat2d-tpu/trace-digest/v1"

#: op-name prefix -> category, first hit wins. The mpiP mapping:
#: 'collective' is the MPITime analogue; 'host/transfer' covers the
#: File_open/File_write class (data movement off the compute stream).
CATEGORIES = [
    ("all-reduce", "collective"),
    ("all-gather", "collective"),
    ("all-to-all", "collective"),
    ("reduce-scatter", "collective"),
    ("collective-permute", "collective"),
    ("collective", "collective"),
    ("ppermute", "collective"),
    ("psum", "collective"),
    ("infeed", "host/transfer"),
    ("outfeed", "host/transfer"),
    ("copy", "host/transfer"),
    ("transfer", "host/transfer"),
    ("send", "host/transfer"),
    ("recv", "host/transfer"),
    ("callback", "host/transfer"),
    ("Rendezvous", "sync"),
    ("Wait", "sync"),
    ("barrier", "sync"),
]

#: Executor-internal events that are bookkeeping, not op self-time.
_NOISE_PREFIXES = ("ThreadpoolListener", "ThunkExecutor", "while",
                   "condition", "branch")


def categorize(name: str) -> str:
    for prefix, cat in CATEGORIES:
        if name.startswith(prefix):
            return cat
    return "compute"


def find_trace_files(logdir: str) -> list:
    return sorted(glob.glob(
        os.path.join(logdir, "**", "*.trace.json.gz"), recursive=True))


def load_events(logdir: str) -> list:
    """Merged events of the LATEST capture: jax writes one
    ``<host>.trace.json.gz`` per host into a per-capture run directory,
    so every file sharing the newest file's directory belongs to the
    same multihost capture (older captures in a reused logdir are
    skipped). Each file's pids are namespaced so same-numbered processes
    on different hosts stay distinct lanes."""
    paths = find_trace_files(logdir)
    if not paths:
        raise FileNotFoundError(
            f"no *.trace.json.gz under {logdir} — is this a "
            f"jax.profiler.trace logdir (heat2d-tpu --profile)?")
    run_dir = os.path.dirname(paths[-1])
    run_paths = [p for p in paths if os.path.dirname(p) == run_dir]
    if len(run_paths) < len(paths):
        print(f"note: digesting the latest capture only "
              f"({len(run_paths)} of {len(paths)} trace files, "
              f"under {run_dir})", file=sys.stderr)
    events = []
    for i, path in enumerate(run_paths):
        with gzip.open(path) as f:
            for e in json.load(f)["traceEvents"]:
                if len(run_paths) > 1:
                    if "pid" in e:
                        e["pid"] = f"h{i}:{e['pid']}"
                    if (e.get("ph") == "M"
                            and e.get("name") == "process_name"):
                        # Hosts name their devices identically
                        # (/device:TPU:0) — prefix the host so lanes
                        # stay per-host, like mpiP's per-rank rows.
                        e.setdefault("args", {})["name"] = (
                            f"h{i}:{e.get('args', {}).get('name', '')}")
                events.append(e)
    return events


def _lane_names(events: list) -> tuple:
    """(pid -> process name, (pid, tid) -> thread name) metadata maps."""
    pids, tids = {}, {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            pids[e["pid"]] = e.get("args", {}).get("name", "")
        elif e.get("name") == "thread_name":
            tids[(e["pid"], e.get("tid"))] = e.get(
                "args", {}).get("name", "")
    return pids, tids


def _is_device_lane(pname: str, tname: str) -> bool:
    """Device-execution lanes: TPU 'XLA Ops' threads, or the CPU
    backend's XLA executor threads (tf_XLAEigen / tf_XLA*CpuClient)."""
    if "/device:" in pname and tname == "XLA Ops":
        return True
    return tname.startswith("tf_XLA")


def digest(events: list, top: int = 25) -> dict:
    """Aggregate trace events into the mpiP-shaped digest dict."""
    pids, tids = _lane_names(events)
    ops: dict = collections.defaultdict(lambda: [0.0, 0])  # name -> [s, n]
    lanes: dict = collections.defaultdict(
        lambda: collections.defaultdict(float))            # lane -> cat -> s
    annotations: dict = collections.defaultdict(lambda: [0.0, 0])
    for e in events:
        if e.get("ph") != "X":
            continue
        name = e.get("name", "")
        pname = pids.get(e["pid"], "")
        tname = tids.get((e["pid"], e.get("tid")), "")
        dur_s = e.get("dur", 0) / 1e6
        if not _is_device_lane(pname, tname):
            # Host-side profile_span annotations (profiling.annotate)
            # still matter — they are the user's own phase markers.
            if tname == "python" and dur_s > 0 and not name.startswith(
                    ("$", "Xla", "PjRt", "Thread")):
                annotations[name][0] += dur_s
                annotations[name][1] += 1
            continue
        if name.startswith(_NOISE_PREFIXES) or dur_s <= 0:
            continue
        ops[name][0] += dur_s
        ops[name][1] += 1
        lane = f"{pname}/{tname}" if pname else tname
        lanes[lane][categorize(name)] += dur_s

    total = sum(s for s, _ in ops.values())
    top_ops = [
        {"op": name, "category": categorize(name),
         "total_s": round(s, 6), "count": n,
         "share_pct": round(100.0 * s / total, 2) if total else 0.0}
        for name, (s, n) in sorted(ops.items(), key=lambda kv: -kv[1][0])
    ][:top]

    cat_totals: dict = collections.defaultdict(float)
    lane_rows = []
    for lane in sorted(lanes):
        cats = lanes[lane]
        lane_total = sum(cats.values())
        for c, s in cats.items():
            cat_totals[c] += s
        lane_rows.append({
            "lane": lane,
            "total_s": round(lane_total, 6),
            "categories": {c: round(s, 6) for c, s in sorted(cats.items())},
            # mpiP's MPI% column: collective share of this lane's time.
            "collective_pct": round(
                100.0 * cats.get("collective", 0.0) / lane_total, 2)
            if lane_total else 0.0,
        })

    return {
        "schema": DIGEST_SCHEMA,
        "total_op_s": round(total, 6),
        "n_lanes": len(lane_rows),
        "categories": {c: round(s, 6)
                       for c, s in sorted(cat_totals.items())},
        "top_ops": top_ops,
        "lanes": lane_rows,
        "annotations": [
            {"name": n, "total_s": round(s, 6), "count": c}
            for n, (s, c) in sorted(annotations.items(),
                                    key=lambda kv: -kv[1][0])][:top],
    }


def to_markdown(d: dict, logdir: str = "") -> str:
    lines = [
        "# Trace digest — the mpiP analogue"
        + (f" ({logdir})" if logdir else ""),
        "",
        "Aggregated from the captured `jax.profiler.trace` device events "
        "(Report.pdf p.34-37 reproduced for XLA: per-op self-time shares "
        "instead of per-MPI-callsite shares; the 'collective' category is "
        "the MPITime analogue). Seconds sum across "
        f"{d['n_lanes']} device lane(s) — shares are the meaningful "
        "column, as in mpiP.", "",
        "## Per-device category shares (AppTime/MPITime analogue)", "",
        "| lane | total (s) | collective % | breakdown |",
        "|---|---|---|---|",
    ]
    for row in d["lanes"]:
        br = ", ".join(f"{c}={s:.4g}s"
                       for c, s in row["categories"].items())
        lines.append(f"| {row['lane']} | {row['total_s']:.4g} "
                     f"| {row['collective_pct']} | {br} |")
    lines += [
        "", "## Top ops by self-time (per-callsite analogue)", "",
        "| op | category | time (s) | share | count |",
        "|---|---|---|---|---|",
    ]
    for op in d["top_ops"]:
        lines.append(f"| `{op['op']}` | {op['category']} "
                     f"| {op['total_s']:.4g} | {op['share_pct']}% "
                     f"| {op['count']} |")
    if d.get("annotations"):
        lines += ["", "## Host annotations (profile_span / annotate)", "",
                  "| span | time (s) | count |", "|---|---|---|"]
        for a in d["annotations"]:
            lines.append(
                f"| {a['name']} | {a['total_s']:.4g} | {a['count']} |")
    return "\n".join(lines) + "\n"


def report(logdir: str, top: int = 25) -> dict:
    """Load + digest in one call (the library entry point)."""
    return digest(load_events(logdir), top=top)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="heat2d-tpu-prof",
        description="mpiP-style digest of a jax.profiler.trace logdir "
                    "(capture one with: heat2d-tpu --profile LOGDIR ...)")
    p.add_argument("logdir", help="profiler logdir to digest")
    p.add_argument("--top", type=int, default=25,
                   help="rows in the top-op table (default 25)")
    p.add_argument("--format", default="md", choices=["md", "json"],
                   help="stdout format (default markdown)")
    p.add_argument("--json-out", default=None,
                   help="also write the JSON digest to this path")
    args = p.parse_args(argv)

    try:
        d = report(args.logdir, top=args.top)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 1
    if args.json_out:
        from heat2d_tpu.io.binary import write_json_atomic
        write_json_atomic(d, args.json_out)
    if args.format == "json":
        print(json.dumps(d, indent=2))
    else:
        print(to_markdown(d, logdir=args.logdir), end="")
    return 0


if __name__ == "__main__":
    sys.exit(main())
