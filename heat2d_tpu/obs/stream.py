"""In-loop telemetry streaming — residual trajectories and chunk progress
out of the COMPILED convergence loops.

The reference could only see its convergence residual by recompiling with
DEBUG printf; here the compiled ``lax.while_loop`` emits each chunk's
(step, residual) pair through ``jax.debug.callback`` into a host-side
collector — without ever syncing the loop itself to the host (the
callback is fire-and-forget; the carry never leaves the device).

Strictly opt-in: the engine/ensemble/sharded loops take ``tap=None`` by
default and add ZERO equations to the traced program when no tap is
given, so the timed hot path is byte-identical with telemetry disabled
(tests pin the jaxpr). Inside ``shard_map`` the callback fires once per
shard with the same psum'd residual — the stream dedupes by step, which
is also why taps must be tolerant of replay (jax may invoke callbacks
more than once under retracing).
"""

from __future__ import annotations

import threading

from heat2d_tpu.obs.metrics import MetricsRegistry


def flush_taps() -> None:
    """Drain queued ``jax.debug.callback`` work so a collector read
    immediately after a run sees every chunk — the callbacks are
    fire-and-forget and may still be in flight when the run's outputs
    are already ready. No-op on jax versions without the barrier."""
    import jax

    barrier = getattr(jax, "effects_barrier", None)
    if barrier is not None:
        barrier()


class TelemetryStream:
    """Host-side collector for the compiled loops' telemetry taps.

    ``tap`` is the scalar-residual hook (engine/sharded loops):
    called as ``tap(step, residual)``. ``tap_members`` is the ensemble
    hook: ``tap_members(chunk_index, steps_done, residuals, done)`` with
    per-member vectors. Both dedupe (per step / per chunk) because
    sharded programs fire the callback once per device with replicated
    values.
    """

    def __init__(self, registry: MetricsRegistry | None = None):
        self._lock = threading.Lock()
        self._resid: dict = {}          # step -> residual
        self._chunks: dict = {}         # chunk index -> member snapshot
        self.registry = registry

    # -- taps (called from jax.debug.callback; args are jax scalars) --- #

    def tap(self, step, residual) -> None:
        k, r = int(step), float(residual)
        with self._lock:
            fresh = k not in self._resid
            if fresh:
                self._resid[k] = r
        if fresh and self.registry is not None:
            self.registry.series("residual", k, r)

    def tap_members(self, chunk, steps_done, residuals, done) -> None:
        c = int(chunk)
        snap = {
            "chunk": c,
            "steps_done": [int(s) for s in steps_done],
            "residuals": [float(r) for r in residuals],
            "done": [bool(d) for d in done],
        }
        with self._lock:
            fresh = c not in self._chunks
            if fresh:
                self._chunks[c] = snap
        if fresh and self.registry is not None:
            self.registry.event("ensemble_chunk", **snap)

    # -- views --------------------------------------------------------- #

    def trajectory(self) -> list:
        """Residual trajectory in step order:
        ``[{"step": k, "residual": r}, ...]``."""
        with self._lock:
            return [{"step": k, "residual": self._resid[k]}
                    for k in sorted(self._resid)]

    def residuals(self) -> list:
        """Just the residual values, in step order."""
        return [p["residual"] for p in self.trajectory()]

    def chunk_progress(self) -> list:
        """Ensemble chunk-progress snapshots in chunk order."""
        with self._lock:
            return [self._chunks[c] for c in sorted(self._chunks)]
