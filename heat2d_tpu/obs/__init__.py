"""Run-telemetry subsystem — the mpiP analogue as a first-class layer.

The reference's authors justified every design decision with a profile
(Report.pdf p.34-37: per-rank AppTime/MPITime, per-callsite shares —
File_open 29%, Waitall 21%). This package is that discipline built into
the framework (SURVEY.md §5.1):

- ``metrics``      — process-local registry (counters, gauges, timing
                     histograms, labeled series) with JSONL and
                     Prometheus-text export, plus multihost aggregation
                     (rank-max/rank-mean, the mpiP table columns).
- ``stream``       — opt-in residual-trajectory / chunk-progress
                     streaming out of the compiled convergence loops via
                     ``jax.debug.callback`` (off by default: the timed
                     hot path is byte-identical when disabled).
- ``record``       — the ONE run-record schema every emitter shares
                     (CLI, bench.py, benchmarks/sweep.py).
- ``trace_report`` — ``heat2d-tpu-prof``: parse a captured
                     ``jax.profiler.trace`` logdir into the mpiP-style
                     digest (top ops by self-time, compute vs collective
                     vs host shares per device).
- ``tracing``      — Dapper-style distributed request tracing: a
                     TraceContext minted at admission rides through the
                     batcher, engine, fleet wire, and failover replays;
                     per-process span JSONL merged by
                     ``heat2d-tpu-trace`` (``trace_cli``) into one
                     cross-process timeline + per-request critical
                     path. Opt-in (``HEAT2D_TRACE_DIR``), free when
                     off (jaxpr-pinned).
- ``flight``       — crash flight recorder: a bounded ring of recent
                     spans/events flushed to a digest-sidecar'd
                     post-mortem on SIGTERM, unhandled exceptions, and
                     chaos kills (``HEAT2D_FLIGHT_DIR``).
- ``slo``          — per-signature SLO objectives (latency targets +
                     error-budget burn rate) evaluated from the
                     registry's histograms, exported as ``slo_*``
                     gauges and stamped into run records.
- ``trace_cli``    — the ``heat2d-tpu-trace`` merger/exporter (Chrome
                     trace-event / Perfetto output, connectivity and
                     post-mortem assertions for CI).

Metric families by producer (names are stable; docs/OBSERVABILITY.md
and docs/SERVING.md carry the full tables):

- solver/CLI:   ``steps_done``, ``elapsed_s``, ``warmup_compile_s``
                gauges; ``phase`` span histograms.
- serve/:       ``serve_queue_depth``, ``serve_cache_*`` gauges;
                ``serve_requests_total{outcome}``,
                ``serve_rejected_total{reason}``,
                ``serve_dispatch_total``, ``serve_launches_total``
                counters; ``serve_batch_occupancy``,
                ``serve_batch_fill``, ``serve_queue_wait_s``,
                ``serve_launch_s``, ``serve_e2e_latency_s`` histograms.
- resil/:       ``resil_ckpt_saves_total``, ``resil_ckpt_gc_total``,
                ``resil_ckpt_skipped_torn_total``,
                ``resil_restore_total``,
                ``resil_chaos_injected_total{point}`` counters;
                ``resil_ckpt_retained``, ``resil_ckpt_latest_step``,
                ``resil_ckpt_pending``, ``resil_restore_step`` gauges;
                ``resil_ckpt_save_s``, ``resil_ckpt_async_write_s``
                histograms — plus the serve-side resilience family
                (``serve_retries_total``, ``serve_launch_failures_
                total``, ``serve_watchdog_timeouts_total``,
                ``serve_degraded`` gauge, ``serve_degraded_shed_
                total``, ``serve_breaker_trips_total``).
"""

from heat2d_tpu.obs import flight, slo, tracing
from heat2d_tpu.obs.metrics import MetricsRegistry, get_registry
from heat2d_tpu.obs.record import (RECORD_KINDS, RECORD_SCHEMA,
                                   attach_context, build_record)
from heat2d_tpu.obs.stream import TelemetryStream, flush_taps

__all__ = ["MetricsRegistry", "get_registry", "TelemetryStream",
           "flush_taps", "RECORD_KINDS", "RECORD_SCHEMA",
           "attach_context", "build_record", "tracing", "flight",
           "slo"]
