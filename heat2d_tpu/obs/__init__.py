"""Run-telemetry subsystem — the mpiP analogue as a first-class layer.

The reference's authors justified every design decision with a profile
(Report.pdf p.34-37: per-rank AppTime/MPITime, per-callsite shares —
File_open 29%, Waitall 21%). This package is that discipline built into
the framework (SURVEY.md §5.1):

- ``metrics``      — process-local registry (counters, gauges, timing
                     histograms, labeled series) with JSONL and
                     Prometheus-text export, plus multihost aggregation
                     (rank-max/rank-mean, the mpiP table columns).
- ``stream``       — opt-in residual-trajectory / chunk-progress
                     streaming out of the compiled convergence loops via
                     ``jax.debug.callback`` (off by default: the timed
                     hot path is byte-identical when disabled).
- ``record``       — the ONE run-record schema every emitter shares
                     (CLI, bench.py, benchmarks/sweep.py).
- ``trace_report`` — ``heat2d-tpu-prof``: parse a captured
                     ``jax.profiler.trace`` logdir into the mpiP-style
                     digest (top ops by self-time, compute vs collective
                     vs host shares per device).
"""

from heat2d_tpu.obs.metrics import MetricsRegistry, get_registry
from heat2d_tpu.obs.record import RECORD_SCHEMA, attach_context, build_record
from heat2d_tpu.obs.stream import TelemetryStream, flush_taps

__all__ = ["MetricsRegistry", "get_registry", "TelemetryStream",
           "flush_taps", "RECORD_SCHEMA", "attach_context", "build_record"]
