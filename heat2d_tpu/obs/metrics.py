"""Process-local metrics registry — counters, gauges, timing histograms,
labeled series — with JSONL and Prometheus-text export.

The reference's observability was printf (``Elapsed time: %e sec``) plus
the external mpiP profiler's per-rank tables (Report.pdf p.34-37). This
registry is the in-framework replacement: every subsystem records into
one process-local object, and a multihost run aggregates the registries
cluster-wide via ``process_allgather`` so the exported numbers are the
rank-max / rank-mean columns of the mpiP tables rather than whichever
rank happened to write the file.

Pure host-side Python: nothing here touches a traced value, so recording
a metric never changes a compiled program (the streaming taps in
``obs.stream`` are the only telemetry that enters jit, and they are
opt-in).
"""

from __future__ import annotations

import contextlib
import datetime
import json
import logging
import math
import random
import re
import threading
import time

log = logging.getLogger("heat2d_tpu.obs")

#: histogram sample cap: below it quantiles are EXACT; above it the
#: reservoir keeps a uniform sample (Algorithm R) while count/sum/min/
#: max/mean stay exact — bounded memory under fleet soak (a plain
#: append-forever list was a leak).
HIST_RESERVOIR_CAP = 4096


def _utc_now_iso() -> str:
    return datetime.datetime.now(datetime.timezone.utc).isoformat(
        timespec="seconds")


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


_PROM_NAME = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    n = _PROM_NAME.sub("_", name)
    return n if not n[:1].isdigit() else "_" + n


def _prom_value(v: str) -> str:
    """Escape a label value per the Prometheus text-format spec."""
    return (v.replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _prom_labels(labels: tuple) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{_prom_name(k)}="{_prom_value(v)}"'
                     for k, v in labels)
    return "{" + inner + "}"


def quantile(sorted_samples: list, q: float) -> float:
    """Nearest-rank quantile of an already-sorted sample list."""
    if not sorted_samples:
        return float("nan")
    i = max(0, math.ceil(q * len(sorted_samples)) - 1)
    return float(sorted_samples[i])


class Reservoir:
    """Bounded histogram storage: exact count/sum/min/max always;
    the raw samples exactly up to ``cap``, then Algorithm R uniform
    reservoir sampling (each of the n observations has cap/n odds of
    being retained), so quantiles stay unbiased ESTIMATES above the
    cap and EXACT below it. Deterministically seeded: two registries
    fed the same stream summarize identically."""

    __slots__ = ("cap", "count", "sum", "min", "max", "samples", "_rng")

    def __init__(self, cap: int = HIST_RESERVOIR_CAP):
        self.cap = cap
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.samples: list = []
        self._rng = random.Random(0x1612)

    def add(self, v: float) -> None:
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        if len(self.samples) < self.cap:
            self.samples.append(v)
        else:
            i = self._rng.randrange(self.count)
            if i < self.cap:
                self.samples[i] = v

    def exact(self) -> bool:
        """True while quantiles are exact (no sample was evicted)."""
        return self.count <= self.cap


class CounterDeltas:
    """Differentiate cumulative counters between calls — the ONE
    windowing primitive behind ``obs.slo.BurnWindow``, the control
    plane's observed-rate estimate, and the retuner's demand signal
    (each previously hand-rolled the same snapshot-and-subtract).
    ``tick(registry, name)`` returns {label-pairs tuple: delta since
    the previous tick} per series; the first tick sees the full
    cumulative value. Counters are monotonic, so a negative delta
    means the registry was swapped — that series resets to its new
    total rather than reporting nonsense."""

    def __init__(self):
        self._last: dict = {}

    def tick(self, registry, name: str) -> dict:
        out = {}
        for k, v in registry.find_counters(name).items():
            key = (name, k)
            d = v - self._last.get(key, 0.0)
            self._last[key] = v
            out[k] = d if d >= 0 else v
        return out


class MetricsRegistry:
    """Counters, gauges, timing histograms and labeled series.

    Thread-safe (``jax.debug.callback`` may fire from runtime threads).
    Identity of a metric is (name, labels): the same name with different
    labels is a different time series, as in Prometheus.
    """

    def __init__(self, hist_cap: int = HIST_RESERVOIR_CAP):
        self._lock = threading.Lock()
        self._hist_cap = hist_cap
        self._counters: dict = {}
        self._gauges: dict = {}
        self._histograms: dict = {}     # key -> Reservoir (bounded)
        self._series: dict = {}
        self._events: list = []

    # -- recording ----------------------------------------------------- #

    def counter(self, name: str, value: float = 1.0, **labels) -> None:
        """Monotonically add ``value`` to the counter."""
        k = (name, _label_key(labels))
        with self._lock:
            self._counters[k] = self._counters.get(k, 0.0) + float(value)

    def gauge(self, name: str, value: float, **labels) -> None:
        """Set the gauge to the latest ``value``."""
        with self._lock:
            self._gauges[(name, _label_key(labels))] = float(value)

    def observe(self, name: str, value: float, **labels) -> None:
        """Add one sample to the (timing) histogram. Storage is a
        bounded ``Reservoir`` — a soak observing forever holds at most
        ``hist_cap`` samples per series while count/sum/min/max/mean
        stay exact."""
        k = (name, _label_key(labels))
        with self._lock:
            r = self._histograms.get(k)
            if r is None:
                r = self._histograms[k] = Reservoir(self._hist_cap)
            r.add(float(value))

    def series(self, name: str, x, y, **labels) -> None:
        """Append an (x, y) point to a labeled series — e.g. the residual
        trajectory (x=step, y=residual) or chunk progress."""
        k = (name, _label_key(labels))
        with self._lock:
            self._series.setdefault(k, []).append((x, y))

    def event(self, kind: str, **fields) -> None:
        """Append a structured event to the JSONL event log."""
        with self._lock:
            self._events.append(
                {"event": kind, "ts": _utc_now_iso(), **fields})

    @contextlib.contextmanager
    def timer(self, name: str, **labels):
        """Time the enclosed block into the ``name`` histogram (seconds)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0, **labels)

    # -- views --------------------------------------------------------- #

    @staticmethod
    def _hist_summary(res: "Reservoir") -> dict:
        s = sorted(res.samples)
        return {
            "count": res.count,
            "sum": float(res.sum),
            "min": float(res.min),
            "max": float(res.max),
            "mean": float(res.sum / res.count) if res.count else
            float("nan"),
            "p50": quantile(s, 0.50),
            "p90": quantile(s, 0.90),
            "p99": quantile(s, 0.99),
        }

    def snapshot(self) -> dict:
        """Point-in-time view: counters/gauges flat, histograms
        summarized, series as point lists."""
        with self._lock:
            return {
                "counters": {self._fmt(k): v
                             for k, v in self._counters.items()},
                "gauges": {self._fmt(k): v
                           for k, v in self._gauges.items()},
                "histograms": {self._fmt(k): self._hist_summary(v)
                               for k, v in self._histograms.items()},
                "series": {self._fmt(k): [[x, y] for x, y in v]
                           for k, v in self._series.items()},
            }

    def events(self) -> list:
        with self._lock:
            return list(self._events)

    @staticmethod
    def _fmt(key: tuple) -> str:
        name, labels = key
        if not labels:
            return name
        return name + "{" + ",".join(f"{k}={v}" for k, v in labels) + "}"

    # -- export -------------------------------------------------------- #

    def write_jsonl(self, path: str, extra_records=()) -> None:
        """JSONL event log: every recorded event, a final ``snapshot``
        line, then any caller-supplied records (e.g. the run record).
        Committed atomically (tmp + ``os.replace``): a consumer tailing
        the export never sees a half-written snapshot line."""
        from heat2d_tpu.io.binary import write_text_atomic

        events = self.events()
        lines = [json.dumps(ev) for ev in events]
        lines.append(json.dumps({"event": "snapshot",
                                 "ts": _utc_now_iso(),
                                 **self.snapshot()}))
        extra = tuple(extra_records)
        lines.extend(json.dumps(rec) for rec in extra)
        write_text_atomic("\n".join(lines) + "\n", path)
        log.debug("wrote %d events + snapshot + %d records to %s",
                  len(events), len(extra), path)

    def prometheus_text(self) -> str:
        """Prometheus text exposition: counters, gauges, and summaries.
        Each histogram emits its EXACT running ``_sum``/``_count``
        (rates — requests/s, mean latency — are computable from two
        scrapes) plus ``{quantile="..."}`` sample lines per the
        summary convention. Every series' pre-existing ``_sum``/
        ``_count`` lines are byte-unchanged — the quantile lines are
        strictly additive per series — so existing scrapers keep
        working."""
        lines = []
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {k: (v.sum, v.count, sorted(v.samples))
                     for k, v in self._histograms.items()}
        seen = set()

        def typ(name, kind):
            if name not in seen:
                seen.add(name)
                lines.append(f"# TYPE {name} {kind}")

        for (name, labels), v in sorted(counters.items()):
            n = _prom_name(name)
            typ(n, "counter")
            lines.append(f"{n}{_prom_labels(labels)} {v}")
        for (name, labels), v in sorted(gauges.items()):
            n = _prom_name(name)
            typ(n, "gauge")
            lines.append(f"{n}{_prom_labels(labels)} {v}")
        for (name, labels), (total, count, samples) in sorted(
                hists.items()):
            n = _prom_name(name)
            typ(n, "summary")
            lines.append(f"{n}_sum{_prom_labels(labels)} {float(total)}")
            lines.append(f"{n}_count{_prom_labels(labels)} {count}")
            for q in (0.5, 0.9, 0.99):
                ql = labels + (("quantile", f"{q}"),)
                lines.append(f"{n}{_prom_labels(ql)} "
                             f"{quantile(samples, q)}")
        return "\n".join(lines) + "\n"

    # -- programmatic lookups (obs/slo.py) ----------------------------- #

    def find_histograms(self, name: str) -> dict:
        """{label-pairs tuple: summary} for every series of ``name`` —
        the structured accessor (snapshot keys flatten labels into
        strings, which is ambiguous for label VALUES containing
        commas, e.g. signature tuples)."""
        with self._lock:
            keys = [k for k in self._histograms if k[0] == name]
            return {k[1]: self._hist_summary(self._histograms[k])
                    for k in keys}

    def find_counters(self, name: str) -> dict:
        """{label-pairs tuple: value} for every series of ``name``."""
        with self._lock:
            return {k[1]: v for k, v in self._counters.items()
                    if k[0] == name}

    def find_gauges(self, name: str) -> dict:
        """{label-pairs tuple: value} for every series of ``name``."""
        with self._lock:
            return {k[1]: v for k, v in self._gauges.items()
                    if k[0] == name}

    # -- multihost aggregation ----------------------------------------- #

    def aggregate_multihost(self) -> dict:
        """Cluster-wide view of counters and gauges: rank-max, rank-mean,
        rank-min over processes via ``process_allgather`` — the shape of
        the reference's mpiP per-rank AppTime/MPITime table (Report.pdf
        p.34: the table's value is exactly that it shows the spread over
        ranks, not one rank's number). Single-process runs return the
        local values in the same shape so consumers need no branch.

        Every process must call this with the same metric names in the
        same order (it is a collective when process_count > 1) — the
        registry enforces a sorted key order for exactly that reason.
        """
        import jax

        with self._lock:
            scalars = {**{("counter",) + k: v
                          for k, v in self._counters.items()},
                       **{("gauge",) + k: v
                          for k, v in self._gauges.items()}}
        keys = sorted(scalars)
        values = [scalars[k] for k in keys]
        if jax.process_count() > 1:
            import numpy as np
            from jax.experimental import multihost_utils
            gathered = multihost_utils.process_allgather(
                np.asarray(values, dtype=np.float64))
            gathered = gathered.reshape(jax.process_count(), len(keys))
        else:
            gathered = [values]
        out = {}
        for i, k in enumerate(keys):
            col = [row[i] for row in gathered]
            out[self._fmt(k[1:])] = {
                "rank_max": float(max(col)),
                "rank_mean": float(sum(col) / len(col)),
                "rank_min": float(min(col)),
            }
        return out


_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-default registry (module-level singleton)."""
    return _default_registry


def reset_registry() -> MetricsRegistry:
    """Fresh default registry (test isolation); returns the new one."""
    global _default_registry
    _default_registry = MetricsRegistry()
    return _default_registry
