"""Roofline ledger — the calibrated bandwidth bound as a package API.

``calibrated_bound_mcells`` used to live in ``bench.py``: computed once,
offline, for the headline record, and unreachable from the serving
stack. This module promotes it (bench.py imports it back) and
generalizes the accounting per (shape, route, dtype, device kind) in the
Williams-et-al roofline frame (PAPERS.md):

- **analytic bytes/cell-step** — what the route's memory structure says
  one cell-update *must* move through HBM (VMEM-resident amortization,
  band halo re-reads, per-step jnp streaming). This is the denominator
  of ROADMAP item 2's headline metric: bf16 storage or deeper temporal
  blocking is honest exactly when it shrinks this number.
- **mcells per HBM byte** — the reciprocal efficiency (structural, not
  measured: independent of clock speed, so a dtype/k knob can be judged
  before any wall-clock run).
- **roofline bound** — the tune_bands.md structural ceiling
  (VPU calibration x band halo-recompute factor), now honest about its
  validity domain: calibrations are keyed per device kind and dtype,
  and uncovered combinations return None instead of a guess.
- **launch stamping** — ``stamp_launch_row`` turns (cells, steps,
  elapsed) into achieved-vs-bound Mcells/s on every serve/mesh launch
  row and exports the ``perf_*`` gauge families.

Pure host-side arithmetic: nothing here touches a traced value, and the
planner calls go through the same ``ops.pallas_stencil`` entry points
the solver routes through, so the models track the actual kernel
configuration (docs/OBSERVABILITY.md "Performance observatory").
"""

from __future__ import annotations

from typing import Optional

#: dtype name -> element bytes for the storage models. Keyed by the
#: canonical names the request schema uses (serve/schema.py).
ITEMSIZE = {"float32": 4, "f32": 4, "bfloat16": 2, "bf16": 2,
            "float16": 2, "float64": 8}

#: Resident-kernel VPU calibration by row width (tune_bands.md round 4):
#: pure-VPU Mcells/s of the FMA step form with no HBM streaming or
#: strips — the numerator of the structural ceiling. Measured at
#: float32 on the tuned chip; ``_CALIB_TABLES`` keys the validity
#: domain explicitly.
VPU_CALIB_MCELLS = {512: 257_000.0, 1024: 254_000.0, 2048: 252_000.0,
                    4096: 248_000.0}

#: (device_kind, dtype) -> row-width calibration table. ``None`` device
#: kind means "the chip class tune_bands.md calibrated" (the default
#: accelerator this tree is tuned on); a deployment on new silicon adds
#: a row here after re-running the calibration, it does NOT inherit
#: another chip's numbers. float32-only today: bf16 compute changes the
#: VPU issue rate, so a bf16 bound requires its own calibration pass
#: (ROADMAP item 2) — until then the bound is honestly absent.
_CALIB_TABLES: dict = {(None, "float32"): VPU_CALIB_MCELLS}


def _itemsize(dtype: str) -> int:
    try:
        return ITEMSIZE[str(dtype)]
    except KeyError:
        raise ValueError(f"no itemsize for dtype {dtype!r}") from None


def resolve_route(nx: int, ny: int, method: str = "auto",
                  problem: str = "heat5") -> str:
    """The memory-structure route a (shape, method) actually executes:
    ``jnp`` | ``pallas`` (VMEM-resident) | ``band`` (HBM-streamed
    bands/window) | ``adi`` | ``mg``. Resolved through the SAME
    dispatch the runners use (``ensemble._pick_method`` +
    ``ps.fits_vmem`` for heat5; ``problems.runners.pick_route`` for
    registry families, which respects the declared kernel routes — so
    e.g. varcoef always resolves to jnp), so the analytic model below
    describes the program that compiles, not the method string the
    caller typed."""
    if method in ("adi", "mg", "jnp"):
        return method
    if problem != "heat5":
        from heat2d_tpu.problems.runners import pick_route
        return pick_route(problem, method, nx, ny)
    from heat2d_tpu.models import ensemble
    from heat2d_tpu.ops import pallas_stencil as ps
    m = ensemble._pick_method(method, nx, ny)
    if m == "pallas" and not ps.fits_vmem((nx, ny)):
        m = "band"
    return m


def analytic_bytes_per_cell_step(nx: int, ny: int, *,
                                 method: str = "auto",
                                 dtype: str = "float32",
                                 problem: str = "heat5") -> dict:
    """HBM bytes one cell-update must move, per route.

    Returns ``{"bytes_per_cell_step", "route", "model", "coarse"}``.
    ``coarse`` marks the implicit routes whose constant is a pass-count
    estimate (documented wide tolerance) rather than a streaming plan:

    - ``jnp``:    read u + write u each step -> ``2b`` (XLA fuses the
                  5-point stencil; coefficient rows are O(1/nx)).
    - ``pallas``: grid VMEM-resident across a ``DEFAULT_TSTEPS`` block
                  -> ``2b/T`` (load once, store once, T steps free).
    - ``band``:   per T-step block each band of ``bm`` rows is read
                  with its 2T halo rows and written back ->
                  ``b*(1 + (bm+2T)/bm)/T`` with bm from the same
                  panel/window planner the kernel uses.
    - ``adi``:    two directional sweeps per step, each building a RHS
                  and running the Thomas forward+back passes -> ~``8b``
                  (coarse).
    - ``mg``:     smoothing + residual + transfer over the level
                  hierarchy (4/3 geometric factor) -> ~``16b``
                  (coarse).

    ``problem``: registry families adjust the constants from their
    declared resource model (problems/base.py): the jnp route reads
    ``reads_per_step`` grid arrays (varcoef streams u + two
    coefficient fields -> 4b), and the band route's halo re-read
    scales with the family halo width (``bm + 2*w*T`` rows per band).
    heat5 keeps the exact pre-registry numbers and model strings.
    """
    b = _itemsize(dtype)
    route = resolve_route(nx, ny, method, problem=problem)
    w, reads = 1, 1
    if problem != "heat5":
        from heat2d_tpu.problems.base import spec_for
        spec = spec_for(problem)
        w, reads = spec.halo_width, spec.reads_per_step
    if route == "jnp":
        n_arrays = reads + 1.0   # reads + the written plane
        return {"bytes_per_cell_step": n_arrays * b, "route": route,
                "model": ("2b stream" if reads == 1
                          else f"{n_arrays:g}b stream "
                               f"(reads={reads})"),
                "coarse": False}
    if route == "adi":
        return {"bytes_per_cell_step": 8.0 * b, "route": route,
                "model": "~8b (2 sweeps x rhs+thomas)", "coarse": True}
    if route == "mg":
        return {"bytes_per_cell_step": 16.0 * b, "route": route,
                "model": "~16b (V-cycle passes x 4/3)", "coarse": True}
    from heat2d_tpu.ops import pallas_stencil as ps
    t = ps.DEFAULT_TSTEPS
    if route == "pallas":
        return {"bytes_per_cell_step": 2.0 * b / t, "route": route,
                "model": f"2b/T resident, T={t}", "coarse": False}
    # band / streaming window: same planners as calibrated_bound_mcells
    p, bm = ps.plan_panels(nx, ny, t)
    if p == 1:
        bm, _ = ps.plan_window_band(nx, ny, t)
    h = w * t
    bpcs = b * (1.0 + (bm + 2 * h) / bm) / t
    model = (f"band bm={bm}, T={t}" if w == 1
             else f"band bm={bm}, T={t}, w={w}")
    return {"bytes_per_cell_step": bpcs, "route": "band",
            "model": model, "coarse": False}


def mcells_per_hbm_byte(nx: int, ny: int, *, method: str = "auto",
                        dtype: str = "float32") -> float:
    """ROADMAP item 2's headline efficiency: cell-updates (in Mcells)
    bought per HBM byte moved. Structural — the reciprocal of the
    analytic bytes/cell-step, so bf16 storage doubling it (or temporal
    blocking k-folding it) shows up before any wall-clock run."""
    m = analytic_bytes_per_cell_step(nx, ny, method=method, dtype=dtype)
    return 1.0 / (1e6 * m["bytes_per_cell_step"])


def boundary_bytes(nx: int, ny: int, *, batch: int = 1,
                   dtype: str = "float32",
                   convergence: bool = False) -> dict:
    """Program-boundary traffic model: bytes a runner's arguments and
    results occupy (u0 + per-member cx/cy in; u out, + steps counters
    for convergence). This is what XLA's ``memory_analysis`` reports
    as argument/output sizes — the cross-check anchor for cost cards
    (exact on every backend, unlike op-level 'bytes accessed', which
    CPU lowering inflates with unfused intermediates)."""
    b = _itemsize(dtype)
    arg = batch * nx * ny * b + 2 * batch * b        # u0, cxs, cys
    out = batch * nx * ny * b + (4 * batch if convergence else 0)
    return {"argument_bytes": arg, "output_bytes": out,
            "total_bytes": arg + out}


def calibrated_bound_mcells(nx: int, ny: int, dtype: str = "float32",
                            device_kind: Optional[str] = None):
    """Structural ceiling for the streaming window route at this shape:
    VPU calibration at the route's row width x bm/(bm+2T) (the band
    halo-recompute factor — the tune_bands.md methodology). None when
    the shape is VMEM-resident (no streaming structure), the width is
    uncalibrated, or the (device kind, dtype) combination has no
    calibration table — an absent bound, never a guessed one. Uses the
    same planners the solver routes through, so the bound tracks the
    actual kernel configuration."""
    table = _CALIB_TABLES.get((device_kind, str(dtype)))
    if table is None:
        return None
    import heat2d_tpu.ops.pallas_stencil as ps

    if ps.fits_vmem((nx, ny)):
        return None
    t = ps.DEFAULT_TSTEPS
    p, bm = ps.plan_panels(nx, ny, t)
    nyp = ny // p
    if p == 1:
        bm, _ = ps.plan_window_band(nx, ny, t)
    calib = table.get(nyp)
    if calib is None:
        return None
    return calib * bm / (bm + 2 * t)


def roofline_bound(nx: int, ny: int, *, method: str = "auto",
                   dtype: str = "float32",
                   device_kind: Optional[str] = None):
    """The bound generalized per (shape, route, dtype, device kind):
    ``{"bound_mcells_per_s", "route", "source"}`` or None where no
    honest ceiling exists (non-streaming routes, uncalibrated widths,
    uncalibrated device/dtype). Today only the band/window route on
    the calibrated chip class at float32 has a number — exactly the
    domain tune_bands.md measured."""
    route = resolve_route(nx, ny, method)
    if route != "band":
        return None
    bound = calibrated_bound_mcells(nx, ny, dtype, device_kind)
    if bound is None:
        return None
    return {"bound_mcells_per_s": bound, "route": route,
            "source": "vpu-calib x bm/(bm+2T)"}


def stamp_launch_row(row: dict, registry=None, *, nx: int, ny: int,
                     steps: float, members: int, elapsed_s: float,
                     method: str = "auto", dtype: str = "float32",
                     signature: Optional[str] = None,
                     card: Optional[dict] = None,
                     problem: str = "heat5") -> dict:
    """Stamp one launch's roofline accounting into its launch-log row
    (``row["perf"]``) and the ``perf_*`` gauge families.

    ``steps`` may be fractional (convergence launches pass the mean
    steps-done across members). ``elapsed_s`` is host wall time around
    the launch — it includes dispatch + fence, so achieved Mcells/s is
    a floor, and a first launch's compile shows up as a collapsed
    figure (the row's ``first_launch`` flag disambiguates). Cheap host
    math on every launch; ``card`` (a cost card, when the perf
    observer is armed) contributes measured arithmetic intensity."""
    cells = float(members) * nx * ny
    achieved = (cells * steps / elapsed_s / 1e6
                if elapsed_s > 0 else 0.0)
    m = analytic_bytes_per_cell_step(nx, ny, method=method, dtype=dtype,
                                     problem=problem)
    # The calibrated ceiling is measured on the heat5 kernels; other
    # families' band programs do different arithmetic per sweep, so
    # the bound is honestly absent rather than borrowed.
    bound = (roofline_bound(nx, ny, method=method, dtype=dtype)
             if problem == "heat5" else None)
    perf = {
        "achieved_mcells_per_s": round(achieved, 3),
        "bound_mcells_per_s": (round(bound["bound_mcells_per_s"], 1)
                               if bound else None),
        "pct_of_bound": (round(100.0 * achieved
                               / bound["bound_mcells_per_s"], 2)
                         if bound else None),
        "bytes_per_cell_step": round(m["bytes_per_cell_step"], 4),
        "mcells_per_hbm_byte": round(
            1.0 / (1e6 * m["bytes_per_cell_step"]), 9),
        "route": m["route"],
        "elapsed_s": round(float(elapsed_s), 6),
    }
    if card is not None and card.get("arithmetic_intensity") is not None:
        perf["arithmetic_intensity"] = card["arithmetic_intensity"]
    row["perf"] = perf
    if registry is not None:
        sig = signature if signature is not None else str(
            row.get("signature"))
        registry.counter("perf_launches_stamped_total")
        registry.gauge("perf_achieved_mcells_per_s", achieved,
                       signature=sig)
        registry.gauge("perf_bytes_per_cell_step",
                       m["bytes_per_cell_step"], signature=sig)
        if bound is not None:
            registry.gauge("perf_pct_of_bound", perf["pct_of_bound"],
                           signature=sig)
        if perf.get("arithmetic_intensity") is not None:
            registry.gauge("perf_arithmetic_intensity",
                           perf["arithmetic_intensity"], signature=sig)
    return perf
