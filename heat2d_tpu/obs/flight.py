"""Crash flight recorder — a bounded black box for post-mortems.

When ``resil/chaos.py`` hard-kills a worker mid-flight, the only
evidence the process used to leave was an exit code. This module is
the black box: a bounded in-memory ring of the most recent spans and
structured events (plus a final metrics snapshot) that flushes to a
digest-sidecar'd JSONL file when the process dies violently — on
SIGTERM, on an unhandled exception (main or any thread), and at the
chaos kill points (``chaos.py`` calls ``crash_flush`` just before
``os._exit``). A post-mortem of a killed worker reconstructs its last
N seconds: which requests were in flight, what the wire had just
delivered, what the registry counted.

The ring is host-side and bounded (``deque(maxlen=ring)``) — a fleet
soak cannot grow it — and recording into it is lock-cheap append.
Like every obs hook it is opt-in (``install(...)`` or
``HEAT2D_FLIGHT_DIR`` in the environment) and free when off: the
tracer's tee (``note_span``) checks one module-level flag.

Flush format (``flight-<service>-<pid>.jsonl``): a ``flight_header``
line (schema, reason, service, pid, timestamps), the ring's entries
oldest-first, then a ``metrics_snapshot`` line when a registry was
attached. The sidecar (``<path>.digest.json``) carries the file's
sha256 + line count, so ``load_postmortem`` can prove the post-mortem
is complete and untorn — the same digest discipline as the
checkpoint files (io/binary.py)."""

from __future__ import annotations

import collections
import hashlib
import json
import os
import signal
import sys
import threading
import time
from typing import Optional

FLIGHT_SCHEMA = "heat2d-tpu/flight-recorder/v1"

ENV_DIR = "HEAT2D_FLIGHT_DIR"
ENV_RING = "HEAT2D_FLIGHT_RING"

DEFAULT_RING = 2048


class PostmortemCorruptError(ValueError):
    """A flight-recorder file failed its integrity checks (sidecar
    sha256 mismatch, truncation, missing sidecar) — a torn flush, not
    a trustworthy post-mortem."""


class FlightRecorder:
    """The ring + its flush. One per process; ``install()`` makes it
    the tracer's tee target and arms the crash hooks."""

    def __init__(self, path: str, *, ring: int = DEFAULT_RING,
                 service: str = "main", registry=None):
        self.path = path
        self.service = service
        self.registry = registry
        self._ring: collections.deque = collections.deque(maxlen=ring)
        self._lock = threading.Lock()
        self._flushed = False
        self.pid = os.getpid()
        self.started = time.time()

    # -- recording (hot path: bounded append) -------------------------- #

    def note(self, kind: str, **fields) -> None:
        """Append one structured event to the ring."""
        with self._lock:
            self._ring.append({"event": kind, "ts": time.time(),
                               **fields})

    def note_span(self, span_record: dict) -> None:
        """The tracer's tee: every finished span lands in the ring."""
        with self._lock:
            self._ring.append(span_record)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # -- flush --------------------------------------------------------- #

    def flush(self, reason: str) -> Optional[str]:
        """Write the black box + digest sidecar; returns the path.
        First flush wins (a SIGTERM racing an excepthook must not
        interleave two dumps); never raises — the recorder must not
        make a dying process die harder."""
        with self._lock:
            if self._flushed:
                return None
            self._flushed = True
            entries = list(self._ring)
        try:
            lines = [json.dumps({
                "event": "flight_header", "schema": FLIGHT_SCHEMA,
                "reason": reason, "service": self.service,
                "pid": self.pid, "started": self.started,
                "flushed": time.time(), "entries": len(entries)})]
            lines += [json.dumps(e) for e in entries]
            if self.registry is not None:
                try:
                    lines.append(json.dumps(
                        {"event": "metrics_snapshot",
                         **self.registry.snapshot()}))
                except Exception:   # noqa: BLE001 — snapshot is best-
                    pass            # effort inside a crash handler
            blob = ("\n".join(lines) + "\n").encode()
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            # the sidecar commits atomically too: a kill between the
            # two writes leaves body + .tmp sidecar, which
            # load_postmortem reports as missing-sidecar (torn), never
            # as a half-parsed digest
            side = self.path + ".digest.json"
            with open(side + ".tmp", "w") as f:
                json.dump({"schema": FLIGHT_SCHEMA, "reason": reason,
                           "sha256": hashlib.sha256(blob).hexdigest(),
                           "lines": len(lines)}, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(side + ".tmp", side)
            return self.path
        except Exception:   # noqa: BLE001 — see docstring
            return None


def load_postmortem(path: str, verify: bool = True) -> list:
    """The flushed entries (header first) as dicts. ``verify=True``
    (default) checks the sidecar digest and raises
    ``PostmortemCorruptError`` on any mismatch — a post-mortem you
    cannot trust is worse than none."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError as e:
        raise PostmortemCorruptError(f"{path}: unreadable: {e}") from e
    if verify:
        try:
            with open(path + ".digest.json") as f:
                side = json.load(f)
        except (OSError, ValueError) as e:
            raise PostmortemCorruptError(
                f"{path}: missing/unreadable digest sidecar: {e}") from e
        actual = hashlib.sha256(blob).hexdigest()
        if actual != side.get("sha256"):
            raise PostmortemCorruptError(
                f"{path}: sha256 mismatch (sidecar "
                f"{str(side.get('sha256'))[:12]}…, file {actual[:12]}…)")
    out = []
    for line in blob.decode(errors="replace").splitlines():
        if not line.strip():
            continue
        try:
            out.append(json.loads(line))
        except ValueError as e:
            raise PostmortemCorruptError(
                f"{path}: torn line in a digest-valid file: {e}") from e
    if verify and len(out) != side.get("lines"):
        raise PostmortemCorruptError(
            f"{path}: {len(out)} lines, sidecar says {side.get('lines')}")
    return out


def find_postmortems(dir: str) -> list:
    """Flight-recorder files under ``dir`` (newest last)."""
    import glob
    return sorted(glob.glob(os.path.join(dir, "flight-*.jsonl")))


# -- the process-global recorder --------------------------------------- #

_lock = threading.Lock()
_recorder: Optional[FlightRecorder] = None
_enabled = False


def install(recorder: Optional[FlightRecorder],
            crash_hooks: bool = True) -> None:
    """Make ``recorder`` the process black box (``None`` disarms) and,
    by default, arm the crash hooks (SIGTERM + unhandled exceptions).
    The chaos kill points flush via ``crash_flush`` regardless."""
    global _recorder, _enabled
    with _lock:
        _recorder, _enabled = recorder, recorder is not None
    if recorder is not None and crash_hooks:
        install_crash_hooks()


def uninstall() -> None:
    global _recorder, _enabled
    with _lock:
        _recorder, _enabled = None, False


def recorder() -> Optional[FlightRecorder]:
    return _recorder


def maybe_install_from_env(service: str = "main",
                           registry=None) -> Optional[FlightRecorder]:
    """Install a recorder iff ``HEAT2D_FLIGHT_DIR`` is set — how fleet
    workers arm their black box from the router CLI's environment.
    Idempotent; returns the active recorder (or None)."""
    with _lock:
        if _recorder is not None:
            return _recorder
    d = os.environ.get(ENV_DIR)
    if not d:
        return None
    try:
        ring = int(os.environ.get(ENV_RING) or DEFAULT_RING)
    except ValueError:
        ring = DEFAULT_RING
    rec = FlightRecorder(
        os.path.join(d, f"flight-{service}-{os.getpid()}.jsonl"),
        ring=ring, service=service, registry=registry)
    install(rec)
    return rec


# -- hooks (cheap no-ops when off) ------------------------------------- #

def note(kind: str, **fields) -> None:
    if _enabled and _recorder is not None:
        _recorder.note(kind, **fields)


def note_span(span_record: dict) -> None:
    if _enabled and _recorder is not None:
        _recorder.note_span(span_record)


def crash_flush(reason: str) -> Optional[str]:
    """Flush the black box if one is installed; safe to call from any
    crash path (chaos kill points, signal handlers) — never raises,
    no-op without a recorder or after the first flush."""
    rec = _recorder
    if rec is None:
        return None
    return rec.flush(reason)


_hooks_installed = False


def install_crash_hooks() -> None:
    """Arm SIGTERM + unhandled-exception flushing (idempotent). The
    previous handlers/hooks still run — the recorder observes the
    death, it does not change it. SIGKILL (the supervisor's fence)
    remains uncatchable by design; the chaos ``os._exit`` kills flush
    via ``crash_flush`` instead."""
    global _hooks_installed
    with _lock:
        if _hooks_installed:
            return
        _hooks_installed = True

    prev_except = sys.excepthook

    def _excepthook(tp, val, tb):
        crash_flush(f"unhandled:{tp.__name__}")
        prev_except(tp, val, tb)

    sys.excepthook = _excepthook

    prev_thread = threading.excepthook

    def _thread_hook(args):
        crash_flush(f"unhandled_thread:{args.exc_type.__name__}")
        prev_thread(args)

    threading.excepthook = _thread_hook

    try:
        prev_term = signal.getsignal(signal.SIGTERM)

        def _on_term(signum, frame):
            if prev_term is signal.SIG_IGN:
                # the process chose to SURVIVE SIGTERM: observing the
                # signal must not spend the one-shot flush, and must
                # certainly not start killing a process that ignores
                # it — the recorder observes deaths, it never causes
                # them
                return
            crash_flush("sigterm")
            if callable(prev_term):
                prev_term(signum, frame)
            else:
                # default disposition: die with the conventional code
                os._exit(128 + signum)

        signal.signal(signal.SIGTERM, _on_term)
    except (ValueError, OSError):
        pass    # not the main thread / unsupported platform
