"""heat2d-tpu-perf — the performance observatory console.

Four modes over the obs/perf + obs/roofline instruments:

- ``--card NXxNY``: compile the SAME serve-batch runner the engine
  dispatches (``models.ensemble.batch_runner``) for one signature and
  dump its XLA cost card — FLOPs, bytes accessed, argument/output/temp
  sizes — beside the analytic roofline models. ``--gate-model-pct P``
  turns the dump into a gate: exit 1 unless the program-boundary bytes
  XLA reports agree with the analytic boundary model within P% (the CI
  perf-gate's first leg — a route whose memory structure drifted from
  its model fails here before any benchmark notices).
- ``--roofline NXxNY[,NXxNY...]``: the analytic ledger per shape —
  route, bytes/cell-step, Mcells-per-HBM-byte, calibrated bound where
  one exists (band route on the calibrated device class).
- ``--soak S``: an in-process serve soak driving the anomaly sentinel
  through the real ControlPlane tick. ``--chaos-slow X`` arms a
  launch-latency injection (resil/chaos.py) at the soak midpoint;
  ``--expect-anomaly`` requires the sentinel to flag it within
  ``--max-detect-windows`` windows of arming, ``--expect-clean``
  requires ZERO findings — the two CI soak legs. A ``kind="perf"``
  record (cards, findings, control decisions, duty cycle, verdict)
  goes to ``--metrics-out``.
- ``--watch DIR``: live console over a trace directory a ``--perf``
  serve run is writing — cost cards joined with launch-span duty per
  lane, refreshed in place.

Everything runs host-side; the only device work is the soak's real
solves and ``--card``'s (cached) compile.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

USAGE_HINT = ("one of --card, --roofline, --soak, --watch is required")


def _parse_shape(s: str) -> tuple:
    try:
        nx, ny = s.lower().split("x")
        return int(nx), int(ny)
    except ValueError:
        raise SystemExit(f"bad shape {s!r} (want NXxNY)") from None


# -- --card ------------------------------------------------------------- #

def cmd_card(args) -> int:
    import jax
    import jax.numpy as jnp

    from heat2d_tpu.models import ensemble
    from heat2d_tpu.obs import perf
    from heat2d_tpu.obs.metrics import MetricsRegistry

    nx, ny = _parse_shape(args.card)
    batch = args.batch
    reg = MetricsRegistry()
    runner = ensemble.batch_runner(nx, ny, args.steps, args.method,
                                   convergence=False, interval=0,
                                   sensitivity=0.0)
    # Abstract operands: only avals matter to lower(), so the card
    # never allocates the grid (a 4096^2 card costs a trace, not HBM).
    sds = jax.ShapeDtypeStruct
    ops = (sds((batch, nx, ny), jnp.float32),
           sds((batch,), jnp.float32), sds((batch,), jnp.float32))
    card = perf.extract_cost_card(
        runner, ops, registry=reg,
        meta={"signature": f"card:{nx}x{ny}x{args.steps}:{args.method}",
              "nx": nx, "ny": ny, "steps": args.steps,
              "method": args.method, "convergence": False,
              "capacity": batch, "dtype": "float32", "route": "batch"})
    if card is None:
        print("cost-card extraction failed (no analysis available)",
              file=sys.stderr)
        return 1
    print(json.dumps(card, indent=None if args.json else 2))
    if args.gate_model_pct is not None:
        agree = (card.get("model") or {}).get("boundary_agreement_pct")
        if agree is None:
            print("gate: no boundary agreement figure", file=sys.stderr)
            return 1
        if abs(agree - 100.0) > args.gate_model_pct:
            print(f"gate: boundary bytes {agree}% of model, outside "
                  f"+-{args.gate_model_pct}%", file=sys.stderr)
            return 1
        print(f"gate: boundary agreement {agree}% within "
              f"+-{args.gate_model_pct}%", file=sys.stderr)
    return 0


# -- --roofline --------------------------------------------------------- #

def cmd_roofline(args) -> int:
    from heat2d_tpu.obs import roofline

    rows = []
    for shape in args.roofline.split(","):
        nx, ny = _parse_shape(shape)
        m = roofline.analytic_bytes_per_cell_step(
            nx, ny, method=args.method)
        bound = roofline.roofline_bound(nx, ny, method=args.method)
        rows.append({
            "shape": f"{nx}x{ny}", "route": m["route"],
            "model": m["model"], "coarse": m["coarse"],
            "bytes_per_cell_step": round(m["bytes_per_cell_step"], 4),
            "mcells_per_hbm_byte": round(
                1.0 / (1e6 * m["bytes_per_cell_step"]), 9),
            "bound_mcells_per_s": (
                round(bound["bound_mcells_per_s"], 1)
                if bound else None),
        })
    if args.json:
        print(json.dumps(rows))
        return 0
    print("| shape | route | bytes/cell-step | Mcells/HBM-byte "
          "| bound Mcells/s | model |")
    print("|---|---|---|---|---|---|")
    for r in rows:
        b = (f"{r['bound_mcells_per_s']:.4g}"
             if r["bound_mcells_per_s"] else "—")
        print(f"| {r['shape']} | {r['route']} "
              f"| {r['bytes_per_cell_step']:.4g} "
              f"| {r['mcells_per_hbm_byte']:.3g} | {b} "
              f"| {r['model']} |")
    return 0


# -- --soak ------------------------------------------------------------- #

class _StubFleet:
    """The minimal fleet surface ControlPlane touches, for a soak with
    no worker processes: shed is recorded, the generation book is
    empty (vacuously valid serving invariant)."""

    class _Sup:
        @staticmethod
        def alive_slots():
            return []

        @staticmethod
        def generations_snapshot():
            return []

    def __init__(self):
        self.sup = self._Sup()
        self.shed = None

    def set_preemptive_shed(self, watermark):
        self.shed = watermark


def cmd_soak(args) -> int:
    from heat2d_tpu.control.plane import ControlPlane
    from heat2d_tpu.obs import perf, tracing
    from heat2d_tpu.obs.metrics import MetricsRegistry
    from heat2d_tpu.obs.record import write_run_jsonl
    from heat2d_tpu.resil import chaos
    from heat2d_tpu.serve.schema import SolveRequest
    from heat2d_tpu.serve.server import SolveServer

    reg = MetricsRegistry()
    observer = perf.PerfObserver(registry=reg, dir=args.trace_dir,
                                 service="perf-soak")
    perf.install(observer)
    sampler = None
    if args.trace_dir:
        tracing.install(tracing.Tracer(args.trace_dir, service="serve"))
        sampler = perf.DutyCycleSampler(reg, window_s=2.0)
        tracing.add_span_tap(sampler.feed)
        sampler.start()

    sentinel = perf.AnomalySentinel(
        warmup=args.warmup, sustain=args.sustain)
    fleet = _StubFleet()
    plane = ControlPlane(fleet, registry=reg, sentinel=sentinel)

    server = SolveServer(max_batch=4, registry=reg).start()
    windows = max(int(args.soak / args.window), 2 * args.warmup + 4)
    arm_at = windows // 2 if args.chaos_slow else None
    detect_at = None
    n_req = 0
    try:
        for w in range(windows):
            if arm_at is not None and w == arm_at:
                chaos.install(chaos.ChaosConfig(
                    launch_latency_s=args.chaos_slow), registry=reg)
            for _ in range(args.per_window):
                # a cx jitter below any physical relevance keeps the
                # SIGNATURE constant (one sentinel series) while
                # defeating the result cache — every solve launches
                n_req += 1
                server.solve(SolveRequest(
                    nx=args.grid, ny=args.grid,
                    steps=args.grid_steps, method="jnp",
                    cx=0.1 + 1e-9 * n_req))
            before = len(sentinel.findings)
            plane.tick()
            if (detect_at is None
                    and len(sentinel.findings) > before):
                detect_at = w
            # pacing keeps the windowed rate metric meaningful without
            # stretching CI: the injected latency dominates when armed
            time.sleep(args.window if args.soak >= windows * args.window
                       else 0.05)
    finally:
        server.stop(drain=True)
        chaos.uninstall()
        if sampler is not None:
            tracing.remove_span_tap(sampler.feed)
            sampler.stop()
        tracing.install(None)
        perf.uninstall()

    findings = list(sentinel.findings)
    decisions = [d for d in plane.decisions
                 if d["action"] == "perf_anomaly"]
    detect_windows = (detect_at - arm_at + 1
                      if detect_at is not None and arm_at is not None
                      else None)
    verdict = {
        "windows": windows, "armed_at_window": arm_at,
        "findings": len(findings),
        "detection_windows": detect_windows,
    }
    print(json.dumps({"verdict": verdict, "findings": findings},
                     indent=None if args.json else 2))

    if args.metrics_out:
        write_run_jsonl(reg, args.metrics_out, "perf", {
            "soak": verdict, "findings": findings,
            "control_decisions": decisions,
            "duty": sampler.snapshot() if sampler else None,
            "cost_cards": observer.cards(),
        })

    if args.expect_anomaly:
        if not findings or not decisions:
            print("expected an anomaly finding in the control plane "
                  "decision log; got none", file=sys.stderr)
            return 1
        if (detect_windows is None
                or detect_windows > args.max_detect_windows):
            print(f"detection took {detect_windows} windows "
                  f"(> {args.max_detect_windows})", file=sys.stderr)
            return 1
    if args.expect_clean and findings:
        print(f"expected a clean soak; sentinel flagged "
              f"{len(findings)} finding(s): {findings[0]}",
              file=sys.stderr)
        return 1
    return 0


# -- --watch ------------------------------------------------------------ #

def _recent_launch_duty(trace_dir: str, window_s: float) -> dict:
    """Per-lane launch duty over the trailing window, read cold from
    the span files (the offline twin of DutyCycleSampler's live tap)."""
    now = time.time()
    lo = now - window_s
    by_lane: dict = {}
    for path in glob.glob(os.path.join(trace_dir, "spans-*.jsonl")):
        try:
            with open(path, errors="replace") as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if (rec.get("event") != "span"
                            or rec.get("kind") != "launch"
                            or rec.get("t1", 0) < lo):
                        continue
                    lane = (f"{rec.get('service', '?')}:"
                            f"{rec.get('pid', 0)}")
                    a = max(float(rec["t0"]), lo)
                    b = min(float(rec["t1"]), now)
                    if b > a:
                        by_lane[lane] = by_lane.get(lane, 0.0) + b - a
        except OSError:
            continue
    return {lane: min(1.0, busy / window_s)
            for lane, busy in by_lane.items()}


def cmd_watch(args) -> int:
    from heat2d_tpu.obs.trace_cli import load_cost_cards

    ticks = 0
    try:
        while True:
            cards = load_cost_cards(args.watch)
            duty = _recent_launch_duty(args.watch, args.watch_window)
            out = ["\x1b[2J\x1b[H" if not args.json else "",
                   f"perf watch — {args.watch} "
                   f"({len(cards)} card(s))"]
            for lane, d in sorted(duty.items()):
                out.append(f"  duty {lane}: {100 * d:5.1f}%")
            for sig, c in sorted(cards.items()):
                m = c.get("model") or {}
                out.append(
                    f"  {sig}: {c.get('bytes_accessed', 0):.3g} B "
                    f"accessed, AI={c.get('arithmetic_intensity')}, "
                    f"boundary {m.get('boundary_agreement_pct')}% "
                    f"of model")
            print("\n".join(filter(None, out)), flush=True)
            ticks += 1
            if args.watch_ticks and ticks >= args.watch_ticks:
                return 0
            time.sleep(args.watch_interval)
    except KeyboardInterrupt:
        return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="heat2d-tpu-perf",
        description="cost cards, roofline ledger, anomaly-sentinel "
                    "soak, live watch")
    p.add_argument("--card", metavar="NXxNY",
                   help="dump the cost card of the serve-batch runner "
                        "at this shape")
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--method", default="auto")
    p.add_argument("--batch", type=int, default=1,
                   help="batch capacity the card describes")
    p.add_argument("--gate-model-pct", type=float, default=None,
                   help="exit 1 unless boundary bytes agree with the "
                        "analytic model within this percent")
    p.add_argument("--roofline", metavar="SHAPES",
                   help="comma-separated NXxNY list: analytic ledger")
    p.add_argument("--soak", type=float, default=None, metavar="S",
                   help="run an S-second serve soak with the sentinel")
    p.add_argument("--window", type=float, default=0.25,
                   help="sentinel window pacing during --soak")
    p.add_argument("--per-window", type=int, default=3,
                   help="requests per soak window")
    p.add_argument("--grid", type=int, default=48,
                   help="soak request grid edge")
    p.add_argument("--grid-steps", type=int, default=30,
                   help="soak request step count")
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--sustain", type=int, default=2)
    p.add_argument("--chaos-slow", type=float, default=None,
                   metavar="SEC", help="inject this launch latency at "
                                       "the soak midpoint")
    p.add_argument("--expect-anomaly", action="store_true",
                   help="exit 1 unless the sentinel flags the "
                        "injection fast enough")
    p.add_argument("--expect-clean", action="store_true",
                   help="exit 1 if the sentinel flags anything")
    p.add_argument("--max-detect-windows", type=int, default=3)
    p.add_argument("--trace-dir", default=None,
                   help="soak trace/card dir (also arms the duty "
                        "sampler)")
    p.add_argument("--metrics-out", default=None,
                   help="write the kind=perf run record JSONL here")
    p.add_argument("--watch", metavar="DIR",
                   help="live console over a --perf run's trace dir")
    p.add_argument("--watch-interval", type=float, default=1.0)
    p.add_argument("--watch-window", type=float, default=5.0)
    p.add_argument("--watch-ticks", type=int, default=0,
                   help="stop after N refreshes (0 = until ^C)")
    p.add_argument("--json", action="store_true",
                   help="single-line JSON output")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.card:
        return cmd_card(args)
    if args.roofline:
        return cmd_roofline(args)
    if args.soak is not None:
        return cmd_soak(args)
    if args.watch:
        return cmd_watch(args)
    print(USAGE_HINT, file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
