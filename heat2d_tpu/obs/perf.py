"""The performance observatory: cost cards, duty-cycle sampling, and
the online anomaly sentinel.

Three instruments, one discipline (free when off, jaxpr-pinned like
every obs hook — tests/test_perf.py):

- **Cost cards** (``extract_cost_card`` / ``PerfObserver``): at a
  runner's first launch per (signature, capacity, route), re-lower the
  ALREADY-COMPILED jit callable and read XLA's own cost/memory
  analysis — FLOPs, op-level bytes accessed, argument/output/temp
  sizes, generated-code size — cross-checked against the analytic
  models in ``obs.roofline``. Program-boundary bytes (argument +
  output) agree near-exactly with the boundary model on every backend;
  op-level 'bytes accessed' is recorded with its agreement ratio but
  only asserted where the kernel is an opaque custom call (TPU), since
  CPU lowering counts unfused intermediates. XLA counts a while/fori
  body ONCE regardless of trip count, so per-step byte figures here
  are per body application, never multiplied by steps.
- **Duty-cycle sampler** (``DutyCycleSampler``): a background thread
  fed by the distributed tracer's span stream (``tracing.add_span_
  tap``) integrating closed launch-span intervals over a sliding
  window per (service, pid) lane — the live "how busy is each lane"
  gauge. Launch spans are emitted retroactively after a launch
  completes (serve/server.py), so the sampler merges closed intervals
  rather than counting open spans. Free when off: the tap list is
  empty unless a sampler is started, and the tracer's write path
  checks it with one truthiness test.
- **Anomaly sentinel** (``AnomalySentinel``): EWMA + MAD per
  (signature, metric) over windowed request rate, windowed mean
  latency, cumulative p99, and roofline fraction. Robust scale
  (1.4826 x MAD, floored at ``rel_floor`` x baseline) keeps the score
  dimensionless; the baseline is frozen while a window scores
  anomalous so an outburst cannot poison its own reference; a finding
  needs ``sustain`` consecutive anomalous windows; zero-traffic
  windows are no evidence (the BurnWindow convention). Findings land
  in the ControlPlane decision log beside burn (control/plane.py
  ``sentinel=``).

Everything here is host-side Python over the metrics registry and the
span feed; nothing touches a traced value.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time
from typing import Optional

log = logging.getLogger("heat2d_tpu.obs")

PERF_SCHEMA = "heat2d-tpu/cost-card/v1"

#: extraction failure placeholder cached in the card book so a runner
#: that cannot be lowered is probed once, not per launch
_FAILED = object()


def extract_cost_card(runner, args, *, meta: dict,
                      registry=None) -> Optional[dict]:
    """One cost card from XLA's compile-time analyses.

    ``runner`` is a jit callable (or an object carrying one as
    ``.jitted`` — the mesh/spatial runners); ``args`` the launch
    operands (concrete arrays or ShapeDtypeStructs — only avals
    matter). Lowering retraces the SAME function the launch calls, so
    the traced program is byte-identical whether extraction runs or
    not (the jaxpr pin), and jax's compile cache absorbs most of the
    cost. Returns None (never raises) when the backend/runner offers
    no analysis — counted as ``perf_card_failures_total{stage}``.
    """
    def _fail(stage: str, err) -> None:
        if registry is not None:
            registry.counter("perf_card_failures_total", stage=stage)
        log.debug("cost-card extraction failed at %s: %s", stage, err)

    import jax

    target = getattr(runner, "jitted", runner)
    if not hasattr(target, "lower"):
        _fail("no-lower", type(target).__name__)
        return None
    try:
        compiled = target.lower(*args).compile()
    except Exception as e:  # noqa: BLE001 — observability must not throw
        _fail("compile", e)
        return None
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        ca = ca or {}
    except Exception as e:  # noqa: BLE001
        _fail("cost-analysis", e)
        ca = {}
    try:
        ma = compiled.memory_analysis()
    except Exception as e:  # noqa: BLE001
        _fail("memory-analysis", e)
        ma = None

    def _mem(field: str) -> int:
        return int(getattr(ma, field, 0) or 0)

    flops = float(ca.get("flops", 0.0) or 0.0)
    bytes_accessed = float(ca.get("bytes accessed", 0.0) or 0.0)
    arg_b = _mem("argument_size_in_bytes")
    out_b = _mem("output_size_in_bytes")
    tmp_b = _mem("temp_size_in_bytes")
    card = {
        "schema": PERF_SCHEMA,
        **meta,
        "backend": jax.default_backend(),
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "argument_bytes": arg_b,
        "output_bytes": out_b,
        "temp_bytes": tmp_b,
        "peak_bytes": arg_b + out_b + tmp_b,
        "generated_code_bytes": _mem("generated_code_size_in_bytes"),
        "arithmetic_intensity": (round(flops / bytes_accessed, 4)
                                 if bytes_accessed > 0 else None),
    }
    try:
        card["device_kind"] = jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001
        card["device_kind"] = None
    nx, ny = meta.get("nx"), meta.get("ny")
    batch = int(meta.get("capacity") or meta.get("batch") or 1)
    if nx and ny:
        from heat2d_tpu.obs import roofline
        bb = roofline.boundary_bytes(
            nx, ny, batch=batch,
            dtype=meta.get("dtype", "float32"),
            convergence=bool(meta.get("convergence", False)))
        measured = arg_b + out_b
        m = roofline.analytic_bytes_per_cell_step(
            nx, ny, method=meta.get("method", "auto"),
            dtype=meta.get("dtype", "float32"))
        card["model"] = {
            "boundary_bytes": bb["total_bytes"],
            "measured_boundary_bytes": measured,
            "boundary_agreement_pct": (
                round(100.0 * measured / bb["total_bytes"], 2)
                if bb["total_bytes"] else None),
            "bytes_per_cell_step": round(m["bytes_per_cell_step"], 4),
            "route": m["route"],
            "coarse": m["coarse"],
            # loop bodies are counted once by XLA, so this is op-level
            # bytes per cell per BODY application (2b = perfectly fused
            # stream; CPU lowering sits well above it)
            "hlo_bytes_per_cell": (
                round(bytes_accessed / (batch * nx * ny), 3)
                if bytes_accessed > 0 else None),
        }
    return card


class PerfObserver:
    """The card book: dedup-by-key cost-card extraction at first
    launch, optional JSONL persistence beside the trace spans
    (``cost-cards-<service>-<pid>.jsonl``, the file heat2d-tpu-trace
    joins on), ``perf_cost_cards_total`` accounting."""

    def __init__(self, registry=None, dir: Optional[str] = None,
                 service: str = "perf"):
        self.registry = registry
        self.dir = dir
        self.service = service
        self._lock = threading.Lock()
        self._cards: dict = {}          # key -> card dict | _FAILED
        self._file = None
        if dir:
            os.makedirs(dir, exist_ok=True)
            self._path = os.path.join(
                dir, f"cost-cards-{service}-{os.getpid()}.jsonl")
        else:
            self._path = None

    @staticmethod
    def _key(meta: dict) -> tuple:
        return (meta.get("signature"), meta.get("capacity"),
                meta.get("route"))

    def observe(self, runner, args, meta: dict) -> Optional[dict]:
        """Card for (signature, capacity, route): cached after the
        first extraction, including cached failure — a launch path
        never pays the probe twice."""
        key = self._key(meta)
        with self._lock:
            hit = self._cards.get(key)
        if hit is not None:
            return None if hit is _FAILED else hit
        card = extract_cost_card(runner, args, meta=meta,
                                 registry=self.registry)
        with self._lock:
            # double-checked: a racing launch may have filled the slot
            hit = self._cards.get(key)
            if hit is not None:
                return None if hit is _FAILED else hit
            self._cards[key] = card if card is not None else _FAILED
        if card is None:
            return None
        if self.registry is not None:
            self.registry.counter("perf_cost_cards_total",
                                  route=str(card.get("route")
                                            or meta.get("route")
                                            or "batch"))
        self._persist(card)
        return card

    def card_for(self, signature, capacity=None,
                 route=None) -> Optional[dict]:
        with self._lock:
            hit = self._cards.get((signature, capacity, route))
        return None if hit is None or hit is _FAILED else hit

    def cards(self) -> list:
        with self._lock:
            return [c for c in self._cards.values()
                    if c is not _FAILED]

    def snapshot(self) -> dict:
        return {"schema": PERF_SCHEMA, "cards": self.cards()}

    def _persist(self, card: dict) -> None:
        if self._path is None:
            return
        line = json.dumps(card) + "\n"
        with self._lock:
            if self._file is None:
                self._file = open(self._path, "a", encoding="utf-8")
            self._file.write(line)
            self._file.flush()

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None


# -- module-level arming (the tracing.install pattern) ----------------- #

_lock = threading.Lock()
_observer: Optional[PerfObserver] = None
_env_checked = False


def install(obs: PerfObserver) -> None:
    global _observer
    with _lock:
        _observer = obs


def uninstall() -> None:
    global _observer, _env_checked
    with _lock:
        if _observer is not None:
            _observer.close()
        _observer = None
        _env_checked = True     # an explicit uninstall wins over env


def activate_from_env() -> None:
    """Arm from ``HEAT2D_PERF_DIR`` (cards persisted there) or
    ``HEAT2D_PERF=1`` (in-memory book only) — once per process, like
    ``tracing.activate_from_env``."""
    global _env_checked, _observer
    with _lock:
        if _env_checked or _observer is not None:
            return
        _env_checked = True
        d = os.environ.get("HEAT2D_PERF_DIR")
        if not d and os.environ.get("HEAT2D_PERF") != "1":
            return
        from heat2d_tpu.obs.metrics import get_registry
        _observer = PerfObserver(registry=get_registry(),
                                 dir=d or None, service="env")


def enabled() -> bool:
    activate_from_env()
    return _observer is not None


def observer() -> Optional[PerfObserver]:
    activate_from_env()
    return _observer


def observe_launch(runner, args, *, meta: dict) -> Optional[dict]:
    """The launch-path hook: no-op (None) when no observer is armed."""
    obs = observer()
    if obs is None:
        return None
    return obs.observe(runner, args, meta)


def card_for(signature, capacity=None, route=None) -> Optional[dict]:
    obs = observer()
    if obs is None:
        return None
    return obs.card_for(signature, capacity, route)


# -- duty-cycle sampler ------------------------------------------------ #

class DutyCycleSampler:
    """Launch-occupancy duty cycle per (service, pid) lane from the
    tracer's span feed.

    Wire it with ``tracing.add_span_tap(sampler.feed)`` and
    ``sampler.start()``. ``feed`` runs on whatever thread emits a span
    — it does ONE kind check and a deque append under the lock.
    Serve launch spans carry epoch t0/t1 and are emitted after the
    launch completes, so each ``_sample`` merges the closed intervals
    that overlap the trailing window (plus any still-open
    ``span_start``) into per-lane busy time / window. Exported as
    ``perf_duty_cycle{lane=...}`` + ``perf_duty_samples_total``."""

    def __init__(self, registry=None, *, window_s: float = 2.0,
                 interval_s: float = 0.25,
                 span_kinds: tuple = ("launch",)):
        self.registry = registry
        self.window_s = float(window_s)
        self.interval_s = float(interval_s)
        self._kinds = frozenset(span_kinds)
        self._lock = threading.Lock()
        self._closed: collections.deque = collections.deque()
        self._open: dict = {}           # span_id -> (t0, lane)
        self._duty: dict = {}           # lane -> last sampled duty
        self.samples = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # the tracer tap — hot-ish path, keep tiny
    def feed(self, rec: dict) -> None:
        if rec.get("kind") not in self._kinds:
            return
        lane = f"{rec.get('service', '?')}:{rec.get('pid', 0)}"
        ev = rec.get("event")
        with self._lock:
            if ev == "span":
                self._open.pop(rec.get("span_id"), None)
                self._closed.append(
                    (float(rec["t0"]), float(rec["t1"]), lane))
            elif ev == "span_start":
                self._open[rec.get("span_id")] = (
                    float(rec["t0"]), lane)

    def _sample(self, now: Optional[float] = None) -> dict:
        # spans carry epoch timestamps (tracing.Tracer.epoch_of)
        now = time.time() if now is None else now
        lo = now - self.window_s
        with self._lock:
            while self._closed and self._closed[0][1] < lo:
                self._closed.popleft()
            spans = list(self._closed)
            spans.extend((t0, now, lane)
                         for t0, lane in self._open.values())
        by_lane: dict = {}
        for t0, t1, lane in spans:
            a, b = max(t0, lo), min(t1, now)
            if b > a:
                by_lane.setdefault(lane, []).append((a, b))
        duty = {}
        for lane, ivals in by_lane.items():
            ivals.sort()
            busy, cur0, cur1 = 0.0, ivals[0][0], ivals[0][1]
            for a, b in ivals[1:]:
                if a > cur1:
                    busy += cur1 - cur0
                    cur0, cur1 = a, b
                else:
                    cur1 = max(cur1, b)
            busy += cur1 - cur0
            duty[lane] = min(1.0, busy / self.window_s)
        # lanes that went idle decay to 0 instead of holding stale duty
        for lane in self._duty:
            duty.setdefault(lane, 0.0)
        self._duty = duty
        self.samples += 1
        if self.registry is not None:
            self.registry.counter("perf_duty_samples_total")
            for lane, d in duty.items():
                self.registry.gauge("perf_duty_cycle", d, lane=lane)
        return duty

    def duty(self, lane: Optional[str] = None):
        if lane is None:
            return dict(self._duty)
        return self._duty.get(lane, 0.0)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def _loop() -> None:
            while not self._stop.wait(self.interval_s):
                self._sample()

        self._thread = threading.Thread(
            target=_loop, name="heat2d-perf-duty", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)

    def snapshot(self) -> dict:
        return {"duty": dict(self._duty), "samples": self.samples,
                "window_s": self.window_s}


# -- anomaly sentinel -------------------------------------------------- #

class AnomalySentinel:
    """EWMA + MAD change detection per (signature, metric).

    Metrics per tick (each skipped when unobservable, and a
    zero-traffic window contributes NO evidence — the BurnWindow
    convention, so a drained queue never reads as a regression):

    - ``rate_rps``       windowed request rate (CounterDeltas over
                         ``serve_signature_requests_total``); DOWN bad.
    - ``latency_mean_s`` windowed mean latency (sum/count deltas of
                         ``serve_signature_latency_s`` — exact, and
                         immune to the cumulative reservoir's
                         first-compile spike); UP bad.
    - ``p99_s``          the cumulative tail of the same histogram
                         (Dean & Barroso's number); UP bad.
    - ``roofline_pct``   latest ``perf_pct_of_bound`` gauge (absent
                         off-accelerator); DOWN bad.

    Score = bad-direction deviation / robust scale, with scale =
    max(1.4826 x MAD over recent history, ``rel_floor`` x |EWMA|).
    The baseline is NOT updated by a window that scores anomalous
    (outbursts must not become their own reference); a finding fires
    after ``sustain`` consecutive anomalous windows, once per episode.
    Defaults (k=5, rel_floor=0.5, sustain=2, warmup=3) flag a
    sustained >250% deviation — conservative enough for a zero-false-
    positive healthy soak, and a seeded ``--chaos-slow`` 25x latency
    regression scores ~48 (docs/OBSERVABILITY.md)."""

    METRIC_DIRECTION = {"rate_rps": -1, "latency_mean_s": +1,
                        "p99_s": +1, "roofline_pct": -1}

    def __init__(self, *, alpha: float = 0.3, k: float = 5.0,
                 rel_floor: float = 0.5, sustain: int = 2,
                 warmup: int = 3, history: int = 64,
                 clock=time.monotonic):
        from heat2d_tpu.obs.metrics import CounterDeltas
        self.alpha, self.k = alpha, k
        self.rel_floor, self.sustain = rel_floor, sustain
        self.warmup, self.history = warmup, history
        self._clock = clock
        self._deltas = CounterDeltas()
        self._hist_last: dict = {}      # sig -> (sum, count)
        self._state: dict = {}          # (sig, metric) -> state dict
        self._last_t: Optional[float] = None
        self.findings: list = []

    @staticmethod
    def _sig(label_pairs: tuple) -> Optional[str]:
        return dict(label_pairs).get("signature")

    def tick(self, registry) -> list:
        """Evaluate one window; returns NEW findings (also appended to
        ``self.findings``). Call at a steady cadence (the ControlPlane
        tick)."""
        now = self._clock()
        dt = (now - self._last_t) if self._last_t is not None else None
        self._last_t = now

        per_sig: dict = {}
        for labels, d in self._deltas.tick(
                registry, "serve_signature_requests_total").items():
            sig = self._sig(labels)
            if sig is not None:
                per_sig[sig] = per_sig.get(sig, 0.0) + d
        lat = {self._sig(k): v for k, v in registry.find_histograms(
            "serve_signature_latency_s").items()}
        frac = {self._sig(k): v for k, v in registry.find_gauges(
            "perf_pct_of_bound").items()}

        out = []
        for sig, d in per_sig.items():
            if d <= 0 or dt is None or dt <= 0:
                continue            # zero traffic / first tick: no window
            obs = {"rate_rps": d / dt}
            summ = lat.get(sig)
            if summ is not None:
                s, c = float(summ["sum"]), float(summ["count"])
                ps, pc = self._hist_last.get(sig, (0.0, 0.0))
                self._hist_last[sig] = (s, c)
                if c > pc:
                    obs["latency_mean_s"] = (s - ps) / (c - pc)
                p99 = summ.get("p99")
                if p99 == p99:      # not NaN
                    obs["p99_s"] = float(p99)
            f = frac.get(sig)
            if f is not None:
                obs["roofline_pct"] = float(f)
            for metric, x in obs.items():
                finding = self._observe(sig, metric, x, registry)
                if finding is not None:
                    out.append(finding)
        self.findings.extend(out)
        return out

    def _observe(self, sig: str, metric: str, x: float,
                 registry) -> Optional[dict]:
        st = self._state.setdefault((sig, metric), {
            "ewma": None, "hist": collections.deque(
                maxlen=self.history), "n": 0, "streak": 0,
            "flagged": False})
        finding = None
        anomalous = False
        if st["n"] >= self.warmup and st["ewma"] is not None:
            hist = sorted(st["hist"])
            med = hist[len(hist) // 2]
            mad = sorted(abs(v - med) for v in hist)[len(hist) // 2]
            scale = max(1.4826 * mad,
                        self.rel_floor * max(abs(st["ewma"]), 1e-9))
            score = (self.METRIC_DIRECTION[metric] * (x - st["ewma"])
                     / scale)
            if registry is not None:
                registry.gauge("perf_anomaly_score", score,
                               signature=sig, metric=metric)
            anomalous = score >= self.k
            if anomalous:
                st["streak"] += 1
                if st["streak"] >= self.sustain and not st["flagged"]:
                    st["flagged"] = True
                    finding = {
                        "signature": sig, "metric": metric,
                        "value": round(x, 6),
                        "baseline": round(st["ewma"], 6),
                        "score": round(score, 2),
                        "windows": st["streak"],
                    }
                    if registry is not None:
                        registry.counter("perf_anomalies_total",
                                         metric=metric)
            else:
                st["streak"] = 0
                st["flagged"] = False
        if not anomalous:
            # baseline adapts only on windows it would accept
            st["ewma"] = (x if st["ewma"] is None else
                          self.alpha * x + (1 - self.alpha)
                          * st["ewma"])
            st["hist"].append(x)
            st["n"] += 1
        return finding
