"""Per-signature SLO objectives — latency targets and error-budget
burn, computed from the metrics registry.

An SLO is a promise per compiled signature: "p99 end-to-end latency
under T seconds, failure ratio under B". The serving layers already
record everything needed — per-signature latency histograms
(``serve_signature_latency_s{signature=...}`` /
``fleet_signature_latency_s``) and per-signature outcome counters —
so evaluation is pure registry arithmetic, run at export time (the
CLIs call it once before writing the run record), never on the
serving hot path.

Burn rate is the SRE convention: ``error_rate / error_budget`` — 1.0
means failures are consuming the budget exactly as fast as allowed,
>1 means the objective will be violated if the rate holds. Results
are exported twice: as ``slo_*`` gauges through the registry (so a
Prometheus scrape sees them beside the raw histograms) and as the
``slo`` row list stamped into run records (docs/OBSERVABILITY.md has
the schema)."""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

#: outcomes that spend error budget: structured rejections that mean
#: the SERVER failed the request (shed/timeout/fault), not that the
#: request was invalid.
FAILURE_OUTCOMES_EXCLUDED = ("completed", "cache_hit", "coalesced",
                             "rejected_invalid")


@dataclasses.dataclass(frozen=True)
class SLOPolicy:
    """One objective: a p99 latency target (seconds) and an error
    budget (allowed failure fraction, e.g. 0.001 == 99.9%)."""

    latency_p99_s: float
    error_budget: float = 0.001

    def __post_init__(self):
        if self.latency_p99_s <= 0:
            raise ValueError(f"latency_p99_s must be > 0, got "
                             f"{self.latency_p99_s}")
        if not (0 < self.error_budget <= 1):
            raise ValueError(f"error_budget must be in (0, 1], got "
                             f"{self.error_budget}")


def evaluate(registry, *, prefix: str = "serve",
             default: Optional[SLOPolicy] = None,
             policies: Optional[Dict[str, SLOPolicy]] = None) -> list:
    """Evaluate SLOs against the ``<prefix>_signature_*`` families.

    ``policies`` maps signature strings to objectives; ``default``
    covers every signature not named (None = signatures without a
    policy are reported but not judged). Returns one row per observed
    signature and exports the ``slo_*`` gauges as a side effect."""
    policies = policies or {}
    rows = []
    hists = registry.find_histograms(prefix + "_signature_latency_s")
    counts = registry.find_counters(prefix + "_signature_requests_total")

    sigs = sorted(({dict(k).get("signature") for k in hists}
                   | {dict(k).get("signature") for k in counts})
                  - {None})
    for sig in sigs:
        pol = policies.get(sig, default)
        summary = None
        for k, v in hists.items():
            if dict(k).get("signature") == sig:
                summary = v
                break
        total = failures = 0.0
        for k, v in counts.items():
            kd = dict(k)
            if kd.get("signature") != sig:
                continue
            total += v
            if kd.get("outcome") not in FAILURE_OUTCOMES_EXCLUDED:
                failures += v
        row = {
            "signature": sig,
            "requests": total,
            "failures": failures,
            "error_rate": (failures / total) if total else 0.0,
            "p50_s": summary["p50"] if summary else None,
            "p99_s": summary["p99"] if summary else None,
        }
        if pol is not None:
            burn = row["error_rate"] / pol.error_budget
            latency_ok = (summary is None
                          or summary["p99"] <= pol.latency_p99_s)
            row.update(
                latency_target_p99_s=pol.latency_p99_s,
                latency_ok=latency_ok,
                error_budget=pol.error_budget,
                burn_rate=burn,
                budget_ok=burn <= 1.0,
                ok=latency_ok and burn <= 1.0)
            if registry is not None:
                if summary is not None:
                    # no latency samples (e.g. every request failed):
                    # no p99 gauge — a NaN would poison strict JSON
                    # consumers of the metrics snapshot
                    registry.gauge("slo_latency_p99_s",
                                   summary["p99"], signature=sig)
                registry.gauge("slo_latency_target_s",
                               pol.latency_p99_s, signature=sig)
                registry.gauge("slo_burn_rate", burn, signature=sig)
                registry.gauge("slo_ok", 1.0 if row["ok"] else 0.0,
                               signature=sig)
        rows.append(row)
    return rows


def stamp_record(extra: dict, rows: list) -> dict:
    """Attach the SLO evaluation to a run-record payload IN PLACE
    (returns it) — the ``slo`` schema row in docs/OBSERVABILITY.md."""
    extra["slo"] = rows
    return extra
