"""Per-signature SLO objectives — latency targets and error-budget
burn, computed from the metrics registry.

An SLO is a promise per compiled signature: "p99 end-to-end latency
under T seconds, failure ratio under B". The serving layers already
record everything needed — per-signature latency histograms
(``serve_signature_latency_s{signature=...}`` /
``fleet_signature_latency_s``) and per-signature outcome counters —
so evaluation is pure registry arithmetic, run at export time (the
CLIs call it once before writing the run record), never on the
serving hot path.

Burn rate is the SRE convention: ``error_rate / error_budget`` — 1.0
means failures are consuming the budget exactly as fast as allowed,
>1 means the objective will be violated if the rate holds. Results
are exported twice: as ``slo_*`` gauges through the registry (so a
Prometheus scrape sees them beside the raw histograms) and as the
``slo`` row list stamped into run records (docs/OBSERVABILITY.md has
the schema)."""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

#: outcomes that spend error budget: structured rejections that mean
#: the SERVER failed the request (shed/timeout/fault), not that the
#: request was invalid.
FAILURE_OUTCOMES_EXCLUDED = ("completed", "cache_hit", "coalesced",
                             "rejected_invalid")


@dataclasses.dataclass(frozen=True)
class SLOPolicy:
    """One objective: a p99 latency target (seconds) and an error
    budget (allowed failure fraction, e.g. 0.001 == 99.9%)."""

    latency_p99_s: float
    error_budget: float = 0.001

    def __post_init__(self):
        if self.latency_p99_s <= 0:
            raise ValueError(f"latency_p99_s must be > 0, got "
                             f"{self.latency_p99_s}")
        if not (0 < self.error_budget <= 1):
            raise ValueError(f"error_budget must be in (0, 1], got "
                             f"{self.error_budget}")


def evaluate(registry, *, prefix: str = "serve",
             default: Optional[SLOPolicy] = None,
             policies: Optional[Dict[str, SLOPolicy]] = None) -> list:
    """Evaluate SLOs against the ``<prefix>_signature_*`` families.

    ``policies`` maps signature strings to objectives; ``default``
    covers every signature not named (None = signatures without a
    policy are reported but not judged). Returns one row per observed
    signature and exports the ``slo_*`` gauges as a side effect."""
    policies = policies or {}
    rows = []
    hists = registry.find_histograms(prefix + "_signature_latency_s")
    counts = registry.find_counters(prefix + "_signature_requests_total")

    sigs = sorted(({dict(k).get("signature") for k in hists}
                   | {dict(k).get("signature") for k in counts})
                  - {None})
    for sig in sigs:
        pol = policies.get(sig, default)
        summary = None
        for k, v in hists.items():
            if dict(k).get("signature") == sig:
                summary = v
                break
        total = failures = 0.0
        for k, v in counts.items():
            kd = dict(k)
            if kd.get("signature") != sig:
                continue
            total += v
            if kd.get("outcome") not in FAILURE_OUTCOMES_EXCLUDED:
                failures += v
        row = {
            "signature": sig,
            "requests": total,
            "failures": failures,
            "error_rate": (failures / total) if total else 0.0,
            "p50_s": summary["p50"] if summary else None,
            "p99_s": summary["p99"] if summary else None,
        }
        if pol is not None and total == 0:
            # Zero traffic: there is nothing to judge. A burn rate of
            # 0/0 is not "healthy", it is ABSENT — no slo_burn_rate
            # gauge, and no ok verdict AT ALL: consumers uniformly do
            # ``row.get("ok", True)`` (serve CLI violation print, the
            # load gate's slo_ok), so the verdict key must be MISSING,
            # not None — a None would read as a violation and fail a
            # gate over a route nobody called. The row still reports
            # the objective so the signature's silence is visible.
            row.update(latency_target_p99_s=pol.latency_p99_s,
                       error_budget=pol.error_budget)
        elif pol is not None:
            burn = row["error_rate"] / pol.error_budget
            latency_ok = (summary is None
                          or summary["p99"] <= pol.latency_p99_s)
            row.update(
                latency_target_p99_s=pol.latency_p99_s,
                latency_ok=latency_ok,
                error_budget=pol.error_budget,
                burn_rate=burn,
                budget_ok=burn <= 1.0,
                ok=latency_ok and burn <= 1.0)
            if registry is not None:
                if summary is not None:
                    # no latency samples (e.g. every request failed):
                    # no p99 gauge — a NaN would poison strict JSON
                    # consumers of the metrics snapshot
                    registry.gauge("slo_latency_p99_s",
                                   summary["p99"], signature=sig)
                registry.gauge("slo_latency_target_s",
                               pol.latency_p99_s, signature=sig)
                registry.gauge("slo_burn_rate", burn, signature=sig)
                registry.gauge("slo_ok", 1.0 if row["ok"] else 0.0,
                               signature=sig)
        rows.append(row)
    return rows


def stamp_record(extra: dict, rows: list) -> dict:
    """Attach the SLO evaluation to a run-record payload IN PLACE
    (returns it) — the ``slo`` schema row in docs/OBSERVABILITY.md."""
    extra["slo"] = rows
    return extra


class BurnWindow:
    """Windowed, SUSTAINED burn-rate detection — the control plane's
    trigger (heat2d_tpu/control/, docs/CONTROL.md).

    ``evaluate`` above is cumulative: ten minutes of clean serving
    dilute a current outage below any threshold. The control plane
    needs the opposite — the burn rate *right now*, held long enough
    to act on. ``tick(registry)`` differentiates the per-signature
    outcome counters since the previous tick (one tick == one window),
    computes each signature's windowed ``error_rate / error_budget``,
    and tracks a consecutive-window streak per signature: a signature
    is **sustained** once its burn exceeded ``threshold`` for
    ``sustain`` ticks in a row. One clean window resets the streak; a
    ZERO-TRAFFIC window is no evidence either way — it neither grows
    nor resets the streak (and, like ``evaluate``, contributes no
    burn gauge).

    Windowed burns are exported as ``slo_windowed_burn_rate``
    gauges beside the cumulative ``slo_burn_rate`` family."""

    def __init__(self, policy: SLOPolicy, *, prefix: str = "fleet",
                 threshold: float = 1.0, sustain: int = 2):
        if sustain < 1:
            raise ValueError(f"sustain must be >= 1, got {sustain}")
        if threshold <= 0:
            raise ValueError(
                f"threshold must be > 0, got {threshold}")
        from heat2d_tpu.obs.metrics import CounterDeltas
        self.policy = policy
        self.prefix = prefix
        self.threshold = threshold
        self.sustain = sustain
        self._deltas = CounterDeltas()
        self._streak: Dict[str, int] = {}

    def tick(self, registry) -> Dict[str, dict]:
        """One window: {signature: {requests, failures, burn_rate,
        windows, sustained}}. ``burn_rate`` is None on a zero-traffic
        window; a registry-less caller gets an empty window, not a
        crash (FleetServer(registry=None) is a supported shape)."""
        if registry is None:
            return {}
        totals: Dict[str, list] = {}
        for k, d in self._deltas.tick(
                registry,
                self.prefix + "_signature_requests_total").items():
            kd = dict(k)
            sig = kd.get("signature")
            if sig is None:
                continue
            t = totals.setdefault(sig, [0.0, 0.0])
            t[0] += d
            if kd.get("outcome") not in FAILURE_OUTCOMES_EXCLUDED:
                t[1] += d
        out: Dict[str, dict] = {}
        for sig, (dt, df) in sorted(totals.items()):
            if dt <= 0:
                streak = self._streak.get(sig, 0)
                out[sig] = {"requests": 0.0, "failures": 0.0,
                            "burn_rate": None, "windows": streak,
                            "sustained": streak >= self.sustain}
                continue
            burn = (df / dt) / self.policy.error_budget
            streak = (self._streak.get(sig, 0) + 1
                      if burn > self.threshold else 0)
            self._streak[sig] = streak
            registry.gauge("slo_windowed_burn_rate", burn,
                           signature=sig)
            out[sig] = {"requests": dt, "failures": df,
                        "burn_rate": burn, "windows": streak,
                        "sustained": streak >= self.sustain}
        return out

    def sustained(self, result: Optional[Dict[str, dict]] = None) -> list:
        """Signatures currently over their sustain threshold. Pass a
        ``tick`` result to avoid consuming a fresh window."""
        if result is not None:
            return sorted(s for s, r in result.items() if r["sustained"])
        return sorted(s for s, n in self._streak.items()
                      if n >= self.sustain)
