"""Distributed request tracing — Dapper-style spans with cross-process
causality over the whole serving stack.

The reference's only profiling artifact is a per-rank mpiP digest
(Report.pdf p.34-37; mirrored by ``obs/trace_report.py``) — a
per-process AGGREGATE. A served request now crosses the fleet router,
a JSONL wire, a worker ``SolveServer``, the micro-batcher, and an
engine launch; an aggregate cannot say where *one slow request* spent
its time. This module is the per-request view: a ``TraceContext``
(``trace_id``/``span_id``/``parent_id``) is minted at request
admission and propagated through every layer — the batcher's queue,
the engine's launches, the fleet wire's DISPATCH lines, failover
replays — so ``heat2d-tpu-trace`` (obs/trace_cli.py) can merge the
per-process span files into ONE timeline with cross-process edges and
a per-request critical-path breakdown (queue wait vs compile vs
launch vs wire vs replay).

**Free when off — the obs prime directive.** Every hook site checks
``tracing.enabled()`` (one module-level bool) first; spans are pure
host-side bookkeeping and never touch a traced value, so the compiled
programs are byte-identical with tracing on or off
(tests/test_tracing.py pins the solver, band-runner, and serve
batch-runner jaxprs). Activation is opt-in: programmatic
(``install(Tracer(...))``) or ``HEAT2D_TRACE_DIR`` in the environment
(how fleet workers inherit the campaign from the router's CLI).

Span records are one JSON object per line in
``<dir>/spans-<service>-<pid>.jsonl``::

    {"event": "span", "schema": ..., "service": "worker0", "pid": 123,
     "trace_id": "4bf9...", "span_id": "00f3...", "parent_id": "...",
     "name": "serve.launch", "kind": "launch", "t0": ..., "t1": ...,
     "attrs": {"signature": "...", "first_launch": true}}

``t0``/``t1`` are epoch seconds derived from one per-process
monotonic->epoch anchor, so intervals are monotonic-accurate and
cross-process alignment is wall-clock-accurate (same-host fleets; see
docs/OBSERVABILITY.md on clock skew). Every finished span is also
teed into the flight recorder's ring buffer (obs/flight.py) when one
is installed — the black box a chaos-killed worker leaves behind.
"""

from __future__ import annotations

import dataclasses
import json
import os
import secrets
import threading
import time
from typing import Optional

TRACE_SCHEMA = "heat2d-tpu/trace-span/v1"

#: span kinds the critical-path breakdown buckets by
#: (obs/trace_cli.py); "internal" is everything else.
SPAN_KINDS = ("request", "queue", "launch", "wire", "replay", "phase",
              "event", "internal")


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """One node of a request's causal tree: the globally-unique
    ``trace_id`` names the request, ``span_id`` names this operation.
    Plain data — it crosses the fleet wire as two hex strings."""

    trace_id: str
    span_id: str

    def to_wire(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @classmethod
    def from_wire(cls, d) -> Optional["TraceContext"]:
        """A context from a wire dict, or None for anything malformed —
        an old supervisor's trace-less line must parse as 'no trace',
        never as an error (fleet back-compat)."""
        if not isinstance(d, dict):
            return None
        tid, sid = d.get("trace_id"), d.get("span_id")
        if not (isinstance(tid, str) and isinstance(sid, str)
                and tid and sid):
            return None
        return cls(trace_id=tid, span_id=sid)


def _new_trace_id() -> str:
    return secrets.token_hex(16)


def _new_span_id() -> str:
    return secrets.token_hex(8)


class Span:
    """One in-progress operation. Created by ``Tracer.begin`` (or the
    ``span()`` context manager); ``end()`` stamps the close time and
    emits the record. Spans may be ended from a DIFFERENT thread than
    they began on (a queue span begins on the submitting thread and
    ends on the scheduler thread) — the tracer's emit path is
    thread-safe and ``end()`` is idempotent."""

    __slots__ = ("tracer", "name", "kind", "ctx", "parent_id", "t0",
                 "attrs", "_done")

    def __init__(self, tracer: "Tracer", name: str, kind: str,
                 ctx: TraceContext, parent_id: Optional[str],
                 t0: float, attrs: dict):
        self.tracer = tracer
        self.name = name
        self.kind = kind
        self.ctx = ctx
        self.parent_id = parent_id
        self.t0 = t0
        self.attrs = attrs
        self._done = False

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def end(self, **attrs) -> None:
        """Close the span (idempotent — a future's done-callback may
        race a failure path; first close wins)."""
        if self._done:
            return
        self._done = True
        if attrs:
            self.attrs.update(attrs)
        self.tracer._emit(self, time.monotonic())


class _NullSpan:
    """The disabled-path stand-in: every method a no-op, ``ctx`` is
    None, so hook sites can run unconditionally after one enabled()
    check."""

    ctx = None
    attrs: dict = {}

    def set(self, **attrs):
        return self

    def end(self, **attrs):
        pass


NULL_SPAN = _NullSpan()


class Tracer:
    """Per-process span sink. ``dir`` is the shared trace directory
    (one file per process inside it); ``sink`` (a callable taking the
    record dict) replaces the file for in-process tests. ``service``
    names this process's lane in the merged timeline ("router",
    "worker0", "cli")."""

    def __init__(self, dir: Optional[str] = None, *,
                 service: str = "main", sink=None):
        if dir is None and sink is None:
            raise ValueError("Tracer needs a dir or a sink")
        self.dir = dir
        self.service = service
        self.sink = sink
        self.pid = os.getpid()
        # ONE monotonic->epoch anchor per tracer: every span timestamp
        # is epoch0 + (mono - mono0), so in-process intervals are
        # monotonic-exact and never jump with wall-clock adjustments.
        self._epoch0 = time.time()
        self._mono0 = time.monotonic()
        self._lock = threading.Lock()
        self._file = None
        self.path = (None if dir is None else os.path.join(
            dir, f"spans-{service}-{self.pid}.jsonl"))
        self.spans_emitted = 0

    # -- time ---------------------------------------------------------- #

    def epoch_of(self, mono: float) -> float:
        """Epoch seconds for a ``time.monotonic()`` stamp (how
        retroactive spans — queue waits recorded at dispatch — get
        consistent timestamps)."""
        return self._epoch0 + (mono - self._mono0)

    # -- span lifecycle ------------------------------------------------ #

    def mint(self, parent: Optional[TraceContext] = None) -> TraceContext:
        """A fresh context: same trace as ``parent`` (new span id), or
        a brand-new trace when there is no parent — request admission
        mints the root here."""
        return TraceContext(
            trace_id=parent.trace_id if parent else _new_trace_id(),
            span_id=_new_span_id())

    def begin(self, name: str, *, kind: str = "internal",
              parent: Optional[TraceContext] = None, **attrs) -> Span:
        ctx = self.mint(parent)
        sp = Span(self, name, kind, ctx,
                  parent.span_id if parent else None,
                  time.monotonic(), dict(attrs))
        # A span_start record the moment the span opens: a process
        # killed mid-span (the chaos scenario this subsystem exists
        # for) still leaves its open spans in the file/ring, so the
        # merged trace stays CONNECTED — the reader synthesizes an
        # "unfinished" span for any start without a matching end.
        self._write({
            "event": "span_start", "schema": TRACE_SCHEMA,
            "service": self.service, "pid": self.pid,
            "trace_id": ctx.trace_id, "span_id": ctx.span_id,
            "parent_id": sp.parent_id, "name": name, "kind": kind,
            "t0": self.epoch_of(sp.t0), "attrs": dict(sp.attrs),
        })
        return sp

    def emit_span(self, name: str, t0_mono: float, t1_mono: float, *,
                  kind: str = "internal",
                  parent: Optional[TraceContext] = None,
                  **attrs) -> TraceContext:
        """A retroactively-timed, already-finished span (e.g. the
        queue wait, known only at dispatch). Returns its context."""
        sp = Span(self, name, kind, self.mint(parent),
                  parent.span_id if parent else None, t0_mono,
                  dict(attrs))
        sp._done = True
        self._emit(sp, t1_mono)
        return sp.ctx

    def event(self, name: str, *, parent: Optional[TraceContext] = None,
              **attrs) -> TraceContext:
        """An instantaneous marker span (kind="event") — e.g. a wire
        line's receipt, a failover replay decision."""
        now = time.monotonic()
        return self.emit_span(name, now, now, kind="event",
                              parent=parent, **attrs)

    # -- emission ------------------------------------------------------ #

    def _emit(self, span: Span, t1_mono: float) -> None:
        rec = {
            "event": "span", "schema": TRACE_SCHEMA,
            "service": self.service, "pid": self.pid,
            "trace_id": span.ctx.trace_id, "span_id": span.ctx.span_id,
            "parent_id": span.parent_id,
            "name": span.name, "kind": span.kind,
            "t0": self.epoch_of(span.t0),
            "t1": self.epoch_of(t1_mono),
            "attrs": span.attrs,
        }
        self.spans_emitted += 1
        self._write(rec)

    def _write(self, rec: dict) -> None:
        from heat2d_tpu.obs import flight
        flight.note_span(rec)
        if _span_taps:
            # live consumers (obs.perf.DutyCycleSampler): a tap must
            # never take the emitting path down, and an empty tap list
            # costs one truthiness check (the free-when-off contract)
            for tap in tuple(_span_taps):
                try:
                    tap(rec)
                except Exception:  # noqa: BLE001
                    pass
        with self._lock:
            if self.sink is not None:
                self.sink(rec)
                return
            try:
                if self._file is None:
                    os.makedirs(self.dir, exist_ok=True)
                    self._file = open(self.path, "a")
                # one line per record, flushed: a killed process's file
                # is complete up to the kill (torn-line tolerant
                # readers skip at most the final line)
                self._file.write(json.dumps(rec) + "\n")
                self._file.flush()
            except OSError:
                pass    # tracing must never take the serving path down

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None


# -- the process-global tracer (chaos.py's install/env pattern) -------- #

_lock = threading.Lock()
_tracer: Optional[Tracer] = None
_enabled = False        # fast-path guard: False == all hooks no-op
_env_checked = False
#: live span consumers teed from Tracer._write (obs.perf duty-cycle
#: sampling). Module-level so taps survive tracer swaps; empty ==
#: zero-cost.
_span_taps: list = []

ENV_DIR = "HEAT2D_TRACE_DIR"


def add_span_tap(fn) -> None:
    """Tee every emitted span record to ``fn(rec)`` (host-side, called
    on the emitting thread). Exceptions from taps are swallowed."""
    with _lock:
        if fn not in _span_taps:
            _span_taps.append(fn)


def remove_span_tap(fn) -> None:
    with _lock:
        if fn in _span_taps:
            _span_taps.remove(fn)


def install(tracer: Optional[Tracer]) -> None:
    """Activate a tracer programmatically; ``None`` disarms. A tracer
    being replaced is closed (its span file handle released)."""
    global _tracer, _enabled, _env_checked
    with _lock:
        if _tracer is not None and _tracer is not tracer:
            _tracer.close()
        _env_checked = True
        _tracer, _enabled = tracer, tracer is not None


def uninstall() -> None:
    """Disarm and forget; the environment is re-read on next use
    (fresh processes pick their campaign up from ``HEAT2D_TRACE_DIR``)."""
    global _tracer, _enabled, _env_checked
    with _lock:
        if _tracer is not None:
            _tracer.close()
        _tracer, _enabled, _env_checked = None, False, False


def activate_from_env(service: str = "main") -> Optional[Tracer]:
    """Install a tracer iff ``HEAT2D_TRACE_DIR`` is set (how worker
    subprocesses join the router's campaign — the supervisor passes
    the environment through). Idempotent: an already-installed tracer
    wins."""
    global _tracer, _enabled, _env_checked
    with _lock:
        if _tracer is not None:
            return _tracer
        d = os.environ.get(ENV_DIR)
        if d:
            _tracer = Tracer(d, service=service)
            _enabled = True
        _env_checked = True
        return _tracer


def tracer() -> Optional[Tracer]:
    """The active tracer, consulting the environment on first use."""
    if not _env_checked:
        activate_from_env()
    return _tracer


def enabled() -> bool:
    if not _env_checked:
        activate_from_env()
    return _enabled


# -- ambient context (thread-local) ------------------------------------ #

_ambient = threading.local()


def set_ambient(ctx: Optional[TraceContext]) -> None:
    """Set THIS thread's ambient parent context — what free-floating
    spans (``phase()`` entries) attach to when nothing explicit is in
    scope. The CLI's run-root sets it; server/worker paths never do
    (their parents are always explicit)."""
    _ambient.ctx = ctx


def ambient() -> Optional[TraceContext]:
    return getattr(_ambient, "ctx", None)


# -- hook-site conveniences (cheap no-ops when off) -------------------- #

def begin(name: str, *, kind: str = "internal",
          parent: Optional[TraceContext] = None, **attrs):
    """A live span, or ``NULL_SPAN`` when tracing is off — hook sites
    call ``.end()`` unconditionally."""
    t = tracer() if _enabled or not _env_checked else None
    if t is None:
        return NULL_SPAN
    return t.begin(name, kind=kind, parent=parent, **attrs)


def emit(name: str, t0_mono: float, t1_mono: float, *,
         kind: str = "internal", parent: Optional[TraceContext] = None,
         **attrs) -> Optional[TraceContext]:
    t = tracer() if _enabled or not _env_checked else None
    if t is None:
        return None
    return t.emit_span(name, t0_mono, t1_mono, kind=kind,
                       parent=parent, **attrs)


def event(name: str, *, parent: Optional[TraceContext] = None,
          **attrs) -> Optional[TraceContext]:
    t = tracer() if _enabled or not _env_checked else None
    if t is None:
        return None
    return t.event(name, parent=parent, **attrs)
