"""The ONE run-record schema.

Before this module the repo had three divergent ad-hoc record shapes
(``RunResult.to_record``, the CLI's JSON record, ``bench.py``'s driver
line, plus the sweep harness's rows). Every emitter now shares one
envelope: a ``schema`` tag, the record ``kind``, an ISO timestamp, the
jax version, the device summary, and the multihost world — the execution
context the reference only printf'd (SURVEY.md §5.5). Payload keys
(config, timings, throughput, suite columns) ride beside the envelope so
existing consumers keep working.

Observability payload rows (PR 9, docs/OBSERVABILITY.md):

- ``trace_id`` — the run's distributed-tracing root (present when the
  emitter ran with ``--trace-dir``): the join key into the span files a
  ``heat2d-tpu-trace`` merge reads.
- ``trace`` — the emitting CLI's tracing summary (span dir, spans
  emitted, post-mortem count for fleets).
- ``slo`` — per-signature SLO evaluation rows (obs/slo.py: p50/p99 vs
  target, error rate, burn rate, ok) when an SLO target was given.

Algorithmic-speed payload rows (PR 14, docs/ALGORITHMS.md): bench
records (and the tpu_smoke implicit section) carry a
``time_to_solution`` block — per-method rows with ``steps``,
``time_to_solution_s`` (measured wall-clock to a fixed physical
t_final), ``modeled_s`` (the deterministic step-cost model), and
``accuracy`` (L2 error vs the analytic separable-mode solution), plus
a summary with per-route ``*_steps_ratio`` / ``*_wall_speedup`` /
``*_modeled_speedup`` / ``*_matched_accuracy`` — so BENCH_r*
trajectories compare methods at equal ACCURACY, not equal steps
(models/solution.py).
"""

from __future__ import annotations

import datetime

RECORD_SCHEMA = "heat2d-tpu/run-record/v1"

#: The record kinds emitters currently produce — consumers keying on
#: ``kind`` can enumerate what exists without grepping call sites.
#: "run" (CLI solver), "ensemble" (CLI batched sweep), "bench"/"sweep"
#: (benchmark harnesses), "serve" (heat2d-tpu-serve: launch log +
#: serving telemetry snapshot rides in the same JSONL), "tune"
#: (heat2d-tpu-tune: search summary + tune_* metric families), "fleet"
#: (heat2d-tpu-fleet: supervisor/soak summary + fleet_* families),
#: "inverse" (heat2d-tpu-inverse: recovery summary — iteration count,
#: final loss, convergence flag — + the inverse_* metric families and
#: per-iteration loss/grad-norm series), "multichip" (the strong-
#: scaling gate: per-chip Mcells/s at 1 vs n chips + efficiency per
#: halo route — parallel/scaling.py), "load" (heat2d-tpu-load: the
#: latency/throughput surface — per-point offered/achieved req/s,
#: latency quantiles, shed rate, replay-fidelity skew, per-signature
#: SLO rows — plus the fitted capacity model (max sustainable req/s,
#: per-unit rate, units-for-N sizing) and the gate verdict against
#: the committed baseline — heat2d_tpu/load/, docs/LOADGEN.md),
#: "control" (the fleet control plane: decision log, rollout outcomes
#: with parity/revert verdicts, worker config generations and the
#: no-unvalidated-serving invariant, staged retune candidates —
#: heat2d_tpu/control/, docs/CONTROL.md), "mesh_chaos" (the mesh
#: fault-tolerance gate — heat2d_tpu/mesh/chaos_gate.py: one row per
#: injected device-fault scenario (device loss / silent bit flip /
#: hung collective) with the MEASURED detection + recovery seconds,
#: bitwise-parity verdict vs the single-chip oracle, quarantine set,
#: and the no-quarantined-serving invariant —
#: docs/RESILIENCE.md failure model), "perf" (heat2d-tpu-perf: the
#: performance observatory — per-program cost cards (XLA compile-time
#: FLOPs / bytes-accessed / argument+output+temp sizes cross-checked
#: against the analytic roofline models), roofline rows per signature
#: (achieved vs bound Mcells/s, bytes/cell-step, Mcells-per-HBM-byte),
#: duty-cycle summary, and the anomaly sentinel's findings beside the
#: soak verdict — heat2d_tpu/obs/perf.py, docs/OBSERVABILITY.md),
#: "autoscale" (heat2d-tpu-fleet --autoscale: the elastic soak — the
#: actuator's action audit trail (scale-ups/downs with victim slots
#: and drain cleanliness, paroles, mesh resizes), the pool-size trace
#: against the diurnal envelope, the chip-seconds ledger vs the
#: static-provisioning baseline with the savings fraction, and the
#: live-migration rows (checkpoint iteration, wire bytes, destination
#: slot, bitwise-vs-oracle verdict) beside the autoscale_* metric
#: families — heat2d_tpu/autoscale/, docs/CONTROL.md "Actuation"),
#: "dist" (heat2d-tpu-dist: the multihost pod runtime — per-leg rows
#: from the worker (bring-up world summary + link census, the dist_*
#: metric totals, the failure-domain bridge snapshot with its
#: seq-fenced shrink+failover transactions, serving_invariant
#: verdict) and from the drivers (--selftest bitwise-parity verdict
#: vs the single-process program, --soak --kill-host recovery
#: verdict) — heat2d_tpu/dist/, docs/DISTRIBUTED.md).
RECORD_KINDS = ("run", "ensemble", "bench", "sweep", "serve", "tune",
                "fleet", "inverse", "multichip", "load", "control",
                "mesh_chaos", "perf", "autoscale", "dist")


def run_context() -> dict:
    """The shared envelope: schema tag + execution context."""
    import jax

    from heat2d_tpu.parallel.multihost import world_summary
    from heat2d_tpu.utils.device import device_summary

    return {
        "schema": RECORD_SCHEMA,
        "timestamp": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
        "jax_version": jax.__version__,
        "device": device_summary(),
        "world": world_summary(),
    }


def attach_context(rec: dict, kind: str) -> dict:
    """Add the shared envelope to an existing record IN PLACE (returns it).
    Keys the record already carries are kept — emitters may pre-fill e.g.
    ``device`` with something richer."""
    rec.setdefault("kind", kind)
    for k, v in run_context().items():
        rec.setdefault(k, v)
    return rec


def build_record(kind: str, config=None, steps_done=None, elapsed_s=None,
                 mcells_per_s=None, warmup_s=None, extra=None) -> dict:
    """Unified run record. ``config`` may be a HeatConfig or a dict;
    ``warmup_s`` is the compile+warmup time the timed span excludes
    (utils/timing.py) — a first-class metric here, not a discard.
    ``extra`` merges payload keys (existing keys win over the envelope,
    so kind-specific shapes stay stable)."""
    rec: dict = {}
    if config is not None:
        rec["config"] = (config if isinstance(config, dict)
                         else config.to_dict())
    if steps_done is not None:
        rec["steps_done"] = int(steps_done)
    if elapsed_s is not None:
        rec["elapsed_s"] = float(elapsed_s)
    if mcells_per_s is not None:
        rec["mcells_per_s"] = float(mcells_per_s)
    if warmup_s is not None:
        rec["warmup_s"] = float(warmup_s)
    if extra:
        rec.update(extra)
    return attach_context(rec, kind)


def write_run_jsonl(registry, path: str, kind: str, extra: dict,
                    more=()) -> None:
    """The one-line telemetry export shared by the CLIs: the
    registry's events + snapshot plus a ``kind`` run record carrying
    ``extra`` as its payload. ``more`` appends additional (kind,
    extra) record pairs to the same JSONL — e.g. the fleet CLI's
    ``kind="control"`` record riding beside its ``kind="fleet"`` one.
    No-op without a registry or path."""
    if registry is None or not path:
        return
    records = [{"event": "run_record",
                **build_record(kind, extra=dict(extra))}]
    for k2, e2 in more:
        records.append({"event": "run_record",
                        **build_record(k2, extra=dict(e2))})
    registry.write_jsonl(path, extra_records=records)
