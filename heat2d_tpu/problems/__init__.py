"""Problem registry: pluggable stencil/PDE families (docs/PROBLEMS.md).

The package splits jax-free from jax-bound on purpose:

- ``base``     — FamilySpec contract + FAMILY_SPECS (no jax): config
                 validation, serve admission, mesh bytes routing,
                 tune keys, roofline constants read this half.
- ``kernels``  — the jax/numpy kernel templates per family.
- ``registry`` — runtime Family objects binding spec + kernels.
- ``runners``  — generic batched jnp/pallas/band ensemble runners.

Import ``heat2d_tpu.problems`` (this module) for the full API;
import ``heat2d_tpu.problems.base`` directly on host-side paths that
must stay jax-free.
"""

from heat2d_tpu.problems.base import (FAMILY_SPECS, FamilySpec,
                                      capability_matrix, spec_for,
                                      state_arrays, supports_method)
from heat2d_tpu.problems.registry import (Family, family_names,
                                          get_family, register)
from heat2d_tpu.vocab import DEFAULT_PROBLEM, PROBLEMS

__all__ = [
    "FAMILY_SPECS", "FamilySpec", "capability_matrix", "spec_for",
    "state_arrays", "supports_method", "Family", "family_names",
    "get_family", "register", "DEFAULT_PROBLEM", "PROBLEMS",
]
