"""Problem-family kernel templates — the jax half of the registry.

Per family, three forms of the SAME update (the contract the parity
tests pin against each other):

- ``<fam>_step(u, cx, cy)`` — the jnp reference step: interior
  updated via ``.at[].set``, a ``halo_width``-deep boundary ring held
  (the clamped BC every mode shares — ops/stencil.py boundary
  semantics, generalized to wider rings).
- ``<fam>_step_value(u, *scalars)`` — the Pallas band/ensemble
  template: value-in/value-out on an array, reassembled via
  concatenation of static slices (Mosaic has no scatter lowering —
  ops/pallas_stencil._step_value's scheme, generalized to ring depth
  ``halo_width``). Inside the band kernels the caller's keep-mask
  owns the GLOBAL boundary; this form holds the LOCAL window ring.
- ``<fam>_np_step(u, cx, cy)`` — the numpy golden oracle, evaluated
  in float64 and cast back (parity tolerance is documented per test,
  not bitwise: the jnp forms accumulate in float32).

``heat5`` deliberately re-exports the EXISTING functions
(``ops.stencil.stencil_step`` / ``ops.pallas_stencil._step_value``)
rather than reimplementing them — the byte-identity pins require the
same function objects on every pre-registry path.

Family constants (advection velocity, reaction rate) come from
``vocab.py`` so the jax-free stability checks and the traced kernels
can never disagree about the numbers.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from heat2d_tpu.ops.stencil import stencil_step, stencil_step_var
from heat2d_tpu.vocab import ADVECTION_VELOCITY, REACTION_RATE


def _ring_reassemble(u, new, w):
    """Value-form reassembly: ``new`` replaces the interior of ``u``
    inside a ``w``-deep held ring (concatenation of static slices —
    the Mosaic-safe scheme)."""
    mid = jnp.concatenate([u[w:-w, :w], new, u[w:-w, -w:]], axis=1)
    return jnp.concatenate([u[:w, :], mid, u[-w:, :]], axis=0)


# --------------------------------------------------------------------- #
# heat5 — the reference family (existing functions, byte-identical)
# --------------------------------------------------------------------- #

def heat5_step(u, cx, cy):
    """The reference update — ops.stencil.stencil_step verbatim (the
    registry must not introduce a second copy of the hot math)."""
    return stencil_step(u, cx, cy)


def heat5_step_value(u, cx, cy):
    from heat2d_tpu.ops.pallas_stencil import _step_value
    return _step_value(u, cx, cy)


def heat5_np_step(u, cx, cy):
    v = np.asarray(u, np.float64)
    c = v[1:-1, 1:-1]
    sx = v[2:, 1:-1] + v[:-2, 1:-1]
    sy = v[1:-1, 2:] + v[1:-1, :-2]
    out = np.array(u, copy=True)
    out[1:-1, 1:-1] = (c + cx * (sx - 2.0 * c)
                       + cy * (sy - 2.0 * c)).astype(u.dtype)
    return out


# --------------------------------------------------------------------- #
# varcoef — per-cell diffusivity fields (promoted ops.stencil_step_var)
# --------------------------------------------------------------------- #

def varcoef_profiles(nx, ny, xp=jnp, dtype=None):
    """The family's deterministic "graded-material lens" coefficient
    PROFILES: separable polynomial bumps in [0.5, 1.0], multiplied by
    (cx, cy) to give per-cell fields bounded by the constant
    coefficients — so ``kx + ky <= cx + cy`` pointwise and the heat5
    stability box governs (ops/stencil.py stability note). Profiles
    depend only on the grid shape; the request's two knobs stay
    (cx, cy), exactly like every other family."""
    dtype = dtype or (jnp.float32 if xp is jnp else np.float32)
    si = xp.linspace(0.0, 1.0, nx, dtype=dtype)[:, None]
    sj = xp.linspace(0.0, 1.0, ny, dtype=dtype)[None, :]
    px = (0.5 + 2.0 * si * (1.0 - si)).astype(dtype)
    py = (0.5 + 2.0 * sj * (1.0 - sj)).astype(dtype)
    ones = xp.ones((nx, ny), dtype)
    return px * ones, py * ones


def varcoef_step(u, cx, cy):
    px, py = varcoef_profiles(u.shape[0], u.shape[1])
    return stencil_step_var(u, cx * px, cy * py)


def varcoef_np_step(u, cx, cy):
    px, py = varcoef_profiles(u.shape[0], u.shape[1], xp=np,
                              dtype=np.float64)
    v = np.asarray(u, np.float64)
    kx, ky = cx * px, cy * py
    c = v[1:-1, 1:-1]
    sx = v[2:, 1:-1] + v[:-2, 1:-1]
    sy = v[1:-1, 2:] + v[1:-1, :-2]
    out = np.array(u, copy=True)
    out[1:-1, 1:-1] = (c + kx[1:-1, 1:-1] * (sx - 2.0 * c)
                       + ky[1:-1, 1:-1] * (sy - 2.0 * c)).astype(u.dtype)
    return out


# --------------------------------------------------------------------- #
# heat9 — 4th-order 9-point (wide) stencil, halo width 2
# --------------------------------------------------------------------- #

def _heat9_interior(u, cx, cy):
    """4th-order central second differences on the w=2 interior:
    ``dxx4 = (-u[i-2] + 16 u[i-1] - 30 u[i] + 16 u[i+1] - u[i+2])/12``
    per axis (the classic 5-point-per-axis wide stencil)."""
    c = u[2:-2, 2:-2]
    dxx = (-u[4:, 2:-2] + 16.0 * u[3:-1, 2:-2] - 30.0 * c
           + 16.0 * u[1:-3, 2:-2] - u[:-4, 2:-2]) * (1.0 / 12.0)
    dyy = (-u[2:-2, 4:] + 16.0 * u[2:-2, 3:-1] - 30.0 * c
           + 16.0 * u[2:-2, 1:-3] - u[2:-2, :-4]) * (1.0 / 12.0)
    return c + cx * dxx + cy * dyy


def heat9_step(u, cx, cy):
    return u.at[2:-2, 2:-2].set(_heat9_interior(u, cx, cy)
                                .astype(u.dtype))


def heat9_step_value(u, cx, cy):
    return _ring_reassemble(u, _heat9_interior(u, cx, cy), 2)


def heat9_np_step(u, cx, cy):
    v = np.asarray(u, np.float64)
    c = v[2:-2, 2:-2]
    dxx = (-v[4:, 2:-2] + 16.0 * v[3:-1, 2:-2] - 30.0 * c
           + 16.0 * v[1:-3, 2:-2] - v[:-4, 2:-2]) / 12.0
    dyy = (-v[2:-2, 4:] + 16.0 * v[2:-2, 3:-1] - 30.0 * c
           + 16.0 * v[2:-2, 1:-3] - v[2:-2, :-4]) / 12.0
    out = np.array(u, copy=True)
    out[2:-2, 2:-2] = (c + cx * dxx + cy * dyy).astype(u.dtype)
    return out


def heat9_mode_factor(nx, ny, cx, cy):
    """Exact per-step amplification of the lowest separable sine mode
    under the 4th-order operator: the discrete sine IS an eigenvector
    of ``dxx4`` on the held-ring domain restricted to the full-domain
    mode structure, with eigenvalue ``lam4(k) = (30 - 32 cos k +
    2 cos 2k)/12`` at ``k = pi/(n-1)`` — the analytic-accuracy oracle
    (tests compare a small-amplitude evolution's decay rate)."""
    kx = np.pi / (nx - 1)
    ky = np.pi / (ny - 1)
    lam4 = lambda k: (30.0 - 32.0 * np.cos(k) + 2.0 * np.cos(2 * k)) / 12.0
    return 1.0 - cx * lam4(kx) - cy * lam4(ky)


# --------------------------------------------------------------------- #
# advdiff — central advection + diffusion (fixed family velocities)
# --------------------------------------------------------------------- #

def _advdiff_interior(u, cx, cy, vx, vy):
    c = u[1:-1, 1:-1]
    sx = u[2:, 1:-1] + u[:-2, 1:-1]
    sy = u[1:-1, 2:] + u[1:-1, :-2]
    dx = u[2:, 1:-1] - u[:-2, 1:-1]
    dy = u[1:-1, 2:] - u[1:-1, :-2]
    return (c + cx * (sx - 2.0 * c) + cy * (sy - 2.0 * c)
            - 0.5 * vx * dx - 0.5 * vy * dy)


def advdiff_step(u, cx, cy):
    vx, vy = ADVECTION_VELOCITY
    return u.at[1:-1, 1:-1].set(
        _advdiff_interior(u, cx, cy, vx, vy).astype(u.dtype))


def advdiff_step_value(u, cx, cy, vx, vy):
    return _ring_reassemble(u, _advdiff_interior(u, cx, cy, vx, vy), 1)


def advdiff_np_step(u, cx, cy):
    vx, vy = ADVECTION_VELOCITY
    v = np.asarray(u, np.float64)
    c = v[1:-1, 1:-1]
    sx = v[2:, 1:-1] + v[:-2, 1:-1]
    sy = v[1:-1, 2:] + v[1:-1, :-2]
    dx = v[2:, 1:-1] - v[:-2, 1:-1]
    dy = v[1:-1, 2:] - v[1:-1, :-2]
    out = np.array(u, copy=True)
    out[1:-1, 1:-1] = (c + cx * (sx - 2.0 * c) + cy * (sy - 2.0 * c)
                       - 0.5 * vx * dx
                       - 0.5 * vy * dy).astype(u.dtype)
    return out


# --------------------------------------------------------------------- #
# reactdiff — reaction-diffusion with a saturating nonlinear source
# --------------------------------------------------------------------- #
#
# The source is Michaelis-Menten kinetics, r*u/(1+u): genuinely
# nonlinear (the property the capability matrix gates ADI/MG/ABFT on —
# no closed-form linear recurrence exists), yet BOUNDED for any u >= 0
# (the term saturates at r), so the family is stable on the reference
# initial condition, whose values run to ~nx^2*ny^2/16 — far outside
# the [0, 1] range a logistic source would need. The reaction Jacobian
# r/(1+u)^2 <= r gives the explicit bound ops/stability.py names.

def _reactdiff_interior(u, cx, cy, r):
    c = u[1:-1, 1:-1]
    sx = u[2:, 1:-1] + u[:-2, 1:-1]
    sy = u[1:-1, 2:] + u[1:-1, :-2]
    return (c + cx * (sx - 2.0 * c) + cy * (sy - 2.0 * c)
            + r * c / (1.0 + c))


def reactdiff_step(u, cx, cy):
    r = REACTION_RATE
    return u.at[1:-1, 1:-1].set(
        _reactdiff_interior(u, cx, cy, r).astype(u.dtype))


def reactdiff_step_value(u, cx, cy, r):
    return _ring_reassemble(u, _reactdiff_interior(u, cx, cy, r), 1)


def reactdiff_np_step(u, cx, cy):
    r = REACTION_RATE
    v = np.asarray(u, np.float64)
    c = v[1:-1, 1:-1]
    sx = v[2:, 1:-1] + v[:-2, 1:-1]
    sy = v[1:-1, 2:] + v[1:-1, :-2]
    out = np.array(u, copy=True)
    out[1:-1, 1:-1] = (c + cx * (sx - 2.0 * c) + cy * (sy - 2.0 * c)
                       + r * c / (1.0 + c)).astype(u.dtype)
    return out


# --------------------------------------------------------------------- #
# scalar-operand mappings (the SMEM rows of the batched kernels)
# --------------------------------------------------------------------- #

def heat5_scalars(cx, cy):
    return (cx, cy)


def varcoef_scalars(cx, cy):
    return (cx, cy)


def heat9_scalars(cx, cy):
    return (cx, cy)


def advdiff_scalars(cx, cy):
    vx, vy = ADVECTION_VELOCITY
    return (cx, cy, jnp.full_like(cx, vx), jnp.full_like(cy, vy))


def reactdiff_scalars(cx, cy):
    return (cx, cy, jnp.full_like(cx, REACTION_RATE))
