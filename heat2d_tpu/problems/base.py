"""Problem-family CONTRACT — the jax-free declarative half of the
registry.

A problem family is one spatial operator: the reference hardcodes
exactly one (the 5-point constant-coefficient heat stencil —
SURVEY.md §7.1; ROADMAP open item 1), and before this package every
layer of the platform was welded to it. ``FamilySpec`` is what a
family DECLARES about itself; everything the dispatch spine needs on
host-side paths (config validation, serving admission, the mesh
scheduler's bytes model, tune-db keys, roofline constants) reads the
spec alone and never imports jax — the kernels live in
``problems/kernels.py`` and bind lazily through
``problems/registry.py``.

Capability gating falls out of the declared properties
(docs/PROBLEMS.md capability matrix):

- ``time_methods`` — which time discretizations the platform's built
  kernels serve for this operator. The implicit routes (ADI's batched
  constant-coefficient Thomas sweeps, MG's 5-point V-cycle smoother)
  are OPERATOR-SPECIFIC kernels, so only ``heat5`` inherits them
  today; a nonlinear source additionally rules them out structurally
  (Crank-Nicolson's linear solves do not apply). The gate's error
  NAMES the reason (``gate_reason``).
- ``abft`` — whether ABFT's closed-form checksum recurrence holds
  (requires linearity AND the separable-mode eigenvector structure
  plus a constant boundary flux — ops/abft.py); nonlinear families
  get probe/quarantine tiers only.
- ``kernel_routes`` — which explicit batched kernel templates exist
  (``varcoef``'s per-cell coefficient FIELDS don't ride the scalar
  SMEM operand scheme, so it is jnp-only).
- ``halo_width`` — T_spatial: the operator's spatial radius. The band
  templates carry ``halo_width * T`` ghost rows per sweep and the
  per-step keep-mask holds a ``halo_width``-deep boundary ring (the
  Bandishti-et-al wider-stencil generalization, PAPERS.md).
- ``state_arrays`` / ``reads_per_step`` — the resource model: grid-
  sized device arrays per member (mesh scheduler bytes routing,
  tune/space VMEM working set) and HBM arrays read per jnp step
  (obs/roofline.py bytes/cell-step).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from heat2d_tpu.vocab import (ADVECTION_VELOCITY, DEFAULT_PROBLEM,
                              IMPLICIT_METHODS, PROBLEMS, REACTION_RATE)


@dataclasses.dataclass(frozen=True)
class FamilySpec:
    """What one problem family declares about itself (module
    docstring). Pure data — the registry binds kernels to it."""

    name: str
    title: str
    #: spatial radius (T_spatial): per-step valid-region shrink, halo
    #: ring depth, and the boundary-ring width the update holds.
    halo_width: int
    #: linear in u — the property the implicit/ABFT gates derive from.
    linear: bool
    #: grid-sized device arrays per member (u + coefficient fields).
    state_arrays: int
    #: HBM grid arrays read per jnp step (u + coefficient fields).
    reads_per_step: int
    #: SMEM scalar operands of the kernel templates (cx, cy, + family
    #: constants that ride as traced values).
    n_scalars: int
    #: time discretizations the platform's kernels serve (subset of
    #: vocab.TIME_METHODS).
    time_methods: Tuple[str, ...]
    #: explicit batched kernel routes with a template for this family
    #: (subset of vocab.EXPLICIT_ROUTES).
    kernel_routes: Tuple[str, ...]
    #: ABFT closed-form checksum recurrence applies (ops/abft.py).
    abft: bool
    #: the diff subsystem's adjoints cover this operator.
    adjoint: bool
    #: why the non-declared methods are missing — quoted verbatim by
    #: the gates' structured errors.
    gate_reason: str
    #: declared dtype casts the IR verifier's dtype-flow pass accepts
    #: in this family's traced programs: (src, dst) dtype-name pairs,
    #: the registry twin of the lint baseline's justified entries
    #: (analysis/dtype_flow.py). Entries may be flag-dependent (x64
    #: tracing inserts narrowings non-x64 tracing never creates), so
    #: an entry matching nothing is valid, but a cast matching no
    #: entry is a finding.
    cast_allowlist: Tuple[Tuple[str, str], ...] = ()

    @property
    def min_grid(self) -> int:
        """Smallest nx/ny with at least one interior cell: the held
        boundary ring is ``halo_width`` deep on each side."""
        return 2 * self.halo_width + 1

    def supports_method(self, method: str) -> Tuple[bool, Optional[str]]:
        """(ok, reason) for a solve ``method`` against this family's
        declared capabilities. ``method`` is a serve/config method
        name: 'explicit' checks ``time_methods``, 'auto'/explicit
        kernel routes check ``kernel_routes``, implicit methods check
        ``time_methods``. The reason string NAMES the unsupported
        combination — it becomes the ConfigError/Rejected message
        verbatim."""
        if method == "explicit":
            if "explicit" in self.time_methods:
                return True, None
            return False, (
                f"problem {self.name!r} does not support explicit "
                f"time stepping (supported time methods: "
                f"{self.time_methods})")
        if method in IMPLICIT_METHODS:
            if method in self.time_methods:
                return True, None
            return False, (
                f"problem {self.name!r} does not support method "
                f"{method!r}: {self.gate_reason} (supported time "
                f"methods: {self.time_methods})")
        if method == "auto" or method in self.kernel_routes:
            return True, None
        return False, (
            f"problem {self.name!r} has no {method!r} kernel template "
            f"(available routes: {self.kernel_routes}); use one of "
            f"those or 'auto'")


_IMPLICIT_5PT = ("the batched tridiagonal (ADI) and multigrid kernels "
                 "are built for the constant-coefficient 5-point "
                 "operator")

#: The declarative registry half: every family's spec, keyed by name.
#: Kernel-free on purpose — admission paths read this without jax.
FAMILY_SPECS = {
    "heat5": FamilySpec(
        name="heat5",
        title="5-point constant-coefficient heat (the reference)",
        halo_width=1, linear=True, state_arrays=1, reads_per_step=1,
        n_scalars=2,
        time_methods=("explicit",) + IMPLICIT_METHODS,
        kernel_routes=("jnp", "pallas", "band"),
        abft=True, adjoint=True,
        gate_reason="(fully supported)"),
    "varcoef": FamilySpec(
        name="varcoef",
        title="variable-coefficient (heterogeneous-material) diffusion",
        halo_width=1, linear=True, state_arrays=3, reads_per_step=3,
        n_scalars=2,
        time_methods=("explicit",),
        kernel_routes=("jnp",),
        abft=False, adjoint=True,
        gate_reason=_IMPLICIT_5PT,
        # Under x64 tracing the coefficient-field builder's
        # jnp.linspace computes float64 endpoints narrowed to the f32
        # fields; the fields themselves are f32 end-to-end.
        cast_allowlist=(("float64", "float32"),)),
    "heat9": FamilySpec(
        name="heat9",
        title="4th-order 9-point (wide-stencil) heat",
        halo_width=2, linear=True, state_arrays=1, reads_per_step=1,
        n_scalars=2,
        time_methods=("explicit",),
        kernel_routes=("jnp", "pallas", "band"),
        abft=False, adjoint=False,
        gate_reason=_IMPLICIT_5PT + " (the 4th-order operator is "
                    "pentadiagonal per axis)"),
    "advdiff": FamilySpec(
        name="advdiff",
        title="advection-diffusion (central advection)",
        halo_width=1, linear=True, state_arrays=1, reads_per_step=1,
        n_scalars=4,
        time_methods=("explicit",),
        kernel_routes=("jnp", "pallas", "band"),
        abft=False, adjoint=False,
        gate_reason=_IMPLICIT_5PT + " (no advection terms in the "
                    "tridiagonal systems)"),
    "reactdiff": FamilySpec(
        name="reactdiff",
        title="reaction-diffusion (saturating nonlinear source)",
        halo_width=1, linear=False, state_arrays=1, reads_per_step=1,
        n_scalars=3,
        time_methods=("explicit",),
        kernel_routes=("jnp", "pallas", "band"),
        abft=False, adjoint=False,
        gate_reason="the nonlinear source term rules out the "
                    "Crank-Nicolson linear solves (and the ABFT "
                    "checksum recurrence); nonlinear families get "
                    "explicit stepping + probe/quarantine only"),
}

assert tuple(FAMILY_SPECS) == PROBLEMS, \
    "FAMILY_SPECS and vocab.PROBLEMS drifted"


def spec_for(problem: str) -> FamilySpec:
    """The declared spec, or a ValueError naming the vocabulary —
    raised as the caller's structured error type (ConfigError is a
    ValueError subclass; serve admission catches and re-codes)."""
    try:
        return FAMILY_SPECS[problem]
    except KeyError:
        raise ValueError(
            f"unknown problem {problem!r}; registered families: "
            f"{PROBLEMS}") from None


def supports_method(problem: str, method: str):
    """(ok, reason) — module-level convenience over ``spec_for``."""
    return spec_for(problem).supports_method(method)


def state_arrays(problem: str = DEFAULT_PROBLEM) -> int:
    """Grid-sized device arrays per member — the mesh scheduler's
    bytes-model multiplier (heat5 = 1: byte-identical routing)."""
    return spec_for(problem).state_arrays


def capability_matrix() -> dict:
    """problem -> {time_methods, kernel_routes, abft, adjoint,
    linear, halo_width} — the docs/PROBLEMS.md table and the CI
    ``problems-smoke`` assertion read the same source."""
    return {
        name: {
            "time_methods": spec.time_methods,
            "kernel_routes": spec.kernel_routes,
            "abft": spec.abft,
            "adjoint": spec.adjoint,
            "linear": spec.linear,
            "halo_width": spec.halo_width,
        }
        for name, spec in FAMILY_SPECS.items()
    }


# Re-exported family constants (vocab.py owns them; stability and the
# kernels bind the same values through this namespace).
ADVECTION_VELOCITY = ADVECTION_VELOCITY
REACTION_RATE = REACTION_RATE
