"""Generic batched ensemble runners over the problem registry.

models/ensemble.py's heat5 runners are kept VERBATIM (the jaxpr pins
hold them byte-identical); these are their family-generic twins, one
per explicit kernel route, parameterized by the registry's kernel
templates instead of the hardcoded 5-point update:

- jnp    — vmap of the engine fixed-step loop over ``family.step``
- pallas — one batched VMEM-resident kernel: SMEM scalar block grows
           from (1, 1, 2) to (1, 1, S) for the family's S scalar
           operands; the fori_loop traces ``family.step_value``
- band   — the gathered-strip temporally-blocked sweep with halo
           depth ``h = halo_width * T`` per sweep (the Bandishti et
           al. wider-stencil generalization, PAPERS.md): strips carry
           h rows, the keep-mask holds a ``halo_width``-deep global
           boundary ring, and pollution from the held LOCAL window
           edges advances ``halo_width`` rows per step — after T
           steps it reaches exactly the discarded h-row halo, never
           the kept band interior.

Convergence composes for free: ensemble's ``_run_batch_conv_kernel``
is runner-agnostic, so any family's fixed-step runner slots in as its
``runner=`` argument (the per-member residual is a plain difference
norm — family-independent).

Route legality is decided here (``pick_route``) from the declared
spec: a named route missing from ``kernel_routes`` is a structured
ConfigError naming the combination; 'auto' resolves pallas-if-fits
else band else jnp, restricted to the declared routes (heat5's
resolution is byte-identical to ``ensemble._pick_method``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from heat2d_tpu.models import engine
from heat2d_tpu.problems.base import spec_for
from heat2d_tpu.problems.registry import get_family
from heat2d_tpu.vocab import DEFAULT_PROBLEM, IMPLICIT_METHODS


def pick_route(problem: str, method: str, nx: int, ny: int) -> str:
    """Resolve a serve/config ``method`` to a concrete kernel route
    for ``problem``, enforcing the declared capability matrix. Raises
    ConfigError (the structured validation type) naming the
    unsupported combination. heat5 + auto resolves exactly as
    ``ensemble._pick_method`` (pallas when a member fits VMEM, band
    otherwise) — the pinned legacy behavior."""
    from heat2d_tpu.config import ConfigError

    spec = spec_for(problem)
    ok, reason = spec.supports_method(method)
    if not ok:
        raise ConfigError(reason)
    if method in IMPLICIT_METHODS:
        return method
    if method != "auto":
        return method
    from heat2d_tpu.ops.pallas_stencil import fits_vmem
    routes = spec.kernel_routes
    if "pallas" in routes and fits_vmem((nx, ny)):
        return "pallas"
    if "band" in routes:
        return "band"
    return "jnp"


# --------------------------------------------------------------------- #
# jnp route — vmap of the engine loop over the family's reference step
# --------------------------------------------------------------------- #

def _run_batch_jnp_family(u0, cxs, cys, *, steps, family):
    def solve_one(u, cx, cy):
        u, _ = engine.run_fixed(lambda v: family.step(v, cx, cy), u,
                                steps)
        return u

    return jax.vmap(solve_one)(u0, cxs, cys)


# --------------------------------------------------------------------- #
# pallas route — batched VMEM-resident kernel, S-scalar SMEM block
# --------------------------------------------------------------------- #

def _family_ensemble_kernel(s_ref, u_ref, out_ref, *, steps, step_value,
                            n_scalars):
    scalars = tuple(s_ref[0, 0, k] for k in range(n_scalars))
    u = u_ref[0]
    u = jax.lax.fori_loop(0, steps,
                          lambda _, v: step_value(v, *scalars), u,
                          unroll=False)
    out_ref[0] = u


def _scal_block(family, cxs, cys):
    """(B, 1, S) SMEM operand block: the family's scalar mapping of
    the request's two coefficient knobs (family constants ride as
    traced values so one executable serves every member)."""
    return jnp.stack(family.scalars(cxs, cys), axis=1)[:, None, :]


def _run_batch_pallas_family(u0, cxs, cys, *, steps, family):
    from heat2d_tpu.ops.pallas_stencil import (_interpret, _mem_spaces,
                                               _parallel_grid)

    b, nx, ny = u0.shape
    s = family.spec.n_scalars
    scal = _scal_block(family, cxs, cys)
    mspace, smem = _mem_spaces()
    grid_spec = pl.GridSpec(
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, 1, s), lambda i: (i, 0, 0), **smem),
            pl.BlockSpec((1, nx, ny), lambda i: (i, 0, 0), **mspace),
        ],
        out_specs=pl.BlockSpec((1, nx, ny), lambda i: (i, 0, 0),
                               **mspace),
    )
    return pl.pallas_call(
        functools.partial(_family_ensemble_kernel, steps=steps,
                          step_value=family.step_value,
                          n_scalars=s),
        out_shape=jax.ShapeDtypeStruct(u0.shape, u0.dtype),
        grid_spec=grid_spec,
        interpret=_interpret(),
        **_parallel_grid(1))(scal, u0)


# --------------------------------------------------------------------- #
# band route — gathered-strip sweeps with halo depth h = w * T
# --------------------------------------------------------------------- #

def _family_band_kernel(s_ref, up_ref, u_ref, dn_ref, out_ref, *, bm,
                        tsteps, w, nx, step_value, n_scalars):
    j = pl.program_id(1)
    h = w * tsteps
    scalars = tuple(s_ref[0, 0, k] for k in range(n_scalars))
    ext = jnp.concatenate([up_ref[0, 0], u_ref[0], dn_ref[0, 0]],
                          axis=0)
    gi = (j * bm - h
          + jax.lax.broadcasted_iota(jnp.int32, (bm + 2 * h, 1), 0))
    keep = (gi <= w - 1) | (gi >= nx - w)
    from heat2d_tpu.ops.pallas_stencil import _unrolled_steps
    out_ref[0] = _unrolled_steps(
        tsteps,
        lambda v: jnp.where(keep, v, step_value(v, *scalars)),
        ext)[h:-h]


def _family_band_sweep(scal, u, bm, tsteps, family, nx, ny):
    from heat2d_tpu.ops.pallas_stencil import (_interpret, _mem_spaces,
                                               _parallel_grid,
                                               _row_strips)

    b, m, n = u.shape
    nblk = m // bm
    w = family.spec.halo_width
    s = family.spec.n_scalars
    h = w * tsteps
    zeros = jnp.zeros((b, 1, h, n), u.dtype)
    ups, dns = _row_strips(u.reshape(b, nblk, bm, n), h, zeros, zeros)
    mspace, smem = _mem_spaces()
    grid_spec = pl.GridSpec(
        grid=(b, nblk),
        in_specs=[
            pl.BlockSpec((1, 1, s), lambda i, j: (i, 0, 0), **smem),
            pl.BlockSpec((1, 1, h, n), lambda i, j: (i, j, 0, 0),
                         **mspace),
            pl.BlockSpec((1, bm, n), lambda i, j: (i, j, 0), **mspace),
            pl.BlockSpec((1, 1, h, n), lambda i, j: (i, j, 0, 0),
                         **mspace),
        ],
        out_specs=pl.BlockSpec((1, bm, n), lambda i, j: (i, j, 0),
                               **mspace),
    )
    return pl.pallas_call(
        functools.partial(_family_band_kernel, bm=bm, tsteps=tsteps,
                          w=w, nx=nx, step_value=family.step_value,
                          n_scalars=s),
        out_shape=jax.ShapeDtypeStruct(u.shape, u.dtype),
        grid_spec=grid_spec,
        interpret=_interpret(),
        input_output_aliases={2: 0},
        **_parallel_grid(2))(scal, ups, u, dns)


def _run_batch_band_family(u0, cxs, cys, *, steps, family):
    from heat2d_tpu.ops import pallas_stencil as ps

    b, nx, ny = u0.shape
    w = family.spec.halo_width
    # The shared gathered-strip schedule (shallow-band reduction keeps
    # the per-sweep halo depth w*t below the band height) — the same
    # plan the IR verifier re-derives when checking traced strip depths.
    bm, m_pad, t, _ = ps.band_plan(nx, ny, u0.dtype, halo_width=w)
    u = u0
    if m_pad > nx:
        u = jnp.pad(u, ((0, 0), (0, m_pad - nx), (0, 0)))
    scal = _scal_block(family, cxs, cys)
    nsweeps, rem = divmod(steps, t)
    if nsweeps:
        u = jax.lax.fori_loop(
            0, nsweeps,
            lambda _, v: _family_band_sweep(scal, v, bm, t, family,
                                            nx, ny),
            u, unroll=False)
    if rem:
        u = _family_band_sweep(scal, u, bm, rem, family, nx, ny)
    return u[:, :nx] if m_pad > nx else u


# --------------------------------------------------------------------- #
# Dispatch — the ensemble layer's entry points
# --------------------------------------------------------------------- #

_ROUTE_RUNNERS = {
    "jnp": _run_batch_jnp_family,
    "pallas": _run_batch_pallas_family,
    "band": _run_batch_band_family,
}


def fixed_runner(problem: str, route: str):
    """The family's fixed-step batch runner for a resolved explicit
    route — signature-compatible with ensemble._BATCH_RUNNERS values
    (``(u0, cxs, cys, *, steps) -> batch``), so the convergence
    chunked loop and the mesh shard_map wrap it unchanged."""
    if problem == DEFAULT_PROBLEM:
        from heat2d_tpu.models import ensemble
        return ensemble._BATCH_RUNNERS[route]
    try:
        base = _ROUTE_RUNNERS[route]
    except KeyError:
        raise ValueError(
            f"no generic batch runner for route {route!r} "
            f"(explicit routes: {tuple(_ROUTE_RUNNERS)})") from None
    return functools.partial(base, family=get_family(problem))
