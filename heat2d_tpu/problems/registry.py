"""Runtime problem registry — spec + bound kernels per family.

``base.FAMILY_SPECS`` is the jax-free declarative half; this module
binds each spec to its kernel templates (``problems/kernels.py``) and
exposes the lookup the dispatch spine uses on device-side paths:

    fam = get_family("advdiff")
    step = fam.step                  # u -> u' (jnp reference form)
    vals = fam.step_value            # value-form (Pallas templates)
    ops  = fam.scalars(cxs, cys)     # SMEM scalar operands, len S

The two kernel forms plus the numpy oracle are THE contract a family
ships (tests/test_problems.py pins them against each other); adding a
family = one FamilySpec + these callables + a registry entry.

``register()`` exists so an out-of-tree scenario can plug in without
editing this package — the capability gates and resource models read
the spec it carries, so the whole platform (serve admission, mesh
routing, tune keys, roofline) follows for free.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional

from heat2d_tpu.problems import kernels as _k
from heat2d_tpu.problems.base import FAMILY_SPECS, FamilySpec


@dataclasses.dataclass(frozen=True)
class Family:
    """One registered problem family: the declared spec plus the
    kernel templates bound to it.

    - ``step(u, cx, cy)`` — jnp reference step (at-based interior
      update; the solver's serial mode and the vmapped jnp batch
      runner build on it).
    - ``step_value(u, *scalars)`` — value-form template (concatenate
      reassembly, Mosaic-safe) with exactly ``spec.n_scalars`` scalar
      operands; the generic Pallas ensemble/band kernels trace it.
    - ``scalars(cx, cy)`` — maps the request's two coefficient knobs
      to the family's scalar-operand tuple (family constants ride as
      traced values so one compiled kernel serves all members).
    - ``np_step(u, cx, cy)`` — numpy float64 golden oracle.
    - ``mode_factor(nx, ny, cx, cy)`` — analytic per-step
      amplification of the lowest sine mode, when the family has one
      (linear, constant-coefficient); None otherwise.
    """

    spec: FamilySpec
    step: Callable
    step_value: Callable
    scalars: Callable
    np_step: Callable
    mode_factor: Optional[Callable] = None

    @property
    def name(self) -> str:
        return self.spec.name


def _heat5_mode_factor(nx, ny, cx, cy):
    from heat2d_tpu.ops.analytic import mode_decay_factor
    return mode_decay_factor(nx, ny, cx, cy)


_FAMILIES: Dict[str, Family] = {}


def register(family: Family) -> Family:
    """Add (or replace) a family. The spec must already satisfy the
    base contract; out-of-tree specs just construct FamilySpec."""
    _FAMILIES[family.name] = family
    return family


def get_family(problem: str) -> Family:
    try:
        return _FAMILIES[problem]
    except KeyError:
        raise ValueError(
            f"unknown problem {problem!r}; registered families: "
            f"{tuple(_FAMILIES)}") from None


def family_names():
    return tuple(_FAMILIES)


register(Family(
    spec=FAMILY_SPECS["heat5"],
    step=_k.heat5_step,
    step_value=_k.heat5_step_value,
    scalars=_k.heat5_scalars,
    np_step=_k.heat5_np_step,
    mode_factor=_heat5_mode_factor,
))

register(Family(
    spec=FAMILY_SPECS["varcoef"],
    step=_k.varcoef_step,
    # varcoef carries per-cell coefficient FIELDS: no value-form
    # scalar-operand template exists (kernel_routes declares jnp-only;
    # the route gate rejects pallas/band before anything traces this).
    step_value=_k.varcoef_step,
    scalars=_k.varcoef_scalars,
    np_step=_k.varcoef_np_step,
))

register(Family(
    spec=FAMILY_SPECS["heat9"],
    step=_k.heat9_step,
    step_value=_k.heat9_step_value,
    scalars=_k.heat9_scalars,
    np_step=_k.heat9_np_step,
    mode_factor=_k.heat9_mode_factor,
))

register(Family(
    spec=FAMILY_SPECS["advdiff"],
    step=_k.advdiff_step,
    step_value=_k.advdiff_step_value,
    scalars=_k.advdiff_scalars,
    np_step=_k.advdiff_np_step,
))

register(Family(
    spec=FAMILY_SPECS["reactdiff"],
    step=_k.reactdiff_step,
    step_value=_k.reactdiff_step_value,
    scalars=_k.reactdiff_scalars,
    np_step=_k.reactdiff_np_step,
))
