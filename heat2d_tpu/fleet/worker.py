"""Fleet worker — one ``SolveServer`` behind a stdio wire.

Runs as a subprocess of the fleet supervisor (``python -m
heat2d_tpu.fleet.worker``): a full serving stack (micro-batcher,
worker-local result cache, retry/watchdog/breaker) whose front door is
the JSONL protocol in ``fleet/wire.py`` instead of an in-process
``submit()``. The worker is deliberately BORING: all fleet policy —
routing, failover, cross-worker dedup, quotas — lives in the
supervisor/router process; a worker just serves what it is handed and
proves it is alive.

Liveness: a daemon thread heartbeats every ``--heartbeat`` seconds.
The chaos hook ``chaos.heartbeat_point()`` sits in front of each beat
(``HEAT2D_CHAOS_HEARTBEAT_DROP_AFTER`` makes a worker go silent while
still serving — the gray failure the supervisor must catch on
heartbeat age alone), and ``chaos.worker_request_point()`` sits in
each request pickup (``HEAT2D_CHAOS_WORKER_KILL_AFTER`` hard-kills
mid-load; ``HEAT2D_CHAOS_SLOW_WORKER_S`` makes a straggler). Chaos
config arrives via the environment, so the supervisor can aim a
campaign at individual workers with per-slot env vars.

Shutdown: a ``{"event": "shutdown"}`` line — or stdin EOF, which is
what a dead supervisor looks like — drains the server gracefully
(``stop(drain=True)``: every admitted request resolves and its
response line is flushed before exit 0). No orphaned worker outlives
its supervisor.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import threading

from heat2d_tpu.analysis.locks import AuditedLock

log = logging.getLogger("heat2d_tpu.fleet.worker")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="heat2d-tpu-fleet-worker",
        description="fleet worker: a SolveServer behind the JSONL "
                    "stdio wire (spawned by the fleet supervisor)")
    p.add_argument("--worker-id", type=int, default=0)
    p.add_argument("--heartbeat", type=float, default=0.25, metavar="S")
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--max-delay", type=float, default=0.005)
    p.add_argument("--queue-depth", type=int, default=256)
    p.add_argument("--cache-size", type=int, default=256)
    p.add_argument("--timeout", type=float, default=30.0)
    return p


def _warm_signature(server, emit, rid: int, spec: dict) -> None:
    """Compile the signature's base program (capacity 1) and report
    warm. Deliberately NOT the whole padded-capacity ladder: wider
    capacities compile on demand, each a one-time stall shared by the
    batch that needs it — pre-compiling them here was measured to
    starve the serving cores for seconds after every restart (the cure
    worse than the blip, especially on small hosts). The gate exists
    to keep a FULLY cold worker out of the hot path, and one compiled
    program per hot signature is exactly that line."""
    from heat2d_tpu.serve.schema import SolveRequest
    try:
        req = SolveRequest.from_dict(spec)
        server.engine.solve_batch([req])
    except Exception as e:  # noqa: BLE001 — a failed warmup must not
        #                     keep the worker out of the routing set
        log.warning("warmup failed for %s: %r", spec, e)
    emit({"id": rid, "ok": True, "warm": True})


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from heat2d_tpu.fleet import wire
    from heat2d_tpu.obs import MetricsRegistry, flight, tracing
    from heat2d_tpu.resil import chaos
    from heat2d_tpu.serve.schema import (Rejected, SolveRequest,
                                         attach_trace)
    from heat2d_tpu.serve.server import SolveServer

    registry = MetricsRegistry()
    service = f"worker{args.worker_id}"
    # Observability is env-armed (both opt-in, both free when unset):
    # the router CLI sets HEAT2D_TRACE_DIR / HEAT2D_FLIGHT_DIR and the
    # supervisor passes the environment through, so every worker joins
    # the tracing campaign and carries a black box the chaos kill
    # points will flush (docs/OBSERVABILITY.md).
    tracing.activate_from_env(service=service)
    flight.maybe_install_from_env(service=service, registry=registry)

    # Mesh serving is env-armed like every worker knob (the supervisor
    # passes the environment through): HEAT2D_MESH_SERVE=1 swaps the
    # single-chip engine for the mesh-aware one, so a fleet can run
    # every worker's buckets sharded over that worker's attached
    # devices (heat2d-tpu-load --target fleet --mesh sets it).
    engine = None
    if os.environ.get("HEAT2D_MESH_SERVE", "") not in ("", "0"):
        from heat2d_tpu.mesh import MeshEnsembleEngine
        # --max-batch becomes the per-chip bound (scales with the
        # worker's attached mesh instead of being discarded)
        engine = MeshEnsembleEngine(registry=registry,
                                    max_batch_per_chip=args.max_batch)
    server = SolveServer(
        max_batch=args.max_batch, max_delay=args.max_delay,
        max_queue=args.queue_depth, cache_size=args.cache_size,
        default_timeout=args.timeout,
        registry=registry, engine=engine).start()

    wlock = AuditedLock("fleet.worker.wire")

    def emit(obj: dict) -> None:
        line = json.dumps(obj)
        with wlock:
            try:
                sys.stdout.write(line + "\n")
                sys.stdout.flush()
            except (BrokenPipeError, OSError):
                # supervisor is gone; the stdin EOF will end the loop
                pass

    stop_hb = threading.Event()

    def hb_loop() -> None:
        while not stop_hb.wait(args.heartbeat):
            if chaos.heartbeat_point():
                emit({"event": "hb", "worker": args.worker_id})

    threading.Thread(target=hb_loop, name="heat2d-fleet-hb",
                     daemon=True).start()
    warm_threads: list = []
    ready = {"event": "ready", "pid": os.getpid(),
             "worker": args.worker_id, "protocol": wire.PROTOCOL}
    # The tuning-db stamp this worker is serving under (HEAT2D_TUNE_DB
    # arrives through the supervisor's env): path + epoch + validated.
    # The control plane's rollout gate reads it off the ready line to
    # prove which config GENERATION every worker runs — a crash
    # restart mid-rollout must always report the validated incumbent,
    # never a candidate (docs/CONTROL.md).
    try:
        from heat2d_tpu.tune import runtime as tune_runtime
        info = tune_runtime.describe_active()
        if info is not None:
            ready["tune"] = info
    except Exception as e:  # noqa: BLE001 — a broken db must not keep
        #                     the worker from serving (it degrades to
        #                     the heuristic anyway)
        log.warning("tune-db stamp unavailable: %r", e)
    emit(ready)

    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            msg = json.loads(line)
        except ValueError:
            log.warning("worker %d: skipping unparseable line",
                        args.worker_id)
            continue
        if msg.get("event") == "shutdown":
            break
        if "id" not in msg:
            continue
        rid = msg["id"]
        if msg.get("event") == "warmup":
            # Warm-restart: compile this signature's base program off
            # the request path, then report warm (_warm_signature on
            # why only the base). Not client load: it bypasses the
            # chaos request hook and the batcher (a direct engine
            # launch).
            t = threading.Thread(
                target=_warm_signature,
                args=(server, emit, rid, msg.get("req") or {}),
                name="heat2d-fleet-warmup", daemon=True)
            warm_threads.append(t)
            t.start()
            continue
        # The dispatch's trace context (absent on old-supervisor
        # lines). The pickup marker is emitted BEFORE the chaos point:
        # when HEAT2D_CHAOS_WORKER_KILL_AFTER fires here, the flight
        # recorder's flushed ring already holds the in-flight
        # request's span — the post-mortem names what died with us.
        ctx = wire.decode_trace(msg)
        if ctx is not None and tracing.enabled():
            tracing.event("fleet.recv", parent=ctx, rid=rid,
                          worker=args.worker_id)
        # Fault-injection point: slow-worker latency and the mid-load
        # hard kill both land here — the request is accepted (the
        # supervisor holds it in flight) but may never be answered.
        chaos.worker_request_point()
        try:
            req = SolveRequest.from_dict(msg.get("req") or {})
        except Rejected as e:
            emit(wire.encode_rejection(rid, e))
            continue
        if ctx is not None:
            attach_trace(req, ctx)  # serve spans nest under the wire's
        fut = server.submit(req)

        def _done(f, rid=rid, ctx=ctx):
            exc = f.exception()
            if exc is None:
                emit(wire.encode_result(rid, f.result()))
            else:
                emit(wire.encode_rejection(rid, exc))
            if ctx is not None and tracing.enabled():
                tracing.event("fleet.reply", parent=ctx, rid=rid,
                              ok=exc is None, worker=args.worker_id)

        fut.add_done_callback(_done)

    # Graceful exit: drain resolves every in-flight future, and each
    # resolution's done-callback emits its response before we return.
    # An in-flight warmup compile must finish first — tearing the
    # interpreter down under an active XLA compile is not a clean exit.
    for t in warm_threads:
        t.join(timeout=120)
    server.stop(drain=True)
    stop_hb.set()
    return 0


if __name__ == "__main__":
    sys.exit(main())
