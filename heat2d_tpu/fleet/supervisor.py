"""Worker-pool supervisor — spawn, watch, fence, restart.

Owns N worker subprocesses (``fleet/worker.py``) and nothing about
requests: the router above it keeps the in-flight bookkeeping, the
supervisor keeps the PROCESSES — spawn with per-slot env (chaos
campaigns aim at individual workers), read their stdout on a thread
per worker, and run one monitor loop that declares a worker dead on
either signal:

- **process exit** (``poll()`` — a crash, an injected
  ``os._exit(137)``);
- **heartbeat age** (no line from the worker within
  ``heartbeat_timeout`` — catches the gray failure where the process
  is alive but silent: a dropped-heartbeat campaign, a wedged
  runtime, a stop-the-world hang).

Declaring death FENCES first: the process is killed before the router
hears ``on_worker_lost``, so a half-dead worker cannot race its
replacement with late answers (the wire's per-dispatch ids make such
lines harmless anyway — fencing just keeps the property structural).

Restarts are automatic with FULL-JITTERED capped exponential backoff
(``resil.retry.RetryPolicy(jitter=True)``): N workers killed by the
same fault come back decorrelated instead of thundering-herding the
same signature. The attempt counter resets when a replacement reports
ready, so a stable worker earns back a fast restart.

The supervisor's shutdown is graceful end to end: each worker gets a
``shutdown`` line (drain: in-flight answers flush before exit 0), then
an escalating terminate/kill for stragglers. ``stop()`` returns True
iff every CURRENT worker exited cleanly — the chaos soak's "clean
supervisor exit" assertion.
"""

from __future__ import annotations

import json
import logging
import os
import random
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

from heat2d_tpu.analysis.locks import AuditedLock, guarded_by
from heat2d_tpu.resil.retry import RetryPolicy, wait_for

log = logging.getLogger("heat2d_tpu.fleet")

#: default jittered backoff for worker restarts (docstring above)
DEFAULT_RESTART_POLICY = RetryPolicy(max_attempts=1000, base_delay=0.2,
                                     backoff=2.0, max_delay=5.0,
                                     jitter=True)


class WorkerGone(RuntimeError):
    """Raised by ``send`` when the target worker's pipe is gone; the
    router treats it like a death it just hasn't been told about yet."""


class WorkerHandle:
    """One live worker process + its liveness state."""

    def __init__(self, slot: int, proc: subprocess.Popen):
        self.slot = slot
        self.proc = proc
        self.spawned = time.monotonic()
        self.last_seen = self.spawned
        self.ready = False
        self.dead = False
        #: a deliberate drain (restart/retire) is in progress: the
        #: shutdown line is — or is about to be — in the pipe. Checked
        #: UNDER ``write_lock`` by ``_write``, which closes the
        #: admission race structurally: stdin is FIFO, so any request
        #: line that won the lock before the drain fence was processed
        #: before the worker exits, and no line can land after the
        #: shutdown line (it would be silently dropped by the exiting
        #: worker and sit un-replayed until its deadline).
        self.draining = False
        self.restarted = False      # a replacement, not a first spawn
        self.via = "start"          # start | restart | rollout | scale_up
        self.overlay = None         # one-generation env overlay, if any
        self.info = None            # the worker's ready line (tune
        #                             stamp etc.), once it reports
        self.write_lock = AuditedLock(f"fleet.worker{slot}.pipe")

    def pid(self) -> int:
        return self.proc.pid


@guarded_by("_lock", "_handles", "_generations")
class Supervisor:
    """Spawn/watch/restart N fleet workers. See the module docstring
    for the failure model; the router wires the three callbacks."""

    def __init__(self, workers: int, *,
                 worker_args: Optional[List[str]] = None,
                 env: Optional[dict] = None,
                 per_worker_env: Optional[Dict[int, dict]] = None,
                 heartbeat_interval: float = 0.25,
                 heartbeat_timeout: float = 2.0,
                 ready_timeout: float = 60.0,
                 restart_policy: Optional[RetryPolicy] = None,
                 restart_rng: Optional[random.Random] = None,
                 max_restarts: Optional[int] = None,
                 registry=None,
                 on_response: Optional[Callable[[int, dict], None]] = None,
                 on_worker_lost: Optional[Callable[[int], None]] = None,
                 on_worker_ready: Optional[Callable[[int], None]] = None,
                 on_worker_retiring: Optional[Callable[[int], None]] = None,
                 on_tick: Optional[Callable[[], None]] = None,
                 clock: Optional[Callable[[], float]] = None):
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        self.n = workers
        self.worker_args = list(worker_args or [])
        self.env = dict(env or {})
        self.per_worker_env = {int(k): dict(v) for k, v in
                               (per_worker_env or {}).items()}
        self.heartbeat_interval = heartbeat_interval
        self.heartbeat_timeout = heartbeat_timeout
        self.ready_timeout = ready_timeout
        self.restart_policy = (DEFAULT_RESTART_POLICY
                               if restart_policy is None
                               else restart_policy)
        self.restart_rng = restart_rng
        self.max_restarts = max_restarts
        self.registry = registry
        self.on_response = on_response
        self.on_worker_lost = on_worker_lost
        self.on_worker_ready = on_worker_ready
        #: fires when a retirement is ADMITTED, strictly before the
        #: drain begins — the router takes the slot out of its routing
        #: set here, so no request admitted mid-retire can target the
        #: draining worker (the ordering the autoscaler's scale-down
        #: correctness rests on)
        self.on_worker_retiring = on_worker_retiring
        self.on_tick = on_tick
        #: the dispatch-guarding deadline clock (resil.retry.wait_for
        #: convention): injectable so ready-wait scenarios are
        #: deterministic on any host speed; None = wall monotonic
        self.clock = clock

        self._lock = AuditedLock("fleet.supervisor")
        self._handles: List[Optional[WorkerHandle]] = [None] * workers
        self._attempts = [0] * workers       # consecutive failed spawns
        self._restart_at = [None] * workers  # due time while slot dead
        self._spawn_counts = [0] * workers   # generations per slot
        #: slots permanently removed by ``retire_worker`` — never
        #: respawned (slot indices are not reused; a later
        #: ``add_worker`` appends a FRESH slot instead)
        self._retired: set = set()
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self.restarts = 0
        self.deaths = 0
        #: one row per worker GENERATION that reported ready: slot,
        #: pid, how it was spawned (start | restart | rollout), any
        #: one-generation env overlay it ran under, and the tune-db
        #: stamp it reported — the audit trail the control plane's
        #: no-unvalidated-serving invariant is asserted on
        #: (docs/CONTROL.md). Appended under ``_lock``; read through
        #: ``generations_snapshot()``.
        self._generations: List[dict] = []

    # -- lifecycle ----------------------------------------------------- #

    def start(self, wait_ready: bool = True) -> "Supervisor":
        self._stop.clear()      # stop()/start() cycles must re-arm
        #                         the monitor, not leave it stillborn
        for slot in range(self.n):
            self._spawn(slot)
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         name="heat2d-fleet-monitor",
                                         daemon=True)
        self._monitor.start()
        if wait_ready:
            # the ONE hand-rolled-timer-free deadline convention
            # (resil.retry.wait_for on Watchdog(clock=)): a frozen
            # injected clock waits forever, an advanced one times out
            # deterministically — no wall-clock flakes on slow hosts
            wait_for(lambda: all(h is not None and h.ready
                                 for h in self._handles),
                     self.ready_timeout, clock=self.clock)
        return self

    def stop(self, timeout: float = 30.0) -> bool:
        """Drain-shutdown every worker; True iff all exited 0."""
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=10)
        clean = True
        with self._lock:
            # dead handles awaiting restart were already reaped by the
            # death path; cleanliness is about the CURRENT workers
            handles = [h for h in self._handles
                       if h is not None and not h.dead]
            self._handles = [None] * self.n
        for h in handles:
            with h.write_lock:
                h.draining = True
            try:
                self._write(h, {"event": "shutdown"},
                            during_drain=True)
            except WorkerGone:
                pass
        deadline = time.monotonic() + timeout
        for h in handles:
            left = max(0.1, deadline - time.monotonic())
            try:
                rc = h.proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                log.warning("worker %d did not drain in time; killing",
                            h.slot)
                h.proc.kill()
                h.proc.wait(timeout=10)
                rc = None
            if rc != 0:
                clean = False
            self._close_pipes(h)
        self._gauge_alive()
        return clean

    def __enter__(self) -> "Supervisor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- the router's surface ------------------------------------------ #

    def alive_slots(self) -> List[int]:
        with self._lock:
            return [h.slot for h in self._handles
                    if h is not None and h.ready and not h.dead]

    def send(self, slot: int, obj: dict) -> None:
        with self._lock:
            h = self._handles[slot]
        if h is None or h.dead or h.draining:
            raise WorkerGone(f"worker {slot} is not running")
        self._write(h, obj)

    def kill_worker(self, slot: int) -> None:
        """Hard-kill a worker (the chaos soak's mid-load kill). The
        monitor detects the exit and runs the normal death path."""
        with self._lock:
            h = self._handles[slot]
        if h is not None:
            log.warning("chaos: hard-killing worker %d (pid %d)",
                        slot, h.pid())
            h.proc.kill()

    # -- the control plane's surface (docs/CONTROL.md) ------------------ #

    def worker_info(self, slot: int) -> Optional[dict]:
        """The CURRENT worker's ready line (pid, protocol, tune-db
        stamp), or None while the slot has no ready worker."""
        with self._lock:
            h = self._handles[slot]
        if h is None or h.dead or not h.ready:
            return None
        return dict(h.info or {})

    def generations_snapshot(self) -> List[dict]:
        """Every worker generation that reported ready, in order — the
        control plane's audit trail for the no-unvalidated-serving
        invariant."""
        with self._lock:
            return [dict(g) for g in self._generations]

    def update_slot_env(self, slot: int, env: dict) -> None:
        """DURABLY merge ``env`` into one slot's per-worker env: every
        future spawn of the slot (crash restarts included) carries it.
        Contrast ``restart_worker``'s overlay, which lives for exactly
        one generation."""
        with self._lock:
            self.per_worker_env.setdefault(int(slot), {}).update(env)

    def restart_worker(self, slot: int,
                       env_overlay: Optional[dict] = None,
                       timeout: float = 30.0) -> None:
        """Deliberate in-place restart of one slot — the control
        plane's rollout actuator. The old worker drains (shutdown
        line, then escalating kill); the replacement spawns with
        ``env_overlay`` applied on top of the durable env **for this
        generation only**: any LATER restart of the slot — including
        a crash restart mid-kill-storm — rebuilds the env from the
        durable config alone, so an overlay (candidate) config can
        never be resurrected by the failure path. Blocks until the
        old process exited and the replacement was spawned (not until
        it is ready — poll ``worker_info``/``alive_slots``)."""
        with self._lock:
            h = self._handles[slot]
            self._restart_at[slot] = None
            if h is not None:
                # hand the slot from the monitor to us: no death path,
                # no competing backoff restart
                h.dead = True
        unclean = False
        if h is not None:
            with h.write_lock:
                # flip the drain fence under the pipe lock: any send
                # that already won the lock wrote BEFORE this point
                # (FIFO — the worker processes it before exiting), any
                # later one is refused by _write's draining check
                h.draining = True
            try:
                self._write(h, {"event": "shutdown"},
                            during_drain=True)
            except WorkerGone:
                pass
            try:
                h.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                log.warning("worker %d did not drain for a deliberate "
                            "restart; killing", slot)
                h.proc.kill()
                try:
                    h.proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
            unclean = h.proc.returncode != 0
            if unclean:
                self._close_pipes(h)
            else:
                # clean drain: every answer was emitted before exit,
                # but the reader thread may still be pulling buffered
                # lines — close only our write end and let the reader
                # run to EOF (closing stdout under it drops answers)
                try:
                    if h.proc.stdin is not None:
                        h.proc.stdin.close()
                except OSError:
                    pass
        if unclean and self.on_worker_lost is not None:
            # exit != 0 covers both the forced kill above AND a worker
            # that was already dead/crashed when the restart began
            # (h.dead=True fenced the monitor's death path out): the
            # router must get the same worker-lost sweep the crash
            # path runs, or its in-flight records for this slot sit
            # until their deadline instead of replaying
            self.on_worker_lost(slot)
        self.restarts += 1
        if self.registry is not None:
            self.registry.counter("fleet_worker_restarts_total")
        log.info("deliberate restart of worker %d%s", slot,
                 " (env overlay)" if env_overlay else "")
        self._spawn(slot, overlay=env_overlay, via="rollout")

    # -- the autoscaler's surface (docs/CONTROL.md actuation) ----------- #

    def pool_size(self) -> int:
        """Provisioned (non-retired) slots — the unit count the
        capacity model sizes against. Includes slots whose worker is
        momentarily dead-awaiting-restart or still warming up: those
        chips are still PAID FOR, which is what sizing is about."""
        with self._lock:
            return self.n - len(self._retired)

    def provisioned_slots(self) -> List[int]:
        """The non-retired slot indices, ascending — the autoscaler
        picks its scale-down victims from the top of this list."""
        with self._lock:
            return [s for s in range(self.n) if s not in self._retired]

    def add_worker(self) -> int:
        """Scale-up actuation: append ONE fresh slot to the pool and
        spawn its worker (``via="scale_up"``). Returns the new slot
        index; the caller learns readiness the usual way
        (``on_worker_ready`` / ``alive_slots``). Slot indices grow
        monotonically — retired indices are never reused, so a slot
        number stays an unambiguous identity across the generations
        audit trail."""
        with self._lock:
            slot = self.n
            self.n += 1
            self._handles.append(None)
            self._attempts.append(0)
            self._restart_at.append(None)
            self._spawn_counts.append(0)
        log.info("scale-up: adding worker slot %d", slot)
        self._spawn(slot, via="scale_up")
        if self.registry is not None:
            self.registry.gauge("fleet_pool_size",
                                float(self.pool_size()))
        return slot

    def retire_worker(self, slot: int, timeout: float = 30.0) -> bool:
        """Scale-down actuation: drain-to-retire one slot, permanently.

        Ordering contract (the satellite fix this path exists for):
        the slot is FENCED before the drain begins —

        1. under ``_lock``: the slot joins ``_retired`` (no respawn,
           ever), its backoff timer clears, and its handle goes dead
           (``alive_slots`` stops listing it, ``send`` refuses it,
           the monitor's death path is disarmed);
        2. ``on_worker_retiring`` tells the router to drop the slot
           from its routing table;
        3. only THEN does the drain start: ``draining`` flips under
           the pipe's ``write_lock`` and the shutdown line goes out —
           so a request admitted mid-retire either wrote before the
           fence (FIFO: the worker answers it before exiting) or is
           refused with ``WorkerGone`` and re-dispatched. It can
           never land behind the shutdown line.

        In-flight work the worker already holds finishes during the
        drain (answers flush before exit 0). A drain that outlives
        ``timeout`` (on the supervisor's injectable ``clock``) is
        killed and ``on_worker_lost`` replays its in-flight requests.
        Returns True iff the drain was clean. Idempotent."""
        with self._lock:
            if slot in self._retired:
                return True
            if not 0 <= slot < self.n:
                raise ValueError(f"no such slot {slot}")
            h = self._handles[slot]
            self._retired.add(slot)
            self._restart_at[slot] = None
            if h is not None:
                h.dead = True   # fence: monitor, alive_slots, send
        if self.on_worker_retiring is not None:
            self.on_worker_retiring(slot)
        clean = True
        if h is not None:
            with h.write_lock:
                h.draining = True
            try:
                self._write(h, {"event": "shutdown"},
                            during_drain=True)
            except WorkerGone:
                pass
            drained = wait_for(lambda: h.proc.poll() is not None,
                               timeout, clock=self.clock)
            if not drained:
                log.warning("worker %d did not drain for retirement; "
                            "killing", slot)
                h.proc.kill()
                try:
                    h.proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
            clean = h.proc.returncode == 0
            if clean:
                # clean drain: answers were emitted before exit, but
                # the reader may still be pulling buffered lines —
                # close only stdin and let it run to EOF
                try:
                    if h.proc.stdin is not None:
                        h.proc.stdin.close()
                except OSError:
                    pass
            else:
                self._close_pipes(h)
        if not clean and self.on_worker_lost is not None:
            # same rationale as restart_worker: an unclean exit may
            # have dropped in-flight answers — the router must replay
            self.on_worker_lost(slot)
        log.info("worker %d retired (%s drain)", slot,
                 "clean" if clean else "unclean")
        if self.registry is not None:
            self.registry.counter("fleet_worker_retirements_total",
                                  outcome=("clean" if clean
                                           else "unclean"))
            self.registry.gauge("fleet_pool_size",
                                float(self.pool_size()))
        self._gauge_alive()
        return clean

    # -- spawn / death / restart --------------------------------------- #

    def _worker_cmd(self, slot: int) -> List[str]:
        return [sys.executable, "-m", "heat2d_tpu.fleet.worker",
                "--worker-id", str(slot),
                "--heartbeat", str(self.heartbeat_interval),
                *self.worker_args]

    def _worker_env(self, slot: int) -> dict:
        import heat2d_tpu
        env = dict(os.environ)
        # the worker must import this heat2d_tpu regardless of cwd or
        # whether the package is pip-installed in the child's env
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(heat2d_tpu.__file__)))
        env["PYTHONPATH"] = pkg_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH")
            else "")
        env.update(self.env)
        env.update(self.per_worker_env.get(slot, {}))
        return env

    def _spawn(self, slot: int, overlay: Optional[dict] = None,
               via: Optional[str] = None) -> None:
        env = self._worker_env(slot)
        if overlay:
            # ONE-generation overlay (restart_worker): applied to this
            # spawn only — never persisted, so a later crash restart
            # rebuilds from the durable env alone
            env.update(overlay)
        proc = subprocess.Popen(
            self._worker_cmd(slot), stdin=subprocess.PIPE,
            stdout=subprocess.PIPE, stderr=None,  # stderr passes through
            env=env, text=True, bufsize=1)
        h = WorkerHandle(slot, proc)
        h.overlay = dict(overlay) if overlay else None
        with self._lock:
            self._handles[slot] = h
            self._restart_at[slot] = None
            self._spawn_counts[slot] += 1
            h.restarted = self._spawn_counts[slot] > 1
            h.via = via or ("restart" if h.restarted else "start")
        threading.Thread(target=self._read_loop, args=(h,),
                         name=f"heat2d-fleet-reader-{slot}",
                         daemon=True).start()
        log.info("spawned worker %d (pid %d)", slot, proc.pid)

    def _read_loop(self, h: WorkerHandle) -> None:
        try:
            for line in h.proc.stdout:
                h.last_seen = time.monotonic()
                line = line.strip()
                if not line:
                    continue
                try:
                    msg = json.loads(line)
                except ValueError:
                    continue        # torn line from a killed worker
                ev = msg.get("event")
                if ev == "ready":
                    h.info = msg
                    h.ready = True
                    with self._lock:
                        self._attempts[h.slot] = 0
                        self._generations.append({
                            "slot": h.slot, "pid": h.pid(),
                            "via": h.via, "restarted": h.restarted,
                            "overlay": h.overlay,
                            "tune": msg.get("tune")})
                    self._gauge_alive()
                    log.info("worker %d ready (pid %d%s)", h.slot,
                             h.pid(),
                             ", restart" if h.restarted else "")
                    if self.on_worker_ready is not None:
                        self.on_worker_ready(h.slot,
                                             restarted=h.restarted,
                                             via=h.via)
                elif ev == "hb":
                    pass            # last_seen update above is the point
                elif "id" in msg and self.on_response is not None:
                    self.on_response(h.slot, msg)
        except (OSError, ValueError):
            pass                    # pipe torn down under the reader
        # EOF: the process is exiting; the monitor loop reaps it.

    def _write(self, h: WorkerHandle, obj: dict,
               during_drain: bool = False) -> None:
        """One request/control line into the worker's stdin.

        The ``draining`` re-check happens UNDER ``write_lock`` — the
        fence that makes deliberate drains race-free: ``send`` may
        have read ``draining=False`` an instant before the drain
        began, but it cannot WRITE after the shutdown line, because
        the drain path flips the flag and emits the shutdown while
        holding this same lock (``during_drain=True`` is that path's
        own pass). A line refused here raises ``WorkerGone`` and the
        router re-dispatches — instead of the old failure mode where
        the line landed behind the shutdown, was dropped by the
        exiting worker, and its request hung to deadline."""
        try:
            with h.write_lock:
                if h.draining and not during_drain:
                    raise WorkerGone(
                        f"worker {h.slot} is draining for a deliberate "
                        f"restart/retire")
                h.proc.stdin.write(json.dumps(obj) + "\n")
                h.proc.stdin.flush()
        except WorkerGone:
            raise
        except (BrokenPipeError, OSError, ValueError) as e:
            raise WorkerGone(f"worker {h.slot}: {e!r}") from None

    def _close_pipes(self, h: WorkerHandle) -> None:
        for f in (h.proc.stdin, h.proc.stdout):
            try:
                if f is not None:
                    f.close()
            except OSError:
                pass

    def _monitor_loop(self) -> None:
        poll = max(0.02, min(self.heartbeat_timeout / 4, 0.2))
        while not self._stop.wait(poll):
            now = time.monotonic()
            for slot in range(self.n):
                try:
                    self._monitor_slot(slot, now)
                except Exception:
                    # the monitor IS the fleet's failure detector: a
                    # transient here (Popen EAGAIN, a broken callback)
                    # must not kill supervision for every worker
                    log.exception("monitor pass failed for slot %d",
                                  slot)
            if self.on_tick is not None:
                try:
                    self.on_tick()
                except Exception:
                    log.exception("on_tick callback failed")

    def _monitor_slot(self, slot: int, now: float) -> None:
        with self._lock:
            h = self._handles[slot]
            due = self._restart_at[slot]
        if h is None or h.dead:
            if (due is not None and now >= due
                    and not self._stop.is_set()):
                self._restart(slot)
            return
        if h.proc.poll() is not None:
            self._declare_dead(h, "exit")
        elif (h.ready
              and now - h.last_seen > self.heartbeat_timeout):
            self._declare_dead(h, "heartbeat")
        elif (not h.ready
              and now - h.spawned > self.ready_timeout):
            self._declare_dead(h, "spawn_timeout")

    def _declare_dead(self, h: WorkerHandle, cause: str) -> None:
        rc = h.proc.poll()
        log.warning("worker %d declared dead (%s, rc=%s)", h.slot,
                    cause, rc)
        h.dead = True
        self.deaths += 1
        # FENCE before failover: a heartbeat-silent worker is still
        # serving — kill it so it cannot answer after its in-flight
        # work is replayed elsewhere.
        try:
            h.proc.kill()
            h.proc.wait(timeout=10)
        except (OSError, subprocess.TimeoutExpired):
            pass
        self._close_pipes(h)
        if self.registry is not None:
            self.registry.counter("fleet_worker_deaths_total",
                                  cause=cause)
        self._gauge_alive()
        with self._lock:
            attempt = self._attempts[h.slot]
            self._attempts[h.slot] += 1
            exhausted = (self.max_restarts is not None
                         and self._attempts[h.slot] > self.max_restarts)
            self._restart_at[h.slot] = (
                None if exhausted
                else time.monotonic() + self.restart_policy.delay(
                    attempt, rng=self.restart_rng))
        if exhausted:
            log.error("worker %d: restart budget exhausted (%d); "
                      "slot stays down", h.slot, self.max_restarts)
        if self.on_worker_lost is not None:
            self.on_worker_lost(h.slot)

    def _restart(self, slot: int) -> None:
        self.restarts += 1
        if self.registry is not None:
            self.registry.counter("fleet_worker_restarts_total")
        log.info("restarting worker %d (restart #%d)", slot,
                 self.restarts)
        try:
            self._spawn(slot)
        except Exception:
            # Popen can fail transiently (fork EAGAIN, fd exhaustion);
            # back off instead of hot-looping a failing spawn
            with self._lock:
                attempt = self._attempts[slot]
                self._attempts[slot] += 1
                self._restart_at[slot] = (
                    time.monotonic() + self.restart_policy.delay(
                        attempt, rng=self.restart_rng))
            log.exception("respawn of worker %d failed; retrying "
                          "with backoff", slot)

    def _gauge_alive(self) -> None:
        if self.registry is not None:
            self.registry.gauge("fleet_workers_alive",
                                len(self.alive_slots()))
