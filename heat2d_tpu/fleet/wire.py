"""Fleet wire protocol — JSON lines over a worker's stdio pipe.

One JSON object per line, both directions. The supervisor writes
request envelopes and control events to a worker's stdin; the worker
writes responses and liveness events to stdout. Grids travel base64 —
the payloads are small final states (a few KB for the serving grid
sizes), and a text protocol keeps the framing trivially debuggable
(``strace``/log-tail shows complete messages) and crash-safe: a worker
killed mid-line leaves one torn line the reader skips, never a
desynchronized binary stream.

supervisor -> worker::

    {"id": 7, "req": {...SolveRequest.spec()...},
     "trace": {"trace_id": "...", "span_id": "..."}}   # optional
    {"event": "shutdown"}              # drain and exit 0

``trace`` is the OPTIONAL distributed-tracing context of the router's
dispatch span (obs/tracing.py): a worker parents its serving spans on
it so ``heat2d-tpu-trace`` can stitch the cross-process timeline.
Strictly additive and envelope-level: lines without it parse
unchanged (an old supervisor drives a new worker untraced, a new
supervisor's trace field is ignored by an old worker's
``msg.get``-based reader), and it never enters the request spec —
trace context must not perturb content hashes or batch buckets.

worker -> supervisor::

    {"event": "ready", "pid": 1234, "worker": 0}
    {"event": "hb", "worker": 0}       # periodic heartbeat
    {"id": 7, "ok": true,  ...encode_result fields...}
    {"id": 7, "ok": false, "rejected": {...Rejected.to_record()...}}

``id`` is the supervisor's in-flight key: it is unique per DISPATCH
(a replayed request gets a fresh id), so a late line from a fenced
worker can never be confused with the replay's answer.
"""

from __future__ import annotations

import base64

from heat2d_tpu.serve.schema import Rejected, SolveResult

PROTOCOL = "heat2d-tpu/fleet-wire/v1"


def decode_trace(msg: dict):
    """The dispatch line's tracing context, or None — malformed and
    absent are the same non-event (back-compat is load-bearing: a
    fenced old worker's lines must never fail to parse)."""
    from heat2d_tpu.obs.tracing import TraceContext
    return TraceContext.from_wire(msg.get("trace"))


def encode_result(rid: int, res: SolveResult) -> dict:
    import numpy as np
    u = np.ascontiguousarray(np.asarray(res.u))
    return {
        "id": rid, "ok": True,
        "steps_done": int(res.steps_done),
        "content_hash": res.content_hash,
        "batch_size": int(res.batch_size),
        "worker_cache_hit": bool(res.cache_hit),
        "u_shape": [int(d) for d in u.shape],
        "u_dtype": str(u.dtype),
        "u_b64": base64.b64encode(u.tobytes()).decode("ascii"),
    }


def decode_result(msg: dict) -> SolveResult:
    """The worker's answer as a ``SolveResult``. ``u`` is a read-only
    numpy view over the decoded bytes — results are immutable by
    contract (the fleet cache shares them across callers)."""
    import numpy as np
    u = np.frombuffer(base64.b64decode(msg["u_b64"]),
                      dtype=msg["u_dtype"]).reshape(msg["u_shape"])
    return SolveResult(u=u, steps_done=int(msg["steps_done"]),
                       content_hash=msg["content_hash"],
                       batch_size=int(msg.get("batch_size", 1)))


def encode_rejection(rid: int, exc: BaseException) -> dict:
    if isinstance(exc, Rejected):
        return {"id": rid, "ok": False, "rejected": exc.to_record()}
    return {"id": rid, "ok": False,
            "rejected": {"rejected": "error", "message": repr(exc)}}


def decode_rejection(msg: dict) -> Rejected:
    d = dict(msg.get("rejected") or {})
    code = d.pop("rejected", "error")
    message = d.pop("message", "")
    return Rejected(code, message, **d)
