"""Fleet subsystem — supervised multi-worker serving with
chaos-proven failover (ROADMAP item 5).

The reference's ``grad1612_mpi_heat.c`` runs one fixed set of ranks
and dies whole if any rank dies; a single ``SolveServer`` (PR 2/3) has
the same blast radius — one process. This package composes the
existing ingredients (content-hashed requests, admission control,
chaos harness, circuit breaker, jittered retry) into a pool that
SURVIVES worker loss under live traffic:

- ``worker``     — one ``SolveServer`` behind a JSONL stdio wire,
                   heartbeating, chaos-injectable, drain-on-shutdown.
- ``supervisor`` — spawn/watch/fence/restart N workers: death on
                   process exit OR heartbeat age, fence before
                   failover, full-jittered restart backoff.
- ``router``     — ``FleetServer``: rendezvous routing by compiled
                   signature, in-flight replay to survivors (dedup'd
                   by the sha256 content hash — at most a latency
                   blip, never a lost or duplicated answer), a shared
                   cross-worker result cache that outlives any worker,
                   per-tenant quotas/priorities, and the degraded-mode
                   breaker fed by worker deaths.
- ``wire``       — the JSONL protocol (per-dispatch ids make late
                   answers from fenced workers structurally harmless).
- ``cli``        — ``heat2d-tpu-fleet``: the chaos soak that proves
                   the composition (kill k of N mid-load; assert
                   bitwise-correct answers, nothing silently lost,
                   throughput recovery, clean exit).

Everything here is host-side orchestration: workers run the exact
serving stack a standalone ``SolveServer`` runs, so fleet answers are
bitwise the single-process answers (the soak's oracle check).
"""

from heat2d_tpu.fleet.router import (FleetServer, TenantPolicy,
                                     route_signature)
from heat2d_tpu.fleet.supervisor import Supervisor, WorkerGone

__all__ = ["FleetServer", "Supervisor", "TenantPolicy", "WorkerGone",
           "route_signature"]
