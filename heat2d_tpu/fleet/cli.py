"""``heat2d-tpu-fleet`` — drive a supervised worker pool, optionally
under chaos, and prove the fleet invariants from outside.

The soak (``--soak S``) sustains a closed-loop load of ``--concurrency``
outstanding requests over a rotating set of signatures for S seconds.
With ``--chaos``, ``--kill K`` workers are hard-killed at the soak's
midpoint (the supervisor must detect, fail over, and restart them).
After the load drains, the CLI asserts the chaos-soak acceptance
criteria and exits nonzero if any fail:

1. **Zero incorrect results** — every distinct request is re-solved by
   a single-worker ORACLE (an in-process ``SolveServer``) and every
   fleet response must match it bitwise.
2. **Nothing silently lost** — submitted == completed + structured
   ``Rejected`` (and under default sizing, zero rejections).
3. **Throughput recovers** — after the kill, the completion rate over
   a sliding window must return to within ``--recovery-margin``
   (default 20%) of the pre-kill steady state. Recovery is MEASURED,
   not scheduled: the load keeps running until the bar clears (the
   time-to-recovery is reported) or 3x the nominal soak elapses
   (a failure).
4. **Clean exit** — every worker drains and exits 0 at shutdown.

``--metrics-out`` writes the registry JSONL + a ``kind="fleet"`` run
record (soak phases, throughput windows, worker deaths/restarts,
replay counts). CI's ``fleet-soak`` job runs exactly this on CPU with
3 workers and one mid-load kill.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import threading
import time

from heat2d_tpu.analysis.locks import AuditedLock


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="heat2d-tpu-fleet",
        description="supervised multi-worker serving pool with "
                    "chaos-proven failover (docs/FLEET.md)")
    p.add_argument("--workers", type=int, default=3,
                   help="worker subprocesses in the pool")
    p.add_argument("--soak", type=float, default=None, metavar="S",
                   help="run the sustained-load soak for S seconds "
                        "and assert the fleet invariants")
    p.add_argument("--chaos", action="store_true",
                   help="with --soak: hard-kill --kill workers at the "
                        "soak midpoint (failover + restart must absorb "
                        "it)")
    p.add_argument("--kill", type=int, default=1, metavar="K",
                   help="workers to kill with --chaos (k of N)")
    p.add_argument("--concurrency", type=int, default=8,
                   help="outstanding requests in the closed loop")
    p.add_argument("--nx", type=int, default=16)
    p.add_argument("--ny", type=int, default=16)
    p.add_argument("--steps", type=int, default=4)
    p.add_argument("--signatures", type=int, default=2,
                   help="distinct compiled signatures in the request "
                        "mix (steps, steps+1, ...)")
    p.add_argument("--recovery-margin", type=float, default=0.2,
                   help="allowed post-restart throughput drop vs the "
                        "pre-kill window (0.2 = within 20%%)")
    p.add_argument("--window", type=float, default=None, metavar="S",
                   help="throughput measurement window (default: a "
                        "third of the soak)")
    p.add_argument("--heartbeat-timeout", type=float, default=2.0)
    p.add_argument("--timeout", type=float, default=60.0,
                   help="per-request fleet deadline")
    p.add_argument("--metrics-out", default=None, metavar="PATH",
                   help="write telemetry JSONL (fleet_* families + the "
                        "kind='fleet' run record)")
    p.add_argument("--trace-dir", default=None, metavar="DIR",
                   help="arm fleet-wide distributed tracing AND the "
                        "workers' crash flight recorders: the router "
                        "and every worker write span JSONL into DIR "
                        "(workers inherit HEAT2D_TRACE_DIR/"
                        "HEAT2D_FLIGHT_DIR through the supervisor); "
                        "merge with heat2d-tpu-trace DIR. A chaos-"
                        "killed worker leaves a digest-sidecar'd "
                        "post-mortem of its last seconds")
    p.add_argument("--worker-env", action="append", default=[],
                   metavar="SLOT:KEY=VAL",
                   help="extra env for ONE worker slot (repeatable) — "
                        "e.g. 0:HEAT2D_CHAOS_WORKER_KILL_AFTER=5 aims "
                        "a chaos self-kill at worker 0 (unlike the "
                        "supervisor-side --chaos SIGKILL, a self-kill "
                        "flushes the worker's flight recorder)")
    p.add_argument("--slo-p99", type=float, default=None, metavar="S",
                   help="per-signature p99 latency target; evaluation "
                        "lands in the run record's 'slo' rows and the "
                        "slo_* gauges (docs/OBSERVABILITY.md)")
    p.add_argument("--slo-error-budget", type=float, default=0.001,
                   metavar="F",
                   help="allowed failure fraction per signature")
    p.add_argument("--control", action="store_true",
                   help="arm the SLO-driven control plane beside the "
                        "soak (docs/CONTROL.md): a BurnWindow watches "
                        "per-signature burn and sheds/retunes before "
                        "the breaker trips; workers serve under the "
                        "control db directory's validated tuning db")
    p.add_argument("--control-db", default=None, metavar="DIR",
                   help="directory for the control plane's "
                        "validated.json / candidate.json tuning dbs "
                        "(default: a temp dir)")
    p.add_argument("--control-rollout", action="store_true",
                   help="with --control: at the soak midpoint, stage "
                        "a candidate db for the hottest signature "
                        "(simulated measurement backend) and run one "
                        "safe rollout — canary, bitwise parity, "
                        "observation, promote or auto-revert — while "
                        "the load keeps running")
    p.add_argument("--control-bad-candidate", action="store_true",
                   help="inject a deliberately-bad candidate: the "
                        "canary's one-generation env overlay carries "
                        "HEAT2D_CHAOS_SLOW_WORKER_S, so the rollout "
                        "MUST measure the regression and auto-revert "
                        "with bitwise post-revert parity (the CLI "
                        "fails otherwise)")
    p.add_argument("--control-storm-phase", default=None,
                   choices=["canary", "parity", "observe", "promote"],
                   help="arm a chaos kill storm (every worker hard-"
                        "killed) to land when the rollout reaches "
                        "this window; the CLI then asserts no worker "
                        "generation ever served a non-validated "
                        "config")
    p.add_argument("--control-observe", type=float, default=2.0,
                   metavar="S",
                   help="rollout observation window (paired probes + "
                        "windowed SLO burn)")
    p.add_argument("--autoscale", action="store_true",
                   help="run the ELASTIC soak instead of the chaos "
                        "soak: a compressed diurnal traffic envelope "
                        "(load/synth 'diurnal' profile) drives the "
                        "control plane's capacity advice through an "
                        "autoscale.Actuator — the worker pool must "
                        "follow the envelope both directions, SLOs "
                        "must hold through every resize, chip-seconds "
                        "must land below static provisioning at "
                        "--workers, and one live-migrated inverse job "
                        "must finish bitwise-identical to its "
                        "unmigrated oracle (docs/CONTROL.md "
                        "'Actuation')")
    p.add_argument("--autoscale-util", type=float, default=0.6,
                   metavar="F",
                   help="target utilization: the capacity fit is "
                        "derated to F of the calibrated per-worker "
                        "rate, so sizing keeps 1-F headroom")
    p.add_argument("--autoscale-seed", type=int, default=0,
                   help="seed naming the synthesized diurnal workload")
    p.add_argument("--platform", default=None, choices=["cpu", "tpu"],
                   help="force a JAX platform for the workers "
                        "(default cpu: the soak is a logic gate, not a "
                        "bench)")
    p.add_argument("--log-level", default=None,
                   choices=["debug", "info", "warning", "error"])
    return p


def _requests(args, n: int):
    """The soak's request stream (a generator): ``n`` requests over
    ``--signatures`` distinct compiled signatures with rotating
    diffusivities. The pool repeats with period 256 per signature —
    bounded so the oracle can verify every distinct hash — which is
    why ``run_soak`` disables every result cache: the repeats must
    re-solve, or the throughput gate would measure cache service."""
    from heat2d_tpu.serve.schema import SolveRequest
    for i in range(n):
        yield SolveRequest(
            nx=args.nx, ny=args.ny,
            steps=args.steps + (i % args.signatures),
            cx=0.05 + 0.0003 * (i % 256), cy=0.1, method="jnp")


def _parse_worker_env(specs) -> dict:
    """--worker-env SLOT:KEY=VAL flags -> per_worker_env dict."""
    out: dict = {}
    for spec in specs:
        try:
            slot, kv = spec.split(":", 1)
            key, val = kv.split("=", 1)
            out.setdefault(int(slot), {})[key] = val
        except ValueError:
            raise SystemExit(f"bad --worker-env {spec!r} "
                             f"(want SLOT:KEY=VAL)") from None
    return out


def run_soak(args, registry) -> int:
    from heat2d_tpu.fleet.router import FleetServer
    from heat2d_tpu.serve.schema import Rejected

    failures = []
    events = []                 # (t, "completed" | rejected-code)
    ev_lock = AuditedLock("fleet.cli.events")
    responses = {}              # content_hash -> result bytes
    env = ({"JAX_PLATFORMS": args.platform} if args.platform
           else {"JAX_PLATFORMS": "cpu"})

    # -- control plane setup (docs/CONTROL.md) -------------------------- #
    control = args.control or args.control_rollout
    validated_path = candidate_path = None
    if control:
        import tempfile
        cdir = args.control_db or tempfile.mkdtemp("heat2d-control")
        os.makedirs(cdir, exist_ok=True)
        validated_path = os.path.join(cdir, "validated.json")
        candidate_path = os.path.join(cdir, "candidate.json")
        # every worker serves under the VALIDATED db path (a missing
        # file degrades to "no db"); rollouts hand the candidate path
        # to a canary via a one-generation env overlay only
        env["HEAT2D_TUNE_DB"] = validated_path
    if args.control_storm_phase:
        from heat2d_tpu.resil import chaos
        chaos.install(chaos.ChaosConfig(
            rollout_kill_phase=args.control_storm_phase,
            rollout_kills=0), registry=registry)

    fleet = FleetServer(
        workers=args.workers, registry=registry,
        default_timeout=args.timeout,
        heartbeat_timeout=args.heartbeat_timeout,
        # ALL result caches are OFF for the soak: the request pool
        # cycles (bounded so the oracle can verify every distinct
        # hash), and either the router-side shared cache or the
        # workers' own LRUs would absorb the repeats — the throughput
        # windows must measure the SOLVE path the chaos is aimed at,
        # not cache service (which has its own tests).
        cache_size=0, worker_cache_size=0,
        env=env,
        per_worker_env=_parse_worker_env(args.worker_env))
    killed = []
    submitted = 0
    sem = threading.Semaphore(args.concurrency)

    def on_done(fut, req):
        import numpy as np
        now = time.monotonic()
        try:
            res = fut.result()
            with ev_lock:
                events.append((now, "completed"))
                responses.setdefault(req.content_hash(),
                                     np.asarray(res.u).tobytes())
                if responses[req.content_hash()] != \
                        np.asarray(res.u).tobytes():
                    failures.append(
                        f"divergent responses for {req.content_hash()}")
        except Rejected as e:
            with ev_lock:
                events.append((now, f"rejected_{e.code}"))
        except Exception as e:  # noqa: BLE001 — a soak reports, always
            with ev_lock:
                events.append((now, f"error:{e!r}"))
        sem.release()

    print(f"# fleet soak: {args.workers} workers, {args.soak:.0f}s, "
          f"concurrency {args.concurrency}"
          + (f", killing {args.kill} at midpoint" if args.chaos else "")
          + (", control plane armed" if control else ""))
    plane = None
    rollout_thread = None
    rollout_out: dict = {}
    control_extra = None
    with fleet:
        # Warmup OUTSIDE the measured window: every signature compiles
        # its padded batch programs on every worker-reachable path, so
        # the pre-kill window measures steady-state serving, not
        # compilation (the throughput-recovery gate needs a real
        # baseline to compare against).
        warm = [fleet.submit(r) for r in
                (dataclasses.replace(req, cx=0.9 + 0.0003 * j)
                 for j, req in enumerate(_requests(
                     args, args.signatures * max(args.concurrency, 8))))]
        for f in warm:
            try:
                f.result(timeout=args.timeout + 60)
            except Exception:   # noqa: BLE001 — warmup is best-effort
                pass
        if control:
            from heat2d_tpu.control import ControlPlane, Retuner
            from heat2d_tpu.obs import slo as _slo
            plane = ControlPlane(
                fleet,
                policy=_slo.SLOPolicy(
                    latency_p99_s=args.slo_p99 or 30.0,
                    error_budget=args.slo_error_budget),
                retuner=Retuner(fleet, candidate_path=candidate_path,
                                validated_path=validated_path),
                registry=registry).start()
        t_start = time.monotonic()
        kill_at = t_start + args.soak / 2 if args.chaos else None
        rollout_at = (t_start + args.soak / 2
                      if args.control_rollout else None)
        window = args.window or max(1.0, args.soak / 3)
        reqs = iter(_requests(args, 10 ** 9))
        t_rec = None        # when the fleet was whole-and-warm again
        pre = post = None   # rps windows
        t_thr = None        # when throughput was back within margin
        last_check = 0.0
        while True:
            now = time.monotonic()
            if (killed and t_rec is None
                    and fleet.sup.deaths >= len(killed)
                    and fleet.sup.restarts >= len(killed)
                    and len(fleet.sup.alive_slots()) == args.workers
                    and not fleet._cold):
                t_rec = now
                print(f"# t+{now - t_start:.1f}s: fleet recovered "
                      f"({args.workers} workers alive and warm)")
            if (pre is not None and t_thr is None
                    and now >= kill_at + window   # window all post-kill
                    and now - last_check >= 0.25):
                # the recovery probe: completion rate over the sliding
                # last window, against the pre-kill baseline
                last_check = now
                with ev_lock:
                    r = _rate(events, 0.0, now - window, now)
                if r >= (1.0 - args.recovery_margin) * pre:
                    t_thr, post = now, r
                    print(f"# t+{now - t_start:.1f}s: throughput "
                          f"recovered ({r:.1f} rps vs {pre:.1f} "
                          f"pre-kill)")
            if (rollout_at is not None and rollout_thread is None
                    and now >= rollout_at):
                rollout_at = None
                rollout_thread = _start_rollout(
                    args, plane, validated_path, candidate_path,
                    rollout_out, failures)
            if now - t_start >= args.soak:
                # "throughput recovered after restart" is measured, not
                # scheduled: under --chaos the load keeps running until
                # the sliding window clears the recovery bar (hard-
                # capped at 3x the nominal soak, reported as a failure)
                chaos_done = (not args.chaos
                              or (t_thr is not None and t_rec is not None)
                              or now - t_start >= 3 * args.soak)
                # a mid-soak rollout keeps its observation probes under
                # live load: the loop runs until it settles (capped)
                rollout_done = (rollout_thread is None
                                or not rollout_thread.is_alive()
                                or now - t_start >= 6 * args.soak)
                if chaos_done and rollout_done:
                    break
            if (kill_at is not None and not killed
                    and now >= kill_at):
                with ev_lock:
                    pre = _rate(events, t_start, kill_at - t_start
                                - window, kill_at - t_start)
                for k in range(args.kill):
                    fleet.sup.kill_worker(k)
                    killed.append(k)
                print(f"# t+{now - t_start:.1f}s: killed "
                      f"worker(s) {killed} (pre-kill {pre:.1f} rps)")
            if not sem.acquire(timeout=0.1):
                continue
            req = next(reqs)
            submitted += 1
            fleet.submit(req).add_done_callback(
                lambda f, r=req: on_done(f, r))
        if rollout_thread is not None:
            rollout_thread.join(timeout=3 * args.soak + 120)
            if rollout_thread.is_alive():
                failures.append("control rollout did not finish")
        # drain: wait for every outstanding slot back
        for _ in range(args.concurrency):
            sem.acquire(timeout=args.timeout + 30)
        if plane is not None:
            plane.stop()
            control_extra = plane.summary()
            control_extra["validated_path"] = validated_path
            control_extra["candidate_path"] = candidate_path
            # what every CURRENT worker reports serving, pre-shutdown
            control_extra["workers_tune"] = {
                str(s): (fleet.sup.worker_info(s) or {}).get("tune")
                for s in fleet.sup.alive_slots()}
        deaths, restarts = fleet.sup.deaths, fleet.sup.restarts
        alive = len(fleet.sup.alive_slots())
        clean = fleet.stop()
    if args.control_storm_phase:
        from heat2d_tpu.resil import chaos
        chaos.uninstall()

    answered = len(events)
    completed = sum(1 for _t, o in events if o == "completed")
    rejected = answered - completed
    if answered != submitted:
        failures.append(f"silent loss: {submitted} submitted but only "
                        f"{answered} answered")
    if completed == 0:
        failures.append("no request completed")
    errors = [o for _t, o in events if o.startswith("error:")]
    if errors:
        failures.append(f"{len(errors)} unstructured errors, e.g. "
                        f"{errors[0]}")

    # -- oracle: every distinct request, bitwise ----------------------- #
    mismatches = _oracle_check(args, responses)
    if mismatches:
        failures.append(f"{mismatches} responses differ bitwise from "
                        f"the single-worker oracle")

    # -- throughput windows -------------------------------------------- #
    summary = {
        "workers": args.workers, "soak_s": args.soak,
        "submitted": submitted, "completed": completed,
        "rejected": rejected, "distinct": len(responses),
        "deaths": deaths, "restarts": restarts,
        "replays": fleet.replays, "alive_at_end": alive,
        "clean_exit": clean, "killed": killed,
    }
    if args.chaos:
        if post is None:        # never cleared the bar: report the tail
            t_end = events[-1][0] if events else time.monotonic()
            post = _rate(events, 0.0, t_end - window, t_end)
        summary.update(
            pre_kill_rps=round(pre or 0.0, 2),
            post_restart_rps=round(post, 2), window_s=window,
            restart_recovery_s=(None if t_rec is None
                                else round(t_rec - kill_at, 2)),
            throughput_recovery_s=(None if t_thr is None
                                   else round(t_thr - kill_at, 2)))
        if registry is not None:
            registry.gauge("fleet_throughput_rps", pre or 0.0,
                           window="pre_kill")
            registry.gauge("fleet_throughput_rps", post,
                           window="post_restart")
            if t_thr is not None:
                registry.gauge("fleet_recovery_s", t_thr - kill_at)
        if not pre:
            failures.append("no pre-kill steady state measured — the "
                            "recovery gate would be vacuous (soak too "
                            "short or workers never warmed)")
        if t_rec is None:
            failures.append("fleet never returned to full strength "
                            "(no recovery point observed)")
        if deaths < len(killed):
            failures.append(f"killed {len(killed)} workers but only "
                            f"{deaths} deaths detected")
        if restarts < len(killed):
            failures.append(f"no restart after kill ({restarts} < "
                            f"{len(killed)})")
        if pre and t_thr is None:
            failures.append(
                f"throughput did not recover within {3 * args.soak:.0f}"
                f"s: {post:.1f} rps vs {pre:.1f} pre-kill (margin "
                f"{args.recovery_margin})")
    if not clean:
        failures.append("supervisor shutdown was not clean")

    # -- control-plane acceptance (docs/CONTROL.md) --------------------- #
    if control_extra is not None:
        from heat2d_tpu.tune.db import TuningDB
        if not control_extra.get("no_unvalidated_serving"):
            failures.append(
                "control: a non-rollout worker generation served a "
                "non-validated config: "
                f"{control_extra.get('unvalidated_serving')}")
        oc = rollout_out.get("outcome")
        control_extra["rollout_outcome"] = oc
        if args.control_rollout and oc is None:
            failures.append("control: the rollout never produced an "
                            "outcome")
        elif args.control_bad_candidate:
            if not (oc or "").startswith("reverted"):
                failures.append(f"control: the deliberately-bad "
                                f"candidate was NOT auto-reverted "
                                f"(outcome {oc})")
            elif rollout_out.get("post_revert_parity") is not True:
                failures.append("control: post-revert answers were "
                                "not bitwise-identical to the "
                                "pre-rollout baseline")
        elif args.control_storm_phase and (oc or "").startswith(
                "reverted"):
            if rollout_out.get("post_revert_parity") is not True:
                failures.append("control: storm revert without a "
                                "bitwise post-revert parity proof")
        elif args.control_rollout and not args.control_storm_phase:
            if oc != "promoted":
                failures.append(f"control: a healthy candidate did "
                                f"not promote (outcome {oc})")
            else:
                vdb = TuningDB(validated_path)
                if not (vdb.validated and vdb.epoch
                        == rollout_out.get("epoch")):
                    failures.append(
                        f"control: promote did not advance the "
                        f"validated db (epoch {vdb.epoch}, validated "
                        f"{vdb.validated})")
        summary["control"] = {
            "rollout_outcome": oc,
            "no_unvalidated_serving":
                control_extra.get("no_unvalidated_serving"),
            "decisions": len(control_extra.get("decisions", [])),
        }

    print(f"# soak summary: {json.dumps(summary)}")
    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    _write_metrics(args, registry, dict(summary, failures=failures),
                   control=control_extra)
    print("fleet soak " + ("FAILED" if failures else "passed"))
    return 1 if failures else 0


def run_autoscale(args, registry) -> int:
    """The elastic soak (CI's ``autoscale-soak`` job): calibrate one
    worker's throughput, synthesize a compressed diurnal day from
    ``load/synth``, and let the control plane + actuator run the pool —
    then assert the closed loop actually closed (docs/CONTROL.md):

    1. capacity FOLLOWS the envelope — scale-ups and scale-downs both
       happen, and the mean pool size under the envelope's peak beats
       the mean under its trough;
    2. SLOs hold through every resize — nothing lost, nothing
       rejected, no unstructured errors;
    3. elasticity is cheaper than static provisioning — the actuator's
       chip-seconds ledger lands below ``--workers`` workers held for
       the whole window;
    4. one long-running inverse job, live-migrated off a retiring
       worker mid-optimization, finishes bitwise-identical to the
       oracle that never moved;
    5. (multi-device processes) mesh resize down/up, quarantine, and
       parole all serve bitwise-identical answers with the
       ``no_quarantined_serving`` invariant intact and the paroled
       device back in the serving set.
    """
    import math

    import numpy as np

    from heat2d_tpu.autoscale import Actuator, AutoscalePolicy
    from heat2d_tpu.autoscale import migrate as migrate_mod
    from heat2d_tpu.control import ControlPlane
    from heat2d_tpu.fleet.router import FleetServer
    from heat2d_tpu.load import capacity
    from heat2d_tpu.load.synth import PROFILES, synthesize
    from heat2d_tpu.obs import MetricsRegistry
    from heat2d_tpu.obs import slo as _slo
    from heat2d_tpu.resil.retry import wait_for
    from heat2d_tpu.serve.schema import Rejected, SolveRequest

    failures = []
    events = []                 # (t, "completed" | rejected-code)
    ev_lock = AuditedLock("fleet.cli.autoscale")
    env = {"JAX_PLATFORMS": args.platform or "cpu"}
    profile = PROFILES["diurnal"]
    period = profile.diurnal_period_s
    amp = profile.diurnal_amplitude
    soak = args.soak if args.soak is not None else 1.5 * period
    min_w, max_w = 1, args.workers
    submitted = 0

    print(f"# autoscale soak: diurnal envelope ({period:.0f}s period, "
          f"amplitude {amp}), {soak:.0f}s, pool [{min_w}, {max_w}]")
    fleet = FleetServer(
        workers=min_w, registry=registry,
        default_timeout=args.timeout,
        heartbeat_timeout=args.heartbeat_timeout,
        # caches off for the same reason as the chaos soak: capacity
        # calibration and the envelope response must measure the SOLVE
        # path, not cache service
        cache_size=0, worker_cache_size=0,
        # the envelope DELIBERATELY under-provisions at the trough
        # (that is the savings), so the rising edge queues until the
        # scale-up absorbs it — admission must hold the backlog, not
        # shed it: this soak's SLO gate is completion, not queue depth
        # (router admission AND the workers' own batcher doors)
        max_inflight=100_000, queue_depth=100_000,
        worker_timeout=args.timeout, env=env)

    def on_done(fut, _req):
        now = time.monotonic()
        try:
            fut.result()
            with ev_lock:
                events.append((now, "completed"))
        except Rejected as e:
            with ev_lock:
                events.append((now, f"rejected_{e.code}"))
        except Exception as e:  # noqa: BLE001 — a soak reports, always
            with ev_lock:
                events.append((now, f"error:{e!r}"))

    plane = None
    summary: dict = {}
    control_extra = None
    with fleet:
        # -- warmup: every signature compiles off the measured path -- #
        warm = [fleet.submit(SolveRequest(
            nx=profile.nx, ny=profile.ny, steps=profile.steps + s,
            cx=0.9 + 0.001 * s, cy=0.1, method=profile.method))
            for s in range(profile.signatures)]
        for f in warm:
            try:
                f.result(timeout=args.timeout + 60)
            except Exception:   # noqa: BLE001 — warmup is best-effort
                pass

        # -- calibration: one worker's sustainable rate -------------- #
        cal_done: list = []
        cal_conc = max(2, min(4, args.concurrency))
        sem = threading.Semaphore(cal_conc)
        cal_end = time.monotonic() + 3.0
        i = 0
        while time.monotonic() < cal_end:
            if not sem.acquire(timeout=0.1):
                continue
            i += 1
            fut = fleet.submit(SolveRequest(
                nx=profile.nx, ny=profile.ny, steps=profile.steps,
                cx=round(0.05 + 0.0001 * (i % 997), 6), cy=0.1,
                method=profile.method))
            fut.add_done_callback(
                lambda f: (cal_done.append(time.monotonic()),
                           sem.release()))
        for _ in range(cal_conc):
            sem.acquire(timeout=args.timeout + 30)
        # steady state only: drop the first half second as ramp
        t0c = cal_done[0] if cal_done else time.monotonic()
        late = [t for t in cal_done if t - t0c >= 0.5]
        span = (cal_done[-1] - t0c - 0.5) if len(late) >= 2 else 0.0
        measured = len(late) / span if span > 0 else 0.0
        if measured <= 0:
            print("FAIL: calibration measured no throughput",
                  file=sys.stderr)
            fleet.stop()
            return 1
        fit = capacity.fit_capacity(
            [{"offered_rps": measured, "achieved_rps": measured,
              "shed_rate": 0.0, "slo_ok": True},
             {"offered_rps": 4 * measured, "achieved_rps": measured,
              "shed_rate": 0.5, "slo_ok": False}], units=1)
        # derate to the target utilization: the autoscaler sizes for
        # headroom, not the saturation knee it calibrated at
        fit["per_unit_rps"] = round(
            fit["per_unit_rps"] * args.autoscale_util, 4)
        # base rate such that the envelope's PEAK needs the whole pool
        # and its trough needs ~min_workers
        base_rate = max_w * fit["per_unit_rps"] / (1.0 + amp)
        print(f"# calibrated {measured:.1f} rps/worker "
              f"(derated per-unit {fit['per_unit_rps']:.1f}); "
              f"base rate {base_rate:.1f} rps")
        sched = synthesize(profile, base_rate, soak,
                           seed=args.autoscale_seed, max_arrivals=20000)

        # -- arm the loop: plane -> actuator -> fleet ---------------- #
        policy = AutoscalePolicy(
            min_workers=min_w, max_workers=max_w,
            up_cooldown_s=1.0, down_cooldown_s=2.0,
            down_hold_ticks=2, max_step_up=2, max_step_down=1,
            drain_timeout_s=args.timeout)
        actuator = Actuator(fleet, policy, registry=registry)
        plane = ControlPlane(
            fleet,
            policy=_slo.SLOPolicy(latency_p99_s=args.slo_p99 or 30.0,
                                  error_budget=args.slo_error_budget),
            interval=0.25, capacity_fit=fit, registry=registry,
            actuator=actuator).start()

        # -- replay the synthesized day (open loop) ------------------ #
        t_load = time.monotonic()
        for arr in sched.arrivals:
            now = time.monotonic() - t_load
            if arr.t > now:
                time.sleep(arr.t - now)
            submitted += 1
            fleet.submit(SolveRequest(**arr.spec)).add_done_callback(
                lambda f, r=arr: on_done(f, r))
        deadline = time.monotonic() + args.timeout + 60
        while time.monotonic() < deadline:
            with ev_lock:
                if len(events) >= submitted:
                    break
            time.sleep(0.05)
        plane.stop()
        control_extra = plane.summary()

        # -- live-migration leg -------------------------------------- #
        if fleet.sup.pool_size() < 2:
            # migration needs a survivor to land on
            fleet.add_worker()
        mig_summary = _autoscale_migration_leg(
            args, actuator, fleet, migrate_mod, MetricsRegistry,
            wait_for, failures)

        # -- mesh resize / parole leg (multi-device only) ------------ #
        mesh_summary = _autoscale_mesh_leg(args, actuator, profile,
                                           registry, failures)

        auto = actuator.summary()
        clean = fleet.stop()

    # -- acceptance ----------------------------------------------------- #
    answered = len(events)
    completed = sum(1 for _t, o in events if o == "completed")
    if answered != submitted:
        failures.append(f"silent loss: {submitted} submitted but only "
                        f"{answered} answered")
    bad = [o for _t, o in events if o != "completed"]
    if bad:
        # "SLOs hold through every resize": a drain that dropped or
        # rejected even one request is an elastic-path failure
        failures.append(f"{len(bad)} requests not completed through "
                        f"the resizes, e.g. {bad[0]}")
    if auto["scale_ups"] < 1 or auto["scale_downs"] < 1:
        failures.append(
            f"capacity did not follow the envelope both directions "
            f"({auto['scale_ups']} ups, {auto['scale_downs']} downs)")
    # the pool must TRACK the envelope: mean size under the peak half
    # vs the trough half of the sinusoid
    peak, trough = [], []
    for t, pool in auto["trace"]:
        phase = math.sin(2.0 * math.pi * (t - t_load) / period)
        if phase > 0.5:
            peak.append(pool)
        elif phase < -0.5:
            trough.append(pool)
    if peak and trough:
        if (sum(peak) / len(peak)) <= (sum(trough) / len(trough)):
            failures.append(
                f"pool did not track the envelope: peak mean "
                f"{sum(peak) / len(peak):.2f} <= trough mean "
                f"{sum(trough) / len(trough):.2f}")
    else:
        failures.append("soak too short to sample both envelope "
                        "phases")
    if auto["chip_seconds"] >= auto["static_chip_seconds"]:
        failures.append(
            f"elasticity saved nothing: {auto['chip_seconds']:.1f} "
            f"chip-seconds vs static "
            f"{auto['static_chip_seconds']:.1f}")
    if not clean:
        failures.append("supervisor shutdown was not clean")

    summary = {
        "soak_s": soak, "submitted": submitted,
        "completed": completed,
        "calibrated_rps_per_worker": round(measured, 2),
        "base_rate_rps": round(base_rate, 2),
        "scale_ups": auto["scale_ups"],
        "scale_downs": auto["scale_downs"],
        "workers_min": auto["workers_min"],
        "workers_max": auto["workers_max"],
        "chip_seconds": round(auto["chip_seconds"], 1),
        "static_chip_seconds": round(auto["static_chip_seconds"], 1),
        "savings_fraction": round(auto["savings_fraction"], 3),
        "migration": mig_summary,
        "mesh": mesh_summary,
        "clean_exit": clean,
    }
    print(f"# autoscale summary: {json.dumps(summary)}")
    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    if args.metrics_out:
        from heat2d_tpu.obs.record import write_run_jsonl
        write_run_jsonl(
            registry, args.metrics_out, "autoscale",
            dict(summary, failures=failures,
                 actions=auto["actions"],
                 migrations=auto["migration_rows"]),
            more=[("control", control_extra)] if control_extra else ())
    print("autoscale soak " + ("FAILED" if failures else "passed"))
    return 1 if failures else 0


def _autoscale_migration_leg(args, actuator, fleet, migrate_mod,
                             MetricsRegistry, wait_for, failures):
    """Prove live migration end to end: a long inverse job attached to
    the highest provisioned slot, that slot retired mid-optimization
    (the actuator's own scale-down path), the resumed job joined and
    compared BITWISE against an oracle that never migrated."""
    import numpy as np

    from heat2d_tpu.diff.inverse import (InverseProblem,
                                         observation_mask,
                                         unit_reference_init)

    import jax.numpy as jnp
    from heat2d_tpu.diff.adjoint import make_diff_solve

    nx = ny = 12
    steps, iters, lr = 10, 600, 0.05
    u0 = unit_reference_init(nx, ny)
    u_true = np.asarray(make_diff_solve(nx, ny, steps)(
        jnp.asarray(u0), 0.1, 0.1))
    prob = InverseProblem(nx=nx, ny=ny, steps=steps, target="init",
                          obs_mask=observation_mask(nx, ny, every=1),
                          obs_values=u_true, cx=0.1, cy=0.1)
    # oracle FIRST: warms the memoized compile, so the live job's
    # iteration cadence is steady when the checkpoint lands
    oracle = migrate_mod.run_unmigrated(prob, iterations=iters, lr=lr)
    job_reg = MetricsRegistry()
    job = migrate_mod.InverseJob(prob, iterations=iters, lr=lr,
                                 registry=job_reg).start()
    victim = fleet.sup.provisioned_slots()[-1]
    actuator.attach_job(victim, job)

    def _progress() -> float:
        return job_reg.snapshot()["counters"].get(
            "inverse_iterations_total", 0.0)

    # retire mid-flight: the job must be demonstrably PAST iteration 0
    # and short of done when the drain takes its worker
    wait_for(lambda: _progress() >= 50, 120.0)
    row = actuator.retire(victim)
    mig = row.get("migrated") or []
    out = {"victim": victim, "clean_drain": row.get("clean"),
           "migrated": bool(mig)}
    if not mig or not mig[0].get("resumed"):
        failures.append("no live migration occurred on retire "
                        f"(row {row})")
        return out
    rec = mig[0]
    out.update(iteration=rec["iteration"], dest=rec["to"],
               wire_bytes=rec["bytes"])
    if not 0 < rec["iteration"] < iters:
        failures.append(f"checkpoint not mid-flight: iteration "
                        f"{rec['iteration']} of {iters}")
    moved = actuator.jobs_on(rec["to"])[-1]
    try:
        moved.join(timeout=600)
    except Exception as e:  # noqa: BLE001 — a soak reports, always
        failures.append(f"migrated job failed to finish: {e!r}")
        return out
    sol = moved.solution
    if sol is None or sol.paused:
        failures.append("migrated job did not run to completion")
        return out
    bitwise = (
        np.asarray(sol.params).tobytes()
        == np.asarray(oracle.params).tobytes()
        and list(sol.loss_history) == list(oracle.loss_history))
    out["bitwise_vs_oracle"] = bitwise
    if not bitwise:
        failures.append("migrated inverse job is NOT bitwise-identical "
                        "to the unmigrated oracle")
    return out


def _autoscale_mesh_leg(args, actuator, profile, registry, failures):
    """Mesh elasticity on multi-device processes: voluntary resize
    down and back up, a quarantine, and a parole — every leg bitwise
    vs the full-mesh baseline, the serving invariant provable
    throughout, and the paroled device back in the serving set."""
    import numpy as np

    import jax

    from heat2d_tpu.mesh.degrade import FaultPolicy, serving_invariant
    from heat2d_tpu.mesh.engine import MeshEnsembleEngine
    from heat2d_tpu.serve.schema import SolveRequest

    nd = jax.local_device_count()
    if nd < 2:
        return {"skipped": f"single-device process (nd={nd})"}
    engine = MeshEnsembleEngine(registry=registry, fault=FaultPolicy())
    actuator.mesh_engine = engine
    actuator.health = engine.health
    reqs = [SolveRequest(nx=profile.nx, ny=profile.ny,
                         steps=profile.steps,
                         cx=round(0.07 + 0.001 * i, 6), cy=0.1,
                         method="jnp") for i in range(2 * nd)]

    def solve_bytes():
        return [np.asarray(u).tobytes()
                for u, _s in engine.solve_batch(reqs)]

    base = solve_bytes()
    legs = {}
    actuator.resize_mesh(nd - 1)
    legs["resized_down"] = solve_bytes()
    actuator.resize_mesh(nd)
    legs["resized_up"] = solve_bytes()
    engine.health.quarantine(nd - 1, "probe_failure")
    legs["degraded"] = solve_bytes()
    parole_rows = actuator.parole_all()
    paroled = [r for r in parole_rows if r["outcome"] == "paroled"]
    if not paroled:
        failures.append(f"parole denied a healthy device "
                        f"({parole_rows})")
    mark = len(engine.launch_log)
    legs["paroled"] = solve_bytes()
    for name, got in legs.items():
        if got != base:
            failures.append(f"mesh leg '{name}' diverged bitwise from "
                            f"the full-mesh baseline")
    inv = serving_invariant(engine.health, engine.launch_log)
    if not inv["ok"]:
        failures.append(f"no_quarantined_serving violated: "
                        f"{inv['violations']}")
    served_after = any(
        (nd - 1) in ((r.get("mesh") or {}).get("devices") or ())
        for r in engine.launch_log[mark:])
    if paroled and not served_after:
        failures.append("paroled device never re-entered the serving "
                        "set")
    return {"devices": nd, "paroled": len(paroled),
            "resizes": len(engine.resize_log),
            "invariant_ok": inv["ok"],
            "paroled_device_served": served_after}


def _start_rollout(args, plane, validated_path, candidate_path,
                   out, failures):
    """Stage a candidate for the hottest signature (simulated
    measurement backend — the rollout machinery, not kernel speed, is
    under test on CPU) and run one safe rollout on a thread beside the
    live load. Appends to ``failures`` / updates ``out`` in place."""
    from heat2d_tpu.control import RolloutConfig

    staged = None
    for sig, _n in plane.retuner.hot_signatures():
        staged = plane.retuner.stage_candidate(sig)
        if staged is not None:
            break
    if staged is None:
        failures.append("control rollout: no tunable hot signature "
                        "to stage")
        return None
    extra = ({"HEAT2D_CHAOS_SLOW_WORKER_S": "0.5"}
             if args.control_bad_candidate else {})
    cfg = RolloutConfig(
        candidate_path=candidate_path, validated_path=validated_path,
        probe_spec={"nx": args.nx, "ny": args.ny, "steps": args.steps,
                    "cx": 0.123, "cy": 0.1, "method": "jnp"},
        observe_s=args.control_observe,
        probe_timeout=args.timeout,
        extra_canary_env=extra)
    print(f"# control: staged candidate epoch {staged['epoch']} for "
          f"{staged['signature']}; starting rollout"
          + (" (bad-candidate injection armed)" if extra else ""))

    def _run():
        out.update(plane.run_rollout(cfg))
        print(f"# control: rollout outcome {out.get('outcome')}")

    t = threading.Thread(target=_run, name="heat2d-control-rollout",
                         daemon=True)
    t.start()
    return t


def _rate(events, t_start: float, lo: float, hi: float) -> float:
    """Completions per second inside the (lo, hi] soak-relative
    window."""
    if hi <= lo:
        return 0.0
    n = sum(1 for t, o in events
            if o == "completed" and lo < t - t_start <= hi)
    return n / (hi - lo)


def _oracle_check(args, responses) -> int:
    """Re-solve every distinct request on ONE in-process server and
    count bitwise mismatches against the fleet's answers."""
    import numpy as np

    from heat2d_tpu.serve.schema import SolveRequest
    from heat2d_tpu.serve.server import SolveServer

    todo = dict(responses)
    mismatches = 0
    with SolveServer(registry=None) as oracle:
        # regenerate the request stream and solve each distinct hash
        for req in _requests(args, 10 ** 6):
            h = req.content_hash()
            if h not in todo:
                if not todo:
                    break
                continue
            want = todo.pop(h)
            got = np.asarray(
                oracle.solve(req, timeout=120).u).tobytes()
            if got != want:
                mismatches += 1
    return mismatches + len(todo)


def _write_metrics(args, registry, extra, control=None) -> None:
    from heat2d_tpu.obs.record import write_run_jsonl
    if args.slo_p99 is not None and registry is not None:
        from heat2d_tpu.obs import slo
        slo.stamp_record(extra, slo.evaluate(
            registry, prefix="fleet",
            default=slo.SLOPolicy(latency_p99_s=args.slo_p99,
                                  error_budget=args.slo_error_budget)))
    if args.trace_dir:
        from heat2d_tpu.obs import flight, tracing
        t = tracing.tracer()
        extra["trace"] = {
            "dir": args.trace_dir,
            "router_spans": t.spans_emitted if t is not None else 0,
            "postmortems": len(flight.find_postmortems(args.trace_dir)),
        }
    # the control plane's decisions/rollouts/invariant ride as their
    # own kind="control" record beside the fleet record
    write_run_jsonl(registry, args.metrics_out, "fleet", extra,
                    more=[("control", control)] if control else ())


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.log_level:
        import logging
        logging.basicConfig(
            format="%(asctime)s %(name)s %(levelname)s %(message)s")
        logging.getLogger("heat2d_tpu").setLevel(
            getattr(logging, args.log_level.upper()))
    # The router/oracle process stays on CPU unless told otherwise —
    # workers get their platform via env (run_soak).
    os.environ.setdefault("JAX_PLATFORMS", args.platform or "cpu")
    if args.trace_dir:
        # Router tracer here; workers inherit the campaign through the
        # environment (the supervisor copies os.environ into each
        # worker): every process writes spans into the ONE directory,
        # and each worker arms a flight recorder the chaos kill points
        # will flush (docs/OBSERVABILITY.md).
        # explicit flag wins over any stale env vars: if setdefault
        # kept an old HEAT2D_TRACE_DIR, the workers (which inherit the
        # env) would write spans into a DIFFERENT directory than the
        # router traces and --require-postmortem checks — a silently
        # split campaign
        os.environ["HEAT2D_TRACE_DIR"] = args.trace_dir
        os.environ["HEAT2D_FLIGHT_DIR"] = args.trace_dir
        from heat2d_tpu.obs import tracing
        tracing.install(tracing.Tracer(args.trace_dir, service="router"))

    if ((args.control_storm_phase or args.control_bad_candidate)
            and not args.control_rollout):
        # without a rollout there is no storm window and no canary to
        # poison — a soak that "passed" would prove nothing
        print("--control-storm-phase/--control-bad-candidate require "
              "--control-rollout (they act on a live rollout)",
              file=sys.stderr)
        return 2
    from heat2d_tpu.obs import MetricsRegistry
    registry = MetricsRegistry()
    if args.autoscale:
        return run_autoscale(args, registry)
    if args.soak is not None:
        return run_soak(args, registry)
    print("nothing to do: pass --soak S (optionally --chaos) — the "
          "fleet embeds in-process via heat2d_tpu.fleet.FleetServer; "
          "docs/FLEET.md", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
